"""Shared neural-vocoder building blocks (functional JAX, NWC layout).

The Qwen audio stacks ship the same component family in several
checkpoints — causal 1-D convs, causal transposed convs, SnakeBeta
activations, ConvNeXt blocks, a sliding-window rotary transformer with
LayerScale residuals, and a progressive Snake/trans-conv decoder — with
per-model wiring differences:

- Qwen3-TTS 12.5 Hz codec decoder
  (reference: vllm_omni/model_executor/models/qwen3_tts/tokenizer_12hz/
  modeling_qwen3_tts_tokenizer_v2.py) — trans-convs trim the RIGHT
  (kernel - stride) samples.
- Qwen3-Omni code2wav
  (reference: vllm_omni/model_executor/models/qwen3_omni/
  qwen3_omni_code2wav.py + transformers Qwen3OmniMoeCode2Wav) —
  trans-convs trim (kernel - stride) from BOTH sides.

TPU-first: channel-last [B, T, C] tensors keep channels on the lane
dim, causal convs are explicit left-pad + VALID `lax` convs (static
shapes, MXU-friendly), and the sliding window is a static additive mask
XLA folds into the softmax — the whole decode stays one jitted graph.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from vllm_omni_tpu.models.common import nn
from vllm_omni_tpu.ops import rms_norm


# torch parity needs full-precision convs (the XLA default may lower
# fp32 convs to a faster, lower-precision path); vocoder convs are a
# negligible share of pipeline FLOPs, so always ask for exact fp32.
_PRECISION = jax.lax.Precision.HIGHEST


# ----------------------------------------------------------------- convs
def cconv_init(key, cin, cout, k, dtype, groups: int = 1):
    return {"w": nn.conv1d_init(key, cin // groups, cout, k,
                                dtype=dtype)["w"],
            "b": jnp.zeros((cout,), dtype)}


def cconv(p, x, k: int, dilation: int = 1, stride: int = 1,
          groups: int = 1):
    """Causal 1-D conv, NWC: left-pad (k-1)*dilation - (stride-1), plus
    right pad up to a full output frame (reference CausalConvNet
    padding)."""
    eff_k = (k - 1) * dilation + 1
    pad = eff_k - stride
    length = x.shape[1]
    n_frames = (length - eff_k + pad) / stride + 1
    ideal = (math.ceil(n_frames) - 1) * stride + (eff_k - pad)
    extra = max(0, ideal - length)
    y = jax.lax.conv_general_dilated(
        jnp.pad(x, ((0, 0), (pad, extra), (0, 0))),
        p["w"].astype(x.dtype),
        window_strides=(stride,),
        padding="VALID",
        rhs_dilation=(dilation,),
        dimension_numbers=("NWC", "WIO", "NWC"),
        feature_group_count=groups,
        precision=_PRECISION,
    )
    return y + p["b"].astype(x.dtype)


def tconv_init(key, cin, cout, k, dtype):
    # stored in forward-conv layout [k, cout, cin] for
    # ``transpose_kernel=True`` (torch ConvTranspose1d semantics)
    return {"w": nn.conv1d_init(key, cout, cin, k, dtype=dtype)["w"],
            "b": jnp.zeros((cout,), dtype)}


def tconv(p, x, k: int, stride: int, trim_left: bool = False):
    """Causal transposed conv: full transpose then trim (k - stride)
    samples.  ``trim_left=False`` trims the right only (12.5 Hz codec
    CausalTransConvNet); ``trim_left=True`` trims both sides
    (Qwen3OmniMoeCausalTransConvNet)."""
    y = jax.lax.conv_transpose(
        x, p["w"].astype(x.dtype), strides=(stride,), padding="VALID",
        dimension_numbers=("NWC", "WIO", "NWC"), transpose_kernel=True,
        precision=_PRECISION,
    )
    trim = k - stride
    if trim > 0:
        left = trim if trim_left else 0
        y = y[:, left: y.shape[1] - trim]
    return y + p["b"].astype(x.dtype)


def snake_init(ch, dtype):
    return {"alpha": jnp.zeros((ch,), dtype), "beta": jnp.zeros((ch,), dtype)}


def snake(p, x):
    """SnakeBeta: x + 1/exp(beta) * sin^2(x * exp(alpha))."""
    a = jnp.exp(p["alpha"].astype(jnp.float32))
    b = jnp.exp(p["beta"].astype(jnp.float32))
    xf = x.astype(jnp.float32)
    y = xf + (1.0 / (b + 1e-9)) * jnp.square(jnp.sin(xf * a))
    return y.astype(x.dtype)


def convnext_init(key, dim, dtype):
    k = jax.random.split(key, 3)
    return {
        "dw": cconv_init(k[0], dim, dim, 7, dtype, groups=dim),
        "norm": nn.layernorm_init(dim, dtype=dtype),
        "pw1": nn.linear_init(k[1], dim, 4 * dim, dtype=dtype),
        "pw2": nn.linear_init(k[2], 4 * dim, dim, dtype=dtype),
        "gamma": jnp.full((dim,), 1e-6, dtype),
    }


def convnext(p, x):
    h = cconv(p["dw"], x, 7, groups=x.shape[-1])
    h = nn.layernorm(p["norm"], h)
    h = nn.linear(p["pw2"], jax.nn.gelu(nn.linear(p["pw1"], h),
                                        approximate=False))
    return x + p["gamma"].astype(x.dtype) * h


# ------------------------------------------------------------ transformer
@dataclass(frozen=True)
class TransformerSpec:
    """Geometry of the sliding-window rotary pre-transformer."""
    hidden_size: int
    num_layers: int
    num_heads: int
    num_kv_heads: int
    head_dim: int
    intermediate_size: int
    sliding_window: int
    layer_scale: float = 0.01
    rope_theta: float = 10000.0
    rms_eps: float = 1e-5


def transformer_layer_init(key, spec: TransformerSpec, dtype):
    k = jax.random.split(key, 6)
    h, d = spec.hidden_size, spec.head_dim
    return {
        "input_norm": nn.rmsnorm_init(h, dtype),
        "q_proj": nn.linear_init(k[0], h, spec.num_heads * d, bias=False,
                                 dtype=dtype),
        "k_proj": nn.linear_init(k[1], h, spec.num_kv_heads * d,
                                 bias=False, dtype=dtype),
        "v_proj": nn.linear_init(k[2], h, spec.num_kv_heads * d,
                                 bias=False, dtype=dtype),
        "o_proj": nn.linear_init(k[3], spec.num_heads * d, h, bias=False,
                                 dtype=dtype),
        "attn_scale": jnp.full((h,), spec.layer_scale, dtype),
        "post_norm": nn.rmsnorm_init(h, dtype),
        # gate/up kept as separate leaves so the HF checkpoint's
        # gate_proj/up_proj map 1:1 (no fused-weight surgery)
        "gate": nn.linear_init(k[4], h, spec.intermediate_size,
                               bias=False, dtype=dtype),
        "up": nn.linear_init(jax.random.fold_in(k[4], 1), h,
                             spec.intermediate_size, bias=False,
                             dtype=dtype),
        "down": nn.linear_init(k[5], spec.intermediate_size, h,
                               bias=False, dtype=dtype),
        "mlp_scale": jnp.full((h,), spec.layer_scale, dtype),
    }


def transformer_init(key, spec: TransformerSpec, dtype):
    ks = jax.random.split(key, spec.num_layers)
    return {
        "layers": [transformer_layer_init(ks[i], spec, dtype)
                   for i in range(spec.num_layers)],
        "final_norm": nn.rmsnorm_init(spec.hidden_size, dtype),
    }


def sliding_transformer(params, spec: TransformerSpec, x):
    """Causal sliding-window rotary transformer with LayerScale
    residuals (GQA-aware; kv heads repeat when fewer than q heads)."""
    from vllm_omni_tpu.ops import apply_rope, compute_rope_freqs

    b, t, _ = x.shape
    pos = jnp.arange(t)
    cos, sin = compute_rope_freqs(pos, spec.head_dim, spec.rope_theta)
    # causal + sliding window: key j visible to query i iff
    # i - window < j <= i
    dist = pos[:, None] - pos[None, :]
    mask = (dist >= 0) & (dist < spec.sliding_window)
    bias = jnp.where(mask, 0.0, -1e30).astype(jnp.float32)
    rep = spec.num_heads // spec.num_kv_heads

    for lp in params["layers"]:
        h = rms_norm(x, lp["input_norm"]["w"], spec.rms_eps)
        flat = h.reshape(b * t, -1)
        q = nn.linear(lp["q_proj"], flat).reshape(b * t, -1, spec.head_dim)
        kk = nn.linear(lp["k_proj"], flat).reshape(b * t, -1, spec.head_dim)
        v = nn.linear(lp["v_proj"], flat).reshape(b * t, -1, spec.head_dim)
        q = apply_rope(q, cos if b == 1 else jnp.tile(cos, (b, 1)),
                       sin if b == 1 else jnp.tile(sin, (b, 1)))
        kk = apply_rope(kk, cos if b == 1 else jnp.tile(cos, (b, 1)),
                        sin if b == 1 else jnp.tile(sin, (b, 1)))
        q = q.reshape(b, t, -1, spec.head_dim)
        kk = kk.reshape(b, t, -1, spec.head_dim)
        v = v.reshape(b, t, -1, spec.head_dim)
        if rep > 1:
            kk = jnp.repeat(kk, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        # dense attention with the window bias: the window is a static
        # mask, XLA folds it into the softmax
        s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                       kk.astype(jnp.float32)) / math.sqrt(spec.head_dim)
        a = jax.nn.softmax(s + bias[None, None], axis=-1).astype(x.dtype)
        o = jnp.einsum("bhqk,bkhd->bqhd", a, v).reshape(b, t, -1)
        o = nn.linear(lp["o_proj"], o)
        x = x + lp["attn_scale"].astype(x.dtype) * o
        h = rms_norm(x, lp["post_norm"]["w"], spec.rms_eps)
        y = nn.linear(lp["down"],
                      jax.nn.silu(nn.linear(lp["gate"], h))
                      * nn.linear(lp["up"], h))
        x = x + lp["mlp_scale"].astype(x.dtype) * y
    return rms_norm(x, params["final_norm"]["w"], spec.rms_eps)


def transformer_flat_map(m: dict, prefix: str, path: tuple,
                         num_layers: int) -> None:
    """HF layer names (``{prefix}.layers.N...``) -> param-tree paths
    rooted at ``path`` for the sliding transformer."""
    for i in range(num_layers):
        lp = f"{prefix}.layers.{i}"
        tgt = path + ("layers", i)
        m[f"{lp}.input_layernorm.weight"] = tgt + ("input_norm", "w")
        for proj in ("q_proj", "k_proj", "v_proj", "o_proj"):
            m[f"{lp}.self_attn.{proj}.weight"] = tgt + (proj, "w")
        m[f"{lp}.self_attn_layer_scale.scale"] = tgt + ("attn_scale",)
        m[f"{lp}.post_attention_layernorm.weight"] = tgt + ("post_norm",
                                                            "w")
        m[f"{lp}.mlp.gate_proj.weight"] = tgt + ("gate", "w")
        m[f"{lp}.mlp.up_proj.weight"] = tgt + ("up", "w")
        m[f"{lp}.mlp.down_proj.weight"] = tgt + ("down", "w")
        m[f"{lp}.mlp_layer_scale.scale"] = tgt + ("mlp_scale",)
    m[f"{prefix}.norm.weight"] = path + ("final_norm", "w")


# ----------------------------------------------------- decoder waveform
def decoder_stack_init(key, in_dim: int, decoder_dim: int,
                       upsample_rates, dtype):
    """Snake/trans-conv progressive decoder: conv(in->decoder_dim, 7),
    per-rate [Snake, TransConv(2r, r), 3x residual units (dilations
    1/3/9)], final Snake + conv(->1, 7)."""
    ks = jax.random.split(key, 2 + 8 * len(upsample_rates))
    ki = iter(ks)
    p = {"dec_in": cconv_init(next(ki), in_dim, decoder_dim, 7, dtype),
         "dec_blocks": []}
    for i, r in enumerate(upsample_rates):
        cin = decoder_dim // (2 ** i)
        cout = decoder_dim // (2 ** (i + 1))
        blk = {
            "snake": snake_init(cin, dtype),
            "tconv": tconv_init(next(ki), cin, cout, 2 * r, dtype),
            "units": [],
        }
        for _ in (1, 3, 9):  # dilations are static (decoder_stack_apply)
            blk["units"].append({
                "snake1": snake_init(cout, dtype),
                "conv1": cconv_init(next(ki), cout, cout, 7, dtype),
                "snake2": snake_init(cout, dtype),
                "conv2": cconv_init(next(ki), cout, cout, 1, dtype),
            })
        p["dec_blocks"].append(blk)
    out_dim = decoder_dim // (2 ** len(upsample_rates))
    p["out_snake"] = snake_init(out_dim, dtype)
    p["out_conv"] = cconv_init(next(ki), out_dim, 1, 7, dtype)
    return p


def decoder_stack_apply(params, x, upsample_rates,
                        trim_left: bool = False):
    """[B, T, in_dim] -> waveform [B, ~T*prod(rates)] in [-1, 1]."""
    w = cconv(params["dec_in"], x, 7)
    for blk, r in zip(params["dec_blocks"], upsample_rates):
        w = snake(blk["snake"], w)
        w = tconv(blk["tconv"], w, 2 * r, r, trim_left=trim_left)
        for u, dil in zip(blk["units"], (1, 3, 9)):
            res = w
            w = cconv(u["conv1"], snake(u["snake1"], w), 7, dilation=dil)
            w = cconv(u["conv2"], snake(u["snake2"], w), 1)
            w = w + res
    w = cconv(params["out_conv"], snake(params["out_snake"], w), 7)
    return jnp.clip(w[..., 0], -1.0, 1.0)


def decoder_stack_flat_map(m: dict, prefix: str, path: tuple,
                           n_rates: int) -> None:
    """HF ModuleList names (``{prefix}.N...``) -> paths rooted at
    ``path`` for the decoder stack."""
    m[f"{prefix}.0.conv.weight"] = path + ("dec_in", "w")
    m[f"{prefix}.0.conv.bias"] = path + ("dec_in", "b")
    for i in range(n_rates):
        d = f"{prefix}.{1 + i}.block"
        tgt = path + ("dec_blocks", i)
        m[f"{d}.0.alpha"] = tgt + ("snake", "alpha")
        m[f"{d}.0.beta"] = tgt + ("snake", "beta")
        m[f"{d}.1.conv.weight"] = tgt + ("tconv", "w")
        m[f"{d}.1.conv.bias"] = tgt + ("tconv", "b")
        for j in range(3):
            u = f"{d}.{2 + j}"
            ut = tgt + ("units", j)
            m[f"{u}.act1.alpha"] = ut + ("snake1", "alpha")
            m[f"{u}.act1.beta"] = ut + ("snake1", "beta")
            m[f"{u}.conv1.conv.weight"] = ut + ("conv1", "w")
            m[f"{u}.conv1.conv.bias"] = ut + ("conv1", "b")
            m[f"{u}.act2.alpha"] = ut + ("snake2", "alpha")
            m[f"{u}.act2.beta"] = ut + ("snake2", "beta")
            m[f"{u}.conv2.conv.weight"] = ut + ("conv2", "w")
            m[f"{u}.conv2.conv.bias"] = ut + ("conv2", "b")
    last = 1 + n_rates
    m[f"{prefix}.{last}.alpha"] = path + ("out_snake", "alpha")
    m[f"{prefix}.{last}.beta"] = path + ("out_snake", "beta")
    m[f"{prefix}.{last + 1}.conv.weight"] = path + ("out_conv", "w")
    m[f"{prefix}.{last + 1}.conv.bias"] = path + ("out_conv", "b")
