"""T5 / UMT5 text encoder (diffusion-pipeline conditioning stack).

Checkpoint-schema implementation of the transformers
``T5EncoderModel`` / ``UMT5EncoderModel`` encoders — the text towers the
reference's Wan (UMT5-XXL), SD3 and Flux (T5-XL) pipelines condition on
(reference: vllm_omni/diffusion/models/wan2_2/pipeline_wan2_2.py text
encoder; diffusers loads them via transformers).  T5 specifics honored
exactly: pre-RMSNorm without mean subtraction or bias, NO 1/sqrt(d)
attention scaling (folded into init), bucketed relative position bias
(shared across layers for T5, per-layer for UMT5), gated-GELU or ReLU
feed-forward.

TPU-first: pure functions over a param pytree; the relative-position
bucket table is precomputed host-side per (bucketed) sequence length so
the jitted forward sees a static gather.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.models.common import nn
from vllm_omni_tpu.ops import rms_norm

logger = init_logger(__name__)


@dataclass(frozen=True)
class T5Config:
    vocab_size: int = 256384
    d_model: int = 4096
    d_kv: int = 64
    d_ff: int = 10240
    num_layers: int = 24
    num_heads: int = 64
    rel_buckets: int = 32
    rel_max_distance: int = 128
    eps: float = 1e-6
    gated_act: bool = True      # gated-gelu (wi_0/wi_1) vs relu (wi)
    per_layer_rel_bias: bool = True  # UMT5: every layer; T5: layer 0 only

    @staticmethod
    def tiny(vocab_size: int = 64) -> "T5Config":
        return T5Config(vocab_size=vocab_size, d_model=32, d_kv=8,
                        d_ff=64, num_layers=2, num_heads=4)

    @staticmethod
    def from_hf(d: dict) -> "T5Config":
        act = d.get("feed_forward_proj", "gated-gelu")
        return T5Config(
            vocab_size=d.get("vocab_size", 256384),
            d_model=d.get("d_model", 4096),
            d_kv=d.get("d_kv", 64),
            d_ff=d.get("d_ff", 10240),
            num_layers=d.get("num_layers", 24),
            num_heads=d.get("num_heads", 64),
            rel_buckets=d.get("relative_attention_num_buckets", 32),
            rel_max_distance=d.get("relative_attention_max_distance",
                                   128),
            eps=d.get("layer_norm_epsilon", 1e-6),
            gated_act="gated" in act,
            per_layer_rel_bias=d.get("model_type", "umt5") == "umt5",
        )


def init_params(key, cfg: T5Config, dtype=jnp.float32):
    ki = iter(jax.random.split(key, 2 + 8 * cfg.num_layers))
    d = cfg.d_model
    inner = cfg.num_heads * cfg.d_kv
    p = {
        "embed": nn.embedding_init(next(ki), cfg.vocab_size, d, dtype),
        "final_norm": nn.rmsnorm_init(d, dtype),
        "layers": [],
    }
    for i in range(cfg.num_layers):
        layer = {
            "attn_norm": nn.rmsnorm_init(d, dtype),
            "q": nn.linear_init(next(ki), d, inner, bias=False,
                                dtype=dtype),
            "k": nn.linear_init(next(ki), d, inner, bias=False,
                                dtype=dtype),
            "v": nn.linear_init(next(ki), d, inner, bias=False,
                                dtype=dtype),
            "o": nn.linear_init(next(ki), inner, d, bias=False,
                                dtype=dtype),
            "ff_norm": nn.rmsnorm_init(d, dtype),
        }
        if cfg.gated_act:
            layer["wi_0"] = nn.linear_init(next(ki), d, cfg.d_ff,
                                           bias=False, dtype=dtype)
            layer["wi_1"] = nn.linear_init(next(ki), d, cfg.d_ff,
                                           bias=False, dtype=dtype)
        else:
            layer["wi"] = nn.linear_init(next(ki), d, cfg.d_ff,
                                         bias=False, dtype=dtype)
        layer["wo"] = nn.linear_init(next(ki), cfg.d_ff, d, bias=False,
                                     dtype=dtype)
        if cfg.per_layer_rel_bias or i == 0:
            layer["rel_bias"] = nn.embedding_init(
                next(ki), cfg.rel_buckets, cfg.num_heads, dtype)
        p["layers"].append(layer)
    return p


def relative_position_buckets(seq_len: int, num_buckets: int,
                              max_distance: int) -> np.ndarray:
    """[S, S] bucket ids (bidirectional; transformers
    T5Attention._relative_position_bucket).  Host-side: the table is a
    static operand of the jitted forward."""
    ctx = np.arange(seq_len)
    rel = ctx[None, :] - ctx[:, None]  # memory - query
    nb = num_buckets // 2
    buckets = (rel > 0).astype(np.int64) * nb
    rel = np.abs(rel)
    max_exact = nb // 2
    is_small = rel < max_exact
    large = max_exact + (
        np.log(np.maximum(rel, 1) / max_exact)
        / math.log(max_distance / max_exact) * (nb - max_exact)
    ).astype(np.int64)
    large = np.minimum(large, nb - 1)
    buckets += np.where(is_small, rel, large)
    return buckets


def forward(params, cfg: T5Config, token_ids: jax.Array,
            mask: jax.Array | None = None) -> jax.Array:
    """token_ids [B, S] (+ padding mask [B, S], 1 = live) ->
    last_hidden_state [B, S, d_model]."""
    b, s = token_ids.shape
    x = nn.embedding(params["embed"], token_ids)
    buckets = jnp.asarray(
        relative_position_buckets(s, cfg.rel_buckets,
                                  cfg.rel_max_distance))
    pad_bias = (jnp.where(mask > 0, 0.0, -1e30)[:, None, None, :]
                if mask is not None else 0.0)
    rel_bias = None
    for layer in params["layers"]:
        if "rel_bias" in layer:
            # [S, S, H] -> [H, S, S]
            rel_bias = jnp.transpose(
                nn.embedding(layer["rel_bias"], buckets), (2, 0, 1))
        h = rms_norm(x, layer["attn_norm"]["w"], cfg.eps)
        q = nn.linear(layer["q"], h).reshape(b, s, cfg.num_heads, -1)
        k = nn.linear(layer["k"], h).reshape(b, s, cfg.num_heads, -1)
        v = nn.linear(layer["v"], h).reshape(b, s, cfg.num_heads, -1)
        # NO 1/sqrt(d_kv) scale: T5 folds it into the init
        scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                            k.astype(jnp.float32),
                            precision=jax.lax.Precision.HIGHEST)
        scores = scores + rel_bias[None].astype(jnp.float32) + pad_bias
        a = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
        o = jnp.einsum("bhqk,bkhd->bqhd", a, v,
                       precision=jax.lax.Precision.HIGHEST)
        x = x + nn.linear(layer["o"], o.reshape(b, s, -1))
        h = rms_norm(x, layer["ff_norm"]["w"], cfg.eps)
        if cfg.gated_act:
            h = (jax.nn.gelu(nn.linear(layer["wi_0"], h),
                             approximate=True)
                 * nn.linear(layer["wi_1"], h))
        else:
            h = jax.nn.relu(nn.linear(layer["wi"], h))
        x = x + nn.linear(layer["wo"], h)
    out = rms_norm(x, params["final_norm"]["w"], cfg.eps)
    if mask is not None:
        out = out * mask[..., None].astype(out.dtype)
    return out


# ------------------------------------------------------- checkpoint load
def hf_flat_map(cfg: T5Config, prefix: str = "") -> dict:
    m: dict[str, tuple] = {
        # tied table: checkpoints carry either spelling (save_model
        # dedupes the alias)
        f"{prefix}shared.weight": ("embed", "w"),
        f"{prefix}encoder.embed_tokens.weight": ("embed", "w"),
        f"{prefix}encoder.final_layer_norm.weight": ("final_norm", "w"),
    }
    for i in range(cfg.num_layers):
        blk = f"{prefix}encoder.block.{i}"
        tgt = ("layers", i)
        for hf, ours in (("layer.0.SelfAttention.q", "q"),
                         ("layer.0.SelfAttention.k", "k"),
                         ("layer.0.SelfAttention.v", "v"),
                         ("layer.0.SelfAttention.o", "o")):
            m[f"{blk}.{hf}.weight"] = tgt + (ours, "w")
        m[f"{blk}.layer.0.layer_norm.weight"] = tgt + ("attn_norm", "w")
        m[f"{blk}.layer.1.layer_norm.weight"] = tgt + ("ff_norm", "w")
        ff = ("DenseGatedActDense" if cfg.gated_act else "DenseReluDense")
        # transformers uses DenseReluDense as the attr name for BOTH
        # variants in many checkpoints; accept either spelling
        for dense in (ff, "DenseReluDense", "DenseGatedActDense"):
            if cfg.gated_act:
                m.setdefault(f"{blk}.layer.1.{dense}.wi_0.weight",
                             tgt + ("wi_0", "w"))
                m.setdefault(f"{blk}.layer.1.{dense}.wi_1.weight",
                             tgt + ("wi_1", "w"))
            else:
                m.setdefault(f"{blk}.layer.1.{dense}.wi.weight",
                             tgt + ("wi", "w"))
            m.setdefault(f"{blk}.layer.1.{dense}.wo.weight",
                         tgt + ("wo", "w"))
        if cfg.per_layer_rel_bias or i == 0:
            m[f"{blk}.layer.0.SelfAttention.relative_attention_bias"
              f".weight"] = tgt + ("rel_bias", "w")
    return m


def hf_transform(name: str, arr):
    """Linears [out, in] -> [in, out]; embeddings (shared token table and
    the [num_buckets, n_heads] relative bias) stay as stored."""
    if arr.ndim == 2 and "shared" not in name \
            and "embed_tokens" not in name \
            and "relative_attention_bias" not in name:
        return arr.T
    return arr


def load_t5(model_dir: str, cfg: T5Config = None, dtype=jnp.float32,
            prefix: str = "", hf_cfg: dict = None):
    """Stream a T5/UMT5 encoder out of a checkpoint directory."""
    from vllm_omni_tpu.model_loader.safetensors_loader import (
        load_checkpoint_tree,
    )

    if cfg is None:
        cfg = T5Config.from_hf(hf_cfg or {})
    shapes = jax.eval_shape(
        lambda: init_params(jax.random.PRNGKey(0), cfg, jnp.float32))
    tree = jax.tree.map(lambda t: np.zeros(t.shape, np.float32), shapes)
    flat = hf_flat_map(cfg, prefix)
    # count coverage per UNIQUE leaf path: a checkpoint carrying BOTH
    # spellings of the tied token table (shared.weight /
    # encoder.embed_tokens.weight) must not mask a genuinely missing
    # tensor elsewhere
    seen: set[tuple] = set()

    def name_map(nm):
        path = flat.get(nm)
        if path is not None:
            seen.add(path)
        return path

    load_checkpoint_tree(
        model_dir, name_map, tree, dtype=np.float32,
        transform=hf_transform, name_filter=lambda nm: nm in flat,
    )
    n_leaves = len(jax.tree.leaves(tree))
    if len(seen) < n_leaves:
        raise ValueError(
            f"{model_dir} covered {len(seen)}/{n_leaves} T5 encoder "
            f"weights")
    return jax.tree.map(lambda a: jnp.asarray(a, dtype), tree), cfg
