"""Generic Qwen-style causal transformer (GQA + RoPE + RMSNorm + SwiGLU).

One implementation serves every AR component in the framework: the
diffusion pipelines' text encoder (reference: Qwen2.5-VL encode_prompt,
pipeline_qwen_image.py:622-636), the Qwen3-Omni thinker/talker backbones
(reference: models/qwen3_omni/qwen3_moe.py — dense variant first, MoE via
``moe=True``), and the TTS LM.  Pure functions over a param pytree; both a
full-sequence forward (prefill / text encoding) and a paged-KV decode step
for the continuous-batching engine.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from vllm_omni_tpu.models.common import nn
from vllm_omni_tpu.ops import (
    apply_rope,
    cache_shape,
    compute_mrope_freqs,
    compute_rope_freqs,
    flash_attention,
    gather_pages,
    paged_attention,
    ragged_paged_attention,
    rms_norm,
    silu_mul,
    write_kv_cache,
)


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    hidden_size: int = 1024
    num_layers: int = 8
    num_heads: int = 8
    num_kv_heads: int = 4
    head_dim: int = 128
    intermediate_size: int = 4096
    rope_theta: float = 1e6
    rms_eps: float = 1e-6
    # Multimodal 3D-RoPE: when set (3 splits of head_dim//2), positions are
    # [B, 3, S] (temporal/height/width streams, models/common/mrope.py)
    # instead of [B, S] (reference: OmniMRotaryEmbedding, mrope.py:25)
    mrope_sections: Optional[tuple[int, int, int]] = None
    qk_norm: bool = False  # per-head q/k RMSNorm (Qwen3 style)
    attention_bias: bool = False  # q/k/v projection biases (Qwen2 style)
    tie_word_embeddings: bool = False
    # Mixture-of-Experts (Qwen3-MoE style: softmax-topk router, normalized
    # gate weights; reference backbone models/qwen3_omni/qwen3_moe.py).
    # Expert weights are stacked on a leading E axis — shard it over the
    # mesh "ep" axis and GSPMD partitions the expert einsums (EP).
    moe: bool = False
    num_experts: int = 8
    num_experts_per_tok: int = 2
    moe_intermediate_size: int = 0  # 0 => intermediate_size
    # norm_topk_prob: Qwen3-MoE renormalizes the kept router weights;
    # the Qwen3-Omni talker keeps raw softmax mass (False)
    moe_renormalize: bool = True
    # Qwen2-MoE-style always-on shared expert beside the routed ones,
    # combined through a learned sigmoid gate (the Qwen3-Omni talker LM,
    # transformers Qwen3OmniMoeTalkerTextSparseMoeBlock); 0 => none
    shared_expert_size: int = 0
    # "routed" (grouped-matmul top-k dispatch; EP over the mesh "ep" axis
    # when ops.moe.set_ep_mesh was called) | "dense" (oracle: all experts
    # compute all tokens)
    moe_dispatch: str = "routed"
    # Tensor parallelism (Megatron col/row sharding over a mesh axis).
    # When set, the forward runs INSIDE shard_map over this axis with
    # per-shard weights (heads and MLP columns divided): psum after
    # o_proj/down restores full activations, all_gather reassembles
    # vocab-sharded logits.  None => single-shard semantics, no
    # collectives (reference: tensor_parallel_size in stage YAML,
    # model_executor/stage_configs/qwen3_omni_moe.yaml:27).
    tp_axis: Optional[str] = None

    @staticmethod
    def tiny(vocab_size: int = 128) -> "TransformerConfig":
        return TransformerConfig(
            vocab_size=vocab_size,
            hidden_size=64,
            num_layers=2,
            num_heads=4,
            num_kv_heads=2,
            head_dim=16,
            intermediate_size=128,
        )

    @staticmethod
    def tiny_moe(vocab_size: int = 128) -> "TransformerConfig":
        return TransformerConfig(
            vocab_size=vocab_size,
            hidden_size=64,
            num_layers=2,
            num_heads=4,
            num_kv_heads=2,
            head_dim=16,
            intermediate_size=128,
            moe=True,
            num_experts=4,
            num_experts_per_tok=2,
            moe_intermediate_size=64,
        )


def init_params(key, cfg: TransformerConfig, dtype=jnp.float32):
    keys = jax.random.split(key, cfg.num_layers + 3)
    params = {
        "embed": nn.embedding_init(keys[0], cfg.vocab_size, cfg.hidden_size, dtype),
        "final_norm": nn.rmsnorm_init(cfg.hidden_size, dtype),
        "layers": [],
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = nn.linear_init(
            keys[1], cfg.hidden_size, cfg.vocab_size, bias=False, dtype=dtype
        )
    q_dim = cfg.num_heads * cfg.head_dim
    kv_dim = cfg.num_kv_heads * cfg.head_dim
    for i in range(cfg.num_layers):
        k = jax.random.split(keys[i + 3], 8)
        qkv_bias = cfg.attention_bias
        layer = {
            "input_norm": nn.rmsnorm_init(cfg.hidden_size, dtype),
            "q_proj": nn.linear_init(k[0], cfg.hidden_size, q_dim, bias=qkv_bias, dtype=dtype),
            "k_proj": nn.linear_init(k[1], cfg.hidden_size, kv_dim, bias=qkv_bias, dtype=dtype),
            "v_proj": nn.linear_init(k[2], cfg.hidden_size, kv_dim, bias=qkv_bias, dtype=dtype),
            "o_proj": nn.linear_init(k[3], q_dim, cfg.hidden_size, bias=False, dtype=dtype),
            "post_norm": nn.rmsnorm_init(cfg.hidden_size, dtype),
        }
        if cfg.moe:
            e = cfg.num_experts
            inter = cfg.moe_intermediate_size or cfg.intermediate_size
            scale_in = 1.0 / (cfg.hidden_size ** 0.5)
            scale_out = 1.0 / (inter ** 0.5)
            k6, k7 = jax.random.split(k[6])
            layer["router"] = nn.linear_init(
                k[5], cfg.hidden_size, e, bias=False, dtype=dtype
            )
            # stacked expert weights: leading E axis is the EP shard axis
            layer["experts"] = {
                "gate_up": jax.random.uniform(
                    k6, (e, cfg.hidden_size, 2 * inter), dtype,
                    minval=-scale_in, maxval=scale_in,
                ),
                "down": jax.random.uniform(
                    k7, (e, inter, cfg.hidden_size), dtype,
                    minval=-scale_out, maxval=scale_out,
                ),
            }
            if cfg.shared_expert_size:
                ks1, ks2, ks3 = jax.random.split(k[7], 3)
                layer["shared_expert"] = {
                    "gate_up": nn.linear_init(
                        ks1, cfg.hidden_size, 2 * cfg.shared_expert_size,
                        bias=False, dtype=dtype),
                    "down": nn.linear_init(
                        ks2, cfg.shared_expert_size, cfg.hidden_size,
                        bias=False, dtype=dtype),
                }
                layer["shared_gate"] = nn.linear_init(
                    ks3, cfg.hidden_size, 1, bias=False, dtype=dtype)
        else:
            layer["gate_up"] = nn.linear_init(
                k[4], cfg.hidden_size, 2 * cfg.intermediate_size, bias=False, dtype=dtype
            )
            layer["down"] = nn.linear_init(
                k[5], cfg.intermediate_size, cfg.hidden_size, bias=False, dtype=dtype
            )
        if cfg.qk_norm:
            layer["q_norm"] = nn.rmsnorm_init(cfg.head_dim, dtype)
            layer["k_norm"] = nn.rmsnorm_init(cfg.head_dim, dtype)
        params["layers"].append(layer)
    return params


def _qkv(layer, cfg: TransformerConfig, x):
    """x: [T, hidden] -> q [T, H, D], k/v [T, Hkv, D] with RoPE-ready
    layout.  Head counts derive from the weights, not the config: under
    tensor parallelism each shard carries num_heads/tp heads."""
    t = x.shape[0]
    q = nn.linear(layer["q_proj"], x).reshape(t, -1, cfg.head_dim)
    k = nn.linear(layer["k_proj"], x).reshape(t, -1, cfg.head_dim)
    v = nn.linear(layer["v_proj"], x).reshape(t, -1, cfg.head_dim)
    if cfg.qk_norm:
        q = rms_norm(q, layer["q_norm"]["w"], cfg.rms_eps)
        k = rms_norm(k, layer["k_norm"]["w"], cfg.rms_eps)
    return q, k, v


def _moe_mlp_dense(layer, cfg: TransformerConfig, x):
    """Dense-dispatch MoE oracle: every expert computes every token,
    combined with the (renormalized) top-k router weights as a [T, E]
    mask.  Kept as the numerics oracle for the routed path (and the
    GSPMD fallback when neither routing mode applies); a k/E FLOP waste
    at real geometries (VERDICT r1 weak#4)."""
    t = x.shape[0]
    router_logits = x @ layer["router"]["w"]  # [T, E]
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    topk_w, topk_idx = jax.lax.top_k(probs, cfg.num_experts_per_tok)
    if cfg.moe_renormalize:
        topk_w = topk_w / jnp.sum(topk_w, axis=-1, keepdims=True)
    # [T, E] combine weights (zero for non-selected experts)
    combine = jnp.zeros_like(probs).at[
        jnp.arange(t)[:, None], topk_idx
    ].set(topk_w)
    h = jnp.einsum("th,ehf->etf", x, layer["experts"]["gate_up"])
    h = silu_mul(h)
    y = jnp.einsum("etf,efh->eth", h, layer["experts"]["down"])
    return jnp.einsum("eth,te->th", y, combine.astype(x.dtype))


def _moe_mlp(layer, cfg: TransformerConfig, x):
    """Top-k MoE dispatch.  Default: routed grouped-matmul (ops/moe.py —
    FLOPs scale with top-k, not E), expert-parallel over the mesh ``ep``
    axis when one is registered via ``ops.moe.set_ep_mesh``.  The dense
    path (``cfg.moe_dispatch == "dense"``) is the test oracle."""
    lead = x.shape[:-1]
    x = x.reshape(-1, x.shape[-1])
    if cfg.moe_dispatch == "dense":
        out = _moe_mlp_dense(layer, cfg, x)
    else:
        from vllm_omni_tpu.ops import moe as moe_ops

        mesh = moe_ops.ep_mesh()
        if mesh is not None:
            ep_fn = (moe_ops.routed_moe_ep_a2a
                     if cfg.moe_dispatch == "a2a"
                     else moe_ops.routed_moe_ep)
            out = ep_fn(
                x, layer["router"]["w"], layer["experts"]["gate_up"],
                layer["experts"]["down"], cfg.num_experts_per_tok, mesh,
                renormalize=cfg.moe_renormalize,
            )
        else:
            out = moe_ops.routed_moe(
                x, layer["router"]["w"], layer["experts"]["gate_up"],
                layer["experts"]["down"], cfg.num_experts_per_tok,
                renormalize=cfg.moe_renormalize,
            )
    if "shared_expert" in layer:
        # always-on shared expert, sigmoid-gated per token
        se = nn.linear(layer["shared_expert"]["down"],
                       silu_mul(nn.linear(layer["shared_expert"]["gate_up"],
                                          x)))
        gate = jax.nn.sigmoid(
            nn.linear(layer["shared_gate"], x).astype(jnp.float32))
        out = out + (gate.astype(se.dtype) * se)
    return out.reshape(*lead, out.shape[-1])


def _mlp(layer, cfg: TransformerConfig, x):
    if cfg.moe:
        return _moe_mlp(layer, cfg, x)
    return nn.linear(layer["down"], silu_mul(nn.linear(layer["gate_up"], x)))


def _rope_tables(cfg: TransformerConfig, positions):
    """cos/sin tables from positions, 1-D or multimodal 3-D.

    1-D rope: positions [B, S] (prefill) or [B] (decode).  MRoPE
    (cfg.mrope_sections set): [B, 3, S] or [B, 3] — the three streams are
    flattened batch-major to match the activations' [B*S] layout.
    """
    if cfg.mrope_sections is None:
        return compute_rope_freqs(
            positions.reshape(-1), cfg.head_dim, cfg.rope_theta
        )
    if positions.ndim == 3:  # [B, 3, S] -> [3, B*S]
        p = positions.transpose(1, 0, 2).reshape(3, -1)
    else:  # [B, 3] -> [3, B]
        p = positions.T
    return compute_mrope_freqs(
        p, cfg.head_dim, cfg.mrope_sections, cfg.rope_theta
    )


def _embed_input(params, token_ids, inputs_embeds, embeds_mask):
    """Token embedding, or upstream-stage hidden states as inputs.

    ``inputs_embeds`` replaces the embedding lookup — the embeds-as-input
    path a downstream stage uses to consume upstream hidden states
    (reference: OmniGPUModelRunner._preprocess override,
    worker/gpu_model_runner.py:925).  An optional ``embed_proj`` adapts a
    different upstream width (reference: the talker projects thinker
    hidden states, models/qwen3_omni/qwen3_omni_moe_talker.py).
    ``embeds_mask`` selects per position: True rows take (projected)
    embeds, False rows the token embedding — needed when a preempted
    embeds request re-prefills prompt *and* generated tokens, whose
    embeddings come from the table.
    """
    if inputs_embeds is None:
        return nn.embedding(params["embed"], token_ids)
    x = inputs_embeds
    if "embed_proj" in params:
        proj = params["embed_proj"]
        if "fc1" in proj:
            # two-layer ResizeMLP (the talker's hidden_projection,
            # transformers Qwen3OmniMoeTalkerResizeMLP)
            x = nn.linear(proj["fc2"], jax.nn.silu(nn.linear(proj["fc1"],
                                                             x)))
        else:
            x = nn.linear(proj, x)
    if embeds_mask is not None:
        tok = nn.embedding(params["embed"], token_ids)
        x = jnp.where(embeds_mask[..., None], x, tok)
    return x


def _layer_step(layer, cfg: TransformerConfig, x, cos, sin, attend):
    """One transformer block: norm → qkv+rope → ``attend`` → residual →
    norm → MLP → residual.  ``attend(q, k, v)`` supplies the attention
    variant (dense causal / cached-context chunked / paged decode) and
    returns o with leading dims matching ``x``'s.  One body serves every
    forward so the variants cannot silently diverge."""
    b = x.shape[:-1]
    h = rms_norm(x, layer["input_norm"]["w"], cfg.rms_eps)
    q, k, v = _qkv(layer, cfg, h.reshape(-1, h.shape[-1]))
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    o = attend(q, k, v)
    o = o.reshape(*b, -1) @ layer["o_proj"]["w"]
    if cfg.tp_axis is not None:
        # row-parallel o_proj: each shard holds a partial sum
        o = jax.lax.psum(o, cfg.tp_axis)
    x = x + o
    h = rms_norm(x, layer["post_norm"]["w"], cfg.rms_eps)
    y = _mlp(layer, cfg, h)
    if cfg.tp_axis is not None:
        y = jax.lax.psum(y, cfg.tp_axis)
    return x + y


def forward_hidden(
    params,
    cfg: TransformerConfig,
    token_ids: jax.Array,  # [B, S]
    positions: Optional[jax.Array] = None,  # [B, S]
    inputs_embeds: Optional[jax.Array] = None,  # [B, S, hidden]
    attn_mask: Optional[jax.Array] = None,  # [B, S] 1=attendable key
    drop_last_layers: int = 0,
    apply_final_norm: bool = True,
    collect_hidden_layers: tuple = (),
    embeds_mask: Optional[jax.Array] = None,  # [B, S] True=row uses embeds
) -> jax.Array:
    """Full-sequence causal forward returning final hidden states
    [B, S, hidden] (the text-encoder path; also prefill without cache).

    ``attn_mask`` excludes padded KEY positions on top of causality —
    needed when padding sits mid-sequence (LongCat-Image pads the user
    prompt to a fixed length BETWEEN the template prefix and suffix, so
    suffix tokens would otherwise attend pad keys).

    ``drop_last_layers=1, apply_final_norm=False`` yields the HF
    ``output_hidden_states[-2]`` convention (the penultimate layer's
    raw output) that Z-Image conditions on (pipeline_z_image.py:261-266).

    ``collect_hidden_layers``: HF hidden_states indices (0 = embeddings,
    k = after layer k) to gather and concatenate on the feature axis —
    the Flux2-Klein text conditioning stacks Qwen3 layers (9, 18, 27)
    (pipeline_flux2_klein.py:247-302).  When set, the concatenation is
    returned instead of the final hidden states.
    """
    b, s = token_ids.shape
    x = _embed_input(params, token_ids, inputs_embeds, embeds_mask)
    if positions is None:
        shape = (b, s) if cfg.mrope_sections is None else (b, 3, s)
        positions = jnp.broadcast_to(jnp.arange(s)[None, :], shape)
    cos, sin = _rope_tables(cfg, positions)

    def attend(q, k, v):
        return flash_attention(
            q.reshape(b, s, -1, cfg.head_dim),
            k.reshape(b, s, -1, cfg.head_dim),
            v.reshape(b, s, -1, cfg.head_dim),
            causal=True,
            kv_mask=attn_mask,
        )

    layers = params["layers"]
    if drop_last_layers:
        layers = layers[:len(layers) - drop_last_layers]
    collected = {0: x} if 0 in collect_hidden_layers else {}
    for li, layer in enumerate(layers):
        x = _layer_step(layer, cfg, x, cos, sin, attend)
        if li + 1 in collect_hidden_layers:
            collected[li + 1] = x
    if collect_hidden_layers:
        missing = [k for k in collect_hidden_layers if k not in collected]
        if missing:
            raise ValueError(
                f"collect_hidden_layers {missing} out of range for "
                f"{len(layers)} layers")
        return jnp.concatenate(
            [collected[k] for k in collect_hidden_layers], axis=-1)
    if not apply_final_norm:
        return x
    return rms_norm(x, params["final_norm"]["w"], cfg.rms_eps)


def logits_from_hidden(params, cfg: TransformerConfig, hidden: jax.Array):
    if cfg.tie_word_embeddings:
        # embed table is replicated under TP — logits already full
        return hidden @ params["embed"]["w"].T
    logits = nn.linear(params["lm_head"], hidden)
    if cfg.tp_axis is not None:
        # column-parallel lm_head: reassemble the vocab axis
        logits = jax.lax.all_gather(
            logits, cfg.tp_axis, axis=logits.ndim - 1, tiled=True)
    return logits


def forward_prefill(
    params,
    cfg: TransformerConfig,
    token_ids: jax.Array,  # [B, S] (right-padded)
    positions: jax.Array,  # [B, S]
    kv_caches: list,  # per-layer (k, v) paged caches
    slot_mapping: jax.Array,  # [B, S] flat slots (-1 for padding)
    inputs_embeds: Optional[jax.Array] = None,  # [B, S, embed_width]
    embeds_mask: Optional[jax.Array] = None,  # [B, S] bool: row uses embeds
    deepstack: Optional[jax.Array] = None,  # [B, n_deep, S, hidden]
):
    """Prefill: causal attention within the prompt, writing KV pages
    (embeds-as-input handling: see ``_embed_input``).

    ``deepstack`` carries multiscale visual features (zeros at non-visual
    positions); level ``i`` is added to the residual stream after decoder
    layer ``i`` (reference: Qwen3-Omni thinker deepstack injection,
    qwen3_omni_moe_thinker.py:177-178).

    Returns (hidden [B, S, hidden], new kv_caches).
    """
    b, s = token_ids.shape
    x = _embed_input(params, token_ids, inputs_embeds, embeds_mask)
    cos, sin = _rope_tables(cfg, positions)
    flat_slots = slot_mapping.reshape(-1)
    new_caches = []
    for i, (layer, (k_cache, v_cache)) in enumerate(
            zip(params["layers"], kv_caches)):
        def attend(q, k, v, k_cache=k_cache, v_cache=v_cache):
            k_cache, v_cache = write_kv_cache(
                k_cache, v_cache, k, v, flat_slots
            )
            new_caches.append((k_cache, v_cache))
            return flash_attention(
                q.reshape(b, s, -1, cfg.head_dim),
                k.reshape(b, s, -1, cfg.head_dim),
                v.reshape(b, s, -1, cfg.head_dim),
                causal=True,
            )

        x = _layer_step(layer, cfg, x, cos, sin, attend)
        if deepstack is not None and i < deepstack.shape[1]:
            x = x + deepstack[:, i].astype(x.dtype)
    return rms_norm(x, params["final_norm"]["w"], cfg.rms_eps), new_caches


def forward_prefill_chunked(
    params,
    cfg: TransformerConfig,
    token_ids: jax.Array,  # [B, S] chunk tokens (right-padded)
    positions: jax.Array,  # [B, S] global positions
    kv_caches: list,
    slot_mapping: jax.Array,  # [B, S] flat slots (-1 for padding)
    block_tables: jax.Array,  # [B, max_pages] page ids covering the context
    context_lens: jax.Array,  # [B] prefix + chunk length
    q_starts: jax.Array,  # [B] global position of the chunk's first token
    inputs_embeds: Optional[jax.Array] = None,
    embeds_mask: Optional[jax.Array] = None,
    deepstack: Optional[jax.Array] = None,  # [B, n_deep, S, hidden]
):
    """Prefill continuation: a chunk attends the cached KV of earlier
    chunks plus itself causally (chunked prefill — the capability the
    reference inherits from vLLM's scheduler and the r1 scheduler left as
    NotImplementedError).  ``deepstack`` rows cover THIS chunk's positions
    (the caller slices the request-level table like prompt_embeds).

    The chunk's KV is written to the paged cache first, then each layer
    gathers the full context ``[B, ctx, Hkv, D]`` through ``block_tables``
    and runs flash attention with per-sequence query offsets
    (``q_starts``) so query i attends keys at positions <= q_starts+i.
    Peak memory is O(B*ctx*Hkv*D) per layer — pages, never O(S²).

    Returns (hidden [B, S, hidden], new kv_caches).
    """
    b, s = token_ids.shape
    hkv, _, page_size, d = cache_shape(kv_caches[0][0])
    x = _embed_input(params, token_ids, inputs_embeds, embeds_mask)
    cos, sin = _rope_tables(cfg, positions)
    flat_slots = slot_mapping.reshape(-1)
    ctx_width = block_tables.shape[1] * page_size
    kv_mask = (jnp.arange(ctx_width)[None, :]
               < context_lens[:, None]).astype(jnp.int32)
    new_caches = []
    for i, (layer, (k_cache, v_cache)) in enumerate(
            zip(params["layers"], kv_caches)):
        def attend(q, k, v, k_cache=k_cache, v_cache=v_cache):
            k_cache, v_cache = write_kv_cache(
                k_cache, v_cache, k, v, flat_slots
            )
            new_caches.append((k_cache, v_cache))
            # gather context pages: [Hkv, B, P, page, D] -> [B, ctx, Hkv, D]
            # (gather_pages dequantizes the int8 layout's pages)
            kg = jnp.transpose(
                gather_pages(k_cache, block_tables), (1, 2, 3, 0, 4)
            ).reshape(b, ctx_width, hkv, d).astype(k.dtype)
            vg = jnp.transpose(
                gather_pages(v_cache, block_tables), (1, 2, 3, 0, 4)
            ).reshape(b, ctx_width, hkv, d).astype(v.dtype)
            return flash_attention(
                q.reshape(b, s, -1, cfg.head_dim), kg, vg,
                causal=True, kv_mask=kv_mask, q_offsets=q_starts,
            )

        x = _layer_step(layer, cfg, x, cos, sin, attend)
        if deepstack is not None and i < deepstack.shape[1]:
            x = x + deepstack[:, i].astype(x.dtype)
    return rms_norm(x, params["final_norm"]["w"], cfg.rms_eps), new_caches


def forward_unified(
    params,
    cfg: TransformerConfig,
    token_ids: jax.Array,    # [T] token-packed mixed batch
    positions: jax.Array,    # [T] global positions ([3, T] under mrope)
    kv_caches: list,
    slot_mapping: jax.Array,  # [T] flat slots (-1 for padding rows)
    page_tables: jax.Array,   # [S, max_pages]
    seq_lens: jax.Array,      # [S] context incl. this step's tokens
    cu_q_lens: jax.Array,     # [S+1] aligned packed segment starts
    q_lens: jax.Array,        # [S] real token count per sequence
    num_seqs: jax.Array,      # [1]
    inputs_embeds: Optional[jax.Array] = None,  # [T, embed_width]
    embeds_mask: Optional[jax.Array] = None,    # [T] True=row uses embeds
    deepstack: Optional[jax.Array] = None,      # [n_deep, T, hidden]
):
    """Unified ragged mixed-batch forward: prefill chunks and 1-token
    decode rows share ONE token-packed execution (ops/
    ragged_paged_attention.py; layout contract in its module docstring
    and docs/ragged_batching.md).  Each layer scatters the step's KV
    through the slot mapping, then attends the paged context raggedly —
    replacing the fresh/chunk/decode triple dispatch for mixed steps.

    ``inputs_embeds``/``embeds_mask`` are the embeds-as-input path
    scattered onto the packed token axis (see ``_embed_input``);
    ``deepstack`` carries multiscale visual features per packed row
    (zeros at non-visual rows), level ``i`` added to the residual
    stream after decoder layer ``i`` — the same contract as
    ``forward_prefill``, so embeds/deepstack batches ride the unified
    dispatch instead of a separately padded executable.

    Returns (hidden [T, hidden], new kv_caches).
    """
    x = _embed_input(params, token_ids, inputs_embeds, embeds_mask)
    if cfg.mrope_sections is None:
        cos, sin = _rope_tables(cfg, positions)
    else:
        # [3, T] -> the [B, 3, S] convention with B=1
        cos, sin = _rope_tables(cfg, positions[None])
    new_caches = []
    for i, (layer, (k_cache, v_cache)) in enumerate(
            zip(params["layers"], kv_caches)):
        def attend(q, k, v, k_cache=k_cache, v_cache=v_cache):
            k_cache, v_cache = write_kv_cache(
                k_cache, v_cache, k, v, slot_mapping
            )
            new_caches.append((k_cache, v_cache))
            return ragged_paged_attention(
                q, k_cache, v_cache, page_tables, cu_q_lens, q_lens,
                seq_lens, num_seqs,
            )

        x = _layer_step(layer, cfg, x, cos, sin, attend)
        if deepstack is not None and i < deepstack.shape[0]:
            x = x + deepstack[i].astype(x.dtype)
    return rms_norm(x, params["final_norm"]["w"], cfg.rms_eps), new_caches


def forward_decode(
    params,
    cfg: TransformerConfig,
    token_ids: jax.Array,  # [B]
    positions: jax.Array,  # [B]
    kv_caches: list,
    slot_mapping: jax.Array,  # [B]
    block_tables: jax.Array,  # [B, max_pages]
    context_lens: jax.Array,  # [B] (including the new token)
):
    """One decode step over a batch of sequences with paged attention.

    Returns (hidden [B, hidden], new kv_caches).
    """
    x = nn.embedding(params["embed"], token_ids)  # [B, hidden]
    cos, sin = _rope_tables(cfg, positions)
    new_caches = []
    for layer, (k_cache, v_cache) in zip(params["layers"], kv_caches):
        def attend(q, k, v, k_cache=k_cache, v_cache=v_cache):
            k_cache, v_cache = write_kv_cache(
                k_cache, v_cache, k, v, slot_mapping
            )
            new_caches.append((k_cache, v_cache))
            return paged_attention(
                q, k_cache, v_cache, block_tables, context_lens
            )

        x = _layer_step(layer, cfg, x, cos, sin, attend)
    return rms_norm(x, params["final_norm"]["w"], cfg.rms_eps), new_caches
