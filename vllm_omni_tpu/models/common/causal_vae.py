"""Wan-family causal 3D VAE (functional JAX, NTHWC) — checkpoint-compatible.

The reference's Qwen-Image VAE *is* the Wan video VAE design (reference:
vllm_omni/diffusion/models/qwen_image/autoencoder_kl_qwenimage.py:667
``AutoencoderKLQwenImage`` — CausalConv3d stacks, channel-RMS norms,
single-head spatial attention in the mid block, and temporal up/down
resampling where the first frame is coded independently so F pixel frames
map to ``1 + (F-1)/4`` latent frames).  Images are 1-frame videos.

TPU-first design notes:
- The reference decodes frame-by-frame with a feature cache (GPU memory
  optimization).  Causal convolutions make that loop equivalent to ONE
  full-sequence convolution with zero left-padding in time, so here the
  whole clip decodes in a single conv pass per layer — XLA sees static
  shapes and large convs for the MXU instead of a Python loop.
- The cached temporal resamplers have first-frame special cases; their
  full-sequence equivalents (derived from the cache protocol at
  autoencoder_kl_qwenimage.py:168-213,629-666) are:
    * upsample3d: frame 0 passes through; frames 1..T-1 run the
      (3,1,1)->2C time conv over a zero-history stream and each output
      splits channel-wise into two interleaved frames.
    * downsample3d: frame 0 passes through; a VALID stride-2 k=3 time
      conv over the full stream yields the remaining frames.
- T==1 (image) inputs take a pure-2D path: with 2 frames of causal zero
  padding, only the LAST temporal kernel tap ever touches data, so each
  3D conv collapses exactly to a 2D conv with ``w[kt-1]``.

Weight layout matches the diffusers checkpoint modulo axis order: conv3d
``[kt, kh, kw, cin, cout]`` (DHWIO), conv2d ``[kh, kw, cin, cout]``
(HWIO), norms ``[C]`` — see ``model_loader/diffusers_loader.py`` for the
name map and axis transposes.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax import lax

# Qwen-Image / Wan2.1 per-channel latent statistics (reference:
# autoencoder_kl_qwenimage.py:692-697 config defaults).
QWEN_IMAGE_LATENTS_MEAN = (
    -0.7571, -0.7089, -0.9113, 0.1075, -0.1745, 0.9653, -0.1517, 1.5508,
    0.4134, -0.0715, 0.5517, -0.3632, -0.1922, -0.9497, 0.2503, -0.2921,
)
QWEN_IMAGE_LATENTS_STD = (
    2.8184, 1.4541, 2.3275, 2.6558, 1.2196, 1.7708, 2.6052, 2.0743,
    3.2687, 2.1526, 2.8652, 1.5579, 1.6382, 1.1253, 2.8251, 1.9160,
)


@dataclass(frozen=True)
class CausalVAEConfig:
    z_channels: int = 16
    base_dim: int = 96
    dim_mult: tuple[int, ...] = (1, 2, 4, 4)
    num_res_blocks: int = 2
    attn_scales: tuple[float, ...] = ()
    # per down-transition (len == len(dim_mult)-1); decoder reverses it
    temporal_downsample: tuple[bool, ...] = (False, True, True)
    latents_mean: tuple[float, ...] | None = None
    latents_std: tuple[float, ...] | None = None

    @property
    def spatial_ratio(self) -> int:
        return 2 ** (len(self.dim_mult) - 1)

    @property
    def latent_channels(self) -> int:
        """Alias so pipelines address the latent width uniformly across
        VAE families."""
        return self.z_channels

    @property
    def temporal_ratio(self) -> int:
        return 2 ** sum(self.temporal_downsample)

    def latent_frames(self, frames: int) -> int:
        if frames < 1:
            raise ValueError("need at least one frame")
        return 1 + -(-(frames - 1) // self.temporal_ratio)

    def pixel_frames(self, latent_frames: int) -> int:
        return 1 + (latent_frames - 1) * self.temporal_ratio

    @staticmethod
    def qwen_image() -> "CausalVAEConfig":
        return CausalVAEConfig(
            latents_mean=QWEN_IMAGE_LATENTS_MEAN,
            latents_std=QWEN_IMAGE_LATENTS_STD,
        )

    @staticmethod
    def tiny() -> "CausalVAEConfig":
        return CausalVAEConfig(
            z_channels=4,
            base_dim=8,
            dim_mult=(1, 2),
            num_res_blocks=1,
            temporal_downsample=(True,),
        )


# ----------------------------------------------------------------- helpers
def _uniform(key, shape, fan_in, dtype):
    s = 1.0 / math.sqrt(fan_in)
    return jax.random.uniform(key, shape, dtype, -s, s)


def _c3_init(key, cin, cout, kt, ks, dtype):
    return {
        "w": _uniform(key, (kt, ks, ks, cin, cout), cin * kt * ks * ks, dtype),
        "b": jnp.zeros((cout,), dtype),
    }


def _c2_init(key, cin, cout, ks, dtype):
    return {
        "w": _uniform(key, (ks, ks, cin, cout), cin * ks * ks, dtype),
        "b": jnp.zeros((cout,), dtype),
    }


def _rms_init(ch, dtype):
    return {"g": jnp.ones((ch,), dtype)}


def _rms(p, x):
    """Channel RMS norm (reference QwenImageRMS_norm: L2-normalize over C,
    scale by sqrt(C) * gamma) — channel axis is last in NTHWC."""
    xf = x.astype(jnp.float32)
    n = jnp.sqrt(jnp.sum(xf * xf, axis=-1, keepdims=True))
    c = x.shape[-1]
    y = xf / jnp.maximum(n, 1e-12) * math.sqrt(c)
    return (y * p["g"].astype(jnp.float32)).astype(x.dtype)


def _cconv3d(p, x, t_stride: int = 1, t_pad: str = "causal"):
    """Causal 3D conv over [B, T, H, W, C]; T==1 stride-1 inputs collapse
    to a 2D conv with the last temporal tap (zero history contributes 0)."""
    w = p["w"]
    kt, kh, kw = w.shape[:3]
    if x.shape[1] == 1 and t_stride == 1:
        y = lax.conv_general_dilated(
            x[:, 0], w[kt - 1].astype(x.dtype), (1, 1),
            [(kh // 2, kh // 2), (kw // 2, kw // 2)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )[:, None]
    else:
        pt = (2 * (kt // 2), 0) if t_pad == "causal" else (0, 0)
        y = lax.conv_general_dilated(
            x, w.astype(x.dtype), (t_stride, 1, 1),
            [pt, (kh // 2, kh // 2), (kw // 2, kw // 2)],
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"),
        )
    return y + p["b"].astype(x.dtype)


def _conv2d_frames(p, x, stride: int = 1, padding="SAME"):
    """Per-frame 2D conv: fold T into batch."""
    b, t, h, w, c = x.shape
    y = lax.conv_general_dilated(
        x.reshape(b * t, h, w, c), p["w"].astype(x.dtype), (stride, stride),
        padding if isinstance(padding, list) else padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    ) + p["b"].astype(x.dtype)
    return y.reshape(b, t, *y.shape[1:])


def _res_init(key, cin, cout, dtype):
    k = jax.random.split(key, 3)
    p = {
        "norm1": _rms_init(cin, dtype),
        "conv1": _c3_init(k[0], cin, cout, 3, 3, dtype),
        "norm2": _rms_init(cout, dtype),
        "conv2": _c3_init(k[1], cout, cout, 3, 3, dtype),
    }
    if cin != cout:
        p["skip"] = _c3_init(k[2], cin, cout, 1, 1, dtype)
    return p


def _res(p, x):
    h = _cconv3d(p["skip"], x) if "skip" in p else x
    y = _cconv3d(p["conv1"], jax.nn.silu(_rms(p["norm1"], x)))
    y = _cconv3d(p["conv2"], jax.nn.silu(_rms(p["norm2"], y)))
    return h + y


def _attn_init(key, ch, dtype):
    k = jax.random.split(key, 2)
    return {
        "norm": _rms_init(ch, dtype),
        "qkv": _c2_init(k[0], ch, 3 * ch, 1, dtype),
        "proj": _c2_init(k[1], ch, ch, 1, dtype),
    }


def _attn(p, x):
    """Per-frame single-head spatial attention (reference
    QwenImageAttentionBlock)."""
    b, t, h, w, c = x.shape
    xn = _rms(p["norm"], x).reshape(b * t, h * w, c)
    qkv = xn @ p["qkv"]["w"][0, 0] + p["qkv"]["b"]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    s = jnp.einsum("bqc,bkc->bqk", q, k).astype(jnp.float32) / math.sqrt(c)
    a = jax.nn.softmax(s, axis=-1).astype(x.dtype)
    o = jnp.einsum("bqk,bkc->bqc", a, v) @ p["proj"]["w"][0, 0]
    o = o + p["proj"]["b"]
    return x + o.reshape(b, t, h, w, c)


def _mid_init(key, ch, dtype):
    k = jax.random.split(key, 3)
    return {
        "res0": _res_init(k[0], ch, ch, dtype),
        "attn0": _attn_init(k[1], ch, dtype),
        "res1": _res_init(k[2], ch, ch, dtype),
    }


def _mid(p, x):
    return _res(p["res1"], _attn(p["attn0"], _res(p["res0"], x)))


def _time_upsample(p, x):
    """Cached-protocol equivalent (see module docstring): frame 0 passes
    through; the (3,1,1)->2C conv runs over frames 1.. with zero history,
    each output splitting channel-wise into two frames."""
    if x.shape[1] == 1:
        return x
    c = x.shape[-1]
    h = _cconv3d(p, x[:, 1:])  # [B, T-1, H, W, 2C]
    pairs = jnp.stack([h[..., :c], h[..., c:]], axis=2)
    inter = pairs.reshape(x.shape[0], -1, *x.shape[2:])
    return jnp.concatenate([x[:, :1], inter], axis=1)


def _time_downsample(p, x):
    """Frame 0 passes through; VALID stride-2 k=3 time conv over the full
    stream yields the rest (chunk protocol: windows [x_{2j-2}, x_{2j-1},
    x_{2j}])."""
    if x.shape[1] < 3:
        return x[:, :1]
    rest = _cconv3d(p, x, t_stride=2, t_pad="valid")
    return jnp.concatenate([x[:, :1], rest], axis=1)


def _s_upsample2x(x):
    b, t, h, w, c = x.shape
    y = jnp.repeat(jnp.repeat(x, 2, axis=2), 2, axis=3)
    return y


# ------------------------------------------------------------------ decoder
def _decoder_dims(cfg: CausalVAEConfig) -> list[int]:
    mults = [cfg.dim_mult[-1]] + list(reversed(cfg.dim_mult))
    return [cfg.base_dim * m for m in mults]


def init_decoder(key, cfg: CausalVAEConfig, dtype=jnp.float32):
    dims = _decoder_dims(cfg)
    t_up = tuple(reversed(cfg.temporal_downsample))
    keys = jax.random.split(key, 4 + len(cfg.dim_mult))
    p = {
        "conv_in": _c3_init(keys[0], cfg.z_channels, dims[0], 3, 3, dtype),
        "mid": _mid_init(keys[1], dims[0], dtype),
        "ups": [],
    }
    for i, (cin, cout) in enumerate(zip(dims[:-1], dims[1:])):
        if i > 0:
            cin //= 2
        ks = jax.random.split(keys[2 + i], cfg.num_res_blocks + 3)
        blk = {"res": []}
        cur = cin
        for j in range(cfg.num_res_blocks + 1):
            blk["res"].append(_res_init(ks[j], cur, cout, dtype))
            cur = cout
        if i != len(cfg.dim_mult) - 1:
            blk["up"] = {"conv": _c2_init(ks[-2], cout, cout // 2, 3, dtype)}
            if t_up[i]:
                blk["up"]["time"] = _c3_init(
                    ks[-1], cout, 2 * cout, 3, 1, dtype)
        p["ups"].append(blk)
    out_dim = dims[-1]
    p["norm_out"] = _rms_init(out_dim, dtype)
    p["conv_out"] = _c3_init(keys[-1], out_dim, 3, 3, 3, dtype)
    return p


def decode_core(p, cfg: CausalVAEConfig, z: jax.Array) -> jax.Array:
    """decoder-only: [B, T, h, w, z] (post post_quant_conv) -> pixels."""
    x = _cconv3d(p["conv_in"], z)
    x = _mid(p["mid"], x)
    for blk in p["ups"]:
        for rb in blk["res"]:
            x = _res(rb, x)
        if "up" in blk:
            if "time" in blk["up"]:
                x = _time_upsample(blk["up"]["time"], x)
            x = _conv2d_frames(blk["up"]["conv"], _s_upsample2x(x))
    x = jax.nn.silu(_rms(p["norm_out"], x))
    return jnp.clip(_cconv3d(p["conv_out"], x), -1.0, 1.0)


# ------------------------------------------------------------------ encoder
def _encoder_dims(cfg: CausalVAEConfig) -> list[int]:
    return [cfg.base_dim * m for m in [1] + list(cfg.dim_mult)]


def init_encoder(key, cfg: CausalVAEConfig, dtype=jnp.float32):
    dims = _encoder_dims(cfg)
    keys = jax.random.split(key, 4 + len(cfg.dim_mult))
    p = {
        "conv_in": _c3_init(keys[0], 3, dims[0], 3, 3, dtype),
        "downs": [],
    }
    scale = 1.0
    for i, (cin, cout) in enumerate(zip(dims[:-1], dims[1:])):
        ks = jax.random.split(keys[1 + i], 2 * cfg.num_res_blocks + 2)
        blk = {"res": [], "attn": []}
        cur = cin
        for j in range(cfg.num_res_blocks):
            blk["res"].append(_res_init(ks[j], cur, cout, dtype))
            if scale in cfg.attn_scales:
                blk["attn"].append(_attn_init(ks[cfg.num_res_blocks + j],
                                              cout, dtype))
            cur = cout
        if i != len(cfg.dim_mult) - 1:
            blk["down"] = {"conv": _c2_init(ks[-2], cout, cout, 3, dtype)}
            if cfg.temporal_downsample[i]:
                blk["down"]["time"] = _c3_init(ks[-1], cout, cout, 3, 1,
                                               dtype)
            scale /= 2.0
        p["downs"].append(blk)
    top = dims[-1]
    p["mid"] = _mid_init(keys[-2], top, dtype)
    p["norm_out"] = _rms_init(top, dtype)
    p["conv_out"] = _c3_init(keys[-1], top, 2 * cfg.z_channels, 3, 3, dtype)
    return p


def encode_core(p, cfg: CausalVAEConfig, x: jax.Array) -> jax.Array:
    """encoder-only: [B, T, H, W, 3] -> moments [B, Tl, h, w, 2*z]
    (pre quant_conv)."""
    x = _cconv3d(p["conv_in"], x)
    for blk in p["downs"]:
        for j, rb in enumerate(blk["res"]):
            x = _res(rb, x)
            if blk["attn"]:
                x = _attn(blk["attn"][j], x)
        if "down" in blk:
            x = _conv2d_frames(blk["down"]["conv"], x, stride=2,
                               padding=[(0, 1), (0, 1)])
            if "time" in blk["down"]:
                x = _time_downsample(blk["down"]["time"], x)
    x = _mid(p["mid"], x)
    x = jax.nn.silu(_rms(p["norm_out"], x))
    return _cconv3d(p["conv_out"], x)


# ---------------------------------------------------------------- full VAE
def init_params(key, cfg: CausalVAEConfig, dtype=jnp.float32,
                encoder: bool = True, decoder: bool = True):
    k = jax.random.split(key, 4)
    p = {}
    if decoder:
        p["decoder"] = init_decoder(k[0], cfg, dtype)
        p["post_quant_conv"] = _c3_init(
            k[1], cfg.z_channels, cfg.z_channels, 1, 1, dtype)
    if encoder:
        p["encoder"] = init_encoder(k[2], cfg, dtype)
        p["quant_conv"] = _c3_init(
            k[3], 2 * cfg.z_channels, 2 * cfg.z_channels, 1, 1, dtype)
    return p


def _mean_std(cfg: CausalVAEConfig, dtype):
    mean = jnp.asarray(cfg.latents_mean, dtype)
    std = jnp.asarray(cfg.latents_std, dtype)
    return mean, std


def decode(p, cfg: CausalVAEConfig, latents: jax.Array) -> jax.Array:
    """[B, T, h, w, z] normalized latents -> [B, F, H, W, 3] in [-1, 1]
    (reference decode path: denormalize -> post_quant_conv -> decoder ->
    clamp, pipeline_qwen_image.py:706-715)."""
    if cfg.latents_mean is not None:
        mean, std = _mean_std(cfg, latents.dtype)
        latents = latents * std + mean
    z = _cconv3d(p["post_quant_conv"], latents)
    return decode_core(p["decoder"], cfg, z)


def decode_image(p, cfg: CausalVAEConfig, latents: jax.Array) -> jax.Array:
    """[B, h, w, z] -> [B, H, W, 3] (1-frame video squeeze)."""
    return decode(p, cfg, latents[:, None])[:, 0]


def encode(p, cfg: CausalVAEConfig, x: jax.Array) -> jax.Array:
    """[B, F, H, W, 3] in [-1, 1] -> normalized latent MEAN [B, Tl, h, w,
    z] (deterministic conditioning encode — posterior mean, matching the
    reference's .mode())."""
    moments = _cconv3d(p["quant_conv"], encode_core(p["encoder"], cfg, x))
    mean = moments[..., : cfg.z_channels]
    if cfg.latents_mean is not None:
        m, s = _mean_std(cfg, mean.dtype)
        mean = (mean - m) / s
    return mean


def encode_image(p, cfg: CausalVAEConfig, x: jax.Array) -> jax.Array:
    """[B, H, W, 3] -> [B, h, w, z]."""
    return encode(p, cfg, x[:, None])[:, 0]
