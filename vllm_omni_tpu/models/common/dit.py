"""Shared cross-attention DiT block (Wan / StableAudio style).

Where the QwenImage/SD3/Flux family uses *joint* text+image attention
(models/qwen_image/transformer.py block_forward), the video and audio DiT
families condition via *cross*-attention: self-attention over media tokens
(RoPE'd), cross-attention into encoder states, gated MLP — all modulated by
adaLN from the timestep embedding (reference architectures:
vllm_omni/diffusion/models/wan2_2/, models/stable_audio/).

One functional block implementation serves both families; the caller
supplies RoPE frequencies for its token geometry (3D for video, 1D for
audio).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from vllm_omni_tpu.models.common import nn
from vllm_omni_tpu.ops import flash_attention, rms_norm


def init_cross_block(key, inner: int, ctx_dim: int, mlp_dim: int,
                     head_dim: int, dtype=jnp.float32):
    k = jax.random.split(key, 10)
    return {
        # adaLN: shift/scale/gate for self-attn + shift/scale/gate for mlp
        "mod": nn.linear_init(k[0], inner, 6 * inner, dtype=dtype),
        "to_q": nn.linear_init(k[1], inner, inner, dtype=dtype),
        "to_k": nn.linear_init(k[2], inner, inner, dtype=dtype),
        "to_v": nn.linear_init(k[3], inner, inner, dtype=dtype),
        "to_out": nn.linear_init(k[4], inner, inner, dtype=dtype),
        "norm_q": nn.rmsnorm_init(head_dim, dtype),
        "norm_k": nn.rmsnorm_init(head_dim, dtype),
        "cross_norm": nn.rmsnorm_init(inner, dtype),
        "cross_q": nn.linear_init(k[5], inner, inner, dtype=dtype),
        "cross_k": nn.linear_init(k[6], ctx_dim, inner, dtype=dtype),
        "cross_v": nn.linear_init(k[7], ctx_dim, inner, dtype=dtype),
        "cross_out": nn.linear_init(k[8], inner, inner, dtype=dtype),
        "mlp1": nn.linear_init(k[9], inner, mlp_dim, dtype=dtype),
        "mlp2": nn.linear_init(jax.random.fold_in(k[9], 1), mlp_dim, inner,
                               dtype=dtype),
    }


def _heads(x, h):
    b, s, d = x.shape
    return x.reshape(b, s, h, d // h)


def _merge(x):
    b, s, h, d = x.shape
    return x.reshape(b, s, h * d)


def _norm_nomod(x):
    return nn.layernorm({}, x)


def _rope_apply(x, cos, sin):
    # x: [B, S, H, D]; cos/sin: [S, D//2]
    d = x.shape[-1]
    x1, x2 = x[..., : d // 2], x[..., d // 2:]
    c = cos[None, :, None, :]
    s = sin[None, :, None, :]
    return jnp.concatenate(
        [x1 * c - x2 * s, x2 * c + x1 * s], axis=-1
    ).astype(x.dtype)


def cross_block_forward(
    blk,
    x: jax.Array,          # [B, S, inner] media tokens
    ctx: jax.Array,        # [B, S_ctx, ctx_dim] encoder states
    temb: jax.Array,       # [B, inner] timestep embedding
    rope: tuple,           # (cos, sin) each [S, head_dim//2]
    num_heads: int,
    ctx_mask=None,         # [B, S_ctx] 1/0
    self_attn_fn=None,     # (q, k, v) [B,S,H,D] -> [B,S,H,D]; SP override
):
    mod = nn.linear(blk["mod"], jax.nn.silu(temb))[:, None, :]
    sh1, sc1, g1, sh2, sc2, g2 = jnp.split(mod, 6, axis=-1)
    cos, sin = rope

    # self-attention (RoPE, qk-norm)
    h = _norm_nomod(x) * (1 + sc1) + sh1
    q = rms_norm(_heads(nn.linear(blk["to_q"], h), num_heads),
                 blk["norm_q"]["w"])
    k = rms_norm(_heads(nn.linear(blk["to_k"], h), num_heads),
                 blk["norm_k"]["w"])
    v = _heads(nn.linear(blk["to_v"], h), num_heads)
    q = _rope_apply(q, cos, sin)
    k = _rope_apply(k, cos, sin)
    if self_attn_fn is not None:
        # sequence-parallel path (shard_map USP over the token axis)
        attn = self_attn_fn(q, k, v)
    else:
        attn = flash_attention(q, k, v, causal=False)
    x = x + g1 * nn.linear(blk["to_out"], _merge(attn))

    # cross-attention into encoder states (un-modulated, Wan style)
    h = rms_norm(x, blk["cross_norm"]["w"])
    q = _heads(nn.linear(blk["cross_q"], h), num_heads)
    k = _heads(nn.linear(blk["cross_k"], ctx), num_heads)
    v = _heads(nn.linear(blk["cross_v"], ctx), num_heads)
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if ctx_mask is not None:
        s = jnp.where(ctx_mask[:, None, None, :] > 0, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(x.dtype)
    x = x + nn.linear(blk["cross_out"], _merge(o))

    # gated MLP
    h = _norm_nomod(x) * (1 + sc2) + sh2
    x = x + g2 * nn.linear(blk["mlp2"],
                           jax.nn.gelu(nn.linear(blk["mlp1"], h),
                                       approximate=True))
    return x
