"""Minimal functional NN primitives over parameter pytrees.

The framework keeps model parameters as nested dicts of jax.Arrays (pytrees)
and model code as pure functions — the idiomatic layout for pjit/shard_map
sharding (params are annotated with NamedSharding at load time, activations
with with_sharding_constraint inside the jitted step).  This replaces the
reference's torch ``nn.Module`` graph + forward hooks with compiler-visible
functions.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp


# ---------------------------------------------------------------- init utils
def linear_init(key, in_dim: int, out_dim: int, bias: bool = True, dtype=jnp.float32):
    kw, kb = jax.random.split(key)
    scale = 1.0 / math.sqrt(in_dim)
    p = {
        "w": jax.random.uniform(
            kw, (in_dim, out_dim), dtype, minval=-scale, maxval=scale
        )
    }
    if bias:
        p["b"] = jnp.zeros((out_dim,), dtype)
    return p


def linear(p, x):
    if "w_q" in p:
        # int8 weight-only quantization: weights live in HBM as int8 +
        # per-out-channel scales; the dequant multiply fuses into the matmul
        # (XLA), halving weight bandwidth (reference FP8 path:
        # diffusion/quantization/fp8.py — TPU gets int8 first)
        w = p["w_q"].astype(x.dtype) * p["w_scale"].astype(x.dtype)
        y = x @ w
    elif "w_q4" in p:
        # int4 weight-only: two nibbles per stored byte, unpacked inline
        # (diffusion/quantization.py) — quarter weight bandwidth, and the
        # full 60-layer Qwen-Image DiT fits one chip's HBM resident
        from vllm_omni_tpu.diffusion.quantization import unpack_int4

        w = unpack_int4(p["w_q4"], x.shape[-1], x.dtype) \
            * p["w_scale"].astype(x.dtype)
        y = x @ w
    else:
        y = x @ p["w"]
    if "b" in p:
        y = y + p["b"]
    return y


def embedding_init(key, vocab: int, dim: int, dtype=jnp.float32):
    return {"w": jax.random.normal(key, (vocab, dim), dtype) * 0.02}


def embedding(p, ids):
    return p["w"][ids]


def rmsnorm_init(dim: int, dtype=jnp.float32):
    return {"w": jnp.ones((dim,), dtype)}


def layernorm_init(dim: int, affine: bool = True, dtype=jnp.float32):
    if not affine:
        return {}
    return {"w": jnp.ones((dim,), dtype), "b": jnp.zeros((dim,), dtype)}


def layernorm(p, x, eps: float = 1e-6):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    y = (xf - mu) * jax.lax.rsqrt(var + eps)
    if "w" in p:
        y = y * p["w"].astype(jnp.float32) + p["b"].astype(jnp.float32)
    return y.astype(x.dtype)


def conv2d_init(
    key, in_ch: int, out_ch: int, kernel: int, bias: bool = True, dtype=jnp.float32
):
    fan_in = in_ch * kernel * kernel
    scale = 1.0 / math.sqrt(fan_in)
    p = {
        "w": jax.random.uniform(
            key, (kernel, kernel, in_ch, out_ch), dtype, minval=-scale, maxval=scale
        )
    }
    if bias:
        p["b"] = jnp.zeros((out_ch,), dtype)
    return p


def conv2d(p, x, stride: int = 1, padding: str | Sequence = "SAME"):
    """x: [B, H, W, C] (NHWC — the TPU-native conv layout)."""
    y = jax.lax.conv_general_dilated(
        x,
        p["w"].astype(x.dtype),
        window_strides=(stride, stride),
        padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    if "b" in p:
        y = y + p["b"].astype(x.dtype)
    return y


def conv1d_init(
    key, in_ch: int, out_ch: int, kernel: int, bias: bool = True, dtype=jnp.float32
):
    fan_in = in_ch * kernel
    scale = 1.0 / math.sqrt(fan_in)
    p = {
        "w": jax.random.uniform(
            key, (kernel, in_ch, out_ch), dtype, minval=-scale, maxval=scale
        )
    }
    if bias:
        p["b"] = jnp.zeros((out_ch,), dtype)
    return p


def conv1d(p, x, stride: int = 1, padding: str | Sequence = "SAME",
           dilation: int = 1):
    """x: [B, T, C] (NWC — TPU-native 1-D conv layout)."""
    y = jax.lax.conv_general_dilated(
        x,
        p["w"].astype(x.dtype),
        window_strides=(stride,),
        padding=padding,
        rhs_dilation=(dilation,),
        dimension_numbers=("NWC", "WIO", "NWC"),
    )
    if "b" in p:
        y = y + p["b"].astype(x.dtype)
    return y


def conv1d_transpose(p, x, stride: int, padding: str = "SAME"):
    """Transposed 1-D conv (upsampling by ``stride``); x: [B, T, C]."""
    y = jax.lax.conv_transpose(
        x,
        p["w"].astype(x.dtype),
        strides=(stride,),
        padding=padding,
        dimension_numbers=("NWC", "WIO", "NWC"),
    )
    if "b" in p:
        y = y + p["b"].astype(x.dtype)
    return y


def groupnorm_init(channels: int, dtype=jnp.float32):
    return {"w": jnp.ones((channels,), dtype), "b": jnp.zeros((channels,), dtype)}


def groupnorm(p, x, groups: int = 32, eps: float = 1e-6):
    """x: [B, H, W, C]."""
    b, h, w, c = x.shape
    g = min(groups, c)
    while c % g:
        g -= 1
    xf = x.astype(jnp.float32).reshape(b, h, w, g, c // g)
    mu = jnp.mean(xf, axis=(1, 2, 4), keepdims=True)
    var = jnp.var(xf, axis=(1, 2, 4), keepdims=True)
    y = ((xf - mu) * jax.lax.rsqrt(var + eps)).reshape(b, h, w, c)
    y = y * p["w"].astype(jnp.float32) + p["b"].astype(jnp.float32)
    return y.astype(x.dtype)


def sinusoid_positions(length: int, channels: int,
                       max_timescale: float = 10000.0):
    """Whisper SinusoidsPositionEmbedding table [length, channels]
    (shared by the Qwen3 AuT and Qwen2.5-Omni audio towers)."""
    import math

    import numpy as np

    log_inc = math.log(max_timescale) / (channels // 2 - 1)
    inv = np.exp(-log_inc * np.arange(channels // 2, dtype=np.float32))
    ang = np.arange(length, dtype=np.float32)[:, None] * inv[None, :]
    return np.concatenate([np.sin(ang), np.cos(ang)], axis=1)


def timestep_embedding(t: jax.Array, dim: int, max_period: float = 10000.0):
    """Sinusoidal timestep embedding [B] -> [B, dim] (flip_sin_to_cos=True,
    matching diffusers' Timesteps used by the reference pipelines)."""
    half = dim // 2
    freqs = jnp.exp(
        -math.log(max_period) * jnp.arange(half, dtype=jnp.float32) / half
    )
    args = t.astype(jnp.float32)[:, None] * freqs[None, :]
    return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)


def bias_attention(q, k, v, bias):
    """GQA attention with an additive bias mask, fp32 softmax.

    q [B, Sq, H, D] x k/v [B, Sk, Hkv, D], bias [B, 1, Sq, Sk] ->
    [B, Sq, H, D].  KV heads repeat up to the query head count
    (grouped-query attention); scores and softmax run in fp32 with the
    values' dtype restored on the way out.  Shared by the causal-MM
    generator families (Bagel, HunyuanImage-3) whose denoise attends a
    masked prefix context."""
    hq, hkv = q.shape[2], k.shape[2]
    if hq != hkv:
        k = jnp.repeat(k, hq // hkv, axis=2)
        v = jnp.repeat(v, hq // hkv, axis=2)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / math.sqrt(q.shape[-1])
    a = jax.nn.softmax(s + bias.astype(jnp.float32), axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", a.astype(v.dtype), v)
