"""Multimodal 3D-RoPE position computation (host-side).

The TPU-native counterpart of the reference's OmniMRotaryEmbedding position
math (reference: model_executor/layers/rotary_embedding/mrope.py:25 — 554
LoC of image/video/audio/audio-in-video interleave; thinker usage
qwen3_omni_moe_thinker.py:1193 ``get_mrope_input_positions``).

Positions are three parallel streams (temporal, height, width), one value
per token.  The behavioral contract:

- **text** tokens advance all three streams together by 1 per token;
- **image** tokens (grid h×w after spatial merge): temporal stays at the
  running base, height enumerates rows, width enumerates columns; the base
  then advances by max(h, w) — so the next text token clears the image's
  largest spatial extent;
- **video** tokens (t frames of h×w): like images per frame, with the
  temporal stream advancing ``t_scale`` per frame (tokens-per-second
  alignment); base advances by max(t*t_scale, h, w);
- **audio** tokens: all three streams advance together (audio is purely
  temporal); base advances by the token count;
- **audio-in-video**: the caller emits the video chunks and audio chunks
  as separate interleaved items sharing a ``t_base`` so both modalities
  ride one timeline (reference: get_updates_use_audio_in_video,
  qwen3_omni_moe_thinker.py:389).

Everything here is plain numpy on the host — the device only ever sees the
final [3, T] int32 array (ops/rope.py compute_mrope_freqs applies the
sectioned frequencies).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np


@dataclass(frozen=True)
class MMItem:
    """One multimodal span inside an (already placeholder-expanded) token
    sequence."""

    modality: str  # "image" | "video" | "audio"
    offset: int  # token index where the item's tokens start
    # image: (1, h, w); video: (t, h, w) — dims AFTER spatial merge;
    # audio: (n,) token count
    grid: tuple[int, ...]
    # temporal scale per video frame (seconds-per-frame * tokens-per-second)
    t_scale: int = 1
    # optional shared-timeline override (audio-in-video interleave): the
    # temporal stream starts at t_base instead of the running base
    t_base: Optional[int] = None

    @property
    def num_tokens(self) -> int:
        if self.modality == "audio":
            return int(self.grid[0])
        t, h, w = self.grid
        return int(t * h * w)


def compute_mrope_positions(
    num_tokens: int,
    items: Sequence[MMItem] = (),
) -> tuple[np.ndarray, int]:
    """Return (positions [3, num_tokens] int32, delta).

    ``delta`` maps generated-token index to its position: a token at
    sequence index p >= num_tokens sits at position p + delta on all three
    streams (reference: mrope position delta carried per request).
    """
    pos = np.zeros((3, num_tokens), np.int32)
    items = sorted(items, key=lambda it: it.offset)
    base = 0  # running position base (shared by the 3 streams for text)
    idx = 0  # next sequence index to fill
    for it in items:
        if it.offset < idx:
            raise ValueError(
                f"overlapping multimodal items at offset {it.offset}"
            )
        # text run before the item
        n_text = it.offset - idx
        if n_text:
            r = np.arange(base, base + n_text, dtype=np.int32)
            pos[:, idx:it.offset] = r[None, :]
            base += n_text
            idx = it.offset
        n = it.num_tokens
        if idx + n > num_tokens:
            raise ValueError(
                f"item at offset {it.offset} overruns the sequence "
                f"({idx + n} > {num_tokens})"
            )
        t0 = base if it.t_base is None else it.t_base
        if it.modality == "audio":
            r = np.arange(t0, t0 + n, dtype=np.int32)
            pos[:, idx:idx + n] = r[None, :]
            base = max(base, t0 + n)
        elif it.modality in ("image", "video"):
            t, h, w = it.grid
            tt = (np.arange(t, dtype=np.int32) * it.t_scale)[:, None, None]
            hh = np.arange(h, dtype=np.int32)[None, :, None]
            ww = np.arange(w, dtype=np.int32)[None, None, :]
            flat_t = np.broadcast_to(tt, (t, h, w)).reshape(-1)
            flat_h = np.broadcast_to(hh, (t, h, w)).reshape(-1)
            flat_w = np.broadcast_to(ww, (t, h, w)).reshape(-1)
            pos[0, idx:idx + n] = t0 + flat_t
            pos[1, idx:idx + n] = t0 + flat_h
            pos[2, idx:idx + n] = t0 + flat_w
            # next base = max emitted position + 1 (the convention the
            # reference/HF get_rope_index uses): the largest temporal
            # position is (t-1)*t_scale, not t*t_scale
            base = max(base, t0 + max((t - 1) * it.t_scale + 1, h, w))
        else:
            raise ValueError(f"unknown modality {it.modality!r}")
        idx += n
    # trailing text
    if idx < num_tokens:
        r = np.arange(base, base + (num_tokens - idx), dtype=np.int32)
        pos[:, idx:] = r[None, :]
        base += num_tokens - idx
    delta = int(base - num_tokens)
    return pos, delta


def expand_placeholders(
    token_ids: Sequence[int],
    placeholder_id: dict[str, int],
    items: Sequence[tuple[str, tuple[int, ...]]],
) -> tuple[list[int], list[MMItem]]:
    """Expand single placeholder tokens into per-item token runs.

    ``token_ids`` contains one ``placeholder_id[modality]`` token per
    multimodal item, in order; ``items`` is the matching (modality, grid)
    list.  Returns the expanded ids (each placeholder repeated to the
    item's token count) and the positioned ``MMItem`` list (reference:
    prompt-update replacement, qwen3_omni_moe_thinker.py:430-536).
    """
    id_to_mod = {v: k for k, v in placeholder_id.items()}
    out: list[int] = []
    placed: list[MMItem] = []
    it = iter(items)
    for tok in token_ids:
        mod = id_to_mod.get(tok)
        if mod is None:
            out.append(int(tok))
            continue
        try:
            want_mod, grid = next(it)
        except StopIteration:
            raise ValueError("more placeholder tokens than items") from None
        if want_mod != mod:
            raise ValueError(
                f"placeholder order mismatch: token says {mod!r}, "
                f"items say {want_mod!r}"
            )
        item = MMItem(modality=mod, offset=len(out), grid=tuple(grid))
        out.extend([int(tok)] * item.num_tokens)
        placed.append(item)
    if next(it, None) is not None:
        raise ValueError("more items than placeholder tokens")
    return out, placed
