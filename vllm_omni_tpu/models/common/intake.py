"""Conditioning-image intake shared by the causal-MM generator
pipelines (Bagel, HunyuanImage-3) and the image-edit families.

Reference: vllm_omni/diffusion/models/bagel/pipeline_bagel.py
prepare_vae_images (:393) / hunyuan_image_3/pipeline_hunyuan_image_3.py
vae_encode (:369) — uint8 -> [-1, 1] float, bilinear resize to the
model's geometry, VAE encode.  Centralized so dtype/resize/validation
fixes reach every family at once.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def prepare_cond_image(image, target_h: int, target_w: int) -> np.ndarray:
    """Any uint8/float HxWx3 array-like -> float32 [target_h, target_w, 3]
    in [-1, 1] (bilinear resize when the shape differs)."""
    img = np.asarray(image)
    if img.ndim != 3 or img.shape[-1] != 3:
        raise ValueError(f"conditioning image must be HxWx3, got "
                         f"{img.shape}")
    if img.dtype == np.uint8:
        img = img.astype(np.float32) / 127.5 - 1.0
    img = img.astype(np.float32)
    if img.shape[:2] != (target_h, target_w):
        img = np.asarray(jax.image.resize(
            jnp.asarray(img), (target_h, target_w, 3), "bilinear"))
    return img
