"""StableAudio Open DiT at the published checkpoint schema.

Checkpoint-faithful twin of the reference's ``StableAudioDiTModel``
(vllm_omni/diffusion/models/stable_audio/stable_audio_transformer.py:
364-602, itself the diffusers StableAudioDiTModel): Gaussian-Fourier
time embedding, duration (global) token prepended to the latent
sequence, GQA cross-attention into projected T5 states, SwiGLU FFs, and
partial 1-D rotary (first head_dim//2 dims only,
apply_rotary_emb_stable_audio :24-55).

TPU-first: NWC layouts throughout ([B, L, C] — the reference's [B, C, L]
conv layout would force transposes around every matmul), the 1x1
pre/post convs are plain matmuls, and the whole step jits into one
XLA program.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.models.common import nn


@dataclass(frozen=True)
class StableAudioCkptConfig:
    in_channels: int = 64
    num_layers: int = 24
    num_heads: int = 24
    num_kv_heads: int = 12          # cross-attention GQA only
    head_dim: int = 64
    cross_attention_dim: int = 768
    cross_attention_input_dim: int = 768
    global_states_input_dim: int = 1536
    time_proj_dim: int = 256
    sample_size: int = 1024         # max latent frames

    @property
    def inner_dim(self) -> int:
        return self.num_heads * self.head_dim

    @property
    def ff_inner(self) -> int:
        return 4 * self.inner_dim

    @property
    def rot_dim(self) -> int:
        return self.head_dim // 2

    @staticmethod
    def tiny() -> "StableAudioCkptConfig":
        return StableAudioCkptConfig(
            in_channels=8, num_layers=2, num_heads=4, num_kv_heads=2,
            head_dim=16, cross_attention_dim=32,
            cross_attention_input_dim=32, global_states_input_dim=64,
            time_proj_dim=32, sample_size=64)

    @staticmethod
    def from_hf(d: dict) -> "StableAudioCkptConfig":
        return StableAudioCkptConfig(
            in_channels=d.get("in_channels", 64),
            num_layers=d.get("num_layers", 24),
            num_heads=d.get("num_attention_heads", 24),
            num_kv_heads=d.get("num_key_value_attention_heads", 12),
            head_dim=d.get("attention_head_dim", 64),
            cross_attention_dim=d.get("cross_attention_dim", 768),
            cross_attention_input_dim=d.get(
                "cross_attention_input_dim", 768),
            global_states_input_dim=d.get(
                "global_states_input_dim", 1536),
            time_proj_dim=d.get("time_proj_dim", 256),
            sample_size=d.get("sample_size", 1024),
        )


def init_params(key, cfg: StableAudioCkptConfig, dtype=jnp.float32):
    inner, c = cfg.inner_dim, cfg.in_channels
    ks = iter(jax.random.split(key, 16 + 12 * cfg.num_layers))

    def lin(i, o, bias=True):
        return nn.linear_init(next(ks), i, o, bias=bias, dtype=dtype)

    p = {
        "time_fourier": jax.random.normal(
            next(ks), (cfg.time_proj_dim // 2,), dtype),
        "tfc1": lin(cfg.time_proj_dim, inner),
        "tfc2": lin(inner, inner),
        "gfc1": lin(cfg.global_states_input_dim, inner, bias=False),
        "gfc2": lin(inner, inner, bias=False),
        "cfc1": lin(cfg.cross_attention_input_dim,
                    cfg.cross_attention_dim, bias=False),
        "cfc2": lin(cfg.cross_attention_dim, cfg.cross_attention_dim,
                    bias=False),
        "pre_conv": lin(c, c, bias=False),     # 1x1 conv == matmul
        "proj_in": lin(c, inner, bias=False),
        "proj_out": lin(inner, c, bias=False),
        "post_conv": lin(c, c, bias=False),
        "blocks": [],
    }
    kv_dim = cfg.num_kv_heads * cfg.head_dim
    for _ in range(cfg.num_layers):
        p["blocks"].append({
            "norm1": nn.layernorm_init(inner, dtype=dtype),
            "q1": lin(inner, inner, bias=False),
            "k1": lin(inner, inner, bias=False),
            "v1": lin(inner, inner, bias=False),
            "o1": lin(inner, inner, bias=False),
            "norm2": nn.layernorm_init(inner, dtype=dtype),
            "q2": lin(inner, inner, bias=False),
            "k2": lin(cfg.cross_attention_dim, kv_dim, bias=False),
            "v2": lin(cfg.cross_attention_dim, kv_dim, bias=False),
            "o2": lin(inner, inner, bias=False),
            "norm3": nn.layernorm_init(inner, dtype=dtype),
            "ff_proj": lin(inner, 2 * cfg.ff_inner),
            "ff_out": lin(cfg.ff_inner, inner),
        })
    return p


def rope_1d(cfg: StableAudioCkptConfig, length: int):
    """diffusers get_1d_rotary_pos_embed(rot_dim, use_real=True,
    repeat_interleave_real=False): cos/sin each [L, rot_dim] with the
    rot_dim//2 frequencies tiled twice (half-split convention)."""
    rot = cfg.rot_dim
    freqs = 1.0 / (10000.0 ** (np.arange(0, rot, 2, dtype=np.float64)
                               / rot))
    ang = np.arange(length, dtype=np.float64)[:, None] * freqs[None, :]
    cos = np.concatenate([np.cos(ang), np.cos(ang)], axis=-1)
    sin = np.concatenate([np.sin(ang), np.sin(ang)], axis=-1)
    return (jnp.asarray(cos, jnp.float32), jnp.asarray(sin, jnp.float32))


def _apply_rope(x, rope):
    """Rotate the first rot_dim dims of each head; pass the rest
    through (reference apply_rotary_emb_stable_audio)."""
    cos, sin = rope
    rot = cos.shape[-1]
    x_rot, x_pass = x[..., :rot], x[..., rot:]
    xf = x_rot.astype(jnp.float32)
    x1, x2 = jnp.split(xf, 2, axis=-1)
    rotated = jnp.concatenate([-x2, x1], axis=-1)
    out = xf * cos[None, :, None, :] + rotated * sin[None, :, None, :]
    return jnp.concatenate([out.astype(x.dtype), x_pass], axis=-1)


def _attn(q, k, v, mask=None):
    """q [B,S,H,D], k/v [B,T,Hkv,D] -> [B,S,H*D] via the shared GQA
    helper (fp32 softmax; KV heads repeat internally)."""
    b, sq = q.shape[0], q.shape[1]
    if mask is None:
        bias = jnp.zeros((b, 1, 1, k.shape[1]), jnp.float32)
    else:
        bias = jnp.where(mask[:, None, None, :], 0.0, -1e30)
    o = nn.bias_attention(q, k, v, bias)
    return o.reshape(b, sq, -1)


def forward(params, cfg: StableAudioCkptConfig, latents, timesteps, ctx,
            global_states, ctx_mask=None):
    """latents [B, L, C], timesteps [B], ctx [B, S, ctx_in],
    global_states [B, global_in] -> velocity [B, L, C].

    Mirrors the reference forward (stable_audio_transformer.py:489-566):
    project conditioning, prepend the duration+time token, run the
    blocks, drop the token, residual 1x1 convs around the stack."""
    h, hk, d = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim

    cross = nn.linear(params["cfc2"],
                      jax.nn.silu(nn.linear(params["cfc1"], ctx)))
    glob = nn.linear(params["gfc2"], jax.nn.silu(
        nn.linear(params["gfc1"], global_states)))[:, None, :]
    # Gaussian Fourier features, cos first (flip_sin_to_cos)
    ang = (2.0 * jnp.pi) * timesteps.astype(jnp.float32)[:, None] \
        * params["time_fourier"].astype(jnp.float32)[None, :]
    four = jnp.concatenate([jnp.cos(ang), jnp.sin(ang)],
                           axis=-1).astype(latents.dtype)
    temb = nn.linear(params["tfc2"],
                     jax.nn.silu(nn.linear(params["tfc1"], four)))
    glob = glob + temb[:, None, :]

    x = nn.linear(params["pre_conv"], latents) + latents
    x = nn.linear(params["proj_in"], x)
    x = jnp.concatenate([glob.astype(x.dtype), x], axis=1)
    b, n = x.shape[0], x.shape[1]
    rope = rope_1d(cfg, n)

    for blk in params["blocks"]:
        r = x
        y = nn.layernorm(blk["norm1"], x)
        q = nn.linear(blk["q1"], y).reshape(b, n, h, d)
        k = nn.linear(blk["k1"], y).reshape(b, n, h, d)
        v = nn.linear(blk["v1"], y).reshape(b, n, h, d)
        q, k = _apply_rope(q, rope), _apply_rope(k, rope)
        x = r + nn.linear(blk["o1"], _attn(q, k, v))

        r = x
        y = nn.layernorm(blk["norm2"], x)
        s = cross.shape[1]
        q = nn.linear(blk["q2"], y).reshape(b, n, h, d)
        k = nn.linear(blk["k2"], cross).reshape(b, s, hk, d)
        v = nn.linear(blk["v2"], cross).reshape(b, s, hk, d)
        x = r + nn.linear(blk["o2"], _attn(q, k, v, mask=ctx_mask))

        r = x
        y = nn.layernorm(blk["norm3"], x)
        val, gate = jnp.split(nn.linear(blk["ff_proj"], y), 2, axis=-1)
        x = r + nn.linear(blk["ff_out"], val * jax.nn.silu(gate))

    x = nn.linear(params["proj_out"], x)[:, 1:]
    return nn.linear(params["post_conv"], x) + x


# ------------------------------------------------------- checkpoint load
def load_stable_audio_dit(model_dir: str,
                          cfg: StableAudioCkptConfig = None,
                          dtype=jnp.bfloat16):
    """Stream transformer/ at the diffusers names (reference
    load_weights name_mapping, stable_audio_transformer.py:570-600)."""
    import json
    import os

    from vllm_omni_tpu.models.flux.loader import load_routed

    if cfg is None:
        with open(os.path.join(model_dir, "config.json")) as f:
            cfg = StableAudioCkptConfig.from_hf(json.load(f))
    shapes = jax.eval_shape(
        lambda: init_params(jax.random.PRNGKey(0), cfg, jnp.float32))

    r: dict[str, tuple] = {"time_proj.weight": ("raw", ("time_fourier",))}

    def lin(hf, *path, bias=True):
        r[f"{hf}.weight"] = ("direct", path + ("w",))
        if bias:
            r[f"{hf}.bias"] = ("direct", path + ("b",))

    lin("timestep_proj.linear_1", "tfc1")
    lin("timestep_proj.linear_2", "tfc2")
    lin("global_proj.linear_1", "gfc1", bias=False)
    lin("global_proj.linear_2", "gfc2", bias=False)
    lin("cross_attention_proj.0", "cfc1", bias=False)
    lin("cross_attention_proj.2", "cfc2", bias=False)
    lin("proj_in", "proj_in", bias=False)
    lin("proj_out", "proj_out", bias=False)
    for nm, tgt in (("preprocess_conv", "pre_conv"),
                    ("postprocess_conv", "post_conv")):
        r[f"{nm}.weight"] = ("raw", (tgt, "w"))
    for i in range(cfg.num_layers):
        b, t = f"transformer_blocks.{i}", ("blocks", i)
        for nm in ("norm1", "norm2", "norm3"):
            r[f"{b}.{nm}.weight"] = ("direct", t + (nm, "w"))
            r[f"{b}.{nm}.bias"] = ("direct", t + (nm, "b"))
        for a, (qn, kn, vn, on) in (("attn1", ("q1", "k1", "v1", "o1")),
                                    ("attn2", ("q2", "k2", "v2", "o2"))):
            lin(f"{b}.{a}.to_q", *t, qn, bias=False)
            lin(f"{b}.{a}.to_k", *t, kn, bias=False)
            lin(f"{b}.{a}.to_v", *t, vn, bias=False)
            lin(f"{b}.{a}.to_out.0", *t, on, bias=False)
        lin(f"{b}.ff.net.0.proj", *t, "ff_proj")
        lin(f"{b}.ff.net.2", *t, "ff_out")

    def conv1x1(arr):
        # torch Conv1d [out, in, 1] -> [in, out] matmul
        return np.ascontiguousarray(arr[..., 0].T)

    transforms = {"preprocess_conv.weight": conv1x1,
                  "postprocess_conv.weight": conv1x1}
    return load_routed(model_dir, r, shapes, dtype,
                       transforms=transforms), cfg
