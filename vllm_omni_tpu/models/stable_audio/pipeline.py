"""StableAudio-style text-to-audio pipeline.

Reference: vllm_omni/diffusion/models/stable_audio/ — DiT over 1-D audio
latents with cross-attention into text + seconds-timing conditioning, then
an autoencoder decode to waveform.  The TPU build shares the
cross-attention DiT block (models/common/dit.py) with 1-D RoPE and decodes
latents through a transposed-conv1d stack (NWC layout, the vocoder
pattern from models/qwen3_omni/code2wav.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.diffusion import cache as step_cache
from vllm_omni_tpu.diffusion import scheduler as fm
from vllm_omni_tpu.diffusion.request import DiffusionOutput, OmniDiffusionRequest
from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.models.common import dit, nn
from vllm_omni_tpu.models.common.transformer import (
    TransformerConfig,
    forward_hidden,
    init_params as init_text_params,
)
from vllm_omni_tpu.utils.tokenizer import ByteTokenizer

logger = init_logger(__name__)


@dataclass(frozen=True)
class StableAudioDiTConfig:
    latent_channels: int = 64
    num_layers: int = 24
    num_heads: int = 24
    head_dim: int = 64
    ctx_dim: int = 768
    theta: float = 10000.0
    mlp_ratio: float = 4.0

    @property
    def inner_dim(self) -> int:
        return self.num_heads * self.head_dim

    @staticmethod
    def tiny() -> "StableAudioDiTConfig":
        return StableAudioDiTConfig(
            latent_channels=8, num_layers=2, num_heads=4, head_dim=16,
            ctx_dim=64,
        )


@dataclass(frozen=True)
class StableAudioPipelineConfig:
    text: TransformerConfig = field(default_factory=TransformerConfig)
    dit: StableAudioDiTConfig = field(default_factory=StableAudioDiTConfig)
    # decoder: latent frame -> upsample x prod(factors) samples
    decoder_channels: int = 128
    upsample_factors: tuple = (8, 8, 4, 2)  # 2048 samples per latent frame
    sample_rate: int = 44100
    max_text_len: int = 64

    @staticmethod
    def tiny() -> "StableAudioPipelineConfig":
        return StableAudioPipelineConfig(
            text=TransformerConfig.tiny(vocab_size=256),
            dit=StableAudioDiTConfig.tiny(),
            decoder_channels=16,
            upsample_factors=(2, 2),
            sample_rate=16000,
        )

    @property
    def samples_per_latent(self) -> int:
        out = 1
        for f in self.upsample_factors:
            out *= f
        return out


def init_dit_params(key, cfg: StableAudioDiTConfig, dtype=jnp.float32):
    inner = cfg.inner_dim
    mlp = int(inner * cfg.mlp_ratio)
    keys = jax.random.split(key, cfg.num_layers + 5)
    return {
        "lat_in": nn.linear_init(keys[0], cfg.latent_channels, inner,
                                 dtype=dtype),
        "time_in1": nn.linear_init(keys[1], 256, inner, dtype=dtype),
        "time_in2": nn.linear_init(keys[2], inner, inner, dtype=dtype),
        "norm_out_mod": nn.linear_init(keys[3], inner, 2 * inner,
                                       dtype=dtype),
        "proj_out": nn.linear_init(keys[4], inner, cfg.latent_channels,
                                   dtype=dtype),
        "blocks": [
            dit.init_cross_block(keys[i + 5], inner, cfg.ctx_dim, mlp,
                                 cfg.head_dim, dtype)
            for i in range(cfg.num_layers)
        ],
    }


def init_decoder_params(key, cfg: StableAudioPipelineConfig,
                        dtype=jnp.float32):
    keys = jax.random.split(key, 2 + len(cfg.upsample_factors))
    ch = cfg.decoder_channels
    p = {
        "pre": nn.conv1d_init(keys[0], cfg.dit.latent_channels, ch, 7,
                              dtype=dtype),
        "ups": [],
        "post": nn.conv1d_init(
            keys[1], max(ch // (2 ** len(cfg.upsample_factors)), 4), 1, 7,
            dtype=dtype),
    }
    for i, f in enumerate(cfg.upsample_factors):
        out_ch = max(ch // 2, 4)
        p["ups"].append(nn.conv1d_init(keys[i + 2], ch, out_ch, 2 * f,
                                       dtype=dtype))
        ch = out_ch
    return p


def dit_forward(params, cfg: StableAudioDiTConfig, latents, ctx, timesteps,
                ctx_mask=None, attn_fn=None):
    """latents [B, T, C] -> velocity [B, T, C] (1-D RoPE positions)."""
    x = nn.linear(params["lat_in"], latents)
    temb = nn.linear(
        params["time_in2"],
        jax.nn.silu(nn.linear(
            params["time_in1"],
            nn.timestep_embedding(timesteps, 256).astype(x.dtype))),
    )
    t = latents.shape[1]
    half = cfg.head_dim // 2
    inv = 1.0 / (cfg.theta ** (jnp.arange(half, dtype=jnp.float32) / half))
    ang = jnp.arange(t, dtype=jnp.float32)[:, None] * inv[None, :]
    rope = (jnp.cos(ang), jnp.sin(ang))
    for blk in params["blocks"]:
        x = dit.cross_block_forward(blk, x, ctx, temb, rope, cfg.num_heads,
                                    ctx_mask, self_attn_fn=attn_fn)
    mod = nn.linear(params["norm_out_mod"], jax.nn.silu(temb))[:, None, :]
    shift, scale = jnp.split(mod, 2, axis=-1)
    x = nn.layernorm({}, x) * (1 + scale) + shift
    return nn.linear(params["proj_out"], x)


def decode_audio(params, cfg: StableAudioPipelineConfig, latents):
    """[B, T, C] latents -> [B, T*up] waveform in [-1, 1]."""
    x = nn.conv1d(params["pre"], latents)
    for up, f in zip(params["ups"], cfg.upsample_factors):
        x = jax.nn.silu(x)
        x = nn.conv1d_transpose(up, x, stride=f)
    return jnp.tanh(nn.conv1d(params["post"], jax.nn.silu(x)))[..., 0]


class StableAudioPipeline:
    """Text -> audio waveform (float32 [N] in [-1, 1])."""

    output_type = "audio"

    def __init__(self, config: StableAudioPipelineConfig, dtype=jnp.bfloat16,
                 seed: int = 0, mesh=None, cache_config=None):
        from vllm_omni_tpu.parallel.pipeline_mesh import MeshWiring

        self.cfg = config
        self.dtype = dtype
        self.mesh = mesh
        self.cache_config = cache_config
        # dp batches + USP over audio tokens; no CFG batch (guidance-free
        # sampler) and no TP wiring — refuse those axes
        self.wiring = MeshWiring(mesh, type(self).__name__).validate(
            {"dp", "ring", "ulysses"})
        if config.text.hidden_size != config.dit.ctx_dim:
            raise ValueError("text hidden_size must equal dit ctx_dim")
        self.tokenizer = ByteTokenizer(config.text.vocab_size)
        k1, k2, k3 = jax.random.split(jax.random.PRNGKey(seed), 3)
        logger.info("Initializing StableAudioPipeline (dtype=%s)", dtype)
        self.text_params = self.wiring.place(
            init_text_params(k1, config.text, dtype))
        self.dit_params = self.wiring.place(
            init_dit_params(k2, config.dit, dtype))
        self.decoder_params = self.wiring.place(
            init_decoder_params(k3, config, dtype))
        self._denoise_cache: dict = {}
        # params are explicit jit ARGUMENTS (closure capture would bake
        # them into the executable — sleep()/weight swaps wouldn't apply),
        # and the jit is built once, not per request
        self._text_encode_jit = jax.jit(
            lambda p, i: forward_hidden(p, self.cfg.text, i))

    def encode_prompt(self, prompts: list[str]):
        ids, lens = self.tokenizer.batch_encode(prompts,
                                                self.cfg.max_text_len)
        hidden = self._text_encode_jit(self.text_params, jnp.asarray(ids))
        mask = (np.arange(self.cfg.max_text_len)[None, :]
                < lens[:, None]).astype(np.int32)
        return hidden, jnp.asarray(mask)

    def _denoise_fn(self, lat_len, sched_len, batch=0):
        key = (lat_len, sched_len) + (
            (batch,) if self.mesh is not None else ())
        if key in self._denoise_cache:
            return self._denoise_cache[key]
        cfg = self.cfg
        wiring = self.wiring
        attn_fn = wiring.self_attn_fn(cfg.dit.num_heads, lat_len, batch)

        cache_cfg = self.cache_config

        @jax.jit
        def run(dit_params, latents, ctx, ctx_mask, sigmas, timesteps,
                num_steps):
            schedule = fm.FlowMatchSchedule(sigmas=sigmas,
                                            timesteps=timesteps)

            def eval_velocity(lat, i):
                t = jnp.broadcast_to(timesteps[i], (lat.shape[0],))
                lat = wiring.constrain(lat, seq_dim=1)
                return dit_forward(dit_params, cfg.dit, lat, ctx, t,
                                   ctx_mask, attn_fn=attn_fn)

            return step_cache.run_denoise_loop(
                cache_cfg, schedule, eval_velocity, latents, num_steps)

        self._denoise_cache[key] = run
        return run

    def forward(self, req: OmniDiffusionRequest) -> list[DiffusionOutput]:
        sp = req.sampling_params
        cfg = self.cfg
        # duration in seconds via extras; default 1s
        seconds = float(sp.extra.get("seconds_total", 1.0))
        lat_len = max(8, int(seconds * cfg.sample_rate
                             // cfg.samples_per_latent))
        prompts = req.prompt
        b = len(prompts)
        ctx, ctx_mask = self.encode_prompt(prompts)
        seed = (sp.seed if sp.seed is not None
                else int(np.random.randint(0, 2 ** 31 - 1)))
        noise = jax.random.normal(
            jax.random.PRNGKey(seed),
            (b, lat_len, cfg.dit.latent_channels), self.dtype,
        )
        num_steps = sp.num_inference_steps
        sched_len = max(8, 1 << (num_steps - 1).bit_length())
        schedule = fm.make_schedule(num_steps, shift=1.0)
        sigmas = jnp.zeros((sched_len + 1,)).at[: num_steps + 1].set(
            schedule.sigmas)
        timesteps = jnp.zeros((sched_len,)).at[:num_steps].set(
            schedule.timesteps)
        run = self._denoise_fn(lat_len, sched_len, batch=b)
        latents, skipped = run(self.dit_params, noise, ctx, ctx_mask,
                               sigmas, timesteps, jnp.int32(num_steps))
        self.last_skipped_steps = int(skipped)
        wav = jax.jit(
            lambda p, l: decode_audio(p, cfg, l)
        )(self.decoder_params, latents)
        wav = np.asarray(wav, np.float32)
        return [
            DiffusionOutput(
                request_id=req.request_ids[i], prompt=prompts[i],
                data=wav[i], output_type="audio",
                metrics={"sample_rate": float(cfg.sample_rate)},
            )
            for i in range(b)
        ]
