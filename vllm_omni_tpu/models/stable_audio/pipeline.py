"""StableAudio-style text-to-audio pipeline.

Reference: vllm_omni/diffusion/models/stable_audio/ — DiT over 1-D audio
latents with cross-attention into text + seconds-timing conditioning, then
an autoencoder decode to waveform.  The TPU build shares the
cross-attention DiT block (models/common/dit.py) with 1-D RoPE and decodes
latents through a transposed-conv1d stack (NWC layout, the vocoder
pattern from models/qwen3_omni/code2wav.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.diffusion import cache as step_cache
from vllm_omni_tpu.diffusion import scheduler as fm
from vllm_omni_tpu.diffusion.request import DiffusionOutput, OmniDiffusionRequest
from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.models.common import dit, nn
from vllm_omni_tpu.models.common.transformer import (
    TransformerConfig,
    forward_hidden,
    init_params as init_text_params,
)
from vllm_omni_tpu.utils.tokenizer import ByteTokenizer

logger = init_logger(__name__)


@dataclass(frozen=True)
class StableAudioDiTConfig:
    latent_channels: int = 64
    num_layers: int = 24
    num_heads: int = 24
    head_dim: int = 64
    ctx_dim: int = 768
    theta: float = 10000.0
    mlp_ratio: float = 4.0

    @property
    def inner_dim(self) -> int:
        return self.num_heads * self.head_dim

    @staticmethod
    def tiny() -> "StableAudioDiTConfig":
        return StableAudioDiTConfig(
            latent_channels=8, num_layers=2, num_heads=4, head_dim=16,
            ctx_dim=64,
        )


@dataclass(frozen=True)
class StableAudioPipelineConfig:
    text: TransformerConfig = field(default_factory=TransformerConfig)
    dit: StableAudioDiTConfig = field(default_factory=StableAudioDiTConfig)
    # decoder: latent frame -> upsample x prod(factors) samples
    decoder_channels: int = 128
    upsample_factors: tuple = (8, 8, 4, 2)  # 2048 samples per latent frame
    sample_rate: int = 44100
    max_text_len: int = 64

    @staticmethod
    def tiny() -> "StableAudioPipelineConfig":
        return StableAudioPipelineConfig(
            text=TransformerConfig.tiny(vocab_size=256),
            dit=StableAudioDiTConfig.tiny(),
            decoder_channels=16,
            upsample_factors=(2, 2),
            sample_rate=16000,
        )

    @property
    def samples_per_latent(self) -> int:
        out = 1
        for f in self.upsample_factors:
            out *= f
        return out


def init_dit_params(key, cfg: StableAudioDiTConfig, dtype=jnp.float32):
    inner = cfg.inner_dim
    mlp = int(inner * cfg.mlp_ratio)
    keys = jax.random.split(key, cfg.num_layers + 5)
    return {
        "lat_in": nn.linear_init(keys[0], cfg.latent_channels, inner,
                                 dtype=dtype),
        "time_in1": nn.linear_init(keys[1], 256, inner, dtype=dtype),
        "time_in2": nn.linear_init(keys[2], inner, inner, dtype=dtype),
        "norm_out_mod": nn.linear_init(keys[3], inner, 2 * inner,
                                       dtype=dtype),
        "proj_out": nn.linear_init(keys[4], inner, cfg.latent_channels,
                                   dtype=dtype),
        "blocks": [
            dit.init_cross_block(keys[i + 5], inner, cfg.ctx_dim, mlp,
                                 cfg.head_dim, dtype)
            for i in range(cfg.num_layers)
        ],
    }


def init_decoder_params(key, cfg: StableAudioPipelineConfig,
                        dtype=jnp.float32):
    keys = jax.random.split(key, 2 + len(cfg.upsample_factors))
    ch = cfg.decoder_channels
    p = {
        "pre": nn.conv1d_init(keys[0], cfg.dit.latent_channels, ch, 7,
                              dtype=dtype),
        "ups": [],
        "post": nn.conv1d_init(
            keys[1], max(ch // (2 ** len(cfg.upsample_factors)), 4), 1, 7,
            dtype=dtype),
    }
    for i, f in enumerate(cfg.upsample_factors):
        out_ch = max(ch // 2, 4)
        p["ups"].append(nn.conv1d_init(keys[i + 2], ch, out_ch, 2 * f,
                                       dtype=dtype))
        ch = out_ch
    return p


def dit_forward(params, cfg: StableAudioDiTConfig, latents, ctx, timesteps,
                ctx_mask=None, attn_fn=None):
    """latents [B, T, C] -> velocity [B, T, C] (1-D RoPE positions)."""
    x = nn.linear(params["lat_in"], latents)
    temb = nn.linear(
        params["time_in2"],
        jax.nn.silu(nn.linear(
            params["time_in1"],
            nn.timestep_embedding(timesteps, 256).astype(x.dtype))),
    )
    t = latents.shape[1]
    half = cfg.head_dim // 2
    inv = 1.0 / (cfg.theta ** (jnp.arange(half, dtype=jnp.float32) / half))
    ang = jnp.arange(t, dtype=jnp.float32)[:, None] * inv[None, :]
    rope = (jnp.cos(ang), jnp.sin(ang))
    for blk in params["blocks"]:
        x = dit.cross_block_forward(blk, x, ctx, temb, rope, cfg.num_heads,
                                    ctx_mask, self_attn_fn=attn_fn)
    mod = nn.linear(params["norm_out_mod"], jax.nn.silu(temb))[:, None, :]
    shift, scale = jnp.split(mod, 2, axis=-1)
    x = nn.layernorm({}, x) * (1 + scale) + shift
    return nn.linear(params["proj_out"], x)


def decode_audio(params, cfg: StableAudioPipelineConfig, latents):
    """[B, T, C] latents -> [B, T*up] waveform in [-1, 1]."""
    x = nn.conv1d(params["pre"], latents)
    for up, f in zip(params["ups"], cfg.upsample_factors):
        x = jax.nn.silu(x)
        x = nn.conv1d_transpose(up, x, stride=f)
    return jnp.tanh(nn.conv1d(params["post"], jax.nn.silu(x)))[..., 0]


class StableAudioPipeline:
    """Text -> audio waveform (float32 [N] in [-1, 1])."""

    output_type = "audio"
    # ckpt_* / t5 / proj / oobleck trees exist only after from_pretrained
    param_attrs = ("text_params", "dit_params", "decoder_params",
                   "ckpt_dit_params", "t5_params", "proj_params",
                   "oobleck_params")

    def __init__(self, config: StableAudioPipelineConfig, dtype=jnp.bfloat16,
                 seed: int = 0, mesh=None, cache_config=None):
        from vllm_omni_tpu.parallel.pipeline_mesh import MeshWiring

        self.cfg = config
        self.dtype = dtype
        self.mesh = mesh
        self.cache_config = cache_config
        # dp batches + USP over audio tokens; no CFG batch (guidance-free
        # sampler) and no TP wiring — refuse those axes
        self.wiring = MeshWiring(mesh, type(self).__name__).validate(
            {"dp", "ring", "ulysses"})
        if config.text.hidden_size != config.dit.ctx_dim:
            raise ValueError("text hidden_size must equal dit ctx_dim")
        self.tokenizer = ByteTokenizer(config.text.vocab_size)
        k1, k2, k3 = jax.random.split(jax.random.PRNGKey(seed), 3)
        logger.info("Initializing StableAudioPipeline (dtype=%s)", dtype)
        self.text_params = self.wiring.place(
            init_text_params(k1, config.text, dtype))
        self.dit_params = self.wiring.place(
            init_dit_params(k2, config.dit, dtype))
        self.decoder_params = self.wiring.place(
            init_decoder_params(k3, config, dtype))
        self._denoise_cache: dict = {}
        # params are explicit jit ARGUMENTS (closure capture would bake
        # them into the executable — sleep()/weight swaps wouldn't apply),
        # and the jit is built once, not per request
        self._text_encode_jit = jax.jit(
            lambda p, i: forward_hidden(p, self.cfg.text, i))

    def encode_prompt(self, prompts: list[str]):
        ids, lens = self.tokenizer.batch_encode(prompts,
                                                self.cfg.max_text_len)
        hidden = self._text_encode_jit(self.text_params, jnp.asarray(ids))
        mask = (np.arange(self.cfg.max_text_len)[None, :]
                < lens[:, None]).astype(np.int32)
        return hidden, jnp.asarray(mask)

    def _denoise_fn(self, lat_len, sched_len, batch=0):
        key = (lat_len, sched_len) + (
            (batch,) if self.mesh is not None else ())
        if key in self._denoise_cache:
            return self._denoise_cache[key]
        cfg = self.cfg
        wiring = self.wiring
        attn_fn = wiring.self_attn_fn(cfg.dit.num_heads, lat_len, batch)

        cache_cfg = self.cache_config

        @jax.jit
        def run(dit_params, latents, ctx, ctx_mask, sigmas, timesteps,
                num_steps):
            schedule = fm.FlowMatchSchedule(sigmas=sigmas,
                                            timesteps=timesteps)

            def eval_velocity(lat, i):
                t = jnp.broadcast_to(timesteps[i], (lat.shape[0],))
                lat = wiring.constrain(lat, seq_dim=1)
                return dit_forward(dit_params, cfg.dit, lat, ctx, t,
                                   ctx_mask, attn_fn=attn_fn)

            return step_cache.run_denoise_loop(
                cache_cfg, schedule, eval_velocity, latents, num_steps)

        self._denoise_cache[key] = run
        return run

    @classmethod
    def from_pretrained(cls, model_dir: str, dtype=jnp.bfloat16,
                        seed: int = 0, mesh=None, cache_config=None,
                        max_text_len: int = 128) -> "StableAudioPipeline":
        """Build from a diffusers-format StableAudio Open repo
        (transformer/ + text_encoder/ T5 + tokenizer/ +
        projection_model/ + vae/ AutoencoderOobleck + scheduler/;
        reference: pipeline_stable_audio.py:88-140).  Every component
        loads real weights or this raises."""
        import json
        import os

        from vllm_omni_tpu.model_loader import diffusers_loader as dl
        from vllm_omni_tpu.models.common import t5 as t5_mod
        from vllm_omni_tpu.models.stable_audio import (
            ckpt_transformer as sdit,
        )
        from vllm_omni_tpu.models.stable_audio import oobleck

        if cache_config is not None:
            raise ValueError(
                "StableAudio's DPM-Solver++ sampler has no step cache")
        dl.load_model_index(model_dir)
        dit_params, dit_cfg = sdit.load_stable_audio_dit(
            os.path.join(model_dir, "transformer"), dtype=dtype)
        te_dir = os.path.join(model_dir, "text_encoder")
        with open(os.path.join(te_dir, "config.json")) as f:
            t5_cfg = t5_mod.T5Config.from_hf(json.load(f))
        t5_params, _ = t5_mod.load_t5(te_dir, cfg=t5_cfg, dtype=dtype)
        proj_params, proj_cfg = load_projection_model(
            os.path.join(model_dir, "projection_model"), dtype=dtype)
        ob_params, ob_cfg = oobleck.load_oobleck_decoder(
            os.path.join(model_dir, "vae"), dtype=jnp.float32)
        sched = dl.scheduler_config(model_dir)

        pipe = cls(StableAudioPipelineConfig.tiny(), dtype=dtype,
                   seed=seed, mesh=mesh, cache_config=None)
        pipe.ckpt_dit_params = pipe.wiring.place(dit_params)
        pipe.ckpt_dit_cfg = dit_cfg
        pipe.t5_params = pipe.wiring.place(t5_params)
        pipe.t5_cfg = t5_cfg
        pipe.proj_params = pipe.wiring.place(proj_params)
        pipe.proj_cfg = proj_cfg
        pipe.oobleck_params = pipe.wiring.place(ob_params)
        pipe.oobleck_cfg = ob_cfg
        pipe.sched_cfg = {
            "sigma_min": sched.get("sigma_min", 0.3),
            "sigma_max": sched.get("sigma_max", 500.0),
            "sigma_data": sched.get("sigma_data", 1.0),
        }
        pipe.ckpt_max_text_len = max_text_len
        tok_dir = os.path.join(model_dir, "tokenizer")
        if not os.path.isdir(tok_dir):
            raise ValueError(f"{model_dir} has no tokenizer/ directory")
        from transformers import AutoTokenizer

        pipe.hf_tokenizer = AutoTokenizer.from_pretrained(tok_dir)
        # the reference pads to tokenizer.model_max_length
        # (encode_prompt, pipeline_stable_audio.py:218-224); honor it
        # when the tokenizer declares a sane value
        ml = getattr(pipe.hf_tokenizer, "model_max_length", None)
        if ml and 0 < int(ml) <= 4096:
            pipe.ckpt_max_text_len = int(ml)
        return pipe

    # ------------------------------------------------- real-weight path
    def _encode_t5(self, texts: list[str]):
        """Tokenize + T5 encode; returns (embeds [B,S,D], mask [B,S])."""
        from vllm_omni_tpu.models.common import t5 as t5_mod

        enc = self.hf_tokenizer(
            texts, padding="max_length", truncation=True,
            max_length=self.ckpt_max_text_len, return_tensors="np")
        ids = jnp.asarray(enc["input_ids"])
        mask = jnp.asarray(enc["attention_mask"])
        if not hasattr(self, "_t5_jit"):
            self._t5_jit = jax.jit(
                lambda p, i, m: t5_mod.forward(p, self.t5_cfg, i, m))
        return self._t5_jit(self.t5_params, ids, mask), mask

    def _ckpt_denoise_fn(self, lat_len: int, steps: int, do_cfg: bool):
        key = ("ckpt", lat_len, steps, do_cfg)
        if key in self._denoise_cache:
            return self._denoise_cache[key]
        from vllm_omni_tpu.models.stable_audio import (
            ckpt_transformer as sdit,
        )

        dcfg = self.ckpt_dit_cfg
        sd = self.sched_cfg["sigma_data"]

        @jax.jit
        def run(params, latents, ctx, glob, sigmas, guidance, key):
            def body(i, carry):
                lat, prev_d = carry
                sig = sigmas[i]
                inp = fm.edm_precondition_inputs(lat, sig, sd)
                t = jnp.broadcast_to(fm.edm_sigma_to_t(sig),
                                     (ctx.shape[0],))
                model_in = (jnp.concatenate([inp, inp], axis=0)
                            if do_cfg else inp)
                v = sdit.forward(params, dcfg,
                                 model_in.astype(self.dtype), t, ctx,
                                 glob).astype(jnp.float32)
                if do_cfg:
                    vu, vc = jnp.split(v, 2, axis=0)
                    v = vu + guidance * (vc - vu)
                denoised = fm.edm_precondition_outputs(lat, v, sig, sd)
                step_noise = jax.random.normal(
                    jax.random.fold_in(key, i), lat.shape, lat.dtype)
                lat = fm.edm_sde_dpm_step(lat, denoised, prev_d, i,
                                          sigmas, step_noise)
                return lat, denoised

            return jax.lax.fori_loop(
                0, steps, body, (latents, jnp.zeros_like(latents)))[0]

        self._denoise_cache[key] = run
        return run

    def _forward_ckpt(self, req: OmniDiffusionRequest):
        sp = req.sampling_params
        dcfg = self.ckpt_dit_cfg
        ob = self.oobleck_cfg
        prompts = req.prompt
        b = len(prompts)
        guidance = (sp.guidance_scale
                    if sp.guidance_scale is not None else 7.0)
        do_cfg = guidance > 1.0
        neg = sp.negative_prompt or None

        pos, pos_mask = self._encode_t5(prompts)
        if do_cfg and neg is not None:
            negs = [neg] * b if isinstance(neg, str) else list(neg)
            nege, neg_mask = self._encode_t5(negs)
            # negatives zero their pad positions before the CFG concat
            # (reference encode_prompt, pipeline_stable_audio.py:262-268)
            nege = nege * neg_mask[..., None].astype(nege.dtype)
            embeds = jnp.concatenate([nege, pos], axis=0)
            mask = jnp.concatenate([neg_mask, pos_mask], axis=0)
        else:
            embeds, mask = pos, pos_mask
        tp = self.proj_params.get("text_proj")
        if tp:  # identity when text and conditioning dims match
            embeds = embeds @ tp["w"] + tp["b"]
        embeds = embeds * mask[..., None].astype(embeds.dtype)

        sr = ob.sampling_rate
        max_s = dcfg.sample_size * ob.hop_length / sr
        start_s = float(sp.extra.get("audio_start_in_s", 0.0))
        end_s = float(sp.extra.get(
            "audio_end_in_s", sp.extra.get("seconds_total", max_s)))
        if start_s < 0 or end_s < start_s:
            raise ValueError(
                f"audio_end_in_s={end_s} must be >= audio_start_in_s="
                f"{start_s} >= 0")
        if end_s - start_s > max_s + 1e-6:
            raise ValueError(
                f"requested {end_s - start_s:.1f}s exceeds the model "
                f"maximum {max_s:.1f}s")
        start_tok = embed_seconds(self.proj_params["start"],
                                  self.proj_cfg,
                                  jnp.full((b,), start_s, jnp.float32))
        end_tok = embed_seconds(self.proj_params["end"], self.proj_cfg,
                                jnp.full((b,), end_s, jnp.float32))
        if do_cfg and neg is not None:
            start_tok = jnp.concatenate([start_tok, start_tok], axis=0)
            end_tok = jnp.concatenate([end_tok, end_tok], axis=0)
        ctx = jnp.concatenate(
            [embeds, start_tok.astype(embeds.dtype),
             end_tok.astype(embeds.dtype)], axis=1)
        glob = jnp.concatenate([start_tok, end_tok],
                               axis=-1)[:, 0, :]
        if do_cfg and neg is None:
            # CFG against the fully-zeroed conditioning (reference
            # :478-489); duration tokens stay on both halves
            ctx = jnp.concatenate([jnp.zeros_like(ctx), ctx], axis=0)
            glob = jnp.concatenate([glob, glob], axis=0)

        steps = max(1, sp.num_inference_steps)
        sched = fm.make_edm_dpm_schedule(steps, **self.sched_cfg)
        lat_len = dcfg.sample_size
        seed = (sp.seed if sp.seed is not None
                else int(np.random.randint(0, 2 ** 31 - 1)))
        noise = jax.random.normal(
            jax.random.PRNGKey(seed), (b, lat_len, dcfg.in_channels),
            jnp.float32) * sched.init_noise_sigma
        run = self._ckpt_denoise_fn(lat_len, steps, do_cfg)
        latents = run(self.ckpt_dit_params, noise,
                      ctx.astype(self.dtype), glob.astype(self.dtype),
                      sched.sigmas, jnp.float32(guidance),
                      jax.random.PRNGKey(seed + 1))

        from vllm_omni_tpu.models.stable_audio import oobleck

        if not hasattr(self, "_oobleck_jit"):
            self._oobleck_jit = jax.jit(
                lambda p, z: oobleck.decode(p, ob, z))
        wav = self._oobleck_jit(self.oobleck_params,
                                latents.astype(jnp.float32))
        # [B, T, C] -> [B, C, T] trimmed to the requested span
        wav = np.asarray(wav, np.float32).transpose(0, 2, 1)
        wav = wav[..., int(start_s * sr): int(end_s * sr)]
        return [
            DiffusionOutput(
                request_id=req.request_ids[i], prompt=prompts[i],
                data=wav[i], output_type="audio",
                metrics={"sample_rate": float(sr)},
            )
            for i in range(b)
        ]

    def forward(self, req: OmniDiffusionRequest) -> list[DiffusionOutput]:
        if getattr(self, "ckpt_dit_params", None) is not None:
            return self._forward_ckpt(req)
        sp = req.sampling_params
        cfg = self.cfg
        # duration in seconds via extras; default 1s
        seconds = float(sp.extra.get("seconds_total", 1.0))
        lat_len = max(8, int(seconds * cfg.sample_rate
                             // cfg.samples_per_latent))
        prompts = req.prompt
        b = len(prompts)
        ctx, ctx_mask = self.encode_prompt(prompts)
        seed = (sp.seed if sp.seed is not None
                else int(np.random.randint(0, 2 ** 31 - 1)))
        noise = jax.random.normal(
            jax.random.PRNGKey(seed),
            (b, lat_len, cfg.dit.latent_channels), self.dtype,
        )
        num_steps = sp.num_inference_steps
        sched_len = max(8, 1 << (num_steps - 1).bit_length())
        schedule = fm.make_schedule(num_steps, shift=1.0)
        sigmas = jnp.zeros((sched_len + 1,)).at[: num_steps + 1].set(
            schedule.sigmas)
        timesteps = jnp.zeros((sched_len,)).at[:num_steps].set(
            schedule.timesteps)
        run = self._denoise_fn(lat_len, sched_len, batch=b)
        latents, skipped = run(self.dit_params, noise, ctx, ctx_mask,
                               sigmas, timesteps, jnp.int32(num_steps))
        self.last_skipped_steps = int(skipped)
        wav = jax.jit(
            lambda p, l: decode_audio(p, cfg, l)
        )(self.decoder_params, latents)
        wav = np.asarray(wav, np.float32)
        return [
            DiffusionOutput(
                request_id=req.request_ids[i], prompt=prompts[i],
                data=wav[i], output_type="audio",
                metrics={"sample_rate": float(cfg.sample_rate)},
            )
            for i in range(b)
        ]


# ---------------------------------------------------------- real weights
def load_projection_model(model_dir: str, dtype=jnp.float32):
    """projection_model/ of a StableAudio Open repo: an optional text
    projection plus two number conditioners embedding the start/end
    seconds (diffusers StableAudioProjectionModel; used reference-side
    via encode_prompt/encode_duration, pipeline_stable_audio.py:123-128,
    280-330).  Feature vector per conditioner: [t, sin(2*pi*t*w),
    cos(2*pi*t*w)] -> Linear."""
    import json
    import os

    from vllm_omni_tpu.model_loader.safetensors_loader import (
        iter_safetensors,
    )

    with open(os.path.join(model_dir, "config.json")) as f:
        hf = json.load(f)
    cfg = {
        "min_value": hf.get("min_value", 0.0),
        "max_value": hf.get("max_value", 512.0),
    }
    params: dict = {"start": {}, "end": {}}
    names = {
        "start_number_conditioner.time_positional_embedding.0.weights":
            ("start", "freqs"),
        "start_number_conditioner.time_positional_embedding.1.weight":
            ("start", "w"),
        "start_number_conditioner.time_positional_embedding.1.bias":
            ("start", "b"),
        "end_number_conditioner.time_positional_embedding.0.weights":
            ("end", "freqs"),
        "end_number_conditioner.time_positional_embedding.1.weight":
            ("end", "w"),
        "end_number_conditioner.time_positional_embedding.1.bias":
            ("end", "b"),
        "text_projection.weight": ("text_proj", "w"),
        "text_projection.bias": ("text_proj", "b"),
    }
    for name, arr in iter_safetensors(model_dir,
                                      name_filter=lambda n: n in names):
        grp, leaf = names[name]
        if leaf == "w" and arr.ndim == 2:
            arr = np.ascontiguousarray(arr.T)
        params.setdefault(grp, {})[leaf] = jnp.asarray(arr, dtype)
    for grp in ("start", "end"):
        if set(params[grp]) != {"freqs", "w", "b"}:
            raise ValueError(
                f"{model_dir}: number conditioner '{grp}' incomplete "
                f"(got {sorted(params[grp])})")
    return params, cfg


def embed_seconds(p, proj_cfg: dict, seconds):
    """[B] seconds -> [B, 1, dim] conditioning tokens."""
    lo, hi = proj_cfg["min_value"], proj_cfg["max_value"]
    t = (jnp.clip(seconds, lo, hi) - lo) / (hi - lo)
    ang = (2.0 * jnp.pi) * t[:, None] \
        * p["freqs"].astype(jnp.float32)[None, :]
    feats = jnp.concatenate(
        [t[:, None], jnp.sin(ang), jnp.cos(ang)], axis=-1)
    out = feats.astype(p["w"].dtype) @ p["w"] + p["b"]
    return out[:, None, :]
