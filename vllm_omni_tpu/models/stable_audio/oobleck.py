"""AutoencoderOobleck decoder (StableAudio Open audio VAE) in JAX.

Checkpoint-schema twin of the diffusers ``AutoencoderOobleck`` decoder
the reference pipeline decodes through (pipeline_stable_audio.py:
174-181, 555-560): Snake1d activations (log-scale alpha/beta), dilated
residual units, strided transposed-conv upsampling, all convolutions
weight-normalized in the checkpoint (folded to plain kernels at load).

TPU-first: NWC layout, weight-norm folded on the host so the device
kernels are ordinary convs XLA can fuse.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.models.common import nn
from vllm_omni_tpu.models.common.vocoder import snake, snake_init


@dataclass(frozen=True)
class OobleckConfig:
    audio_channels: int = 2
    decoder_channels: int = 128
    decoder_input_channels: int = 64
    channel_multiples: tuple = (1, 2, 4, 8, 16)
    downsampling_ratios: tuple = (2, 4, 4, 8, 8)
    sampling_rate: int = 44100

    @property
    def upsampling_ratios(self) -> tuple:
        return tuple(reversed(self.downsampling_ratios))

    @property
    def hop_length(self) -> int:
        out = 1
        for rr in self.downsampling_ratios:
            out *= rr
        return out

    @staticmethod
    def tiny() -> "OobleckConfig":
        return OobleckConfig(audio_channels=1, decoder_channels=8,
                             decoder_input_channels=4,
                             channel_multiples=(1, 2),
                             downsampling_ratios=(2, 4),
                             sampling_rate=16000)

    @staticmethod
    def from_hf(d: dict) -> "OobleckConfig":
        return OobleckConfig(
            audio_channels=d.get("audio_channels", 2),
            decoder_channels=d.get("decoder_channels", 128),
            decoder_input_channels=d.get("decoder_input_channels", 64),
            channel_multiples=tuple(d.get("channel_multiples",
                                          (1, 2, 4, 8, 16))),
            downsampling_ratios=tuple(d.get("downsampling_ratios",
                                            (2, 4, 4, 8, 8))),
            sampling_rate=d.get("sampling_rate", 44100),
        )


def _dims(cfg: OobleckConfig):
    """Per-upsample-block (input_dim, output_dim, stride) following the
    diffusers OobleckDecoder: multiples [1] + channel_multiples, block i
    maps channels*mult[n-i] -> channels*mult[n-i-1]."""
    mult = (1,) + tuple(cfg.channel_multiples)
    n = len(cfg.upsampling_ratios)
    ch = cfg.decoder_channels
    return [(ch * mult[n - i], ch * mult[n - i - 1], s)
            for i, s in enumerate(cfg.upsampling_ratios)]


def init_decoder(key, cfg: OobleckConfig, dtype=jnp.float32):
    ks = iter(jax.random.split(key, 4 + 16 * len(cfg.upsampling_ratios)))
    dims = _dims(cfg)

    def res_unit(dim):
        return {"snake1": snake_init(dim, dtype),
                "conv1": nn.conv1d_init(next(ks), dim, dim, 7,
                                        dtype=dtype),
                "snake2": snake_init(dim, dtype),
                "conv2": nn.conv1d_init(next(ks), dim, dim, 1,
                                        dtype=dtype)}

    p = {"conv1": nn.conv1d_init(next(ks), cfg.decoder_input_channels,
                                 dims[0][0], 7, dtype=dtype),
         "blocks": [],
         "snake_out": snake_init(cfg.decoder_channels, dtype),
         "conv_out": nn.conv1d_init(next(ks), cfg.decoder_channels,
                                    cfg.audio_channels, 7, bias=False,
                                    dtype=dtype)}
    for cin, cout, s in dims:
        p["blocks"].append({
            "snake1": snake_init(cin, dtype),
            # torch ConvTranspose1d [in, out, k] -> [k, out, in] (the
            # transpose_kernel=True forward layout, as code2wav)
            "tconv": {"w": jnp.zeros((2 * s, cout, cin), dtype),
                      "b": jnp.zeros((cout,), dtype)},
            "res1": res_unit(cout),
            "res2": res_unit(cout),
            "res3": res_unit(cout),
        })
    return p


def _res_unit(p, x, dilation: int):
    h = snake(p["snake1"], x)
    h = nn.conv1d(p["conv1"], h, padding=[(3 * dilation, 3 * dilation)],
                  dilation=dilation)
    h = snake(p["snake2"], h)
    return x + nn.conv1d(p["conv2"], h, padding=[(0, 0)])


def _tconv(p, x, stride: int):
    """torch ConvTranspose1d(k=2*stride, stride, padding=ceil(s/2)):
    VALID transpose then symmetric trim."""
    y = jax.lax.conv_transpose(
        x, p["w"].astype(x.dtype), strides=(stride,), padding="VALID",
        dimension_numbers=("NWC", "WIO", "NWC"), transpose_kernel=True)
    pad = -(-stride // 2)
    y = y[:, pad: y.shape[1] - pad]
    return y + p["b"].astype(x.dtype)


def decode(p, cfg: OobleckConfig, z):
    """z [B, T, decoder_input_channels] -> waveform
    [B, T*hop, audio_channels] (NWC)."""
    x = nn.conv1d(p["conv1"], z, padding=[(3, 3)])
    for bp, (_, _, s) in zip(p["blocks"], _dims(cfg)):
        x = snake(bp["snake1"], x)
        x = _tconv(bp["tconv"], x, s)
        x = _res_unit(bp["res1"], x, 1)
        x = _res_unit(bp["res2"], x, 3)
        x = _res_unit(bp["res3"], x, 9)
    x = snake(p["snake_out"], x)
    return nn.conv1d(p["conv_out"], x, padding=[(3, 3)])


# ------------------------------------------------------- checkpoint load
def load_oobleck_decoder(model_dir: str, cfg: OobleckConfig = None,
                         dtype=jnp.float32):
    """Stream the weight-normalized decoder out of vae/ — each conv's
    ``weight_g``/``weight_v`` pair (or ``parametrizations.weight.
    original0/1``) folds to w = g * v / ||v|| on the host."""
    import json
    import os

    from vllm_omni_tpu.model_loader.safetensors_loader import (
        iter_safetensors,
    )

    if cfg is None:
        with open(os.path.join(model_dir, "config.json")) as f:
            cfg = OobleckConfig.from_hf(json.load(f))
    shapes = jax.eval_shape(
        lambda: init_decoder(jax.random.PRNGKey(0), cfg, jnp.float32))

    # hf conv name -> (tree path, kind); kind drives the layout fold
    routes: dict[str, tuple] = {}

    def conv(hf, *path, kind="conv"):
        routes[hf] = (path, kind)

    def res_unit(hf, *path):
        for t, ours in (("snake1.alpha", ("snake1", "alpha")),
                        ("snake1.beta", ("snake1", "beta")),
                        ("snake2.alpha", ("snake2", "alpha")),
                        ("snake2.beta", ("snake2", "beta"))):
            routes[f"{hf}.{t}"] = (path + ours, "snake")
        conv(f"{hf}.conv1", *path, "conv1")
        conv(f"{hf}.conv2", *path, "conv2")

    conv("decoder.conv1", "conv1")
    for i in range(len(cfg.upsampling_ratios)):
        b, t = f"decoder.block.{i}", ("blocks", i)
        routes[f"{b}.snake1.alpha"] = (t + ("snake1", "alpha"), "snake")
        routes[f"{b}.snake1.beta"] = (t + ("snake1", "beta"), "snake")
        conv(f"{b}.conv_t1", *t, "tconv", kind="tconv")
        for j in (1, 2, 3):
            res_unit(f"{b}.res_unit{j}", *t, f"res{j}")
    routes["decoder.snake1.alpha"] = (("snake_out", "alpha"), "snake")
    routes["decoder.snake1.beta"] = (("snake_out", "beta"), "snake")
    conv("decoder.conv2", "conv_out")

    # expand to tensor-level names: weight-norm pairs + biases
    want: dict[str, tuple] = {}
    for hf, (path, kind) in routes.items():
        if kind == "snake":
            want[hf] = (path, "snake", None)
            continue
        for suf, part in (("weight_g", "g"), ("weight_v", "v"),
                          ("parametrizations.weight.original0", "g"),
                          ("parametrizations.weight.original1", "v"),
                          ("weight", "w"), ("bias", "b")):
            want[f"{hf}.{suf}"] = (path, kind, part)

    tree = jax.tree.map(lambda _: None, shapes,
                        is_leaf=lambda x: not isinstance(x, (dict, list)))

    def node(path):
        t = tree
        for k in path[:-1]:
            t = t[k]
        return t

    pending: dict[tuple, dict] = {}
    for name, arr in iter_safetensors(model_dir,
                                      name_filter=lambda nm: nm in want):
        path, kind, part = want[name]
        if kind == "snake":
            node(path)[path[-1]] = jnp.asarray(arr.reshape(-1), dtype)
            continue
        if part == "b":
            node(path + ("b",))["b"] = jnp.asarray(arr, dtype)
            continue
        if part == "w":
            w = arr
        else:
            slot = pending.setdefault(path, {})
            slot[part] = arr
            if len(slot) < 2:
                continue
            v, g = slot.pop("v"), slot.pop("g")
            del pending[path]
            # torch weight_norm dim=0: per-out-channel direction
            norm = np.sqrt((v.astype(np.float64) ** 2)
                           .sum(axis=tuple(range(1, v.ndim)),
                                keepdims=True))
            w = (g.astype(np.float64) * v.astype(np.float64)
                 / norm).astype(np.float32)
        # Conv1d [out, in, k] -> WIO [k, in, out]; ConvTranspose1d
        # [in, out, k] -> [k, out, in] (transpose_kernel layout) — both
        # are transpose(2, 1, 0)
        w = np.ascontiguousarray(w.transpose(2, 1, 0))
        node(path + ("w",))["w"] = jnp.asarray(w, dtype)

    missing = [jax.tree_util.keystr(kp) for kp, leaf
               in jax.tree_util.tree_leaves_with_path(
                   tree, is_leaf=lambda x: x is None) if leaf is None]
    if missing:
        raise ValueError(f"{model_dir}: oobleck decoder missing "
                         f"{len(missing)} leaves (e.g. {missing[:3]})")
    return tree, cfg
