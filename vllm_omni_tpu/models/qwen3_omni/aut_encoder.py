"""Checkpoint-schema AuT audio encoder (real-weight path).

Structural match for the HF ``Qwen3OmniMoeAudioEncoder`` (transformers
qwen3_omni_moe/modeling_qwen3_omni_moe.py; reference consumes the same
tower inside the thinker, vllm_omni/model_executor/models/qwen3_omni/
qwen3_omni_moe_thinker.py): mel frames are split into windows of
``2 * n_window`` frames, each window runs three stride-2 3x3 Conv2d
stages over (freq, time) (8x downsample on both axes), a linear
``conv_out`` folds the frequency axis into ``d_model``, whisper-style
sinusoid positions RESTART per window, and the flattened tokens run a
pre-LayerNorm transformer with BLOCK-DIAGONAL attention
(``n_window_infer``-frame inference windows).  Output head:
ln_post -> proj1 -> gelu -> proj2 -> ``output_dim``.

TPU-first: the reference pads ragged chunk lists with
nn.utils.rnn.pad_sequence and indexes with boolean masks — dynamic
shapes XLA cannot tile.  Here the clip zero-pads to a whole number of
windows and ALL windows (tail included) batch as ONE static conv
([nw, 2w, F] -> [nw, t', d]) — bit-equal to the reference, whose tail
window is convolved zero-padded too; the valid token set is then a
single contiguous slice.  Block-diagonal attention is an additive
[T', T'] bias built host-side from the group ids (exact, and at 30 s
of audio T' = 750 the bias is 2.2 MB — nothing).  The simplified
whisper-style tower in ``audio_encoder.py`` remains the random-init
fast path; this module is the one ``load_aut_encoder`` fills from a
checkpoint.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.models.common import nn


def _gelu(x):
    # exact (erf) GELU — torch F.gelu / ACT2FN["gelu"]; jax.nn.gelu
    # defaults to the tanh approximation, which breaks checkpoint parity
    return jax.nn.gelu(x, approximate=False)


@dataclass(frozen=True)
class AuTEncoderConfig:
    """Mirrors Qwen3OmniMoeAudioEncoderConfig (HF defaults)."""

    num_mel_bins: int = 128
    d_model: int = 1280
    encoder_layers: int = 32
    encoder_attention_heads: int = 20
    encoder_ffn_dim: int = 5120
    downsample_hidden_size: int = 480
    n_window: int = 100
    n_window_infer: int = 400
    output_dim: int = 3584
    max_source_positions: int = 1500

    @property
    def window_frames(self) -> int:
        return 2 * self.n_window

    @property
    def freq_after_cnn(self) -> int:
        f = self.num_mel_bins
        for _ in range(3):
            f = (f - 1) // 2 + 1
        return f

    @staticmethod
    def conv_out_len(frames: int) -> int:
        t = frames
        for _ in range(3):
            t = (t - 1) // 2 + 1
        return t

    @staticmethod
    def tiny(output_dim: int = 64) -> "AuTEncoderConfig":
        return AuTEncoderConfig(
            num_mel_bins=32, d_model=64, encoder_layers=2,
            encoder_attention_heads=4, encoder_ffn_dim=128,
            downsample_hidden_size=16, n_window=8, n_window_infer=32,
            output_dim=output_dim, max_source_positions=64,
        )

    @staticmethod
    def from_hf(hf: dict) -> "AuTEncoderConfig":
        return AuTEncoderConfig(
            num_mel_bins=hf.get("num_mel_bins", 128),
            d_model=hf.get("d_model", 1280),
            encoder_layers=hf.get("encoder_layers", 32),
            encoder_attention_heads=hf.get("encoder_attention_heads",
                                           20),
            encoder_ffn_dim=hf.get("encoder_ffn_dim", 5120),
            downsample_hidden_size=hf.get("downsample_hidden_size", 480),
            n_window=hf.get("n_window", 100),
            n_window_infer=hf.get("n_window_infer", 400),
            output_dim=hf.get("output_dim", 3584),
            max_source_positions=hf.get("max_source_positions", 1500),
        )


def init_params(key, cfg: AuTEncoderConfig, dtype=jnp.float32):
    k = jax.random.split(key, cfg.encoder_layers + 8)
    d, dh = cfg.d_model, cfg.downsample_hidden_size
    params = {
        "conv2d1": nn.conv2d_init(k[0], 1, dh, 3, dtype=dtype),
        "conv2d2": nn.conv2d_init(k[1], dh, dh, 3, dtype=dtype),
        "conv2d3": nn.conv2d_init(k[2], dh, dh, 3, dtype=dtype),
        "conv_out": nn.linear_init(k[3], dh * cfg.freq_after_cnn, d,
                                   bias=False, dtype=dtype),
        "ln_post": nn.layernorm_init(d, dtype=dtype),
        "proj1": nn.linear_init(k[4], d, d, dtype=dtype),
        "proj2": nn.linear_init(k[5], d, cfg.output_dim, dtype=dtype),
        "layers": [],
    }
    for i in range(cfg.encoder_layers):
        kk = jax.random.split(k[i + 8], 6)
        params["layers"].append({
            "attn_norm": nn.layernorm_init(d, dtype=dtype),
            "q_proj": nn.linear_init(kk[0], d, d, dtype=dtype),
            "k_proj": nn.linear_init(kk[1], d, d, dtype=dtype),
            "v_proj": nn.linear_init(kk[2], d, d, dtype=dtype),
            "out_proj": nn.linear_init(kk[3], d, d, dtype=dtype),
            "final_norm": nn.layernorm_init(d, dtype=dtype),
            "fc1": nn.linear_init(kk[4], d, cfg.encoder_ffn_dim,
                                  dtype=dtype),
            "fc2": nn.linear_init(kk[5], cfg.encoder_ffn_dim, d,
                                  dtype=dtype),
        })
    return params


sinusoid_positions = nn.sinusoid_positions


def _conv_stack(params, window: jax.Array) -> jax.Array:
    """[N, frames, mel] -> [N, t', d_model] through the three stride-2
    convs (NHWC: H=freq, W=time) + conv_out fold."""
    x = window.transpose(0, 2, 1)[..., None]  # [N, F, T, 1]
    for key in ("conv2d1", "conv2d2", "conv2d3"):
        x = _gelu(nn.conv2d(params[key], x, stride=2,
                                  padding=((1, 1), (1, 1))))
    n, f, t, c = x.shape
    # HF: permute(0,3,1,2).view(b, t, c*f) — channel-major then freq
    x = x.transpose(0, 2, 3, 1).reshape(n, t, c * f)
    return nn.linear(params["conv_out"], x)


def _group_bias(token_groups: np.ndarray) -> np.ndarray:
    """[T'] group ids -> additive block-diagonal bias [1, 1, T', T']."""
    same = token_groups[:, None] == token_groups[None, :]
    return np.where(same, 0.0, -1e30)[None, None].astype(np.float32)


def attention_groups(cfg: AuTEncoderConfig, num_tokens: int) -> np.ndarray:
    """Group id per token: inference windows of
    ``conv_out_len(window_frames) * (n_window_infer // window_frames)``
    tokens (the reference's cu_seqlens construction)."""
    per = cfg.conv_out_len(cfg.window_frames) \
        * (cfg.n_window_infer // cfg.window_frames)
    return np.arange(num_tokens) // max(per, 1)


def forward(params, cfg: AuTEncoderConfig, mel: jax.Array):
    """One clip: mel [T, num_mel_bins] (T need not be a window multiple)
    -> [T', output_dim] with T' = sum of per-window conv_out lengths.

    The ragged tail window is zero-padded to a full window and run
    through the SAME batched conv — exactly what the reference's
    pad_sequence + masked-select does (its tail outputs see the
    bias-propagated pad region, so convolving the tail at its true
    length would NOT be bit-equal).  The tail's valid tokens are the
    first ``conv_out_len(tail)`` rows of the last window, so the valid
    token set is one contiguous slice — no gather.  Host-side control
    flow only touches STATIC values (T).
    """
    t_frames = int(mel.shape[0])
    w = cfg.window_frames
    if w % 8:
        raise ValueError("window_frames (2*n_window) must be a multiple "
                         "of 8 so per-window conv lengths compose")
    n_win = -(-t_frames // w)
    tail = t_frames - (t_frames // w) * w
    pad = n_win * w - t_frames
    mel_p = jnp.pad(mel, ((0, pad), (0, 0))) if pad else mel
    emb = _conv_stack(params, mel_p.reshape(n_win, w, cfg.num_mel_bins))
    tp = emb.shape[1]  # conv_out_len(w)
    emb = emb + jnp.asarray(
        sinusoid_positions(tp, cfg.d_model), emb.dtype)[None]
    n_tokens = (t_frames // w) * tp \
        + (cfg.conv_out_len(tail) if tail else 0)
    x = emb.reshape(n_win * tp, cfg.d_model)[:n_tokens]

    groups = attention_groups(cfg, int(x.shape[0]))
    bias = jnp.asarray(_group_bias(groups))
    nh = cfg.encoder_attention_heads
    hd = cfg.d_model // nh
    for layer in params["layers"]:
        h = nn.layernorm(layer["attn_norm"], x, eps=1e-5)
        q = nn.linear(layer["q_proj"], h).reshape(1, -1, nh, hd)
        k = nn.linear(layer["k_proj"], h).reshape(1, -1, nh, hd)
        v = nn.linear(layer["v_proj"], h).reshape(1, -1, nh, hd)
        o = nn.bias_attention(q, k, v, bias)
        x = x + nn.linear(layer["out_proj"],
                          o.reshape(-1, cfg.d_model))
        h = nn.layernorm(layer["final_norm"], x, eps=1e-5)
        x = x + nn.linear(layer["fc2"], _gelu(
            nn.linear(layer["fc1"], h)))
    x = nn.layernorm(params["ln_post"], x, eps=1e-5)
    x = _gelu(nn.linear(params["proj1"], x))
    return nn.linear(params["proj2"], x)


# ------------------------------------------------------------------ loader

_LAYER_MAP = {
    "self_attn.q_proj": "q_proj",
    "self_attn.k_proj": "k_proj",
    "self_attn.v_proj": "v_proj",
    "self_attn.out_proj": "out_proj",
    "self_attn_layer_norm": "attn_norm",
    "final_layer_norm": "final_norm",
    "fc1": "fc1",
    "fc2": "fc2",
}


def load_aut_encoder(model_dir: str, cfg: AuTEncoderConfig | None = None,
                     prefix: str = "thinker.audio_tower.",
                     dtype=jnp.float32):
    """Fill the param tree from safetensors under ``prefix``.

    Torch Conv2d weights [out, in, kh, kw] transpose to HWIO; torch
    linears [out, in] transpose to [in, out]; LayerNorms keep w/b.
    Returns (params, cfg).
    """
    import json
    import os
    import re

    from vllm_omni_tpu.model_loader.safetensors_loader import (
        iter_safetensors,
    )

    if cfg is None:
        with open(os.path.join(model_dir, "config.json")) as f:
            hf = json.load(f)
        for part in ("thinker_config", "audio_config"):
            if part in hf:
                hf = hf[part]
        cfg = AuTEncoderConfig.from_hf(hf)
    shapes = jax.eval_shape(
        lambda: init_params(jax.random.PRNGKey(0), cfg, dtype))
    params = jax.tree.map(lambda s: np.zeros(s.shape, s.dtype), shapes)
    layer_re = re.compile(r"^layers\.(\d+)\.(.+?)\.(weight|bias)$")
    loaded, unmapped = 0, []
    for name, arr in iter_safetensors(
            model_dir, lambda n: n.startswith(prefix)):
        sub = name[len(prefix):]
        m = layer_re.match(sub)
        if m:
            li, inner, kind = int(m.group(1)), m.group(2), m.group(3)
            key = _LAYER_MAP.get(inner)
            if key is None or li >= cfg.encoder_layers:
                unmapped.append(name)
                continue
            leaf = params["layers"][li][key]
            if kind == "bias":
                leaf["b"][...] = arr
            elif key in ("attn_norm", "final_norm"):
                leaf["w"][...] = arr
            else:
                leaf["w"][...] = arr.T
            loaded += 1
            continue
        base, _, kind = sub.rpartition(".")
        if base in ("conv2d1", "conv2d2", "conv2d3"):
            if kind == "weight":
                params[base]["w"][...] = np.transpose(arr, (2, 3, 1, 0))
            else:
                params[base]["b"][...] = arr
        elif base == "conv_out" and kind == "weight":
            params[base]["w"][...] = arr.T
        elif base in ("proj1", "proj2"):
            params[base]["w" if kind == "weight" else "b"][
                ...] = arr.T if kind == "weight" else arr
        elif base == "ln_post":
            params[base]["w" if kind == "weight" else "b"][...] = arr
        else:
            unmapped.append(name)
            continue
        loaded += 1
    if loaded == 0:
        raise ValueError(f"no tensors under prefix {prefix!r} in "
                         f"{model_dir}")
    if unmapped:
        from vllm_omni_tpu.logger import init_logger

        init_logger(__name__).warning(
            "unmapped audio-tower tensors (%d): %s", len(unmapped),
            unmapped[:6])
    return jax.tree.map(jnp.asarray, params), cfg
