"""Qwen3-Omni code2wav: ConvNet vocoder, codec tokens → waveform (stage 2).

Reference: vllm_omni/model_executor/models/qwen3_omni/qwen3_omni_code2wav.py
— a one-shot ConvNet generator run under the generation scheduler fast path
(core/sched/omni_generation_scheduler.py:33-261): the whole codec sequence
arrives as the "prompt", one forward emits the waveform, request finishes.

TPU-first layout: NWC 1-D convs (lane dim = channels), transposed-conv
upsampling stack, snake-ish (silu) activations.  Implements the generation
runner model protocol (worker/generation_runner.py).
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.models.common import nn


@dataclass(frozen=True)
class Code2WavConfig:
    codec_vocab: int = 4099
    channels: int = 512
    upsample_factors: tuple = (8, 5, 4, 2)  # total 320x = 16kHz @ 50Hz codes
    kernel: int = 7
    num_res_layers: int = 2

    @staticmethod
    def tiny() -> "Code2WavConfig":
        return Code2WavConfig(
            codec_vocab=64, channels=16, upsample_factors=(2, 2), kernel=3,
            num_res_layers=1,
        )

    @property
    def total_upsample(self) -> int:
        return math.prod(self.upsample_factors)


def init_code2wav_params(key, cfg: Code2WavConfig, dtype=jnp.float32):
    keys = jax.random.split(key, 3 + 2 * len(cfg.upsample_factors)
                            * (1 + cfg.num_res_layers))
    ki = iter(keys)
    params = {
        "embed": nn.embedding_init(next(ki), cfg.codec_vocab, cfg.channels, dtype),
        "pre": nn.conv1d_init(next(ki), cfg.channels, cfg.channels,
                              cfg.kernel, dtype=dtype),
        "ups": [],
        "post": nn.conv1d_init(next(ki), cfg.channels
                               // (2 ** len(cfg.upsample_factors)), 1,
                               cfg.kernel, dtype=dtype),
    }
    ch = cfg.channels
    for f in cfg.upsample_factors:
        out_ch = ch // 2
        block = {
            "up": nn.conv1d_init(next(ki), ch, out_ch, 2 * f, dtype=dtype),
            "res": [
                nn.conv1d_init(next(ki), out_ch, out_ch, cfg.kernel, dtype=dtype)
                for _ in range(cfg.num_res_layers)
            ],
        }
        params["ups"].append(block)
        ch = out_ch
    return params


class Code2WavModel:
    """Generation-runner model protocol implementation."""

    def __init__(self, cfg: Code2WavConfig):
        self.cfg = cfg

    def forward(self, params, token_ids: jax.Array, lengths: jax.Array):
        """token_ids [B, S] codec ids, lengths [B] -> {"audio": [B, S*up]}.

        Padding tokens produce garbage samples past lengths*up; the runner
        slices them off per request (slice_output).
        """
        cfg = self.cfg
        x = nn.embedding(params["embed"], token_ids)  # [B, S, C]
        x = nn.conv1d(params["pre"], x)
        for block, f in zip(params["ups"], cfg.upsample_factors):
            x = jax.nn.silu(x)
            x = nn.conv1d_transpose(block["up"], x, stride=f)
            for res in block["res"]:
                x = x + nn.conv1d(res, jax.nn.silu(x))
        x = jax.nn.silu(x)
        wav = jnp.tanh(nn.conv1d(params["post"], x))  # [B, S*up, 1]
        return {"audio": wav[..., 0]}

    def slice_output(self, outputs: dict, row: int, in_len: int):
        up = self.cfg.total_upsample
        return {"audio": np.asarray(outputs["audio"][row, : in_len * up])}


def tiny_factory():
    """model_factory for generation stages: (params, model_obj, eos)."""
    cfg = Code2WavConfig.tiny()
    params = init_code2wav_params(jax.random.PRNGKey(2), cfg)
    return params, Code2WavModel(cfg), None
