"""Qwen3-Omni code2wav: RVQ codec codes -> waveform (stage 2).

Checkpoint-schema implementation of the transformers
``Qwen3OmniMoeCode2Wav`` vocoder the reference serves one-shot under its
generation scheduler (reference:
vllm_omni/model_executor/models/qwen3_omni/qwen3_omni_code2wav.py:36-258,
core/sched/omni_generation_scheduler.py:33-261):

1. code embedding — one table over ``codebook_size * num_quantizers``
   ids; each RVQ layer k is offset by ``k * codebook_size`` and the K
   embeddings per frame are averaged,
2. pre-transformer — sliding-window rotary transformer with LayerScale
   residuals (temporal context),
3. upsampling — trans-conv(f, f) + ConvNeXt per ratio,
4. decoder — progressive Snake/trans-conv stack to 24 kHz samples,
   trans-convs trimming (kernel - stride) on BOTH sides
   (Qwen3OmniMoeCausalTransConvNet semantics).

TPU-first: NWC layout throughout, the full decode is ONE jitted graph
(the reference chunks in Python for GPU memory; ``chunked_decode`` here
mirrors its bounded-memory streaming loop).  NOTE: unlike the 12.5 Hz
TTS codec, the two-sided trans-conv trim gives each decoder stage one
frame of lookahead, so chunked and full decode intentionally drift near
chunk boundaries — exactly as the reference's own chunked_decode does
(pinned in tests/model_loader/test_code2wav_parity.py).
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.models.common import nn
from vllm_omni_tpu.models.common import vocoder as vk

logger = init_logger(__name__)


@dataclass(frozen=True)
class Code2WavConfig:
    """Mirrors transformers ``Qwen3OmniMoeCode2WavConfig``."""
    codebook_size: int = 2048
    num_quantizers: int = 16
    hidden_size: int = 1024
    decoder_dim: int = 1536
    upsample_rates: tuple = (8, 5, 4, 3)
    upsampling_ratios: tuple = (2, 2)
    num_layers: int = 8
    num_heads: int = 16
    num_kv_heads: int = 16
    intermediate_size: int = 3072
    sliding_window: int = 72
    layer_scale: float = 0.01
    rope_theta: float = 10000.0
    rms_eps: float = 1e-5
    output_sample_rate: int = 24000

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def codec_vocab(self) -> int:
        """Flat id space across the K offset codebooks."""
        return self.codebook_size * self.num_quantizers

    @property
    def total_upsample(self) -> int:
        return int(math.prod(self.upsample_rates)
                   * math.prod(self.upsampling_ratios))

    def waveform_len(self, frames: int) -> int:
        """Exact output samples for ``frames`` codec frames (decoder
        trans-convs lose one input frame per stage to two-sided trim)."""
        t = frames * int(math.prod(self.upsampling_ratios))
        for r in self.upsample_rates:
            t = (t - 1) * r
        return max(t, 0)

    def transformer_spec(self) -> vk.TransformerSpec:
        return vk.TransformerSpec(
            hidden_size=self.hidden_size, num_layers=self.num_layers,
            num_heads=self.num_heads, num_kv_heads=self.num_kv_heads,
            head_dim=self.head_dim,
            intermediate_size=self.intermediate_size,
            sliding_window=self.sliding_window,
            layer_scale=self.layer_scale, rope_theta=self.rope_theta,
            rms_eps=self.rms_eps,
        )

    @staticmethod
    def tiny() -> "Code2WavConfig":
        return Code2WavConfig(
            codebook_size=32, num_quantizers=2, hidden_size=16,
            decoder_dim=24, upsample_rates=(2,), upsampling_ratios=(2,),
            num_layers=1, num_heads=2, num_kv_heads=1,
            intermediate_size=32, sliding_window=4,
        )


def init_code2wav_params(key, cfg: Code2WavConfig, dtype=jnp.float32):
    keys = jax.random.split(key, 4 + 2 * len(cfg.upsampling_ratios))
    ki = iter(keys)
    return {
        "embed": nn.embedding_init(next(ki), cfg.codec_vocab,
                                   cfg.hidden_size, dtype),
        "transformer": vk.transformer_init(next(ki),
                                           cfg.transformer_spec(), dtype),
        "upsample": [
            {"tconv": vk.tconv_init(next(ki), cfg.hidden_size,
                                    cfg.hidden_size, f, dtype),
             "convnext": vk.convnext_init(next(ki), cfg.hidden_size,
                                          dtype)}
            for f in cfg.upsampling_ratios
        ],
        "decoder": vk.decoder_stack_init(next(ki), cfg.hidden_size,
                                         cfg.decoder_dim,
                                         cfg.upsample_rates, dtype),
    }


def decode_codes(params, cfg: Code2WavConfig, codes: jax.Array) -> jax.Array:
    """codes [B, K, T] -> waveform [B, waveform_len(T)] in [-1, 1]."""
    offsets = (jnp.arange(cfg.num_quantizers)
               * cfg.codebook_size)[None, :, None]
    h = nn.embedding(params["embed"], codes + offsets)  # [B, K, T, H]
    h = jnp.mean(h, axis=1)                             # [B, T, H]
    h = vk.sliding_transformer(params["transformer"],
                               cfg.transformer_spec(), h)
    for up, f in zip(params["upsample"], cfg.upsampling_ratios):
        h = vk.tconv(up["tconv"], h, f, f)
        h = vk.convnext(up["convnext"], h)
    return vk.decoder_stack_apply(params["decoder"], h,
                                  cfg.upsample_rates, trim_left=True)


def chunked_decode(params, cfg: Code2WavConfig, codes,
                   chunk_size: int = 300, left_context: int = 25):
    """Frame-chunked decode with left context (reference chunked_decode,
    qwen3_omni_code2wav.py:160-199) — bounded-memory streaming; causality
    keeps chunk outputs close to the full decode."""
    t = codes.shape[-1]
    wavs = []
    start = 0
    while start < t:
        end = min(start + chunk_size, t)
        ctx = left_context if start >= left_context else start
        wav = decode_codes(params, cfg, codes[..., start - ctx: end])
        wavs.append(np.asarray(wav[..., ctx * cfg.total_upsample:]))
        start = end
    return np.concatenate(wavs, axis=-1)


class Code2WavModel:
    """Generation-runner model protocol: the talker's MTP head emits
    ``num_quantizers`` interleaved code streams; the runner hands them
    over as [B, S] rows of packed frames."""

    def __init__(self, cfg: Code2WavConfig):
        self.cfg = cfg

    def forward(self, params, token_ids: jax.Array, lengths: jax.Array):
        cfg = self.cfg
        del lengths
        b, s = token_ids.shape
        k = cfg.num_quantizers
        # partial trailing frames pad with code 0 (never drop to zero
        # frames — degenerate LM samples still produce audio)
        frames = max(1, -(-s // k))
        ids = jnp.clip(token_ids, 0, cfg.codebook_size - 1)
        ids = jnp.pad(ids, ((0, 0), (0, frames * k - s)))
        codes = ids.reshape(b, frames, k).transpose(0, 2, 1)
        wav = decode_codes(params, cfg, codes)
        return {"audio": wav}

    def slice_output(self, outputs: dict, row: int, in_len: int):
        # The decoder's per-stage one-frame lookahead means the last few
        # samples of the slice see the code-0 bucket padding beyond this
        # request's frames — the same batch semantics as the reference,
        # whose runner also decodes padded [B, K, T] and prefix-slices
        # (qwen3_omni_code2wav.py:199-213).  Deterministic, and bounded
        # by one receptive field.
        frames = max(1, -(-in_len // self.cfg.num_quantizers))
        n = self.cfg.waveform_len(frames)
        return {"audio": np.asarray(outputs["audio"][row, :n])}


def tiny_factory():
    """model_factory for generation stages: (params, model_obj, eos)."""
    cfg = Code2WavConfig.tiny()
    params = init_code2wav_params(jax.random.PRNGKey(2), cfg)
    return params, Code2WavModel(cfg), None


# ------------------------------------------------------- checkpoint load
def hf_flat_map(cfg: Code2WavConfig, prefix: str = "code2wav.") -> dict:
    """HF tensor name -> param-tree path for ``Qwen3OmniMoeCode2Wav``
    (composite Qwen3-Omni checkpoints store it under ``code2wav.``)."""
    m: dict[str, tuple] = {}
    m[f"{prefix}code_embedding.weight"] = ("embed", "w")
    vk.transformer_flat_map(m, f"{prefix}pre_transformer",
                            ("transformer",), cfg.num_layers)
    for i in range(len(cfg.upsampling_ratios)):
        up = f"{prefix}upsample.{i}"
        m[f"{up}.0.conv.weight"] = ("upsample", i, "tconv", "w")
        m[f"{up}.0.conv.bias"] = ("upsample", i, "tconv", "b")
        cn = f"{up}.1"
        m[f"{cn}.dwconv.conv.weight"] = ("upsample", i, "convnext", "dw",
                                         "w")
        m[f"{cn}.dwconv.conv.bias"] = ("upsample", i, "convnext", "dw",
                                       "b")
        m[f"{cn}.norm.weight"] = ("upsample", i, "convnext", "norm", "w")
        m[f"{cn}.norm.bias"] = ("upsample", i, "convnext", "norm", "b")
        m[f"{cn}.pwconv1.weight"] = ("upsample", i, "convnext", "pw1", "w")
        m[f"{cn}.pwconv1.bias"] = ("upsample", i, "convnext", "pw1", "b")
        m[f"{cn}.pwconv2.weight"] = ("upsample", i, "convnext", "pw2", "w")
        m[f"{cn}.pwconv2.bias"] = ("upsample", i, "convnext", "pw2", "b")
        m[f"{cn}.gamma"] = ("upsample", i, "convnext", "gamma")
    vk.decoder_stack_flat_map(m, f"{prefix}decoder", ("decoder",),
                              len(cfg.upsample_rates))
    return m


def hf_transform(name: str, arr):
    """torch layouts -> ours: Conv1d [out, in, k] -> WIO [k, in, out]
    and ConvTranspose1d [in, out, k] -> [k, out, in] (the
    ``transpose_kernel=True`` forward layout) — both are
    transpose(2, 1, 0); linears [out, in] -> [in, out]; embeddings stay
    [vocab, dim]."""
    if arr.ndim == 3:
        return arr.transpose(2, 1, 0)
    if arr.ndim == 2 and name.endswith("weight") \
            and "code_embedding" not in name:
        return arr.T
    return arr


def config_from_hf(d: dict) -> Code2WavConfig:
    """Build from a ``code2wav_config`` dict (HF composite config)."""
    hidden = d.get("hidden_size", 1024)
    heads = d.get("num_attention_heads", 16)
    return Code2WavConfig(
        codebook_size=d.get("codebook_size", 2048),
        num_quantizers=d.get("num_quantizers", 16),
        hidden_size=hidden,
        decoder_dim=d.get("decoder_dim", 1536),
        upsample_rates=tuple(d.get("upsample_rates", (8, 5, 4, 3))),
        upsampling_ratios=tuple(d.get("upsampling_ratios", (2, 2))),
        num_layers=d.get("num_hidden_layers", 8),
        num_heads=heads,
        num_kv_heads=d.get("num_key_value_heads", heads),
        intermediate_size=d.get("intermediate_size", 3072),
        sliding_window=d.get("sliding_window", 72),
        layer_scale=d.get("layer_scale_initial_scale", 0.01),
        rope_theta=d.get("rope_theta", 10000.0),
        rms_eps=d.get("rms_norm_eps", 1e-5),
    )


def load_code2wav(model_dir: str, cfg: Code2WavConfig = None,
                  dtype=jnp.float32):
    """Stream the ``code2wav.*`` weights of a Qwen3-Omni checkpoint into
    our param tree; every leaf must be covered."""
    import json
    import os

    from vllm_omni_tpu.model_loader.safetensors_loader import (
        load_checkpoint_tree,
    )

    if cfg is None:
        cfg_path = os.path.join(model_dir, "config.json")
        d = {}
        if os.path.isfile(cfg_path):
            with open(cfg_path) as f:
                d = json.load(f).get("code2wav_config", {})
        cfg = config_from_hf(d)
    shapes = jax.eval_shape(
        lambda: init_code2wav_params(jax.random.PRNGKey(0), cfg,
                                     jnp.float32))
    tree = jax.tree.map(lambda t: np.zeros(t.shape, np.float32), shapes)
    flat = hf_flat_map(cfg)
    n, unmapped = load_checkpoint_tree(
        model_dir, flat.get, tree, dtype=np.float32,
        transform=hf_transform,
    )
    n_leaves = len(jax.tree.leaves(tree))
    if n != n_leaves:
        raise ValueError(
            f"{model_dir} covered {n}/{n_leaves} code2wav weights")
    extra = [u for u in unmapped if u.startswith("code2wav.")]
    if extra:
        logger.warning("code2wav loader: %d unmapped code2wav tensors "
                       "(e.g. %s)", len(extra), extra[:3])
    return tree, cfg


def load_factory(model_dir: str, dtype="float32"):
    """model_factory for real-weight code2wav stages."""
    tree, cfg = load_code2wav(model_dir, dtype=jnp.dtype(dtype))
    return tree, Code2WavModel(cfg), None
