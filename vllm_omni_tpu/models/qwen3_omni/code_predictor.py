"""Qwen3-Omni talker code predictor (MTP over RVQ code groups).

Checkpoint-schema implementation of the transformers
``Qwen3OmniMoeTalkerCodePredictorModelForConditionalGeneration``
(reference: vllm_omni/model_executor/models/qwen3_omni/
qwen3_omni_moe_code_predictor_mtp.py) — a small dense Qwen3 transformer
that, given a talker frame's hidden state and its group-0 codec code,
autoregressively emits the remaining ``num_code_groups - 1`` RVQ codes:
the step-g sequence is [hidden, embed_talker(code_0), embed_1(code_1),
..., embed_g(code_g)] and ``lm_head[g]`` reads code ``g+1`` off the last
position.

Distinct from the engine's EAGLE-style draft head (mtp.py), which
accelerates group-0 decoding — this module produces the *other groups*
of each frame, the codes2wav vocoder's full [K, T] input.

TPU-first: the whole per-frame rollout is one jitted ``lax.scan`` over a
fixed-width buffer (G+1 positions, causal mask) — no KV bookkeeping, no
dynamic shapes; at G=32 the sequence is tiny and the MXU cost is the
lm_head/embed matmuls.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.models.common import transformer as tfm

logger = init_logger(__name__)


def config_from_hf(d: dict) -> tfm.TransformerConfig:
    """``code_predictor_config`` dict -> dense TransformerConfig."""
    heads = d.get("num_attention_heads", 16)
    return tfm.TransformerConfig(
        vocab_size=d.get("vocab_size", 2048),
        hidden_size=d.get("hidden_size", 1024),
        num_layers=d.get("num_hidden_layers", 5),
        num_heads=heads,
        num_kv_heads=d.get("num_key_value_heads", heads),
        head_dim=d.get("head_dim") or d.get("hidden_size", 1024) // heads,
        intermediate_size=d.get("intermediate_size", 3072),
        rope_theta=d.get("rope_theta", 10000.0),
        rms_eps=d.get("rms_norm_eps", 1e-6),
        qk_norm=True,
        attention_bias=d.get("attention_bias", False),
        tie_word_embeddings=True,  # no own single lm_head in the tree
    )


def init_params(key, cfg: tfm.TransformerConfig, num_code_groups: int,
                dtype=jnp.float32):
    """Transformer trunk + per-group embedding tables and heads (groups
    1..G-1; group 0 is embedded by the talker's own codec table).

    The tables live STACKED ([G-1, V, H] / [G-1, H, V]) so the rollout
    indexes them without per-call restacking — at real geometry the
    tables are ~250 MB and predict_codes runs once per audio frame."""
    ke, kh = jax.random.split(jax.random.fold_in(key, 1000))
    base = tfm.init_params(key, cfg, dtype)
    g = num_code_groups - 1
    return {
        "layers": base["layers"], "final_norm": base["final_norm"],
        "embeds": jax.random.normal(
            ke, (g, cfg.vocab_size, cfg.hidden_size), dtype) * 0.02,
        "heads": jax.random.normal(
            kh, (g, cfg.hidden_size, cfg.vocab_size), dtype) * 0.02,
    }


def _trunk(params, cfg: tfm.TransformerConfig, seq):
    """Causal forward over [B, S, H] embeddings -> final hidden."""
    b, s = seq.shape[:2]
    return tfm.forward_hidden(
        params, cfg, jnp.zeros((b, s), jnp.int32), inputs_embeds=seq)


def predict_group_logits(params, cfg: tfm.TransformerConfig, seq,
                         step: int):
    """Prefill-style logits: ``seq`` is [B, 2+step, H] ([hidden, embed_0,
    ..., embed_step]); returns lm_head[step] logits at the last position
    (HF forward with generation_steps inferred from length)."""
    h = _trunk(params, cfg, seq)
    return h[:, -1] @ params["heads"][step]


def predict_codes(params, cfg: tfm.TransformerConfig,
                  hidden: jax.Array,        # [B, H] talker frame hidden
                  code0_embed: jax.Array,   # [B, H] talker embed of code 0
                  num_code_groups: int) -> jax.Array:
    """Greedy rollout of groups 1..G-1; returns codes [B, G-1].

    Fixed-width jitted scan: the sequence buffer holds G+1 positions,
    step g writes embed_g(code_g) into slot 2+g and reads lm_head[g] at
    position 1+g — causality makes the not-yet-written tail irrelevant.
    """
    g_total = num_code_groups - 1
    b, h = hidden.shape
    width = 2 + g_total
    embeds = params["embeds"]   # [G-1, V, H]
    heads = params["heads"]     # [G-1, H, V]

    buf = jnp.zeros((b, width, h), hidden.dtype)
    buf = buf.at[:, 0].set(hidden).at[:, 1].set(code0_embed)

    def step(carry, g):
        buf = carry
        hall = _trunk(params, cfg, buf)          # [B, width, H]
        # logits for group g+1 sit at position 1+g
        pos_h = jax.lax.dynamic_index_in_dim(hall, 1 + g, axis=1,
                                             keepdims=False)
        logits = pos_h @ heads[g]
        code = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B]
        emb = embeds[g][code]                     # [B, H]
        buf = jax.lax.dynamic_update_slice_in_dim(
            buf, emb[:, None].astype(buf.dtype), 2 + g, axis=1)
        return buf, code

    _, codes = jax.lax.scan(step, buf, jnp.arange(g_total))
    return jnp.moveaxis(codes, 0, 1)  # [B, G-1]


# ------------------------------------------------------- checkpoint load
_HF_PREFIX = "talker.code_predictor."


def load_code_predictor(model_dir: str, dtype=jnp.float32):
    """Stream ``talker.code_predictor.*`` weights of a Qwen3-Omni
    checkpoint.  Returns (params, cfg, num_code_groups)."""
    import json
    import os
    import re

    from vllm_omni_tpu.model_loader.safetensors_loader import (
        iter_safetensors,
        np_param_dtype,
    )

    with open(os.path.join(model_dir, "config.json")) as f:
        talker_cfg = json.load(f)["talker_config"]
    pred = talker_cfg["code_predictor_config"]
    groups = pred.get("num_code_groups",
                      talker_cfg.get("num_code_groups", 32))
    cfg = config_from_hf(pred)

    np_dtype = np_param_dtype(dtype)
    shapes = jax.eval_shape(
        lambda: init_params(jax.random.PRNGKey(0), cfg, groups,
                            jnp.float32))
    params = jax.tree_util.tree_map(
        lambda s: np.zeros(s.shape, np_dtype), shapes)

    layer_re = re.compile(
        rf"^{re.escape(_HF_PREFIX)}model\.layers\.(\d+)\.(.+?)\.weight$")
    direct = {
        "input_layernorm": ("input_norm", False),
        "post_attention_layernorm": ("post_norm", False),
        "self_attn.q_proj": ("q_proj", True),
        "self_attn.k_proj": ("k_proj", True),
        "self_attn.v_proj": ("v_proj", True),
        "self_attn.o_proj": ("o_proj", True),
        "self_attn.q_norm": ("q_norm", False),
        "self_attn.k_norm": ("k_norm", False),
        "mlp.down_proj": ("down", True),
    }
    inter = cfg.intermediate_size
    loaded, unmapped = 0, []
    for name, arr in iter_safetensors(
            model_dir, lambda n: n.startswith(_HF_PREFIX)):
        m = layer_re.match(name)
        if m:
            layer = params["layers"][int(m.group(1))]
            sub = m.group(2)
            if sub in direct:
                key, transpose = direct[sub]
                layer[key]["w"][...] = arr.T if transpose else arr
            elif sub == "mlp.gate_proj":
                layer["gate_up"]["w"][:, :inter] = arr.T
            elif sub == "mlp.up_proj":
                layer["gate_up"]["w"][:, inter:] = arr.T
            else:
                unmapped.append(name)
                continue
            loaded += 1
            continue
        tail = name[len(_HF_PREFIX):]
        em = re.match(r"^model\.codec_embedding\.(\d+)\.weight$", tail)
        hm = re.match(r"^lm_head\.(\d+)\.weight$", tail)
        if tail == "model.norm.weight":
            params["final_norm"]["w"][...] = arr
        elif em:
            params["embeds"][int(em.group(1))][...] = arr
        elif hm:
            params["heads"][int(hm.group(1))][...] = arr.T
        else:
            unmapped.append(name)
            continue
        loaded += 1
    if unmapped:
        logger.warning("code_predictor: %d unmapped tensors (e.g. %s)",
                       len(unmapped), unmapped[:4])
    # coverage: every expected HF tensor must have arrived, else the
    # zero-filled buffers would silently emit garbage codes
    # (11 per layer: 2 norms + 4 attn projs + q/k norms + gate/up/down)
    expected = cfg.num_layers * 11 + 1 + 2 * (groups - 1)
    if loaded != expected:
        raise ValueError(
            f"{model_dir}: code_predictor covered {loaded}/{expected} "
            f"weights (unmapped: {unmapped[:4]})")
    logger.info("code_predictor: loaded %d tensors", loaded)
    params = jax.tree_util.tree_map(jnp.asarray, params)
    return params, cfg, groups
