"""Thinker multimodal front end: images/audio -> prompt embeds + MRoPE.

The TPU-native collapse of the reference's multimodal processing chain
(reference: Qwen3OmniMoeThinkerMultiModalProcessor placeholder expansion,
qwen3_omni_moe_thinker.py:235-536; ``embed_multimodal`` merging encoder
outputs into input embeddings :813-941; interleaved position computation
:1081,1193).  One host-side processor object:

1. runs the audio/vision encoders over the request's raw media,
2. expands each modality's placeholder token to the item's token count,
3. scatters encoder outputs into the text-embedding table lookups to form
   ``prompt_embeds``,
4. computes the 3-stream MRoPE positions + generated-token delta.

The result rides the engine's existing embeds-as-input path (the runner's
``inputs_embeds``/``embeds_mask`` machinery) — no new device plumbing.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.models.common.mrope import (
    MMItem,
    compute_mrope_positions,
    expand_placeholders,
)
from vllm_omni_tpu.models.qwen3_omni import audio_encoder, vision_encoder


@dataclass
class ProcessedMM:
    prompt_token_ids: list[int]
    prompt_embeds: np.ndarray  # [S, hidden]
    mrope_positions: np.ndarray  # [3, S]
    mrope_delta: int
    # multiscale visual features as sparse spans: [(offset, arr)] where
    # arr is [n_deep, T_item, hidden] covering prompt positions
    # offset..offset+T_item; level i adds to the residual stream after
    # decoder layer i.  Sparse (per visual item, not a dense [n_deep, S,
    # hidden] table) so a request's host memory scales with its visual
    # tokens, not its context length (reference: deepstack injection,
    # qwen3_omni_moe_thinker.py:177-178)
    deepstack_embeds: Optional[list[tuple[int, np.ndarray]]] = None


class ThinkerMMProcessor:
    """Host-side multimodal input processor for a thinker stage.

    ``multi_modal_data`` accepted by __call__:
      {"image": [HxWx3 uint8/float arrays...],
       "audio": [1-D waveforms or [T, n_mels] mel arrays...]}
    The prompt contains one placeholder token per item, in order.
    """

    def __init__(
        self,
        embed_table: np.ndarray,  # [V, hidden] — thinker token embeddings
        image_token_id: int,
        audio_token_id: int,
        vision_params=None,
        vision_cfg: Optional[vision_encoder.VisionEncoderConfig] = None,
        audio_params=None,
        audio_cfg: Optional[audio_encoder.AudioEncoderConfig] = None,
        sample_rate: int = 16000,
    ):
        self.embed_table = np.asarray(embed_table)
        self.image_token_id = image_token_id
        self.audio_token_id = audio_token_id
        self.vision_params = vision_params
        self.vision_cfg = vision_cfg
        self.audio_params = audio_params
        self.audio_cfg = audio_cfg
        self.sample_rate = sample_rate
        self.placeholder_id = {
            "image": image_token_id, "audio": audio_token_id,
        }
        self._id_to_mod = {v: k for k, v in self.placeholder_id.items()}
        # NOTE: the vision jit compiles once per distinct (H, W) — callers
        # should normalize to a small set of canonical resolutions; audio
        # lengths are bucketed below so mel-length variety is bounded.
        self._vision_fwd = jax.jit(
            lambda p, x: vision_encoder.forward(p, vision_cfg, x)
        ) if vision_cfg else None
        self._audio_fwd = jax.jit(
            lambda p, x, m: audio_encoder.forward(p, audio_cfg, x, m)[0]
        ) if audio_cfg else None

    # ------------------------------------------------------------ encoders
    # Contract: encoders return (feats [T, hidden], grid, deepstack) where
    # deepstack is None or [n_deep, T, hidden] multiscale features to be
    # injected after early LM layers (a 2-tuple without deepstack is
    # tolerated for out-of-tree processors).
    def _encode_image(self, img: np.ndarray):
        if self.vision_cfg is None:
            raise ValueError("no vision encoder configured for this stage")
        img = np.asarray(img)
        if img.dtype == np.uint8:
            img = img.astype(np.float32) / 127.5 - 1.0
        gh, gw = self.vision_cfg.grid(img.shape[0], img.shape[1])
        feats = self._vision_fwd(self.vision_params, img[None])
        return np.asarray(feats[0]), (1, gh, gw), None

    def _encode_audio(self, aud: np.ndarray):
        if self.audio_cfg is None:
            raise ValueError("no audio encoder configured for this stage")
        from vllm_omni_tpu.utils.audio import bucket_waveform_to_mel

        max_f = self.audio_cfg.max_frames
        # shared guard + mel transform (length checks and the
        # samples-per-frame constant live in ONE place); this tower does
        # its own frame-count bucketing below because the encoder masks
        # padded frames rather than treating them as silence
        aud = bucket_waveform_to_mel(
            np.asarray(aud), sr=self.sample_rate,
            n_mels=self.audio_cfg.n_mels, max_frames=max_f,
            pad_pow2=False)
        t = aud.shape[0]
        # bucket the frame count (powers of two, capped at max_frames so
        # padding never exceeds the cap the guard promises) so the encoder
        # compiles once per bucket, not once per clip length; padded
        # frames are masked out inside the encoder
        bucket = 16
        while bucket < t:
            bucket *= 2
        bucket = min(bucket, max_f)
        mel = np.zeros((bucket, aud.shape[1]), np.float32)
        mel[:t] = aud
        mask = (np.arange(bucket) < t).astype(np.int32)
        feats = self._audio_fwd(self.audio_params, mel[None], mask[None])
        n = self.audio_cfg.num_tokens(t)
        return np.asarray(feats[0, :n]), (n,), None

    # ------------------------------------------------------------- process
    def __call__(
        self,
        prompt_token_ids: Sequence[int],
        multi_modal_data: dict[str, Any],
    ) -> ProcessedMM:
        # encode media in prompt order: walk placeholders, pull from the
        # per-modality queues (reference placeholder replacement,
        # qwen3_omni_moe_thinker.py:430-536)
        queues = {
            "image": list(multi_modal_data.get("image", ())),
            "audio": list(multi_modal_data.get("audio", ())),
        }
        prompt_token_ids = list(map(int, prompt_token_ids))
        # Prompts arriving as plain text (API server chat messages) carry
        # no placeholder tokens; by convention missing placeholders are
        # prepended in media order — media-first prompts, the common chat
        # layout (reference inserts placeholders during template
        # processing, qwen3_omni_moe_thinker.py:330).
        have = {m: sum(1 for t in prompt_token_ids
                       if self._id_to_mod.get(t) == m)
                for m in queues}
        prepend: list[int] = []
        for mod, q in queues.items():
            for _ in range(len(q) - have[mod]):
                prepend.append(self.placeholder_id[mod])
        if prepend:
            prompt_token_ids = prepend + prompt_token_ids
        feats: list[np.ndarray] = []
        deepstacks: list[Optional[np.ndarray]] = []
        items_spec: list[tuple[str, tuple]] = []
        for tok in prompt_token_ids:
            mod = self._id_to_mod.get(int(tok))
            if mod is None:
                continue
            if not queues[mod]:
                raise ValueError(f"prompt has more {mod} placeholders than "
                                 f"{mod} items")
            raw = queues[mod].pop(0)
            res = (self._encode_image(raw) if mod == "image"
                   else self._encode_audio(raw))
            # encoders may return (feats, grid) or, for deepstack towers,
            # (feats, grid, deepstack [n_deep, T, hidden])
            f, grid = res[0], res[1]
            deepstacks.append(res[2] if len(res) > 2 else None)
            feats.append(f)
            items_spec.append((mod, grid))
        for mod, q in queues.items():
            if q:
                raise ValueError(f"{len(q)} unused {mod} items")

        expanded, items = expand_placeholders(
            list(map(int, prompt_token_ids)), self.placeholder_id, items_spec
        )
        embeds = self.embed_table[np.asarray(expanded)].astype(np.float32)
        for item, f in zip(items, feats):
            embeds[item.offset:item.offset + item.num_tokens] = f
        deep = [(item.offset, d) for item, d in zip(items, deepstacks)
                if d is not None] or None
        positions, delta = compute_mrope_positions(len(expanded), items)
        return ProcessedMM(
            prompt_token_ids=expanded,
            prompt_embeds=embeds,
            mrope_positions=positions,
            mrope_delta=delta,
            deepstack_embeds=deep,
        )


# --------------------------------------------------------------- factories
def build_tiny_processor(params, model_cfg, **_):
    """mm_processor factory for tests/dry-runs: tiny random encoders sized
    to the thinker's hidden width; placeholder ids live at the top of the
    tiny vocab (image = V-3, audio = V-2)."""
    hidden = model_cfg.hidden_size
    v_cfg = vision_encoder.VisionEncoderConfig.tiny(out_dim=hidden)
    a_cfg = audio_encoder.AudioEncoderConfig.tiny(out_dim=hidden)
    v_params = vision_encoder.init_params(
        jax.random.PRNGKey(11), v_cfg, jnp.float32
    )
    a_params = audio_encoder.init_params(
        jax.random.PRNGKey(12), a_cfg, jnp.float32
    )
    vocab = model_cfg.vocab_size
    return ThinkerMMProcessor(
        embed_table=np.asarray(params["embed"]["w"]),
        image_token_id=vocab - 3,
        audio_token_id=vocab - 2,
        vision_params=v_params,
        vision_cfg=v_cfg,
        audio_params=a_params,
        audio_cfg=a_cfg,
    )
