"""ViT vision tower: pixels -> thinker embeddings.

TPU-native counterpart of the reference thinker's vision tower (reference:
model_executor/models/qwen3_omni/qwen3_omni_moe_thinker.py — Qwen2.5-VL
style vision encoder consumed via transformers: 14px patches, 2-D rotary
positions, bidirectional attention, 2x2 spatial merge into the LM width).

Design: patch embedding as a reshape + matmul (kernel == stride), 2-D RoPE
reusing ``compute_mrope_freqs`` with two sections (row/col own half the
rotary dims each), bidirectional flash attention, and a spatial-merge MLP
whose output grid (h/merge, w/merge) is also the MRoPE image grid.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from vllm_omni_tpu.models.common import nn
from vllm_omni_tpu.ops import (
    apply_rope,
    compute_mrope_freqs,
    flash_attention,
    rms_norm,
)


@dataclass(frozen=True)
class VisionEncoderConfig:
    patch_size: int = 14
    d_model: int = 1152
    num_layers: int = 12
    num_heads: int = 8
    spatial_merge: int = 2
    out_dim: int = 2048  # thinker hidden width
    rms_eps: float = 1e-6

    @staticmethod
    def tiny(out_dim: int = 64) -> "VisionEncoderConfig":
        return VisionEncoderConfig(
            patch_size=4, d_model=32, num_layers=2, num_heads=4,
            spatial_merge=2, out_dim=out_dim,
        )

    def grid(self, height: int, width: int) -> tuple[int, int]:
        """Output token grid (rows, cols) for an image — the MRoPE grid."""
        m = self.patch_size * self.spatial_merge
        if height % m or width % m:
            raise ValueError(
                f"image {height}x{width} must be a multiple of {m} "
                f"(patch {self.patch_size} x merge {self.spatial_merge})"
            )
        return height // m, width // m


def init_params(key, cfg: VisionEncoderConfig, dtype=jnp.float32):
    k = jax.random.split(key, cfg.num_layers + 3)
    p = cfg.patch_size
    m = cfg.spatial_merge
    params = {
        "patch_embed": nn.linear_init(k[0], p * p * 3, cfg.d_model, dtype=dtype),
        "merge": nn.linear_init(
            k[1], m * m * cfg.d_model, cfg.out_dim, dtype=dtype
        ),
        "final_norm": nn.rmsnorm_init(cfg.d_model, dtype),
        "layers": [],
    }
    for i in range(cfg.num_layers):
        kk = jax.random.split(k[i + 3], 6)
        params["layers"].append({
            "input_norm": nn.rmsnorm_init(cfg.d_model, dtype),
            "q_proj": nn.linear_init(kk[0], cfg.d_model, cfg.d_model, dtype=dtype),
            "k_proj": nn.linear_init(kk[1], cfg.d_model, cfg.d_model, dtype=dtype),
            "v_proj": nn.linear_init(kk[2], cfg.d_model, cfg.d_model, dtype=dtype),
            "o_proj": nn.linear_init(kk[3], cfg.d_model, cfg.d_model, dtype=dtype),
            "post_norm": nn.rmsnorm_init(cfg.d_model, dtype),
            "up": nn.linear_init(kk[4], cfg.d_model, 4 * cfg.d_model, dtype=dtype),
            "down": nn.linear_init(kk[5], 4 * cfg.d_model, cfg.d_model, dtype=dtype),
        })
    return params


def forward(
    params,
    cfg: VisionEncoderConfig,
    pixels: jax.Array,  # [B, H, W, 3] float in [-1, 1]
):
    """Return embeds [B, (H/p/m)*(W/p/m), out_dim] (row-major grid —
    matching the MRoPE h/w enumeration in models/common/mrope.py)."""
    b, height, width, _ = pixels.shape
    p = cfg.patch_size
    m = cfg.spatial_merge
    gh, gw = height // p, width // p  # patch grid
    # patchify: [B, gh, p, gw, p, 3] -> [B, gh*gw, p*p*3]
    x = pixels.reshape(b, gh, p, gw, p, 3).transpose(0, 1, 3, 2, 4, 5)
    x = x.reshape(b, gh * gw, p * p * 3)
    x = nn.linear(params["patch_embed"], x)
    t = gh * gw

    # 2-D rope: row/col streams own half the rotary dims each
    head_dim = cfg.d_model // cfg.num_heads
    rows = jnp.repeat(jnp.arange(gh), gw)
    cols = jnp.tile(jnp.arange(gw), gh)
    pos2 = jnp.stack([rows, cols])  # [2, T]
    half = head_dim // 2
    cos, sin = compute_mrope_freqs(
        pos2, head_dim, (half - half // 2, half // 2), theta=10000.0
    )

    for layer in params["layers"]:
        h = rms_norm(x, layer["input_norm"]["w"], cfg.rms_eps)
        q = nn.linear(layer["q_proj"], h).reshape(b * t, cfg.num_heads, head_dim)
        k = nn.linear(layer["k_proj"], h).reshape(b * t, cfg.num_heads, head_dim)
        v = nn.linear(layer["v_proj"], h).reshape(b, t, cfg.num_heads, head_dim)
        # rope tables repeat per batch row ([T, half] tiled to [B*T, half])
        q = apply_rope(q, jnp.tile(cos, (b, 1)), jnp.tile(sin, (b, 1)))
        k = apply_rope(k, jnp.tile(cos, (b, 1)), jnp.tile(sin, (b, 1)))
        o = flash_attention(
            q.reshape(b, t, cfg.num_heads, head_dim),
            k.reshape(b, t, cfg.num_heads, head_dim),
            v, causal=False,
        )
        x = x + nn.linear(layer["o_proj"], o.reshape(b, t, -1))
        h = rms_norm(x, layer["post_norm"]["w"], cfg.rms_eps)
        x = x + nn.linear(layer["down"], jax.nn.gelu(nn.linear(layer["up"], h)))
    x = rms_norm(x, params["final_norm"]["w"], cfg.rms_eps)

    # spatial merge: [B, gh, gw, d] -> [B, gh/m, gw/m, m*m*d] -> out_dim
    x = x.reshape(b, gh // m, m, gw // m, m, cfg.d_model)
    x = x.transpose(0, 1, 3, 2, 4, 5).reshape(
        b, (gh // m) * (gw // m), m * m * cfg.d_model
    )
    return nn.linear(params["merge"], x)
