from vllm_omni_tpu.models.qwen3_omni import code2wav, talker, thinker

__all__ = ["code2wav", "talker", "thinker"]
