"""MTP draft head: multi-token prediction for talker spec decode.

TPU-native counterpart of the reference's talker code predictor
(reference: models/qwen3_omni/qwen3_omni_moe_code_predictor_mtp.py; hooked
into the runner at worker/gpu_model_runner.py:1085, EAGLE-style draft
propose gpu_ar_model_runner.py:466-497).

Shape: a single transformer block over the fusion of the backbone's last
hidden state and the embedding of the token just sampled —
``h' = block(proj([embed(t); h]))`` — whose logits (through the backbone's
own lm_head) propose the next token; chaining k times yields k draft
tokens.  The backbone then *verifies* all k in one multi-token forward
(the runner rides the chunked-prefill kernel), accepting the longest
matching prefix — output tokens are exactly what plain decoding would
produce, steps are fewer.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from vllm_omni_tpu.models.common import nn
from vllm_omni_tpu.models.common.transformer import (
    TransformerConfig,
    _layer_step,
    _rope_tables,
)
from vllm_omni_tpu.ops import flash_attention, rms_norm


@dataclass(frozen=True)
class MTPConfig:
    num_draft_tokens: int = 3


def init_mtp_params(key, cfg: TransformerConfig, dtype=jnp.float32):
    """One extra block + fusion projection; embeddings/lm_head are shared
    with the backbone (passed at draft time)."""
    k1, k2 = jax.random.split(key)
    from vllm_omni_tpu.models.common.transformer import init_params

    # borrow a 1-layer skeleton for the block params
    skel = init_params(
        k1,
        TransformerConfig(
            vocab_size=1,  # unused — no embed/lm_head of its own
            hidden_size=cfg.hidden_size,
            num_layers=1,
            num_heads=cfg.num_heads,
            num_kv_heads=cfg.num_kv_heads,
            head_dim=cfg.head_dim,
            intermediate_size=cfg.intermediate_size,
            qk_norm=cfg.qk_norm,
        ),
        dtype,
    )
    return {
        "fuse": nn.linear_init(
            k2, 2 * cfg.hidden_size, cfg.hidden_size, bias=False, dtype=dtype
        ),
        "block": skel["layers"][0],
        "norm": nn.rmsnorm_init(cfg.hidden_size, dtype),
    }


def tiny_factory(params, model_cfg, num_draft_tokens: int):
    """draft_factory hook for stage configs: random-weight MTP head sized
    to the backbone (acceptance near zero untrained — correctness
    machinery only; real heads come from checkpoint loading)."""
    mtp_params = init_mtp_params(
        jax.random.PRNGKey(21), model_cfg, jnp.float32
    )
    return make_draft_fn(params, model_cfg, mtp_params, num_draft_tokens)


def make_draft_fn(backbone_params, cfg: TransformerConfig, mtp_params,
                  num_draft_tokens: int = 3):
    """Return ``draft(last_hidden [B, H], last_token [B], positions [B])
    -> draft tokens [B, k]`` (jitted).

    Each chain step attends only its own fused state (sequence length 1 —
    the draft block is stateless across steps, trading a little accuracy
    for zero KV bookkeeping; the backbone verify forward is the ground
    truth either way).
    """
    import dataclasses

    from vllm_omni_tpu.models.common.transformer import logits_from_hidden

    # the draft block is always dense, even under an MoE backbone (the
    # reference MTP head is a plain block too) — and the backbone's
    # lm_head/embeddings are shared through `cfg` untouched
    block_cfg = dataclasses.replace(cfg, moe=False)

    @jax.jit
    def draft(last_hidden, last_token, positions):
        b = last_hidden.shape[0]

        def one(carry, _):
            h, tok, pos = carry
            e = nn.embedding(backbone_params["embed"], tok)
            x = nn.linear(mtp_params["fuse"],
                          jnp.concatenate([e, h], axis=-1))
            cos, sin = _rope_tables(
                # draft positions continue the sequence; mrope streams are
                # equal past the prompt so a 1-D continuation is exact
                block_cfg, pos[:, None] if block_cfg.mrope_sections is None
                else jnp.broadcast_to(pos[:, None, None], (b, 3, 1)),
            )

            def attend(q, k, v):
                return flash_attention(
                    q.reshape(b, 1, block_cfg.num_heads,
                              block_cfg.head_dim),
                    k.reshape(b, 1, block_cfg.num_kv_heads,
                              block_cfg.head_dim),
                    v.reshape(b, 1, block_cfg.num_kv_heads,
                              block_cfg.head_dim),
                )

            x = _layer_step(
                mtp_params["block"], block_cfg, x[:, None], cos, sin,
                attend,
            )[:, 0]
            h_new = rms_norm(x, mtp_params["norm"]["w"], block_cfg.rms_eps)
            logits = logits_from_hidden(backbone_params, block_cfg, h_new)
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return (h_new, nxt, pos + 1), nxt

        (_, _, _), toks = jax.lax.scan(
            one, (last_hidden, last_token.astype(jnp.int32),
                  positions.astype(jnp.int32)),
            None, length=num_draft_tokens,
        )
        return jnp.moveaxis(toks, 0, 1)  # [B, k]

    return draft
