"""Qwen3-Omni-MoE thinker: the understanding LM (stage 0).

Reference: vllm_omni/model_executor/models/qwen3_omni/
qwen3_omni_moe_thinker.py (MoE backbone qwen3_moe.py; AuT audio encoder and
vision tower are modality front-ends feeding the same LM).  The TPU build
runs the MoE text backbone on the shared functional transformer
(models/common/transformer.py) with qk_norm (Qwen3 style); audio/vision
encoders land as separate encoder modules that prepend embeddings via the
prompt_embeds path.

The thinker's engine runs with ``collect_hidden=True`` so every generated
token's final hidden state ships to the talker stage (reference:
hidden-state slicing into pooler_output, gpu_ar_model_runner.py:525-568).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from vllm_omni_tpu.models.common.transformer import TransformerConfig, init_params

# Real Qwen3-Omni-30B-A3B thinker geometry (for weight loading later):
# hidden 2048, 48 layers, 32 heads / 4 kv, head_dim 128, 128 experts top-8,
# moe_intermediate 768 (HF config of Qwen3-Omni-MoE thinker text model).
QWEN3_OMNI_THINKER_30B = TransformerConfig(
    vocab_size=151936,
    hidden_size=2048,
    num_layers=48,
    num_heads=32,
    num_kv_heads=4,
    head_dim=128,
    intermediate_size=768,
    qk_norm=True,
    moe=True,
    num_experts=128,
    num_experts_per_tok=8,
    moe_intermediate_size=768,
    # multimodal 3D-RoPE splits of head_dim//2 = 64 (t/h/w), the Qwen-Omni
    # mrope_section from the HF config (reference: mrope.py:25 usage)
    mrope_sections=(24, 20, 20),
)


def tiny_config(vocab_size: int = 128) -> TransformerConfig:
    import dataclasses

    # head_dim 16 -> half 8 -> (4, 2, 2) mrope splits
    return dataclasses.replace(
        TransformerConfig.tiny_moe(vocab_size), mrope_sections=(4, 2, 2)
    )


def tiny_factory():
    """model_factory for tests/dry-runs: random-weight tiny MoE thinker."""
    cfg = tiny_config()
    params = init_params(jax.random.PRNGKey(0), cfg, jnp.float32)
    return params, cfg, None


def real_factory(model_dir: str, dtype="bfloat16", **kw):
    """Arch-registry front door: load the REAL thinker LM from a
    Qwen3-Omni checkpoint directory (the same loader the family's stage
    YAML names, stage_configs/qwen3_omni_moe.yaml:11-16)."""
    from vllm_omni_tpu.model_loader.hf_qwen import load_qwen_lm

    return load_qwen_lm(
        model_dir, dtype=dtype,
        hf_config_name="thinker_config.text_config",
        submodel="thinker", **kw)
