"""Qwen3-Omni thinker multimodal front end over the CHECKPOINT towers.

The shared ThinkerMMProcessor machinery (placeholder expansion, embeds
scatter, MRoPE) driving the real-weight AuT audio encoder
(aut_encoder.py) and ViT vision tower (vit_encoder.py): images flatten
through the same HF Qwen2VL smart-resize / merge-interleave path the
Qwen2.5 intake uses (the Qwen3 ViT consumes the identical patch
order), waveforms become 128-bin log-mels for the windowed AuT stack.
Reference: Qwen3OmniMoeThinkerMultiModalProcessor,
qwen3_omni_moe_thinker.py:235-536.
"""

from __future__ import annotations

import numpy as np

from vllm_omni_tpu.models.qwen2_5_omni.multimodal import flatten_image
from vllm_omni_tpu.models.qwen3_omni import aut_encoder as aut
from vllm_omni_tpu.models.qwen3_omni import vit_encoder as vit
from vllm_omni_tpu.models.qwen3_omni.multimodal import ThinkerMMProcessor


class _VitGeom:
    """flatten_image reads patch geometry fields; adapt the ViT config."""

    def __init__(self, cfg: vit.ViTEncoderConfig):
        self.patch_size = cfg.patch_size
        self.spatial_merge_size = cfg.spatial_merge_size
        self.temporal_patch_size = cfg.temporal_patch_size


class Qwen3ThinkerMMProcessor(ThinkerMMProcessor):
    """Placeholder/MRoPE machinery from the shared processor; encoding
    through the checkpoint-schema AuT + ViT towers."""

    def __init__(self, embed_table, image_token_id: int,
                 audio_token_id: int, aut_params,
                 aut_cfg: aut.AuTEncoderConfig, vit_params,
                 vit_cfg: vit.ViTEncoderConfig,
                 sample_rate: int = 16000):
        super().__init__(embed_table, image_token_id, audio_token_id,
                         vision_params=None, vision_cfg=None,
                         audio_params=None, audio_cfg=None,
                         sample_rate=sample_rate)
        self.aut_params, self.aut_cfg = aut_params, aut_cfg
        self.vit_params, self.vit_cfg = vit_params, vit_cfg
        import jax

        self._vit_jit = jax.jit(vit.forward, static_argnums=(1, 3))
        self._aut_jit = jax.jit(aut.forward, static_argnums=(1,))

    def _encode_image(self, img: np.ndarray):
        pixels, grid = flatten_image(img, _VitGeom(self.vit_cfg))
        import jax.numpy as jnp

        feats, deepstack = self._vit_jit(
            self.vit_params, self.vit_cfg, jnp.asarray(pixels), grid)
        t, gh, gw = grid
        sm = self.vit_cfg.spatial_merge_size
        # deepstack merger outputs [n_deep, T/m^2, out_hidden]: injected
        # into the residual stream after early LM layers (reference:
        # qwen3_omni_moe_thinker.py:177-178 via _get_deepstack_input_embeds)
        ds = (np.stack([np.asarray(d) for d in deepstack], axis=0)
              if deepstack else None)
        return np.asarray(feats), (t, gh // sm, gw // sm), ds

    def _encode_audio(self, aud: np.ndarray):
        from vllm_omni_tpu.utils.audio import bucket_waveform_to_mel

        aud = bucket_waveform_to_mel(
            aud, sr=self.sample_rate, n_mels=self.aut_cfg.num_mel_bins,
            max_frames=2 * self.aut_cfg.max_source_positions)
        import jax.numpy as jnp

        feats = self._aut_jit(self.aut_params, self.aut_cfg,
                              jnp.asarray(aud))
        return np.asarray(feats), (feats.shape[0],), None


def build_real_processor(params, model_cfg, model_dir: str,
                         image_token_id: int = 151655,
                         audio_token_id: int = 151646,
                         dtype="float32", **_):
    """mm_processor factory for real-weight Qwen3-Omni thinker stages:
    loads the AuT audio tower and ViT vision tower from the composite
    checkpoint."""
    import jax.numpy as jnp

    jdtype = jnp.dtype(dtype) if isinstance(dtype, str) else dtype
    aut_params, aut_cfg = aut.load_aut_encoder(model_dir, dtype=jdtype)
    vit_params, vit_cfg = vit.load_vit_encoder(model_dir, dtype=jdtype)
    return Qwen3ThinkerMMProcessor(
        embed_table=np.asarray(params["embed"]["w"]),
        image_token_id=image_token_id,
        audio_token_id=audio_token_id,
        aut_params=aut_params, aut_cfg=aut_cfg,
        vit_params=vit_params, vit_cfg=vit_cfg,
    )


def build_tiny_processor(params, model_cfg, **_):
    """Random tiny towers at the real AuT/ViT schema."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    hidden = model_cfg.hidden_size
    aut_cfg = dataclasses.replace(aut.AuTEncoderConfig.tiny(),
                                  output_dim=hidden)
    vit_cfg = dataclasses.replace(vit.ViTEncoderConfig.tiny(),
                                  out_hidden_size=hidden)
    vocab = model_cfg.vocab_size
    return Qwen3ThinkerMMProcessor(
        embed_table=np.asarray(params["embed"]["w"]),
        image_token_id=vocab - 3,
        audio_token_id=vocab - 2,
        aut_params=aut.init_params(jax.random.PRNGKey(41), aut_cfg,
                                   jnp.float32),
        aut_cfg=aut_cfg,
        vit_params=vit.init_params(jax.random.PRNGKey(42), vit_cfg,
                                   jnp.float32),
        vit_cfg=vit_cfg,
    )
