"""AuT-style audio encoder: log-mel frames -> thinker embeddings.

TPU-native counterpart of the reference thinker's audio tower (reference:
model_executor/models/qwen3_omni/qwen3_omni_moe_thinker.py — the AuT
encoder consumed via transformers; behavioral shape: whisper-style conv
subsampling over mel frames, a bidirectional transformer encoder, and an
output projection into the LM's embedding width; audio token count
qwen3_omni_moe_thinker.py:991 ``_compute_audio_token_count``).

Design: pure-functional pytree params like the rest of the framework; the
conv front-end is two stride-2 1-D convolutions (4x temporal downsample)
expressed as patch-matmuls (reshape + dot — MXU-friendly, no XLA conv
needed for stride == kernel), sinusoidal absolute positions, and
bidirectional flash attention with a length mask.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.models.common import nn
from vllm_omni_tpu.ops import flash_attention, rms_norm


@dataclass(frozen=True)
class AudioEncoderConfig:
    n_mels: int = 128
    d_model: int = 512
    num_layers: int = 8
    num_heads: int = 8
    out_dim: int = 2048  # thinker hidden width
    max_frames: int = 3000  # mel frames before downsampling
    rms_eps: float = 1e-6

    # temporal downsample factor of the conv front-end (2 stride-2 stages)
    downsample: int = 4

    @staticmethod
    def tiny(out_dim: int = 64) -> "AudioEncoderConfig":
        return AudioEncoderConfig(
            n_mels=16, d_model=32, num_layers=2, num_heads=4,
            out_dim=out_dim, max_frames=256,
        )

    def num_tokens(self, num_frames: int) -> int:
        """Audio token count for a mel clip (reference:
        _compute_audio_token_count)."""
        return -(-num_frames // self.downsample)


def init_params(key, cfg: AudioEncoderConfig, dtype=jnp.float32):
    k = jax.random.split(key, cfg.num_layers + 4)
    head_dim = cfg.d_model // cfg.num_heads
    params = {
        # stage 1: pairs of mel frames -> d_model; stage 2: pairs -> d_model
        "conv1": nn.linear_init(k[0], 2 * cfg.n_mels, cfg.d_model, dtype=dtype),
        "conv2": nn.linear_init(k[1], 2 * cfg.d_model, cfg.d_model, dtype=dtype),
        "out_proj": nn.linear_init(k[2], cfg.d_model, cfg.out_dim, dtype=dtype),
        "final_norm": nn.rmsnorm_init(cfg.d_model, dtype),
        "layers": [],
    }
    for i in range(cfg.num_layers):
        kk = jax.random.split(k[i + 4], 6)
        params["layers"].append({
            "input_norm": nn.rmsnorm_init(cfg.d_model, dtype),
            "q_proj": nn.linear_init(kk[0], cfg.d_model, cfg.d_model, dtype=dtype),
            "k_proj": nn.linear_init(kk[1], cfg.d_model, cfg.d_model, dtype=dtype),
            "v_proj": nn.linear_init(kk[2], cfg.d_model, cfg.d_model, dtype=dtype),
            "o_proj": nn.linear_init(kk[3], cfg.d_model, cfg.d_model, dtype=dtype),
            "post_norm": nn.rmsnorm_init(cfg.d_model, dtype),
            "up": nn.linear_init(kk[4], cfg.d_model, 4 * cfg.d_model, dtype=dtype),
            "down": nn.linear_init(kk[5], 4 * cfg.d_model, cfg.d_model, dtype=dtype),
        })
    del head_dim
    return params


def _sinusoid_positions(t: int, d: int) -> np.ndarray:
    pos = np.arange(t)[:, None].astype(np.float32)
    dim = np.arange(0, d, 2)[None, :].astype(np.float32)
    angle = pos / np.power(10000.0, dim / d)
    out = np.zeros((t, d), np.float32)
    out[:, 0::2] = np.sin(angle)
    out[:, 1::2] = np.cos(angle)
    return out


def _downsample_pair(x: jnp.ndarray, w) -> jnp.ndarray:
    """[B, T, C] -> [B, ceil(T/2), 2C] @ w — a stride-2 'conv' as a patch
    matmul (kernel == stride keeps it a pure reshape, which XLA tiles on
    the MXU without any convolution lowering)."""
    b, t, c = x.shape
    if t % 2:
        x = jnp.pad(x, ((0, 0), (0, 1), (0, 0)))
        t += 1
    x = x.reshape(b, t // 2, 2 * c)
    return jax.nn.gelu(nn.linear(w, x))


def forward(
    params,
    cfg: AudioEncoderConfig,
    mel: jax.Array,  # [B, T, n_mels] log-mel frames (right-padded)
    frame_mask: jax.Array | None = None,  # [B, T] 1 = valid frame
):
    """Return (embeds [B, T//downsample, out_dim], token_mask [B, T'])."""
    b, t, _ = mel.shape
    x = _downsample_pair(mel, params["conv1"])
    x = _downsample_pair(x, params["conv2"])
    tp = x.shape[1]
    x = x + jnp.asarray(_sinusoid_positions(tp, cfg.d_model), x.dtype)
    if frame_mask is not None:
        # a token is valid if any of its downsample-window frames is
        pad = (-t) % cfg.downsample
        fm = jnp.pad(frame_mask, ((0, 0), (0, pad)))
        token_mask = fm.reshape(b, tp, cfg.downsample).max(axis=-1)
    else:
        token_mask = jnp.ones((b, tp), jnp.int32)
    head_dim = cfg.d_model // cfg.num_heads
    for layer in params["layers"]:
        h = rms_norm(x, layer["input_norm"]["w"], cfg.rms_eps)
        q = nn.linear(layer["q_proj"], h).reshape(b, tp, cfg.num_heads, head_dim)
        k = nn.linear(layer["k_proj"], h).reshape(b, tp, cfg.num_heads, head_dim)
        v = nn.linear(layer["v_proj"], h).reshape(b, tp, cfg.num_heads, head_dim)
        o = flash_attention(q, k, v, causal=False, kv_mask=token_mask)
        x = x + nn.linear(layer["o_proj"], o.reshape(b, tp, -1))
        h = rms_norm(x, layer["post_norm"]["w"], cfg.rms_eps)
        x = x + nn.linear(layer["down"], jax.nn.gelu(nn.linear(layer["up"], h)))
    x = rms_norm(x, params["final_norm"]["w"], cfg.rms_eps)
    return nn.linear(params["out_proj"], x), token_mask
