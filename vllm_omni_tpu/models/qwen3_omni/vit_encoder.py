"""Checkpoint-schema vision tower (real-weight path).

Structural match for the HF ``Qwen3OmniMoeVisionEncoder`` (transformers
qwen3_omni_moe/modeling_qwen3_omni_moe.py; the reference thinker
consumes the same tower, vllm_omni/model_executor/models/qwen3_omni/
qwen3_omni_moe_thinker.py): Conv3d patch embed over
(temporal_patch, p, p), a learned position table bilinearly
interpolated to the image grid (fast_pos_embed_interpolate), 2D rotary
embeddings over merge-grouped (row, col) positions, pre-LN blocks with
fused-qkv attention and gelu-tanh MLP, a spatial-merge MLP head, and
DEEPSTACK side outputs (postshuffle-norm mergers at intermediate
depths) that the LM injects into its early layers.

TPU-first: tokens arrive merge-grouped (the HF processor's patch
order), so every stage is a static reshape + matmul; the Conv3d with
kernel == stride is a pure patch matmul (no conv lowering); attention
runs full (bidirectional) per image — one image per call keeps
cu_seqlens out of the graph entirely.  The simplified tower in
``vision_encoder.py`` remains the random-init fast path; this module
is the one ``load_vit_encoder`` fills from a checkpoint.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.models.common import nn


def _gelu_tanh(x):
    return jax.nn.gelu(x, approximate=True)  # gelu_pytorch_tanh


def _gelu_exact(x):
    return jax.nn.gelu(x, approximate=False)  # nn.GELU in the mergers


@dataclass(frozen=True)
class ViTEncoderConfig:
    """Mirrors Qwen3OmniMoeVisionEncoderConfig (HF defaults)."""

    depth: int = 27
    hidden_size: int = 1152
    intermediate_size: int = 4304
    num_heads: int = 16
    in_channels: int = 3
    patch_size: int = 16
    spatial_merge_size: int = 2
    temporal_patch_size: int = 2
    out_hidden_size: int = 3584
    num_position_embeddings: int = 2304
    deepstack_visual_indexes: tuple[int, ...] = (8, 16, 24)

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def patch_dim(self) -> int:
        return (self.in_channels * self.temporal_patch_size
                * self.patch_size ** 2)

    @property
    def num_grid_per_side(self) -> int:
        return int(self.num_position_embeddings ** 0.5)

    @staticmethod
    def tiny(out_hidden_size: int = 48) -> "ViTEncoderConfig":
        return ViTEncoderConfig(
            depth=3, hidden_size=32, intermediate_size=64, num_heads=4,
            patch_size=4, spatial_merge_size=2, temporal_patch_size=2,
            out_hidden_size=out_hidden_size, num_position_embeddings=16,
            deepstack_visual_indexes=(1,),
        )

    @staticmethod
    def from_hf(hf: dict) -> "ViTEncoderConfig":
        return ViTEncoderConfig(
            depth=hf.get("depth", 27),
            hidden_size=hf.get("hidden_size", 1152),
            intermediate_size=hf.get("intermediate_size", 4304),
            num_heads=hf.get("num_heads", 16),
            in_channels=hf.get("in_channels", 3),
            patch_size=hf.get("patch_size", 16),
            spatial_merge_size=hf.get("spatial_merge_size", 2),
            temporal_patch_size=hf.get("temporal_patch_size", 2),
            out_hidden_size=hf.get("out_hidden_size", 3584),
            num_position_embeddings=hf.get("num_position_embeddings",
                                           2304),
            deepstack_visual_indexes=tuple(
                hf.get("deepstack_visual_indexes", (8, 16, 24))),
        )


def _merger_init(key, cfg: ViTEncoderConfig, dtype, postshuffle: bool):
    k1, k2 = jax.random.split(key)
    big = cfg.hidden_size * cfg.spatial_merge_size ** 2
    return {
        "ln_q": nn.layernorm_init(big if postshuffle else cfg.hidden_size,
                                  dtype=dtype),
        "fc1": nn.linear_init(k1, big, big, dtype=dtype),
        "fc2": nn.linear_init(k2, big, cfg.out_hidden_size, dtype=dtype),
    }


def init_params(key, cfg: ViTEncoderConfig, dtype=jnp.float32):
    n_deep = len(cfg.deepstack_visual_indexes)
    k = jax.random.split(key, cfg.depth + n_deep + 4)
    params = {
        "patch_embed": nn.linear_init(k[0], cfg.patch_dim,
                                      cfg.hidden_size, dtype=dtype),
        "pos_embed": nn.embedding_init(k[1], cfg.num_position_embeddings,
                                       cfg.hidden_size, dtype),
        "merger": _merger_init(k[2], cfg, dtype, postshuffle=False),
        "deepstack_mergers": [
            _merger_init(k[3 + i], cfg, dtype, postshuffle=True)
            for i in range(n_deep)
        ],
        "blocks": [],
    }
    for i in range(cfg.depth):
        kk = jax.random.split(k[3 + n_deep + i], 4)
        params["blocks"].append({
            "norm1": nn.layernorm_init(cfg.hidden_size, dtype=dtype),
            "norm2": nn.layernorm_init(cfg.hidden_size, dtype=dtype),
            "qkv": nn.linear_init(kk[0], cfg.hidden_size,
                                  3 * cfg.hidden_size, dtype=dtype),
            "proj": nn.linear_init(kk[1], cfg.hidden_size,
                                   cfg.hidden_size, dtype=dtype),
            "fc1": nn.linear_init(kk[2], cfg.hidden_size,
                                  cfg.intermediate_size, dtype=dtype),
            "fc2": nn.linear_init(kk[3], cfg.intermediate_size,
                                  cfg.hidden_size, dtype=dtype),
        })
    return params


# ------------------------------------------------------------ host tables


def merge_grouped_positions(t: int, grid_h: int, grid_w: int,
                            merge: int) -> np.ndarray:
    """(row, col) per token in merge-grouped order (rot_pos_emb):
    [h/m, w/m, m, m] blocks, repeated over t frames."""
    mh, mw = grid_h // merge, grid_w // merge
    rows = (np.arange(mh)[:, None, None, None] * merge
            + np.arange(merge)[None, None, :, None])
    cols = (np.arange(mw)[None, :, None, None] * merge
            + np.arange(merge)[None, None, None, :])
    rows = np.broadcast_to(rows, (mh, mw, merge, merge)).reshape(-1)
    cols = np.broadcast_to(cols, (mh, mw, merge, merge)).reshape(-1)
    coords = np.stack([rows, cols], axis=-1)
    return np.tile(coords, (t, 1))


def rope_tables(cfg: ViTEncoderConfig, t: int, grid_h: int,
                grid_w: int) -> tuple[np.ndarray, np.ndarray]:
    """Neox cos/sin [T, head_dim]: freq table dim head_dim//2 indexed by
    (row, col), halves concatenated then doubled (rot_pos_emb +
    apply_rotary_pos_emb_vision)."""
    dim = cfg.head_dim // 2
    inv = 1.0 / 10000.0 ** (np.arange(0, dim, 2, dtype=np.float64) / dim)
    pos = merge_grouped_positions(t, grid_h, grid_w,
                                  cfg.spatial_merge_size)
    freqs = pos[:, :, None] * inv[None, None, :]  # [T, 2, dim//2]
    emb = freqs.reshape(len(pos), -1)             # [T, dim]
    emb = np.concatenate([emb, emb], axis=-1)     # [T, head_dim]
    return (np.cos(emb).astype(np.float32),
            np.sin(emb).astype(np.float32))


def pos_embed_indices(cfg: ViTEncoderConfig, grid_h: int,
                      grid_w: int) -> tuple[np.ndarray, np.ndarray]:
    """Bilinear interpolation of the learned position grid
    (fast_pos_embed_interpolate): 4 corner index sets + weights, in
    RASTER order [grid_h * grid_w]."""
    side = cfg.num_grid_per_side
    h_idx = np.linspace(0, side - 1, grid_h)
    w_idx = np.linspace(0, side - 1, grid_w)
    hf_, wf_ = h_idx.astype(np.int64), w_idx.astype(np.int64)
    hc = np.clip(hf_ + 1, None, side - 1)
    wc = np.clip(wf_ + 1, None, side - 1)
    dh, dw = h_idx - hf_, w_idx - wf_
    idx = np.stack([
        (hf_[:, None] * side + wf_[None, :]).reshape(-1),
        (hf_[:, None] * side + wc[None, :]).reshape(-1),
        (hc[:, None] * side + wf_[None, :]).reshape(-1),
        (hc[:, None] * side + wc[None, :]).reshape(-1),
    ])
    w = np.stack([
        ((1 - dh)[:, None] * (1 - dw)[None, :]).reshape(-1),
        ((1 - dh)[:, None] * dw[None, :]).reshape(-1),
        (dh[:, None] * (1 - dw)[None, :]).reshape(-1),
        (dh[:, None] * dw[None, :]).reshape(-1),
    ]).astype(np.float32)
    return idx, w


def _interp_pos_embed(params, cfg: ViTEncoderConfig, t: int, grid_h: int,
                      grid_w: int):
    idx, w = pos_embed_indices(cfg, grid_h, grid_w)
    table = params["pos_embed"]["w"]
    pe = (table[idx[0]] * w[0][:, None] + table[idx[1]] * w[1][:, None]
          + table[idx[2]] * w[2][:, None] + table[idx[3]] * w[3][:, None])
    # raster -> merge-grouped order, repeated over frames
    m = cfg.spatial_merge_size
    pe = pe.reshape(grid_h // m, m, grid_w // m, m, -1)
    pe = pe.transpose(0, 2, 1, 3, 4).reshape(grid_h * grid_w, -1)
    return jnp.tile(pe, (t, 1))


def _merger(p, x, cfg: ViTEncoderConfig, postshuffle: bool):
    big = cfg.hidden_size * cfg.spatial_merge_size ** 2
    if postshuffle:
        x = nn.layernorm(p["ln_q"], x.reshape(-1, big), eps=1e-6)
    else:
        x = nn.layernorm(p["ln_q"], x, eps=1e-6).reshape(-1, big)
    return nn.linear(p["fc2"], _gelu_exact(nn.linear(p["fc1"], x)))


def _rotate_half(x):
    half = x.shape[-1] // 2
    return jnp.concatenate([-x[..., half:], x[..., :half]], axis=-1)


def forward(params, cfg: ViTEncoderConfig, patches: jax.Array,
            grid_thw: tuple[int, int, int]):
    """One image/video: pre-patchified ``patches`` [T, patch_dim] in the
    HF processor's merge-grouped order with grid (t, h, w) ->
    (embeds [T/m^2, out_hidden], deepstack list of the same shape)."""
    t, gh, gw = grid_thw
    x = nn.linear(params["patch_embed"], patches)
    x = x + _interp_pos_embed(params, cfg, t, gh, gw).astype(x.dtype)
    cos, sin = rope_tables(cfg, t, gh, gw)
    cos = jnp.asarray(cos)[None, :, None, :]
    sin = jnp.asarray(sin)[None, :, None, :]
    n = x.shape[0]
    nh, hd = cfg.num_heads, cfg.head_dim
    # frames attend only within themselves (cu_seqlens repeats the
    # per-frame token count over t)
    frame = np.arange(n) // (gh * gw)
    bias = jnp.asarray(np.where(
        frame[:, None] == frame[None, :], 0.0, -1e30
    )[None, None].astype(np.float32))
    deepstack = []
    for i, blk in enumerate(params["blocks"]):
        h = nn.layernorm(blk["norm1"], x, eps=1e-6)
        qkv = nn.linear(blk["qkv"], h).reshape(n, 3, nh, hd)
        q, k, v = (qkv[:, 0][None], qkv[:, 1][None], qkv[:, 2][None])
        q = q * cos.astype(q.dtype) + _rotate_half(q) * sin.astype(q.dtype)
        k = k * cos.astype(k.dtype) + _rotate_half(k) * sin.astype(k.dtype)
        o = nn.bias_attention(q, k, v, bias)
        x = x + nn.linear(blk["proj"], o.reshape(n, -1))
        h = nn.layernorm(blk["norm2"], x, eps=1e-6)
        x = x + nn.linear(blk["fc2"], _gelu_tanh(
            nn.linear(blk["fc1"], h)))
        if i in cfg.deepstack_visual_indexes:
            di = cfg.deepstack_visual_indexes.index(i)
            deepstack.append(_merger(params["deepstack_mergers"][di], x,
                                     cfg, postshuffle=True))
    return _merger(params["merger"], x, cfg, postshuffle=False), deepstack


def patchify(frames: np.ndarray, cfg: ViTEncoderConfig
             ) -> tuple[np.ndarray, tuple[int, int, int]]:
    """[T, H, W, 3] float frames -> (patches [N, patch_dim], grid_thw)
    in the HF processor's order (images with T=1 tile the frame over
    the temporal patch)."""
    tp, p, m = cfg.temporal_patch_size, cfg.patch_size, \
        cfg.spatial_merge_size
    t, height, width, ch = frames.shape
    if t % tp:
        frames = np.concatenate(
            [frames, np.repeat(frames[-1:], tp - t % tp, axis=0)])
        t = frames.shape[0]
    gh, gw = height // p, width // p
    x = frames.reshape(t // tp, tp, gh // m, m, p, gw // m, m, p, ch)
    # -> [gt, h/m, w/m, m, m, ch, tp, p, p]
    x = x.transpose(0, 2, 5, 3, 6, 8, 1, 4, 7)
    return (x.reshape(t // tp * gh * gw, cfg.patch_dim),
            (t // tp, gh, gw))


# ------------------------------------------------------------------ loader

_BLOCK_MAP = {
    "norm1": "norm1",
    "norm2": "norm2",
    "attn.qkv": "qkv",
    "attn.proj": "proj",
    "mlp.linear_fc1": "fc1",
    "mlp.linear_fc2": "fc2",
}


def load_vit_encoder(model_dir: str, cfg: ViTEncoderConfig | None = None,
                     prefix: str = "thinker.visual.",
                     dtype=jnp.float32):
    """Fill the param tree from safetensors under ``prefix``.  The
    Conv3d patch embed [out, in, tp, p, p] flattens to the patch-matmul
    layout [in*tp*p*p, out] matching the processor's (ch, tp, p, p)
    element order.  Returns (params, cfg)."""
    import json
    import os
    import re

    from vllm_omni_tpu.model_loader.safetensors_loader import (
        iter_safetensors,
    )

    if cfg is None:
        with open(os.path.join(model_dir, "config.json")) as f:
            hf = json.load(f)
        for part in ("thinker_config", "vision_config"):
            if part in hf:
                hf = hf[part]
        cfg = ViTEncoderConfig.from_hf(hf)
    shapes = jax.eval_shape(
        lambda: init_params(jax.random.PRNGKey(0), cfg, dtype))
    params = jax.tree.map(lambda s: np.zeros(s.shape, s.dtype), shapes)
    block_re = re.compile(r"^blocks\.(\d+)\.(.+?)\.(weight|bias)$")
    merger_re = re.compile(
        r"^merger(?:_list\.(\d+))?\.(ln_q|mlp\.0|mlp\.2)\.(weight|bias)$")
    loaded, unmapped = 0, []
    for name, arr in iter_safetensors(
            model_dir, lambda n: n.startswith(prefix)):
        sub = name[len(prefix):]
        m = block_re.match(sub)
        if m:
            li, inner, kind = int(m.group(1)), m.group(2), m.group(3)
            key = _BLOCK_MAP.get(inner)
            if key is None or li >= cfg.depth:
                unmapped.append(name)
                continue
            leaf = params["blocks"][li][key]
            if kind == "bias":
                leaf["b"][...] = arr
            elif key in ("norm1", "norm2"):
                leaf["w"][...] = arr
            else:
                leaf["w"][...] = arr.T
            loaded += 1
            continue
        m = merger_re.match(sub)
        if m:
            which, inner, kind = m.group(1), m.group(2), m.group(3)
            tree = (params["merger"] if which is None
                    else params["deepstack_mergers"][int(which)])
            key = {"ln_q": "ln_q", "mlp.0": "fc1", "mlp.2": "fc2"}[inner]
            leaf = tree[key]
            if kind == "bias":
                leaf["b"][...] = arr
            elif key == "ln_q":
                leaf["w"][...] = arr
            else:
                leaf["w"][...] = arr.T
            loaded += 1
            continue
        if sub == "patch_embed.proj.weight":
            # [out, in, tp, p, p] -> [in, tp, p, p, out] -> flat [pd, out]
            params["patch_embed"]["w"][...] = np.transpose(
                arr, (1, 2, 3, 4, 0)).reshape(cfg.patch_dim, -1)
            loaded += 1
        elif sub == "patch_embed.proj.bias":
            params["patch_embed"]["b"][...] = arr
            loaded += 1
        elif sub == "pos_embed.weight":
            params["pos_embed"]["w"][...] = arr
            loaded += 1
        else:
            unmapped.append(name)
    if loaded == 0:
        raise ValueError(f"no tensors under prefix {prefix!r} in "
                         f"{model_dir}")
    if unmapped:
        from vllm_omni_tpu.logger import init_logger

        init_logger(__name__).warning(
            "unmapped vision-tower tensors (%d): %s", len(unmapped),
            unmapped[:6])
    return jax.tree.map(jnp.asarray, params), cfg
