"""Qwen3-Omni-MoE talker: AR codec-token LM (stage 1).

Reference: vllm_omni/model_executor/models/qwen3_omni/
qwen3_omni_moe_talker.py — a smaller MoE LM that consumes the thinker's
hidden states (projected into its own width) and autoregressively emits
speech-codec tokens; the MTP code predictor
(qwen3_omni_moe_code_predictor_mtp.py) is a later spec-decode extension.

The thinker→talker handoff rides the engine's prompt_embeds path: the
stage input processor packs thinker hidden states as prompt_embeds, and
the transformer's optional ``embed_proj`` adapts thinker width → talker
width (models/common/transformer.py forward_prefill).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from vllm_omni_tpu.models.common import nn
from vllm_omni_tpu.models.common.transformer import TransformerConfig, init_params

# codec vocabulary (speech tokens); real talker: 32 layers hidden 1024.
QWEN3_OMNI_TALKER_30B = TransformerConfig(
    vocab_size=4096 + 8,  # codec codes + specials
    hidden_size=1024,
    num_layers=20,
    num_heads=16,
    num_kv_heads=4,
    head_dim=128,
    intermediate_size=3072,
    qk_norm=True,
    moe=True,
    num_experts=64,
    num_experts_per_tok=6,
    moe_intermediate_size=384,
)

CODEC_EOS = 4097  # end-of-speech codec token (tiny preset convention)


def tiny_config(codec_vocab: int = 64) -> TransformerConfig:
    return TransformerConfig(
        vocab_size=codec_vocab,
        hidden_size=64,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        head_dim=16,
        intermediate_size=128,
        moe=True,
        num_experts=4,
        num_experts_per_tok=2,
        moe_intermediate_size=64,
    )


def init_talker_params(key, cfg: TransformerConfig, thinker_hidden: int,
                       dtype=jnp.float32):
    """Talker params = MoE LM + projection from thinker hidden width."""
    params = init_params(key, cfg, dtype)
    params["embed_proj"] = nn.linear_init(
        jax.random.fold_in(key, 99), thinker_hidden, cfg.hidden_size,
        bias=False, dtype=dtype,
    )
    return params


def tiny_factory():
    """model_factory: tiny talker consuming 64-wide thinker states."""
    cfg = tiny_config()
    params = init_talker_params(jax.random.PRNGKey(1), cfg,
                                thinker_hidden=64)
    return params, cfg, None


# ------------------------------------------------------- checkpoint load
def load_talker(model_dir: str, dtype=jnp.bfloat16):
    """Load the ``talker.*`` weights of a Qwen3-Omni checkpoint.

    The talker LM is a Qwen3-MoE with a shared expert
    (norm_topk_prob=False) whose token table is ``codec_embedding`` and
    whose output head is ``codec_head`` — both handled by the shared
    Qwen loader.  On top of it ride two ResizeMLP projections from
    thinker width (transformers Qwen3OmniMoeTalkerResizeMLP):
    ``hidden_projection`` feeds the prompt-embeds path (wired as
    ``embed_proj`` so forward_prefill applies it), ``text_projection``
    is kept for the thinker-text conditioning stream.

    Returns (params, cfg, eos) — the model_factory contract; eos is the
    talker's codec EOS id.
    """
    import json
    import os

    import numpy as np

    from vllm_omni_tpu.model_loader.hf_qwen import (
        config_from_hf,
        load_qwen_lm,
    )
    from vllm_omni_tpu.model_loader.safetensors_loader import (
        load_checkpoint_tree,
        np_param_dtype,
    )

    cfg = config_from_hf(model_dir, "talker_config.text_config")
    params, _, _ = load_qwen_lm(model_dir, cfg=cfg, dtype=dtype,
                                submodel="talker")

    with open(os.path.join(model_dir, "config.json")) as f:
        talker_cfg = json.load(f).get("talker_config", {})
    eos = talker_cfg.get("codec_eos_token_id")
    thinker_hidden = talker_cfg.get("thinker_hidden_size", cfg.hidden_size)

    # second pass: the two thinker-width ResizeMLP projections
    # (decoded selectively — the name_filter skips the rest of the
    # composite checkpoint at the shard-key level)
    want = {}
    for hf_key, ours in (("hidden_projection", "embed_proj"),
                         ("text_projection", "text_proj")):
        for fc in ("fc1", "fc2"):
            for leaf, suffix in (("w", "weight"), ("b", "bias")):
                want[f"talker.{hf_key}.linear_{fc}.{suffix}"] = \
                    (ours, fc, leaf)
    np_dtype = np_param_dtype(dtype)
    inter = cfg.intermediate_size
    proj = {
        key: {"fc1": {"w": np.zeros((thinker_hidden, inter), np_dtype),
                      "b": np.zeros((inter,), np_dtype)},
              "fc2": {"w": np.zeros((inter, cfg.hidden_size), np_dtype),
                      "b": np.zeros((cfg.hidden_size,), np_dtype)}}
        for key in ("embed_proj", "text_proj")
    }
    n, _ = load_checkpoint_tree(
        model_dir, want.get, proj, dtype=np_dtype,
        name_filter=lambda name: name in want,
    )
    if n != len(want):
        raise ValueError(
            f"{model_dir}: talker projections covered {n}/{len(want)} "
            "tensors")
    for key in ("embed_proj", "text_proj"):
        params[key] = jax.tree_util.tree_map(jnp.asarray, proj[key])
    return params, cfg, eos


def project_thinker_text(params, text_embeds):
    """Apply the talker's ``text_projection`` ResizeMLP to thinker text
    embeddings (the conditioning stream the reference sums with the
    projected hidden states, qwen3_omni_moe_talker.py)."""
    p = params["text_proj"]
    return jax.numpy.asarray(
        nn.linear(p["fc2"], jax.nn.silu(nn.linear(p["fc1"], text_embeds))))
