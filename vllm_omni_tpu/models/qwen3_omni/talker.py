"""Qwen3-Omni-MoE talker: AR codec-token LM (stage 1).

Reference: vllm_omni/model_executor/models/qwen3_omni/
qwen3_omni_moe_talker.py — a smaller MoE LM that consumes the thinker's
hidden states (projected into its own width) and autoregressively emits
speech-codec tokens; the MTP code predictor
(qwen3_omni_moe_code_predictor_mtp.py) is a later spec-decode extension.

The thinker→talker handoff rides the engine's prompt_embeds path: the
stage input processor packs thinker hidden states as prompt_embeds, and
the transformer's optional ``embed_proj`` adapts thinker width → talker
width (models/common/transformer.py forward_prefill).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from vllm_omni_tpu.models.common import nn
from vllm_omni_tpu.models.common.transformer import TransformerConfig, init_params

# codec vocabulary (speech tokens); real talker: 32 layers hidden 1024.
QWEN3_OMNI_TALKER_30B = TransformerConfig(
    vocab_size=4096 + 8,  # codec codes + specials
    hidden_size=1024,
    num_layers=20,
    num_heads=16,
    num_kv_heads=4,
    head_dim=128,
    intermediate_size=3072,
    qk_norm=True,
    moe=True,
    num_experts=64,
    num_experts_per_tok=6,
    moe_intermediate_size=384,
)

CODEC_EOS = 4097  # end-of-speech codec token (tiny preset convention)


def tiny_config(codec_vocab: int = 64) -> TransformerConfig:
    return TransformerConfig(
        vocab_size=codec_vocab,
        hidden_size=64,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        head_dim=16,
        intermediate_size=128,
        moe=True,
        num_experts=4,
        num_experts_per_tok=2,
        moe_intermediate_size=64,
    )


def init_talker_params(key, cfg: TransformerConfig, thinker_hidden: int,
                       dtype=jnp.float32):
    """Talker params = MoE LM + projection from thinker hidden width."""
    params = init_params(key, cfg, dtype)
    params["embed_proj"] = nn.linear_init(
        jax.random.fold_in(key, 99), thinker_hidden, cfg.hidden_size,
        bias=False, dtype=dtype,
    )
    return params


def tiny_factory():
    """model_factory: tiny talker consuming 64-wide thinker states."""
    cfg = tiny_config()
    params = init_talker_params(jax.random.PRNGKey(1), cfg,
                                thinker_hidden=64)
    return params, cfg, None
