"""Image VAE (AutoencoderKL-style) in functional JAX, NHWC layout.

Role of the reference's ``autoencoder_kl_qwenimage.py`` (16 latent
channels, 8x spatial compression): encoder for image-edit conditioning,
decoder for the pipeline's final latents->pixels stage.  Mid-block
attention + resnet stacks, nearest-neighbour upsampling — all MXU-friendly
convs that XLA fuses; VAE *patch parallel* (reference
vae_patch_parallel.py) maps to sharding H over mesh axes with halo
exchange at the pipeline level (later phase).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from vllm_omni_tpu.models.common import nn


@dataclass(frozen=True)
class VAEConfig:
    latent_channels: int = 16
    base_channels: int = 128
    channel_multipliers: tuple[int, ...] = (1, 2, 4, 4)
    layers_per_block: int = 2
    scaling_factor: float = 0.3611
    shift_factor: float = 0.1159

    @property
    def spatial_ratio(self) -> int:
        return 2 ** (len(self.channel_multipliers) - 1)

    @staticmethod
    def tiny() -> "VAEConfig":
        return VAEConfig(
            latent_channels=4,
            base_channels=16,
            channel_multipliers=(1, 2),
            layers_per_block=1,
            scaling_factor=1.0,
            shift_factor=0.0,
        )


def _resnet_init(key, cin, cout, dtype):
    k = jax.random.split(key, 3)
    p = {
        "norm1": nn.groupnorm_init(cin, dtype),
        "conv1": nn.conv2d_init(k[0], cin, cout, 3, dtype=dtype),
        "norm2": nn.groupnorm_init(cout, dtype),
        "conv2": nn.conv2d_init(k[1], cout, cout, 3, dtype=dtype),
    }
    if cin != cout:
        p["skip"] = nn.conv2d_init(k[2], cin, cout, 1, dtype=dtype)
    return p


def _resnet(p, x):
    h = nn.conv2d(p["conv1"], jax.nn.silu(nn.groupnorm(p["norm1"], x)))
    h = nn.conv2d(p["conv2"], jax.nn.silu(nn.groupnorm(p["norm2"], h)))
    if "skip" in p:
        x = nn.conv2d(p["skip"], x)
    return x + h


def _attn_init(key, ch, dtype):
    k = jax.random.split(key, 4)
    return {
        "norm": nn.groupnorm_init(ch, dtype),
        "q": nn.linear_init(k[0], ch, ch, dtype=dtype),
        "k": nn.linear_init(k[1], ch, ch, dtype=dtype),
        "v": nn.linear_init(k[2], ch, ch, dtype=dtype),
        "o": nn.linear_init(k[3], ch, ch, dtype=dtype),
    }


def _attn(p, x):
    b, h, w, c = x.shape
    xn = nn.groupnorm(p["norm"], x).reshape(b, h * w, c)
    q = nn.linear(p["q"], xn)
    k = nn.linear(p["k"], xn)
    v = nn.linear(p["v"], xn)
    s = jnp.einsum("bqc,bkc->bqk", q, k).astype(jnp.float32) / jnp.sqrt(c)
    a = jax.nn.softmax(s, axis=-1).astype(x.dtype)
    o = nn.linear(p["o"], jnp.einsum("bqk,bkc->bqc", a, v))
    return x + o.reshape(b, h, w, c)


def init_decoder(key, cfg: VAEConfig, dtype=jnp.float32):
    mults = cfg.channel_multipliers
    chans = [cfg.base_channels * m for m in mults]
    top = chans[-1]
    keys = jax.random.split(key, 4 + len(mults))
    p = {
        "conv_in": nn.conv2d_init(keys[0], cfg.latent_channels, top, 3, dtype=dtype),
        "mid_res1": _resnet_init(keys[1], top, top, dtype),
        "mid_attn": _attn_init(keys[2], top, dtype),
        "mid_res2": _resnet_init(keys[3], top, top, dtype),
        "ups": [],
    }
    cur = top
    for i, ch in enumerate(reversed(chans)):
        ks = jax.random.split(keys[4 + i], cfg.layers_per_block + 2)
        blk = {"res": []}
        for j in range(cfg.layers_per_block + 1):
            blk["res"].append(_resnet_init(ks[j], cur, ch, dtype))
            cur = ch
        if i < len(chans) - 1:
            blk["up_conv"] = nn.conv2d_init(ks[-1], cur, cur, 3, dtype=dtype)
        p["ups"].append(blk)
    p["norm_out"] = nn.groupnorm_init(cur, dtype)
    p["conv_out"] = nn.conv2d_init(jax.random.fold_in(key, 7), cur, 3, 3, dtype=dtype)
    return p


def decode(p, cfg: VAEConfig, latents: jax.Array) -> jax.Array:
    """latents: [B, h, w, latent_channels] -> images [B, H, W, 3] in [-1, 1]."""
    z = latents / cfg.scaling_factor + cfg.shift_factor
    x = nn.conv2d(p["conv_in"], z)
    x = _resnet(p["mid_res1"], x)
    x = _attn(p["mid_attn"], x)
    x = _resnet(p["mid_res2"], x)
    for i, blk in enumerate(p["ups"]):
        for r in blk["res"]:
            x = _resnet(r, x)
        if "up_conv" in blk:
            b, h, w, c = x.shape
            x = jax.image.resize(x, (b, h * 2, w * 2, c), "nearest")
            x = nn.conv2d(blk["up_conv"], x)
    x = jax.nn.silu(nn.groupnorm(p["norm_out"], x))
    return nn.conv2d(p["conv_out"], x)


def init_encoder(key, cfg: VAEConfig, dtype=jnp.float32):
    mults = cfg.channel_multipliers
    chans = [cfg.base_channels * m for m in mults]
    keys = jax.random.split(key, 5 + len(mults))
    p = {
        "conv_in": nn.conv2d_init(keys[0], 3, chans[0], 3, dtype=dtype),
        "downs": [],
    }
    cur = chans[0]
    for i, ch in enumerate(chans):
        ks = jax.random.split(keys[1 + i], cfg.layers_per_block + 2)
        blk = {"res": []}
        for j in range(cfg.layers_per_block):
            blk["res"].append(_resnet_init(ks[j], cur, ch, dtype))
            cur = ch
        if i < len(chans) - 1:
            blk["down_conv"] = nn.conv2d_init(ks[-1], cur, cur, 3, dtype=dtype)
        p["downs"].append(blk)
    top = chans[-1]
    p["mid_res1"] = _resnet_init(keys[-3], top, top, dtype)
    p["mid_attn"] = _attn_init(keys[-2], top, dtype)
    p["mid_res2"] = _resnet_init(keys[-1], top, top, dtype)
    p["norm_out"] = nn.groupnorm_init(top, dtype)
    p["conv_out"] = nn.conv2d_init(
        jax.random.fold_in(key, 9), top, 2 * cfg.latent_channels, 3, dtype=dtype
    )
    return p


def encode(p, cfg: VAEConfig, images: jax.Array) -> jax.Array:
    """images [B, H, W, 3] in [-1, 1] -> latent mean [B, h, w, C] (scaled)."""
    x = nn.conv2d(p["conv_in"], images)
    for blk in p["downs"]:
        for r in blk["res"]:
            x = _resnet(r, x)
        if "down_conv" in blk:
            x = nn.conv2d(blk["down_conv"], x, stride=2)
    x = _resnet(p["mid_res1"], x)
    x = _attn(p["mid_attn"], x)
    x = _resnet(p["mid_res2"], x)
    x = jax.nn.silu(nn.groupnorm(p["norm_out"], x))
    moments = nn.conv2d(p["conv_out"], x)
    mean = moments[..., : cfg.latent_channels]
    return (mean - cfg.shift_factor) * cfg.scaling_factor
