"""Qwen-Image text->image pipeline (TPU-native).

Role of the reference's ``QwenImagePipeline``
(vllm_omni/diffusion/models/qwen_image/pipeline_qwen_image.py:241,539-722):
encode_prompt (text-encoder hidden states) -> prepare latents/timesteps
(FlowMatch) -> denoise loop (CFG + MMDiT) -> VAE decode.

TPU-first: the whole denoise loop is ONE jitted computation
(lax.fori_loop over steps — no per-step Python dispatch, no CUDA-graph
machinery); CFG runs as a doubled batch (or, distributed, over the ``cfg``
mesh axis); shapes are static per (H, W) geometry — the step count is a
dynamic loop bound over a padded schedule, so XLA caches one executable
per resolution regardless of num_inference_steps.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.diffusion import cache as step_cache
from vllm_omni_tpu.diffusion import scheduler as fm
from vllm_omni_tpu.diffusion.request import (
    DiffusionOutput,
    InvalidRequestError,
    OmniDiffusionRequest,
)
from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.models.common.transformer import (
    TransformerConfig,
    forward_hidden,
    init_params as init_text_params,
)
from vllm_omni_tpu.models.qwen_image import transformer as dit
from vllm_omni_tpu.models.qwen_image import vae as vae_mod
from vllm_omni_tpu.models.qwen_image.transformer import QwenImageDiTConfig
from vllm_omni_tpu.models.qwen_image.vae import VAEConfig
from vllm_omni_tpu.utils.tokenizer import ByteTokenizer

logger = init_logger(__name__)


@dataclass(frozen=True)
class QwenImagePipelineConfig:
    dit: QwenImageDiTConfig = field(default_factory=QwenImageDiTConfig)
    vae: VAEConfig = field(default_factory=VAEConfig)
    text: TransformerConfig = field(default_factory=TransformerConfig)
    max_text_len: int = 128
    shift: float = 1.0
    use_dynamic_shifting: bool = True
    # "euler" | "unipc" (order-2 multistep, diffusion/scheduler.py)
    scheduler: str = "euler"
    # Schedule arrays are padded to this bucket so the step count is a
    # *dynamic* fori_loop bound: XLA compiles one executable per (H, W)
    # geometry, not per step count, and a 1-step warmup warms the same
    # executable that 50-step requests hit.
    steps_bucket: int = 64

    @staticmethod
    def tiny() -> "QwenImagePipelineConfig":
        return QwenImagePipelineConfig(
            dit=QwenImageDiTConfig.tiny(),
            vae=VAEConfig.tiny(),
            text=TransformerConfig.tiny(vocab_size=512),
            max_text_len=32,
        )

    @staticmethod
    def bench() -> "QwenImagePipelineConfig":
        """Single-chip bench scale (fits one v5e with bf16 weights)."""
        return QwenImagePipelineConfig(
            dit=QwenImageDiTConfig(
                num_layers=16, num_heads=16, head_dim=128, joint_dim=1024
            ),
            vae=VAEConfig(base_channels=64),
            text=TransformerConfig(
                vocab_size=512,
                hidden_size=1024,
                num_layers=8,
                num_heads=8,
                num_kv_heads=4,
                head_dim=128,
                intermediate_size=2816,
            ),
        )


# Text-encoder chat template + drop index for Qwen-Image (reference:
# pipeline_qwen_image.py:293-294 — the first 34 tokens are the fixed
# system/user preamble and are dropped from the embeddings).
PROMPT_TEMPLATE = (
    "<|im_start|>system\nDescribe the image by detailing the color, shape, "
    "size, texture, quantity, text, spatial relationships of the objects "
    "and background:<|im_end|>\n<|im_start|>user\n{}<|im_end|>\n"
    "<|im_start|>assistant\n"
)
PROMPT_TEMPLATE_DROP_IDX = 34


class QwenImagePipeline:
    """Text -> image.  Weights are random-initialized from the config, or
    loaded from a diffusers-format checkpoint via ``from_pretrained``."""

    output_type = "image"

    def __init__(
        self,
        config: QwenImagePipelineConfig,
        dtype=jnp.bfloat16,
        seed: int = 0,
        mesh=None,
        cache_config=None,  # StepCacheConfig | None (step-skip acceleration)
        init_weights: bool = True,
    ):
        self.cfg = config
        self.dtype = dtype
        self.mesh = mesh
        self.cache_config = cache_config
        if config.text.hidden_size != config.dit.joint_dim:
            raise ValueError(
                "text hidden_size must equal dit joint_dim "
                f"({config.text.hidden_size} != {config.dit.joint_dim})"
            )
        self.tokenizer = ByteTokenizer(config.text.vocab_size)
        key = jax.random.PRNGKey(seed)
        k1, k2, k3 = jax.random.split(key, 3)
        # The VAE decoder is always random-init (causal-VAE weight port
        # pending); DiT/text skip init when a checkpoint will overwrite
        # them (init_weights=False avoids materializing + placing tens of
        # GB of randoms only to discard them).
        self.vae_params = self._place(vae_mod.init_decoder(
            k3, config.vae, dtype))
        if init_weights:
            logger.info(
                "Initializing QwenImagePipeline params (dtype=%s)", dtype)
            self.text_params = self._place(
                init_text_params(k1, config.text, dtype))
            self.dit_params = self._place(
                dit.init_params(k2, config.dit, dtype), tp=True)
        else:
            self.text_params = self.dit_params = None
        self._denoise_cache: dict = {}
        # HF text-encode mode (from_pretrained): chat template + drop_idx
        self.hf_tokenizer = None

    def _place(self, params, tp: bool = False):
        """Put a param tree on the mesh: TP layout for the DiT, replicated
        otherwise (reference: SP plan application at model init,
        diffusion/registry.py:122-294).  No-op without a mesh."""
        if self.mesh is None:
            return params
        from vllm_omni_tpu.parallel.sharding import (
            replicated,
            shard_dit_params,
        )

        if tp:
            return shard_dit_params(params, self.mesh)
        return jax.device_put(params, replicated(self.mesh))

    @classmethod
    def from_pretrained(
        cls,
        model_dir: str,
        dtype=jnp.bfloat16,
        seed: int = 0,
        mesh=None,
        cache_config=None,
        max_text_len: int = 512,
    ) -> "QwenImagePipeline":
        """Build from a diffusers-format checkpoint directory (reference:
        DiffusersPipelineLoader, diffusion/model_loader/diffusers_loader.py
        + pipeline component resolution, omni_diffusion.py:34-109).

        Loads the DiT and the Qwen2.5-VL-style text encoder with real
        weights, the HF tokenizer, and the FlowMatch scheduler shift
        config.  The VAE decoder keeps our conv architecture (temporal/
        causal VAE weight port is tracked separately) — random-init with a
        warning when the checkpoint's VAE doesn't match.
        """
        import os

        from vllm_omni_tpu.model_loader import diffusers_loader as dl

        dl.load_model_index(model_dir)  # validates layout
        dit_params, dit_cfg = dl.load_qwen_image_dit(
            os.path.join(model_dir, "transformer"), dtype=dtype
        )
        te_dir = os.path.join(model_dir, "text_encoder")
        text_params, text_cfg = dl.load_text_encoder(te_dir, dtype=dtype)
        sched = dl.scheduler_config(model_dir)
        config = QwenImagePipelineConfig(
            dit=dit_cfg,
            vae=VAEConfig(latent_channels=dit_cfg.out_channels),
            text=text_cfg,
            max_text_len=max_text_len,
            # defaults mirror diffusers FlowMatchEulerDiscreteScheduler
            # (and scheduler_config()'s own) so present-but-sparse and
            # absent scheduler configs behave identically
            shift=sched.get("shift", 1.0),
            use_dynamic_shifting=sched.get("use_dynamic_shifting", False),
        )
        pipe = cls(config, dtype=dtype, seed=seed, mesh=mesh,
                   cache_config=cache_config, init_weights=False)
        pipe.dit_params = pipe._place(dit_params, tp=True)
        pipe.text_params = pipe._place(text_params)
        logger.warning(
            "VAE weights not loaded from %s (conv decoder is random-init; "
            "causal-VAE port pending)", model_dir,
        )
        tok_dir = os.path.join(model_dir, "tokenizer")
        if os.path.isdir(tok_dir):
            from transformers import AutoTokenizer

            pipe.hf_tokenizer = AutoTokenizer.from_pretrained(tok_dir)
            # the drop-34 preamble removal in _encode_prompt_hf is only
            # correct under right padding; some checkpoints ship
            # padding_side='left' in tokenizer_config.json
            pipe.hf_tokenizer.padding_side = "right"
        else:
            logger.warning("no tokenizer/ under %s; byte fallback",
                           model_dir)
        return pipe

    # ------------------------------------------------------------- encode
    def encode_prompt(self, prompts: list[str]):
        """Returns (hidden [B, S, joint_dim], mask [B, S])."""
        if self.hf_tokenizer is not None:
            return self._encode_prompt_hf(prompts)
        ids, lens = self.tokenizer.batch_encode(prompts, self.cfg.max_text_len)
        hidden = self._encode_jit(self.text_params, jnp.asarray(ids))
        mask = (
            np.arange(self.cfg.max_text_len)[None, :] < lens[:, None]
        ).astype(np.int32)
        return hidden, jnp.asarray(mask)

    def _encode_prompt_hf(self, prompts: list[str]):
        """Real-checkpoint text encoding: chat-template the prompt, take
        the final hidden states, and drop the fixed 34-token preamble
        (reference: _get_qwen_prompt_embeds, pipeline_qwen_image.py:366-399
        — with right padding, dropping the first `drop_idx` positions
        equals dropping the first drop_idx real tokens; we keep a static
        [B, max_text_len] shape and carry validity in the mask)."""
        drop = PROMPT_TEMPLATE_DROP_IDX
        txts = [PROMPT_TEMPLATE.format(p) for p in prompts]
        enc = self.hf_tokenizer(
            txts,
            max_length=self.cfg.max_text_len + drop,
            padding="max_length",
            truncation=True,
            return_tensors="np",
        )
        ids = np.asarray(enc["input_ids"], np.int32)
        mask = np.asarray(enc["attention_mask"], np.int32)
        hidden = self._encode_jit(self.text_params, jnp.asarray(ids))
        return (
            hidden[:, drop:].astype(self.dtype),
            jnp.asarray(mask[:, drop:]),
        )

    @functools.cached_property
    def _encode_jit(self):
        # params are an explicit jit ARGUMENT: closure capture would bake
        # them into the executable as constants, so sleep() couldn't free
        # the buffers and weight swaps would silently not apply
        return jax.jit(
            lambda p, ids: forward_hidden(p, self.cfg.text, ids)
        )

    # ------------------------------------------------------------ denoise
    def _sp_attn_fn(self, n_heads: int, seq_len: int, batch2: int):
        """shard_map-wrapped joint USP attention for the DiT blocks, or
        None when the mesh/shape constraints don't allow the explicit SP
        path (GSPMD still partitions the dense fallback correctly)."""
        mesh = self.mesh
        if mesh is None:
            return None
        ax = dict(zip(mesh.axis_names, mesh.devices.shape))
        sp = ax.get("ring", 1) * ax.get("ulysses", 1)
        tp = ax.get("tp", 1)
        if sp == 1 and tp == 1:
            return None
        if (seq_len % sp or n_heads % tp
                or (n_heads // tp) % ax.get("ulysses", 1)
                or batch2 % (ax.get("cfg", 1) * ax.get("dp", 1))):
            logger.warning(
                "mesh %s does not divide (seq=%d, heads=%d, batch=%d); "
                "falling back to GSPMD-partitioned dense attention",
                ax, seq_len, n_heads, batch2,
            )
            return None
        from jax import shard_map
        from jax.sharding import PartitionSpec as P

        from vllm_omni_tpu.parallel.context import joint_sp_attention

        bspec = ("cfg", "dp")
        img_spec = P(bspec, ("ring", "ulysses"), "tp", None)
        txt_spec = P(bspec, None, "tp", None)
        mask_spec = P(bspec, None)
        inner = shard_map(
            functools.partial(
                joint_sp_attention, ulysses_axis="ulysses", ring_axis="ring"
            ),
            mesh=mesh,
            in_specs=(img_spec,) * 3 + (txt_spec,) * 3 + (mask_spec,),
            out_specs=(img_spec, txt_spec),
        )

        def attn_fn(qi, ki, vi, qt, kt, vt, txt_kv_mask):
            if txt_kv_mask is None:
                txt_kv_mask = jnp.ones(qt.shape[:2], jnp.int32)
            img_o, txt_o = inner(qi, ki, vi, qt, kt, vt, txt_kv_mask)
            # block_forward's attn_fn contract: flattened [B, S, H*D]
            return (img_o.reshape(*img_o.shape[:2], -1),
                    txt_o.reshape(*txt_o.shape[:2], -1))

        return attn_fn

    def _denoise_fn(self, grid_h: int, grid_w: int, sched_len: int,
                    batch2: int = 0):
        # batch2 affects only the shard_map attn dispatch decision — keep
        # it out of the key on meshless pipelines (jit handles shapes).
        key = (grid_h, grid_w, sched_len) + (
            (batch2,) if self.mesh is not None else ())
        if key in self._denoise_cache:
            return self._denoise_cache[key]

        cfg = self.cfg
        attn_fn = self._sp_attn_fn(
            cfg.dit.num_heads, grid_h * grid_w, batch2)
        mesh = self.mesh
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            lat2_sharding = NamedSharding(
                mesh, P(("cfg", "dp"), ("ring", "ulysses"), None))
            txt2_sharding = NamedSharding(mesh, P(("cfg", "dp"), None, None))

        @jax.jit
        def run(
            dit_params, latents, txt, txt_mask, neg_txt, neg_mask,
            sigmas, timesteps, gscale, num_steps,
        ):
            # latents: [B, S_img, C_in]; txt/neg_txt: [B, S_txt, joint];
            # sigmas/timesteps padded to sched_len(+1); num_steps is a
            # traced scalar — the loop bound is dynamic, the shapes static.
            schedule = fm.FlowMatchSchedule(sigmas=sigmas, timesteps=timesteps)
            do_cfg = neg_txt is not None
            txt_all = (
                jnp.concatenate([txt, neg_txt], axis=0) if do_cfg else txt
            )
            mask_all = (
                jnp.concatenate([txt_mask, neg_mask], axis=0)
                if do_cfg
                else txt_mask
            )
            if mesh is not None:
                # CFG parallel: the [positive; negative] halves of the
                # doubled batch ride the cfg axis (cfg outermost in the
                # batch spec), image sequence over the SP axes — GSPMD
                # inserts the cfg combine at the guidance step below.
                txt_all = jax.lax.with_sharding_constraint(
                    txt_all, txt2_sharding)

            def eval_velocity(lat, i):
                t = jnp.broadcast_to(timesteps[i], (lat.shape[0],))
                lat_in = jnp.concatenate([lat, lat], 0) if do_cfg else lat
                t_in = jnp.concatenate([t, t], 0) if do_cfg else t
                if mesh is not None:
                    lat_in = jax.lax.with_sharding_constraint(
                        lat_in, lat2_sharding)
                v = dit.forward(
                    dit_params, cfg.dit, lat_in, txt_all, t_in,
                    (grid_h, grid_w), attn_fn=attn_fn, txt_mask=mask_all,
                )
                if do_cfg:
                    v_pos, v_neg = jnp.split(v, 2, axis=0)
                    v = v_neg + gscale * (v_pos - v_neg)
                return v

            return step_cache.run_denoise_loop(
                self.cache_config, schedule, eval_velocity, latents,
                num_steps, solver=self.cfg.scheduler,
            )

        self._denoise_cache[key] = run
        return run

    # ----------------------------------------------------------- generate
    def forward(self, req: OmniDiffusionRequest) -> list[DiffusionOutput]:
        sp = req.sampling_params
        cfg = self.cfg
        ratio = cfg.vae.spatial_ratio
        patch = cfg.dit.patch_size
        mult = ratio * patch
        if sp.height % mult or sp.width % mult:
            raise InvalidRequestError(
                f"height/width must be multiples of {mult} "
                f"(vae ratio {ratio} x patch {patch}); got "
                f"{sp.height}x{sp.width}"
            )
        if sp.num_inference_steps < 1:
            raise InvalidRequestError("num_inference_steps must be >= 1")
        lat_h, lat_w = sp.height // ratio, sp.width // ratio
        grid_h, grid_w = lat_h // patch, lat_w // patch
        seq_len = grid_h * grid_w
        n_per = max(1, sp.num_images_per_prompt)
        prompts = [p for p in req.prompt for _ in range(n_per)]
        b = len(prompts)

        # Encode each unique prompt once, then repeat embeddings per image
        # (reference repeats post-encode too, pipeline_qwen_image.py).
        if req.prompt_embeds is not None:
            txt = jnp.asarray(req.prompt_embeds, self.dtype)
            txt_mask = jnp.ones(txt.shape[:2], jnp.int32)
        else:
            txt, txt_mask = self.encode_prompt(req.prompt)
        if n_per > 1:
            txt = jnp.repeat(txt, n_per, axis=0)
            txt_mask = jnp.repeat(txt_mask, n_per, axis=0)
        do_cfg = sp.guidance_scale > 1.0
        neg_txt = neg_mask = None
        if do_cfg:
            if req.negative_prompt_embeds is not None:
                neg_txt = jnp.asarray(req.negative_prompt_embeds, self.dtype)
                neg_mask = jnp.ones(neg_txt.shape[:2], jnp.int32)
            else:
                neg_txt, neg_mask = self.encode_prompt(
                    [sp.negative_prompt] * len(req.prompt)
                )
            if n_per > 1:
                neg_txt = jnp.repeat(neg_txt, n_per, axis=0)
                neg_mask = jnp.repeat(neg_mask, n_per, axis=0)

        # Unseeded requests sample a fresh seed (reference semantics: a
        # torch Generator is only seeded when the user provides one).
        seed = (
            sp.seed
            if sp.seed is not None
            else int(np.random.randint(0, 2**31 - 1))
        )
        noise = jax.random.normal(
            jax.random.PRNGKey(seed),
            (b, seq_len, cfg.dit.in_channels),
            jnp.float32,
        ).astype(self.dtype)

        mu = fm.compute_dynamic_shift_mu(seq_len)
        num_steps = sp.num_inference_steps
        schedule = fm.make_schedule(
            num_steps,
            shift=cfg.shift,
            use_dynamic_shifting=cfg.use_dynamic_shifting,
            mu=mu,
        )
        sched_len = max(num_steps, cfg.steps_bucket)
        sigmas = jnp.zeros((sched_len + 1,)).at[: num_steps + 1].set(
            schedule.sigmas
        )
        timesteps = jnp.zeros((sched_len,)).at[:num_steps].set(
            schedule.timesteps
        )
        run = self._denoise_fn(
            grid_h, grid_w, sched_len, batch2=(2 * b if do_cfg else b))
        latents, skipped_steps = run(
            self.dit_params,
            noise,
            txt,
            txt_mask,
            neg_txt,
            neg_mask,
            sigmas,
            timesteps,
            jnp.float32(sp.guidance_scale),
            jnp.int32(num_steps),
        )
        self.last_skipped_steps = int(skipped_steps)

        images = self._decode_latents(latents, grid_h, grid_w)
        images = np.asarray(images)
        outs = []
        for i, prompt in enumerate(prompts):
            rid = req.request_ids[i // n_per]
            if n_per > 1:
                rid = f"{rid}-{i % n_per}"
            outs.append(
                DiffusionOutput(
                    request_id=rid,
                    prompt=prompt,
                    data=images[i],
                    output_type="image",
                )
            )
        return outs

    @functools.cached_property
    def _decode_jit(self):
        @functools.partial(jax.jit, static_argnames=("grid_h", "grid_w"))
        def dec(vae_params, latents, grid_h, grid_w):
            cfg = self.cfg
            patch = cfg.dit.patch_size
            b = latents.shape[0]
            # unpack [B, gh*gw, p*p*C] -> [B, gh*p, gw*p, C]
            c = cfg.vae.latent_channels
            x = latents.reshape(b, grid_h, grid_w, patch, patch, c)
            x = x.transpose(0, 1, 3, 2, 4, 5).reshape(
                b, grid_h * patch, grid_w * patch, c
            )
            img = vae_mod.decode(vae_params, cfg.vae, x)
            img = jnp.clip((img.astype(jnp.float32) + 1.0) * 127.5, 0, 255)
            return img.astype(jnp.uint8)

        return dec

    def _decode_latents(self, latents, grid_h, grid_w):
        # DiT out_channels == vae latent channels; proj_out emits
        # patch^2 * C which equals in_channels when packing matches.
        return self._decode_jit(self.vae_params, latents, grid_h, grid_w)
