"""Qwen-Image text->image pipeline (TPU-native).

Role of the reference's ``QwenImagePipeline``
(vllm_omni/diffusion/models/qwen_image/pipeline_qwen_image.py:241,539-722):
encode_prompt (text-encoder hidden states) -> prepare latents/timesteps
(FlowMatch) -> denoise loop (CFG + MMDiT) -> VAE decode.

TPU-first: the whole denoise loop is ONE jitted computation
(lax.fori_loop over steps — no per-step Python dispatch, no CUDA-graph
machinery); CFG runs as a doubled batch (or, distributed, over the ``cfg``
mesh axis); shapes are static per (H, W) geometry — the step count is a
dynamic loop bound over a padded schedule, so XLA caches one executable
per resolution regardless of num_inference_steps.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.diffusion import cache as step_cache
from vllm_omni_tpu.diffusion import scheduler as fm
from vllm_omni_tpu.diffusion.request import (
    DiffusionOutput,
    OmniDiffusionRequest,
)
from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.models.common.transformer import (
    TransformerConfig,
    forward_hidden,
    init_params as init_text_params,
)
from vllm_omni_tpu.models.qwen_image import transformer as dit
from vllm_omni_tpu.models.qwen_image import vae as vae_mod
from vllm_omni_tpu.models.qwen_image.transformer import QwenImageDiTConfig
from vllm_omni_tpu.models.qwen_image.vae import VAEConfig
from vllm_omni_tpu.utils.tokenizer import ByteTokenizer

logger = init_logger(__name__)


@dataclass(frozen=True)
class QwenImagePipelineConfig:
    dit: QwenImageDiTConfig = field(default_factory=QwenImageDiTConfig)
    vae: VAEConfig = field(default_factory=VAEConfig)
    text: TransformerConfig = field(default_factory=TransformerConfig)
    max_text_len: int = 128
    shift: float = 1.0
    use_dynamic_shifting: bool = True
    # Schedule arrays are padded to this bucket so the step count is a
    # *dynamic* fori_loop bound: XLA compiles one executable per (H, W)
    # geometry, not per step count, and a 1-step warmup warms the same
    # executable that 50-step requests hit.
    steps_bucket: int = 64

    @staticmethod
    def tiny() -> "QwenImagePipelineConfig":
        return QwenImagePipelineConfig(
            dit=QwenImageDiTConfig.tiny(),
            vae=VAEConfig.tiny(),
            text=TransformerConfig.tiny(vocab_size=512),
            max_text_len=32,
        )

    @staticmethod
    def bench() -> "QwenImagePipelineConfig":
        """Single-chip bench scale (fits one v5e with bf16 weights)."""
        return QwenImagePipelineConfig(
            dit=QwenImageDiTConfig(
                num_layers=16, num_heads=16, head_dim=128, joint_dim=1024
            ),
            vae=VAEConfig(base_channels=64),
            text=TransformerConfig(
                vocab_size=512,
                hidden_size=1024,
                num_layers=8,
                num_heads=8,
                num_kv_heads=4,
                head_dim=128,
                intermediate_size=2816,
            ),
        )


class QwenImagePipeline:
    """Text -> image. Weights are random-initialized unless a checkpoint
    is provided (weight loading lands with the safetensors loader)."""

    output_type = "image"

    def __init__(
        self,
        config: QwenImagePipelineConfig,
        dtype=jnp.bfloat16,
        seed: int = 0,
        mesh=None,
        cache_config=None,  # StepCacheConfig | None (step-skip acceleration)
    ):
        self.cfg = config
        self.dtype = dtype
        self.mesh = mesh
        self.cache_config = cache_config
        if config.text.hidden_size != config.dit.joint_dim:
            raise ValueError(
                "text hidden_size must equal dit joint_dim "
                f"({config.text.hidden_size} != {config.dit.joint_dim})"
            )
        self.tokenizer = ByteTokenizer(config.text.vocab_size)
        key = jax.random.PRNGKey(seed)
        k1, k2, k3 = jax.random.split(key, 3)
        logger.info("Initializing QwenImagePipeline params (dtype=%s)", dtype)
        self.text_params = init_text_params(k1, config.text, dtype)
        self.dit_params = dit.init_params(k2, config.dit, dtype)
        self.vae_params = vae_mod.init_decoder(k3, config.vae, dtype)
        self._denoise_cache: dict = {}

    # ------------------------------------------------------------- encode
    def encode_prompt(self, prompts: list[str]):
        """Returns (hidden [B, S, joint_dim], mask [B, S])."""
        ids, lens = self.tokenizer.batch_encode(prompts, self.cfg.max_text_len)
        hidden = self._encode_jit(jnp.asarray(ids))
        mask = (
            np.arange(self.cfg.max_text_len)[None, :] < lens[:, None]
        ).astype(np.int32)
        return hidden, jnp.asarray(mask)

    @functools.cached_property
    def _encode_jit(self):
        return jax.jit(
            lambda ids: forward_hidden(self.text_params, self.cfg.text, ids)
        )

    # ------------------------------------------------------------ denoise
    def _denoise_fn(self, grid_h: int, grid_w: int, sched_len: int):
        key = (grid_h, grid_w, sched_len)
        if key in self._denoise_cache:
            return self._denoise_cache[key]

        cfg = self.cfg

        @jax.jit
        def run(
            dit_params, latents, txt, txt_mask, neg_txt, neg_mask,
            sigmas, timesteps, gscale, num_steps,
        ):
            # latents: [B, S_img, C_in]; txt/neg_txt: [B, S_txt, joint];
            # sigmas/timesteps padded to sched_len(+1); num_steps is a
            # traced scalar — the loop bound is dynamic, the shapes static.
            schedule = fm.FlowMatchSchedule(sigmas=sigmas, timesteps=timesteps)
            do_cfg = neg_txt is not None
            txt_all = (
                jnp.concatenate([txt, neg_txt], axis=0) if do_cfg else txt
            )
            mask_all = (
                jnp.concatenate([txt_mask, neg_mask], axis=0)
                if do_cfg
                else txt_mask
            )

            def eval_velocity(lat, i):
                t = jnp.broadcast_to(timesteps[i], (lat.shape[0],))
                lat_in = jnp.concatenate([lat, lat], 0) if do_cfg else lat
                t_in = jnp.concatenate([t, t], 0) if do_cfg else t
                v = dit.forward(
                    dit_params, cfg.dit, lat_in, txt_all, t_in,
                    (grid_h, grid_w), txt_mask=mask_all,
                )
                if do_cfg:
                    v_pos, v_neg = jnp.split(v, 2, axis=0)
                    v = v_neg + gscale * (v_pos - v_neg)
                return v

            return step_cache.run_denoise_loop(
                self.cache_config, schedule, eval_velocity, latents,
                num_steps,
            )

        self._denoise_cache[key] = run
        return run

    # ----------------------------------------------------------- generate
    def forward(self, req: OmniDiffusionRequest) -> list[DiffusionOutput]:
        sp = req.sampling_params
        cfg = self.cfg
        ratio = cfg.vae.spatial_ratio
        patch = cfg.dit.patch_size
        mult = ratio * patch
        if sp.height % mult or sp.width % mult:
            raise ValueError(
                f"height/width must be multiples of {mult} "
                f"(vae ratio {ratio} x patch {patch}); got "
                f"{sp.height}x{sp.width}"
            )
        if sp.num_inference_steps < 1:
            raise ValueError("num_inference_steps must be >= 1")
        lat_h, lat_w = sp.height // ratio, sp.width // ratio
        grid_h, grid_w = lat_h // patch, lat_w // patch
        seq_len = grid_h * grid_w
        n_per = max(1, sp.num_images_per_prompt)
        prompts = [p for p in req.prompt for _ in range(n_per)]
        b = len(prompts)

        # Encode each unique prompt once, then repeat embeddings per image
        # (reference repeats post-encode too, pipeline_qwen_image.py).
        if req.prompt_embeds is not None:
            txt = jnp.asarray(req.prompt_embeds, self.dtype)
            txt_mask = jnp.ones(txt.shape[:2], jnp.int32)
        else:
            txt, txt_mask = self.encode_prompt(req.prompt)
        if n_per > 1:
            txt = jnp.repeat(txt, n_per, axis=0)
            txt_mask = jnp.repeat(txt_mask, n_per, axis=0)
        do_cfg = sp.guidance_scale > 1.0
        neg_txt = neg_mask = None
        if do_cfg:
            if req.negative_prompt_embeds is not None:
                neg_txt = jnp.asarray(req.negative_prompt_embeds, self.dtype)
                neg_mask = jnp.ones(neg_txt.shape[:2], jnp.int32)
            else:
                neg_txt, neg_mask = self.encode_prompt(
                    [sp.negative_prompt] * len(req.prompt)
                )
            if n_per > 1:
                neg_txt = jnp.repeat(neg_txt, n_per, axis=0)
                neg_mask = jnp.repeat(neg_mask, n_per, axis=0)

        # Unseeded requests sample a fresh seed (reference semantics: a
        # torch Generator is only seeded when the user provides one).
        seed = (
            sp.seed
            if sp.seed is not None
            else int(np.random.randint(0, 2**31 - 1))
        )
        noise = jax.random.normal(
            jax.random.PRNGKey(seed),
            (b, seq_len, cfg.dit.in_channels),
            jnp.float32,
        ).astype(self.dtype)

        mu = fm.compute_dynamic_shift_mu(seq_len)
        num_steps = sp.num_inference_steps
        schedule = fm.make_schedule(
            num_steps,
            shift=cfg.shift,
            use_dynamic_shifting=cfg.use_dynamic_shifting,
            mu=mu,
        )
        sched_len = max(num_steps, cfg.steps_bucket)
        sigmas = jnp.zeros((sched_len + 1,)).at[: num_steps + 1].set(
            schedule.sigmas
        )
        timesteps = jnp.zeros((sched_len,)).at[:num_steps].set(
            schedule.timesteps
        )
        run = self._denoise_fn(grid_h, grid_w, sched_len)
        latents, skipped_steps = run(
            self.dit_params,
            noise,
            txt,
            txt_mask,
            neg_txt,
            neg_mask,
            sigmas,
            timesteps,
            jnp.float32(sp.guidance_scale),
            jnp.int32(num_steps),
        )
        self.last_skipped_steps = int(skipped_steps)

        images = self._decode_latents(latents, grid_h, grid_w)
        images = np.asarray(images)
        outs = []
        for i, prompt in enumerate(prompts):
            rid = req.request_ids[i // n_per]
            if n_per > 1:
                rid = f"{rid}-{i % n_per}"
            outs.append(
                DiffusionOutput(
                    request_id=rid,
                    prompt=prompt,
                    data=images[i],
                    output_type="image",
                )
            )
        return outs

    @functools.cached_property
    def _decode_jit(self):
        @functools.partial(jax.jit, static_argnames=("grid_h", "grid_w"))
        def dec(vae_params, latents, grid_h, grid_w):
            cfg = self.cfg
            patch = cfg.dit.patch_size
            b = latents.shape[0]
            # unpack [B, gh*gw, p*p*C] -> [B, gh*p, gw*p, C]
            c = cfg.vae.latent_channels
            x = latents.reshape(b, grid_h, grid_w, patch, patch, c)
            x = x.transpose(0, 1, 3, 2, 4, 5).reshape(
                b, grid_h * patch, grid_w * patch, c
            )
            img = vae_mod.decode(vae_params, cfg.vae, x)
            img = jnp.clip((img.astype(jnp.float32) + 1.0) * 127.5, 0, 255)
            return img.astype(jnp.uint8)

        return dec

    def _decode_latents(self, latents, grid_h, grid_w):
        # DiT out_channels == vae latent channels; proj_out emits
        # patch^2 * C which equals in_channels when packing matches.
        return self._decode_jit(self.vae_params, latents, grid_h, grid_w)
