"""Qwen-Image text->image pipeline (TPU-native).

Role of the reference's ``QwenImagePipeline``
(vllm_omni/diffusion/models/qwen_image/pipeline_qwen_image.py:241,539-722):
encode_prompt (text-encoder hidden states) -> prepare latents/timesteps
(FlowMatch) -> denoise loop (CFG + MMDiT) -> VAE decode.

TPU-first: the whole denoise loop is ONE jitted computation
(lax.fori_loop over steps — no per-step Python dispatch, no CUDA-graph
machinery); CFG runs as a doubled batch (or, distributed, over the ``cfg``
mesh axis); shapes are static per (H, W) geometry — the step count is a
dynamic loop bound over a padded schedule, so XLA caches one executable
per resolution regardless of num_inference_steps.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.diffusion import cache as step_cache
from vllm_omni_tpu.diffusion import scheduler as fm
from vllm_omni_tpu.diffusion.request import (
    DiffusionOutput,
    InvalidRequestError,
    OmniDiffusionRequest,
)
from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.models.common import causal_vae as vae_mod
from vllm_omni_tpu.models.common.causal_vae import CausalVAEConfig
from vllm_omni_tpu.models.common.transformer import (
    TransformerConfig,
    forward_hidden,
    init_params as init_text_params,
)
from vllm_omni_tpu.models.qwen_image import transformer as dit
from vllm_omni_tpu.models.qwen_image.transformer import QwenImageDiTConfig
from vllm_omni_tpu.utils.tokenizer import ByteTokenizer

logger = init_logger(__name__)


@dataclass(frozen=True)
class QwenImagePipelineConfig:
    dit: QwenImageDiTConfig = field(default_factory=QwenImageDiTConfig)
    vae: CausalVAEConfig = field(
        default_factory=CausalVAEConfig.qwen_image)
    text: TransformerConfig = field(default_factory=TransformerConfig)
    max_text_len: int = 128
    shift: float = 1.0
    use_dynamic_shifting: bool = True
    # "euler" | "unipc" (order-2 multistep, diffusion/scheduler.py)
    scheduler: str = "euler"
    # Schedule arrays are padded to this bucket so the step count is a
    # *dynamic* fori_loop bound: XLA compiles one executable per (H, W)
    # geometry, not per step count, and a 1-step warmup warms the same
    # executable that 50-step requests hit.
    steps_bucket: int = 64

    @staticmethod
    def tiny() -> "QwenImagePipelineConfig":
        return QwenImagePipelineConfig(
            dit=QwenImageDiTConfig.tiny(),
            vae=CausalVAEConfig.tiny(),
            text=TransformerConfig.tiny(vocab_size=512),
            max_text_len=32,
        )

    @staticmethod
    def bench() -> "QwenImagePipelineConfig":
        """Single-chip bench scale (fits one v5e with bf16 weights)."""
        return QwenImagePipelineConfig(
            dit=QwenImageDiTConfig(
                num_layers=16, num_heads=16, head_dim=128, joint_dim=1024
            ),
            vae=CausalVAEConfig(base_dim=64),
            text=TransformerConfig(
                vocab_size=512,
                hidden_size=1024,
                num_layers=8,
                num_heads=8,
                num_kv_heads=4,
                head_dim=128,
                intermediate_size=2816,
            ),
        )

    @staticmethod
    def resident() -> "QwenImagePipelineConfig":
        """Real Qwen-Image BLOCK geometry (joint 3584 / 24 heads / the
        MXU shapes that set the perf ceiling) with the layer count
        auto-sized to what fits the attached chip's HBM resident in
        bf16 — 60 (the full model) on large-HBM parts, ~18 on a 16 GB
        v5e.  The honest single-chip bench preset: per-layer timing is
        identical to the full model, only the layer count is reduced
        (and reported).  The text encoder keeps the real 3584 width
        (joint-attention parity) at a reduced depth — text encode is a
        one-shot cost outside the denoise loop."""
        import dataclasses

        one_layer = jax.eval_shape(lambda: dit.init_params(
            jax.random.PRNGKey(0),
            dataclasses.replace(QwenImageDiTConfig(), num_layers=1),
            jnp.bfloat16))
        per_block_bytes = 2 * sum(
            int(np.prod(leaf.shape))
            for leaf in jax.tree.leaves(one_layer["blocks"]))
        try:
            from vllm_omni_tpu.platforms import current_platform

            hbm = current_platform().hbm_bytes() or 16e9
        except Exception:
            hbm = 16e9
        # reserve for activations @1024px, VAE (fp32), the text stack,
        # and compiled-executable scratch
        budget = max(hbm - 5e9, per_block_bytes * 2)
        layers = int(min(60, max(2, budget // per_block_bytes)))
        return QwenImagePipelineConfig(
            dit=QwenImageDiTConfig(num_layers=layers),
            vae=CausalVAEConfig.qwen_image(),
            text=TransformerConfig(
                vocab_size=512,
                hidden_size=3584,
                num_layers=4,
                num_heads=28,
                num_kv_heads=4,
                head_dim=128,
                intermediate_size=18944,
            ),
        )

    @staticmethod
    def real_q() -> "QwenImagePipelineConfig":
        """Real Qwen-Image DiT geometry (full 60 layers / 24 heads /
        3584 — the 20.4B-param transformer that sets the headline
        number) with the ``resident()`` lite text stack (real 3584
        width at reduced depth; text encode is a one-shot cost outside
        the denoise loop).  Built with ``quantize_init='int4'`` the DiT
        packs to 10.3 GB and the FULL depth sits resident in one 16 GB
        chip's HBM — the honest single-chip route to a measured (not
        extrapolated) 60-layer number when host->HBM bandwidth can't
        sustain layerwise streaming."""
        return QwenImagePipelineConfig(
            dit=QwenImageDiTConfig(),
            vae=CausalVAEConfig.qwen_image(),
            text=TransformerConfig(
                vocab_size=512,
                hidden_size=3584,
                num_layers=4,
                num_heads=28,
                num_kv_heads=4,
                head_dim=128,
                intermediate_size=18944,
            ),
        )

    @staticmethod
    def real() -> "QwenImagePipelineConfig":
        """The REAL Qwen-Image geometry (reference:
        transformer config.json — 60 layers / 24 heads / joint 3584;
        Qwen2.5-VL-7B text encoder; 8x causal VAE).  20.4B-param DiT:
        doesn't fit one v5e chip resident — run with TP over a mesh or
        layerwise weight streaming (``ops/offload.py``)."""
        return QwenImagePipelineConfig(
            dit=QwenImageDiTConfig(),
            vae=CausalVAEConfig.qwen_image(),
            text=TransformerConfig(
                vocab_size=152064,
                hidden_size=3584,
                num_layers=28,
                num_heads=28,
                num_kv_heads=4,
                head_dim=128,
                intermediate_size=18944,
            ),
            max_text_len=512,
            use_dynamic_shifting=True,
        )


# Text-encoder chat template + drop index for Qwen-Image (reference:
# pipeline_qwen_image.py:293-294 — the first 34 tokens are the fixed
# system/user preamble and are dropped from the embeddings).
PROMPT_TEMPLATE = (
    "<|im_start|>system\nDescribe the image by detailing the color, shape, "
    "size, texture, quantity, text, spatial relationships of the objects "
    "and background:<|im_end|>\n<|im_start|>user\n{}<|im_end|>\n"
    "<|im_start|>assistant\n"
)
PROMPT_TEMPLATE_DROP_IDX = 34


@jax.jit
def _rel_drift(lat, prev):
    """TeaCache drift gate for the streamed (host-loop) denoise: one
    fused scalar => ONE host sync per step (module-level jit so the
    executable compiles once per process, not once per image)."""
    diff = jnp.mean(jnp.abs(lat.astype(jnp.float32) - prev))
    base = jnp.mean(jnp.abs(prev))
    return diff / jnp.maximum(base, 1e-8)


class QwenImagePipeline:
    """Text -> image.  Weights are random-initialized from the config, or
    loaded from a diffusers-format checkpoint via ``from_pretrained``."""

    output_type = "image"
    # Edit pipelines condition on VAE-encoded input images, so their VAE
    # keeps the encoder half.
    needs_vae_encoder = False

    def __init__(
        self,
        config: QwenImagePipelineConfig,
        dtype=jnp.bfloat16,
        seed: int = 0,
        mesh=None,
        cache_config=None,  # StepCacheConfig | None (step-skip acceleration)
        init_weights: bool = True,
        offload: str = "",  # "" | "layerwise" (weights stream from host)
        quantize_init: str = "",  # "" | "int8" | "fp8" | "int4"
        step_loop: str = "device",  # "device" (fori_loop) | "host"
        step_chunk: int = 1,  # denoise steps per device call (host loop)
    ):
        from vllm_omni_tpu.parallel.pipeline_mesh import MeshWiring

        self.cfg = config
        self.dtype = dtype
        self.mesh = mesh
        self.wiring = MeshWiring(mesh, type(self).__name__).validate(
            {"dp", "cfg", "ring", "ulysses", "tp", "pp"})
        if self.wiring.size("pp") > 1 and len(self.wiring.active) > 1:
            raise ValueError(
                "pp composes with no other axis yet — rebuild the mesh "
                f"with pp alone (active: {sorted(self.wiring.active)})")
        if (self.wiring.size("pp") > 1 and cache_config is not None
                and getattr(cache_config, "backend", "") == "dbcache"):
            raise ValueError(
                "dbcache is not wired into the pp denoise path yet — "
                "use teacache or pp without a step cache")
        self.cache_config = cache_config
        self.offload = offload
        if offload not in ("", "layerwise"):
            raise ValueError(f"unknown offload mode {offload!r}")
        self.step_loop = step_loop
        self.step_chunk = int(step_chunk)
        if step_loop not in ("device", "host"):
            raise ValueError(f"unknown step_loop mode {step_loop!r}")
        if self.step_chunk < 1:
            raise ValueError(f"step_chunk must be >=1, got {step_chunk}")
        if step_loop == "host":
            # A CHUNK of jitted denoise steps per device call instead of
            # the whole loop in one call: a 60-layer 50-step execution
            # runs minutes in a single RPC, which remote-attached TPUs
            # (tunnel transports) can kill mid-flight; chunked calls
            # (seconds each) stay under any per-call ceiling while
            # amortizing the per-RPC round trip over step_chunk steps.
            # Same executable for every chunk size — num_steps is a
            # traced scalar, the schedule is rolled to the chunk start.
            if mesh is not None:
                raise ValueError("step_loop='host' is single-device")
            if offload == "layerwise":
                raise ValueError(
                    "layerwise offload already drives a host loop")
            # step caches DO work here: the cache carry (skip state,
            # Taylor anchors, drift accumulator) threads through each
            # chunked device call explicitly (cache.run_denoise_loop
            # carry_in/return_carry), with cache decisions indexed by
            # the GLOBAL step — identical skips to one uninterrupted
            # device loop.
            if config.scheduler != "euler":
                raise ValueError(
                    "step_loop='host' supports the euler solver only "
                    "(multistep solvers carry state across the calls)")
        if offload == "layerwise":
            # Streaming drives a Python block loop on ONE device; the
            # multi-chip answer to big models is TP over a mesh instead.
            if mesh is not None:
                raise ValueError("layerwise offload is single-device; "
                                 "use mesh TP for multi-chip")
            if cache_config is not None and cache_config.backend not in (
                    "", "teacache"):
                # teacache's whole-model skip maps cleanly onto the host
                # block-walk (a skipped step saves the full weight
                # transfer); dbcache's split eval does not
                raise ValueError("layerwise offload supports the "
                                 "teacache step cache only")
            if config.scheduler != "euler":
                raise ValueError("layerwise offload supports the euler "
                                 "solver only")
        if config.text.hidden_size != config.dit.joint_dim:
            raise ValueError(
                "text hidden_size must equal dit joint_dim "
                f"({config.text.hidden_size} != {config.dit.joint_dim})"
            )
        self.tokenizer = ByteTokenizer(config.text.vocab_size)
        key = jax.random.PRNGKey(seed)
        k1, k2, k3 = jax.random.split(key, 3)
        # Decoder-only VAE for text->image (edit pipelines add the
        # encoder); fp32 regardless of model dtype — the 127M-param VAE
        # is not the memory story and bf16 visibly banding-artifacts the
        # decoded image.  DiT/text skip init when a checkpoint will
        # overwrite them (init_weights=False avoids materializing +
        # placing tens of GB of randoms only to discard them).
        self.vae_params = self._place(vae_mod.init_params(
            k3, config.vae, jnp.float32, encoder=self.needs_vae_encoder))
        if init_weights and offload == "layerwise":
            from vllm_omni_tpu.diffusion import offload as ol

            logger.info("Host-init for layerwise streaming (dtype=%s)",
                        dtype)
            # repeated blocks alias a few distinct host buffers: the
            # streamed transfer volume is identical, and materializing
            # 50+ GB of distinct randoms first-touch-faults for minutes
            # on sandboxed hosts (real checkpoints take the loader path)
            self.text_params = ol.host_tiled_init_aliased(
                jax.eval_shape(
                    lambda: init_text_params(k1, config.text, dtype)),
                dtype, block_key="layers", seed=seed + 1)
            self.dit_params = ol.host_tiled_init_aliased(
                jax.eval_shape(
                    lambda: dit.init_params(k2, config.dit, dtype)),
                dtype, block_key="blocks", seed=seed + 2)
        elif init_weights and quantize_init:
            # Quantize each DiT block as it is initialized: peak device
            # memory is the quantized tree plus ONE transient bf16 block,
            # so a model whose float tree exceeds HBM (real Qwen-Image:
            # 41 GB bf16 vs 16 GB v5e) still builds quantized-resident
            # (int4 -> 10.3 GB).  Mesh placement would need sharded
            # per-block quantization — single-device only for now.
            if mesh is not None:
                raise ValueError(
                    "quantize_init is single-device; quantize after "
                    "sharded init (engine post-hoc path) instead")
            logger.info(
                "Initializing QwenImagePipeline params (dtype=%s, "
                "blockwise %s quantization)", dtype, quantize_init)
            self.text_params = self._place(
                init_text_params(k1, config.text, dtype))
            self.dit_params = self._init_dit_quantized(
                k2, quantize_init)
        elif init_weights:
            logger.info(
                "Initializing QwenImagePipeline params (dtype=%s)", dtype)
            self.text_params = self._place(
                init_text_params(k1, config.text, dtype))
            self.dit_params = self._place(
                dit.init_params(k2, config.dit, dtype), tp=True)
        else:
            self.text_params = self.dit_params = None
        self._denoise_cache: dict = {}
        # HF text-encode mode (from_pretrained): chat template + drop_idx
        self.hf_tokenizer = None

    def _place(self, params, tp: bool = False):
        """Put a param tree on the mesh: TP layout for the DiT, replicated
        otherwise (reference: SP plan application at model init,
        diffusion/registry.py:122-294).  Without a mesh, commit to the
        default device once — leaving loader numpy trees uncommitted would
        re-transfer the weights on every jit call.

        Under pipeline parallelism the DiT blocks restack onto a leading
        layer axis sharded over ``pp`` (each rank holds L/pp blocks —
        parallel/pp.py)."""
        if self.mesh is None:
            return jax.device_put(params)
        if tp and self.wiring.size("pp") > 1:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from vllm_omni_tpu.parallel import pp as pp_mod

            n_blocks = len(params["blocks"])
            pp = self.wiring.size("pp")
            if n_blocks % pp:
                raise ValueError(
                    f"num_layers={n_blocks} must divide pp={pp}")
            stacked = pp_mod.stack_blocks(params["blocks"])
            top = {k: v for k, v in params.items() if k != "blocks"}
            rep = NamedSharding(self.mesh, P())
            return {
                **jax.device_put(top, rep),
                "blocks_stacked": jax.tree.map(
                    lambda x: jax.device_put(
                        x, NamedSharding(self.mesh, P("pp"))),
                    stacked),
            }
        from vllm_omni_tpu.parallel.sharding import (
            replicated,
            shard_dit_params,
        )

        if tp:
            return shard_dit_params(params, self.mesh)
        return jax.device_put(params, replicated(self.mesh))

    def _init_dit_quantized(self, key, mode: str):
        """Init + quantize the DiT one block at a time on device,
        emitting blocks STACKED on a leading layer axis (the lax.scan
        layout ``dit.forward`` walks).

        Uses ``init_params``' exact key schedule (split L+8; top from
        keys[:6], block i from keys[i+8]) so the result is a
        QUANTIZATION OF THE SAME random model a dense build produces —
        dense-vs-quantized closeness tests stay meaningful.  The init is
        a scan whose body is (init one bf16 block -> quantize): the bf16
        weights exist only as a ~0.7 GB transient inside one scan
        iteration, and the scan's stacked output buffer is allocated
        once at the quantized size.  This is how the real 60-layer
        geometry (41 GB bf16) builds on a 16 GB chip."""
        from vllm_omni_tpu.diffusion.quantization import quantize_params

        cfg_d = self.cfg.dit
        dtype = self.dtype

        @jax.jit
        def q_top(ks):
            return quantize_params(dit.init_top(ks, cfg_d, dtype=dtype),
                                   mode=mode)

        @jax.jit
        def q_blocks(ks):
            def body(carry, k):
                blk = dit.init_block(k, cfg_d, dtype=dtype)
                return carry, quantize_params(blk, mode=mode)

            _, stacked = jax.lax.scan(body, None, ks)
            return stacked

        keys = jax.random.split(key, cfg_d.num_layers + 8)
        out = q_top(keys[:8])
        out["blocks_stacked"] = q_blocks(keys[8:])
        return out

    @classmethod
    def from_pretrained(
        cls,
        model_dir: str,
        dtype=jnp.bfloat16,
        seed: int = 0,
        mesh=None,
        cache_config=None,
        max_text_len: int = 512,
        offload: str = "",
    ) -> "QwenImagePipeline":
        """Build from a diffusers-format checkpoint directory (reference:
        DiffusersPipelineLoader, diffusion/model_loader/diffusers_loader.py
        + pipeline component resolution, omni_diffusion.py:34-109).

        Loads the DiT, the Qwen2.5-VL-style text encoder, and the causal
        VAE with real weights, plus the HF tokenizer and the FlowMatch
        scheduler shift config.
        """
        import os

        from vllm_omni_tpu.model_loader import diffusers_loader as dl

        dl.load_model_index(model_dir)  # validates layout
        dit_params, dit_cfg = dl.load_qwen_image_dit(
            os.path.join(model_dir, "transformer"), dtype=dtype
        )
        te_dir = os.path.join(model_dir, "text_encoder")
        text_params, text_cfg = dl.load_text_encoder(te_dir, dtype=dtype)
        vae_params, vae_cfg = dl.load_causal_vae(
            os.path.join(model_dir, "vae"), dtype=jnp.float32,
            encoder=cls.needs_vae_encoder,
        )
        sched = dl.scheduler_config(model_dir)
        config = QwenImagePipelineConfig(
            dit=dit_cfg,
            vae=vae_cfg,
            text=text_cfg,
            max_text_len=max_text_len,
            # defaults mirror diffusers FlowMatchEulerDiscreteScheduler
            # (and scheduler_config()'s own) so present-but-sparse and
            # absent scheduler configs behave identically
            shift=sched.get("shift", 1.0),
            use_dynamic_shifting=sched.get("use_dynamic_shifting", False),
        )
        pipe = cls(config, dtype=dtype, seed=seed, mesh=mesh,
                   cache_config=cache_config, init_weights=False,
                   offload=offload)
        if offload == "layerwise":
            # keep the loader's host numpy trees — blocks stream per use
            pipe.dit_params = dit_params
            pipe.text_params = text_params
        else:
            pipe.dit_params = pipe._place(dit_params, tp=True)
            pipe.text_params = pipe._place(text_params)
        pipe.vae_params = pipe._place(vae_params)
        tok_dir = os.path.join(model_dir, "tokenizer")
        if os.path.isdir(tok_dir):
            from transformers import AutoTokenizer

            pipe.hf_tokenizer = AutoTokenizer.from_pretrained(tok_dir)
            # the drop-34 preamble removal in _encode_prompt_hf is only
            # correct under right padding; some checkpoints ship
            # padding_side='left' in tokenizer_config.json
            pipe.hf_tokenizer.padding_side = "right"
        else:
            logger.warning("no tokenizer/ under %s; byte fallback",
                           model_dir)
        return pipe

    # ------------------------------------------------------------- encode
    def encode_prompt(self, prompts: list[str]):
        """Returns (hidden [B, S, joint_dim], mask [B, S])."""
        if self.hf_tokenizer is not None:
            return self._encode_prompt_hf(prompts)
        ids, lens = self.tokenizer.batch_encode(prompts, self.cfg.max_text_len)
        hidden = self._encode_jit(self.text_params, jnp.asarray(ids))
        mask = (
            np.arange(self.cfg.max_text_len)[None, :] < lens[:, None]
        ).astype(np.int32)
        return hidden, jnp.asarray(mask)

    def _encode_prompt_hf(self, prompts: list[str]):
        """Real-checkpoint text encoding: chat-template the prompt, take
        the final hidden states, and drop the fixed 34-token preamble
        (reference: _get_qwen_prompt_embeds, pipeline_qwen_image.py:366-399
        — with right padding, dropping the first `drop_idx` positions
        equals dropping the first drop_idx real tokens; we keep a static
        [B, max_text_len] shape and carry validity in the mask)."""
        drop = PROMPT_TEMPLATE_DROP_IDX
        txts = [PROMPT_TEMPLATE.format(p) for p in prompts]
        enc = self.hf_tokenizer(
            txts,
            max_length=self.cfg.max_text_len + drop,
            padding="max_length",
            truncation=True,
            return_tensors="np",
        )
        ids = np.asarray(enc["input_ids"], np.int32)
        mask = np.asarray(enc["attention_mask"], np.int32)
        hidden = self._encode_jit(self.text_params, jnp.asarray(ids))
        return (
            hidden[:, drop:].astype(self.dtype),
            jnp.asarray(mask[:, drop:]),
        )

    @functools.cached_property
    def _encode_jit(self):
        if self.offload == "layerwise":
            return lambda p, ids: self._stream_encode_hidden(ids)
        # params are an explicit jit ARGUMENT: closure capture would bake
        # them into the executable as constants, so sleep() couldn't free
        # the buffers and weight swaps would silently not apply
        return jax.jit(
            lambda p, ids: forward_hidden(p, self.cfg.text, ids)
        )

    # ---------------------------------------------- layerwise streaming
    @functools.cached_property
    def _text_stream(self):
        from vllm_omni_tpu.diffusion import offload as ol

        top, layers = ol.split_host_blocks(self.text_params, "layers")
        return jax.device_put(top), layers

    @functools.cached_property
    def _dit_stream(self):
        from vllm_omni_tpu.diffusion import offload as ol

        top, blocks = ol.split_host_blocks(self.dit_params, "blocks")
        return jax.device_put(top), blocks

    @functools.cached_property
    def _dit_streamer(self):
        """Persistent streamer with as many blocks pinned resident in HBM
        as fit beyond activations + double buffer — pinned blocks are
        transferred once per pipeline, not once per step, cutting the
        transfer-bound step time proportionally."""
        from vllm_omni_tpu.diffusion.offload import BlockStreamer

        _, blocks = self._dit_stream
        return BlockStreamer(blocks,
                             pinned=BlockStreamer.auto_pin(blocks))

    @functools.cached_property
    def _stream_text_jits(self):
        from vllm_omni_tpu.models.common import nn as cnn
        from vllm_omni_tpu.models.common import transformer as tfm
        from vllm_omni_tpu.ops import flash_attention, rms_norm

        tcfg = self.cfg.text

        @jax.jit
        def prefix(top, ids):
            b, s = ids.shape
            x = cnn.embedding(top["embed"], ids)
            # text-only positions: 1-D, or equal-stream [B, 3, S] when
            # the encoder config carries mrope sections (Qwen2.5-VL
            # checkpoints do — equal streams are numerically 1-D rope)
            shape = (b, s) if tcfg.mrope_sections is None else (b, 3, s)
            positions = jnp.broadcast_to(jnp.arange(s)[None, :], shape)
            cos, sin = tfm._rope_tables(tcfg, positions)
            return x, cos, sin

        @jax.jit
        def layer(lp, x, cos, sin):
            b, s, _ = x.shape

            def attend(q, k, v):
                return flash_attention(
                    q.reshape(b, s, tcfg.num_heads, tcfg.head_dim),
                    k.reshape(b, s, tcfg.num_kv_heads, tcfg.head_dim),
                    v.reshape(b, s, tcfg.num_kv_heads, tcfg.head_dim),
                    causal=True,
                )

            return tfm._layer_step(lp, tcfg, x, cos, sin, attend)

        @jax.jit
        def suffix(top, x):
            return rms_norm(x, top["final_norm"]["w"], tcfg.rms_eps)

        return prefix, layer, suffix

    def _stream_encode_hidden(self, ids: jax.Array) -> jax.Array:
        """Text-encoder forward with layer weights streamed from host —
        the 7B encoder's 15 GB of bf16 weights never need to be resident
        at once."""
        import time as _time

        from vllm_omni_tpu.diffusion.offload import BlockStreamer

        t0 = _time.perf_counter()
        prefix, layer, suffix = self._stream_text_jits
        top, layers = self._text_stream
        x, cos, sin = prefix(top, jnp.asarray(ids))
        x = BlockStreamer(layers).run(
            lambda lp, c: layer(lp, c, cos, sin), x)
        out = suffix(top, x)
        jax.block_until_ready(out)
        logger.info("streamed text encode: %.1fs (%d layers)",
                    _time.perf_counter() - t0, len(layers))
        return out

    @functools.cached_property
    def _stream_dit_jits(self):
        from vllm_omni_tpu.models.common import nn as cnn
        from vllm_omni_tpu.ops import rms_norm

        cfg = self.cfg

        @functools.partial(jax.jit, static_argnames=("grid_h", "grid_w"))
        def prefix(top, latents, txt_states, txt_mask, t, grid_h, grid_w):
            img = cnn.linear(top["img_in"], latents)
            txt = rms_norm(txt_states, top["txt_norm"]["w"])
            txt = cnn.linear(top["txt_in"], txt)
            temb = cnn.timestep_embedding(t, 256)
            temb = cnn.linear(
                top["time_in2"],
                jax.nn.silu(cnn.linear(top["time_in1"],
                                       temb.astype(img.dtype))))
            temb_act = jax.nn.silu(temb)
            img_freqs, txt_freqs = dit.rope_freqs(
                cfg.dit, grid_h, grid_w, txt_states.shape[1])
            kv_mask = jnp.concatenate(
                [txt_mask.astype(jnp.int32),
                 jnp.ones((img.shape[0], img.shape[1]), jnp.int32)],
                axis=1,
            )
            return img, txt, temb_act, img_freqs, txt_freqs, kv_mask

        @jax.jit
        def block(blk, img, txt, temb_act, img_freqs, txt_freqs, kv_mask):
            return dit.block_forward(
                blk, cfg.dit, img, txt, temb_act, img_freqs, txt_freqs,
                None, kv_mask)

        @jax.jit
        def suffix(top, img, temb_act):
            mod = cnn.linear(top["norm_out_mod"], temb_act)
            scale, shift = jnp.split(mod, 2, axis=-1)
            img = (cnn.layernorm({}, img) * (1.0 + scale[:, None, :])
                   + shift[:, None, :])
            return cnn.linear(top["proj_out"], img)

        @functools.partial(jax.jit, static_argnames=("do_cfg",))
        def sched_step(latents, v, sigmas, i, gscale, do_cfg):
            if do_cfg:
                v_pos, v_neg = jnp.split(v, 2, axis=0)
                v = v_neg + gscale * (v_pos - v_neg)
            dt = sigmas[i + 1] - sigmas[i]
            return (latents.astype(jnp.float32)
                    + dt * v.astype(jnp.float32)).astype(latents.dtype)

        return prefix, block, suffix, sched_step

    def _stream_denoise(self, latents, txt_all, mask_all, sigmas,
                        timesteps, gscale, num_steps, grid_h, grid_w,
                        do_cfg):
        """Python-driven denoise loop with DiT block weights streamed
        from host per step (one jitted executable per piece; the 60-block
        walk transfers 41 GB/step for the real geometry, overlapped with
        compute by the BlockStreamer lookahead; blocks that fit HBM stay
        pinned resident across steps).

        TeaCache rides the host loop: the lax.cond gate of the jitted
        path (diffusion/cache.py:cached_eval) becomes a Python branch —
        a skipped step here saves not just the DiT FLOPs but the whole
        per-step weight transfer, which is what the streamed walk is
        bound by."""
        import time as _time

        prefix, block, suffix, sched_step = self._stream_dit_jits
        top, _ = self._dit_stream
        streamer = self._dit_streamer
        sigmas = jnp.asarray(sigmas)
        gscale = jnp.float32(gscale)
        t_start = _time.perf_counter()
        cc = self.cache_config
        use_cache = cc is not None and cc.enabled
        prev_v = prev_lat = None
        accum = float("inf")
        n = int(num_steps)
        self.last_skipped_steps = 0
        scm = cc.scm_steps_mask if use_cache else None
        for i in range(n):
            if use_cache and prev_lat is not None:
                accum += float(_rel_drift(latents, prev_lat))
                in_window = (i >= cc.warmup_steps
                             and i < n - cc.tail_steps)
                # deterministic steps-cache-mask overrides the drift
                # gate when configured (same precedence as the jitted
                # path, diffusion/cache.py:cached_eval); steps beyond
                # the mask compute, matching _scm_mask_array's padding
                if scm is not None:
                    want_skip = i < len(scm) and not bool(scm[i])
                else:
                    want_skip = accum < cc.rel_l1_threshold
                if in_window and want_skip:
                    self.last_skipped_steps += 1
                    latents = sched_step(latents, prev_v, sigmas,
                                         jnp.int32(i), gscale,
                                         do_cfg=do_cfg)
                    continue
            lat_in = (jnp.concatenate([latents, latents], axis=0)
                      if do_cfg else latents)
            t = jnp.broadcast_to(timesteps[i], (lat_in.shape[0],))
            img, txt_i, temb_act, img_f, txt_f, kv_mask = prefix(
                top, lat_in, txt_all, mask_all, t,
                grid_h=grid_h, grid_w=grid_w)
            img, txt_i = streamer.run(
                lambda blk, c: block(blk, c[0], c[1], temb_act, img_f,
                                     txt_f, kv_mask),
                (img, txt_i))
            v = suffix(top, img, temb_act)
            if use_cache:
                prev_v = v
                prev_lat = latents.astype(jnp.float32)
                accum = 0.0
            latents = sched_step(latents, v, sigmas, jnp.int32(i), gscale,
                                 do_cfg=do_cfg)
            if i == 0:
                jax.block_until_ready(latents)
                logger.info("streamed denoise: first step %.1fs "
                            "(includes per-piece compiles)",
                            _time.perf_counter() - t_start)
        jax.block_until_ready(latents)
        n_run = n - self.last_skipped_steps
        self.last_stream_denoise_s = _time.perf_counter() - t_start
        logger.info(
            "streamed denoise: %d steps (%d run, %d cache-skipped) in "
            "%.1fs", n, n_run, self.last_skipped_steps,
            self.last_stream_denoise_s)
        return latents

    # ------------------------------------------------------------ denoise
    def _sp_attn_fn(self, n_heads: int, seq_len: int, batch2: int):
        """shard_map-wrapped joint USP attention for the DiT blocks, or
        None when the mesh/shape constraints don't allow the explicit SP
        path (GSPMD still partitions the dense fallback correctly).
        Shared wiring: parallel/pipeline_mesh.py."""
        return self.wiring.joint_attn_fn(n_heads, seq_len, batch2)

    def _denoise_fn(self, grid_h: int, grid_w: int, sched_len: int,
                    batch2: int = 0,
                    cond_grids: tuple[tuple[int, int], ...] = (),
                    frames: int = 1):
        # batch2 affects only the shard_map attn dispatch decision — keep
        # it out of the key on meshless pipelines (jit handles shapes).
        key = (grid_h, grid_w, sched_len, cond_grids, frames) + (
            (batch2,) if self.mesh is not None else ())
        if key in self._denoise_cache:
            return self._denoise_cache[key]

        cfg = self.cfg
        n_cond = sum(ch * cw for ch, cw in cond_grids)
        if self.wiring.size("pp") > 1:
            run = self._pp_denoise_fn(grid_h, grid_w, sched_len,
                                      cond_grids, frames)
            self._denoise_cache[key] = run
            return run
        attn_fn = self._sp_attn_fn(
            cfg.dit.num_heads, frames * grid_h * grid_w + n_cond, batch2)
        mesh = self.mesh
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            lat2_sharding = NamedSharding(
                mesh, P(("cfg", "dp"), ("ring", "ulysses"), None))
            txt2_sharding = NamedSharding(mesh, P(("cfg", "dp"), None, None))

        @jax.jit
        def run(
            dit_params, latents, txt, txt_mask, neg_txt, neg_mask,
            sigmas, timesteps, gscale, num_steps, cond=None,
            step_offset=None, total_steps=None, cache_carry=None,
        ):
            # latents: [B, S_img, C_in]; txt/neg_txt: [B, S_txt, joint];
            # sigmas/timesteps padded to sched_len(+1); num_steps is a
            # traced scalar — the loop bound is dynamic, the shapes static.
            schedule = fm.FlowMatchSchedule(sigmas=sigmas, timesteps=timesteps)
            do_cfg = neg_txt is not None
            txt_all = (
                jnp.concatenate([txt, neg_txt], axis=0) if do_cfg else txt
            )
            mask_all = (
                jnp.concatenate([txt_mask, neg_mask], axis=0)
                if do_cfg
                else txt_mask
            )
            if mesh is not None:
                # CFG parallel: the [positive; negative] halves of the
                # doubled batch ride the cfg axis (cfg outermost in the
                # batch spec), image sequence over the SP axes — GSPMD
                # inserts the cfg combine at the guidance step below.
                txt_all = jax.lax.with_sharding_constraint(
                    txt_all, txt2_sharding)

            def embed(lat, i):
                t = jnp.broadcast_to(timesteps[i], (lat.shape[0],))
                s_gen = lat.shape[1]
                # image edit: VAE-encoded condition tokens extend the
                # sequence; velocity is read off the generated tokens
                lat_model = (lat if cond is None
                             else jnp.concatenate([lat, cond], axis=1))
                lat_in = (jnp.concatenate([lat_model, lat_model], 0)
                          if do_cfg else lat_model)
                t_in = jnp.concatenate([t, t], 0) if do_cfg else t
                if mesh is not None:
                    lat_in = jax.lax.with_sharding_constraint(
                        lat_in, lat2_sharding)
                return s_gen, lat_in, t_in

            def finish(img, temb_act, s_gen):
                v = dit.forward_suffix(dit_params, img,
                                       temb_act)[:, :s_gen]
                if do_cfg:
                    v_pos, v_neg = jnp.split(v, 2, axis=0)
                    v = v_neg + gscale * (v_pos - v_neg)
                return v

            def prefix_state(lat, i):
                s_gen, lat_in, t_in = embed(lat, i)
                return s_gen, dit.forward_prefix(
                    dit_params, cfg.dit, lat_in, txt_all, t_in,
                    (grid_h, grid_w), txt_mask=mask_all,
                    cond_grids=cond_grids, frames=frames)

            def run_blocks(state, blocks):
                # list -> unrolled loop, stacked dict -> lax.scan
                # (dit.walk_blocks — one block's HLO in the program)
                img, txt_i, temb_act, img_f, txt_f, kv_mask = state
                img, txt_i = dit.walk_blocks(
                    blocks, cfg.dit, img, txt_i, temb_act, img_f,
                    txt_f, attn_fn, kv_mask)
                return (img, txt_i, temb_act, img_f, txt_f, kv_mask)

            def slice_blocks(lo, hi):
                if "blocks_stacked" in dit_params:
                    return jax.tree.map(
                        lambda x: x[lo:hi], dit_params["blocks_stacked"])
                return dit_params["blocks"][lo:hi]

            # ONE block-stack implementation serves the uncached,
            # teacache, and dbcache paths (dbcache splits it at
            # fn_compute_blocks — the always-computed anchor)
            fn_blocks = (self.cache_config.fn_compute_blocks
                         if self.cache_config is not None else 0)
            n_blocks = cfg.dit.num_layers

            def eval_velocity(lat, i):
                s_gen, state = prefix_state(lat, i)
                state = run_blocks(state, slice_blocks(0, n_blocks))
                return finish(state[0], state[2], s_gen)

            def eval_first(lat, i):
                s_gen, state = prefix_state(lat, i)
                state = run_blocks(state, slice_blocks(0, fn_blocks))
                return state, finish(state[0], state[2], s_gen)

            def eval_rest(state):
                state = run_blocks(state,
                                   slice_blocks(fn_blocks, n_blocks))
                return finish(state[0], state[2],
                              int(latents.shape[1]))

            return step_cache.run_denoise_loop(
                self.cache_config, schedule, eval_velocity, latents,
                num_steps, solver=self.cfg.scheduler,
                eval_split=(eval_first, eval_rest),
                step_offset=step_offset, total_steps=total_steps,
                carry_in=cache_carry,
                # chunked callers (step_offset set) always get the
                # 3-tuple — (latents, 0, None) when uncached — so the
                # host loop has ONE call shape; plain callers keep the
                # 2-tuple
                return_carry=(cache_carry is not None
                              or step_offset is not None),
            )

        self._denoise_cache[key] = run
        return run

    # ----------------------------------------------------------- generate
    def forward(self, req: OmniDiffusionRequest) -> list[DiffusionOutput]:
        sp = req.sampling_params
        cfg = self.cfg
        ratio = cfg.vae.spatial_ratio
        patch = cfg.dit.patch_size
        mult = ratio * patch
        if sp.height % mult or sp.width % mult:
            raise InvalidRequestError(
                f"height/width must be multiples of {mult} "
                f"(vae ratio {ratio} x patch {patch}); got "
                f"{sp.height}x{sp.width}"
            )
        if sp.num_inference_steps < 1:
            raise InvalidRequestError("num_inference_steps must be >= 1")
        lat_h, lat_w = sp.height // ratio, sp.width // ratio
        grid_h, grid_w = lat_h // patch, lat_w // patch
        frames = self._latent_frames(req)
        seq_len = frames * grid_h * grid_w
        n_per = max(1, sp.num_images_per_prompt)
        prompts = [p for p in req.prompt for _ in range(n_per)]
        b = len(prompts)

        # Encode each unique prompt once, then repeat embeddings per image
        # (reference repeats post-encode too, pipeline_qwen_image.py).
        if req.prompt_embeds is not None:
            txt = jnp.asarray(req.prompt_embeds, self.dtype)
            txt_mask = jnp.ones(txt.shape[:2], jnp.int32)
        else:
            txt, txt_mask = self.encode_prompt(req.prompt)
        if n_per > 1:
            txt = jnp.repeat(txt, n_per, axis=0)
            txt_mask = jnp.repeat(txt_mask, n_per, axis=0)
        do_cfg = sp.guidance_scale > 1.0
        neg_txt = neg_mask = None
        if do_cfg:
            if req.negative_prompt_embeds is not None:
                neg_txt = jnp.asarray(req.negative_prompt_embeds, self.dtype)
                neg_mask = jnp.ones(neg_txt.shape[:2], jnp.int32)
            else:
                neg_txt, neg_mask = self.encode_prompt(
                    [sp.negative_prompt] * len(req.prompt)
                )
            if n_per > 1:
                neg_txt = jnp.repeat(neg_txt, n_per, axis=0)
                neg_mask = jnp.repeat(neg_mask, n_per, axis=0)

        # Unseeded requests sample a fresh seed (reference semantics: a
        # torch Generator is only seeded when the user provides one).
        seed = (
            sp.seed
            if sp.seed is not None
            else int(np.random.randint(0, 2**31 - 1))
        )
        noise = jax.random.normal(
            jax.random.PRNGKey(seed),
            (b, seq_len, cfg.dit.in_channels),
            jnp.float32,
        ).astype(self.dtype)

        mu = fm.compute_dynamic_shift_mu(seq_len)
        num_steps = sp.num_inference_steps
        schedule = fm.make_schedule(
            num_steps,
            shift=cfg.shift,
            use_dynamic_shifting=cfg.use_dynamic_shifting,
            mu=mu,
        )
        sched_len = max(num_steps, cfg.steps_bucket)
        sigmas = jnp.zeros((sched_len + 1,)).at[: num_steps + 1].set(
            schedule.sigmas
        )
        timesteps = jnp.zeros((sched_len,)).at[:num_steps].set(
            schedule.timesteps
        )
        cond_tokens, cond_grids = self._edit_cond(req, b)
        if self.offload == "layerwise":
            if cond_tokens is not None:
                raise InvalidRequestError(
                    "image-edit conditioning is not supported with "
                    "layerwise offload yet")
            if frames != 1:
                raise InvalidRequestError(
                    "layered generation (frames > 1) is not supported "
                    "with layerwise offload yet")
            txt_all = (jnp.concatenate([txt, neg_txt], axis=0)
                       if do_cfg else txt)
            mask_all = (jnp.concatenate([txt_mask, neg_mask], axis=0)
                        if do_cfg else txt_mask)
            latents = self._stream_denoise(
                noise, txt_all, mask_all, sigmas, timesteps,
                sp.guidance_scale, num_steps, grid_h, grid_w, do_cfg)
        else:
            run = self._denoise_fn(
                grid_h, grid_w, sched_len, batch2=(2 * b if do_cfg else b),
                cond_grids=cond_grids, frames=frames)
            gscale = jnp.float32(sp.guidance_scale)
            if self.step_loop == "host":
                # step_chunk steps per device call (see __init__): the
                # SAME compiled executable runs with num_steps=k over
                # the schedule rolled so index 0 is the chunk start.
                # With a step cache, its carry threads through the
                # chunks (device-resident; no host transfer) and skip
                # decisions use the GLOBAL step index — identical to
                # one uninterrupted device loop.
                import time as _time

                t_start = _time.perf_counter()
                use_cc = (self.cache_config is not None
                          and self.cache_config.enabled)
                carry = step_cache.init_cache_carry(
                    self.cache_config, noise)
                latents = noise
                skipped = jnp.int32(0)
                for i in range(0, num_steps, self.step_chunk):
                    k = min(self.step_chunk, num_steps - i)
                    latents, sk, carry = run(
                        self.dit_params, latents, txt, txt_mask,
                        neg_txt, neg_mask,
                        jnp.roll(sigmas, -i), jnp.roll(timesteps, -i),
                        gscale, jnp.int32(k), cond=cond_tokens,
                        step_offset=jnp.int32(i),
                        total_steps=jnp.int32(num_steps),
                        cache_carry=carry,
                    )
                    skipped = skipped + sk
                jax.block_until_ready(latents)
                self.last_skipped_steps = (
                    int(jax.device_get(skipped)) if use_cc else 0)
                self.last_stream_denoise_s = (
                    _time.perf_counter() - t_start)
            else:
                latents, skipped_steps = run(
                    self.dit_params,
                    noise,
                    txt,
                    txt_mask,
                    neg_txt,
                    neg_mask,
                    sigmas,
                    timesteps,
                    gscale,
                    jnp.int32(num_steps),
                    cond=cond_tokens,
                )
                self.last_skipped_steps = int(skipped_steps)

        images = self._decode_latents(latents, grid_h, grid_w,
                                      frames=frames)
        images = np.asarray(images)
        outs = []
        for i, prompt in enumerate(prompts):
            rid = req.request_ids[i // n_per]
            if n_per > 1:
                rid = f"{rid}-{i % n_per}"
            outs.append(
                DiffusionOutput(
                    request_id=rid,
                    prompt=prompt,
                    data=images[i],
                    output_type="image",
                )
            )
        return outs

    def _pp_denoise_fn(self, grid_h: int, grid_w: int, sched_len: int,
                       cond_grids: tuple = (), frames: int = 1):
        """Denoise with the block stack pipelined over the ``pp`` axis
        (GPipe microbatches, parallel/pp.py): per-rank weight memory
        drops to L/pp blocks; the CFG-doubled batch supplies the
        microbatches."""
        from jax.sharding import PartitionSpec as P

        from vllm_omni_tpu.parallel import pp as pp_mod

        cfg = self.cfg
        mesh = self.mesh
        pp = self.wiring.size("pp")

        from jax import shard_map

        @jax.jit
        def run(dit_params, latents, txt, txt_mask, neg_txt, neg_mask,
                sigmas, timesteps, gscale, num_steps, cond=None):
            schedule = fm.FlowMatchSchedule(sigmas=sigmas,
                                            timesteps=timesteps)
            do_cfg = neg_txt is not None
            txt_all = (jnp.concatenate([txt, neg_txt], axis=0)
                       if do_cfg else txt)
            mask_all = (jnp.concatenate([txt_mask, neg_mask], axis=0)
                        if do_cfg else txt_mask)
            blocks = dit_params["blocks_stacked"]

            def eval_velocity(lat, i):
                t = jnp.broadcast_to(timesteps[i], (lat.shape[0],))
                s_gen = lat.shape[1]
                lat_model = (lat if cond is None
                             else jnp.concatenate([lat, cond], axis=1))
                lat_in = (jnp.concatenate([lat_model, lat_model], 0)
                          if do_cfg else lat_model)
                t_in = jnp.concatenate([t, t], 0) if do_cfg else t
                img, txt_i, temb_act, img_f, txt_f, kv_mask = \
                    dit.forward_prefix(
                        dit_params, cfg.dit, lat_in, txt_all, t_in,
                        (grid_h, grid_w), txt_mask=mask_all,
                        cond_grids=cond_grids, frames=frames)
                b2 = img.shape[0]
                if b2 % pp:
                    raise ValueError(
                        f"(cfg-doubled) batch {b2} must divide pp={pp}")

                # freqs are batch-free trace constants; only batched
                # activations ride the microbatch carry
                def scan_blocks(local_blocks, carry):
                    im, tx, temb_c, kvm = carry

                    def body(c, blk):
                        i_, t_ = c
                        i_, t_ = dit.block_forward(
                            blk, cfg.dit, i_, t_, temb_c, img_f, txt_f,
                            None, kvm)
                        return (i_, t_), None

                    (im, tx), _ = jax.lax.scan(body, (im, tx),
                                               local_blocks)
                    return (im, tx, temb_c, kvm)

                sm = shard_map(
                    functools.partial(pp_mod.pipeline_apply,
                                      scan_fn=scan_blocks),
                    mesh=mesh,
                    in_specs=(pp_mod.pp_block_specs(blocks), P()),
                    out_specs=P(),
                    check_vma=False,
                )
                mb = pp_mod.microbatch(
                    (img, txt_i, temb_act, kv_mask), pp)
                img = pp_mod.unmicrobatch(sm(blocks, mb))[0]
                v = dit.forward_suffix(dit_params, img, temb_act)[:, :s_gen]
                if do_cfg:
                    v_pos, v_neg = jnp.split(v, 2, axis=0)
                    v = v_neg + gscale * (v_pos - v_neg)
                return v

            return step_cache.run_denoise_loop(
                self.cache_config, schedule, eval_velocity, latents,
                num_steps, solver=self.cfg.scheduler,
            )

        return run

    def _edit_cond(self, req, batch: int):
        """(cond_tokens [B, S_cond, in_channels] | None, cond_grids) —
        edit pipelines override to VAE-encode input images."""
        return None, ()

    @functools.cached_property
    def _decode_jit(self):
        @functools.partial(jax.jit, static_argnames=("grid_h", "grid_w"))
        def dec(vae_params, latents, grid_h, grid_w):
            cfg = self.cfg
            patch = cfg.dit.patch_size
            b = latents.shape[0]
            # unpack [B, gh*gw, p*p*C] -> [B, gh*p, gw*p, C]
            c = cfg.vae.z_channels
            x = latents.reshape(b, grid_h, grid_w, patch, patch, c)
            x = x.transpose(0, 1, 3, 2, 4, 5).reshape(
                b, grid_h * patch, grid_w * patch, c
            )
            img = vae_mod.decode_image(
                vae_params, cfg.vae, x.astype(jnp.float32))
            img = jnp.clip((img + 1.0) * 127.5, 0, 255)
            return img.astype(jnp.uint8)

        return dec

    def _latent_frames(self, req) -> int:
        """Simultaneously-generated image planes (rope frame axis);
        layered pipelines override (reference:
        pipeline_qwen_image_layered.py:457-553)."""
        return 1

    def _decode_latents(self, latents, grid_h, grid_w, frames: int = 1):
        # DiT out_channels == vae latent channels; proj_out emits
        # patch^2 * C which equals in_channels when packing matches.
        if frames == 1:
            return self._decode_jit(self.vae_params, latents, grid_h,
                                    grid_w)
        b = latents.shape[0]
        per = latents.reshape(b * frames, grid_h * grid_w,
                              latents.shape[-1])
        imgs = self._decode_jit(self.vae_params, per, grid_h, grid_w)
        return imgs.reshape(b, frames, *imgs.shape[1:])

    def _encode_image_latents(self, images: jax.Array) -> jax.Array:
        """[B, H, W, 3] in [-1, 1] -> packed [B, gh*gw, p*p*z] latents
        (inverse of the decode unpack) — used by edit pipelines."""
        cfg = self.cfg
        patch = cfg.dit.patch_size
        lat = vae_mod.encode_image(
            self.vae_params, cfg.vae, images.astype(jnp.float32))
        b, h, w, c = lat.shape
        gh, gw = h // patch, w // patch
        x = lat.reshape(b, gh, patch, gw, patch, c)
        return x.transpose(0, 1, 3, 2, 4, 5).reshape(
            b, gh * gw, patch * patch * c).astype(self.dtype)
