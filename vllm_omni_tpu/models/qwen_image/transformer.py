"""Qwen-Image MMDiT transformer — TPU-native (functional JAX) redesign.

Behavioral parity with the reference's ``QwenImageTransformer2DModel``
(vllm_omni/diffusion/models/qwen_image/qwen_image_transformer.py:818):
double-stream (text+image) blocks with AdaLayerNorm modulation from the
timestep embedding, joint attention with per-stream QKV projections +
per-head QK RMSNorm, 3-axis (frame/row/col) rotary embeddings on the image
stream, gated residuals, and an AdaLayerNormContinuous output head.

Differences by design (TPU-first):
- torch hooks / _sp_plan are replaced by shard_map sequence parallelism at
  the pipeline level (text stream replicated, image stream sharded — the
  joint text KV rides the ``joint_k/joint_v`` path of
  vllm_omni_tpu.parallel.context.usp_attention).
- attention is the Pallas flash kernel; modulation/MLP fuse under XLA.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from vllm_omni_tpu.models.common import nn
from vllm_omni_tpu.ops import flash_attention, rms_norm


@dataclass(frozen=True)
class QwenImageDiTConfig:
    patch_size: int = 2
    in_channels: int = 64  # 16 VAE latent channels x 2x2 packing
    out_channels: int = 16
    num_layers: int = 60
    num_heads: int = 24
    head_dim: int = 128
    joint_dim: int = 3584  # text-encoder feature dim
    axes_dims: tuple[int, int, int] = (16, 56, 56)  # frame/row/col rope dims
    theta: float = 10000.0
    mlp_ratio: float = 4.0
    # rotary pairing convention: False = half-split (TPU-native default),
    # True = interleaved pairs — the convention real checkpoints were
    # trained with (reference QwenEmbedRope builds torch.polar complex
    # freqs consumed by RotaryEmbedding(is_neox_style=False),
    # qwen_image_transformer.py:553,598-601); from_pretrained sets this
    rope_interleaved: bool = False

    @property
    def inner_dim(self) -> int:
        return self.num_heads * self.head_dim

    @staticmethod
    def tiny() -> "QwenImageDiTConfig":
        return QwenImageDiTConfig(
            in_channels=16,
            out_channels=4,
            num_layers=2,
            num_heads=4,
            head_dim=32,
            joint_dim=64,
            axes_dims=(8, 12, 12),
        )


def init_top(keys, cfg: QwenImageDiTConfig, dtype=jnp.float32):
    """Non-block params from the first 6 of ``init_params``' key array."""
    inner = cfg.inner_dim
    return {
        "img_in": nn.linear_init(keys[0], cfg.in_channels, inner, dtype=dtype),
        "txt_norm": nn.rmsnorm_init(cfg.joint_dim, dtype),
        "txt_in": nn.linear_init(keys[1], cfg.joint_dim, inner, dtype=dtype),
        "time_in1": nn.linear_init(keys[2], 256, inner, dtype=dtype),
        "time_in2": nn.linear_init(keys[3], inner, inner, dtype=dtype),
        "norm_out_mod": nn.linear_init(keys[4], inner, 2 * inner, dtype=dtype),
        "proj_out": nn.linear_init(
            keys[5], inner, cfg.patch_size**2 * cfg.out_channels, dtype=dtype
        ),
    }


def init_block(key, cfg: QwenImageDiTConfig, dtype=jnp.float32):
    """One MMDiT block from its per-block key (``init_params`` passes
    keys[i + 8]; blockwise quantized init reuses the SAME schedule so a
    quantized build is a quantization of the identical random model)."""
    inner = cfg.inner_dim
    mlp = int(inner * cfg.mlp_ratio)
    k = jax.random.split(key, 12)
    return {
        "img_mod": nn.linear_init(k[0], inner, 6 * inner, dtype=dtype),
        "txt_mod": nn.linear_init(k[1], inner, 6 * inner, dtype=dtype),
        "to_q": nn.linear_init(k[2], inner, inner, dtype=dtype),
        "to_k": nn.linear_init(k[3], inner, inner, dtype=dtype),
        "to_v": nn.linear_init(k[4], inner, inner, dtype=dtype),
        "add_q": nn.linear_init(k[5], inner, inner, dtype=dtype),
        "add_k": nn.linear_init(k[6], inner, inner, dtype=dtype),
        "add_v": nn.linear_init(k[7], inner, inner, dtype=dtype),
        "norm_q": nn.rmsnorm_init(cfg.head_dim, dtype),
        "norm_k": nn.rmsnorm_init(cfg.head_dim, dtype),
        "norm_added_q": nn.rmsnorm_init(cfg.head_dim, dtype),
        "norm_added_k": nn.rmsnorm_init(cfg.head_dim, dtype),
        "to_out": nn.linear_init(k[8], inner, inner, dtype=dtype),
        "to_add_out": nn.linear_init(k[9], inner, inner, dtype=dtype),
        "img_mlp1": nn.linear_init(k[10], inner, mlp, dtype=dtype),
        "img_mlp2": nn.linear_init(k[11], mlp, inner, dtype=dtype),
        "txt_mlp1": nn.linear_init(
            jax.random.fold_in(k[10], 1), inner, mlp, dtype=dtype
        ),
        "txt_mlp2": nn.linear_init(
            jax.random.fold_in(k[11], 1), mlp, inner, dtype=dtype
        ),
    }


def init_params(key, cfg: QwenImageDiTConfig, dtype=jnp.float32):
    keys = jax.random.split(key, cfg.num_layers + 8)
    p = init_top(keys, cfg, dtype=dtype)
    p["blocks"] = [
        init_block(keys[i + 8], cfg, dtype=dtype)
        for i in range(cfg.num_layers)
    ]
    return p


def rope_freqs(
    cfg: QwenImageDiTConfig,
    grid_h: int,
    grid_w: int,
    txt_len: int,
    frames: int = 1,
    cond_grids: tuple[tuple[int, int], ...] = (),
):
    """3-axis rotary frequencies for the image grid + continued positions
    for the text stream (reference QwenEmbedRope, scale_rope=True: row/col
    coordinates are centered).

    ``cond_grids``: (gh, gw) per VAE-encoded condition image appended to
    the token sequence (image edit).  Condition tokens share the centered
    row/col layout; their frame coordinate is the entry index, except the
    LAST condition which sits at frame -1 (reference
    _compute_condition_freqs, qwen_image_transformer.py:279-297)."""
    half_dims = [d // 2 for d in cfg.axes_dims]  # complex pairs per axis

    def axis_freqs(pos, half):
        inv = 1.0 / (
            cfg.theta ** (jnp.arange(half, dtype=jnp.float32) / half)
        )
        return pos.astype(jnp.float32)[:, None] * inv[None, :]

    def grid_angles(gh, gw, frame_coord, n_frames=1):
        f = jnp.full((n_frames,), frame_coord).repeat(gh * gw) if \
            n_frames == 1 else jnp.arange(n_frames).repeat(gh * gw)
        # centered rows/cols: -(g - g//2) .. g//2 - 1 (reference
        # _compute_video_freqs scale_rope concat of neg+pos positions —
        # for odd extents the extra row sits on the negative side)
        r = jnp.tile(jnp.arange(gh).repeat(gw), n_frames) - (gh - gh // 2)
        c = jnp.tile(jnp.arange(gw), n_frames * gh) - (gw - gw // 2)
        return jnp.concatenate(
            [
                axis_freqs(f, half_dims[0]),
                axis_freqs(r, half_dims[1]),
                axis_freqs(c, half_dims[2]),
            ],
            axis=-1,
        )  # [S, head_dim//2]

    parts = [grid_angles(grid_h, grid_w, 0, n_frames=frames)]
    for j, (ch, cw) in enumerate(cond_grids):
        frame_coord = -1 if j == len(cond_grids) - 1 else j + 1
        parts.append(grid_angles(ch, cw, frame_coord))
    img_angles = jnp.concatenate(parts, axis=0)

    # Text positions continue at the image extent on every axis
    # (reference: txt_freqs = pos_freqs[max_vid_index : max_vid_index +
    # max_len] — the first text token sits AT max_vid_index).
    extent = max(
        [grid_h // 2, grid_w // 2, len(cond_grids)]
        + [max(ch // 2, cw // 2) for ch, cw in cond_grids]
    )
    tpos = jnp.arange(txt_len) + extent
    txt_angles = jnp.concatenate(
        [axis_freqs(tpos, h) for h in half_dims], axis=-1
    )
    return (
        (jnp.cos(img_angles), jnp.sin(img_angles)),
        (jnp.cos(txt_angles), jnp.sin(txt_angles)),
    )


def _rope_apply(x, cos, sin, interleaved: bool = False):
    """x: [B, S, H, D]; cos/sin: [S, D//2].

    ``interleaved``: rotate (x0,x1),(x2,x3),... pairs — the trained
    checkpoint convention; default pairs (x_j, x_{j+D/2})."""
    c = cos[None, :, None, :]
    s = sin[None, :, None, :]
    if interleaved:
        x1 = x[..., 0::2].astype(jnp.float32)
        x2 = x[..., 1::2].astype(jnp.float32)
        out = jnp.stack([x1 * c - x2 * s, x1 * s + x2 * c], axis=-1)
        return out.reshape(x.shape).astype(x.dtype)
    d = x.shape[-1]
    x1 = x[..., : d // 2].astype(jnp.float32)
    x2 = x[..., d // 2 :].astype(jnp.float32)
    return jnp.concatenate(
        [x1 * c - x2 * s, x2 * c + x1 * s], axis=-1
    ).astype(x.dtype)


def _modulate(x, mod3):
    """mod3: [B, 3*dim] -> (modulated layernorm-ed x, gate)."""
    shift, scale, gate = jnp.split(mod3, 3, axis=-1)
    xn = nn.layernorm({}, x)
    return xn * (1.0 + scale[:, None, :]) + shift[:, None, :], gate[:, None, :]


def _heads(x, h):
    b, s, _ = x.shape
    return x.reshape(b, s, h, -1)


def block_forward(
    blk,
    cfg: QwenImageDiTConfig,
    img: jax.Array,  # [B, S_img, inner]
    txt: jax.Array,  # [B, S_txt, inner]
    temb_act: jax.Array,  # silu(temb) [B, inner]
    img_freqs,
    txt_freqs,
    attn_fn=None,
    kv_mask: Optional[jax.Array] = None,  # [B, S_txt + S_img]
):
    h = cfg.num_heads
    img_mod = nn.linear(blk["img_mod"], temb_act)
    txt_mod = nn.linear(blk["txt_mod"], temb_act)
    img_mod1, img_mod2 = jnp.split(img_mod, 2, axis=-1)
    txt_mod1, txt_mod2 = jnp.split(txt_mod, 2, axis=-1)

    img_n, img_gate1 = _modulate(img, img_mod1)
    txt_n, txt_gate1 = _modulate(txt, txt_mod1)

    qi = rms_norm(_heads(nn.linear(blk["to_q"], img_n), h), blk["norm_q"]["w"])
    ki = rms_norm(_heads(nn.linear(blk["to_k"], img_n), h), blk["norm_k"]["w"])
    vi = _heads(nn.linear(blk["to_v"], img_n), h)
    qt = rms_norm(
        _heads(nn.linear(blk["add_q"], txt_n), h), blk["norm_added_q"]["w"]
    )
    kt = rms_norm(
        _heads(nn.linear(blk["add_k"], txt_n), h), blk["norm_added_k"]["w"]
    )
    vt = _heads(nn.linear(blk["add_v"], txt_n), h)

    il = cfg.rope_interleaved
    qi = _rope_apply(qi, *img_freqs, interleaved=il)
    ki = _rope_apply(ki, *img_freqs, interleaved=il)
    qt = _rope_apply(qt, *txt_freqs, interleaved=il)
    kt = _rope_apply(kt, *txt_freqs, interleaved=il)

    if attn_fn is None:
        # Joint attention, text first (reference layout,
        # qwen_image_transformer.py:654-656).
        q = jnp.concatenate([qt, qi], axis=1)
        k = jnp.concatenate([kt, ki], axis=1)
        v = jnp.concatenate([vt, vi], axis=1)
        o = flash_attention(q, k, v, causal=False, kv_mask=kv_mask)
        s_txt = txt.shape[1]
        txt_o = o[:, :s_txt].reshape(*txt.shape[:2], -1)
        img_o = o[:, s_txt:].reshape(*img.shape[:2], -1)
    else:
        # Sequence-parallel path: image stream sharded, text KV joint.
        # The text part of the mask rides along so padded text tokens are
        # excluded on the distributed path too.
        txt_kv_mask = None if kv_mask is None else kv_mask[:, : txt.shape[1]]
        img_o, txt_o = attn_fn(qi, ki, vi, qt, kt, vt, txt_kv_mask)

    img = img + img_gate1 * nn.linear(blk["to_out"], img_o)
    txt = txt + txt_gate1 * nn.linear(blk["to_add_out"], txt_o)

    img_n2, img_gate2 = _modulate(img, img_mod2)
    img = img + img_gate2 * nn.linear(
        blk["img_mlp2"],
        jax.nn.gelu(nn.linear(blk["img_mlp1"], img_n2), approximate=True),
    )
    txt_n2, txt_gate2 = _modulate(txt, txt_mod2)
    txt = txt + txt_gate2 * nn.linear(
        blk["txt_mlp2"],
        jax.nn.gelu(nn.linear(blk["txt_mlp1"], txt_n2), approximate=True),
    )
    return img, txt


def forward(
    params,
    cfg: QwenImageDiTConfig,
    img_tokens: jax.Array,  # [B, S_img, in_channels] packed latents
    txt_states: jax.Array,  # [B, S_txt, joint_dim]
    timesteps: jax.Array,  # [B] in [0, 1000)
    grid_hw: tuple[int, int],
    attn_fn=None,
    txt_mask: Optional[jax.Array] = None,  # [B, S_txt] 1=real, 0=pad
    cond_grids: tuple[tuple[int, int], ...] = (),
    frames: int = 1,
) -> jax.Array:
    """Returns velocity prediction [B, S_img, patch^2 * out_channels].

    ``cond_grids``: grids of VAE-encoded condition images appended to
    ``img_tokens`` after the generated grid (image edit) — the caller
    slices the velocity back to the generated tokens."""
    img, txt, temb_act, img_freqs, txt_freqs, kv_mask = forward_prefix(
        params, cfg, img_tokens, txt_states, timesteps, grid_hw,
        txt_mask=txt_mask, cond_grids=cond_grids, frames=frames,
    )
    img, txt = walk_blocks(
        params.get("blocks_stacked", params.get("blocks")), cfg, img,
        txt, temb_act, img_freqs, txt_freqs, attn_fn, kv_mask,
    )
    return forward_suffix(params, img, temb_act)


def walk_blocks(blocks, cfg: QwenImageDiTConfig, img, txt, temb_act,
                img_freqs, txt_freqs, attn_fn=None, kv_mask=None):
    """Run the block stack: a Python loop over a LIST of per-block
    pytrees (unrolled — lets XLA fuse across adjacent small blocks), or
    lax.scan over a DICT stacked on a leading layer axis.

    The scan form keeps the compiled program at ONE block's HLO instead
    of L copies — at the real 60-layer geometry the unrolled program is
    large enough to break remote-compile services outright — and pins
    quantized-weight dequant inside the loop body where LICM can't hoist
    L dequantized bf16 blocks out of the step loop (= 41 GB).  Same
    math, identical per-block MXU shapes."""
    if isinstance(blocks, dict):
        def body(carry, blk):
            c_img, c_txt = carry
            c_img, c_txt = block_forward(
                blk, cfg, c_img, c_txt, temb_act, img_freqs, txt_freqs,
                attn_fn, kv_mask,
            )
            return (c_img, c_txt), None

        (img, txt), _ = jax.lax.scan(body, (img, txt), blocks)
        return img, txt
    for blk in blocks:
        img, txt = block_forward(
            blk, cfg, img, txt, temb_act, img_freqs, txt_freqs,
            attn_fn, kv_mask,
        )
    return img, txt


def forward_prefix(
    params,
    cfg: QwenImageDiTConfig,
    img_tokens: jax.Array,
    txt_states: jax.Array,
    timesteps: jax.Array,
    grid_hw: tuple[int, int],
    txt_mask: Optional[jax.Array] = None,
    cond_grids: tuple[tuple[int, int], ...] = (),
    frames: int = 1,
):
    """Everything before the block stack: embeds, time conditioning,
    rope tables, joint KV mask.  Split out so block-streaming
    (diffusion/offload.py) and pipeline parallelism (parallel/pp.py) can
    schedule the stack themselves."""
    img = nn.linear(params["img_in"], img_tokens)
    txt = rms_norm(txt_states, params["txt_norm"]["w"])
    txt = nn.linear(params["txt_in"], txt)

    temb = nn.timestep_embedding(timesteps, 256)
    temb = nn.linear(
        params["time_in2"],
        jax.nn.silu(nn.linear(params["time_in1"], temb.astype(img.dtype))),
    )
    temb_act = jax.nn.silu(temb)

    img_freqs, txt_freqs = rope_freqs(
        cfg, grid_hw[0], grid_hw[1], txt_states.shape[1],
        cond_grids=cond_grids, frames=frames,
    )

    # Joint-attention KV mask: padded text tokens must not receive
    # attention mass (reference encoder_hidden_states_mask semantics,
    # qwen_image_transformer.py:746).
    kv_mask = None
    if txt_mask is not None:
        b, s_img = img.shape[:2]
        kv_mask = jnp.concatenate(
            [txt_mask.astype(jnp.int32), jnp.ones((b, s_img), jnp.int32)],
            axis=1,
        )
    return img, txt, temb_act, img_freqs, txt_freqs, kv_mask


def forward_suffix(params, img: jax.Array, temb_act: jax.Array):
    """AdaLayerNormContinuous output head."""
    mod = nn.linear(params["norm_out_mod"], temb_act)
    scale, shift = jnp.split(mod, 2, axis=-1)
    img = nn.layernorm({}, img) * (1.0 + scale[:, None, :]) + shift[:, None, :]
    return nn.linear(params["proj_out"], img)
