"""Qwen-Image-Edit / Edit-Plus pipelines (image -> image, text-guided).

Reference: vllm_omni/diffusion/models/qwen_image/pipeline_qwen_image_edit.py
(:218 QwenImageEditPipeline) and pipeline_qwen_image_edit_plus.py.  The
editing mechanism: the input image is VAE-encoded, packed like generated
latents, and CONCATENATED to the token sequence; the DiT attends across
both, RoPE gives condition tokens frame coordinate -1
(qwen_image_transformer.py:279-297), and velocity is read off the
generated tokens only.  Edit-Plus extends to multiple condition images
(frame coordinates idx..,-1).

TPU notes: the condition tokens ride the same jitted denoise loop — one
executable per (geometry, cond geometry) pair; the condition encode is a
single VAE encoder call (causal_vae.encode_image).

Text conditioning (from_pretrained): the edit prompt template feeds the
condition image(s) through the checkpoint's Qwen2.5-VL vision tower
during TEXT encoding — ``<|vision_start|><|image_pad|...|><|vision_end|>``
spans carry ViT features into the LM with grid-aware MRoPE positions,
and the first 64 template tokens are dropped
(pipeline_qwen_image_edit.py:266-268,332-375).  Checkpoints whose
text_encoder ships no ``visual.*`` weights fall back to text-only
encoding with a warning.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.diffusion.request import InvalidRequestError
from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.models.qwen_image.pipeline import QwenImagePipeline

logger = init_logger(__name__)

# Reference edit template + drop index
# (pipeline_qwen_image_edit.py:266-268); one vision span per condition
# image (Edit-Plus repeats "Picture {i}: <span>" per image,
# pipeline_qwen_image_edit_plus.py).
EDIT_TEMPLATE_PREFIX = (
    "<|im_start|>system\nDescribe the key features of the input image "
    "(color, shape, size, texture, objects, background), then explain "
    "how the user's text instruction should alter or modify the image. "
    "Generate a new image that meets the user's requirements while "
    "maintaining consistency with the original input where "
    "appropriate.<|im_end|>\n<|im_start|>user\n"
)
VISION_SPAN = "<|vision_start|><|image_pad|><|vision_end|>"
EDIT_TEMPLATE_SUFFIX = "<|im_end|>\n<|im_start|>assistant\n"
EDIT_DROP_IDX = 64


def _find_visual_prefix(te_dir: str):
    """(has_visual_weights, prefix) by peeking at the checkpoint keys."""
    import os

    from safetensors import safe_open

    for fn in sorted(os.listdir(te_dir)):
        if not fn.endswith(".safetensors"):
            continue
        with safe_open(os.path.join(te_dir, fn), "np") as f:
            for k in f.keys():
                if k.startswith("visual."):
                    return True, "visual."
                if k.startswith("model.visual."):
                    return True, "model.visual."
    return False, None


def _to_float_image(img) -> np.ndarray:
    """uint8/float [H, W, 3] -> float32 in [-1, 1]."""
    arr = np.asarray(img)
    if arr.ndim != 3 or arr.shape[-1] != 3:
        raise InvalidRequestError(
            f"conditioning image must be [H, W, 3]; got {arr.shape}")
    if arr.dtype == np.uint8:
        return arr.astype(np.float32) / 127.5 - 1.0
    return arr.astype(np.float32)


class QwenImageEditPipeline(QwenImagePipeline):
    """Single condition image; output geometry follows the request."""

    needs_vae_encoder = True
    max_cond_images = 1

    # vision tower (set by from_pretrained when the checkpoint's
    # text_encoder ships visual.* weights)
    vt_params = None
    vt_cfg = None
    _pending_images: "list[np.ndarray] | None" = None
    # VL pixel budget per condition image during TEXT encoding (None =
    # the tower's default ~1MP budget); Edit-Plus bounds each image so
    # several condition images still fit the text bucket (reference
    # condition resize, pipeline_qwen_image_edit_plus.py)
    vl_max_pixels = None

    @classmethod
    def from_pretrained(cls, model_dir: str, max_text_len: int = 1024,
                        **kw):
        # the reference edit pipelines use tokenizer_max_length 1024
        # (pipeline_qwen_image_edit.py:265) — the template + vision span
        # + instruction need the headroom
        import json
        import os

        pipe = super().from_pretrained(model_dir,
                                       max_text_len=max_text_len, **kw)
        te_dir = os.path.join(model_dir, "text_encoder")
        from vllm_omni_tpu.models.qwen2_5_omni import vision_tower as vt

        with open(os.path.join(te_dir, "config.json")) as f:
            vcfg_json = json.load(f).get("vision_config")
        has_weights, prefix = _find_visual_prefix(te_dir)
        if vcfg_json is None or not has_weights:
            # genuinely vision-less text encoder (e.g. a text-only
            # synthetic checkpoint): degrade with a warning
            logger.warning(
                "text_encoder under %s ships no vision tower; edit "
                "prompts encode text-only", te_dir)
            return pipe
        # a vision-equipped checkpoint MUST load — silent text-only
        # fallback would quietly degrade every edit
        vt_cfg = vt.VisionTowerConfig.from_hf(vcfg_json)
        pipe.vt_params, _ = vt.load_vision_tower(
            te_dir, cfg=vt_cfg, dtype=pipe.dtype, prefix=prefix)
        pipe.vt_cfg = vt_cfg
        pipe._vt_jit = jax.jit(vt.forward, static_argnums=(1, 3))
        return pipe

    def forward(self, req):
        # stash the condition images so the HF text encode can feed them
        # through the vision tower (the reference conditions the prompt
        # embeddings on the image as well as the VAE latents); the ViT
        # features cache per request — positive and negative encodes
        # share them
        if self.hf_tokenizer is not None and self.vt_params is not None:
            self._pending_images = self._cond_images(req)
        try:
            return super().forward(req)
        finally:
            self._pending_images = None
            self._vit_cache = None

    def _encode_prompt_hf(self, prompts: list[str]):
        images = self._pending_images
        if images is None or self.vt_params is None:
            return super()._encode_prompt_hf(prompts)
        from vllm_omni_tpu.models.qwen2_5_omni.multimodal import (
            flatten_image,
        )
        from vllm_omni_tpu.models.qwen3_omni.multimodal import (
            compute_mrope_positions,
            expand_placeholders,
        )

        tok = self.hf_tokenizer
        pad_id = tok.convert_tokens_to_ids("<|image_pad|>")
        if getattr(self, "_vit_cache", None) is not None:
            feats_list, grids = self._vit_cache
        else:
            feats_list, grids = [], []
            for img in images:
                # _cond_images yields [-1, 1] floats (the VAE
                # convention); the ViT preprocessing expects [0, 1]
                img01 = np.clip((np.asarray(img) + 1.0) / 2.0, 0.0, 1.0)
                pixels, (t, gh, gw) = flatten_image(
                    img01, self.vt_cfg, max_pixels=self.vl_max_pixels)
                f = self._vt_jit(self.vt_params, self.vt_cfg,
                                 jnp.asarray(pixels), (t, gh, gw))
                sm = self.vt_cfg.spatial_merge_size
                feats_list.append(np.asarray(f, np.float32))
                grids.append((t, gh // sm, gw // sm))
            self._vit_cache = (feats_list, grids)

        spans = "".join(
            (f"Picture {i + 1}: {VISION_SPAN}" if len(images) > 1
             else VISION_SPAN)
            for i in range(len(images)))
        rows = []
        for p in prompts:
            text = (EDIT_TEMPLATE_PREFIX + spans + p
                    + EDIT_TEMPLATE_SUFFIX)
            ids = tok(text, add_special_tokens=False)["input_ids"]
            expanded, items = expand_placeholders(
                ids, {"image": pad_id},
                [("image", g) for g in grids])
            embeds = np.zeros((len(expanded),
                               self.cfg.text.hidden_size), np.float32)
            mask = np.zeros((len(expanded),), bool)
            for item, f in zip(items, feats_list):
                embeds[item.offset:item.offset + item.num_tokens] = f
                mask[item.offset:item.offset + item.num_tokens] = True
            positions, _ = compute_mrope_positions(len(expanded), items)
            rows.append((expanded, embeds, mask, positions))

        # fixed bucket: positive and negative encodes must agree on the
        # text length (the denoise concatenates the CFG halves), and
        # static shapes keep one executable per geometry — the DiT's
        # kv_mask hides the padding
        max_len = self.cfg.max_text_len + EDIT_DROP_IDX
        for ids, *_ in rows:
            if len(ids) > max_len:
                raise InvalidRequestError(
                    f"edit prompt + vision spans need {len(ids)} tokens "
                    f"but the text bucket holds {max_len}; shorten the "
                    "prompt or reduce condition images")
        b = len(rows)
        ids_b = np.zeros((b, max_len), np.int32)
        emb_b = np.zeros((b, max_len, self.cfg.text.hidden_size),
                         np.float32)
        em_b = np.zeros((b, max_len), bool)
        pos_b = np.zeros((b, 3, max_len), np.int32)
        attn_b = np.zeros((b, max_len), np.int32)
        for i, (ids, emb, em, pos) in enumerate(rows):
            n = len(ids)
            ids_b[i, :n] = ids
            emb_b[i, :n] = emb
            em_b[i, :n] = em
            pos_b[i, :, :n] = pos
            attn_b[i, :n] = 1
        hidden = self._edit_encode_jit(
            self.text_params, jnp.asarray(ids_b), jnp.asarray(pos_b),
            jnp.asarray(emb_b), jnp.asarray(em_b), jnp.asarray(attn_b))
        hidden = hidden[:, EDIT_DROP_IDX:]
        mask = jnp.asarray(attn_b[:, EDIT_DROP_IDX:])
        return hidden.astype(self.dtype), mask

    @property
    def _edit_encode_jit(self):
        fn = self.__dict__.get("_edit_encode_jit_fn")
        if fn is None:
            from vllm_omni_tpu.models.common.transformer import (
                forward_hidden,
            )

            fn = jax.jit(
                lambda p, ids, pos, emb, em, am: forward_hidden(
                    p, self.cfg.text, ids, positions=pos,
                    inputs_embeds=emb, attn_mask=am,
                    embeds_mask=em))
            self.__dict__["_edit_encode_jit_fn"] = fn
        return fn

    def _cond_images(self, req) -> list[np.ndarray]:
        sp = req.sampling_params
        image = sp.image if sp.image is not None else sp.extra.get("image")
        if image is None:
            raise InvalidRequestError(
                f"{type(self).__name__} needs sampling_params.image "
                "(the image to edit)")
        images = image if isinstance(image, (list, tuple)) else [image]
        if (self.max_cond_images is not None
                and len(images) > self.max_cond_images):
            raise InvalidRequestError(
                f"{type(self).__name__} accepts at most "
                f"{self.max_cond_images} condition image(s), got "
                f"{len(images)}")
        return [_to_float_image(im) for im in images]

    def _edit_cond(self, req, batch: int):
        sp = req.sampling_params
        cfg = self.cfg
        mult = cfg.vae.spatial_ratio * cfg.dit.patch_size
        tokens = []
        grids = []
        for img in self._cond_images(req):
            h, w = img.shape[:2]
            # snap the condition geometry to the model's multiple; resize
            # (reference resizes to a ~1MP target area — here the request
            # geometry is authoritative)
            th = max(mult, h // mult * mult)
            tw = max(mult, w // mult * mult)
            if (h, w) != (th, tw):
                img = np.asarray(jax.image.resize(
                    jnp.asarray(img), (th, tw, 3), "bilinear"))
            packed = self._encode_image_latents(
                jnp.asarray(img, jnp.float32)[None])  # [1, S, C]
            tokens.append(jnp.repeat(packed, batch, axis=0))
            grids.append((th // mult, tw // mult))
        cond = jnp.concatenate(tokens, axis=1)
        return cond, tuple(grids)


class QwenImageEditPlusPipeline(QwenImageEditPipeline):
    """Multiple condition images (reference:
    pipeline_qwen_image_edit_plus.py — each extra image appends its own
    token block; RoPE frame coordinates idx.., last at -1)."""

    max_cond_images = None
    # each condition image is bounded to ~384x384 for the VL text
    # encode so several images fit the text bucket (reference
    # condition resize, pipeline_qwen_image_edit_plus.py)
    vl_max_pixels = 384 * 384
