"""Qwen-Image-Edit / Edit-Plus pipelines (image -> image, text-guided).

Reference: vllm_omni/diffusion/models/qwen_image/pipeline_qwen_image_edit.py
(:218 QwenImageEditPipeline) and pipeline_qwen_image_edit_plus.py.  The
editing mechanism: the input image is VAE-encoded, packed like generated
latents, and CONCATENATED to the token sequence; the DiT attends across
both, RoPE gives condition tokens frame coordinate -1
(qwen_image_transformer.py:279-297), and velocity is read off the
generated tokens only.  Edit-Plus extends to multiple condition images
(frame coordinates idx..,-1).

TPU notes: the condition tokens ride the same jitted denoise loop — one
executable per (geometry, cond geometry) pair; the condition encode is a
single VAE encoder call (causal_vae.encode_image).

Documented deviation: the reference's edit prompt template feeds the
input image through the Qwen2.5-VL vision tower during TEXT encoding
(pipeline_qwen_image_edit.py:266); this pipeline encodes the text prompt
only — conditioning flows through the VAE-latent path, which is what
anchors the output to the input image.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.diffusion.request import InvalidRequestError
from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.models.qwen_image.pipeline import QwenImagePipeline

logger = init_logger(__name__)


def _to_float_image(img) -> np.ndarray:
    """uint8/float [H, W, 3] -> float32 in [-1, 1]."""
    arr = np.asarray(img)
    if arr.ndim != 3 or arr.shape[-1] != 3:
        raise InvalidRequestError(
            f"conditioning image must be [H, W, 3]; got {arr.shape}")
    if arr.dtype == np.uint8:
        return arr.astype(np.float32) / 127.5 - 1.0
    return arr.astype(np.float32)


class QwenImageEditPipeline(QwenImagePipeline):
    """Single condition image; output geometry follows the request."""

    needs_vae_encoder = True
    max_cond_images = 1

    def _cond_images(self, req) -> list[np.ndarray]:
        sp = req.sampling_params
        image = sp.image if sp.image is not None else sp.extra.get("image")
        if image is None:
            raise InvalidRequestError(
                f"{type(self).__name__} needs sampling_params.image "
                "(the image to edit)")
        images = image if isinstance(image, (list, tuple)) else [image]
        if (self.max_cond_images is not None
                and len(images) > self.max_cond_images):
            raise InvalidRequestError(
                f"{type(self).__name__} accepts at most "
                f"{self.max_cond_images} condition image(s), got "
                f"{len(images)}")
        return [_to_float_image(im) for im in images]

    def _edit_cond(self, req, batch: int):
        sp = req.sampling_params
        cfg = self.cfg
        mult = cfg.vae.spatial_ratio * cfg.dit.patch_size
        tokens = []
        grids = []
        for img in self._cond_images(req):
            h, w = img.shape[:2]
            # snap the condition geometry to the model's multiple; resize
            # (reference resizes to a ~1MP target area — here the request
            # geometry is authoritative)
            th = max(mult, h // mult * mult)
            tw = max(mult, w // mult * mult)
            if (h, w) != (th, tw):
                img = np.asarray(jax.image.resize(
                    jnp.asarray(img), (th, tw, 3), "bilinear"))
            packed = self._encode_image_latents(
                jnp.asarray(img, jnp.float32)[None])  # [1, S, C]
            tokens.append(jnp.repeat(packed, batch, axis=0))
            grids.append((th // mult, tw // mult))
        cond = jnp.concatenate(tokens, axis=1)
        return cond, tuple(grids)


class QwenImageEditPlusPipeline(QwenImageEditPipeline):
    """Multiple condition images (reference:
    pipeline_qwen_image_edit_plus.py — each extra image appends its own
    token block; RoPE frame coordinates idx.., last at -1)."""

    max_cond_images = None
