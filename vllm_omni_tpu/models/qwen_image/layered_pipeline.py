"""Qwen-Image-Layered: one denoise produces a composite plus N image
layers simultaneously.

Reference: vllm_omni/diffusion/models/qwen_image/
pipeline_qwen_image_layered.py — latents carry ``layers + 1`` planes
packed along the sequence axis; each plane gets its own rope frame
coordinate (img_shapes repeats (1, h, w) layers+1 times, :747-751), the
DiT denoises them jointly so layers stay mutually consistent, and each
plane VAE-decodes to its own image.

TPU notes: the multi-plane sequence rides the base pipeline's ``frames``
axis (transformer rope frames) — same jitted loop, sequence just
``layers+1`` times longer; planes batch through the VAE decoder
together.  The output's ``data`` is [layers+1, H, W, 3]: composite
first, then the layers."""

from __future__ import annotations

from vllm_omni_tpu.models.qwen_image.pipeline import QwenImagePipeline


class QwenImageLayeredPipeline(QwenImagePipeline):
    """Text -> composite + N layers (stacked on data's leading axis)."""

    default_layers = 4

    def _latent_frames(self, req) -> int:
        sp = req.sampling_params
        layers = sp.extra.get("layers", self.default_layers)
        if not isinstance(layers, int) or layers < 1:
            from vllm_omni_tpu.diffusion.request import (
                InvalidRequestError,
            )

            raise InvalidRequestError(
                f"layers must be a positive int, got {layers!r}")
        return layers + 1
