"""GLM-Image: AR-prior + DiT two-model generation.

Reference: vllm_omni/diffusion/models/glm_image/ — pipeline_glm_image.py
(:247-255): an AR vision-language model first generates PRIOR VQ tokens
for the image ("1. AR generates prior_token_ids from text prompt"), then
a double-stream DiT denoises latents conditioned on those prior tokens:
each prior token embeds and ADDS into the image stream before the blocks
(glm_image_transformer.py:678-683), with prior-drop classifier-free
guidance (prior_token_drop) instead of text CFG.

TPU-first composition: the random-init path reuses the shared
Qwen-Image MMDiT double-stream blocks through the decomposed
forward_prefix / block / suffix API — GLM's prior embedding injects
between prefix and blocks without touching the shared transformer; the
AR prior is a causal transformer over the prior vocabulary sampled
greedily under one jitted scan.

from_pretrained loads the REAL checkpoint schema: the GLM DiT
(ckpt_transformer.py — joint-qkv blocks, 12-chunk AdaLN, glyph/prior
projectors, SDXL size/crop conditioning), the ByT5 glyph text encoder,
the AutoencoderKL, and the AR prior VLM (vision_language_encoder/ —
GLM-4.1V schema, prior.py) whose in-pipeline rollout generates
``prior_token_ids`` exactly like the reference (:285, :434-453).
Precomputed priors still win via
``sampling_params.extra["prior_token_ids"]``; checkpoints without the
prior stage fall back to the in-tree random prior with a warning.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.diffusion import scheduler as fm
from vllm_omni_tpu.diffusion.request import (
    DiffusionOutput,
    InvalidRequestError,
    OmniDiffusionRequest,
)
from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.models.common import nn
from vllm_omni_tpu.models.common.transformer import (
    TransformerConfig,
    forward_hidden,
    init_params as init_tfm_params,
    logits_from_hidden,
)
from vllm_omni_tpu.models.qwen_image import transformer as dit
from vllm_omni_tpu.models.qwen_image import vae as vae_mod
from vllm_omni_tpu.models.qwen_image.transformer import QwenImageDiTConfig
from vllm_omni_tpu.models.qwen_image.vae import VAEConfig
from vllm_omni_tpu.utils.tokenizer import ByteTokenizer

logger = init_logger(__name__)


@dataclass(frozen=True)
class GlmImagePipelineConfig:
    text: TransformerConfig = field(default_factory=TransformerConfig)
    # AR prior LM: causal transformer over the prior VQ vocabulary
    prior_lm: TransformerConfig = field(
        default_factory=lambda: TransformerConfig(vocab_size=16384))
    dit: QwenImageDiTConfig = field(default_factory=QwenImageDiTConfig)
    vae: VAEConfig = field(default_factory=VAEConfig)
    prior_vocab: int = 16384
    max_text_len: int = 64
    scheduler: str = "euler"
    steps_bucket: int = 32
    # SDXL-like size/crop conditioning width (reference:
    # GlmImageCombinedTimestepSizeEmbeddings — sinusoid embeds of
    # target_size + crop_coords pooled into the timestep stream)
    condition_dim: int = 64

    @staticmethod
    def tiny() -> "GlmImagePipelineConfig":
        return GlmImagePipelineConfig(
            text=TransformerConfig.tiny(vocab_size=256),
            prior_lm=TransformerConfig.tiny(vocab_size=64),
            dit=QwenImageDiTConfig.tiny(),
            vae=VAEConfig.tiny(),
            prior_vocab=64,
            max_text_len=16,
            condition_dim=8,
        )


class GlmImagePipeline:
    """Text -> AR prior tokens -> prior-conditioned DiT -> image."""

    output_type = "image"
    config_cls = GlmImagePipelineConfig
    # every tree engine.sleep() must offload (both AR priors included)
    param_attrs = ("dit_params", "text_params", "vae_params",
                   "prior_params", "glm_params", "real_dit_params",
                   "t5_params", "prior_vlm_params")

    def __init__(self, config: GlmImagePipelineConfig, dtype=jnp.bfloat16,
                 seed: int = 0, mesh=None, cache_config=None):
        from vllm_omni_tpu.parallel.pipeline_mesh import MeshWiring

        self.cfg = config
        self.dtype = dtype
        self.mesh = mesh
        self.cache_config = cache_config
        self.wiring = MeshWiring(mesh, type(self).__name__).validate(
            {"dp"})
        if cache_config is not None:
            raise ValueError("GLM-Image has no step cache wiring yet")
        if config.text.hidden_size != config.dit.joint_dim:
            raise ValueError("text hidden_size must equal dit joint_dim")
        self.tokenizer = ByteTokenizer(config.text.vocab_size)
        ks = jax.random.split(jax.random.PRNGKey(seed), 6)
        logger.info("Initializing GlmImagePipeline (dtype=%s)", dtype)
        self.text_params = self.wiring.place(
            init_tfm_params(ks[0], config.text, dtype))
        self.prior_params = self.wiring.place(
            init_tfm_params(ks[1], config.prior_lm, dtype))
        self.dit_params = self.wiring.place(
            dit.init_params(ks[2], config.dit, dtype))
        # prior-token conditioning head (prior_token_embedding +
        # prior_projector, glm_image_transformer.py:678-683)
        kc1, kc2 = jax.random.split(jax.random.fold_in(ks[4], 7))
        self.glm_params = self.wiring.place({
            "prior_embed": nn.embedding_init(
                ks[3], config.prior_vocab, config.prior_lm.hidden_size,
                dtype),
            "prior_proj": nn.linear_init(
                ks[4], config.prior_lm.hidden_size, config.dit.inner_dim,
                dtype=dtype),
            # size/crop conditioning MLP into the timestep stream
            "cond_mlp1": nn.linear_init(
                kc1, 4 * config.condition_dim, config.dit.inner_dim,
                dtype=dtype),
            "cond_mlp2": nn.linear_init(
                kc2, config.dit.inner_dim, config.dit.inner_dim,
                dtype=dtype),
        })
        self.vae_params = self.wiring.place(
            vae_mod.init_decoder(ks[5], config.vae, dtype))
        self._denoise_cache: dict = {}
        self._prior_cache: dict = {}
        self._text_encode_jit = jax.jit(
            lambda p, i: forward_hidden(p, self.cfg.text, i))
        self._vae_decode_jit = jax.jit(
            lambda pp, l: vae_mod.decode(pp, self.cfg.vae, l))
        # real-weight path (from_pretrained): checkpoint-schema GLM DiT
        # + ByT5 glyph encoder (ckpt_transformer.py)
        self.real_dit_params = None
        self.real_dit_cfg = None
        self.t5_params = None
        self.t5_cfg = None
        self._t5_encode_jit = None
        self.hf_tokenizer = None
        # real AR prior VLM (vision_language_encoder/, prior.py); its
        # param tree lives in a param_attrs slot so sleep() offloads it
        self.prior_vlm = None
        self.prior_vlm_params = None

    @classmethod
    def from_pretrained(cls, model_dir: str, dtype=jnp.bfloat16,
                        seed: int = 0, mesh=None, cache_config=None,
                        max_text_len: int = 512):
        """Build from a diffusers-format GLM-Image checkpoint
        (transformer/ + ByT5 text_encoder/ + tokenizer/ + AutoencoderKL
        vae/ + scheduler/ + the vision_language_encoder/ AR prior VLM,
        whose in-pipeline rollout generates prior tokens — prior.py)."""
        import json as _json
        import os

        from transformers import AutoTokenizer

        from vllm_omni_tpu.model_loader import diffusers_loader as dl
        from vllm_omni_tpu.models.common import t5 as t5_mod
        from vllm_omni_tpu.models.glm_image import loader as gloader

        dl.load_model_index(model_dir)
        tdir = os.path.join(model_dir, "transformer")
        real_params, real_cfg = gloader.load_glm_dit(tdir, dtype=dtype)
        te = os.path.join(model_dir, "text_encoder")
        with open(os.path.join(te, "config.json")) as f:
            t5_cfg = t5_mod.T5Config.from_hf(_json.load(f))
        t5_params, _ = t5_mod.load_t5(te, cfg=t5_cfg, dtype=dtype)
        vae_tree, vae_cfg = dl.load_image_vae(
            os.path.join(model_dir, "vae"), dtype=dtype, decoder=True)
        import dataclasses

        # tiny stand-in text/dit/prior trees satisfy the random-init
        # invariants; the real path never touches them (the in-tree AR
        # prior stays available as the fallback prior generator)
        config = dataclasses.replace(
            GlmImagePipelineConfig.tiny(),
            vae=vae_cfg, max_text_len=max_text_len,
            condition_dim=real_cfg.condition_dim,
            prior_vocab=real_cfg.prior_vocab)
        pipe = cls(config, dtype=dtype, seed=seed, mesh=mesh,
                   cache_config=cache_config)
        pipe.real_dit_params = pipe.wiring.place(real_params)
        pipe.real_dit_cfg = real_cfg
        pipe.t5_params = pipe.wiring.place(t5_params)
        pipe.t5_cfg = t5_cfg
        # jitted ONCE (a per-request jax.jit(lambda) would retrace and
        # recompile the glyph encoder every call)
        pipe._t5_encode_jit = jax.jit(
            lambda p, i, m: t5_mod.forward(p, t5_cfg, i, m))
        sched = dl.scheduler_config(model_dir)
        pipe.shift = sched.get("shift", 1.0)
        pipe.vae_params = pipe.wiring.place(vae_tree["decoder"])
        pipe.hf_tokenizer = AutoTokenizer.from_pretrained(
            os.path.join(model_dir, "tokenizer"))
        vle = os.path.join(model_dir, "vision_language_encoder")
        if os.path.isdir(vle):
            from vllm_omni_tpu.models.glm_image.prior import (
                GlmImagePrior,
                load_glm_prior,
            )

            # the prior's LM tokenizer is its own (the reference loads
            # a GlmImageProcessor from processor/; model_dir/tokenizer
            # is the ByT5 GLYPH tokenizer) — probe the plausible homes
            ptok = None
            for sub in ("processor", "vision_language_encoder"):
                tdir = os.path.join(model_dir, sub)
                try:
                    ptok = AutoTokenizer.from_pretrained(tdir)
                    break
                except Exception:
                    continue
            # LM only: the t2i rollout is text-only; the 24-block
            # vision tower stays on disk until a condition-image
            # request needs it (GlmImagePrior.load_vision)
            prior_params, prior_cfg = load_glm_prior(vle, dtype=dtype,
                                                     vision=False)
            if prior_cfg.image_vocab != real_cfg.prior_vocab:
                # fail at LOAD, not after a per-request AR rollout
                raise ValueError(
                    f"prior image_vocab {prior_cfg.image_vocab} != DiT "
                    f"prior_vocab {real_cfg.prior_vocab} — mismatched "
                    "checkpoint components")
            pipe.prior_vlm = GlmImagePrior(None, prior_cfg,
                                           tokenizer=ptok,
                                           model_dir=vle)
            pipe.prior_vlm_params = pipe.wiring.place(prior_params)
            if ptok is None:
                logger.warning(
                    "GLM-Image AR prior loaded but no prior tokenizer "
                    "found (processor/ or vision_language_encoder/): "
                    "in-pipeline rollout unavailable — pass "
                    "sampling_params.extra['prior_token_ids']")
        else:
            logger.warning(
                "GLM-Image checkpoint has no vision_language_encoder/: "
                "pass sampling_params.extra['prior_token_ids'] or the "
                "random-init prior runs")
        return pipe

    @property
    def geometry_multiple(self) -> int:
        patch = (self.real_dit_cfg.patch_size
                 if self.real_dit_cfg is not None
                 else self.cfg.dit.patch_size)
        return self.cfg.vae.spatial_ratio * patch

    @staticmethod
    def upsample_prior_ids(ids, h: int, w: int):
        """2x nearest-neighbour upsample of a token grid (reference
        _upsample_token_ids: the AR prior generates at the d32 grid,
        the DiT conditions at d64)."""
        b = ids.shape[0]
        g = ids.reshape(b, h, w)
        g = jnp.repeat(jnp.repeat(g, 2, axis=1), 2, axis=2)
        return g.reshape(b, 4 * h * w)

    # -------------------------------------------------------- AR prior
    def _prior_fn(self, n_tokens: int):
        """Greedy AR generation of ``n_tokens`` prior ids under one
        jitted scan (full-recompute per token — the serving-scale
        version rides the AR engine's paged cache; this is the
        self-contained pipeline path).  Cached per n_tokens: a fresh
        jax.jit per request would recompile every call."""
        if n_tokens in self._prior_cache:
            return self._prior_cache[n_tokens]
        cfg = self.cfg.prior_lm

        @jax.jit
        def gen(params, seed_ids):
            b = seed_ids.shape[0]
            buf = jnp.zeros((b, seed_ids.shape[1] + n_tokens), jnp.int32)
            buf = buf.at[:, : seed_ids.shape[1]].set(seed_ids)

            def step(i, buf):
                hidden = forward_hidden(params, cfg, buf)
                pos = seed_ids.shape[1] + i - 1
                logits = logits_from_hidden(params, cfg,
                                            hidden[:, pos])
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return buf.at[:, pos + 1].set(
                    nxt % self.cfg.prior_vocab)

            buf = jax.lax.fori_loop(0, n_tokens, step, buf)
            return buf[:, seed_ids.shape[1]:]

        self._prior_cache[n_tokens] = gen
        return gen

    # --------------------------------------------------------- denoise
    def _denoise_fn(self, grid_h, grid_w, sched_len):
        key = (grid_h, grid_w, sched_len)
        if key in self._denoise_cache:
            return self._denoise_cache[key]
        cfg = self.cfg

        cdim = cfg.condition_dim

        @jax.jit
        def run(dit_params, glm_params, latents, txt, txt_mask,
                prior_ids, cond_vals, sigmas, timesteps, gscale,
                num_steps):
            schedule = fm.FlowMatchSchedule(sigmas=sigmas,
                                            timesteps=timesteps)
            b = latents.shape[0]
            # prior-drop CFG: conditional + prior-dropped rows in one
            # doubled batch (prior_token_drop semantics)
            txt2 = jnp.concatenate([txt, txt], 0)
            mask2 = jnp.concatenate([txt_mask, txt_mask], 0)
            pe = nn.embedding(glm_params["prior_embed"], prior_ids)
            prior_tok = nn.linear(glm_params["prior_proj"], pe)
            prior2 = jnp.concatenate(
                [prior_tok, jnp.zeros_like(prior_tok)], 0)
            # SDXL-like conditioning: sinusoid embeds of [target_h,
            # target_w, crop_top, crop_left] pooled into the timestep
            # stream (GlmImageCombinedTimestepSizeEmbeddings)
            sin = jnp.concatenate(
                [nn.timestep_embedding(cond_vals[:, i], cdim)
                 for i in range(4)], axis=-1)
            cond = nn.linear(glm_params["cond_mlp2"], jax.nn.silu(
                nn.linear(glm_params["cond_mlp1"],
                          sin.astype(prior_tok.dtype))))
            cond2 = jnp.concatenate([cond, cond], 0)

            def body(i, lat):
                t = jnp.broadcast_to(timesteps[i], (2 * b,))
                lat_in = jnp.concatenate([lat, lat], 0)
                img, txt_i, temb_act, img_f, txt_f, kv_mask = \
                    dit.forward_prefix(
                        dit_params, cfg.dit, lat_in, txt2, t,
                        (grid_h, grid_w), txt_mask=mask2)
                temb_act = temb_act + cond2.astype(temb_act.dtype)
                # GLM conditioning: prior tokens ADD into the image
                # stream before the blocks
                img = img + prior2.astype(img.dtype)
                for blk in dit_params["blocks"]:
                    img, txt_i = dit.block_forward(
                        blk, cfg.dit, img, txt_i, temb_act, img_f,
                        txt_f, None, kv_mask)
                v = dit.forward_suffix(dit_params, img, temb_act)
                v_c, v_u = jnp.split(v, 2, axis=0)
                v = v_u + gscale * (v_c - v_u)
                return fm.step(schedule, lat, v, i)

            return jax.lax.fori_loop(0, num_steps, body, latents)

        self._denoise_cache[key] = run
        return run

    def _real_denoise_fn(self, grid_h, grid_w, sched_len):
        key = ("real", grid_h, grid_w, sched_len)
        if key in self._denoise_cache:
            return self._denoise_cache[key]
        from vllm_omni_tpu.models.glm_image import ckpt_transformer as gd

        rcfg = self.real_dit_cfg

        @jax.jit
        def run(dit_params, latents, txt, txt_mask, prior_ids,
                cond_vals, sigmas, timesteps, gscale, num_steps):
            schedule = fm.FlowMatchSchedule(sigmas=sigmas,
                                            timesteps=timesteps)
            b = latents.shape[0]
            txt2 = jnp.concatenate([txt, txt], 0)
            mask2 = jnp.concatenate([txt_mask, txt_mask], 0)
            prior2 = jnp.concatenate([prior_ids, prior_ids], 0)
            # prior-drop CFG: the unconditional half drops the prior
            drop2 = jnp.concatenate(
                [jnp.zeros((b,), bool), jnp.ones((b,), bool)], 0)
            cond2 = jnp.concatenate([cond_vals, cond_vals], 0)

            def body(i, lat):
                t = jnp.broadcast_to(timesteps[i], (2 * b,))
                lat_in = jnp.concatenate([lat, lat], 0)
                v = gd.forward(
                    dit_params, rcfg, lat_in, txt2, prior2, drop2, t,
                    cond2, (grid_h, grid_w), txt_mask=mask2)
                v_c, v_u = jnp.split(v, 2, axis=0)
                v = v_u + gscale * (v_c - v_u)
                return fm.step(schedule, lat, v, i)

            return jax.lax.fori_loop(0, num_steps, body, latents)

        self._denoise_cache[key] = run
        return run

    def forward(self, req: OmniDiffusionRequest) -> list[DiffusionOutput]:
        sp = req.sampling_params
        cfg = self.cfg
        mult = self.geometry_multiple
        if sp.height % mult or sp.width % mult:
            raise InvalidRequestError(
                f"height/width must be multiples of {mult}")
        grid_h = sp.height // mult
        grid_w = sp.width // mult
        seq_len = grid_h * grid_w
        prompts = req.prompt
        b = len(prompts)

        if self.t5_params is not None:
            # real path: ByT5 glyph encoder with the HF tokenizer
            enc = self.hf_tokenizer(
                list(prompts), padding="max_length", truncation=True,
                max_length=cfg.max_text_len)
            ids = np.asarray(enc["input_ids"], np.int32)
            mask = jnp.asarray(np.asarray(enc["attention_mask"],
                                          np.int32))
            txt = self._t5_encode_jit(self.t5_params, jnp.asarray(ids),
                                      mask)
        else:
            ids, lens = self.tokenizer.batch_encode(prompts,
                                                    cfg.max_text_len)
            txt = self._text_encode_jit(self.text_params,
                                        jnp.asarray(ids))
            mask = jnp.asarray(
                (np.arange(cfg.max_text_len)[None, :]
                 < lens[:, None]).astype(np.int32))

        # stage 1: AR prior tokens — precomputed ids win; else the real
        # prior VLM rolls out in-pipeline (prior.py) at the HALF (d32)
        # grid and 2x nearest-upsamples to the DiT grid (reference
        # generate_prior_tokens + _upsample_token_ids); checkpoints
        # without a prior stage (and odd grids) use the random fallback
        pre = (sp.extra or {}).get("prior_token_ids") \
            if hasattr(sp, "extra") else None
        if pre is not None:
            pre_np = np.asarray(pre, np.int32)
            vocab = (self.real_dit_cfg.prior_vocab
                     if self.real_dit_cfg is not None
                     else cfg.prior_vocab)
            if pre_np.min() < 0 or pre_np.max() >= vocab:
                # XLA would silently clamp out-of-range gather indices —
                # wrong conditioning with no error
                raise InvalidRequestError(
                    f"prior_token_ids out of range [0, {vocab})")
            prior_ids = jnp.asarray(pre_np)
            if prior_ids.ndim == 1:
                prior_ids = jnp.broadcast_to(prior_ids[None],
                                             (b, prior_ids.shape[0]))
            if prior_ids.shape != (b, seq_len):
                raise InvalidRequestError(
                    f"prior_token_ids must be [B, {seq_len}] at the DiT "
                    f"grid; got {tuple(prior_ids.shape)}")
        elif (self.prior_vlm is not None
              and self.prior_vlm.tokenizer is not None):
            # real AR prior VLM in-pipeline (reference
            # generate_prior_tokens, pipeline_glm_image.py:434-525):
            # rollout at the d32 grid (half the d16 DiT grid), 2x
            # nearest-upsample; ODD DiT grids roll out at full res and
            # skip the upsample (still the real prior — never the
            # random fallback)
            if grid_h % 2 == 0 and grid_w % 2 == 0:
                ph, pw, up2 = grid_h // 2, grid_w // 2, True
            else:
                ph, pw, up2 = grid_h, grid_w, False
            extra = sp.extra if hasattr(sp, "extra") and sp.extra else {}
            temp = float(extra.get("prior_temperature", 0.0))
            base_seed = sp.seed if sp.seed is not None else 0
            rows = self.prior_vlm.generate_prior_tokens_batch(
                list(prompts), ph, pw, temperature=temp,
                seed=base_seed, params=self.prior_vlm_params)
            small = jnp.asarray(np.stack(rows), jnp.int32)
            prior_ids = (self.upsample_prior_ids(small, ph, pw)
                         if up2 else small)
        else:
            seed_ids = jnp.asarray(
                np.asarray(ids)[:, :8] % cfg.prior_lm.vocab_size,
                jnp.int32)
            if grid_h % 2 == 0 and grid_w % 2 == 0:
                ph, pw = grid_h // 2, grid_w // 2
                small = self._prior_fn(ph * pw)(self.prior_params,
                                                seed_ids)
                prior_ids = self.upsample_prior_ids(small, ph, pw)
            else:
                prior_ids = self._prior_fn(seq_len)(self.prior_params,
                                                    seed_ids)
            if self.real_dit_params is not None:
                logger.warning(
                    "GLM-Image real-weight run without "
                    "prior_token_ids: using the random-init AR prior")
            prior_ids = prior_ids % (
                self.real_dit_cfg.prior_vocab
                if self.real_dit_cfg is not None else cfg.prior_vocab)

        steps = max(1, sp.num_inference_steps)
        sched_len = max(steps, cfg.steps_bucket)
        schedule = fm.make_schedule(steps,
                                    shift=getattr(self, "shift", 1.0))
        sigmas = jnp.zeros((sched_len + 1,)).at[: steps + 1].set(
            schedule.sigmas)
        timesteps = jnp.zeros((sched_len,)).at[:steps].set(
            schedule.timesteps)

        seed = (sp.seed if sp.seed is not None
                else int(np.random.randint(0, 2 ** 31 - 1)))
        in_ch = (self.real_dit_cfg.patch_size ** 2
                 * self.real_dit_cfg.in_channels
                 if self.real_dit_cfg is not None
                 else cfg.dit.in_channels)
        noise = jax.random.normal(
            jax.random.PRNGKey(seed),
            (b, seq_len, in_ch), jnp.float32,
        ).astype(self.dtype)

        crop = sp.extra.get("crop_coords", (0, 0)) \
            if hasattr(sp, "extra") and sp.extra else (0, 0)
        cond_vals = jnp.asarray(
            np.broadcast_to(np.array(
                [sp.height, sp.width, crop[0], crop[1]], np.float32),
                (b, 4)))
        if self.real_dit_params is not None:
            run = self._real_denoise_fn(grid_h, grid_w, sched_len)
            latents = run(self.real_dit_params, noise, txt, mask,
                          prior_ids, cond_vals, sigmas, timesteps,
                          jnp.float32(sp.guidance_scale),
                          jnp.int32(steps))
        else:
            run = self._denoise_fn(grid_h, grid_w, sched_len)
            latents = run(self.dit_params, self.glm_params, noise, txt,
                          mask, prior_ids, cond_vals, sigmas, timesteps,
                          jnp.float32(sp.guidance_scale),
                          jnp.int32(steps))

        p = (self.real_dit_cfg.patch_size
             if self.real_dit_cfg is not None else cfg.dit.patch_size)
        c = cfg.vae.latent_channels
        x = latents.reshape(b, grid_h, grid_w, p, p, c)
        x = x.transpose(0, 1, 3, 2, 4, 5).reshape(
            b, grid_h * p, grid_w * p, c)
        img = self._vae_decode_jit(self.vae_params, x.astype(jnp.float32))
        img = np.asarray(jnp.clip(
            (img.astype(jnp.float32) + 1.0) * 127.5, 0, 255)
            .astype(jnp.uint8))
        return [
            DiffusionOutput(request_id=req.request_ids[i],
                            prompt=prompts[i], data=img[i],
                            output_type="image")
            for i in range(b)
        ]
