"""GLM-Image AR prior VLM (``vision_language_encoder/``) — the model that
generates ``prior_token_ids`` in-pipeline.

Role (reference: vllm_omni/diffusion/models/glm_image/
pipeline_glm_image.py:285 loads ``GlmImageForConditionalGeneration``;
:434-525 ``generate_prior_tokens`` runs a chat-templated AR rollout and
extracts the target image-token grid).  The class itself is absent from
the installed transformers (4.57.6), so this module implements it from
the checkpoint schema: the trunk is GLM-4.1V — transformers
``Glm4vForConditionalGeneration``, which IS installed and serves as the
torch parity oracle (tests/model_loader/test_glm_prior_parity.py) — and
the image-token machinery follows the reference pipeline's observable
usage:

- image tokens live in the LM vocabulary: ``generate()`` output ids are
  sliced directly into prior tokens (pipeline_glm_image.py:414-421), so
  the LM emits them natively; generation is constrained to the image-id
  range and ids re-base by ``image_start_id`` before the DiT consumes
  them (the DiT's prior embedding covers ``[0, prior_vocab)``,
  glm_image_transformer.py prior_token_embedding);
- text-to-image generates a small preview grid before the full target
  grid (``_compute_generation_params``: t2i's target grid is FIRST in
  ``image_grid_thw`` and extraction offsets past ``sum(grid_sizes[1:])``
  preview tokens);
- condition images map to prior ids via the vision tower + a codebook
  nearest-neighbour (``get_image_features(...).pooler_output`` ->
  ``get_image_tokens``, pipeline_glm_image.py:492-509): the codebook is
  the image-id block of the LM embedding matrix.

TPU-first shape: one jitted KV-cached rollout (``lax.fori_loop`` over a
static token budget, dense single-query attention over a preallocated
cache) instead of HF ``generate``'s Python loop; the vision tower is a
flat-patch matmul pipeline with the bicubic position-embedding resample
implemented as a separable cubic-convolution gather (torch
``grid_sample(mode="bicubic", align_corners=False, padding_mode=
"border")`` semantics, parity-tested).

Deliberate deviations from the unobservable parts (disclosed):
- the chat template is the checkpoint tokenizer's own
  (``apply_chat_template``) or a plain-prompt fallback — the reference's
  ``GlmImageProcessor`` subfolder template is not re-derivable from
  code;
- rollout positions follow the Qwen2-VL/GLM-4.1V ``get_rope_index``
  convention (text 1-D; each image grid a 3-D block whose t/h/w streams
  start where the previous segment ended);
- the reference generates one trailing token after the target grid
  (``max_new_tokens = total + 1``) that extraction always discards; the
  rollout here simply stops at the grid boundary.
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.models.common import nn
from vllm_omni_tpu.ops import flash_attention, rms_norm

logger = init_logger(__name__)


# ------------------------------------------------------------------ configs
@dataclass(frozen=True)
class GlmPriorTextConfig:
    """GLM-4.1V text trunk (transformers Glm4vTextConfig schema)."""

    vocab_size: int = 151552
    hidden_size: int = 4096
    num_layers: int = 40
    num_heads: int = 32
    num_kv_heads: int = 2
    head_dim: int = 128
    intermediate_size: int = 13696
    rope_theta: float = 10000.0
    rms_eps: float = 1e-5
    # 3-D mrope channel split; sum * 2 = rotary dim (partial rotary:
    # GLM-4.1V ships [8, 12, 12] -> 64 of 128 dims rotate)
    mrope_section: tuple = (8, 12, 12)

    @property
    def rotary_dim(self) -> int:
        return 2 * sum(self.mrope_section)


@dataclass(frozen=True)
class GlmPriorVisionConfig:
    """GLM-4.1V vision tower (transformers Glm4vVisionConfig schema)."""

    hidden_size: int = 1536
    depth: int = 24
    num_heads: int = 12
    patch_size: int = 14
    temporal_patch_size: int = 1
    in_channels: int = 3
    out_hidden_size: int = 4096
    intermediate_size: int = 13696
    spatial_merge_size: int = 2
    image_size: int = 336  # native pos-embed grid = image_size//patch
    rms_eps: float = 1e-5

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def pos_grid(self) -> int:
        return self.image_size // self.patch_size


@dataclass(frozen=True)
class GlmPriorConfig:
    text: GlmPriorTextConfig = dataclasses.field(
        default_factory=GlmPriorTextConfig)
    vision: Optional[GlmPriorVisionConfig] = dataclasses.field(
        default_factory=GlmPriorVisionConfig)
    # image tokens occupy [image_start_id, image_start_id + image_vocab)
    # of the LM vocabulary; generated ids re-base by image_start_id
    image_start_id: int = 135168  # 151552 - 16384: trailing vocab block
    image_vocab: int = 16384

    @staticmethod
    def from_hf(d: dict) -> "GlmPriorConfig":
        td = d.get("text_config", d)
        rope = td.get("rope_scaling") or {}
        head_dim = td.get("head_dim") or (
            td["hidden_size"] // td["num_attention_heads"])
        sections = rope.get("mrope_section")
        if sections is None:
            # GLM-4 partial rotary 0.5 proportioned like GLM-4.1V's
            # published [8, 12, 12] for head_dim 128
            sections = (head_dim // 16, 3 * head_dim // 32,
                        3 * head_dim // 32)
        text = GlmPriorTextConfig(
            vocab_size=td.get("vocab_size", 151552),
            hidden_size=td["hidden_size"],
            num_layers=td.get("num_hidden_layers", 40),
            num_heads=td.get("num_attention_heads", 32),
            num_kv_heads=td.get("num_key_value_heads", 2),
            head_dim=head_dim,
            intermediate_size=td.get("intermediate_size", 13696),
            rope_theta=td.get("rope_theta", 10000.0),
            rms_eps=td.get("rms_norm_eps", 1e-5),
            mrope_section=tuple(sections),
        )
        vision = None
        if "vision_config" in d:
            vd = d["vision_config"]
            vision = GlmPriorVisionConfig(
                hidden_size=vd.get("hidden_size", 1536),
                depth=vd.get("depth", 24),
                num_heads=vd.get("num_heads", 12),
                patch_size=vd.get("patch_size", 14),
                temporal_patch_size=vd.get("temporal_patch_size", 1),
                in_channels=vd.get("in_channels", 3),
                out_hidden_size=vd.get("out_hidden_size", 4096),
                intermediate_size=vd.get("intermediate_size", 13696),
                spatial_merge_size=vd.get("spatial_merge_size", 2),
                image_size=vd.get("image_size", 336),
                rms_eps=vd.get("rms_norm_eps", 1e-5),
            )
        vocab = text.vocab_size
        image_vocab = (d.get("image_vocab_size")
                       or d.get("prior_vq_quantizer_codebook_size")
                       or 16384)
        start = (d.get("image_start_token_id")
                 or d.get("image_token_start_id"))
        if start is None:
            start = vocab - image_vocab  # trailing block convention
        return GlmPriorConfig(text=text, vision=vision,
                              image_start_id=int(start),
                              image_vocab=int(image_vocab))

    @staticmethod
    def tiny() -> "GlmPriorConfig":
        # head_dim = hidden // heads (the torch oracle hardcodes it);
        # mrope_section sums to head_dim // 2 (full interleaved rotary,
        # the default partial_rotary_factor=1.0 oracle config)
        return GlmPriorConfig(
            text=GlmPriorTextConfig(
                vocab_size=256, hidden_size=64, num_layers=2,
                num_heads=4, num_kv_heads=2, head_dim=16,
                intermediate_size=96, mrope_section=(2, 3, 3)),
            vision=GlmPriorVisionConfig(
                hidden_size=32, depth=2, num_heads=4, patch_size=14,
                temporal_patch_size=1, in_channels=3,
                out_hidden_size=32, intermediate_size=64,
                spatial_merge_size=2, image_size=112),
            image_start_id=192, image_vocab=64)


# -------------------------------------------------------------------- init
def init_text_params(key, cfg: GlmPriorTextConfig, dtype=jnp.float32):
    ks = iter(jax.random.split(key, 4 + 10 * cfg.num_layers))
    d, hd = cfg.hidden_size, cfg.head_dim

    def lin(i, o, bias):
        return nn.linear_init(next(ks), i, o, bias=bias, dtype=dtype)

    layers = []
    for _ in range(cfg.num_layers):
        layers.append({
            "input_ln": {"w": jnp.ones((d,), dtype)},
            "q": lin(d, cfg.num_heads * hd, True),
            "k": lin(d, cfg.num_kv_heads * hd, True),
            "v": lin(d, cfg.num_kv_heads * hd, True),
            "o": lin(cfg.num_heads * hd, d, False),
            "post_self_attn_ln": {"w": jnp.ones((d,), dtype)},
            "post_attn_ln": {"w": jnp.ones((d,), dtype)},
            "gate_up": lin(d, 2 * cfg.intermediate_size, False),
            "down": lin(cfg.intermediate_size, d, False),
            "post_mlp_ln": {"w": jnp.ones((d,), dtype)},
        })
    return {
        "embed": nn.embedding_init(next(ks), cfg.vocab_size, d, dtype),
        "layers": layers,
        "final_norm": {"w": jnp.ones((d,), dtype)},
        "lm_head": lin(d, cfg.vocab_size, False),
    }


def init_vision_params(key, cfg: GlmPriorVisionConfig, dtype=jnp.float32):
    ks = iter(jax.random.split(key, 8 + 7 * cfg.depth))
    d = cfg.hidden_size
    patch_in = (cfg.in_channels * cfg.temporal_patch_size
                * cfg.patch_size ** 2)

    def lin(i, o, bias):
        return nn.linear_init(next(ks), i, o, bias=bias, dtype=dtype)

    blocks = []
    for _ in range(cfg.depth):
        blocks.append({
            "norm1": {"w": jnp.ones((d,), dtype)},
            "qkv": lin(d, 3 * d, False),
            "proj": lin(d, d, False),
            "norm2": {"w": jnp.ones((d,), dtype)},
            # Glm4VisionMlp: intermediate = out_hidden_size (schema quirk)
            "gate": lin(d, cfg.out_hidden_size, False),
            "up": lin(d, cfg.out_hidden_size, False),
            "down": lin(cfg.out_hidden_size, d, False),
        })
    m = cfg.spatial_merge_size
    oh = cfg.out_hidden_size
    return {
        "patch_proj": lin(patch_in, d, True),
        "pos_embed": (0.02 * jax.random.normal(
            next(ks), (cfg.pos_grid ** 2, d))).astype(dtype),
        "post_conv_norm": {"w": jnp.ones((d,), dtype)},
        "blocks": blocks,
        "post_norm": {"w": jnp.ones((d,), dtype)},
        "downsample": lin(d * m * m, oh, True),
        "merger": {
            "proj": lin(oh, oh, False),
            "ln": {"w": jnp.ones((oh,), dtype),
                   "b": jnp.zeros((oh,), dtype)},
            "gate": lin(oh, cfg.intermediate_size, False),
            "up": lin(oh, cfg.intermediate_size, False),
            "down": lin(cfg.intermediate_size, oh, False),
        },
    }


def init_params(key, cfg: GlmPriorConfig, dtype=jnp.float32):
    k1, k2 = jax.random.split(key)
    p = {"lm": init_text_params(k1, cfg.text, dtype)}
    if cfg.vision is not None:
        p["visual"] = init_vision_params(k2, cfg.vision, dtype)
    return p


# ------------------------------------------------------------- text trunk
def _rope_tables(cfg: GlmPriorTextConfig, positions):
    """positions [B, 3, S] -> (cos, sin) [B, 3, S, rotary_dim] (the
    pre-merge per-stream tables; transformers Glm4vTextRotaryEmbedding
    computes freqs then cat(freqs, freqs))."""
    n = sum(cfg.mrope_section)
    inv = 1.0 / (cfg.rope_theta ** (
        jnp.arange(0, n, dtype=jnp.float32) / n))
    freqs = positions.astype(jnp.float32)[..., None] * inv  # [B,3,S,n]
    emb = jnp.concatenate([freqs, freqs], axis=-1)
    return jnp.cos(emb), jnp.sin(emb)


def _merge_mrope(tab, sections):
    """[B, 3, S, 2n] per-stream table -> [B, S, 2n] merged (sections*2
    chunks pick stream i%3), then keep the first half and interleave-
    duplicate (apply_multimodal_rotary_pos_emb)."""
    n = sum(sections)
    widths = list(sections) * 2
    parts, start = [], 0
    for i, w in enumerate(widths):
        parts.append(tab[:, i % 3, :, start:start + w])
        start += w
    merged = jnp.concatenate(parts, axis=-1)[..., :n]
    return jnp.repeat(merged, 2, axis=-1)  # [B, S, 2n]


def _rotate_interleaved(x):
    """rotate_half_llm: pairs (x0, x1) -> (-x1, x0), interleaved."""
    x1 = x[..., 0::2]
    x2 = x[..., 1::2]
    return jnp.stack([-x2, x1], axis=-1).reshape(x.shape)


def _apply_mrope(q, k, cos, sin, sections):
    """q [B, S, H, hd], cos/sin [B, 3, S, 2n] -> partial interleaved
    rotation of the first 2n dims."""
    rot = 2 * sum(sections)
    mc = _merge_mrope(cos, sections)[:, :, None, :]  # [B,S,1,2n]
    ms = _merge_mrope(sin, sections)[:, :, None, :]

    def rotate(x):
        xr, xp = x[..., :rot], x[..., rot:]
        xr = xr * mc + _rotate_interleaved(xr) * ms
        return jnp.concatenate([xr, xp], axis=-1).astype(x.dtype)

    return rotate(q.astype(jnp.float32)), rotate(k.astype(jnp.float32))


def _text_layer(lp, cfg: GlmPriorTextConfig, x, cos, sin, attend):
    """One GLM sandwich-norm decoder layer (Glm4vTextDecoderLayer:
    input_ln -> attn -> post_self_attn_ln -> +res; post_attn_ln -> MLP
    -> post_mlp_ln -> +res; fused gate_up with silu)."""
    b, s, _ = x.shape
    hd = cfg.head_dim
    h = rms_norm(x, lp["input_ln"]["w"], cfg.rms_eps)
    q = nn.linear(lp["q"], h).reshape(b, s, cfg.num_heads, hd)
    k = nn.linear(lp["k"], h).reshape(b, s, cfg.num_kv_heads, hd)
    v = nn.linear(lp["v"], h).reshape(b, s, cfg.num_kv_heads, hd)
    q, k = _apply_mrope(q, k, cos, sin, cfg.mrope_section)
    o = attend(q, k, v).reshape(b, s, cfg.num_heads * hd)
    o = nn.linear(lp["o"], o)
    o = rms_norm(o, lp["post_self_attn_ln"]["w"], cfg.rms_eps)
    x = x + o
    h = rms_norm(x, lp["post_attn_ln"]["w"], cfg.rms_eps)
    gate, up = jnp.split(nn.linear(lp["gate_up"], h), 2, axis=-1)
    mlp = nn.linear(lp["down"], up * jax.nn.silu(gate))
    mlp = rms_norm(mlp, lp["post_mlp_ln"]["w"], cfg.rms_eps)
    return x + mlp


def text_forward_hidden(params, cfg: GlmPriorTextConfig, inputs,
                        positions):
    """Full-sequence causal forward.  ``inputs``: ids [B, S] or embeds
    [B, S, D]; ``positions`` [B, 3, S].  Returns final-norm hidden."""
    x = (nn.embedding(params["embed"], inputs)
         if inputs.ndim == 2 else inputs)
    cos, sin = _rope_tables(cfg, positions)

    def attend(q, k, v):
        return flash_attention(q, k, v, causal=True)

    for lp in params["layers"]:
        x = _text_layer(lp, cfg, x, cos, sin, attend)
    return rms_norm(x, params["final_norm"]["w"], cfg.rms_eps)


def lm_logits(params, hidden):
    return nn.linear(params["lm_head"], hidden)


# ---------------------------------------------------------- vision trunk
def _cubic_kernel(x):
    """torch bicubic convolution kernel (A = -0.75)."""
    a = -0.75
    ax = jnp.abs(x)
    return jnp.where(
        ax <= 1, ((a + 2) * ax - (a + 3)) * ax * ax + 1,
        jnp.where(ax < 2, (((ax - 5) * ax + 8) * ax - 4) * a, 0.0))


def bicubic_sample(grid, ys, xs):
    """Sample ``grid`` [H, W, D] at continuous (ys, xs) [N] in INPUT
    pixel coordinates, bicubic with border padding — the exact math of
    torch ``grid_sample(mode="bicubic", align_corners=False,
    padding_mode="border")`` after unnormalization."""
    h, w, _ = grid.shape
    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)
    fy = (ys - y0)[:, None]
    fx = (xs - x0)[:, None]
    offs = jnp.arange(-1, 3, dtype=jnp.float32)
    wy = _cubic_kernel(fy - offs)  # [N, 4]
    wx = _cubic_kernel(fx - offs)
    iy = jnp.clip(y0[:, None] + offs, 0, h - 1).astype(jnp.int32)
    ix = jnp.clip(x0[:, None] + offs, 0, w - 1).astype(jnp.int32)
    # [N,4,4,D] neighborhood gather, separable weights
    patch = grid[iy[:, :, None], ix[:, None, :]]
    return jnp.einsum("nijd,ni,nj->nd", patch.astype(jnp.float32),
                      wy, wx)


def _vision_pos_embed(pos_embed, cfg: GlmPriorVisionConfig, grid_h,
                      grid_w, h_coords, w_coords):
    """Glm4vVisionEmbeddings: resample the native pos-embed grid to the
    actual patch grid with bicubic interpolation at patch centers."""
    g = cfg.pos_grid
    table = pos_embed.reshape(g, g, -1)
    ys = (h_coords.astype(jnp.float32) + 0.5) / grid_h * g - 0.5
    xs = (w_coords.astype(jnp.float32) + 0.5) / grid_w * g - 0.5
    return bicubic_sample(table, ys, xs)


def _window_coords(grid_h: int, grid_w: int, merge: int):
    """Patch (h, w) coordinates in spatial-merge-window order (the
    processor's patch packing; Glm4vVisionModel.rot_pos_emb)."""
    hh = np.arange(grid_h)[:, None] * np.ones((1, grid_w), np.int32)
    ww = np.ones((grid_h, 1), np.int32) * np.arange(grid_w)[None, :]

    def windowed(m2d):
        return (m2d.reshape(grid_h // merge, merge, grid_w // merge,
                            merge)
                .transpose(0, 2, 1, 3).reshape(-1))

    return windowed(hh), windowed(ww)


def vision_forward(params, cfg: GlmPriorVisionConfig, patches,
                   grid_h: int, grid_w: int):
    """One image's flat patches [S, in*tps*ps*ps] (merge-window order)
    -> merged features [S/merge^2, out_hidden].  Mirrors
    Glm4vVisionModel.forward for a single (t=1, h, w) grid."""
    m = cfg.spatial_merge_size
    hd = cfg.head_dim
    x = nn.linear(params["patch_proj"], patches)  # [S, D]
    x = rms_norm(x, params["post_conv_norm"]["w"], cfg.rms_eps)
    h_co, w_co = _window_coords(grid_h, grid_w, m)
    x = x + _vision_pos_embed(
        params["pos_embed"], cfg, grid_h, grid_w,
        jnp.asarray(h_co), jnp.asarray(w_co)).astype(x.dtype)

    # 2-axis rope at half head_dim each (Glm4vVisionRotaryEmbedding:
    # inv_freq over head_dim//2, h- and w-frequencies concatenated)
    dim = hd // 2
    inv = 1.0 / (10000.0 ** (
        jnp.arange(0, dim, 2, dtype=jnp.float32) / dim))
    fh = jnp.asarray(h_co, jnp.float32)[:, None] * inv
    fw = jnp.asarray(w_co, jnp.float32)[:, None] * inv
    emb = jnp.concatenate([fh, fw, fh, fw], axis=-1)  # [S, hd]
    cos, sin = jnp.cos(emb), jnp.sin(emb)

    def rot_half(t):
        t1, t2 = jnp.split(t, 2, axis=-1)
        return jnp.concatenate([-t2, t1], axis=-1)

    s = x.shape[0]
    for blk in params["blocks"]:
        h = rms_norm(x, blk["norm1"]["w"], cfg.rms_eps)
        qkv = nn.linear(blk["qkv"], h).reshape(s, 3, cfg.num_heads, hd)
        q, k, v = (qkv[:, 0], qkv[:, 1], qkv[:, 2])
        qf = q.astype(jnp.float32)
        kf = k.astype(jnp.float32)
        c4 = cos[:, None, :]
        s4 = sin[:, None, :]
        q = (qf * c4 + rot_half(qf) * s4).astype(x.dtype)
        k = (kf * c4 + rot_half(kf) * s4).astype(x.dtype)
        o = flash_attention(q[None], k[None], v[None], causal=False)
        x = x + nn.linear(blk["proj"], o[0].reshape(s, -1))
        h = rms_norm(x, blk["norm2"]["w"], cfg.rms_eps)
        x = x + nn.linear(blk["down"], jax.nn.silu(
            nn.linear(blk["gate"], h)) * nn.linear(blk["up"], h))

    x = rms_norm(x, params["post_norm"]["w"], cfg.rms_eps)
    # spatial-merge downsample: window [m, m, D] -> (D, m, m)-ordered
    # conv flatten (torch Conv2d stride=kernel) -> out_hidden
    x = x.reshape(-1, m, m, cfg.hidden_size).transpose(0, 3, 1, 2)
    x = nn.linear(params["downsample"], x.reshape(x.shape[0], -1))
    mg = params["merger"]
    x = nn.linear(mg["proj"], x)
    x = jax.nn.gelu(nn.layernorm(mg["ln"], x, eps=1e-5),
                    approximate=False)
    return nn.linear(mg["down"], jax.nn.silu(
        nn.linear(mg["gate"], x)) * nn.linear(mg["up"], x))


def get_image_tokens(params, cfg: GlmPriorConfig, feats):
    """Map pooled vision features [N, D] to prior ids [N] by nearest
    codebook row — the image-id block of the LM embedding matrix
    (reference get_image_tokens, pipeline_glm_image.py:496)."""
    book = jax.lax.dynamic_slice_in_dim(
        params["lm"]["embed"]["w"], cfg.image_start_id, cfg.image_vocab,
        axis=0).astype(jnp.float32)
    f = feats.astype(jnp.float32)
    # argmin ||f - c||^2 = argmax (f.c - ||c||^2 / 2)
    scores = f @ book.T - 0.5 * jnp.sum(book * book, axis=-1)[None, :]
    return jnp.argmax(scores, axis=-1).astype(jnp.int32)


# ------------------------------------------------------------ AR rollout
def _image_block_positions(start: int, h: int, w: int):
    """Qwen2-VL-convention 3-D positions for one h x w image grid whose
    streams start at ``start``: t constant, h by row, w by col."""
    t = np.full((h * w,), start, np.int32)
    hh = start + np.repeat(np.arange(h, dtype=np.int32), w)
    ww = start + np.tile(np.arange(w, dtype=np.int32), h)
    return np.stack([t, hh, ww]), start + max(h, w)


def rollout_positions(prompt_bucket: int, prompt_len: int,
                      grids: list) -> np.ndarray:
    """[3, prompt_bucket + sum(h*w)] positions: 1-D text (padding slots
    past ``prompt_len`` continue the arange — their K/V are masked out
    of every attention), then one 3-D block per generated grid starting
    where the REAL prompt ended."""
    segs = [np.broadcast_to(np.arange(prompt_bucket, dtype=np.int32),
                            (3, prompt_bucket))]
    nxt = prompt_len
    for h, w in grids:
        block, nxt = _image_block_positions(nxt, h, w)
        segs.append(block)
    return np.concatenate(segs, axis=1)


def make_generate(cfg: GlmPriorConfig, prompt_bucket: int, n_gen: int):
    """Jitted KV-cached greedy/sampled rollout of ``n_gen`` image tokens
    after a prefill of up to ``prompt_bucket`` prompt tokens (the REAL
    length rides in as the dynamic ``prompt_len`` — prompts right-pad to
    the bucket so novel lengths reuse one executable instead of paying a
    full-trunk recompile each).  Returns ids REBASED to [0, image_vocab)
    (logits are masked to the image-id range — the trunk was trained to
    emit image ids here; masking makes the guarantee structural)."""
    tcfg = cfg.text
    total = prompt_bucket + n_gen
    hd, kvh = tcfg.head_dim, tcfg.num_kv_heads

    @jax.jit
    def gen(params, prompt_ids, prompt_len, positions, temperature,
            key):
        lm = params["lm"]
        b = prompt_ids.shape[0]
        cos_all, sin_all = _rope_tables(
            cfg.text, jnp.broadcast_to(positions[None], (b, 3, total)))

        # ---- prefill: full causal forward, collecting per-layer K/V
        # (right-padding is invisible to real tokens under causality;
        # the pad slots' K/V are masked out of decode attention below)
        x = nn.embedding(lm["embed"], prompt_ids)
        cos_p = cos_all[:, :, :prompt_bucket]
        sin_p = sin_all[:, :, :prompt_bucket]
        caches_k, caches_v = [], []

        def attend_collect(q, k, v):
            kb = jnp.zeros((b, total, kvh, hd), q.dtype)
            vb = jnp.zeros((b, total, kvh, hd), q.dtype)
            caches_k.append(kb.at[:, :prompt_bucket].set(k))
            caches_v.append(vb.at[:, :prompt_bucket].set(v))
            return flash_attention(q, k, v, causal=True)

        for lp in lm["layers"]:
            x = _text_layer(lp, tcfg, x, cos_p, sin_p, attend_collect)
        x = rms_norm(x, lm["final_norm"]["w"], tcfg.rms_eps)
        k_cache = jnp.stack(caches_k)  # [L, B, T, kvh, hd]
        v_cache = jnp.stack(caches_v)

        lo = cfg.image_start_id
        allow = jnp.zeros((tcfg.vocab_size,), bool).at[
            lo:lo + cfg.image_vocab].set(True)

        def pick(logits, k):
            masked = jnp.where(allow[None, :], logits, -jnp.inf)
            greedy = jnp.argmax(masked, axis=-1)
            sampled = jax.random.categorical(
                k, masked / jnp.maximum(temperature, 1e-6))
            return jnp.where(temperature > 0, sampled,
                             greedy).astype(jnp.int32)

        key, sub = jax.random.split(key)
        # logits at the LAST REAL prompt token, not the padded tail
        x_last = jnp.take(x, prompt_len - 1, axis=1)
        first = pick(lm_logits(lm, x_last), sub)

        def step(i, carry):
            k_cache, v_cache, tok, out, kk = carry
            pos = prompt_bucket + i
            x = nn.embedding(lm["embed"], tok[:, None])  # [B,1,D]
            cos_i = jax.lax.dynamic_slice_in_dim(cos_all, pos, 1, axis=2)
            sin_i = jax.lax.dynamic_slice_in_dim(sin_all, pos, 1, axis=2)
            ar = jnp.arange(total)
            # real prompt + already-generated tokens; pad slots excluded
            valid = (ar < prompt_len) | ((ar >= prompt_bucket)
                                         & (ar <= pos))
            groups = tcfg.num_heads // kvh

            nk, nv = [], []

            def attend_cached(li):
                def attend(q, kq, vq):
                    # q [B,1,H,hd]; cache [B,T,kvh,hd] updated at pos
                    kc = jax.lax.dynamic_update_slice_in_dim(
                        k_cache[li], kq, pos, axis=1)
                    vc = jax.lax.dynamic_update_slice_in_dim(
                        v_cache[li], vq, pos, axis=1)
                    nk.append(kc)
                    nv.append(vc)
                    qh = q[:, 0].reshape(b, kvh, groups, hd)
                    s = jnp.einsum(
                        "bkgh,btkh->bkgt", qh.astype(jnp.float32),
                        kc.astype(jnp.float32)) / np.sqrt(hd)
                    s = jnp.where(valid[None, None, None, :], s,
                                  -jnp.inf)
                    p = jax.nn.softmax(s, axis=-1)
                    o = jnp.einsum("bkgt,btkh->bkgh", p,
                                   vc.astype(jnp.float32))
                    return o.reshape(b, 1, kvh * groups,
                                     hd).astype(q.dtype)

                return attend

            for li, lp in enumerate(lm["layers"]):
                x = _text_layer(lp, tcfg, x, cos_i, sin_i,
                                attend_cached(li))
            x = rms_norm(x, lm["final_norm"]["w"], tcfg.rms_eps)
            kk, sub = jax.random.split(kk)
            nxt_tok = pick(lm_logits(lm, x[:, -1]), sub)
            out = out.at[:, i].set(tok)
            return (jnp.stack(nk), jnp.stack(nv), nxt_tok, out, kk)

        out = jnp.zeros((b, n_gen), jnp.int32)
        _, _, _, out, _ = jax.lax.fori_loop(
            0, n_gen, step, (k_cache, v_cache, first, out, key))
        return out - lo  # rebase into [0, image_vocab)

    return gen


class GlmImagePrior:
    """The loaded prior VLM + its rollout entry point (the in-pipeline
    replacement for the reference's ``vision_language_encoder``).

    Params may live on the OWNER (the pipeline keeps the tree in a
    ``param_attrs`` slot so engine.sleep()/wake() can offload it) — the
    public methods accept an explicit ``params`` tree and fall back to
    the one given at construction."""

    def __init__(self, params, cfg: GlmPriorConfig, tokenizer=None,
                 model_dir: str = None):
        self.params = params
        self.cfg = cfg
        self.tokenizer = tokenizer
        self.model_dir = model_dir  # enables deferred vision load
        self._gen_cache: dict = {}
        self._vision_jit_cache: dict = {}

    @classmethod
    def from_pretrained(cls, model_dir: str, dtype=jnp.bfloat16,
                        tokenizer=None,
                        vision: bool = True) -> "GlmImagePrior":
        params, cfg = load_glm_prior(model_dir, dtype=dtype,
                                     vision=vision)
        return cls(params, cfg, tokenizer=tokenizer,
                   model_dir=model_dir)

    def load_vision(self, params=None, dtype=jnp.bfloat16):
        """Late-load the vision tower into a params tree that was built
        with ``vision=False`` (returns the updated tree — the caller
        owns placement)."""
        params = self.params if params is None else params
        if "visual" in params:
            return params
        if self.model_dir is None:
            raise RuntimeError("no model_dir recorded for deferred "
                               "vision load")
        full, _ = load_glm_prior(self.model_dir, cfg=self.cfg,
                                 dtype=dtype, vision=True)
        return {**params, "visual": full["visual"]}

    def encode_prompt(self, prompt: str) -> np.ndarray:
        """Chat-template the prompt when the tokenizer carries one
        (reference: processor.apply_chat_template,
        pipeline_glm_image.py:469); plain encode otherwise."""
        tok = self.tokenizer
        if tok is None:
            raise RuntimeError("prior rollout needs a tokenizer")
        if getattr(tok, "chat_template", None):
            ids = tok.apply_chat_template(
                [{"role": "user", "content": prompt}],
                add_generation_prompt=True)
            return np.asarray(ids, np.int32)
        return np.asarray(
            tok(prompt)["input_ids"], np.int32)

    def generate_prior_tokens(self, prompt: str, token_h: int,
                              token_w: int, temperature: float = 0.0,
                              seed: int = 0, params=None) -> np.ndarray:
        """Text-to-image rollout: a half-res preview grid then the
        target grid (reference _compute_generation_params t2i branch;
        odd grids skip the preview); returns the TARGET grid ids
        [token_h * token_w] in [0, image_vocab)."""
        return self.generate_prior_tokens_batch(
            [prompt], token_h, token_w, temperature=temperature,
            seed=seed, params=params)[0]

    def generate_prior_tokens_batch(self, prompts: list, token_h: int,
                                    token_w: int,
                                    temperature: float = 0.0,
                                    seed: int = 0,
                                    params=None) -> list:
        """Batched rollout: prompts sharing a length bucket stack into
        ONE gen() call (exact for greedy — the default; temperature>0
        keeps the per-prompt seed convention, so sampled rows run
        individually)."""
        params = self.params if params is None else params
        grids = []
        if token_h % 2 == 0 and token_w % 2 == 0:
            grids.append((token_h // 2, token_w // 2))
        grids.append((token_h, token_w))
        n_prev = sum(h * w for h, w in grids[:-1])
        n_gen = n_prev + token_h * token_w

        encoded = [np.asarray(self.encode_prompt(p), np.int32)
                   for p in prompts]
        # bucket prompts so novel lengths share one executable (the
        # 40-layer trunk recompiles cost minutes each otherwise)
        buckets = [max(32, -(-len(e) // 32) * 32) for e in encoded]

        def run(idx_group, bucket, run_seed):
            # gen() shares one dynamic prompt_len + positions array per
            # call, so stacked rows must agree on the REAL length
            # (callers group by it)
            rows = [encoded[i] for i in idx_group]
            b = len(rows)
            padded = np.zeros((b, bucket), np.int32)
            for j, r in enumerate(rows):
                padded[j, :len(r)] = r
            positions = rollout_positions(bucket, len(rows[0]), grids)
            key = (bucket, n_gen)
            if key not in self._gen_cache:
                self._gen_cache[key] = make_generate(
                    self.cfg, bucket, n_gen)
            out = self._gen_cache[key](
                params, jnp.asarray(padded),
                jnp.int32(len(rows[0])), jnp.asarray(positions),
                jnp.float32(temperature),
                jax.random.PRNGKey(run_seed))
            return np.asarray(out[:, n_prev:])

        results: list = [None] * len(prompts)
        if temperature > 0:
            # per-row seeds keep identical prompts from sampling
            # identical priors (the pipeline's seed+i convention)
            for i in range(len(prompts)):
                results[i] = run([i], buckets[i], seed + i)[0]
            return results
        # greedy: stack rows with the SAME real length (positions and
        # the dynamic prompt_len are shared per call)
        groups: dict = {}
        for i, e in enumerate(encoded):
            groups.setdefault((buckets[i], len(e)), []).append(i)
        for (bucket, _), idxs in groups.items():
            outs = run(idxs, bucket, seed)
            for j, i in enumerate(idxs):
                results[i] = outs[j]
        return results

    def condition_image_tokens(self, patches, grid_h: int,
                               grid_w: int, params=None) -> np.ndarray:
        """Condition-image path: vision tower -> codebook lookup
        (reference pipeline_glm_image.py:486-509), ids at the merged
        grid, in [0, image_vocab)."""
        params = self.params if params is None else params
        if self.cfg.vision is None:
            raise RuntimeError("checkpoint has no vision tower")
        if "visual" not in params:
            raise RuntimeError(
                "vision tower not loaded (deferred at from_pretrained) "
                "— call load_vision() and re-place the tree first")
        key = (grid_h, grid_w)
        if key not in self._vision_jit_cache:
            vcfg = self.cfg.vision

            @jax.jit
            def run(p, patches):
                feats = vision_forward(p["visual"], vcfg, patches,
                                       grid_h, grid_w)
                return get_image_tokens(p, self.cfg, feats)

            self._vision_jit_cache[key] = run
        return np.asarray(
            self._vision_jit_cache[key](params, patches))


# ------------------------------------------------------------------ loader
def _prior_routing(cfg: GlmPriorConfig, include_vision: bool) -> dict:
    routing = {}

    def lin(hf, *path, bias=True):
        routing[f"{hf}.weight"] = ("direct", (*path, "w"))
        if bias:
            routing[f"{hf}.bias"] = ("direct", (*path, "b"))

    t = cfg.text
    for i in range(t.num_layers):
        hf = f"model.language_model.layers.{i}"
        p = ("lm", "layers", i)
        lin(f"{hf}.self_attn.q_proj", *p, "q")
        lin(f"{hf}.self_attn.k_proj", *p, "k")
        lin(f"{hf}.self_attn.v_proj", *p, "v")
        lin(f"{hf}.self_attn.o_proj", *p, "o", bias=False)
        lin(f"{hf}.mlp.gate_up_proj", *p, "gate_up", bias=False)
        lin(f"{hf}.mlp.down_proj", *p, "down", bias=False)
        for hf_n, ours in (
                ("input_layernorm", "input_ln"),
                ("post_attention_layernorm", "post_attn_ln"),
                ("post_self_attn_layernorm", "post_self_attn_ln"),
                ("post_mlp_layernorm", "post_mlp_ln")):
            routing[f"{hf}.{hf_n}.weight"] = ("raw", (*p, ours, "w"))
    routing["model.language_model.embed_tokens.weight"] = (
        "raw", ("lm", "embed", "w"))
    routing["model.language_model.norm.weight"] = (
        "raw", ("lm", "final_norm", "w"))
    routing["lm_head.weight"] = ("direct", ("lm", "lm_head", "w"))

    if include_vision and cfg.vision is not None:
        v = cfg.vision
        for i in range(v.depth):
            hf = f"model.visual.blocks.{i}"
            p = ("visual", "blocks", i)
            lin(f"{hf}.attn.qkv", *p, "qkv", bias=False)
            lin(f"{hf}.attn.proj", *p, "proj", bias=False)
            lin(f"{hf}.mlp.gate_proj", *p, "gate", bias=False)
            lin(f"{hf}.mlp.up_proj", *p, "up", bias=False)
            lin(f"{hf}.mlp.down_proj", *p, "down", bias=False)
            routing[f"{hf}.norm1.weight"] = ("raw", (*p, "norm1", "w"))
            routing[f"{hf}.norm2.weight"] = ("raw", (*p, "norm2", "w"))
        lin("model.visual.patch_embed.proj", "visual", "patch_proj")
        lin("model.visual.downsample", "visual", "downsample")
        routing["model.visual.embeddings.position_embedding.weight"] = (
            "raw", ("visual", "pos_embed"))
        for hf_n, ours in (("post_conv_layernorm", "post_conv_norm"),
                           ("post_layernorm", "post_norm")):
            routing[f"model.visual.{hf_n}.weight"] = (
                "raw", ("visual", ours, "w"))
        m = "model.visual.merger"
        lin(f"{m}.proj", "visual", "merger", "proj", bias=False)
        lin(f"{m}.gate_proj", "visual", "merger", "gate", bias=False)
        lin(f"{m}.up_proj", "visual", "merger", "up", bias=False)
        lin(f"{m}.down_proj", "visual", "merger", "down", bias=False)
        routing[f"{m}.post_projection_norm.weight"] = (
            "raw", ("visual", "merger", "ln", "w"))
        routing[f"{m}.post_projection_norm.bias"] = (
            "raw", ("visual", "merger", "ln", "b"))
    return routing


def load_glm_prior(model_dir: str, cfg: GlmPriorConfig = None,
                   dtype=jnp.bfloat16, vision: bool = True):
    """Load the AR prior from ``vision_language_encoder/`` at the
    published GLM-4.1V names (model.visual.* / model.language_model.* /
    lm_head).  ``vision=False`` loads the LM only — the t2i rollout is
    text-only, so the pipeline skips the 24-block tower's HBM until a
    condition-image request needs it (``GlmImagePrior.load_vision``)."""
    from vllm_omni_tpu.models.flux.loader import load_routed

    if cfg is None:
        with open(os.path.join(model_dir, "config.json")) as f:
            cfg = GlmPriorConfig.from_hf(json.load(f))
    include_vision = vision and cfg.vision is not None

    def build():
        p = {"lm": init_text_params(jax.random.PRNGKey(0), cfg.text,
                                    dtype)}
        if include_vision:
            p["visual"] = init_vision_params(
                jax.random.PRNGKey(0), cfg.vision, dtype)
        return p

    shapes = jax.eval_shape(build)

    transforms = {}
    if include_vision:
        def conv3d_flat(arr):  # [D, C, tps, ps, ps] -> [in, D]
            return np.ascontiguousarray(
                arr.reshape(arr.shape[0], -1).T)

        def conv2d_flat(arr):  # [out, D, m, m] -> [D*m*m, out]
            return np.ascontiguousarray(
                arr.reshape(arr.shape[0], -1).T)

        transforms["model.visual.patch_embed.proj.weight"] = conv3d_flat
        transforms["model.visual.downsample.weight"] = conv2d_flat

    params = load_routed(model_dir, _prior_routing(cfg, include_vision),
                         shapes, dtype, transforms=transforms)
    logger.info("loaded GLM-Image AR prior: %d-layer LM%s",
                cfg.text.num_layers,
                f" + {cfg.vision.depth}-block vision tower"
                if include_vision else " (vision tower deferred)")
    return params, cfg
