"""Diffusers-format GLM-Image transformer loader.

Checkpoint names per the reference module tree
(glm_image_transformer.py:594-616): ``image_projector.proj``,
``glyph_projector.net.{0.proj,2}``, ``prior_token_embedding``,
``prior_projector.net.{0.proj,2}``,
``time_condition_embed.{timestep,condition}_embedder.linear_{1,2}``,
per block ``norm1.linear`` (12-chunk AdaLN), fused-at-load
``attn1.{to_q,to_k,to_v}`` -> qkv, ``attn1.to_out.0``,
``ff.net.{0.proj,2}`` (shared by both streams), and
``norm_out.linear`` / ``proj_out``.

The patch projector consumes (c, dy, dx)-ordered features in the
reference (:48); rows permute to this repo's (dy, dx, c) packing at
load, and likewise ``proj_out`` columns.
"""

from __future__ import annotations

import json
import os

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.models.flux.loader import load_routed
from vllm_omni_tpu.models.glm_image.ckpt_transformer import (
    GlmDiTConfig,
    init_params,
)


def dit_config_from_diffusers(d: dict) -> GlmDiTConfig:
    in_ch = d.get("in_channels", 16)
    return GlmDiTConfig(
        patch_size=d.get("patch_size", 2),
        in_channels=in_ch,
        out_channels=d.get("out_channels") or in_ch,
        num_layers=d.get("num_layers", 30),
        num_heads=d.get("num_attention_heads", 64),
        head_dim=d.get("attention_head_dim", 40),
        time_embed_dim=d.get("time_embed_dim", 512),
        condition_dim=d.get("condition_dim", 256),
        text_embed_dim=d.get("text_embed_dim", 1472),
        prior_vocab=d.get("prior_vq_quantizer_codebook_size", 16384),
    )


def _routing(cfg: GlmDiTConfig) -> dict:
    r: dict[str, tuple] = {}

    def lin(hf, *path):
        r[f"{hf}.weight"] = ("direct", path + ("w",))
        r[f"{hf}.bias"] = ("direct", path + ("b",))

    def fuse(names, *path):
        for s, n in enumerate(names):
            r[f"{n}.weight"] = ("fuse", path + ("w",), s, len(names))
            r[f"{n}.bias"] = ("fuse", path + ("b",), s, len(names))

    lin("image_projector.proj", "image_proj")
    lin("glyph_projector.net.0.proj", "glyph1")
    lin("glyph_projector.net.2", "glyph2")
    r["prior_token_embedding.weight"] = ("raw", ("prior_embed", "w"))
    lin("prior_projector.net.0.proj", "prior1")
    lin("prior_projector.net.2", "prior2")
    lin("time_condition_embed.timestep_embedder.linear_1", "time_in1")
    lin("time_condition_embed.timestep_embedder.linear_2", "time_in2")
    lin("time_condition_embed.condition_embedder.linear_1", "cond_in1")
    lin("time_condition_embed.condition_embedder.linear_2", "cond_in2")
    lin("norm_out.linear", "norm_out_mod")
    lin("proj_out", "proj_out")
    for i in range(cfg.num_layers):
        b = f"transformer_blocks.{i}"
        t = ("blocks", i)
        lin(f"{b}.norm1.linear", *t, "ada")
        fuse([f"{b}.attn1.to_q", f"{b}.attn1.to_k", f"{b}.attn1.to_v"],
             *t, "qkv")
        lin(f"{b}.attn1.to_out.0", *t, "out")
        lin(f"{b}.ff.net.0.proj", *t, "mlp1")
        lin(f"{b}.ff.net.2", *t, "mlp2")
    return r


def _chan_perm(cfg: GlmDiTConfig, channels: int) -> np.ndarray:
    p = cfg.patch_size
    c = channels
    idx = np.arange(c * p * p).reshape(c, p, p)
    return idx.transpose(1, 2, 0).reshape(-1)


def load_glm_dit(model_dir: str, cfg: GlmDiTConfig = None,
                 dtype=jnp.bfloat16):
    if cfg is None:
        with open(os.path.join(model_dir, "config.json")) as f:
            cfg = dit_config_from_diffusers(json.load(f))
    shapes = jax.eval_shape(
        lambda: init_params(jax.random.PRNGKey(0), cfg, jnp.float32))
    perm_in = _chan_perm(cfg, cfg.in_channels)
    perm_out = _chan_perm(cfg, cfg.out_channels)

    def proj_in_t(arr):
        return np.ascontiguousarray(arr.T[perm_in])

    def proj_out_t(arr):
        return np.ascontiguousarray(arr.T[:, perm_out])

    def proj_out_bias_t(arr):
        return arr[perm_out]

    tree = load_routed(
        model_dir, _routing(cfg), shapes, dtype,
        transforms={"image_projector.proj.weight": proj_in_t,
                    "proj_out.weight": proj_out_t,
                    "proj_out.bias": proj_out_bias_t})
    return tree, cfg
