"""GLM-Image DiT at the real checkpoint schema (functional JAX).

Reference: vllm_omni/diffusion/models/glm_image/glm_image_transformer.py
:542 ``GlmImageTransformer2DModel`` — double-stream blocks with ONE
joint qkv over the concatenated [text, image] sequence, affine-free
LayerNorm QK-norm (eps 1e-5), 2-axis (row, col) half-split rope applied
to IMAGE tokens only (:52-89, apply_rotary_emb use_real_unbind_dim=-2),
a single 12-chunk AdaLayerNormZero whose linear consumes the RAW
timestep embedding (:91-138 — no silu), one SHARED feed-forward for
both streams (:472-473), glyph/prior projector FFs (:594-597), SDXL-like
size/crop conditioning summed into the timestep stream
(GlmImageCombinedTimestepSizeEmbeddings), an activation-free
AdaLayerNormContinuous output head (:140-161), and the prior-token
conditioning added to the image stream pre-blocks (:678-683, embedding
rows zeroed under prior-drop CFG BEFORE the biased projector).

The in-tree stand-in pipeline keeps the shared Qwen-Image MMDiT for
random-init runs; this module is the real-weight path
(``GlmImagePipeline.from_pretrained``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from vllm_omni_tpu.models.common import nn
from vllm_omni_tpu.ops import flash_attention


@dataclass(frozen=True)
class GlmDiTConfig:
    patch_size: int = 2
    in_channels: int = 16
    out_channels: int = 16
    num_layers: int = 30
    num_heads: int = 64
    head_dim: int = 40
    time_embed_dim: int = 512
    condition_dim: int = 256
    text_embed_dim: int = 1472   # ByT5 glyph encoder width
    prior_vocab: int = 16384
    theta: float = 10000.0
    mlp_ratio: float = 4.0
    eps: float = 1e-5

    @property
    def inner_dim(self) -> int:
        return self.num_heads * self.head_dim

    @staticmethod
    def tiny() -> "GlmDiTConfig":
        return GlmDiTConfig(
            in_channels=4, out_channels=4, num_layers=2, num_heads=4,
            head_dim=16, time_embed_dim=32, condition_dim=8,
            text_embed_dim=48, prior_vocab=64)


def init_params(key, cfg: GlmDiTConfig, dtype=jnp.float32):
    d = cfg.inner_dim
    mlp = int(d * cfg.mlp_ratio)
    te = cfg.time_embed_dim
    p_in = cfg.patch_size ** 2 * cfg.in_channels
    keys = jax.random.split(key, cfg.num_layers + 12)
    p = {
        "image_proj": nn.linear_init(keys[0], p_in, d, dtype=dtype),
        "glyph1": nn.linear_init(keys[1], cfg.text_embed_dim, d,
                                 dtype=dtype),
        "glyph2": nn.linear_init(keys[2], d, d, dtype=dtype),
        "prior_embed": nn.embedding_init(keys[3], cfg.prior_vocab, d,
                                         dtype),
        "prior1": nn.linear_init(keys[4], d, d, dtype=dtype),
        "prior2": nn.linear_init(keys[5], d, d, dtype=dtype),
        "time_in1": nn.linear_init(keys[6], 256, te, dtype=dtype),
        "time_in2": nn.linear_init(keys[7], te, te, dtype=dtype),
        "cond_in1": nn.linear_init(keys[8], 4 * cfg.condition_dim, te,
                                   dtype=dtype),
        "cond_in2": nn.linear_init(keys[9], te, te, dtype=dtype),
        "norm_out_mod": nn.linear_init(keys[10], te, 2 * d, dtype=dtype),
        "proj_out": nn.linear_init(
            keys[11], d, cfg.patch_size ** 2 * cfg.out_channels,
            dtype=dtype),
        "blocks": [],
    }
    for i in range(cfg.num_layers):
        k = jax.random.split(keys[i + 12], 4)
        p["blocks"].append({
            "ada": nn.linear_init(k[0], te, 12 * d, dtype=dtype),
            "qkv": nn.linear_init(k[1], d, 3 * d, dtype=dtype),
            "out": nn.linear_init(k[2], d, d, dtype=dtype),
            "mlp1": nn.linear_init(k[3], d, mlp, dtype=dtype),
            "mlp2": nn.linear_init(
                jax.random.fold_in(k[3], 1), mlp, d, dtype=dtype),
        })
    return p


def rope_tables(cfg: GlmDiTConfig, gh: int, gw: int):
    """2-axis (row, col) angles [S_img, head_dim//2]: each axis owns a
    quarter of the head dim's complex pairs (GlmImageRotaryPosEmbed —
    its full-dim table duplicates the halves, which the half-split apply
    folds back into one [S, D/2] table)."""
    quarter = cfg.head_dim // 4

    def ax(pos):
        inv = 1.0 / (cfg.theta ** (
            jnp.arange(quarter, dtype=jnp.float32) * 2 / (cfg.head_dim
                                                          // 2)))
        return pos.astype(jnp.float32)[:, None] * inv[None, :]

    r = jnp.arange(gh).repeat(gw)
    c = jnp.tile(jnp.arange(gw), gh)
    ang = jnp.concatenate([ax(r), ax(c)], axis=-1)
    return jnp.cos(ang), jnp.sin(ang)


def _rope_half(x, cos, sin):
    # apply_rotary_emb use_real_unbind_dim=-2: rotate-half pairing
    d = x.shape[-1]
    x1 = x[..., : d // 2].astype(jnp.float32)
    x2 = x[..., d // 2:].astype(jnp.float32)
    c = cos[None, :, None, :]
    s = sin[None, :, None, :]
    return jnp.concatenate(
        [x1 * c - x2 * s, x2 * c + x1 * s], axis=-1).astype(x.dtype)


def _ln(x, eps):
    return nn.layernorm({}, x, eps=eps)


def _block(blk, cfg: GlmDiTConfig, img, txt, temb, img_freqs, kv_mask):
    h = cfg.num_heads
    eps = cfg.eps
    s_txt = txt.shape[1]
    mod = nn.linear(blk["ada"], temb)
    (sh, c_sh, sc, c_sc, gt, c_gt, sh2, c_sh2, sc2, c_sc2, gt2,
     c_gt2) = jnp.split(mod, 12, axis=-1)
    img_n = _ln(img, eps) * (1 + sc[:, None]) + sh[:, None]
    txt_n = _ln(txt, eps) * (1 + c_sc[:, None]) + c_sh[:, None]

    x = jnp.concatenate([txt_n, img_n], axis=1)
    qkv = nn.linear(blk["qkv"], x)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    b, s = x.shape[:2]
    q = _ln(q.reshape(b, s, h, -1), eps)
    k = _ln(k.reshape(b, s, h, -1), eps)
    v = v.reshape(b, s, h, -1)
    # rope on the IMAGE tokens only
    cos, sin = img_freqs
    q = jnp.concatenate(
        [q[:, :s_txt], _rope_half(q[:, s_txt:], cos, sin)], axis=1)
    k = jnp.concatenate(
        [k[:, :s_txt], _rope_half(k[:, s_txt:], cos, sin)], axis=1)
    o = flash_attention(q, k, v, causal=False, kv_mask=kv_mask)
    o = nn.linear(blk["out"], o.reshape(b, s, -1))
    txt_o, img_o = o[:, :s_txt], o[:, s_txt:]
    img = img + img_o * gt[:, None]
    txt = txt + txt_o * c_gt[:, None]

    img_n2 = _ln(img, eps) * (1 + sc2[:, None]) + sh2[:, None]
    txt_n2 = _ln(txt, eps) * (1 + c_sc2[:, None]) + c_sh2[:, None]

    def ff(x_):
        return nn.linear(blk["mlp2"], jax.nn.gelu(
            nn.linear(blk["mlp1"], x_), approximate=True))

    img = img + ff(img_n2) * gt2[:, None]
    txt = txt + ff(txt_n2) * c_gt2[:, None]
    return img, txt


def forward(
    params,
    cfg: GlmDiTConfig,
    img_tokens: jax.Array,   # [B, gh*gw, p^2*in] packed (dy, dx, c)
    glyph_states: jax.Array,  # [B, S_txt, text_embed_dim]
    prior_ids: jax.Array,    # [B, gh*gw] upsampled prior VQ ids
    prior_drop: jax.Array,   # [B] bool — CFG rows drop the prior
    timesteps: jax.Array,    # [B] in [0, 1000)
    cond_vals: jax.Array,    # [B, 4] target_h, target_w, crop_t, crop_l
    grid_hw: tuple,
    txt_mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Velocity prediction [B, gh*gw, p^2*out_channels]."""
    gh, gw = grid_hw
    b = img_tokens.shape[0]
    img = nn.linear(params["image_proj"], img_tokens)
    txt = nn.linear(params["glyph2"], jax.nn.gelu(
        nn.linear(params["glyph1"], glyph_states), approximate=False))

    pe = nn.embedding(params["prior_embed"], prior_ids)
    pe = jnp.where(prior_drop[:, None, None], jnp.zeros_like(pe), pe)
    prior = nn.linear(params["prior2"], jax.nn.silu(
        nn.linear(params["prior1"], pe)))
    img = img + prior.astype(img.dtype)

    t_emb = nn.linear(params["time_in2"], jax.nn.silu(
        nn.linear(params["time_in1"],
                  nn.timestep_embedding(timesteps, 256).astype(
                      img.dtype))))
    cond_sin = jnp.concatenate(
        [nn.timestep_embedding(cond_vals[:, i], cfg.condition_dim)
         for i in range(4)], axis=-1).astype(img.dtype)
    cond_emb = nn.linear(params["cond_in2"], jax.nn.silu(
        nn.linear(params["cond_in1"], cond_sin)))
    temb = t_emb + cond_emb

    img_freqs = rope_tables(cfg, gh, gw)
    kv_mask = None
    if txt_mask is not None:
        kv_mask = jnp.concatenate(
            [txt_mask.astype(jnp.int32),
             jnp.ones((b, img.shape[1]), jnp.int32)], axis=1)

    for blk in params["blocks"]:
        img, txt = _block(blk, cfg, img, txt, temb, img_freqs, kv_mask)

    # activation-free AdaLayerNormContinuous (scale first)
    mod = nn.linear(params["norm_out_mod"], temb)
    scale, shift = jnp.split(mod, 2, axis=-1)
    img = _ln(img, cfg.eps) * (1 + scale[:, None]) + shift[:, None]
    return nn.linear(params["proj_out"], img)
