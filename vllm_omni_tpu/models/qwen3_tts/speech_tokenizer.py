"""Qwen3-TTS speech tokenizer: waveform <-> discrete codec ids.

Reference: vllm_omni/model_executor/models/qwen3_tts/ — the 12.5Hz/25Hz
speech tokenizers (VQ/whisper encoder stacks) that ground the TTS LM's
codec vocabulary (SURVEY §2.8).

TPU-first design: the encoder is log-mel frames -> strided NWC conv stack
-> nearest-neighbour vector quantization against a learned codebook (one
argmin matmul on the MXU); the decoder renders codec ids back to waveform
through the same transposed-conv vocoder family as code2wav and runs as a
one-shot generation-stage model.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.models.common import nn


@dataclass(frozen=True)
class SpeechTokenizerConfig:
    codebook_size: int = 8192
    code_dim: int = 256
    n_mels: int = 128
    # stride-2 conv stages: mel frame rate / 2^len -> token rate
    encoder_strides: tuple = (2, 2)
    vocoder_channels: int = 256
    vocoder_upsample: tuple = (8, 5, 4, 2)
    kernel: int = 5

    @property
    def downsample(self) -> int:
        return int(np.prod(self.encoder_strides))

    @property
    def samples_per_code(self) -> int:
        return int(math.prod(self.vocoder_upsample))

    @staticmethod
    def tiny() -> "SpeechTokenizerConfig":
        return SpeechTokenizerConfig(
            codebook_size=60, code_dim=16, n_mels=8,
            encoder_strides=(2,), vocoder_channels=16,
            vocoder_upsample=(2, 2), kernel=3,
        )


def init_params(key, cfg: SpeechTokenizerConfig, dtype=jnp.float32):
    keys = jax.random.split(key, 4 + len(cfg.encoder_strides)
                            + 2 * len(cfg.vocoder_upsample))
    ki = iter(keys)
    p = {
        "codebook": jax.random.normal(
            next(ki), (cfg.codebook_size, cfg.code_dim), dtype),
        "enc_in": nn.conv1d_init(next(ki), cfg.n_mels, cfg.code_dim,
                                 cfg.kernel, dtype=dtype),
        "enc": [
            nn.conv1d_init(next(ki), cfg.code_dim, cfg.code_dim,
                           cfg.kernel, dtype=dtype)
            for _ in cfg.encoder_strides
        ],
        "dec_in": nn.conv1d_init(next(ki), cfg.code_dim,
                                 cfg.vocoder_channels, cfg.kernel,
                                 dtype=dtype),
        "dec_ups": [],
        "dec_out": None,
    }
    ch = cfg.vocoder_channels
    for f in cfg.vocoder_upsample:
        out_ch = max(4, ch // 2)
        p["dec_ups"].append({
            "up": nn.conv1d_init(next(ki), ch, out_ch, 2 * f, dtype=dtype),
            "res": nn.conv1d_init(next(ki), out_ch, out_ch, cfg.kernel,
                                  dtype=dtype),
        })
        ch = out_ch
    p["dec_out"] = nn.conv1d_init(next(ki), ch, 1, cfg.kernel, dtype=dtype)
    return p


def encode(params, cfg: SpeechTokenizerConfig, mel: jax.Array) -> jax.Array:
    """Log-mel [B, T, n_mels] -> codec ids [B, T // downsample]."""
    x = nn.conv1d(params["enc_in"], mel)
    for conv, stride in zip(params["enc"], cfg.encoder_strides):
        x = nn.conv1d(conv, jax.nn.silu(x), stride=stride)
    # nearest-neighbour VQ: argmin ||x - c||^2 over the codebook — one
    # [T, D] @ [D, K] matmul plus norms (MXU-friendly)
    cb = params["codebook"]
    dots = jnp.einsum("btd,kd->btk", x, cb)
    d2 = (jnp.sum(x * x, -1, keepdims=True)
          - 2.0 * dots + jnp.sum(cb * cb, -1)[None, None, :])
    return jnp.argmin(d2, axis=-1).astype(jnp.int32)


class SpeechDecoderModel:
    """Generation-runner model: codec ids -> waveform (one-shot)."""

    def __init__(self, cfg: SpeechTokenizerConfig):
        self.cfg = cfg

    @property
    def total_upsample(self) -> int:
        return self.cfg.samples_per_code

    def forward(self, params, token_ids: jax.Array, lengths: jax.Array):
        cfg = self.cfg
        del lengths
        ids = jnp.clip(token_ids, 0, cfg.codebook_size - 1)
        x = params["codebook"][ids]  # [B, S, D]
        x = nn.conv1d(params["dec_in"], x)
        for blk, f in zip(params["dec_ups"], cfg.vocoder_upsample):
            x = jax.nn.silu(x)
            x = nn.conv1d_transpose(blk["up"], x, stride=f)
            x = x + nn.conv1d(blk["res"], jax.nn.silu(x))
        wav = jnp.tanh(nn.conv1d(params["dec_out"], jax.nn.silu(x)))
        return {"audio": wav[..., 0]}

    def slice_output(self, outputs: dict, row: int, in_len: int):
        up = self.cfg.samples_per_code
        return {"audio": np.asarray(outputs["audio"][row, : in_len * up])}


def tiny_decoder_factory():
    """model_factory for the vocoder stage: (params, model_obj, eos)."""
    cfg = SpeechTokenizerConfig.tiny()
    params = init_params(jax.random.PRNGKey(21), cfg)
    return params, SpeechDecoderModel(cfg), None


def tokenize_waveform(params, cfg: SpeechTokenizerConfig,
                      waveform: np.ndarray, sr: int = 16000) -> np.ndarray:
    """Host helper: raw waveform -> codec ids (reference-audio prompts /
    voice cloning intake)."""
    from vllm_omni_tpu.utils.audio import log_mel_spectrogram

    mel = log_mel_spectrogram(waveform, sr=sr, n_mels=cfg.n_mels)
    ids = encode(params, cfg, jnp.asarray(mel)[None])
    return np.asarray(ids[0])
