"""Qwen3-TTS LM: text tokens -> speech-codec tokens (stage 0).

Reference: vllm_omni/model_executor/models/qwen3_tts/ — the TTS language
model autoregressively emits 12.5Hz speech-codec tokens from text (plus
optional voice/reference conditioning).  On the shared functional
transformer the LM is a Qwen3-style (qk-norm) decoder whose output head
covers the codec vocabulary; text and codec ids share one embedding table
partitioned by offset (text ids first, codec ids at ``codec_offset``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from vllm_omni_tpu.models.common.transformer import (
    TransformerConfig,
    init_params,
)

# Real Qwen3-TTS LM geometry (HF config scale): hidden 1024, 28 layers.
QWEN3_TTS_LM = TransformerConfig(
    vocab_size=151936 + 8192 + 8,  # text vocab + codec codes + specials
    hidden_size=1024,
    num_layers=28,
    num_heads=16,
    num_kv_heads=4,
    head_dim=128,
    intermediate_size=3072,
    qk_norm=True,
)

# tiny preset: 64 text ids, 60 codec ids, specials at the top
TINY_TEXT_VOCAB = 64
TINY_CODEC_OFFSET = 64
TINY_CODEC_VOCAB = 60
TINY_EOS = 127


def tiny_config() -> TransformerConfig:
    return TransformerConfig(
        vocab_size=128,
        hidden_size=64,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        head_dim=16,
        intermediate_size=128,
        qk_norm=True,
    )


def tiny_factory():
    """model_factory: tiny TTS LM (text ids < 64, codec ids >= 64)."""
    cfg = tiny_config()
    params = init_params(jax.random.PRNGKey(20), cfg, jnp.float32)
    return params, cfg, TINY_EOS


def real_factory(model_dir: str, dtype="bfloat16", **kw):
    """Arch-registry front door: load the REAL TTS LM from a checkpoint
    directory (the loader the family's stage YAML names,
    stage_configs/qwen3_tts.yaml:13-16)."""
    from vllm_omni_tpu.model_loader.hf_qwen import load_qwen_lm

    return load_qwen_lm(model_dir, dtype=dtype, **kw)


def codec_ids_from_lm_tokens(token_ids, codec_offset: int = TINY_CODEC_OFFSET,
                             codec_vocab: int = TINY_CODEC_VOCAB):
    """Strip non-codec tokens and remove the vocabulary offset (the LM's
    sampled stream may interleave specials; the tokenizer decoder wants
    pure codec ids)."""
    return [int(t) - codec_offset for t in token_ids
            if codec_offset <= int(t) < codec_offset + codec_vocab]

# Real-weight loading: the TTS LM is a Qwen3-style (qk-norm) causal
# transformer over the text+codec vocabulary, served directly by the
# hf_qwen streaming loader — stage YAMLs point model_factory at
# "vllm_omni_tpu.model_loader.hf_qwen:load_qwen_lm" with
# model_factory_args {"model_dir": ..., "hf_config_name": ...}
# (reference: modeling_qwen3_tts.py talker/LM stack).
