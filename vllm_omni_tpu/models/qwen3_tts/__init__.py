"""Qwen3-TTS family: TTS LM + speech tokenizers (text -> speech).

Reference: vllm_omni/model_executor/models/qwen3_tts/ (~7.5k LoC: TTS LM,
12.5Hz/25Hz speech tokenizers with VQ/whisper encoder stacks, custom HF
config registration at engine/arg_utils.py:15-30; SURVEY §2.8).
"""
