"""Qwen3-TTS 12.5 Hz speech tokenizer (functional JAX, NWC layout).

Reference: vllm_omni/model_executor/models/qwen3_tts/tokenizer_12hz/
modeling_qwen3_tts_tokenizer_v2.py — the V2 codec the TTS LM speaks:
16 residual codebooks (1 semantic + 15 acoustic, split-RVQ with 1x1
input/output projections), a causal-conv + ConvNeXt + sliding-window
transformer latent stack, and a Snake-activated transposed-conv
waveform decoder (total upsample 1920 -> 24 kHz from 12.5 Hz frames).

TPU-first notes:
- Channel-last [B, T, C] everywhere; causal convs are explicit left-pad
  + VALID lax convs; transposed convs trim kernel-stride tail samples
  (reference CausalTransConvNet right-trim semantics).
- The whole decode is ONE jitted graph.  The reference decodes in
  Python chunks with a left-context for GPU memory; causality makes
  chunked and full decode agree, which doubles as this module's
  self-consistency test (mirrors chunked_decode,
  modeling_qwen3_tts_tokenizer_v2.py:869-880).
- RVQ decode is an embedding gather + summed 1x1 matmuls; quantize (for
  reference-audio intake) is one [T, K]-distance argmin per codebook on
  the MXU, both-halves-on-input split semantics like transformers Mimi.

The ENCODER half of the checkpoint is a transformers Mimi model
(Qwen3TTSTokenizerV2Encoder, :883); waveform->codes intake can ride
transformers directly on host — this module owns the serving-critical
codes->waveform path plus RVQ quantize for latent-level round trips.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.models.common import nn
from vllm_omni_tpu.models.common import vocoder as vk

from vllm_omni_tpu.logger import init_logger

logger = init_logger(__name__)


@dataclass(frozen=True)
class Tokenizer12HzConfig:
    codebook_size: int = 2048
    num_quantizers: int = 16
    n_semantic: int = 1
    codebook_dim: int = 512     # RVQ input/output width
    latent_dim: int = 1024
    decoder_dim: int = 1536
    upsampling_ratios: tuple[int, ...] = (2, 2)
    upsample_rates: tuple[int, ...] = (8, 5, 4, 3)
    hidden_size: int = 1024
    num_layers: int = 8
    num_heads: int = 16
    num_kv_heads: int = 16
    head_dim: int = 64
    intermediate_size: int = 3072
    sliding_window: int = 72
    layer_scale: float = 0.01
    rope_theta: float = 10000.0
    rms_eps: float = 1e-5
    output_sample_rate: int = 24000

    @property
    def vq_dim(self) -> int:
        return self.codebook_dim // 2

    @property
    def total_upsample(self) -> int:
        return int(math.prod(self.upsampling_ratios)
                   * math.prod(self.upsample_rates))

    @staticmethod
    def tiny() -> "Tokenizer12HzConfig":
        return Tokenizer12HzConfig(
            # covers the tiny TTS LM's 60-id codec vocabulary
            codebook_size=64, num_quantizers=4, n_semantic=1,
            codebook_dim=16, latent_dim=24, decoder_dim=32,
            upsampling_ratios=(2,), upsample_rates=(2, 2),
            hidden_size=24, num_layers=2, num_heads=4, num_kv_heads=4,
            head_dim=6, intermediate_size=48, sliding_window=8,
        )


# -------- shared vocoder primitives (models/common/vocoder.py) --------
_cconv_init = vk.cconv_init
_cconv = vk.cconv
_tconv_init = vk.tconv_init
_tconv = vk.tconv  # default trim: RIGHT only (V2 CausalTransConvNet)
_snake_init = vk.snake_init
_snake = vk.snake
_convnext_init = vk.convnext_init
_convnext = vk.convnext


def _spec(cfg: Tokenizer12HzConfig) -> vk.TransformerSpec:
    return vk.TransformerSpec(
        hidden_size=cfg.hidden_size, num_layers=cfg.num_layers,
        num_heads=cfg.num_heads, num_kv_heads=cfg.num_kv_heads,
        head_dim=cfg.head_dim, intermediate_size=cfg.intermediate_size,
        sliding_window=cfg.sliding_window, layer_scale=cfg.layer_scale,
        rope_theta=cfg.rope_theta, rms_eps=cfg.rms_eps,
    )


def _layer_init(key, cfg: Tokenizer12HzConfig, dtype):
    return vk.transformer_layer_init(key, _spec(cfg), dtype)


def _transformer(params, cfg: Tokenizer12HzConfig, x):
    """Causal sliding-window transformer with LayerScale residuals
    (DecoderTransformerLayer, :408-470)."""
    return vk.sliding_transformer(params, _spec(cfg), x)


# -------------------------------------------------------------------- RVQ
def _rvq_init(key, cfg: Tokenizer12HzConfig, n_layers, dtype):
    ks = jax.random.split(key, n_layers + 2)
    return {
        "input_proj": nn.linear_init(ks[0], cfg.codebook_dim, cfg.vq_dim,
                                     bias=False, dtype=dtype),
        "output_proj": nn.linear_init(ks[1], cfg.vq_dim,
                                      cfg.codebook_dim, bias=False,
                                      dtype=dtype),
        "layers": [
            {
                "embedding_sum": jax.random.normal(
                    ks[2 + i], (cfg.codebook_size, cfg.vq_dim), dtype),
                "cluster_usage": jnp.ones((cfg.codebook_size,), dtype),
            }
            for i in range(n_layers)
        ],
    }


def _codebook(layer):
    """EuclideanCodebook embedding = embedding_sum / cluster_usage
    (:662-680)."""
    usage = jnp.clip(layer["cluster_usage"].astype(jnp.float32),
                     1e-5, None)
    return layer["embedding_sum"].astype(jnp.float32) / usage[:, None]


def _rvq_decode(p, codes):
    """codes [B, n_layers, T] -> [B, T, codebook_dim]."""
    total = 0.0
    for i, layer in enumerate(p["layers"]):
        emb = _codebook(layer)
        total = total + emb[codes[:, i]]
    return nn.linear(p["output_proj"], total)


def _rvq_quantize(p, x):
    """[B, T, codebook_dim] -> codes [B, n_layers, T] (residual nearest-
    neighbour per codebook on the projected latent)."""
    r = nn.linear(p["input_proj"], x).astype(jnp.float32)
    out = []
    for layer in p["layers"]:
        emb = _codebook(layer)
        d2 = (jnp.sum(r * r, -1, keepdims=True)
              - 2.0 * jnp.einsum("btd,kd->btk", r, emb)
              + jnp.sum(emb * emb, -1)[None, None])
        idx = jnp.argmin(d2, -1)
        out.append(idx.astype(jnp.int32))
        r = r - emb[idx]
    return jnp.stack(out, axis=1)


def split_rvq_decode(params, cfg: Tokenizer12HzConfig, codes):
    """codes [B, K, T] -> latent [B, T, codebook_dim] (semantic +
    acoustic halves, SplitResidualVectorQuantizer.decode :797-804)."""
    sem = _rvq_decode(params["rvq_first"], codes[:, : cfg.n_semantic])
    if codes.shape[1] > cfg.n_semantic:
        sem = sem + _rvq_decode(params["rvq_rest"],
                                codes[:, cfg.n_semantic:])
    return sem


def split_rvq_quantize(params, cfg: Tokenizer12HzConfig, latent):
    """Both halves quantize the SAME input (transformers Mimi split
    semantics); returns codes [B, K, T]."""
    sem = _rvq_quantize(params["rvq_first"], latent)
    ac = _rvq_quantize(params["rvq_rest"], latent)
    return jnp.concatenate([sem, ac], axis=1)


# ------------------------------------------------------------- full model
def init_params(key, cfg: Tokenizer12HzConfig, dtype=jnp.float32):
    keys = jax.random.split(key, 16 + cfg.num_layers
                            + 2 * len(cfg.upsampling_ratios)
                            + 8 * len(cfg.upsample_rates))
    ki = iter(keys)
    p = {
        "rvq_first": _rvq_init(next(ki), cfg, cfg.n_semantic, dtype),
        "rvq_rest": _rvq_init(next(ki), cfg,
                              cfg.num_quantizers - cfg.n_semantic, dtype),
        "pre_conv": _cconv_init(next(ki), cfg.codebook_dim,
                                cfg.latent_dim, 3, dtype),
        "transformer": {
            "layers": [_layer_init(next(ki), cfg, dtype)
                       for _ in range(cfg.num_layers)],
            "final_norm": nn.rmsnorm_init(cfg.hidden_size, dtype),
        },
        "upsample": [
            {"tconv": _tconv_init(next(ki), cfg.latent_dim,
                                  cfg.latent_dim, f, dtype),
             "convnext": _convnext_init(next(ki), cfg.latent_dim, dtype)}
            for f in cfg.upsampling_ratios
        ],
        "dec_in": _cconv_init(next(ki), cfg.latent_dim, cfg.decoder_dim,
                              7, dtype),
        "dec_blocks": [],
    }
    for i, r in enumerate(cfg.upsample_rates):
        cin = cfg.decoder_dim // (2 ** i)
        cout = cfg.decoder_dim // (2 ** (i + 1))
        blk = {
            "snake": _snake_init(cin, dtype),
            "tconv": _tconv_init(next(ki), cin, cout, 2 * r, dtype),
            "units": [],
        }
        for _ in (1, 3, 9):  # dilations are static (decode_codes)
            blk["units"].append({
                "snake1": _snake_init(cout, dtype),
                "conv1": _cconv_init(next(ki), cout, cout, 7, dtype),
                "snake2": _snake_init(cout, dtype),
                "conv2": _cconv_init(next(ki), cout, cout, 1, dtype),
            })
        p["dec_blocks"].append(blk)
    out_dim = cfg.decoder_dim // (2 ** len(cfg.upsample_rates))
    p["out_snake"] = _snake_init(out_dim, dtype)
    p["out_conv"] = _cconv_init(next(ki), out_dim, 1, 7, dtype)
    return p


def decode_codes(params, cfg: Tokenizer12HzConfig,
                 codes: jax.Array) -> jax.Array:
    """codes [B, K, T] -> waveform [B, T * total_upsample] in [-1, 1]
    (Qwen3TTSTokenizerV2Decoder.forward, :853-867)."""
    h = split_rvq_decode(params, cfg, codes)       # [B, T, cd]
    h = _cconv(params["pre_conv"], h, 3)
    h = _transformer(params["transformer"], cfg, h)
    for up, f in zip(params["upsample"], cfg.upsampling_ratios):
        h = _tconv(up["tconv"], h, f, f)
        h = _convnext(up["convnext"], h)
    w = _cconv(params["dec_in"], h, 7)
    for blk, r in zip(params["dec_blocks"], cfg.upsample_rates):
        w = _snake(blk["snake"], w)
        w = _tconv(blk["tconv"], w, 2 * r, r)
        for u, dil in zip(blk["units"], (1, 3, 9)):
            res = w
            w = _cconv(u["conv1"], _snake(u["snake1"], w), 7,
                       dilation=dil)
            w = _cconv(u["conv2"], _snake(u["snake2"], w), 1)
            w = w + res
    w = _cconv(params["out_conv"], _snake(params["out_snake"], w), 7)
    return jnp.clip(w[..., 0], -1.0, 1.0)


def chunked_decode(params, cfg: Tokenizer12HzConfig, codes,
                   chunk_size: int = 300, left_context: int = 25):
    """Frame-chunked decode with left context, trimmed and concatenated
    (chunked_decode, :869-880) — causality makes this equal the full
    decode; kept for bounded-memory streaming synthesis."""
    t = codes.shape[-1]
    up = cfg.total_upsample
    wavs = []
    start = 0
    while start < t:
        end = min(start + chunk_size, t)
        ctx = left_context if start - left_context > 0 else start
        wav = decode_codes(params, cfg, codes[..., start - ctx: end])
        wavs.append(np.asarray(wav[..., ctx * up:]))
        start = end
    return np.concatenate(wavs, axis=-1)


class Tokenizer12HzDecoderModel:
    """Generation-runner model: LM codec frames -> waveform.  The TTS LM
    emits ``num_quantizers`` interleaved code streams; the runner hands
    them over as [B, S] rows of packed frames."""

    def __init__(self, cfg: Tokenizer12HzConfig):
        self.cfg = cfg

    @property
    def total_upsample(self) -> int:
        return self.cfg.total_upsample

    def forward(self, params, token_ids: jax.Array, lengths: jax.Array):
        cfg = self.cfg
        del lengths
        b, s = token_ids.shape
        k = cfg.num_quantizers
        # partial trailing frames pad with code 0 (never drop to zero
        # frames — degenerate LM samples still produce audio)
        frames = max(1, -(-s // k))
        ids = jnp.clip(token_ids, 0, cfg.codebook_size - 1)
        ids = jnp.pad(ids, ((0, 0), (0, frames * k - s)))
        codes = ids.reshape(b, frames, k).transpose(0, 2, 1)
        wav = decode_codes(params, cfg, codes)
        return {"audio": wav}

    def slice_output(self, outputs: dict, row: int, in_len: int):
        frames = max(1, -(-in_len // self.cfg.num_quantizers))
        up = self.cfg.total_upsample
        return {"audio": np.asarray(
            outputs["audio"][row, : frames * up])}


def tiny_decoder_factory():
    """model_factory for the 12.5Hz code2wav stage: (params, model, eos)."""
    cfg = Tokenizer12HzConfig.tiny()
    params = init_params(jax.random.PRNGKey(23), cfg)
    return params, Tokenizer12HzDecoderModel(cfg), None


# ------------------------------------------------------- checkpoint load


def hf_flat_map(cfg: Tokenizer12HzConfig) -> dict:
    """HF tensor name -> param-tree path for the DECODER half of
    Qwen3TTSTokenizerV2Model (prefix ``decoder.``); the encoder half is
    a transformers Mimi model and is not loaded here."""
    m: dict[str, tuple] = {}

    def conv(prefix, path):
        m[f"{prefix}.weight"] = path + ("w",)
        m[f"{prefix}.bias"] = path + ("b",)

    def lin(prefix, path):
        m[f"{prefix}.weight"] = path + ("w",)

    for name, n in (("rvq_first", cfg.n_semantic),
                    ("rvq_rest", cfg.num_quantizers - cfg.n_semantic)):
        q = f"decoder.quantizer.{name}"
        lin(f"{q}.input_proj", (name, "input_proj"))
        lin(f"{q}.output_proj", (name, "output_proj"))
        for i in range(n):
            base = f"{q}.vq.layers.{i}._codebook"
            m[f"{base}.embedding_sum"] = (name, "layers", i,
                                          "embedding_sum")
            m[f"{base}.cluster_usage"] = (name, "layers", i,
                                          "cluster_usage")

    conv("decoder.pre_conv.conv", ("pre_conv",))
    for i in range(cfg.num_layers):
        lp = f"decoder.pre_transformer.layers.{i}"
        tgt = ("transformer", "layers", i)
        m[f"{lp}.input_layernorm.weight"] = tgt + ("input_norm", "w")
        for proj in ("q_proj", "k_proj", "v_proj", "o_proj"):
            lin(f"{lp}.self_attn.{proj}", tgt + (proj,))
        m[f"{lp}.self_attn_layer_scale.scale"] = tgt + ("attn_scale",)
        m[f"{lp}.post_attention_layernorm.weight"] = tgt + ("post_norm",
                                                            "w")
        lin(f"{lp}.mlp.gate_proj", tgt + ("gate",))
        lin(f"{lp}.mlp.up_proj", tgt + ("up",))
        lin(f"{lp}.mlp.down_proj", tgt + ("down",))
        m[f"{lp}.mlp_layer_scale.scale"] = tgt + ("mlp_scale",)
    m["decoder.pre_transformer.norm.weight"] = ("transformer",
                                                "final_norm", "w")

    for i in range(len(cfg.upsampling_ratios)):
        conv(f"decoder.upsample.{i}.0.conv",
             ("upsample", i, "tconv"))
        cn = f"decoder.upsample.{i}.1"
        conv(f"{cn}.dwconv.conv", ("upsample", i, "convnext", "dw"))
        m[f"{cn}.norm.weight"] = ("upsample", i, "convnext", "norm", "w")
        m[f"{cn}.norm.bias"] = ("upsample", i, "convnext", "norm", "b")
        for pw in ("pwconv1", "pwconv2"):
            key = "pw1" if pw == "pwconv1" else "pw2"
            m[f"{cn}.{pw}.weight"] = ("upsample", i, "convnext", key, "w")
            m[f"{cn}.{pw}.bias"] = ("upsample", i, "convnext", key, "b")
        m[f"{cn}.gamma"] = ("upsample", i, "convnext", "gamma")

    conv("decoder.decoder.0.conv", ("dec_in",))
    for i in range(len(cfg.upsample_rates)):
        d = f"decoder.decoder.{1 + i}.block"
        tgt = ("dec_blocks", i)
        m[f"{d}.0.alpha"] = tgt + ("snake", "alpha")
        m[f"{d}.0.beta"] = tgt + ("snake", "beta")
        conv(f"{d}.1.conv", tgt + ("tconv",))
        for j in range(3):
            u = f"{d}.{2 + j}"
            ut = tgt + ("units", j)
            m[f"{u}.act1.alpha"] = ut + ("snake1", "alpha")
            m[f"{u}.act1.beta"] = ut + ("snake1", "beta")
            conv(f"{u}.conv1.conv", ut + ("conv1",))
            m[f"{u}.act2.alpha"] = ut + ("snake2", "alpha")
            m[f"{u}.act2.beta"] = ut + ("snake2", "beta")
            conv(f"{u}.conv2.conv", ut + ("conv2",))
    last = 1 + len(cfg.upsample_rates)
    m[f"decoder.decoder.{last}.alpha"] = ("out_snake", "alpha")
    m[f"decoder.decoder.{last}.beta"] = ("out_snake", "beta")
    conv(f"decoder.decoder.{last + 1}.conv", ("out_conv",))
    return m


def hf_transform(name: str, arr):
    """torch layouts -> ours: Conv1d [out, in, k] -> WIO [k, in, out]
    and ConvTranspose1d [in, out, k] -> [k, out, in] (the
    ``transpose_kernel=True`` forward layout) — both are
    transpose(2, 1, 0); linears [out, in] -> [in, out]; 1-wide conv
    projections squeeze to linears."""
    if arr.ndim == 3:
        if arr.shape[-1] == 1 and ("input_proj" in name
                                   or "output_proj" in name):
            return arr[..., 0].transpose(1, 0)  # 1x1 conv -> [in, out]
        return arr.transpose(2, 1, 0)
    if arr.ndim == 2 and name.endswith("weight") \
            and "embedding_sum" not in name:
        return arr.T
    return arr


def load_decoder(model_dir: str, cfg: Tokenizer12HzConfig = None,
                 dtype=jnp.float32):
    """Stream the decoder half of a Qwen3TTSTokenizerV2 checkpoint into
    our param tree; every leaf must be covered (safetensors_loader
    semantics)."""
    import json
    import os

    from vllm_omni_tpu.model_loader.safetensors_loader import (
        load_checkpoint_tree,
    )

    if cfg is None:
        cfg_path = os.path.join(model_dir, "config.json")
        dec = {}
        if os.path.isfile(cfg_path):
            with open(cfg_path) as f:
                dec = json.load(f).get("decoder_config", {})
        cfg = Tokenizer12HzConfig(
            codebook_size=dec.get("codebook_size", 2048),
            num_quantizers=dec.get("num_quantizers", 16),
            codebook_dim=dec.get("codebook_dim", 512),
            latent_dim=dec.get("latent_dim", 1024),
            decoder_dim=dec.get("decoder_dim", 1536),
            upsampling_ratios=tuple(dec.get("upsampling_ratios", (2, 2))),
            upsample_rates=tuple(dec.get("upsample_rates", (8, 5, 4, 3))),
            hidden_size=dec.get("hidden_size", 1024),
            num_layers=dec.get("num_hidden_layers", 8),
            num_heads=dec.get("num_attention_heads", 16),
            num_kv_heads=dec.get("num_key_value_heads", 16),
            head_dim=dec.get(
                "head_dim",
                dec.get("hidden_size", 1024)
                // dec.get("num_attention_heads", 16)),
            intermediate_size=dec.get("intermediate_size", 3072),
            sliding_window=dec.get("sliding_window", 72),
            layer_scale=dec.get("layer_scale_initial_scale", 0.01),
            rope_theta=dec.get("rope_theta", 10000.0),
            rms_eps=dec.get("rms_norm_eps", 1e-5),
        )
    shapes = jax.eval_shape(
        lambda: init_params(jax.random.PRNGKey(0), cfg, jnp.float32))
    from vllm_omni_tpu.model_loader.safetensors_loader import (
        np_param_dtype,
    )

    np_dtype = np_param_dtype(dtype)
    tree = jax.tree.map(lambda t: np.zeros(t.shape, np_dtype), shapes)
    flat = hf_flat_map(cfg)
    n, unmapped = load_checkpoint_tree(
        model_dir, flat.get, tree, dtype=np_dtype,
        transform=hf_transform,
    )
    n_leaves = len(jax.tree.leaves(tree))
    if n != n_leaves:
        raise ValueError(
            f"{model_dir} covered {n}/{n_leaves} 12.5Hz-decoder weights")
    non_encoder = [u for u in unmapped if not u.startswith("encoder.")]
    if non_encoder:
        logger.warning("12.5Hz loader: %d unmapped non-encoder tensors "
                       "(e.g. %s)", len(non_encoder), non_encoder[:3])
    return tree, cfg


def load_decoder_factory(model_dir: str, dtype="float32"):
    """model_factory for real-weight 12.5Hz code2wav stages:
    (params, model, eos)."""
    jdtype = jnp.dtype(dtype) if isinstance(dtype, str) else dtype
    params, cfg = load_decoder(model_dir, dtype=jdtype)
    return params, Tokenizer12HzDecoderModel(cfg), None
