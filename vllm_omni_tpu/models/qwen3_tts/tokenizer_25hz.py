"""Qwen3-TTS 25 Hz speech tokenizer (V1) — decode path.

Reference: vllm_omni/model_executor/models/qwen3_tts/tokenizer_25hz/
modeling_qwen3_tts_tokenizer_v1.py — the V1 codec decodes 25 Hz codes
to waveform through the SAME architecture family as the Qwen2.5-Omni
token2wav stage, with three deltas this module configures on the shared
checkpoint-schema stack (models/qwen2_5_omni/{token2wav_dit,bigvgan}):

- the DiT rotates EVERY attention head (the 2.5-Omni checkpoint rotates
  only head 0),
- sampling is plain Euler over the sway-warped grid (V1 sample loop,
  :1174-1232) instead of RK4,
- the BigVGAN is the ``tts_v1`` variant: conv stem kernel 5 and chained
  AMP blocks with causal convs (+pre conv/act on the first two stages).

Checkpoint layout: ``decoder.dit.*`` / ``decoder.bigvgan.*`` under a
``Qwen3TTSTokenizerV1Model``; the ENCODER half (waveform -> codes) is a
separate model the serving path does not need for synthesis.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.models.qwen2_5_omni import bigvgan as bv
from vllm_omni_tpu.models.qwen2_5_omni import token2wav_dit as t2w


@dataclass(frozen=True)
class Tokenizer25HzConfig:
    """V1 decoder geometry over the shared token2wav stack."""
    dit: t2w.T2WDiTConfig = field(
        default_factory=lambda: t2w.T2WDiTConfig(rope_all_heads=True))
    bigvgan: bv.BigVGANConfig = field(
        default_factory=lambda: bv.BigVGANConfig(variant="tts_v1"))
    # authoritative values come from the checkpoint's decoder_config
    # (output_sample_rate / decode_upsample_rate); the reference class
    # DEFAULTS are mutually inconsistent (decode_upsample_rate=1920 vs
    # a 2x240 network), so real geometry must be read, not assumed
    output_sample_rate: int = 24000
    num_steps: int = 10
    guidance_scale: float = 0.5

    @property
    def codebook_size(self) -> int:
        return self.dit.num_embeds

    @property
    def total_upsample(self) -> int:
        """Waveform samples per codec frame — derived from the actual
        network geometry (repeats x BigVGAN upsample product)."""
        return self.dit.repeats * self.bigvgan.total_upsample

    @staticmethod
    def tiny() -> "Tokenizer25HzConfig":
        dit = t2w.T2WDiTConfig(
            hidden_size=32, num_layers=2, num_heads=2, head_dim=8,
            emb_dim=12, num_embeds=60, mel_dim=8, block_size=4,
            look_ahead_layers=(1,), look_backward_layers=(0,),
            enc_dim=10, enc_emb_dim=6, enc_channels=(8, 8, 8, 8, 24),
            enc_kernel_sizes=(5, 3, 3, 3, 1),
            enc_dilations=(1, 2, 3, 4, 1), enc_attention_channels=4,
            enc_res2net_scale=2, enc_se_channels=4,
            rope_all_heads=True)
        vgan = bv.BigVGANConfig(
            variant="tts_v1", mel_dim=8, upsample_initial_channel=16,
            resblock_kernel_sizes=(3,),
            resblock_dilation_sizes=((1, 3, 5),),
            upsample_rates=(2, 2), upsample_kernel_sizes=(4, 4))
        return Tokenizer25HzConfig(dit=dit, bigvgan=vgan, num_steps=2)


class Tokenizer25HzDecoderModel(t2w.Token2WavRealModel):
    """Generation-runner model protocol: V1 codec ids -> waveform.  The
    shared Token2WavRealModel does the work; V1 just pins the Euler
    solver and carries the composed config."""

    def __init__(self, cfg: Tokenizer25HzConfig):
        super().__init__(cfg.dit, cfg.bigvgan, num_steps=cfg.num_steps,
                         guidance_scale=cfg.guidance_scale,
                         solver="euler")
        self.tokenizer_cfg = cfg

    @property
    def total_upsample(self) -> int:
        return self.tokenizer_cfg.total_upsample


def tiny_decoder_factory():
    """model_factory for a 25Hz code2wav stage: (params, model, eos)."""
    cfg = Tokenizer25HzConfig.tiny()
    params = {
        "dit": t2w.init_params(jax.random.PRNGKey(25), cfg.dit,
                               jnp.float32),
        "bigvgan": bv.init_params(jax.random.PRNGKey(26), cfg.bigvgan,
                                  jnp.float32),
    }
    return params, Tokenizer25HzDecoderModel(cfg), None


# ------------------------------------------------------- checkpoint load
def load_decoder(model_dir: str, dtype=jnp.float32,
                 num_steps: int = 10, guidance_scale: float = 0.5):
    """Stream the ``decoder.{dit,bigvgan}.*`` halves of a
    Qwen3TTSTokenizerV1 checkpoint; returns (params, model, eos) — the
    model_factory contract."""
    import json
    import os

    d = {}
    cfg_path = os.path.join(model_dir, "config.json")
    if os.path.isfile(cfg_path):
        with open(cfg_path) as f:
            d = json.load(f).get("decoder_config", {})
    dit_cfg = t2w.T2WDiTConfig.from_hf(d.get("dit_config", {}),
                                       rope_all_heads=True)
    bv_cfg = bv.BigVGANConfig.from_hf(d.get("bigvgan_config", {}),
                                      variant="tts_v1")
    dit_params, _ = t2w.load_dit(model_dir, cfg=dit_cfg, dtype=dtype,
                                 prefix="decoder.dit.")
    bv_params, _ = bv.load_bigvgan(model_dir, cfg=bv_cfg, dtype=dtype,
                                   prefix="decoder.bigvgan.")
    cfg = Tokenizer25HzConfig(dit=dit_cfg, bigvgan=bv_cfg,
                              output_sample_rate=d.get(
                                  "output_sample_rate", 24000),
                              num_steps=num_steps,
                              guidance_scale=guidance_scale)
    declared = d.get("decode_upsample_rate")
    if declared and declared != cfg.total_upsample:
        import warnings

        warnings.warn(
            f"decoder_config declares decode_upsample_rate={declared} "
            f"but the network geometry yields {cfg.total_upsample} "
            "samples/code — trusting the network", stacklevel=2)
    return ({"dit": dit_params, "bigvgan": bv_params},
            Tokenizer25HzDecoderModel(cfg), None)
