"""Qwen3-TTS 25 Hz speech tokenizer (V1) — decode path.

Reference: vllm_omni/model_executor/models/qwen3_tts/tokenizer_25hz/
modeling_qwen3_tts_tokenizer_v1.py — the V1 codec decodes 25 Hz codes to
waveform through a flow-matching mel DiT (DiTDecoderLayer stack with
AdaLayerNormZero conditioning + DiTCodecEmbedding) followed by a
Snake-activated BigVGAN-style vocoder, with an ECAPA-TDNN speaker
encoder for voice conditioning.

That is the SAME architecture family as this repo's Qwen2.5-Omni
token2wav stage (models/qwen2_5_omni/token2wav.py: flow-matching mel DiT
+ transposed-conv vocoder), so the V1 decoder composes those shared
pieces at the 25 Hz geometry instead of duplicating them — codes embed
into the DiT's conditioning stream, the ODE integrates mel frames, and
the vocoder renders 24 kHz audio.  Reduced depth vs the reference's
ECAPA speaker path (speaker embeddings ride the conditioning vector when
provided; the ECAPA encoder itself is future work at real-weight time).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from vllm_omni_tpu.models.qwen2_5_omni.token2wav import (
    Token2WavConfig,
    Token2WavModel,
    init_token2wav_params,
)


@dataclass(frozen=True)
class Tokenizer25HzConfig:
    """V1 geometry knobs mapped onto the shared token2wav stack
    (reference defaults: 22-layer / 1024-hidden DiT, 16 heads,
    mel 80, 24 kHz out)."""
    codebook_size: int = 4096
    frame_rate: int = 25
    output_sample_rate: int = 24000
    dit_hidden: int = 1024
    dit_layers: int = 22
    dit_heads: int = 16
    n_mels: int = 80

    def token2wav(self) -> Token2WavConfig:
        return Token2WavConfig(
            codec_vocab=self.codebook_size,
            d_model=self.dit_hidden,
            num_layers=self.dit_layers,
            num_heads=self.dit_heads,
            mel_bins=self.n_mels,
        )

    @staticmethod
    def tiny() -> "Tokenizer25HzConfig":
        return Tokenizer25HzConfig(
            codebook_size=60, dit_hidden=32, dit_layers=2, dit_heads=4,
            n_mels=8,
        )


def tiny_decoder_factory():
    """model_factory for a 25Hz code2wav stage: (params, model, eos)."""
    t2w_cfg = Token2WavConfig.tiny()
    params = init_token2wav_params(jax.random.PRNGKey(25), t2w_cfg,
                                   jnp.float32)
    return params, Token2WavModel(t2w_cfg), None
