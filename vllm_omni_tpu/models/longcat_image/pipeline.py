"""LongCat-Image text->image + Edit pipelines.

Reference: vllm_omni/diffusion/models/longcat_image/
(pipeline_longcat_image.py:202, pipeline_longcat_image_edit.py,
longcat_image_transformer.py:505 — "the Transformer model introduced in
Flux": 19 double + 38 single stream blocks at the Flux geometry, but
with TRUE classifier-free guidance over a doubled batch instead of an
embedded guidance scale, no pooled conditioning vector, and an optional
CFG-renorm (cfg_normalize_function, pipeline_longcat_image.py:463) that
rescales the combined prediction back to the conditional norm.

The edit variant VAE-encodes the input image and appends its packed
latents to the token sequence (frame coordinate 1 in RoPE), reading
velocity off the generated tokens — same mechanism as Qwen-Image-Edit.

TPU-first: reuses the Flux MMDiT implementation
(models/flux/transformer.py with pooled_dim=0); the whole denoise loop
is one jitted fori_loop.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.diffusion import cache as step_cache
from vllm_omni_tpu.diffusion import scheduler as fm
from vllm_omni_tpu.diffusion.request import (
    DiffusionOutput,
    InvalidRequestError,
    OmniDiffusionRequest,
)
from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.models.common.transformer import (
    TransformerConfig,
    forward_hidden,
    init_params as init_text_params,
)
from vllm_omni_tpu.models.flux import transformer as fdit
from vllm_omni_tpu.models.flux.transformer import FluxDiTConfig
from vllm_omni_tpu.models.qwen_image import vae as vae_mod
from vllm_omni_tpu.models.qwen_image.vae import VAEConfig
from vllm_omni_tpu.utils.tokenizer import ByteTokenizer

logger = init_logger(__name__)


def _longcat_dit(base: FluxDiTConfig,
                 txt_max_len: int = 512) -> FluxDiTConfig:
    """LongCat deltas over the Flux skeleton (reference:
    longcat_image_transformer.py:505 + prepare_pos_ids
    pipeline_longcat_image.py:112): timestep-only conditioning, GEGLU
    double-block FFs, text rope ids (0, n, n), image grid at modality 1
    offset by the tokenizer max length."""
    import dataclasses

    return dataclasses.replace(
        base, guidance_embed=False, pooled_dim=0,
        ff_double="geglu", txt_rope_arange=True,
        img_frame_coord=1.0, img_rope_offset=txt_max_len)


def longcat_dit_config_from_diffusers(d: dict,
                                      txt_max_len: int = 512
                                      ) -> FluxDiTConfig:
    """LongCatImageTransformer2DModel config.json -> FluxDiTConfig."""
    in_ch = d.get("in_channels", 64)
    return _longcat_dit(FluxDiTConfig(
        in_channels=in_ch,
        out_channels=d.get("out_channels") or in_ch,
        num_double_blocks=d.get("num_layers", 19),
        num_single_blocks=d.get("num_single_layers", 38),
        num_heads=d.get("num_attention_heads", 24),
        head_dim=d.get("attention_head_dim", 128),
        ctx_dim=d.get("joint_attention_dim", 3584),
        axes_dims=tuple(d.get("axes_dims_rope", (16, 56, 56))),
        rope_interleaved=True,  # diffusers pairing
    ), txt_max_len=txt_max_len)


# Template the text encoder wraps prompts in (reference:
# pipeline_longcat_image.py:243-249); embeddings keep only the padded
# user-prompt span between prefix and suffix.
PROMPT_PREFIX = (
    "<|im_start|>system\n"
    "As an image captioning expert, generate a descriptive text prompt "
    "based on an image content, suitable for input to a text-to-image "
    "model.<|im_end|>\n"
    "<|im_start|>user\n"
)
PROMPT_SUFFIX = "<|im_end|>\n<|im_start|>assistant\n"


@dataclass(frozen=True)
class LongCatImagePipelineConfig:
    text: TransformerConfig = field(default_factory=TransformerConfig)
    dit: FluxDiTConfig = field(
        default_factory=lambda: _longcat_dit(FluxDiTConfig()))
    vae: VAEConfig = field(default_factory=VAEConfig)
    max_text_len: int = 64
    scheduler: str = "euler"
    pack: int = 2
    cfg_renorm: bool = True
    cfg_renorm_min: float = 0.0

    @staticmethod
    def tiny() -> "LongCatImagePipelineConfig":
        return LongCatImagePipelineConfig(
            text=TransformerConfig.tiny(vocab_size=256),
            dit=_longcat_dit(FluxDiTConfig.tiny()),
            vae=VAEConfig.tiny(),
            max_text_len=32,
        )


class LongCatImagePipeline:
    """Text -> image (Flux geometry, true CFG + renorm)."""

    output_type = "image"
    needs_image_cond = False

    def __init__(self, config: LongCatImagePipelineConfig,
                 dtype=jnp.bfloat16, seed: int = 0, mesh=None,
                 cache_config=None, init_weights: bool = True):
        from vllm_omni_tpu.parallel.pipeline_mesh import MeshWiring

        self.cfg = config
        self.dtype = dtype
        self.mesh = mesh
        self.cache_config = cache_config
        self.wiring = MeshWiring(mesh, type(self).__name__).validate(
            {"dp", "cfg"})
        if config.dit.guidance_embed or config.dit.pooled_dim:
            raise ValueError(
                "LongCat runs true CFG without pooled conditioning — "
                "use _longcat_dit()")
        if config.text.hidden_size != config.dit.ctx_dim:
            raise ValueError("text hidden_size must equal dit ctx_dim")
        want_in = config.vae.latent_channels * config.pack ** 2
        if config.dit.in_channels != want_in:
            raise ValueError(
                f"dit.in_channels must be latent*pack^2 = {want_in}")
        self.tokenizer = ByteTokenizer(config.text.vocab_size)
        self.hf_tokenizer = None  # set by from_pretrained
        k1, k2, k3 = jax.random.split(jax.random.PRNGKey(seed), 3)
        logger.info("Initializing %s (dtype=%s)", type(self).__name__,
                    dtype)
        if init_weights:
            self.text_params = self.wiring.place(
                init_text_params(k1, config.text, dtype))
            self.dit_params = self.wiring.place(
                fdit.init_params(k2, config.dit, dtype))
            self.vae_params = self.wiring.place(
                vae_mod.init_decoder(k3, config.vae, dtype))
        else:
            self.text_params = self.dit_params = self.vae_params = None
        self.vae_encoder_params = None  # on demand (edit conditioning)
        self._seed = seed
        self._denoise_cache: dict = {}
        self._text_encode_jit = jax.jit(
            lambda p, i, m: forward_hidden(p, self.cfg.text, i,
                                           attn_mask=m))
        self._vae_decode_jit = jax.jit(
            lambda pp, l: vae_mod.decode(pp, self.cfg.vae, l))
        self._vae_encode_jit = jax.jit(
            lambda pp, im: vae_mod.encode(pp, self.cfg.vae, im))

    @property
    def geometry_multiple(self) -> int:
        return self.cfg.vae.spatial_ratio * self.cfg.pack

    def encode_prompt(self, prompts: list[str]):
        if self.hf_tokenizer is not None:
            return self._encode_prompt_hf(prompts)
        ids, lens = self.tokenizer.batch_encode(prompts,
                                                self.cfg.max_text_len)
        hidden = self._text_encode_jit(self.text_params,
                                       jnp.asarray(ids), None)
        mask = (np.arange(self.cfg.max_text_len)[None, :]
                < lens[:, None]).astype(np.int32)
        return hidden, jnp.asarray(mask)

    def _encode_prompt_hf(self, prompts: list[str]):
        """Reference encode (pipeline_longcat_image.py:284-341): tokens =
        prefix + user prompt padded to max_text_len + suffix; the LM runs
        with an attention mask excluding the mid-sequence pads; the
        embeddings keep only the padded user span.  The DiT attends the
        whole span (the reference passes no text mask to the
        transformer), so the returned mask is all-ones."""
        tok = self.hf_tokenizer
        prefix = tok(PROMPT_PREFIX, add_special_tokens=False)["input_ids"]
        suffix = tok(PROMPT_SUFFIX, add_special_tokens=False)["input_ids"]
        bodies = tok(list(prompts),
                     add_special_tokens=False)["input_ids"]
        maxlen = self.cfg.max_text_len
        pad_id = tok.pad_token_id or 0
        ids, mask = [], []
        for body in bodies:
            body = body[:maxlen]
            npad = maxlen - len(body)
            ids.append(prefix + body + [pad_id] * npad + suffix)
            mask.append([1] * (len(prefix) + len(body)) + [0] * npad
                        + [1] * len(suffix))
        hidden = self._text_encode_jit(
            self.text_params, jnp.asarray(np.asarray(ids, np.int32)),
            jnp.asarray(np.asarray(mask, np.int32)))
        hidden = hidden[:, len(prefix):len(prefix) + maxlen]
        return (hidden.astype(self.dtype),
                jnp.ones(hidden.shape[:2], jnp.int32))

    # from_pretrained knobs the Ovis subclass overrides (the load
    # sequence itself is shared)
    config_cls: type = LongCatImagePipelineConfig
    _dit_cfg_from_diffusers = staticmethod(
        lambda d, txt_max_len: longcat_dit_config_from_diffusers(
            d, txt_max_len=txt_max_len))
    _loader_kwargs = {"time_prefix": "time_embed.timestep_embedder"}
    _default_max_text_len = 512

    @classmethod
    def from_pretrained(cls, model_dir: str, dtype=jnp.bfloat16,
                        seed: int = 0, mesh=None, cache_config=None,
                        max_text_len: int = None):
        """Build from a diffusers-format checkpoint (transformer/ +
        Qwen-LM text_encoder/ + tokenizer/ + AutoencoderKL vae/ +
        scheduler/).  Shared by LongCat-Image (+Edit) and Ovis-Image —
        the class attributes above carry the per-family deltas."""
        import json
        import os

        from transformers import AutoTokenizer

        from vllm_omni_tpu.model_loader import diffusers_loader as dl
        from vllm_omni_tpu.models.flux import loader as floader

        if max_text_len is None:
            max_text_len = cls._default_max_text_len
        dl.load_model_index(model_dir)
        tdir = os.path.join(model_dir, "transformer")
        with open(os.path.join(tdir, "config.json")) as f:
            dit_cfg = cls._dit_cfg_from_diffusers(
                json.load(f), txt_max_len=max_text_len)
        dit_params, _ = floader.load_mmdit_family(
            tdir, dit_cfg, dtype=dtype, **cls._loader_kwargs)
        text_params, text_cfg = dl.load_text_encoder(
            os.path.join(model_dir, "text_encoder"), dtype=dtype)
        vae_tree, vae_cfg = dl.load_image_vae(
            os.path.join(model_dir, "vae"), dtype=dtype,
            decoder=True, encoder=cls.needs_image_cond)
        config = cls.config_cls(
            text=text_cfg, dit=dit_cfg, vae=vae_cfg,
            max_text_len=max_text_len)
        pipe = cls(config, dtype=dtype, seed=seed, mesh=mesh,
                   cache_config=cache_config, init_weights=False)
        pipe.dit_params = pipe.wiring.place(dit_params)
        pipe.text_params = pipe.wiring.place(text_params)
        pipe.vae_params = pipe.wiring.place(vae_tree["decoder"])
        if cls.needs_image_cond:
            pipe.vae_encoder_params = pipe.wiring.place(
                vae_tree["encoder"])
        pipe.hf_tokenizer = AutoTokenizer.from_pretrained(
            os.path.join(model_dir, "tokenizer"))
        return pipe

    def _denoise_fn(self, grid_h, grid_w, sched_len, has_cond: bool):
        key = (grid_h, grid_w, sched_len, has_cond)
        if key in self._denoise_cache:
            return self._denoise_cache[key]
        cfg = self.cfg
        wiring = self.wiring
        cache_cfg = self.cache_config

        @jax.jit
        def run(dit_params, latents, ctx, ctx_mask, neg_ctx, neg_mask,
                sigmas, timesteps, gscale, num_steps, cond=None):
            schedule = fm.FlowMatchSchedule(sigmas=sigmas,
                                            timesteps=timesteps)
            do_cfg = neg_ctx is not None
            ctx_all = (jnp.concatenate([ctx, neg_ctx], 0)
                       if do_cfg else ctx)
            mask_all = (jnp.concatenate([ctx_mask, neg_mask], 0)
                        if do_cfg else ctx_mask)

            def eval_velocity(lat, i):
                t = jnp.broadcast_to(timesteps[i], (lat.shape[0],))
                s_gen = lat.shape[1]
                lat_model = (lat if cond is None
                             else jnp.concatenate([lat, cond], axis=1))
                lat_in = (jnp.concatenate([lat_model, lat_model], 0)
                          if do_cfg else lat_model)
                lat_in = wiring.constrain(lat_in)
                t_in = jnp.concatenate([t, t], 0) if do_cfg else t
                # condition tokens carry their own rope ids: modality
                # img_frame_coord+1 on the same grid (reference edit
                # pos ids, pipeline_longcat_image_edit.py:456-462)
                v = fdit.forward(
                    dit_params, cfg.dit, lat_in, ctx_all, None, t_in,
                    (grid_h, grid_w), txt_mask=mask_all,
                    cond_grids=(((grid_h, grid_w),) if cond is not None
                                else ()),
                )[:, :s_gen]
                if do_cfg:
                    v_pos, v_neg = jnp.split(v, 2, axis=0)
                    comb = v_neg + gscale * (v_pos - v_neg)
                    if cfg.cfg_renorm:
                        # rescale to the conditional prediction's norm
                        # (pipeline_longcat_image.py:463-471)
                        cn = jnp.linalg.norm(v_pos.astype(jnp.float32),
                                             axis=-1, keepdims=True)
                        nn_ = jnp.linalg.norm(comb.astype(jnp.float32),
                                              axis=-1, keepdims=True)
                        scale = jnp.clip(cn / (nn_ + 1e-8),
                                         cfg.cfg_renorm_min, 1.0)
                        comb = (comb.astype(jnp.float32) * scale).astype(
                            comb.dtype)
                    v = comb
                return v

            return step_cache.run_denoise_loop(
                cache_cfg, schedule, eval_velocity, latents, num_steps,
                solver=cfg.scheduler)

        self._denoise_cache[key] = run
        return run

    def _edit_cond(self, req, grid_h, grid_w, b):
        return None

    def forward(self, req: OmniDiffusionRequest) -> list[DiffusionOutput]:
        sp = req.sampling_params
        cfg = self.cfg
        mult = self.geometry_multiple
        if sp.height % mult or sp.width % mult:
            raise InvalidRequestError(
                f"height/width must be multiples of {mult}")
        if sp.num_inference_steps < 1:
            raise InvalidRequestError("num_inference_steps must be >= 1")
        grid_h = sp.height // mult
        grid_w = sp.width // mult
        seq_len = grid_h * grid_w
        prompts = req.prompt
        b = len(prompts)

        ctx, ctx_mask = self.encode_prompt(prompts)
        do_cfg = sp.guidance_scale > 1.0
        neg_ctx = neg_mask = None
        if do_cfg:
            neg_ctx, neg_mask = self.encode_prompt(
                [sp.negative_prompt] * b)

        seed = (sp.seed if sp.seed is not None
                else int(np.random.randint(0, 2 ** 31 - 1)))
        noise = jax.random.normal(
            jax.random.PRNGKey(seed),
            (b, seq_len, cfg.dit.in_channels), jnp.float32,
        ).astype(self.dtype)
        cond = self._edit_cond(req, grid_h, grid_w, b)

        num_steps = sp.num_inference_steps
        mu = fm.compute_dynamic_shift_mu(seq_len)
        schedule = fm.make_schedule(num_steps, use_dynamic_shifting=True,
                                    mu=mu)
        sched_len = max(8, 1 << (num_steps - 1).bit_length())
        sigmas = jnp.zeros((sched_len + 1,)).at[: num_steps + 1].set(
            schedule.sigmas)
        timesteps = jnp.zeros((sched_len,)).at[:num_steps].set(
            schedule.timesteps)
        run = self._denoise_fn(grid_h, grid_w, sched_len,
                               has_cond=cond is not None)
        latents, skipped = run(
            self.dit_params, noise, ctx, ctx_mask, neg_ctx, neg_mask,
            sigmas, timesteps, jnp.float32(sp.guidance_scale),
            jnp.int32(num_steps), cond=cond)
        self.last_skipped_steps = int(skipped)

        # unpack [B, gh*gw, pack^2*C] -> [B, H_lat, W_lat, C]
        c = cfg.vae.latent_channels
        p = cfg.pack
        x = latents.reshape(b, grid_h, grid_w, p, p, c)
        x = x.transpose(0, 1, 3, 2, 4, 5).reshape(
            b, grid_h * p, grid_w * p, c)
        img = self._vae_decode_jit(self.vae_params, x.astype(jnp.float32))
        img = np.asarray(jnp.clip(
            (img.astype(jnp.float32) + 1.0) * 127.5, 0, 255)
            .astype(jnp.uint8))
        return [
            DiffusionOutput(request_id=req.request_ids[i],
                            prompt=prompts[i], data=img[i],
                            output_type="image")
            for i in range(b)
        ]


class LongCatImageEditPipeline(LongCatImagePipeline):
    """Image + text -> image: VAE-encoded input latents appended to the
    sequence (reference: pipeline_longcat_image_edit.py:406-456)."""

    needs_image_cond = True

    def _edit_cond(self, req, grid_h, grid_w, b):
        sp = req.sampling_params
        image = sp.image if sp.image is not None else sp.extra.get("image")
        if image is None:
            raise InvalidRequestError(
                "LongCatImageEditPipeline needs sampling_params.image")
        img = np.asarray(image)
        if img.dtype == np.uint8:
            img = img.astype(np.float32) / 127.5 - 1.0
        mult = self.geometry_multiple
        th, tw = grid_h * mult, grid_w * mult
        if img.shape[:2] != (th, tw):
            img = np.asarray(jax.image.resize(
                jnp.asarray(img), (th, tw, 3), "bilinear"))
        if self.vae_encoder_params is None:
            self.vae_encoder_params = self.wiring.place(
                vae_mod.init_encoder(
                    jax.random.PRNGKey(self._seed + 1), self.cfg.vae,
                    jnp.float32))
        lat = self._vae_encode_jit(
            self.vae_encoder_params, jnp.asarray(img, jnp.float32)[None])
        # pack 2x2 into channels, mirroring the generated latents
        p = self.cfg.pack
        c = self.cfg.vae.latent_channels
        h, w = lat.shape[1:3]
        x = lat.reshape(1, h // p, p, w // p, p, c)
        x = x.transpose(0, 1, 3, 2, 4, 5).reshape(
            1, (h // p) * (w // p), p * p * c)
        return jnp.repeat(x.astype(self.dtype), b, axis=0)
