"""Ovis-Image text->image pipeline.

Reference: vllm_omni/diffusion/models/ovis_image/ — a Flux-architecture
MMDiT (6 double + 27 single stream blocks, 24 heads x 128,
joint_attention_dim 2048, ovis_image_transformer.py:340-396) with plain
timestep conditioning (no pooled text vector, no embedded guidance) and
TRUE classifier-free guidance.  Deltas over the shared skeleton: an RMS
norm on text states before the context embedder
(context_embedder_norm), SwiGLU double-block FFs, a silu-gated
single-block MLP, text rope ids (0, n, n), and a Qwen3 LM text encoder
whose embeddings are mask-zeroed then sliced past the chat-template
prefix (pipeline_ovis_image.py:216-256).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field

import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.models.common.transformer import TransformerConfig
from vllm_omni_tpu.models.flux.transformer import FluxDiTConfig
from vllm_omni_tpu.models.longcat_image.pipeline import (
    LongCatImagePipeline,
)
from vllm_omni_tpu.models.qwen_image.vae import VAEConfig

# reference system prompt + drop index (pipeline_ovis_image.py:186-189)
SYSTEM_PROMPT = (
    "Describe the image by detailing the color, quantity, text, shape, "
    "size, texture, spatial\n        relationships of the objects and "
    "background: ")
USER_PROMPT_BEGIN_ID = 28


def _ovis_flags(base: FluxDiTConfig) -> FluxDiTConfig:
    return dataclasses.replace(
        base, guidance_embed=False, pooled_dim=0,
        ctx_rmsnorm=True, ff_double="swiglu", ff_single_gated=True,
        txt_rope_arange=True)


def _ovis_dit() -> FluxDiTConfig:
    return _ovis_flags(FluxDiTConfig(
        num_double_blocks=6, num_single_blocks=27, num_heads=24,
        head_dim=128, ctx_dim=2048,
    ))


def ovis_dit_config_from_diffusers(d: dict) -> FluxDiTConfig:
    """OvisImageTransformer2DModel config.json -> FluxDiTConfig."""
    in_ch = d.get("in_channels", 64)
    return _ovis_flags(FluxDiTConfig(
        in_channels=in_ch,
        out_channels=d.get("out_channels") or in_ch,
        num_double_blocks=d.get("num_layers", 6),
        num_single_blocks=d.get("num_single_layers", 27),
        num_heads=d.get("num_attention_heads", 24),
        head_dim=d.get("attention_head_dim", 128),
        ctx_dim=d.get("joint_attention_dim", 2048),
        axes_dims=tuple(d.get("axes_dims_rope", (16, 56, 56))),
        rope_interleaved=True,
    ))


@dataclass(frozen=True)
class OvisImagePipelineConfig:
    text: TransformerConfig = field(
        default_factory=lambda: TransformerConfig(hidden_size=2048))
    dit: FluxDiTConfig = field(default_factory=_ovis_dit)
    vae: VAEConfig = field(default_factory=VAEConfig)
    max_text_len: int = 64
    scheduler: str = "euler"
    pack: int = 2
    cfg_renorm: bool = False      # Ovis runs plain CFG
    cfg_renorm_min: float = 0.0

    @staticmethod
    def tiny() -> "OvisImagePipelineConfig":
        return OvisImagePipelineConfig(
            text=TransformerConfig.tiny(vocab_size=256),
            dit=_ovis_flags(FluxDiTConfig.tiny()),
            vae=VAEConfig.tiny(),
            max_text_len=32,
        )


class OvisImagePipeline(LongCatImagePipeline):
    """Text -> image (Ovis geometry over the shared Flux MMDiT)."""

    config_cls = OvisImagePipelineConfig
    _dit_cfg_from_diffusers = staticmethod(
        lambda d, txt_max_len: ovis_dit_config_from_diffusers(d))
    _loader_kwargs = {"time_prefix": "timestep_embedder",
                      "ctx_norm_key": "context_embedder_norm"}
    _default_max_text_len = 256

    def _encode_prompt_hf(self, prompts: list[str]):
        """Reference encode (pipeline_ovis_image.py:216-256): chat-
        template wrap -> Qwen3 LM last hidden -> zero padded positions ->
        drop the first USER_PROMPT_BEGIN_ID (template preamble) tokens.
        Right padding keeps pads causally invisible to real tokens, so no
        LM attention mask is needed."""
        tok = self.hf_tokenizer
        texts = []
        for p in prompts:
            msg = [{"role": "user", "content": SYSTEM_PROMPT + p}]
            try:
                texts.append(tok.apply_chat_template(
                    msg, tokenize=False, add_generation_prompt=True,
                    enable_thinking=False))
            except Exception:
                # tokenizer without a chat template (synthetic tests):
                # the Qwen3 non-thinking layout, spelled out
                texts.append(
                    f"<|im_start|>user\n{SYSTEM_PROMPT + p}<|im_end|>\n"
                    "<|im_start|>assistant\n<think>\n\n</think>\n\n")
        maxlen = self.cfg.max_text_len + USER_PROMPT_BEGIN_ID
        # the preamble drop and the causal-invisibility of pads both
        # assume right padding; generation-oriented Qwen configs ship
        # padding_side='left'
        tok.padding_side = "right"
        enc = tok(texts, padding="max_length", truncation=True,
                  max_length=maxlen, add_special_tokens=False)
        ids = np.asarray(enc["input_ids"], np.int32)
        mask = np.asarray(enc["attention_mask"], np.int32)
        hidden = self._text_encode_jit(self.text_params,
                                       jnp.asarray(ids), None)
        hidden = hidden * jnp.asarray(mask)[..., None]
        hidden = hidden[:, USER_PROMPT_BEGIN_ID:]
        # the reference DiT attends the whole (zeroed-pad) span
        return (hidden.astype(self.dtype),
                jnp.ones(hidden.shape[:2], jnp.int32))

