"""Ovis-Image text->image pipeline.

Reference: vllm_omni/diffusion/models/ovis_image/ — a Flux-architecture
MMDiT (6 double + 27 single stream blocks, 24 heads x 128,
joint_attention_dim 2048, ovis_image_transformer.py:340-396) with plain
timestep conditioning (no pooled text vector, no embedded guidance) and
TRUE classifier-free guidance.  That is exactly the LongCat-Image
execution shape, so this pipeline reuses it at the Ovis geometry with
plain CFG (no renorm)."""

from __future__ import annotations

from dataclasses import dataclass, field

from vllm_omni_tpu.models.common.transformer import TransformerConfig
from vllm_omni_tpu.models.flux.transformer import FluxDiTConfig
from vllm_omni_tpu.models.longcat_image.pipeline import (
    LongCatImagePipeline,
    _longcat_dit,
)
from vllm_omni_tpu.models.qwen_image.vae import VAEConfig


def _ovis_dit() -> FluxDiTConfig:
    return _longcat_dit(FluxDiTConfig(
        num_double_blocks=6, num_single_blocks=27, num_heads=24,
        head_dim=128, ctx_dim=2048,
    ))


@dataclass(frozen=True)
class OvisImagePipelineConfig:
    text: TransformerConfig = field(
        default_factory=lambda: TransformerConfig(hidden_size=2048))
    dit: FluxDiTConfig = field(default_factory=_ovis_dit)
    vae: VAEConfig = field(default_factory=VAEConfig)
    max_text_len: int = 64
    scheduler: str = "euler"
    pack: int = 2
    cfg_renorm: bool = False      # Ovis runs plain CFG
    cfg_renorm_min: float = 0.0

    @staticmethod
    def tiny() -> "OvisImagePipelineConfig":
        return OvisImagePipelineConfig(
            text=TransformerConfig.tiny(vocab_size=256),
            dit=_longcat_dit(FluxDiTConfig.tiny()),
            vae=VAEConfig.tiny(),
            max_text_len=32,
        )


class OvisImagePipeline(LongCatImagePipeline):
    """Text -> image (Ovis geometry over the shared Flux MMDiT)."""

    config_cls = OvisImagePipelineConfig
