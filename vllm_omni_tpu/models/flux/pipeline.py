"""Flux text-to-image pipeline (guidance-distilled MMDiT).

Reference: vllm_omni/diffusion/models/flux/ (registry entry FluxPipeline,
diffusion/registry.py:16-102).  Structure mirrors QwenImagePipeline —
text encode → flow-match denoise → VAE decode — with the two Flux
differences: the double+single-stream transformer (flux/transformer.py)
and *embedded* guidance instead of CFG batch-doubling (the distilled
model conditions on the guidance scale directly, so every step runs a
single batch — no cfg axis needed).

The pooled conditioning vector (CLIP in the original) is the masked mean
of the text-encoder hidden states projected by the transformer's pooled
head — one encoder serves both roles (documented deviation; the loader
can override with a real pooled projection later).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.diffusion import cache as step_cache
from vllm_omni_tpu.diffusion import scheduler as fm
from vllm_omni_tpu.diffusion.request import (
    DiffusionOutput,
    InvalidRequestError,
    OmniDiffusionRequest,
)
from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.models.common import clip_text as clip_mod
from vllm_omni_tpu.models.common import t5 as t5_mod
from vllm_omni_tpu.models.common.transformer import (
    TransformerConfig,
    forward_hidden,
    init_params as init_text_params,
)
from vllm_omni_tpu.models.flux import transformer as fdit
from vllm_omni_tpu.models.flux.transformer import FluxDiTConfig
from vllm_omni_tpu.models.qwen_image import vae as vae_mod
from vllm_omni_tpu.models.qwen_image.vae import VAEConfig
from vllm_omni_tpu.utils.tokenizer import ByteTokenizer

logger = init_logger(__name__)


@dataclass(frozen=True)
class FluxPipelineConfig:
    # text: generic in-house encoder (TransformerConfig) or the real T5
    # stack (t5.T5Config); from_pretrained builds the latter and adds
    # the CLIP pooled tower below
    text: TransformerConfig = field(default_factory=TransformerConfig)
    dit: FluxDiTConfig = field(default_factory=FluxDiTConfig)
    vae: VAEConfig = field(default_factory=VAEConfig)
    # real checkpoints pool prompt conditioning from CLIP-L
    # (text_encoder/ beside the T5 text_encoder_2/); None = pooled
    # vector is the masked mean of the text hidden states (documented
    # deviation for random-init configs)
    clip: "clip_mod.CLIPTextConfig | None" = None
    max_text_len: int = 64
    clip_text_len: int = 77
    shift: float = 1.0
    # FLUX.1-dev ships use_dynamic_shifting=true: the sigma schedule
    # shifts with the image token count (diffusers calculate_shift)
    use_dynamic_shifting: bool = False
    base_shift: float = 0.5
    max_shift: float = 1.15
    # "euler" | "unipc" (order-2 multistep, diffusion/scheduler.py)
    scheduler: str = "euler"
    pack: int = 2  # 2x2 latent packing into channels

    @staticmethod
    def tiny() -> "FluxPipelineConfig":
        return FluxPipelineConfig(
            text=TransformerConfig.tiny(vocab_size=256),
            dit=FluxDiTConfig.tiny(),
            vae=VAEConfig.tiny(),
        )


class FluxPipeline:
    """Text -> image, guidance embedded (no CFG doubling)."""

    output_type = "image"

    @property
    def geometry_multiple(self) -> int:
        """Height/width granularity (the engine's warmup geometry hook):
        Flux packs 2x2 latents into channels instead of a DiT patch_size."""
        return self.cfg.vae.spatial_ratio * self.cfg.pack

    def __init__(self, config: FluxPipelineConfig, dtype=jnp.bfloat16,
                 seed: int = 0, mesh=None, cache_config=None,
                 init_weights: bool = True):
        from vllm_omni_tpu.parallel.pipeline_mesh import MeshWiring

        self.cfg = config
        self.dtype = dtype
        self.mesh = mesh
        self.cache_config = cache_config
        # dp only: guidance is embedded (no CFG batch to put on a cfg
        # axis) and SP/TP for the single-stream blocks are not wired —
        # refuse rather than silently ignore (VERDICT r2 weak #3)
        self.wiring = MeshWiring(mesh, type(self).__name__).validate(
            {"dp"})
        self._t5_text = isinstance(config.text, t5_mod.T5Config)
        text_width = (config.text.d_model if self._t5_text
                      else config.text.hidden_size)
        if text_width != config.dit.ctx_dim:
            raise ValueError("text hidden width must equal dit ctx_dim")
        if config.clip is not None:
            if config.dit.pooled_dim != config.clip.hidden_size:
                raise ValueError(
                    "pooled_dim must equal the CLIP tower hidden size")
        elif config.dit.pooled_dim != text_width:
            raise ValueError(
                "pooled_dim must equal text hidden size (the pooled "
                "vector is the masked mean of text hidden states)"
            )
        want_in = config.vae.latent_channels * config.pack ** 2
        if config.dit.in_channels != want_in:
            raise ValueError(
                f"dit.in_channels must be latent*pack^2 = {want_in}"
            )
        self.tokenizer = ByteTokenizer(config.text.vocab_size)
        self.hf_tokenizer = None       # T5 (ctx) — set by from_pretrained
        self.hf_clip_tokenizer = None  # CLIP (pooled)
        self.clip_params = None
        if config.clip is not None:
            # byte fallback so a random-init CLIP tower still tokenizes
            self._clip_fallback_tok = ByteTokenizer(
                config.clip.vocab_size)
        k1, k2, k3, k4 = jax.random.split(jax.random.PRNGKey(seed), 4)
        logger.info("Initializing FluxPipeline params (dtype=%s)", dtype)
        if init_weights:
            self.text_params = self.wiring.place(
                t5_mod.init_params(k1, config.text, dtype)
                if self._t5_text
                else init_text_params(k1, config.text, dtype))
            self.dit_params = self.wiring.place(
                fdit.init_params(k2, config.dit, dtype))
            self.vae_params = self.wiring.place(
                vae_mod.init_decoder(k3, config.vae, dtype))
            if config.clip is not None:
                self.clip_params = self.wiring.place(
                    clip_mod.init_params(k4, config.clip, dtype))
        else:
            self.text_params = self.dit_params = self.vae_params = None
        self._denoise_cache: dict = {}
        # jitted once (per-request jax.jit(lambda) would recompile);
        # params are explicit ARGUMENTS, never closure constants — else
        # sleep()/weight swaps silently don't reach the executable
        if self._t5_text:
            self._text_encode_jit = jax.jit(
                lambda p, i, m: t5_mod.forward(p, self.cfg.text, i, m))
        else:
            self._text_encode_jit = jax.jit(
                lambda p, i: forward_hidden(p, self.cfg.text, i))
        if config.clip is not None:
            self._clip_encode_jit = jax.jit(
                lambda p, i: clip_mod.forward(p, self.cfg.clip, i)[1])
        self._vae_decode_jit = jax.jit(
            lambda pp, l: vae_mod.decode(pp, self.cfg.vae, l))

    # ------------------------------------------------------------- encode
    def encode_prompt(self, prompts: list[str]):
        if self.hf_tokenizer is not None:
            # diffusers FluxPipeline convention: T5 runs UNMASKED over
            # the full padded sequence and the DiT attends every text
            # token — real checkpoints were trained that way, so the
            # mask is all-ones here
            enc = self.hf_tokenizer(
                prompts, padding="max_length", truncation=True,
                max_length=self.cfg.max_text_len)
            ids = np.asarray(enc["input_ids"], np.int32)
            mask = jnp.ones(ids.shape, jnp.int32)
        else:
            ids, lens = self.tokenizer.batch_encode(
                prompts, self.cfg.max_text_len)
            mask = jnp.asarray(
                (np.arange(self.cfg.max_text_len)[None, :]
                 < lens[:, None]).astype(np.int32))
        if self._t5_text:
            hidden = self._text_encode_jit(self.text_params,
                                           jnp.asarray(ids), mask)
        else:
            hidden = self._text_encode_jit(self.text_params,
                                           jnp.asarray(ids))
        if self.cfg.clip is not None:
            # real pooled conditioning: the CLIP-L tower's EOS hidden
            # (reference: FluxPipeline text_encoder + tokenizer pair);
            # without a checkpoint tokenizer the byte fallback keeps
            # random-init configs runnable
            if self.hf_clip_tokenizer is not None:
                cenc = self.hf_clip_tokenizer(
                    prompts, padding="max_length", truncation=True,
                    max_length=self.cfg.clip_text_len)
                cids = np.asarray(cenc["input_ids"], np.int32)
            else:
                cids, _ = self._clip_fallback_tok.batch_encode(
                    prompts, self.cfg.clip_text_len)
            pooled = self._clip_encode_jit(self.clip_params,
                                           jnp.asarray(cids))
        else:
            # pooled vector: masked mean over real tokens
            denom = jnp.maximum(mask.sum(axis=1, keepdims=True), 1)
            pooled = (hidden * mask[..., None]).sum(axis=1) / denom
        return hidden, mask, pooled.astype(hidden.dtype)

    @classmethod
    def from_pretrained(cls, model_dir: str, dtype=jnp.bfloat16,
                        seed: int = 0, mesh=None, cache_config=None,
                        max_text_len: int = 512) -> "FluxPipeline":
        """Build from a diffusers-format FLUX.1 checkpoint directory
        (transformer/ + text_encoder/ CLIP-L + text_encoder_2/ T5 +
        tokenizer{,_2}/ + vae/).  Every component loads real weights or
        this raises."""
        import os

        from transformers import AutoTokenizer

        from vllm_omni_tpu.model_loader import diffusers_loader as dl
        from vllm_omni_tpu.models.flux import loader as floader

        dl.load_model_index(model_dir)  # validates layout
        dit_params, dit_cfg = floader.load_flux_dit(
            os.path.join(model_dir, "transformer"), dtype=dtype)
        te2 = os.path.join(model_dir, "text_encoder_2")
        import json

        with open(os.path.join(te2, "config.json")) as f:
            text_cfg = t5_mod.T5Config.from_hf(json.load(f))
        text_params, _ = t5_mod.load_t5(te2, cfg=text_cfg, dtype=dtype)
        te1 = os.path.join(model_dir, "text_encoder")
        with open(os.path.join(te1, "config.json")) as f:
            clip_cfg = clip_mod.CLIPTextConfig.from_hf(json.load(f))
        clip_params, _ = clip_mod.load_clip_text(te1, cfg=clip_cfg,
                                                 dtype=dtype)
        vae_tree, vae_cfg = dl.load_image_vae(
            os.path.join(model_dir, "vae"), dtype=dtype, decoder=True)
        sched = dl.scheduler_config(model_dir)
        config = FluxPipelineConfig(
            text=text_cfg, dit=dit_cfg, vae=vae_cfg, clip=clip_cfg,
            max_text_len=max_text_len,
            clip_text_len=clip_cfg.max_positions,
            shift=sched.get("shift", 1.0),
            use_dynamic_shifting=sched.get("use_dynamic_shifting",
                                           False),
            base_shift=sched.get("base_shift", 0.5),
            max_shift=sched.get("max_shift", 1.15),
        )
        pipe = cls(config, dtype=dtype, seed=seed, mesh=mesh,
                   cache_config=cache_config, init_weights=False)
        pipe.dit_params = pipe.wiring.place(dit_params)
        pipe.text_params = pipe.wiring.place(text_params)
        pipe.clip_params = pipe.wiring.place(clip_params)
        pipe.vae_params = pipe.wiring.place(vae_tree["decoder"])
        pipe.hf_tokenizer = AutoTokenizer.from_pretrained(
            os.path.join(model_dir, "tokenizer_2"))
        pipe.hf_clip_tokenizer = AutoTokenizer.from_pretrained(
            os.path.join(model_dir, "tokenizer"))
        return pipe

    # ------------------------------------------------------------ denoise
    def _denoise_fn(self, grid_h, grid_w, sched_len):
        key = (grid_h, grid_w, sched_len)
        if key in self._denoise_cache:
            return self._denoise_cache[key]
        cfg = self.cfg
        cache_cfg = self.cache_config

        @jax.jit
        def run(dit_params, latents, ctx, ctx_mask, pooled, sigmas,
                timesteps, gscale, num_steps):
            schedule = fm.FlowMatchSchedule(sigmas=sigmas,
                                            timesteps=timesteps)
            b = latents.shape[0]
            guidance = jnp.broadcast_to(gscale, (b,)).astype(jnp.float32)

            def eval_velocity(lat, i):
                t = jnp.broadcast_to(timesteps[i], (b,))
                return fdit.forward(
                    dit_params, cfg.dit, lat, ctx, pooled, t,
                    (grid_h, grid_w), guidance=guidance, txt_mask=ctx_mask,
                )

            return step_cache.run_denoise_loop(
                cache_cfg, schedule, eval_velocity, latents, num_steps,
                solver=cfg.scheduler)

        self._denoise_cache[key] = run
        return run

    # ------------------------------------------------------------ forward
    def forward(self, req: OmniDiffusionRequest) -> list[DiffusionOutput]:
        sp = req.sampling_params
        cfg = self.cfg
        mult = cfg.vae.spatial_ratio * cfg.pack
        if sp.height % mult or sp.width % mult:
            raise InvalidRequestError(
                f"height/width must be multiples of {mult}")
        lat_h = sp.height // cfg.vae.spatial_ratio
        lat_w = sp.width // cfg.vae.spatial_ratio
        gh, gw = lat_h // cfg.pack, lat_w // cfg.pack
        prompts = req.prompt
        b = len(prompts)

        ctx, ctx_mask, pooled = self.encode_prompt(prompts)
        seed = (sp.seed if sp.seed is not None
                else int(np.random.randint(0, 2 ** 31 - 1)))
        # noise lives in packed-token space [B, gh*gw, C*pack^2]
        noise = jax.random.normal(
            jax.random.PRNGKey(seed),
            (b, gh * gw, cfg.dit.in_channels), self.dtype,
        )
        num_steps = sp.num_inference_steps
        sched_len = max(8, 1 << (num_steps - 1).bit_length())
        schedule = fm.make_schedule(
            num_steps, shift=cfg.shift,
            use_dynamic_shifting=cfg.use_dynamic_shifting,
            mu=fm.compute_dynamic_shift_mu(
                gh * gw, base_shift=cfg.base_shift,
                max_shift=cfg.max_shift))
        sigmas = jnp.zeros((sched_len + 1,)).at[: num_steps + 1].set(
            schedule.sigmas)
        timesteps = jnp.zeros((sched_len,)).at[:num_steps].set(
            schedule.timesteps)
        run = self._denoise_fn(gh, gw, sched_len)
        latents, skipped = run(
            self.dit_params, noise, ctx, ctx_mask, pooled, sigmas,
            timesteps, jnp.float32(sp.guidance_scale),
            jnp.int32(num_steps))
        self.last_skipped_steps = int(skipped)

        # unpack tokens -> latent grid [B, lat_h, lat_w, C]
        c = cfg.vae.latent_channels
        p = cfg.pack
        lat = latents.reshape(b, gh, gw, p, p, c).transpose(0, 1, 3, 2, 4, 5)
        lat = lat.reshape(b, lat_h, lat_w, c)
        imgs = self._vae_decode_jit(self.vae_params, lat)
        imgs = np.asarray(imgs)
        imgs = ((np.clip(imgs, -1, 1) + 1) * 127.5).astype(np.uint8)
        return [
            DiffusionOutput(
                request_id=req.request_ids[i], prompt=prompts[i],
                data=imgs[i], output_type="image",
            )
            for i in range(b)
        ]
