"""Diffusers-format Flux transformer loader.

Streams a FluxTransformer2DModel directory (the naming published
black-forest-labs/FLUX.1-* repos ship) into models/flux/transformer.py
params.  The in-tree layout fuses projections the checkpoint stores
separately — to_q/to_k/to_v stack into img_qkv / txt_qkv, and the
single-stream to_q/to_k/to_v/proj_mlp stack into lin1 — so tensors are
collected first and assembled per block (reference:
vllm_omni/diffusion/models/flux/ loading via DiffusersPipelineLoader).
"""

from __future__ import annotations

import json
import os

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.models.flux.transformer import (
    FluxDiTConfig,
    init_params,
)

logger = init_logger(__name__)


def dit_config_from_diffusers(d: dict) -> FluxDiTConfig:
    return FluxDiTConfig(
        in_channels=d.get("in_channels", 64),
        out_channels=d.get("out_channels") or d.get("in_channels", 64),
        num_double_blocks=d.get("num_layers", 19),
        num_single_blocks=d.get("num_single_layers", 38),
        num_heads=d.get("num_attention_heads", 24),
        head_dim=d.get("attention_head_dim", 128),
        ctx_dim=d.get("joint_attention_dim", 4096),
        pooled_dim=d.get("pooled_projection_dim", 768),
        axes_dims=tuple(d.get("axes_dims_rope", (16, 56, 56))),
        guidance_embed=d.get("guidance_embeds", True),
        rope_interleaved=True,  # real checkpoints use diffusers pairing
    )


def _routing(cfg: FluxDiTConfig,
             time_prefix: str = "time_text_embed.timestep_embedder",
             ctx_norm_key: str = None) -> dict:
    """hf tensor name -> placement: ("direct", path) writes the leaf;
    ("fuse", path, slot, n_slots) buffers one slot of a fused leaf.

    ``time_prefix``/``ctx_norm_key`` absorb the naming deltas of the
    MMDiT siblings: LongCat nests its timestep MLP under
    ``time_embed.timestep_embedder`` (longcat_image_transformer.py:418),
    Ovis under a bare ``timestep_embedder`` with an extra
    ``context_embedder_norm`` RMSNorm (ovis_image_transformer.py:396-400).
    """
    r: dict[str, tuple] = {}

    def lin(hf, *path):
        r[f"{hf}.weight"] = ("direct", path + ("w",))
        r[f"{hf}.bias"] = ("direct", path + ("b",))

    def fuse(names, *path):
        for s, n in enumerate(names):
            r[f"{n}.weight"] = ("fuse", path + ("w",), s, len(names))
            r[f"{n}.bias"] = ("fuse", path + ("b",), s, len(names))

    lin("x_embedder", "img_in")
    lin("context_embedder", "txt_in")
    lin(f"{time_prefix}.linear_1", "time_in1")
    lin(f"{time_prefix}.linear_2", "time_in2")
    lin("norm_out.linear", "norm_out_mod")
    lin("proj_out", "proj_out")
    if ctx_norm_key:
        r[f"{ctx_norm_key}.weight"] = ("direct", ("txt_norm", "w"))
    if cfg.pooled_dim:
        lin("time_text_embed.text_embedder.linear_1", "pooled_in1")
        lin("time_text_embed.text_embedder.linear_2", "pooled_in2")
    if cfg.guidance_embed:
        lin("time_text_embed.guidance_embedder.linear_1",
            "guidance_in1")
        lin("time_text_embed.guidance_embedder.linear_2",
            "guidance_in2")
    for i in range(cfg.num_double_blocks):
        b = f"transformer_blocks.{i}"
        t = ("double", i)
        lin(f"{b}.norm1.linear", *t, "img_mod")
        lin(f"{b}.norm1_context.linear", *t, "txt_mod")
        fuse([f"{b}.attn.to_q", f"{b}.attn.to_k", f"{b}.attn.to_v"],
             *t, "img_qkv")
        fuse([f"{b}.attn.add_q_proj", f"{b}.attn.add_k_proj",
              f"{b}.attn.add_v_proj"], *t, "txt_qkv")
        for hf, ours in (("norm_q", "img_norm_q"),
                         ("norm_k", "img_norm_k"),
                         ("norm_added_q", "txt_norm_q"),
                         ("norm_added_k", "txt_norm_k")):
            r[f"{b}.attn.{hf}.weight"] = ("direct", t + (ours, "w"))
        lin(f"{b}.attn.to_out.0", *t, "img_out")
        lin(f"{b}.attn.to_add_out", *t, "txt_out")
        lin(f"{b}.ff.net.0.proj", *t, "img_mlp1")
        lin(f"{b}.ff.net.2", *t, "img_mlp2")
        lin(f"{b}.ff_context.net.0.proj", *t, "txt_mlp1")
        lin(f"{b}.ff_context.net.2", *t, "txt_mlp2")
    for i in range(cfg.num_single_blocks):
        b = f"single_transformer_blocks.{i}"
        t = ("single", i)
        lin(f"{b}.norm.linear", *t, "mod")
        fuse([f"{b}.attn.to_q", f"{b}.attn.to_k", f"{b}.attn.to_v",
              f"{b}.proj_mlp"], *t, "lin1")
        r[f"{b}.attn.norm_q.weight"] = ("direct", t + ("norm_q", "w"))
        r[f"{b}.attn.norm_k.weight"] = ("direct", t + ("norm_k", "w"))
        lin(f"{b}.proj_out", *t, "lin2")
    return r


def load_flux_dit(model_dir: str, cfg: FluxDiTConfig = None,
                  dtype=jnp.bfloat16):
    """Streaming load of a FluxTransformer2DModel directory."""
    if cfg is None:
        with open(os.path.join(model_dir, "config.json")) as f:
            cfg = dit_config_from_diffusers(json.load(f))
    return load_mmdit_family(model_dir, cfg, dtype=dtype)


def load_mmdit_family(
    model_dir: str, cfg: FluxDiTConfig, dtype=jnp.bfloat16,
    time_prefix: str = "time_text_embed.timestep_embedder",
    ctx_norm_key: str = None,
):
    """Streaming load for the Flux MMDiT family (Flux / LongCat-Image /
    Ovis-Image): tensors place (or buffer, for fused leaves) as shards
    decode — peak host memory stays near one shard plus the pending
    fusion partners, not the full ~24 GB state dict."""
    from vllm_omni_tpu.model_loader.safetensors_loader import (
        iter_safetensors,
    )

    routing = _routing(cfg, time_prefix=time_prefix,
                       ctx_norm_key=ctx_norm_key)
    shapes = jax.eval_shape(
        lambda: init_params(jax.random.PRNGKey(0), cfg, jnp.float32))
    return load_routed(model_dir, routing, shapes, dtype), cfg


def load_routed(model_dir: str, routing: dict, shapes, dtype,
                transforms: dict = None):
    """Streaming routed checkpoint load into a param tree shaped like
    ``shapes`` (a jax.eval_shape result).  2-D tensors transpose from HF
    [out, in] to our [in, out]; "fuse" routes buffer partner tensors and
    concatenate along the output axis; "raw" skips the transpose.
    ``transforms`` maps tensor names to array->array callables applied
    BEFORE routing (e.g. reshaping a patch-conv kernel into the packed-
    token matmul layout).  Raises unless EVERY leaf of the target tree
    is covered with the exact shape."""
    from vllm_omni_tpu.model_loader.safetensors_loader import (
        iter_safetensors,
    )

    p = jax.tree.map(lambda _: None, shapes,
                     is_leaf=lambda x: not isinstance(x, (dict, list)))

    def node_at(tree, path):
        for key in path[:-1]:
            tree = tree[key]
        return tree

    pending: dict[tuple, dict[int, np.ndarray]] = {}
    for name, arr in iter_safetensors(
            model_dir, name_filter=lambda nm: nm in routing):
        route = routing[name]
        if transforms and name in transforms:
            arr = transforms[name](arr)
        elif arr.ndim == 2 and route[0] != "raw":
            arr = np.ascontiguousarray(arr.T)
        if route[0] in ("direct", "raw"):
            path = route[1]
            node_at(p, path)[path[-1]] = jnp.asarray(arr, dtype)
            continue
        _, path, slot, n_slots = route
        slots = pending.setdefault(path, {})
        slots[slot] = arr
        if len(slots) == n_slots:
            axis = 1 if slots[0].ndim == 2 else 0
            fused = np.concatenate([slots[s] for s in range(n_slots)],
                                   axis=axis)
            node_at(p, path)[path[-1]] = jnp.asarray(fused, dtype)
            del pending[path]

    if pending:
        raise ValueError(
            f"{model_dir}: {len(pending)} fused leaves missing slots "
            f"(e.g. {next(iter(pending))})")
    # every leaf must match the init layout exactly — a missing or
    # misshaped tensor raises here, not at trace time
    for path, want in jax.tree.leaves_with_path(shapes):
        keys = tuple(
            k.key if hasattr(k, "key") else k.idx for k in path)
        got = node_at(p, keys).get(keys[-1])
        if got is None or tuple(got.shape) != tuple(want.shape):
            raise ValueError(
                f"{model_dir}: leaf {jax.tree_util.keystr(path)} "
                f"{'missing' if got is None else tuple(got.shape)} != "
                f"{tuple(want.shape)}")
    return p
