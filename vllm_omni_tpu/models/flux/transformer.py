"""Flux-style MMDiT: double-stream + single-stream joint attention.

Reference: vllm_omni/diffusion/models/flux/ (FluxPipeline,
diffusion/registry.py:16-102).  The second joint-attention family next to
Qwen-Image, proving the MMDiT abstraction generalizes (VERDICT r1
next-step #8): where Qwen-Image runs double-stream blocks end-to-end,
Flux runs N double-stream blocks (separate text/image projections, joint
attention) followed by M *single-stream* blocks operating on the
concatenated sequence with a fused qkv+mlp projection, plus a guidance
embedding folded into the timestep conditioning.

Same TPU idioms as qwen_image/transformer.py: functional params, Pallas
flash attention over the joint sequence, 3-axis rope, AdaLN modulation
fused by XLA.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from vllm_omni_tpu.models.common import nn
from vllm_omni_tpu.ops import flash_attention, rms_norm


@dataclass(frozen=True)
class FluxDiTConfig:
    in_channels: int = 64  # 16 VAE latent channels x 2x2 packing
    out_channels: int = 64
    num_double_blocks: int = 19
    num_single_blocks: int = 38
    num_heads: int = 24
    head_dim: int = 128
    ctx_dim: int = 4096  # text-encoder feature dim
    pooled_dim: int = 768  # pooled conditioning vector width
    axes_dims: tuple[int, int, int] = (16, 56, 56)
    theta: float = 10000.0
    mlp_ratio: float = 4.0
    guidance_embed: bool = True
    # rotary pairing convention: False = half-split (TPU-native default),
    # True = interleaved pairs — the diffusers FluxTransformer2DModel
    # convention real checkpoints were trained with (apply_rotary_emb
    # use_real_unbind_dim=-1); from_pretrained sets this
    rope_interleaved: bool = False
    # ---- MMDiT family variants: LongCat-Image / Ovis-Image share the
    # Flux double+single skeleton with these deltas (reference:
    # longcat_image_transformer.py:505, ovis_image_transformer.py:340)
    # text rope rows/cols = arange (LongCat prepare_pos_ids type="text",
    # Ovis text_ids) instead of Flux's zeros
    txt_rope_arange: bool = False
    # axis-0 coordinate of generated-image tokens (LongCat modality 1)
    img_frame_coord: float = 0.0
    # row/col offset of generated-image tokens (LongCat starts the image
    # grid at tokenizer_max_length)
    img_rope_offset: int = 0
    # RMSNorm on text states before the context embedder (Ovis
    # context_embedder_norm)
    ctx_rmsnorm: bool = False
    # double-block feed-forward: "gelu" (Flux gelu-approximate) |
    # "geglu" (LongCat, diffusers FeedForward default) | "swiglu" (Ovis)
    ff_double: str = "gelu"
    # single-block MLP silu-gated with a doubled projection (Ovis)
    ff_single_gated: bool = False

    @property
    def inner_dim(self) -> int:
        return self.num_heads * self.head_dim

    @staticmethod
    def tiny() -> "FluxDiTConfig":
        return FluxDiTConfig(
            in_channels=16, out_channels=16, num_double_blocks=2,
            num_single_blocks=2, num_heads=4, head_dim=32, ctx_dim=64,
            pooled_dim=64, axes_dims=(8, 12, 12),
        )


def init_params(key, cfg: FluxDiTConfig, dtype=jnp.float32):
    inner = cfg.inner_dim
    mlp = int(inner * cfg.mlp_ratio)
    # gated FFs project value+gate in one matmul
    mlp1_out = mlp * (2 if cfg.ff_double in ("geglu", "swiglu") else 1)
    single_mlp = mlp * (2 if cfg.ff_single_gated else 1)
    nblocks = cfg.num_double_blocks + cfg.num_single_blocks
    keys = jax.random.split(key, nblocks + 10)
    p = {
        "img_in": nn.linear_init(keys[0], cfg.in_channels, inner, dtype=dtype),
        "txt_in": nn.linear_init(keys[1], cfg.ctx_dim, inner, dtype=dtype),
        "time_in1": nn.linear_init(keys[2], 256, inner, dtype=dtype),
        "time_in2": nn.linear_init(keys[3], inner, inner, dtype=dtype),
        # pooled_dim=0 => no pooled conditioning head (LongCat-Image
        # conditions on timestep only, longcat_image_transformer.py:540)
        **({"pooled_in1": nn.linear_init(
                keys[4], cfg.pooled_dim, inner, dtype=dtype),
            "pooled_in2": nn.linear_init(keys[5], inner, inner,
                                         dtype=dtype)}
           if cfg.pooled_dim else {}),
        "norm_out_mod": nn.linear_init(keys[6], inner, 2 * inner, dtype=dtype),
        "proj_out": nn.linear_init(
            keys[7], inner, cfg.out_channels, dtype=dtype),
        "double": [],
        "single": [],
    }
    if cfg.ctx_rmsnorm:
        p["txt_norm"] = nn.rmsnorm_init(cfg.ctx_dim, dtype)
    if cfg.guidance_embed:
        p["guidance_in1"] = nn.linear_init(keys[8], 256, inner, dtype=dtype)
        p["guidance_in2"] = nn.linear_init(keys[9], inner, inner, dtype=dtype)
    for i in range(cfg.num_double_blocks):
        k = jax.random.split(keys[i + 10], 12)
        p["double"].append({
            "img_mod": nn.linear_init(k[0], inner, 6 * inner, dtype=dtype),
            "txt_mod": nn.linear_init(k[1], inner, 6 * inner, dtype=dtype),
            "img_qkv": nn.linear_init(k[2], inner, 3 * inner, dtype=dtype),
            "txt_qkv": nn.linear_init(k[3], inner, 3 * inner, dtype=dtype),
            "img_norm_q": nn.rmsnorm_init(cfg.head_dim, dtype),
            "img_norm_k": nn.rmsnorm_init(cfg.head_dim, dtype),
            "txt_norm_q": nn.rmsnorm_init(cfg.head_dim, dtype),
            "txt_norm_k": nn.rmsnorm_init(cfg.head_dim, dtype),
            "img_out": nn.linear_init(k[4], inner, inner, dtype=dtype),
            "txt_out": nn.linear_init(k[5], inner, inner, dtype=dtype),
            "img_mlp1": nn.linear_init(k[6], inner, mlp1_out, dtype=dtype),
            "img_mlp2": nn.linear_init(k[7], mlp, inner, dtype=dtype),
            "txt_mlp1": nn.linear_init(k[8], inner, mlp1_out, dtype=dtype),
            "txt_mlp2": nn.linear_init(k[9], mlp, inner, dtype=dtype),
        })
    for i in range(cfg.num_single_blocks):
        k = jax.random.split(keys[cfg.num_double_blocks + i + 10], 4)
        p["single"].append({
            "mod": nn.linear_init(k[0], inner, 3 * inner, dtype=dtype),
            # fused projection: qkv + mlp hidden in one matmul
            "lin1": nn.linear_init(
                k[1], inner, 3 * inner + single_mlp, dtype=dtype),
            "norm_q": nn.rmsnorm_init(cfg.head_dim, dtype),
            "norm_k": nn.rmsnorm_init(cfg.head_dim, dtype),
            # fused output: [attn_out; act(mlp)] -> inner
            "lin2": nn.linear_init(k[2], inner + mlp, inner, dtype=dtype),
        })
    return p


def rope_freqs(cfg: FluxDiTConfig, grid_h: int, grid_w: int, txt_len: int,
               cond_grids: tuple = ()):
    """3-axis rope over (frame/modality, row, col) ids.

    Flux convention: text ids are all-zeros, image ids (0, row, col).
    LongCat: text (0, n, n), image (1, row + offset, col + offset)
    (prepare_pos_ids, pipeline_longcat_image.py:112-120,412-417).
    Ovis: text (0, n, n), image (0, row, col).

    ``cond_grids``: (gh, gw) per VAE-encoded condition image appended to
    the token sequence (image edit); condition j sits at modality
    coordinate ``img_frame_coord + 1 + j`` with the same row/col offsets
    (LongCat edit: gen=1, cond=2 — pipeline_longcat_image_edit.py:456-471).
    """
    half_dims = [d // 2 for d in cfg.axes_dims]

    def axis_freqs(pos, half):
        inv = 1.0 / (
            cfg.theta ** (jnp.arange(half, dtype=jnp.float32) / half)
        )
        return pos.astype(jnp.float32)[:, None] * inv[None, :]

    off = cfg.img_rope_offset

    def grid_angles(gh, gw, frame_coord):
        r = jnp.arange(gh).repeat(gw) + off
        c = jnp.tile(jnp.arange(gw), gh) + off
        frame = jnp.full_like(r, frame_coord, jnp.float32)
        return jnp.concatenate([
            axis_freqs(frame, half_dims[0]),
            axis_freqs(r, half_dims[1]),
            axis_freqs(c, half_dims[2]),
        ], axis=-1)

    parts = [grid_angles(grid_h, grid_w, cfg.img_frame_coord)]
    for j, (ch, cw) in enumerate(cond_grids):
        parts.append(grid_angles(ch, cw, cfg.img_frame_coord + 1 + j))
    img_angles = jnp.concatenate(parts, axis=0)
    zt = jnp.zeros((txt_len,), jnp.int32)
    tn = jnp.arange(txt_len) if cfg.txt_rope_arange else zt
    txt_angles = jnp.concatenate([
        axis_freqs(zt, half_dims[0]),
        axis_freqs(tn, half_dims[1]),
        axis_freqs(tn, half_dims[2]),
    ], axis=-1)
    # joint layout: text first
    angles = jnp.concatenate([txt_angles, img_angles], axis=0)
    return jnp.cos(angles), jnp.sin(angles)


def _rope_apply(x, cos, sin, interleaved: bool = False):
    c = cos[None, :, None, :]
    s = sin[None, :, None, :]
    if interleaved:
        # diffusers pairing: (x0, x1), (x2, x3), ... rotate together
        x1 = x[..., 0::2].astype(jnp.float32)
        x2 = x[..., 1::2].astype(jnp.float32)
        out = jnp.stack([x1 * c - x2 * s, x1 * s + x2 * c], axis=-1)
        return out.reshape(x.shape).astype(x.dtype)
    d = x.shape[-1]
    x1 = x[..., : d // 2].astype(jnp.float32)
    x2 = x[..., d // 2:].astype(jnp.float32)
    return jnp.concatenate(
        [x1 * c - x2 * s, x2 * c + x1 * s], axis=-1
    ).astype(x.dtype)


def _modulate(x, mod3):
    shift, scale, gate = jnp.split(mod3, 3, axis=-1)
    xn = nn.layernorm({}, x)
    return (xn * (1.0 + scale[:, None, :]) + shift[:, None, :],
            gate[:, None, :])


def _heads(x, h):
    b, s, _ = x.shape
    return x.reshape(b, s, h, -1)


def _ff_act(cfg, h):
    """Double-block FF hidden activation: plain (Flux gelu-tanh) or a
    value*act(gate) pair from a doubled projection (value first, gate
    second — the diffusers GEGLU/SwiGLU layout)."""
    if cfg.ff_double == "gelu":
        return jax.nn.gelu(h, approximate=True)
    v, g = jnp.split(h, 2, axis=-1)
    if cfg.ff_double == "geglu":
        return v * jax.nn.gelu(g, approximate=False)
    if cfg.ff_double == "swiglu":
        return v * jax.nn.silu(g)
    raise ValueError(f"unknown ff_double {cfg.ff_double!r}")


def _double_block(blk, cfg, img, txt, temb_act, freqs, kv_mask):
    h = cfg.num_heads
    s_txt = txt.shape[1]
    img_mod = nn.linear(blk["img_mod"], temb_act)
    txt_mod = nn.linear(blk["txt_mod"], temb_act)
    img_mod1, img_mod2 = jnp.split(img_mod, 2, axis=-1)
    txt_mod1, txt_mod2 = jnp.split(txt_mod, 2, axis=-1)

    img_n, img_gate1 = _modulate(img, img_mod1)
    txt_n, txt_gate1 = _modulate(txt, txt_mod1)
    qi, ki, vi = jnp.split(nn.linear(blk["img_qkv"], img_n), 3, axis=-1)
    qt, kt, vt = jnp.split(nn.linear(blk["txt_qkv"], txt_n), 3, axis=-1)
    qi = rms_norm(_heads(qi, h), blk["img_norm_q"]["w"])
    ki = rms_norm(_heads(ki, h), blk["img_norm_k"]["w"])
    qt = rms_norm(_heads(qt, h), blk["txt_norm_q"]["w"])
    kt = rms_norm(_heads(kt, h), blk["txt_norm_k"]["w"])
    q = _rope_apply(jnp.concatenate([qt, qi], 1), *freqs,
                    interleaved=cfg.rope_interleaved)
    k = _rope_apply(jnp.concatenate([kt, ki], 1), *freqs,
                    interleaved=cfg.rope_interleaved)
    v = jnp.concatenate([_heads(vt, h), _heads(vi, h)], 1)
    o = flash_attention(q, k, v, causal=False, kv_mask=kv_mask)
    txt_o = o[:, :s_txt].reshape(*txt.shape[:2], -1)
    img_o = o[:, s_txt:].reshape(*img.shape[:2], -1)

    img = img + img_gate1 * nn.linear(blk["img_out"], img_o)
    txt = txt + txt_gate1 * nn.linear(blk["txt_out"], txt_o)
    img_n2, img_gate2 = _modulate(img, img_mod2)
    img = img + img_gate2 * nn.linear(
        blk["img_mlp2"], _ff_act(cfg, nn.linear(blk["img_mlp1"], img_n2)))
    txt_n2, txt_gate2 = _modulate(txt, txt_mod2)
    txt = txt + txt_gate2 * nn.linear(
        blk["txt_mlp2"], _ff_act(cfg, nn.linear(blk["txt_mlp1"], txt_n2)))
    return img, txt


def _single_block(blk, cfg, x, temb_act, freqs, kv_mask):
    """Concatenated-stream block: one fused qkv+mlp projection, one fused
    output projection (the Flux single-stream shape)."""
    h = cfg.num_heads
    inner = cfg.inner_dim
    x_n, gate = _modulate(x, nn.linear(blk["mod"], temb_act))
    fused = nn.linear(blk["lin1"], x_n)
    qkv, mlp_h = fused[..., : 3 * inner], fused[..., 3 * inner:]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = rms_norm(_heads(q, h), blk["norm_q"]["w"])
    k = rms_norm(_heads(k, h), blk["norm_k"]["w"])
    q = _rope_apply(q, *freqs, interleaved=cfg.rope_interleaved)
    k = _rope_apply(k, *freqs, interleaved=cfg.rope_interleaved)
    o = flash_attention(q, k, _heads(v, h), causal=False, kv_mask=kv_mask)
    o = o.reshape(*x.shape[:2], -1)
    if cfg.ff_single_gated:
        # Ovis single block: value * silu(gate) from a doubled
        # projection (ovis_image_transformer.py:175-268)
        mv, mg = jnp.split(mlp_h, 2, axis=-1)
        mlp_act = mv * jax.nn.silu(mg)
    else:
        mlp_act = jax.nn.gelu(mlp_h, approximate=True)
    out = nn.linear(
        blk["lin2"], jnp.concatenate([o, mlp_act], axis=-1))
    return x + gate * out


def forward(
    params,
    cfg: FluxDiTConfig,
    img_tokens: jax.Array,  # [B, S_img, in_channels] packed latents
    txt_states: jax.Array,  # [B, S_txt, ctx_dim]
    pooled: jax.Array,  # [B, pooled_dim] pooled conditioning
    timesteps: jax.Array,  # [B] in [0, 1000)
    grid_hw: tuple[int, int],
    guidance: Optional[jax.Array] = None,  # [B] guidance scale embedding
    txt_mask: Optional[jax.Array] = None,  # [B, S_txt]
    cond_grids: tuple = (),  # (gh, gw) per appended condition image
) -> jax.Array:
    """Returns velocity prediction [B, S_img, out_channels] (the caller
    slices off appended condition tokens)."""
    img = nn.linear(params["img_in"], img_tokens)
    txt = txt_states
    if cfg.ctx_rmsnorm:
        txt = rms_norm(txt, params["txt_norm"]["w"])
    txt = nn.linear(params["txt_in"], txt)
    b, s_img = img.shape[:2]
    s_txt = txt.shape[1]

    temb = nn.timestep_embedding(timesteps, 256).astype(img.dtype)
    temb = nn.linear(params["time_in2"],
                     jax.nn.silu(nn.linear(params["time_in1"], temb)))
    if cfg.pooled_dim:
        temb = temb + nn.linear(
            params["pooled_in2"],
            jax.nn.silu(nn.linear(params["pooled_in1"], pooled)))
    if cfg.guidance_embed:
        g = guidance if guidance is not None else jnp.ones((b,), jnp.float32)
        gemb = nn.timestep_embedding(g * 1000.0, 256).astype(img.dtype)
        temb = temb + nn.linear(
            params["guidance_in2"],
            jax.nn.silu(nn.linear(params["guidance_in1"], gemb)))
    temb_act = jax.nn.silu(temb)

    freqs = rope_freqs(cfg, grid_hw[0], grid_hw[1], s_txt,
                       cond_grids=cond_grids)
    kv_mask = None
    if txt_mask is not None:
        kv_mask = jnp.concatenate(
            [txt_mask.astype(jnp.int32), jnp.ones((b, s_img), jnp.int32)],
            axis=1,
        )

    for blk in params["double"]:
        img, txt = _double_block(blk, cfg, img, txt, temb_act, freqs, kv_mask)
    x = jnp.concatenate([txt, img], axis=1)
    for blk in params["single"]:
        x = _single_block(blk, cfg, x, temb_act, freqs, kv_mask)
    img = x[:, s_txt:]

    mod = nn.linear(params["norm_out_mod"], temb_act)
    scale, shift = jnp.split(mod, 2, axis=-1)
    img = nn.layernorm({}, img) * (1.0 + scale[:, None, :]) \
        + shift[:, None, :]
    return nn.linear(params["proj_out"], img)
