"""Wan-style text-to-video pipeline.

Reference: vllm_omni/diffusion/models/wan2_2/ — Wan2.2 T2V
(pipeline: text encode → flow-match denoise over video latents → VAE
decode).  TPU-first like the image pipeline: the whole denoise loop is one
jitted fori_loop with a dynamic step bound; frames ride a leading latent
axis and decode through the image VAE per frame (the reference's
temporally-compressing video VAE is a follow-up — frame-wise decode keeps
the same output contract at tiny/bench scales).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.diffusion import cache as step_cache
from vllm_omni_tpu.diffusion import scheduler as fm
from vllm_omni_tpu.diffusion.request import (
    DiffusionOutput,
    InvalidRequestError,
    OmniDiffusionRequest,
)
from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.models.common.transformer import (
    TransformerConfig,
    forward_hidden,
    init_params as init_text_params,
)
from vllm_omni_tpu.models.qwen_image import vae as vae_mod
from vllm_omni_tpu.models.qwen_image.vae import VAEConfig
from vllm_omni_tpu.models.wan import transformer as wdit
from vllm_omni_tpu.models.wan.transformer import WanDiTConfig
from vllm_omni_tpu.utils.tokenizer import ByteTokenizer

logger = init_logger(__name__)


@dataclass(frozen=True)
class WanPipelineConfig:
    text: TransformerConfig = field(default_factory=TransformerConfig)
    dit: WanDiTConfig = field(default_factory=WanDiTConfig)
    vae: VAEConfig = field(default_factory=VAEConfig)
    max_text_len: int = 64
    flow_shift: float = 3.0

    @staticmethod
    def tiny() -> "WanPipelineConfig":
        return WanPipelineConfig(
            text=TransformerConfig.tiny(vocab_size=256),
            dit=WanDiTConfig.tiny(),
            vae=VAEConfig.tiny(),
        )


class WanT2VPipeline:
    """Text -> video ([F, H, W, 3] uint8 frames)."""

    output_type = "video"

    def __init__(self, config: WanPipelineConfig, dtype=jnp.bfloat16,
                 seed: int = 0, mesh=None, cache_config=None):
        self.cfg = config
        self.dtype = dtype
        self.cache_config = cache_config
        if config.text.hidden_size != config.dit.ctx_dim:
            raise ValueError("text hidden_size must equal dit ctx_dim")
        self.tokenizer = ByteTokenizer(config.text.vocab_size)
        k1, k2, k3 = jax.random.split(jax.random.PRNGKey(seed), 3)
        logger.info("Initializing WanT2VPipeline (dtype=%s)", dtype)
        self.text_params = init_text_params(k1, config.text, dtype)
        self.dit_params = wdit.init_params(k2, config.dit, dtype)
        self.vae_params = vae_mod.init_decoder(k3, config.vae, dtype)
        self._denoise_cache: dict = {}

    def encode_prompt(self, prompts: list[str]):
        ids, lens = self.tokenizer.batch_encode(prompts, self.cfg.max_text_len)
        hidden = jax.jit(
            lambda i: forward_hidden(self.text_params, self.cfg.text, i)
        )(jnp.asarray(ids))
        mask = (np.arange(self.cfg.max_text_len)[None, :]
                < lens[:, None]).astype(np.int32)
        return hidden, jnp.asarray(mask)

    def _denoise_fn(self, frames, grid_h, grid_w, sched_len):
        key = (frames, grid_h, grid_w, sched_len)
        if key in self._denoise_cache:
            return self._denoise_cache[key]
        cfg = self.cfg

        cache_cfg = self.cache_config

        @jax.jit
        def run(dit_params, latents, ctx, ctx_mask, neg_ctx, neg_mask,
                sigmas, timesteps, gscale, num_steps):
            schedule = fm.FlowMatchSchedule(sigmas=sigmas,
                                            timesteps=timesteps)
            do_cfg = neg_ctx is not None
            ctx_all = (jnp.concatenate([ctx, neg_ctx], 0) if do_cfg else ctx)
            mask_all = (jnp.concatenate([ctx_mask, neg_mask], 0)
                        if do_cfg else ctx_mask)

            def eval_velocity(lat, i):
                t = jnp.broadcast_to(timesteps[i], (lat.shape[0],))
                lat_in = jnp.concatenate([lat, lat], 0) if do_cfg else lat
                t_in = jnp.concatenate([t, t], 0) if do_cfg else t
                v = wdit.forward(dit_params, cfg.dit, lat_in, ctx_all, t_in,
                                 ctx_mask=mask_all)
                if do_cfg:
                    v_pos, v_neg = jnp.split(v, 2, axis=0)
                    v = v_neg + gscale * (v_pos - v_neg)
                return v

            return step_cache.run_denoise_loop(
                cache_cfg, schedule, eval_velocity, latents, num_steps)

        self._denoise_cache[key] = run
        return run

    def forward(self, req: OmniDiffusionRequest) -> list[DiffusionOutput]:
        sp = req.sampling_params
        cfg = self.cfg
        ratio = cfg.vae.spatial_ratio
        mult = ratio * cfg.dit.patch_size
        if sp.height % mult or sp.width % mult:
            raise InvalidRequestError(f"height/width must be multiples of {mult}")
        frames = max(1, sp.num_frames)
        lat_h, lat_w = sp.height // ratio, sp.width // ratio
        prompts = req.prompt
        b = len(prompts)

        ctx, ctx_mask = self.encode_prompt(prompts)
        do_cfg = sp.guidance_scale > 1.0
        neg_ctx = neg_mask = None
        if do_cfg:
            neg_ctx, neg_mask = self.encode_prompt(
                [sp.negative_prompt] * b)

        seed = (sp.seed if sp.seed is not None
                else int(np.random.randint(0, 2 ** 31 - 1)))
        noise = jax.random.normal(
            jax.random.PRNGKey(seed),
            (b, frames, lat_h, lat_w, cfg.dit.in_channels), self.dtype,
        )
        num_steps = sp.num_inference_steps
        sched_len = max(8, 1 << (num_steps - 1).bit_length())
        schedule = fm.make_schedule(num_steps, shift=cfg.flow_shift)
        sigmas = jnp.zeros((sched_len + 1,)).at[: num_steps + 1].set(
            schedule.sigmas)
        timesteps = jnp.zeros((sched_len,)).at[:num_steps].set(
            schedule.timesteps)
        run = self._denoise_fn(frames, lat_h // cfg.dit.patch_size,
                               lat_w // cfg.dit.patch_size, sched_len)
        latents, skipped = run(
            self.dit_params, noise, ctx, ctx_mask, neg_ctx,
            neg_mask, sigmas, timesteps,
            jnp.float32(sp.guidance_scale), jnp.int32(num_steps))
        self.last_skipped_steps = int(skipped)

        # frame-wise VAE decode: [B, F, h, w, C] -> [B*F, ...] -> frames
        bf = latents.reshape(b * frames, lat_h, lat_w,
                             cfg.dit.out_channels)
        imgs = jax.jit(
            lambda p, l: vae_mod.decode(p, cfg.vae, l)
        )(self.vae_params, bf)
        imgs = np.asarray(imgs)
        video = ((np.clip(imgs, -1, 1) + 1) * 127.5).astype(np.uint8)
        video = video.reshape(b, frames, sp.height, sp.width, 3)
        return [
            DiffusionOutput(
                request_id=req.request_ids[i], prompt=prompts[i],
                data=video[i], output_type="video",
            )
            for i in range(b)
        ]
