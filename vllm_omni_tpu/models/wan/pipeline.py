"""Wan-style text-to-video pipeline.

Reference: vllm_omni/diffusion/models/wan2_2/ — Wan2.2 T2V / I2V / TI2V
(pipeline: text encode [+ first-frame image encode] → flow-match denoise
over video latents → VAE decode).  TPU-first like the image pipeline: the
whole denoise loop is one jitted fori_loop with a dynamic step bound;
latents ride the temporally-compressed layout of the causal video VAE
(models/common/causal_vae.py — 1 + (F-1)/r latent frames, the same
checkpoint-compatible implementation Qwen-Image loads), and I2V conditions the DiT on
the first frame's VAE latent plus a presence-mask channel concatenated
channel-wise (the reference's y/mask conditioning).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.diffusion import cache as step_cache
from vllm_omni_tpu.diffusion import scheduler as fm
from vllm_omni_tpu.diffusion.request import (
    DiffusionOutput,
    InvalidRequestError,
    OmniDiffusionRequest,
)
from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.models.common.transformer import (
    TransformerConfig,
    forward_hidden,
    init_params as init_text_params,
)
from vllm_omni_tpu.models.common import causal_vae as vvae
from vllm_omni_tpu.models.common.causal_vae import (
    CausalVAEConfig as VideoVAEConfig,
)
from vllm_omni_tpu.models.wan import transformer as wdit
from vllm_omni_tpu.models.common import t5 as t5_mod
from vllm_omni_tpu.models.wan import ckpt_transformer as wckpt
from vllm_omni_tpu.models.wan.transformer import WanDiTConfig
from vllm_omni_tpu.utils.tokenizer import ByteTokenizer

logger = init_logger(__name__)


@dataclass(frozen=True)
class WanPipelineConfig:
    # text: generic in-house encoder (TransformerConfig) or the real
    # UMT5 stack (t5.T5Config); dit: native TPU-first schema
    # (WanDiTConfig) or the published checkpoint schema
    # (ckpt_transformer.WanCkptConfig) — from_pretrained builds the
    # latter pair
    text: TransformerConfig = field(default_factory=TransformerConfig)
    dit: WanDiTConfig = field(default_factory=WanDiTConfig)
    vae: VideoVAEConfig = field(default_factory=VideoVAEConfig)
    max_text_len: int = 64
    flow_shift: float = 3.0
    # "euler" | "unipc" (order-2 multistep, diffusion/scheduler.py)
    scheduler: str = "euler"

    @staticmethod
    def tiny() -> "WanPipelineConfig":
        return WanPipelineConfig(
            text=TransformerConfig.tiny(vocab_size=256),
            dit=WanDiTConfig.tiny(),
            vae=VideoVAEConfig.tiny(),
        )

    @staticmethod
    def tiny_i2v() -> "WanPipelineConfig":
        """I2V tiny: DiT consumes [noise, cond_latent, mask] channels."""
        import dataclasses

        base = WanPipelineConfig.tiny()
        dit = dataclasses.replace(
            base.dit,
            in_channels=2 * base.vae.latent_channels + 1,
            out_channels=base.vae.latent_channels,
        )
        return dataclasses.replace(base, dit=dit)


class WanT2VPipeline:
    """Text -> video ([F, H, W, 3] uint8 frames)."""

    output_type = "video"

    def __init__(self, config: WanPipelineConfig, dtype=jnp.bfloat16,
                 seed: int = 0, mesh=None, cache_config=None,
                 init_weights: bool = True):
        from vllm_omni_tpu.parallel.pipeline_mesh import MeshWiring

        self.cfg = config
        self.dtype = dtype
        self.mesh = mesh
        self.cache_config = cache_config
        # Video is where SP earns its keep: 100k+-token sequences; batch
        # rides dp/cfg.  TP/PP for the Wan DiT are not wired — refuse
        # rather than silently run single-device (VERDICT r2 weak #3).
        self.wiring = MeshWiring(mesh, type(self).__name__).validate(
            {"dp", "cfg", "ring", "ulysses"})
        # checkpoint schema: UMT5 text stack + diffusers-named DiT
        self._ckpt = isinstance(config.dit, wckpt.WanCkptConfig)
        self._t5_text = isinstance(config.text, t5_mod.T5Config)
        text_width = (config.text.d_model if self._t5_text
                      else config.text.hidden_size)
        ctx_width = (config.dit.text_dim if self._ckpt
                     else config.dit.ctx_dim)
        if text_width != ctx_width:
            raise ValueError("text hidden width must equal the DiT's "
                             f"context width ({text_width} != {ctx_width})")
        self.hf_tokenizer = None  # set by from_pretrained
        self.tokenizer = ByteTokenizer(config.text.vocab_size)
        k1, k2, k3 = jax.random.split(jax.random.PRNGKey(seed), 3)
        logger.info("Initializing %s (dtype=%s, schema=%s)",
                    type(self).__name__, dtype,
                    "checkpoint" if self._ckpt else "native")
        if init_weights:
            self.text_params = self.wiring.place(
                t5_mod.init_params(k1, config.text, dtype)
                if self._t5_text
                else init_text_params(k1, config.text, dtype))
            self.dit_params = self.wiring.place(
                wckpt.init_params(k2, config.dit, dtype) if self._ckpt
                else wdit.init_params(k2, config.dit, dtype))
            # checkpoint-compatible Wan causal 3D VAE (the same family
            # as the Qwen-Image VAE — models/common/causal_vae.py;
            # diffusers weights load via model_loader.diffusers_loader)
            self.vae_params = self.wiring.place(vvae.init_params(
                k3, config.vae, jnp.float32, encoder=False))
        else:
            # from_pretrained installs loaded trees — random init at
            # real scale would double peak HBM for nothing
            self.text_params = self.dit_params = self.vae_params = None
        self.vae_encoder_params = None  # built on demand (I2V conditioning)
        self._seed = seed
        self._denoise_cache: dict = {}
        # jitted helpers built ONCE — a fresh jax.jit(lambda) per request
        # would miss the jit cache and recompile every call
        # params are explicit jit ARGUMENTS: a closure-captured tree would
        # be baked into the executable as constants — sleep() couldn't
        # free the buffers and wake()/LoRA swaps would silently not apply
        if self._t5_text:
            self._text_encode_jit = jax.jit(
                lambda p, i, m: t5_mod.forward(p, self.cfg.text, i, m))
        else:
            self._text_encode_jit = jax.jit(
                lambda p, i: forward_hidden(p, self.cfg.text, i))
        # fp32 VAE compute regardless of model dtype (banding artifacts
        # in bf16 decode)
        self._vae_decode_jit = jax.jit(
            lambda pp, l: vvae.decode(pp, self.cfg.vae,
                                      l.astype(jnp.float32)))
        self._vae_encode_jit = jax.jit(
            lambda pp, v: vvae.encode(pp, self.cfg.vae,
                                      v.astype(jnp.float32)))

    def encode_prompt(self, prompts: list[str]):
        if self.hf_tokenizer is not None:
            enc = self.hf_tokenizer(
                prompts, padding="max_length", truncation=True,
                max_length=self.cfg.max_text_len)
            ids = np.asarray(enc["input_ids"], np.int32)
            mask = np.asarray(enc["attention_mask"], np.int32)
        else:
            ids, lens = self.tokenizer.batch_encode(
                prompts, self.cfg.max_text_len)
            mask = (np.arange(self.cfg.max_text_len)[None, :]
                    < lens[:, None]).astype(np.int32)
        if self._t5_text:
            hidden = self._text_encode_jit(
                self.text_params, jnp.asarray(ids), jnp.asarray(mask))
        else:
            hidden = self._text_encode_jit(self.text_params,
                                           jnp.asarray(ids))
        return hidden, jnp.asarray(mask)

    def _denoise_fn(self, frames, grid_h, grid_w, sched_len, batch2=0):
        # batch2 only affects the shard_map attn dispatch decision — keep
        # it out of the key on meshless pipelines (jit handles shapes)
        key = (frames, grid_h, grid_w, sched_len) + (
            (batch2,) if self.mesh is not None else ())
        if key in self._denoise_cache:
            return self._denoise_cache[key]
        cfg = self.cfg
        wiring = self.wiring
        attn_fn = wiring.self_attn_fn(
            cfg.dit.num_heads, frames * grid_h * grid_w, batch2)

        cache_cfg = self.cache_config

        @jax.jit
        def run(dit_params, latents, ctx, ctx_mask, neg_ctx, neg_mask,
                sigmas, timesteps, gscale, num_steps, cond=None):
            schedule = fm.FlowMatchSchedule(sigmas=sigmas,
                                            timesteps=timesteps)
            do_cfg = neg_ctx is not None
            ctx_all = (jnp.concatenate([ctx, neg_ctx], 0) if do_cfg else ctx)
            mask_all = (jnp.concatenate([ctx_mask, neg_mask], 0)
                        if do_cfg else ctx_mask)
            if self._ckpt:
                # raw T5 features -> inner width, once per run (the
                # reference projects in the condition embedder)
                ctx_all = wckpt.project_ctx(dit_params, cfg.dit, ctx_all)
            ctx_all = wiring.constrain(ctx_all)

            def embed(lat, i):
                t = jnp.broadcast_to(timesteps[i], (lat.shape[0],))
                # I2V: first-frame latent + presence mask ride extra
                # channels (the reference y/mask conditioning)
                lat_model = (lat if cond is None
                             else jnp.concatenate([lat, cond], axis=-1))
                lat_in = (jnp.concatenate([lat_model, lat_model], 0)
                          if do_cfg else lat_model)
                # [B, F, H, W, C]: batch over (cfg, dp), frames over the
                # SP axes — the layout the shard_map attention expects
                lat_in = wiring.constrain(lat_in, seq_dim=1)
                t_in = jnp.concatenate([t, t], 0) if do_cfg else t
                wmod = wckpt if self._ckpt else wdit
                return wmod.forward_prefix(dit_params, cfg.dit, lat_in,
                                           t_in)

            def run_blocks(state, blocks):
                x, temb, rope, fgw = state
                from vllm_omni_tpu.models.common import dit as cdit

                for blk in blocks:
                    if self._ckpt:
                        x = wckpt.block_forward(
                            blk, cfg.dit, x, ctx_all, temb, rope,
                            mask_all, self_attn_fn=attn_fn)
                    else:
                        x = cdit.cross_block_forward(
                            blk, x, ctx_all, temb, rope,
                            cfg.dit.num_heads, mask_all,
                            self_attn_fn=attn_fn)
                return (x, temb, rope, fgw)

            def finish(state):
                x, temb, rope, fgw = state
                wmod = wckpt if self._ckpt else wdit
                v = wmod.forward_suffix(dit_params, cfg.dit, x, temb,
                                        fgw)
                if do_cfg:
                    v_pos, v_neg = jnp.split(v, 2, axis=0)
                    v = v_neg + gscale * (v_pos - v_neg)
                return v

            # one block-stack implementation for the uncached, teacache,
            # and dbcache (anchor/tail split) paths
            fn_blocks = (cache_cfg.fn_compute_blocks
                         if cache_cfg is not None else 0)

            def eval_velocity(lat, i):
                return finish(run_blocks(embed(lat, i),
                                         dit_params["blocks"]))

            def eval_first(lat, i):
                state = run_blocks(embed(lat, i),
                                   dit_params["blocks"][:fn_blocks])
                return state, finish(state)

            def eval_rest(state):
                return finish(run_blocks(state,
                                         dit_params["blocks"][fn_blocks:]))

            return step_cache.run_denoise_loop(
                cache_cfg, schedule, eval_velocity, latents, num_steps,
                solver=cfg.scheduler,
                eval_split=(eval_first, eval_rest))

        self._denoise_cache[key] = run
        return run

    def forward(self, req: OmniDiffusionRequest) -> list[DiffusionOutput]:
        sp = req.sampling_params
        cfg = self.cfg
        ratio = cfg.vae.spatial_ratio
        mult = ratio * cfg.dit.patch_size
        if sp.height % mult or sp.width % mult:
            raise InvalidRequestError(f"height/width must be multiples of {mult}")
        frames = max(1, sp.num_frames)
        lat_frames = cfg.vae.latent_frames(frames)
        # decode covers >= requested frames; trim to the request
        out_frames = cfg.vae.pixel_frames(lat_frames)
        lat_h, lat_w = sp.height // ratio, sp.width // ratio
        prompts = req.prompt
        b = len(prompts)

        ctx, ctx_mask = self.encode_prompt(prompts)
        do_cfg = sp.guidance_scale > 1.0
        neg_ctx = neg_mask = None
        if do_cfg:
            neg_ctx, neg_mask = self.encode_prompt(
                [sp.negative_prompt] * b)

        seed = (sp.seed if sp.seed is not None
                else int(np.random.randint(0, 2 ** 31 - 1)))
        lat_ch = cfg.vae.latent_channels
        noise = jax.random.normal(
            jax.random.PRNGKey(seed),
            (b, lat_frames, lat_h, lat_w, lat_ch), self.dtype,
        )
        cond = self._make_cond(req, b, lat_frames, lat_h, lat_w)
        num_steps = sp.num_inference_steps
        sched_len = max(8, 1 << (num_steps - 1).bit_length())
        schedule = fm.make_schedule(num_steps, shift=cfg.flow_shift)
        sigmas = jnp.zeros((sched_len + 1,)).at[: num_steps + 1].set(
            schedule.sigmas)
        timesteps = jnp.zeros((sched_len,)).at[:num_steps].set(
            schedule.timesteps)
        run = self._denoise_fn(lat_frames, lat_h // cfg.dit.patch_size,
                               lat_w // cfg.dit.patch_size, sched_len,
                               batch2=(2 * b if do_cfg else b))
        latents, skipped = run(
            self.dit_params, noise, ctx, ctx_mask, neg_ctx,
            neg_mask, sigmas, timesteps,
            jnp.float32(sp.guidance_scale), jnp.int32(num_steps),
            cond=cond)
        self.last_skipped_steps = int(skipped)

        # temporal VAE decode: [B, Tl, h, w, C] -> [B, F, H, W, 3]
        imgs = self._vae_decode_jit(self.vae_params, latents)
        imgs = np.asarray(imgs)
        video = ((np.clip(imgs, -1, 1) + 1) * 127.5).astype(np.uint8)
        video = video.reshape(b, out_frames, sp.height, sp.width, 3)
        video = video[:, :frames]
        return [
            DiffusionOutput(
                request_id=req.request_ids[i], prompt=prompts[i],
                data=video[i], output_type="video",
            )
            for i in range(b)
        ]


    def _make_cond(self, req, b, lat_frames, lat_h, lat_w):
        """T2V: no conditioning channels."""
        return None

    @classmethod
    def from_pretrained(cls, model_dir: str, dtype=jnp.bfloat16,
                        seed: int = 0, mesh=None, cache_config=None,
                        max_text_len: int = 512) -> "WanT2VPipeline":
        """Build from a diffusers-format Wan2.x checkpoint directory
        (transformer/ + text_encoder/ UMT5 + tokenizer/ + vae/;
        reference: DiffusersPipelineLoader resolving WanPipeline
        components, diffusion/model_loader/diffusers_loader.py).

        Every component loads real weights or this raises — a silently
        random-init sub-module would emit noise (VERDICT r2 weak #4).
        """
        import json
        import os

        from vllm_omni_tpu.model_loader import diffusers_loader as dl

        dl.load_model_index(model_dir)  # validates layout
        dit_params, dit_cfg = wckpt.load_wan_dit(
            os.path.join(model_dir, "transformer"), dtype=dtype)
        te_dir = os.path.join(model_dir, "text_encoder")
        with open(os.path.join(te_dir, "config.json")) as f:
            text_cfg = t5_mod.T5Config.from_hf(json.load(f))
        text_params, _ = t5_mod.load_t5(te_dir, cfg=text_cfg,
                                        dtype=dtype)
        need_enc = bool(getattr(cls, "needs_image_cond", False))
        vae_tree, vae_cfg = dl.load_causal_vae(
            os.path.join(model_dir, "vae"), dtype=jnp.float32,
            encoder=need_enc, decoder=True)
        sched = dl.scheduler_config(model_dir)
        config = WanPipelineConfig(
            text=text_cfg, dit=dit_cfg, vae=vae_cfg,
            max_text_len=max_text_len,
            flow_shift=sched.get("shift", 3.0),
        )
        pipe = cls(config, dtype=dtype, seed=seed, mesh=mesh,
                   cache_config=cache_config, init_weights=False)
        pipe.dit_params = pipe.wiring.place(dit_params)
        pipe.text_params = pipe.wiring.place(text_params)
        pipe.vae_params = pipe.wiring.place(
            {k: vae_tree[k] for k in ("decoder", "post_quant_conv")})
        if need_enc:
            pipe.vae_encoder_params = pipe.wiring.place(
                {k: vae_tree[k] for k in ("encoder", "quant_conv")})
        tok_dir = os.path.join(model_dir, "tokenizer")
        if os.path.isdir(tok_dir):
            from transformers import AutoTokenizer

            pipe.hf_tokenizer = AutoTokenizer.from_pretrained(tok_dir)
        else:
            raise ValueError(
                f"{model_dir} has no tokenizer/ directory — the UMT5 "
                "stack needs the checkpoint's sentencepiece tokenizer")
        return pipe


class WanI2VPipeline(WanT2VPipeline):
    """Image(+text) -> video: the first output frame is anchored to the
    input image via VAE-latent + presence-mask conditioning channels
    (reference: Wan2.2 I2V/TI2V, diffusion/models/wan2_2/)."""

    needs_image_cond = True

    def __init__(self, config: WanPipelineConfig, **kw):
        want = 2 * config.vae.latent_channels + 1
        if config.dit.in_channels != want:
            raise ValueError(
                "I2V DiT must consume [noise, cond, mask] channels: "
                f"in_channels must be {want} (2*latent+mask), got "
                f"{config.dit.in_channels} — use a *_i2v config preset"
            )
        super().__init__(config, **kw)

    def _make_cond(self, req, b, lat_frames, lat_h, lat_w):
        sp = req.sampling_params
        image = sp.image if sp.image is not None else sp.extra.get("image")
        if image is None:
            raise InvalidRequestError(
                "I2V pipeline needs sampling_params.image (first frame)"
            )
        if self.vae_encoder_params is None:
            enc = vvae.init_params(
                jax.random.PRNGKey(self._seed + 1), self.cfg.vae,
                jnp.float32, decoder=False)
            self.vae_encoder_params = self.wiring.place(enc)
        img = np.asarray(image)
        if img.dtype == np.uint8:
            img = img.astype(np.float32) / 127.5 - 1.0
        ratio = self.cfg.vae.spatial_ratio
        if img.shape[:2] != (lat_h * ratio, lat_w * ratio):
            raise InvalidRequestError(
                f"conditioning image must be {lat_h * ratio}x"
                f"{lat_w * ratio}, got {img.shape[:2]}"
            )
        # encode as a 1-frame clip -> [1, 1, h, w, C]
        z = self._vae_encode_jit(
            self.vae_encoder_params,
            jnp.asarray(img, self.dtype)[None, None])
        lat_ch = self.cfg.vae.latent_channels
        cond = jnp.zeros((b, lat_frames, lat_h, lat_w, lat_ch), self.dtype)
        cond = cond.at[:, 0].set(z[0, 0])
        mask = jnp.zeros((b, lat_frames, lat_h, lat_w, 1), self.dtype)
        mask = mask.at[:, 0].set(1.0)
        return jnp.concatenate([cond, mask], axis=-1)
