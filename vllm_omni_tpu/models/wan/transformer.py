"""Wan-style video DiT: 3D (frame/row/col) RoPE + cross-attention blocks.

Reference: vllm_omni/diffusion/models/wan2_2/ — Wan2.2 T2V/I2V/TI2V
transformers (cross-attention conditioning, 3D rotary positions, adaLN from
the flow timestep).  TPU-first: video tokens flatten to one [B, F*H'*W', D]
sequence (static shape per geometry bucket), all blocks share the
cross-attention DiT block (models/common/dit.py), and 3D RoPE reuses the
sectioned axes scheme.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from vllm_omni_tpu.models.common import dit, nn


@dataclass(frozen=True)
class WanDiTConfig:
    patch_size: int = 2          # spatial patch (temporal patch = 1)
    in_channels: int = 16        # video VAE latent channels
    out_channels: int = 16
    num_layers: int = 30
    num_heads: int = 12
    head_dim: int = 128
    ctx_dim: int = 4096          # text-encoder feature dim
    axes_dims: tuple = (44, 42, 42)  # frame/row/col rope sections
    theta: float = 10000.0
    mlp_ratio: float = 4.0

    @property
    def inner_dim(self) -> int:
        return self.num_heads * self.head_dim

    @staticmethod
    def tiny() -> "WanDiTConfig":
        return WanDiTConfig(
            in_channels=4, out_channels=4, num_layers=2, num_heads=4,
            head_dim=32, ctx_dim=64, axes_dims=(16, 8, 8),
        )


def init_params(key, cfg: WanDiTConfig, dtype=jnp.float32):
    inner = cfg.inner_dim
    mlp = int(inner * cfg.mlp_ratio)
    keys = jax.random.split(key, cfg.num_layers + 6)
    patch_in = cfg.in_channels * cfg.patch_size ** 2
    p = {
        "patch_in": nn.linear_init(keys[0], patch_in, inner, dtype=dtype),
        "time_in1": nn.linear_init(keys[1], 256, inner, dtype=dtype),
        "time_in2": nn.linear_init(keys[2], inner, inner, dtype=dtype),
        "norm_out_mod": nn.linear_init(keys[3], inner, 2 * inner, dtype=dtype),
        "proj_out": nn.linear_init(
            keys[4], inner, cfg.patch_size ** 2 * cfg.out_channels,
            dtype=dtype,
        ),
        "blocks": [
            dit.init_cross_block(keys[i + 6], inner, cfg.ctx_dim, mlp,
                                 cfg.head_dim, dtype)
            for i in range(cfg.num_layers)
        ],
    }
    return p


def rope_freqs(cfg: WanDiTConfig, frames: int, grid_h: int, grid_w: int):
    """Sectioned 3D RoPE over (frame, row, col), [S, head_dim//2] each."""
    def axis(pos, half):
        inv = 1.0 / (cfg.theta ** (jnp.arange(half, dtype=jnp.float32) / half))
        return pos[:, None] * inv[None, :]

    f = jnp.arange(frames, dtype=jnp.float32)
    r = jnp.arange(grid_h, dtype=jnp.float32)
    c = jnp.arange(grid_w, dtype=jnp.float32)
    af = axis(f, cfg.axes_dims[0] // 2)  # [F, df]
    ar = axis(r, cfg.axes_dims[1] // 2)
    ac = axis(c, cfg.axes_dims[2] // 2)
    ang = jnp.concatenate([
        jnp.broadcast_to(af[:, None, None, :],
                         (frames, grid_h, grid_w, af.shape[-1])),
        jnp.broadcast_to(ar[None, :, None, :],
                         (frames, grid_h, grid_w, ar.shape[-1])),
        jnp.broadcast_to(ac[None, None, :, :],
                         (frames, grid_h, grid_w, ac.shape[-1])),
    ], axis=-1).reshape(frames * grid_h * grid_w, -1)
    return jnp.cos(ang), jnp.sin(ang)


def patchify(latents: jax.Array, p: int) -> jax.Array:
    """[B, F, H, W, C] -> [B, F*(H/p)*(W/p), C*p*p]."""
    b, f, h, w, c = latents.shape
    x = latents.reshape(b, f, h // p, p, w // p, p, c)
    x = x.transpose(0, 1, 2, 4, 3, 5, 6)
    return x.reshape(b, f * (h // p) * (w // p), p * p * c)


def unpatchify(x: jax.Array, p: int, f: int, gh: int, gw: int,
               c: int) -> jax.Array:
    b = x.shape[0]
    x = x.reshape(b, f, gh, gw, p, p, c)
    x = x.transpose(0, 1, 2, 4, 3, 5, 6)
    return x.reshape(b, f, gh * p, gw * p, c)


def forward_prefix(params, cfg: WanDiTConfig, latents, timesteps):
    """Embeds + conditioning before the block stack (split out so the
    dual-block cache can schedule the stack — diffusion/cache.py)."""
    b, f, h, w, c = latents.shape
    p = cfg.patch_size
    gh, gw = h // p, w // p
    x = nn.linear(params["patch_in"], patchify(latents, p))
    temb = nn.linear(
        params["time_in2"],
        jax.nn.silu(nn.linear(
            params["time_in1"],
            nn.timestep_embedding(timesteps, 256).astype(x.dtype),
        )),
    )
    rope = rope_freqs(cfg, f, gh, gw)
    return x, temb, rope, (f, gh, gw)


def forward_suffix(params, cfg: WanDiTConfig, x, temb, fgw):
    f, gh, gw = fgw
    mod = nn.linear(params["norm_out_mod"], jax.nn.silu(temb))[:, None, :]
    shift, scale = jnp.split(mod, 2, axis=-1)
    x = nn.layernorm({}, x) * (1 + scale) + shift
    out = nn.linear(params["proj_out"], x)
    return unpatchify(out, cfg.patch_size, f, gh, gw, cfg.out_channels)


def forward(
    params,
    cfg: WanDiTConfig,
    latents: jax.Array,   # [B, F, H, W, C] (latent video)
    ctx: jax.Array,       # [B, S_txt, ctx_dim]
    timesteps: jax.Array, # [B]
    ctx_mask=None,
    attn_fn=None,         # SP self-attention override (pipeline mesh)
) -> jax.Array:
    """Velocity prediction, same shape as latents."""
    x, temb, rope, fgw = forward_prefix(params, cfg, latents, timesteps)
    for blk in params["blocks"]:
        x = dit.cross_block_forward(blk, x, ctx, temb, rope, cfg.num_heads,
                                    ctx_mask, self_attn_fn=attn_fn)
    return forward_suffix(params, cfg, x, temb, fgw)
