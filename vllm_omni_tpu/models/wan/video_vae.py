"""Temporally-compressing causal video VAE (functional JAX, NTHWC).

Role of the reference's Wan2.2 video autoencoder (reference:
vllm_omni/diffusion/models/wan2_2/ — 4x temporal + 8x spatial compression
with the first frame coded independently, so F frames map to
``1 + (F-1)/4`` latent frames).  r1 decoded video frame-wise through the
image VAE (VERDICT row 50); this module adds the real temporal axis.

TPU-first design: factorized (2+1)-D convolutions — the spatial half is
the image VAE's conv stack applied per frame (XLA batches frames into one
conv), the temporal half is a *causal* k=3 temporal convolution expressed
as a shifted-sum (einsum over 3 taps — no 3-D conv lowering needed, MXU
does the channel contraction).  Temporal up/down-sampling is stride-2 with
the first frame passed through, matching the 1+(F-1)/r latent layout.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from vllm_omni_tpu.models.common import nn


@dataclass(frozen=True)
class VideoVAEConfig:
    latent_channels: int = 16
    base_channels: int = 96
    channel_multipliers: tuple[int, ...] = (1, 2, 4, 4)
    temporal_stages: int = 2  # 2 stride-2 stages -> 4x temporal
    layers_per_block: int = 2
    scaling_factor: float = 1.0

    @property
    def spatial_ratio(self) -> int:
        return 2 ** (len(self.channel_multipliers) - 1)

    @property
    def temporal_ratio(self) -> int:
        return 2 ** self.temporal_stages

    def latent_frames(self, frames: int) -> int:
        """F pixel frames -> latent frames covering them (first frame
        independent; non-canonical F rounds UP so callers can trim the
        decoded clip to the requested length)."""
        if frames < 1:
            raise ValueError("need at least one frame")
        return 1 + -(-(frames - 1) // self.temporal_ratio)

    def pixel_frames(self, latent_frames: int) -> int:
        return 1 + (latent_frames - 1) * self.temporal_ratio

    @staticmethod
    def tiny() -> "VideoVAEConfig":
        return VideoVAEConfig(
            latent_channels=4,
            base_channels=16,
            channel_multipliers=(1, 2),
            temporal_stages=1,
            layers_per_block=1,
        )


# ------------------------------------------------------------- primitives
def _causal_tconv_init(key, ch, dtype, taps: int = 3):
    """Per-channel-mixing causal temporal conv: taps x [C, C] kernels."""
    ks = jax.random.split(key, taps)
    scale = 1.0 / (ch * taps) ** 0.5
    return {
        "w": jnp.stack([
            jax.random.uniform(k, (ch, ch), dtype, -scale, scale)
            for k in ks
        ]),
        "b": jnp.zeros((ch,), dtype),
    }


def _causal_tconv(p, x):
    """x [B, T, H, W, C]: y_t = sum_j w_j @ x_{t-taps+1+j} with the front
    padded by replicating frame 0 (causal — no future leakage)."""
    taps = p["w"].shape[0]
    front = jnp.repeat(x[:, :1], taps - 1, axis=1)
    xp = jnp.concatenate([front, x], axis=1)
    t = x.shape[1]
    y = 0.0
    for j in range(taps):
        y = y + jnp.einsum("bthwc,cd->bthwd", xp[:, j: j + t], p["w"][j])
    return y + p["b"]


def _sconv_init(key, cin, cout, dtype, k: int = 3):
    return nn.conv2d_init(key, cin, cout, k, dtype=dtype)


def _sconv(p, x):
    """Spatial 3x3 conv applied per frame: fold T into batch."""
    b, t, h, w, c = x.shape
    y = nn.conv2d(p, x.reshape(b * t, h, w, c))
    return y.reshape(b, t, h, w, -1)


def _block_init(key, cin, cout, dtype):
    k = jax.random.split(key, 4)
    p = {
        "norm1": nn.groupnorm_init(cin, dtype),
        "conv1": _sconv_init(k[0], cin, cout, dtype),
        "tconv": _causal_tconv_init(k[1], cout, dtype),
        "norm2": nn.groupnorm_init(cout, dtype),
        "conv2": _sconv_init(k[2], cout, cout, dtype),
    }
    if cin != cout:
        p["skip"] = nn.linear_init(k[3], cin, cout, bias=False, dtype=dtype)
    return p


def _block(p, x):
    """(2+1)-D resnet block: spatial conv → causal temporal conv →
    spatial conv, with gelu-ish (silu) nonlinearities."""
    b, t, h, w, c = x.shape
    y = nn.groupnorm(p["norm1"], x.reshape(b * t, h, w, c))
    y = jax.nn.silu(y).reshape(b, t, h, w, c)
    y = _sconv(p["conv1"], y)
    y = y + _causal_tconv(p["tconv"], y)
    y2 = nn.groupnorm(p["norm2"], y.reshape(b * t, h, w, y.shape[-1]))
    y2 = jax.nn.silu(y2).reshape(y.shape)
    y2 = _sconv(p["conv2"], y2)
    skip = x if "skip" not in p else x @ p["skip"]["w"]
    return skip + y2


def _t_upsample(x):
    """Temporal 2x: first frame stays single, later frames repeat —
    T -> 1 + (T-1)*2 (inverse of the causal stride-2 downsample)."""
    first = x[:, :1]
    rest = jnp.repeat(x[:, 1:], 2, axis=1)
    return jnp.concatenate([first, rest], axis=1)


def _t_downsample(x):
    """Temporal stride-2 keeping frame 0: T -> 1 + (T-1)//2."""
    return jnp.concatenate([x[:, :1], x[:, 1::2]], axis=1)


def _s_upsample(x):
    b, t, h, w, c = x.shape
    y = jax.image.resize(
        x.reshape(b * t, h, w, c), (b * t, 2 * h, 2 * w, c), "nearest"
    )
    return y.reshape(b, t, 2 * h, 2 * w, c)


def _s_downsample(x):
    b, t, h, w, c = x.shape
    return x.reshape(b, t, h // 2, 2, w // 2, 2, c).mean(axis=(3, 5))


# ---------------------------------------------------------------- decoder
def init_decoder(key, cfg: VideoVAEConfig, dtype=jnp.float32):
    mults = cfg.channel_multipliers
    chans = [cfg.base_channels * m for m in mults]
    keys = jax.random.split(key, 3 + len(mults) * (cfg.layers_per_block + 1))
    p = {
        "conv_in": _sconv_init(keys[0], cfg.latent_channels, chans[-1], dtype),
        "stages": [],
        "norm_out": nn.groupnorm_init(chans[0], dtype),
        "conv_out": _sconv_init(keys[1], chans[0], 3, dtype),
    }
    ki = 2
    # top (smallest) to bottom: spatial up per stage transition
    for si in range(len(mults) - 1, -1, -1):
        cin = chans[min(si + 1, len(mults) - 1)]
        cout = chans[si]
        blocks = []
        for li in range(cfg.layers_per_block):
            blocks.append(_block_init(
                keys[ki], cin if li == 0 else cout, cout, dtype))
            ki += 1
        p["stages"].append({"blocks": blocks})
    return p


def decode(p, cfg: VideoVAEConfig, latents: jax.Array) -> jax.Array:
    """[B, Tl, h, w, C] latents -> [B, F, H, W, 3] pixels in [-1, 1]."""
    x = latents / cfg.scaling_factor
    x = _sconv(p["conv_in"], x)
    n = len(cfg.channel_multipliers)
    for si, stage in enumerate(p["stages"]):
        for blk in stage["blocks"]:
            x = _block(blk, x)
        if si < n - 1:
            x = _s_upsample(x)
        if si < cfg.temporal_stages:
            x = _t_upsample(x)
    b, t, h, w, c = x.shape
    x = nn.groupnorm(p["norm_out"], x.reshape(b * t, h, w, c))
    x = jax.nn.silu(x)
    x = nn.conv2d(p["conv_out"], x).reshape(b, t, h, w, 3)
    return jnp.tanh(x)


# ---------------------------------------------------------------- encoder
def init_encoder(key, cfg: VideoVAEConfig, dtype=jnp.float32):
    mults = cfg.channel_multipliers
    chans = [cfg.base_channels * m for m in mults]
    keys = jax.random.split(key, 3 + len(mults) * (cfg.layers_per_block + 1))
    p = {
        "conv_in": _sconv_init(keys[0], 3, chans[0], dtype),
        "stages": [],
        "norm_out": nn.groupnorm_init(chans[-1], dtype),
        "conv_out": _sconv_init(
            keys[1], chans[-1], cfg.latent_channels, dtype),
    }
    ki = 2
    for si in range(len(mults)):
        cin = chans[max(si - 1, 0)]
        cout = chans[si]
        blocks = []
        for li in range(cfg.layers_per_block):
            blocks.append(_block_init(
                keys[ki], cin if li == 0 else cout, cout, dtype))
            ki += 1
        p["stages"].append({"blocks": blocks})
    return p


def encode(p, cfg: VideoVAEConfig, video: jax.Array) -> jax.Array:
    """[B, F, H, W, 3] pixels in [-1, 1] -> [B, Tl, h, w, C] latents
    (mean of the posterior — deterministic conditioning encode)."""
    f = video.shape[1]
    if (f - 1) % cfg.temporal_ratio:
        raise ValueError(
            f"frame count must be 1 + k*{cfg.temporal_ratio}, got {f}"
        )
    x = _sconv(p["conv_in"], video)
    n = len(cfg.channel_multipliers)
    for si, stage in enumerate(p["stages"]):
        for blk in stage["blocks"]:
            x = _block(blk, x)
        if si < n - 1:
            x = _s_downsample(x)
        if si < cfg.temporal_stages:
            x = _t_downsample(x)
    b, t, h, w, c = x.shape
    x = nn.groupnorm(p["norm_out"], x.reshape(b * t, h, w, c))
    x = jax.nn.silu(x)
    x = nn.conv2d(p["conv_out"], x).reshape(b, t, h, w, -1)
    return x * cfg.scaling_factor
