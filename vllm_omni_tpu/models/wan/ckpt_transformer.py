"""Checkpoint-schema Wan video DiT (diffusers WanTransformer3DModel).

The real-weight twin of models/wan/transformer.py: same pipeline
protocol (forward_prefix -> block stack -> forward_suffix, with the
dual-block cache splitting the stack), parameters and math at the
published checkpoint schema (reference:
vllm_omni/diffusion/models/wan2_2/wan2_2_transformer.py —
WanTransformerBlock :589, WanTimeTextImageEmbedding :251,
WanRotaryPosEmbed :147, apply_rotary_emb_wan :34).

Schema specifics honored exactly:
- per-block ``scale_shift_table`` [1, 6, D] added to a GLOBAL
  timestep projection (not per-block adaLN linears),
- fp32 non-affine LayerNorms around self-attn/FFN, affine ``norm2``
  before cross-attention,
- q/k RMSNorm over the FULL inner dim (before head split), biased
  projections throughout,
- interleaved-pair 3D rope ((t, h, w) sections of head_dim:
  [D - 2*(D//3), D//3, D//3]),
- GELU-tanh feed-forward (ffn.net.0.proj / ffn.net.2),
- output modulated by the root scale_shift_table [1, 2, D] + temb.

Supported conditioning matches the repo's Wan pipelines: per-batch
timesteps [B] (T2V / I2V / TI2V via channel concat); the reference's
per-token timestep variant is out of scope here.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.models.common import nn
from vllm_omni_tpu.ops import flash_attention, rms_norm

logger = init_logger(__name__)


@dataclass(frozen=True)
class WanCkptConfig:
    patch_size: int = 2          # spatial; temporal patch is 1
    in_channels: int = 16
    out_channels: int = 16
    num_layers: int = 30
    num_heads: int = 12
    head_dim: int = 128
    ffn_dim: int = 8960
    text_dim: int = 4096         # UMT5 feature width
    freq_dim: int = 256
    theta: float = 10000.0
    eps: float = 1e-6

    @property
    def inner_dim(self) -> int:
        return self.num_heads * self.head_dim

    @staticmethod
    def tiny() -> "WanCkptConfig":
        return WanCkptConfig(in_channels=4, out_channels=4, num_layers=2,
                             num_heads=4, head_dim=32, ffn_dim=64,
                             text_dim=64, freq_dim=32)

    @staticmethod
    def from_hf(d: dict) -> "WanCkptConfig":
        patch = d.get("patch_size", [1, 2, 2])
        return WanCkptConfig(
            patch_size=patch[1],
            in_channels=d.get("in_channels", 16),
            out_channels=d.get("out_channels", 16),
            num_layers=d.get("num_layers", 30),
            num_heads=d.get("num_attention_heads", 12),
            head_dim=d.get("attention_head_dim", 128),
            ffn_dim=d.get("ffn_dim", 8960),
            text_dim=d.get("text_dim", 4096),
            freq_dim=d.get("freq_dim", 256),
            eps=d.get("eps", 1e-6),
        )


def _attn_init(key, dim: int, kv_dim: int, dtype):
    k = jax.random.split(key, 4)
    return {
        "to_q": nn.linear_init(k[0], dim, dim, dtype=dtype),
        "to_k": nn.linear_init(k[1], kv_dim, dim, dtype=dtype),
        "to_v": nn.linear_init(k[2], kv_dim, dim, dtype=dtype),
        "to_out": nn.linear_init(k[3], dim, dim, dtype=dtype),
        "norm_q": nn.rmsnorm_init(dim, dtype),
        "norm_k": nn.rmsnorm_init(dim, dtype),
    }


def init_params(key, cfg: WanCkptConfig, dtype=jnp.float32):
    d = cfg.inner_dim
    keys = jax.random.split(key, cfg.num_layers + 8)
    patch_in = cfg.in_channels * cfg.patch_size ** 2
    p = {
        "patch_embedding": nn.linear_init(keys[0], patch_in, d,
                                          dtype=dtype),
        "condition_embedder": {
            "time_embedder": {
                "linear_1": nn.linear_init(keys[1], cfg.freq_dim, d,
                                           dtype=dtype),
                "linear_2": nn.linear_init(keys[2], d, d, dtype=dtype),
            },
            "time_proj": nn.linear_init(keys[3], d, 6 * d, dtype=dtype),
            "text_embedder": {
                "linear_1": nn.linear_init(keys[4], cfg.text_dim, d,
                                           dtype=dtype),
                "linear_2": nn.linear_init(keys[5], d, d, dtype=dtype),
            },
        },
        "scale_shift_table": jax.random.normal(
            keys[6], (1, 2, d), dtype) / d ** 0.5,
        "proj_out": nn.linear_init(
            keys[7], d, cfg.patch_size ** 2 * cfg.out_channels,
            dtype=dtype),
        "blocks": [],
    }
    for i in range(cfg.num_layers):
        bk = jax.random.split(keys[i + 8] if i + 8 < len(keys)
                              else jax.random.fold_in(key, i), 4)
        p["blocks"].append({
            "attn1": _attn_init(bk[0], d, d, dtype),
            "attn2": _attn_init(bk[1], d, d, dtype),
            "norm2": nn.layernorm_init(d, dtype=dtype),
            "ffn": {
                "fc1": nn.linear_init(bk[2], d, cfg.ffn_dim, dtype=dtype),
                "fc2": nn.linear_init(bk[3], cfg.ffn_dim, d, dtype=dtype),
            },
            "scale_shift_table": jax.random.normal(
                jax.random.fold_in(bk[3], 1), (1, 6, d), dtype) / d ** 0.5,
        })
    return p


# ------------------------------------------------------------------ rope
def rope_tables(cfg: WanCkptConfig, frames: int, grid_h: int,
                grid_w: int):
    """Interleaved-pair 3D rope tables [S, head_dim] (cos, sin) —
    WanRotaryPosEmbed with repeat_interleave(2) over pair frequencies."""
    d = cfg.head_dim
    sizes = [d - 2 * (d // 3), d // 3, d // 3]

    def axis(n, dim):
        inv = 1.0 / (cfg.theta
                     ** (np.arange(0, dim, 2, np.float64) / dim))
        ang = np.arange(n, dtype=np.float64)[:, None] * inv[None, :]
        return (np.repeat(np.cos(ang), 2, axis=-1),
                np.repeat(np.sin(ang), 2, axis=-1))

    cf, sf = axis(frames, sizes[0])
    ch, sh = axis(grid_h, sizes[1])
    cw, sw = axis(grid_w, sizes[2])
    shape = (frames, grid_h, grid_w)

    def grid(t, h, w):
        return np.concatenate([
            np.broadcast_to(t[:, None, None, :], shape + (t.shape[-1],)),
            np.broadcast_to(h[None, :, None, :], shape + (h.shape[-1],)),
            np.broadcast_to(w[None, None, :, :], shape + (w.shape[-1],)),
        ], axis=-1).reshape(frames * grid_h * grid_w, d)

    return (jnp.asarray(grid(cf, ch, cw), jnp.float32),
            jnp.asarray(grid(sf, sh, sw), jnp.float32))


def _rope_apply(x, cos, sin):
    """x [B, S, H, D]; interleaved pairs (apply_rotary_emb_wan):
    out[0::2] = x1*c - x2*s ; out[1::2] = x1*s + x2*c."""
    x1 = x[..., 0::2]
    x2 = x[..., 1::2]
    c = cos[None, :, None, 0::2].astype(jnp.float32)
    s = sin[None, :, None, 1::2].astype(jnp.float32)
    x1f = x1.astype(jnp.float32)
    x2f = x2.astype(jnp.float32)
    out = jnp.stack([x1f * c - x2f * s, x1f * s + x2f * c], axis=-1)
    return out.reshape(x.shape).astype(x.dtype)


def _ln(x, eps):
    """fp32 non-affine LayerNorm (FP32LayerNorm elementwise_affine=False);
    returns fp32."""
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    return (xf - mu) * jax.lax.rsqrt(var + eps)


def _heads(x, n):
    b, s, _ = x.shape
    return x.reshape(b, s, n, -1)


def _merge(x):
    b, s = x.shape[:2]
    return x.reshape(b, s, -1)


# ------------------------------------------------------------ components
def project_ctx(params, cfg: WanCkptConfig, ctx: jax.Array) -> jax.Array:
    """Raw text-encoder features [B, S, text_dim] -> [B, S, inner]
    (PixArtAlphaTextProjection, gelu_tanh)."""
    te = params["condition_embedder"]["text_embedder"]
    return nn.linear(te["linear_2"],
                     jax.nn.gelu(nn.linear(te["linear_1"], ctx),
                                 approximate=True))


def forward_prefix(params, cfg: WanCkptConfig, latents, timesteps):
    """Embeds + conditioning before the block stack.  Returns the same
    state tuple shape as the native module, with the temb slot carrying
    (timestep_proj [B, 6, D], temb [B, D])."""
    from vllm_omni_tpu.models.wan.transformer import patchify

    b, f, h, w, c = latents.shape
    p = cfg.patch_size
    gh, gw = h // p, w // p
    x = nn.linear(params["patch_embedding"], patchify(latents, p))
    te = params["condition_embedder"]["time_embedder"]
    sinus = nn.timestep_embedding(timesteps, cfg.freq_dim).astype(x.dtype)
    temb = nn.linear(te["linear_2"],
                     jax.nn.silu(nn.linear(te["linear_1"], sinus)))
    proj = nn.linear(params["condition_embedder"]["time_proj"],
                     jax.nn.silu(temb))
    d = cfg.inner_dim
    rope = rope_tables(cfg, f, gh, gw)
    return x, (proj.reshape(b, 6, d), temb), rope, (f, gh, gw)


def block_forward(blk, cfg: WanCkptConfig, x, ctx, temb_state, rope,
                  ctx_mask=None, self_attn_fn=None):
    """One WanTransformerBlock (reference :634-676); ``ctx`` must already
    be projected through ``project_ctx``."""
    proj, _ = temb_state
    eps = cfg.eps
    nh = cfg.num_heads
    cos, sin = rope
    mod = (blk["scale_shift_table"].astype(jnp.float32)
           + proj.astype(jnp.float32))  # [B, 6, D]
    sh1, sc1, g1, sh2, sc2, g2 = [mod[:, i][:, None] for i in range(6)]

    # 1. modulated self-attention (qk-norm over the full inner dim)
    a = blk["attn1"]
    h = (_ln(x, eps) * (1 + sc1) + sh1).astype(x.dtype)
    q = rms_norm(nn.linear(a["to_q"], h), a["norm_q"]["w"], eps)
    k = rms_norm(nn.linear(a["to_k"], h), a["norm_k"]["w"], eps)
    v = _heads(nn.linear(a["to_v"], h), nh)
    q = _rope_apply(_heads(q, nh), cos, sin)
    k = _rope_apply(_heads(k, nh), cos, sin)
    if self_attn_fn is not None:
        attn = self_attn_fn(q, k, v)
    else:
        attn = flash_attention(q, k, v, causal=False)
    attn = nn.linear(a["to_out"], _merge(attn))
    x = (x.astype(jnp.float32) + attn.astype(jnp.float32) * g1).astype(
        x.dtype)

    # 2. cross-attention (affine norm2, ungated residual)
    a = blk["attn2"]
    h = nn.layernorm(blk["norm2"], x, eps=eps)
    q = _heads(rms_norm(nn.linear(a["to_q"], h), a["norm_q"]["w"], eps),
               nh)
    k = _heads(rms_norm(nn.linear(a["to_k"], ctx), a["norm_k"]["w"],
                        eps), nh)
    v = _heads(nn.linear(a["to_v"], ctx), nh)
    scale = 1.0 / (cfg.head_dim ** 0.5)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if ctx_mask is not None:
        s = jnp.where(ctx_mask[:, None, None, :] > 0, s, -1e30)
    pattn = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", pattn,
                   v.astype(jnp.float32)).astype(x.dtype)
    x = x + nn.linear(a["to_out"], _merge(o))

    # 3. modulated GELU-tanh feed-forward
    h = (_ln(x, eps) * (1 + sc2) + sh2).astype(x.dtype)
    ff = nn.linear(blk["ffn"]["fc2"],
                   jax.nn.gelu(nn.linear(blk["ffn"]["fc1"], h),
                               approximate=True))
    return (x.astype(jnp.float32) + ff.astype(jnp.float32) * g2).astype(
        x.dtype)


def forward_suffix(params, cfg: WanCkptConfig, x, temb_state, fgw):
    from vllm_omni_tpu.models.wan.transformer import unpatchify

    _, temb = temb_state
    f, gh, gw = fgw
    mod = (params["scale_shift_table"].astype(jnp.float32)
           + temb.astype(jnp.float32)[:, None])  # [B, 2, D]
    shift, scale = mod[:, 0][:, None], mod[:, 1][:, None]
    x = ((_ln(x, cfg.eps) * (1 + scale) + shift)).astype(x.dtype)
    out = nn.linear(params["proj_out"], x)
    return unpatchify(out, cfg.patch_size, f, gh, gw, cfg.out_channels)


def forward(params, cfg: WanCkptConfig, latents, ctx, timesteps,
            ctx_mask=None, attn_fn=None):
    """Velocity prediction (ctx = RAW text features; projected here)."""
    x, temb_state, rope, fgw = forward_prefix(params, cfg, latents,
                                              timesteps)
    ctx = project_ctx(params, cfg, ctx)
    for blk in params["blocks"]:
        x = block_forward(blk, cfg, x, ctx, temb_state, rope,
                          ctx_mask, attn_fn)
    return forward_suffix(params, cfg, x, temb_state, fgw)


# ------------------------------------------------------- checkpoint load
def hf_flat_map(cfg: WanCkptConfig) -> dict:
    m: dict[str, tuple] = {}

    def wb(hf: str, *path):
        m[f"{hf}.weight"] = path + ("w",)
        m[f"{hf}.bias"] = path + ("b",)

    wb("patch_embedding", "patch_embedding")
    ce = ("condition_embedder",)
    wb("condition_embedder.time_embedder.linear_1",
       *ce, "time_embedder", "linear_1")
    wb("condition_embedder.time_embedder.linear_2",
       *ce, "time_embedder", "linear_2")
    wb("condition_embedder.time_proj", *ce, "time_proj")
    wb("condition_embedder.text_embedder.linear_1",
       *ce, "text_embedder", "linear_1")
    wb("condition_embedder.text_embedder.linear_2",
       *ce, "text_embedder", "linear_2")
    m["scale_shift_table"] = ("scale_shift_table",)
    wb("proj_out", "proj_out")
    for i in range(cfg.num_layers):
        b = f"blocks.{i}"
        tgt = ("blocks", i)
        for attn in ("attn1", "attn2"):
            for proj in ("to_q", "to_k", "to_v"):
                wb(f"{b}.{attn}.{proj}", *tgt, attn, proj)
            wb(f"{b}.{attn}.to_out.0", *tgt, attn, "to_out")
            m[f"{b}.{attn}.norm_q.weight"] = tgt + (attn, "norm_q", "w")
            m[f"{b}.{attn}.norm_k.weight"] = tgt + (attn, "norm_k", "w")
        wb(f"{b}.norm2", *tgt, "norm2")
        wb(f"{b}.ffn.net.0.proj", *tgt, "ffn", "fc1")
        wb(f"{b}.ffn.net.2", *tgt, "ffn", "fc2")
        m[f"{b}.scale_shift_table"] = tgt + ("scale_shift_table",)
    return m


def hf_transform(name: str, arr):
    """Conv3d patch embedding [O, C, 1, p, p] -> linear [p*p*C, O]
    matching patchify's (row, col, channel) feature order; other linears
    [out, in] -> [in, out]; tables keep their stored shape."""
    if name == "patch_embedding.weight":
        o, c, kt, kh, kw = arr.shape
        if kt != 1:
            raise ValueError(f"temporal patch {kt} != 1 unsupported")
        return arr.reshape(o, c, kh, kw).transpose(2, 3, 1, 0).reshape(
            kh * kw * c, o)
    if arr.ndim == 2 and name.endswith("weight"):
        return arr.T
    return arr


def load_wan_dit(model_dir: str, cfg: WanCkptConfig = None,
                 dtype=jnp.bfloat16):
    """Stream a diffusers-format Wan transformer directory."""
    import json
    import os

    from vllm_omni_tpu.model_loader.safetensors_loader import (
        load_checkpoint_tree,
    )

    if cfg is None:
        with open(os.path.join(model_dir, "config.json")) as f:
            cfg = WanCkptConfig.from_hf(json.load(f))
    shapes = jax.eval_shape(
        lambda: init_params(jax.random.PRNGKey(0), cfg, jnp.float32))
    tree = jax.tree.map(lambda t: np.zeros(t.shape, np.float32), shapes)
    flat = hf_flat_map(cfg)
    n, _ = load_checkpoint_tree(
        model_dir, flat.get, tree, dtype=np.float32,
        transform=hf_transform, name_filter=lambda nm: nm in flat,
    )
    n_leaves = len(jax.tree.leaves(tree))
    if n < n_leaves:
        raise ValueError(
            f"{model_dir} covered {n}/{n_leaves} Wan DiT weights")
    return jax.tree.map(lambda a: jnp.asarray(a, dtype), tree), cfg
