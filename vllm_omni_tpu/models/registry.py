"""Model registries.

``DiffusionModelRegistry`` mirrors the reference's lazy arch->pipeline map
(vllm_omni/diffusion/registry.py:16-102, 17 pipelines); ``OmniModelRegistry``
mirrors the AR model registry (model_executor/models/registry.py:65).
Builders are lazy import paths so importing the registry stays light.
"""

from __future__ import annotations

import importlib
from dataclasses import dataclass
from typing import Any, Callable, Optional

from vllm_omni_tpu.logger import init_logger

logger = init_logger(__name__)


@dataclass
class _Entry:
    module: str
    attr: str

    def load(self):
        return getattr(importlib.import_module(self.module), self.attr)


# arch name (as appears in model_index.json `_class_name` for diffusers
# checkpoints) -> pipeline class
_DIFFUSION_MODELS: dict[str, _Entry] = {
    "QwenImagePipeline": _Entry(
        "vllm_omni_tpu.models.qwen_image.pipeline", "QwenImagePipeline"
    ),
    # image editing: input image VAE-encoded and appended to the token
    # sequence (reference: pipeline_qwen_image_edit.py:218 /
    # pipeline_qwen_image_edit_plus.py)
    "QwenImageEditPipeline": _Entry(
        "vllm_omni_tpu.models.qwen_image.edit_pipeline",
        "QwenImageEditPipeline"
    ),
    "QwenImageEditPlusPipeline": _Entry(
        "vllm_omni_tpu.models.qwen_image.edit_pipeline",
        "QwenImageEditPlusPipeline"
    ),
    # composite + N layers denoised jointly on the rope frame axis
    # (reference: pipeline_qwen_image_layered.py)
    "QwenImageLayeredPipeline": _Entry(
        "vllm_omni_tpu.models.qwen_image.layered_pipeline",
        "QwenImageLayeredPipeline"
    ),
    # video (reference: Wan2.2 T2V family, diffusion/registry.py:16-102)
    "WanPipeline": _Entry(
        "vllm_omni_tpu.models.wan.pipeline", "WanT2VPipeline"
    ),
    "WanT2VPipeline": _Entry(
        "vllm_omni_tpu.models.wan.pipeline", "WanT2VPipeline"
    ),
    # image(+text)-to-video: first frame anchored via VAE-latent + mask
    # conditioning channels (reference: WanImageToVideoPipeline /
    # Wan2.2 TI2V, diffusion/registry.py:16-102)
    "WanImageToVideoPipeline": _Entry(
        "vllm_omni_tpu.models.wan.pipeline", "WanI2VPipeline"
    ),
    "WanI2VPipeline": _Entry(
        "vllm_omni_tpu.models.wan.pipeline", "WanI2VPipeline"
    ),
    "WanTI2VPipeline": _Entry(
        "vllm_omni_tpu.models.wan.pipeline", "WanI2VPipeline"
    ),
    # joint-attention MMDiT siblings (reference: FluxPipeline / SD3,
    # diffusion/registry.py:16-102) — one shared MMDiT block implementation
    "FluxPipeline": _Entry(
        "vllm_omni_tpu.models.flux.pipeline", "FluxPipeline"
    ),
    "StableDiffusion3Pipeline": _Entry(
        "vllm_omni_tpu.models.sd3.pipeline", "SD3Pipeline"
    ),
    "SD3Pipeline": _Entry(
        "vllm_omni_tpu.models.sd3.pipeline", "SD3Pipeline"
    ),
    # audio (reference: StableAudio family)
    "StableAudioPipeline": _Entry(
        "vllm_omni_tpu.models.stable_audio.pipeline", "StableAudioPipeline"
    ),
    # unified-sequence single-stream DiT (reference: z_image/
    # pipeline_z_image.py)
    "ZImagePipeline": _Entry(
        "vllm_omni_tpu.models.z_image.pipeline", "ZImagePipeline"
    ),
    # Flux-geometry MMDiT with true CFG + renorm (reference:
    # longcat_image/pipeline_longcat_image.py:202)
    "LongCatImagePipeline": _Entry(
        "vllm_omni_tpu.models.longcat_image.pipeline",
        "LongCatImagePipeline"
    ),
    "LongCatImageEditPipeline": _Entry(
        "vllm_omni_tpu.models.longcat_image.pipeline",
        "LongCatImageEditPipeline"
    ),
    # AR+diffusion hybrid: the MoT LLM runs the flow itself (reference:
    # bagel/pipeline_bagel.py:153)
    "BagelPipeline": _Entry(
        "vllm_omni_tpu.models.bagel.pipeline", "BagelPipeline"
    ),
    # the published repo declares this arch in config.json (reference:
    # omni_diffusion.py:79 routes it to BagelPipeline)
    "BagelForConditionalGeneration": _Entry(
        "vllm_omni_tpu.models.bagel.pipeline", "BagelPipeline"
    ),
    # unified causal MM generator, shared single stack (reference:
    # hunyuan_image_3/pipeline_hunyuan_image_3.py:65)
    "HunyuanImage3ForCausalMM": _Entry(
        "vllm_omni_tpu.models.hunyuan_image_3.pipeline",
        "HunyuanImage3Pipeline"
    ),
    # AR-prior + DiT two-model generation (reference:
    # glm_image/pipeline_glm_image.py:247-255)
    "GlmImagePipeline": _Entry(
        "vllm_omni_tpu.models.glm_image.pipeline", "GlmImagePipeline"
    ),
    # Flux-architecture variants over the shared MMDiT (reference:
    # ovis_image/, flux2_klein/)
    "OvisImagePipeline": _Entry(
        "vllm_omni_tpu.models.ovis_image.pipeline", "OvisImagePipeline"
    ),
    "Flux2KleinPipeline": _Entry(
        "vllm_omni_tpu.models.flux2_klein.pipeline", "Flux2KleinPipeline"
    ),
}

# AR architectures -> the family's entry-stage (thinker/LM) REAL
# checkpoint factory.  Stage YAMLs address stages by explicit
# `model_factory` strings; this registry is the arch-name front door
# (reference: model_executor/models/registry.py:65 — e.g.
# Qwen3OmniMoeForConditionalGeneration): resolve(arch) returns a
# callable (model_dir, **kw) -> (params, TransformerConfig,
# eos_token_id) that LOADS the checkpoint — never a random-init toy
# (tiny factories stay reachable only via their explicit module paths,
# e.g. "...thinker:tiny_factory").  Downstream stages
# (talker/code2wav/...) stay per-stage factories in the family's stage
# YAML.
_AR_MODELS: dict[str, _Entry] = {
    "Qwen3OmniMoeForConditionalGeneration": _Entry(
        "vllm_omni_tpu.models.qwen3_omni.thinker", "real_factory"
    ),
    "Qwen2_5OmniForConditionalGeneration": _Entry(
        "vllm_omni_tpu.models.qwen2_5_omni.thinker", "real_factory"
    ),
    "Qwen2_5OmniModel": _Entry(
        "vllm_omni_tpu.models.qwen2_5_omni.thinker", "real_factory"
    ),
    "Qwen3TTSForConditionalGeneration": _Entry(
        "vllm_omni_tpu.models.qwen3_tts.tts_lm", "real_factory"
    ),
    # plain Qwen LMs serve through the same engine (single-stage llm)
    "Qwen2ForCausalLM": _Entry(
        "vllm_omni_tpu.model_loader.hf_qwen", "load_qwen_lm"
    ),
    "Qwen3ForCausalLM": _Entry(
        "vllm_omni_tpu.model_loader.hf_qwen", "load_qwen_lm"
    ),
    "Qwen3MoeForCausalLM": _Entry(
        "vllm_omni_tpu.model_loader.hf_qwen", "load_qwen_lm"
    ),
}


class DiffusionModelRegistry:
    @staticmethod
    def register(arch: str, module: str, attr: str) -> None:
        _DIFFUSION_MODELS[arch] = _Entry(module, attr)

    @staticmethod
    def resolve(arch: str):
        if arch not in _DIFFUSION_MODELS:
            raise KeyError(
                f"unknown diffusion architecture {arch!r}; known: "
                f"{sorted(_DIFFUSION_MODELS)}"
            )
        return _DIFFUSION_MODELS[arch].load()

    @staticmethod
    def supported() -> list[str]:
        return sorted(_DIFFUSION_MODELS)


class OmniModelRegistry:
    @staticmethod
    def register(arch: str, module: str, attr: str) -> None:
        _AR_MODELS[arch] = _Entry(module, attr)

    @staticmethod
    def resolve(arch: str):
        if arch not in _AR_MODELS:
            raise KeyError(
                f"unknown AR architecture {arch!r}; known: {sorted(_AR_MODELS)}"
            )
        return _AR_MODELS[arch].load()

    @staticmethod
    def supported() -> list[str]:
        return sorted(_AR_MODELS)
