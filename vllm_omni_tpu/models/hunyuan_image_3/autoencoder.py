"""HunyuanImage-3 DCAE autoencoder (AutoencoderKLConv3D) — TPU-native.

Reference: vllm_omni/diffusion/models/hunyuan_image_3/autoencoder.py —
3D-conv KL autoencoder with DCAE channel-shuffle resamplers:
ResnetBlocks (GroupNorm32/eps1e-6 + swish + conv3), a single-head
attention middle block, DownsampleDCAE (conv then pixel-unshuffle, plus
a grouped-mean channel shortcut, :174-193) and UpsampleDCAE (conv then
pixel-shuffle, plus a repeat-interleave shortcut, :195-211), and
channel-averaged / repeated residual shortcuts at the encoder tail and
decoder head (:294-299, :369-371).

TPU-first: NDHWC ``lax.conv_general_dilated`` (one frame degenerates the
temporal axis but KEEPS the 3-tap temporal kernel semantics — zero
padding around the single frame, matching the torch Conv3d numerics),
functional param pytrees, attention as one fused jnp softmax (the
latent grid is 64x64 at 1024px — no flash kernel needed).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.models.common import nn


@dataclass(frozen=True)
class DCAEConfig:
    in_channels: int = 3
    out_channels: int = 3
    latent_channels: int = 32
    block_out_channels: tuple = (128, 256, 512, 1024, 1024)
    layers_per_block: int = 2
    ffactor_spatial: int = 16
    ffactor_temporal: int = 1
    scaling_factor: Optional[float] = None
    shift_factor: Optional[float] = None
    downsample_match_channel: bool = True
    upsample_match_channel: bool = True

    @staticmethod
    def from_hf(d: dict) -> "DCAEConfig":
        return DCAEConfig(
            in_channels=d.get("in_channels", 3),
            out_channels=d.get("out_channels", 3),
            latent_channels=d.get("latent_channels", 32),
            block_out_channels=tuple(d.get("block_out_channels",
                                           (128, 256, 512, 1024, 1024))),
            layers_per_block=d.get("layers_per_block", 2),
            ffactor_spatial=d.get("ffactor_spatial", 16),
            ffactor_temporal=d.get("ffactor_temporal", 1),
            scaling_factor=d.get("scaling_factor"),
            shift_factor=d.get("shift_factor"),
            downsample_match_channel=d.get("downsample_match_channel",
                                           True),
            upsample_match_channel=d.get("upsample_match_channel", True),
        )

    @staticmethod
    def tiny() -> "DCAEConfig":
        return DCAEConfig(
            latent_channels=4, block_out_channels=(32, 64),
            layers_per_block=1, ffactor_spatial=2, ffactor_temporal=1)


# ------------------------------------------------------------- primitives
def _conv3d_init(key, cin, cout, k, dtype):
    scale = 1.0 / np.sqrt(cin * k * k * k)
    kw, kb = jax.random.split(key)
    return {
        "w": jax.random.uniform(kw, (k, k, k, cin, cout), dtype,
                                -scale, scale),
        "b": jax.random.uniform(kb, (cout,), dtype, -scale, scale),
    }


def _conv3d(p, x):
    # x [B, T, H, W, C]; kernel [kt, kh, kw, in, out], SAME zero padding
    k = p["w"].shape[0]
    pad = (k - 1) // 2
    out = jax.lax.conv_general_dilated(
        x, p["w"].astype(x.dtype), window_strides=(1, 1, 1),
        padding=[(pad, pad)] * 3,
        dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
    return out + p["b"].astype(out.dtype)


def _gn_init(c, dtype):
    return nn.layernorm_init(c, dtype=dtype)  # {w, b}


def _gn(p, x, groups=32):
    b = x.shape[0]
    c = x.shape[-1]
    g = min(groups, c)
    xf = x.astype(jnp.float32).reshape(b, -1, g, c // g)
    mu = xf.mean(axis=(1, 3), keepdims=True)
    var = xf.var(axis=(1, 3), keepdims=True)
    xn = ((xf - mu) * jax.lax.rsqrt(var + 1e-6)).reshape(x.shape)
    return (xn * p["w"] + p["b"]).astype(x.dtype)


def _swish(x):
    return x * jax.nn.sigmoid(x)


def _resnet_init(key, cin, cout, dtype):
    k = jax.random.split(key, 3)
    p = {
        "norm1": _gn_init(cin, dtype),
        "conv1": _conv3d_init(k[0], cin, cout, 3, dtype),
        "norm2": _gn_init(cout, dtype),
        "conv2": _conv3d_init(k[1], cout, cout, 3, dtype),
    }
    if cin != cout:
        p["nin_shortcut"] = _conv3d_init(k[2], cin, cout, 1, dtype)
    return p


def _resnet(p, x):
    h = _conv3d(p["conv1"], _swish(_gn(p["norm1"], x)))
    h = _conv3d(p["conv2"], _swish(_gn(p["norm2"], h)))
    if "nin_shortcut" in p:
        x = _conv3d(p["nin_shortcut"], x)
    return x + h


def _attn_init(key, c, dtype):
    k = jax.random.split(key, 4)
    return {"norm": _gn_init(c, dtype),
            "q": _conv3d_init(k[0], c, c, 1, dtype),
            "k": _conv3d_init(k[1], c, c, 1, dtype),
            "v": _conv3d_init(k[2], c, c, 1, dtype),
            "proj_out": _conv3d_init(k[3], c, c, 1, dtype)}


def _attn(p, x):
    b, t, h, w, c = x.shape
    hn = _gn(p["norm"], x)
    q = _conv3d(p["q"], hn).reshape(b, t * h * w, c)
    k = _conv3d(p["k"], hn).reshape(b, t * h * w, c)
    v = _conv3d(p["v"], hn).reshape(b, t * h * w, c)
    s = jnp.einsum("bqc,bkc->bqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / np.sqrt(c)
    o = jnp.einsum("bqk,bkc->bqc", jax.nn.softmax(s, axis=-1),
                   v.astype(jnp.float32)).astype(x.dtype)
    o = _conv3d(p["proj_out"], o.reshape(b, t, h, w, c))
    return x + o


def _unshuffle(x, r1):
    # [B, (f r1), (h 2), (w 2), C] -> [B, f, h, w, (r1*2*2*C)] with the
    # torch channel order (r1, r2, r3, c)
    b, t, hh, ww, c = x.shape
    x = x.reshape(b, t // r1, r1, hh // 2, 2, ww // 2, 2, c)
    x = x.transpose(0, 1, 3, 5, 2, 4, 6, 7)
    return x.reshape(b, t // r1, hh // 2, ww // 2, r1 * 4 * c)


def _shuffle(x, r1):
    # inverse of _unshuffle: channels ordered (r1, r2, r3, c)
    b, t, hh, ww, rc = x.shape
    c = rc // (r1 * 4)
    x = x.reshape(b, t, hh, ww, r1, 2, 2, c)
    x = x.transpose(0, 1, 4, 2, 5, 3, 6, 7)
    return x.reshape(b, t * r1, hh * 2, ww * 2, c)


def _down_init(key, cin, cout, temporal, dtype):
    factor = 8 if temporal else 4
    return {"conv": _conv3d_init(key, cin, cout // factor, 3, dtype)}


def _down(p, x, cin, cout, temporal):
    r1 = 2 if temporal else 1
    h = _unshuffle(_conv3d(p["conv"], x), r1)
    shortcut = _unshuffle(x, r1)
    b, t, hh, ww, c = shortcut.shape
    group = c // cout
    shortcut = shortcut.reshape(b, t, hh, ww, cout, group).mean(axis=-1)
    return h + shortcut


def _up_init(key, cin, cout, temporal, dtype):
    factor = 8 if temporal else 4
    return {"conv": _conv3d_init(key, cin, cout * factor, 3, dtype)}


def _up(p, x, cin, cout, temporal):
    r1 = 2 if temporal else 1
    h = _shuffle(_conv3d(p["conv"], x), r1)
    repeats = (8 if temporal else 4) * cout // cin
    shortcut = jnp.repeat(x, repeats, axis=-1)
    return h + _shuffle(shortcut, r1)


# --------------------------------------------------------------- encoder
def _levels_down(cfg: DCAEConfig):
    levels = []
    block_in = cfg.block_out_channels[0]
    for i, ch in enumerate(cfg.block_out_channels):
        spatial = i < np.log2(cfg.ffactor_spatial)
        temporal = spatial and i >= np.log2(
            cfg.ffactor_spatial // cfg.ffactor_temporal)
        down_out = None
        blocks = []
        for _ in range(cfg.layers_per_block):
            blocks.append((block_in, ch))
            block_in = ch
        if spatial or temporal:
            down_out = (cfg.block_out_channels[i + 1]
                        if cfg.downsample_match_channel else block_in)
        levels.append((blocks, down_out, temporal))
        if down_out is not None:
            block_in = down_out
    return levels, block_in


def init_encoder(key, cfg: DCAEConfig, dtype=jnp.float32):
    levels, block_in = _levels_down(cfg)
    keys = iter(jax.random.split(key, 256))
    p = {"conv_in": _conv3d_init(next(keys), cfg.in_channels,
                                 cfg.block_out_channels[0], 3, dtype),
         "down": []}
    for blocks, down_out, temporal in levels:
        lvl = {"block": [
            _resnet_init(next(keys), cin, cout, dtype)
            for cin, cout in blocks]}
        if down_out is not None:
            lvl["downsample"] = _down_init(next(keys), blocks[-1][1],
                                           down_out, temporal, dtype)
        p["down"].append(lvl)
    p["mid_block_1"] = _resnet_init(next(keys), block_in, block_in, dtype)
    p["mid_attn_1"] = _attn_init(next(keys), block_in, dtype)
    p["mid_block_2"] = _resnet_init(next(keys), block_in, block_in, dtype)
    p["norm_out"] = _gn_init(block_in, dtype)
    p["conv_out"] = _conv3d_init(next(keys), block_in,
                                 2 * cfg.latent_channels, 3, dtype)
    return p


def encode(p, cfg: DCAEConfig, x):
    """x [B, T, H, W, C] -> latent distribution moments
    [B, T', H', W', 2*z]."""
    levels, _ = _levels_down(cfg)
    h = _conv3d(p["conv_in"], x)
    for lvl_p, (blocks, down_out, temporal) in zip(p["down"], levels):
        for bp in lvl_p["block"]:
            h = _resnet(bp, h)
        if down_out is not None:
            h = _down(lvl_p["downsample"], h, blocks[-1][1], down_out,
                      temporal)
    h = _resnet(p["mid_block_1"], h)
    h = _attn(p["mid_attn_1"], h)
    h = _resnet(p["mid_block_2"], h)
    group = cfg.block_out_channels[-1] // (2 * cfg.latent_channels)
    b, t, hh, ww, c = h.shape
    # torch groups channels as (c r) with r consecutive — channel-major
    shortcut = h.reshape(b, t, hh, ww, 2 * cfg.latent_channels,
                         group).mean(axis=-1)
    h = _conv3d(p["conv_out"], _swish(_gn(p["norm_out"], h)))
    return h + shortcut


def _levels_up(cfg: DCAEConfig):
    levels = []
    block_in = cfg.block_out_channels[0]
    for i, ch in enumerate(cfg.block_out_channels):
        spatial = i < np.log2(cfg.ffactor_spatial)
        temporal = i < np.log2(cfg.ffactor_temporal)
        blocks = []
        for _ in range(cfg.layers_per_block + 1):
            blocks.append((block_in, ch))
            block_in = ch
        up_out = None
        if spatial or temporal:
            up_out = (cfg.block_out_channels[i + 1]
                      if cfg.upsample_match_channel else block_in)
        levels.append((blocks, up_out, temporal))
        if up_out is not None:
            block_in = up_out
    return levels, block_in


def init_decoder(key, cfg: DCAEConfig, dtype=jnp.float32):
    levels, block_in = _levels_up(cfg)
    keys = iter(jax.random.split(key, 256))
    first = cfg.block_out_channels[0]
    p = {"conv_in": _conv3d_init(next(keys), cfg.latent_channels,
                                 first, 3, dtype)}
    p["mid_block_1"] = _resnet_init(next(keys), first, first, dtype)
    p["mid_attn_1"] = _attn_init(next(keys), first, dtype)
    p["mid_block_2"] = _resnet_init(next(keys), first, first, dtype)
    p["up"] = []
    for blocks, up_out, temporal in levels:
        lvl = {"block": [
            _resnet_init(next(keys), cin, cout, dtype)
            for cin, cout in blocks]}
        if up_out is not None:
            lvl["upsample"] = _up_init(next(keys), blocks[-1][1],
                                       up_out, temporal, dtype)
        p["up"].append(lvl)
    p["norm_out"] = _gn_init(block_in, dtype)
    p["conv_out"] = _conv3d_init(next(keys), block_in,
                                 cfg.out_channels, 3, dtype)
    return p


def decode(p, cfg: DCAEConfig, z):
    """z [B, T', H', W', z_channels] -> [B, T, H, W, out_channels]."""
    levels, _ = _levels_up(cfg)
    repeats = cfg.block_out_channels[0] // cfg.latent_channels
    h = _conv3d(p["conv_in"], z) + jnp.repeat(z, repeats, axis=-1)
    h = _resnet(p["mid_block_1"], h)
    h = _attn(p["mid_attn_1"], h)
    h = _resnet(p["mid_block_2"], h)
    for lvl_p, (blocks, up_out, temporal) in zip(p["up"], levels):
        for bp in lvl_p["block"]:
            h = _resnet(bp, h)
        if up_out is not None:
            h = _up(lvl_p["upsample"], h, blocks[-1][1], up_out,
                    temporal)
    return _conv3d(p["conv_out"], _swish(_gn(p["norm_out"], h)))
