"""HunyuanImage-3 LM-backbone checkpoint loader.

The published checkpoint is one HF repo whose safetensors carry the
causal MoE LM plus the diffusion heads and towers.  This loader covers
the LM BACKBONE (the overwhelming share of the bytes) at the names the
reference consumes (hunyuan_image_3_transformer.py:1825-2030):
``[model.]wte`` / ``ln_f`` / ``layers.N.{input_layernorm,
post_attention_layernorm, self_attn.{q,k,v,o}_proj,
mlp.gate.wg, mlp.experts.E.{gate_and_up_proj|gate_proj+up_proj,
down_proj}, mlp.shared_mlp.*}`` — fused ``gate_and_up_proj`` tensors
store UP first, GATE second (the reference's expert_weights_remapping,
:1816-1819) while this repo's ``silu_mul`` wants gate first, so halves
swap at load.

The UNet projector / timestep-embedder heads load via
``load_hunyuan_heads``; the DCAE autoencoder halves
(AutoencoderKLConv3D, models/hunyuan_image_3/autoencoder.py) via
``load_dcae``.
"""

from __future__ import annotations

import json
import os
import re
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.models.hunyuan_image_3.transformer import (
    HunyuanImage3Config,
    init_params,
)

logger = init_logger(__name__)


def config_from_hf(model_dir: str) -> HunyuanImage3Config:
    with open(os.path.join(model_dir, "config.json")) as f:
        hf = json.load(f)

    def first(v, default=None):
        if isinstance(v, (list, tuple)):
            return v[0]
        return default if v is None else v

    heads = hf["num_attention_heads"]
    return HunyuanImage3Config(
        vocab_size=hf["vocab_size"],
        hidden_size=hf["hidden_size"],
        num_layers=hf["num_hidden_layers"],
        num_heads=heads,
        num_kv_heads=hf.get("num_key_value_heads", heads),
        head_dim=hf.get("attention_head_dim")
        or hf["hidden_size"] // heads,
        intermediate_size=hf.get("intermediate_size", 11008),
        moe_intermediate_size=first(hf.get("moe_intermediate_size"),
                                    3072),
        num_experts=first(hf.get("num_experts"), 1),
        moe_topk=first(hf.get("moe_topk"), 1),
        moe_layer_num_skipped=first(hf.get("moe_layer_num_skipped"), 0),
        rope_theta=hf.get("rope_theta", 10000.0),
        rms_eps=hf.get("rms_norm_eps", 1e-5),
        boi_token_id=hf.get("boi_token_id", 4),
        eoi_token_id=hf.get("eoi_token_id", 5),
        image_token_id=hf.get("image_token_id", 8),
        size_token_id=hf.get("size_token_id", 290800),
        ratio_token_base=hf.get("ratio_token_base", 290816),
    )


_LAYER_RE = re.compile(r"^layers\.(\d+)\.(.+)$")
_EXPERT_RE = re.compile(
    r"^mlp\.experts\.(\d+)\.(gate_and_up_proj|gate_proj|up_proj|"
    r"down_proj)$")


def load_hunyuan_lm(model_dir: str,
                    cfg: Optional[HunyuanImage3Config] = None,
                    dtype=jnp.bfloat16):
    """Returns (params, cfg).  Raises unless every LM leaf is covered."""
    from vllm_omni_tpu.model_loader.safetensors_loader import (
        iter_safetensors,
        np_param_dtype,
    )

    if cfg is None:
        cfg = config_from_hf(model_dir)
    np_dtype = np_param_dtype(dtype)
    # untied output head when the checkpoint ships one (gen_text mode
    # needs real logits; tie_word_embeddings=False in the reference)
    has_head = checkpoint_has_prefix(model_dir, "lm_head.")
    shapes = jax.eval_shape(
        lambda: init_params(jax.random.PRNGKey(0), cfg, jnp.float32,
                            lm_head=has_head))
    tree = jax.tree_util.tree_map(
        lambda s: np.zeros(s.shape, np_dtype), shapes)
    inter = cfg.moe_intermediate_size
    n = 0
    unmapped: list[str] = []
    # per-layer expert write counters: the stacked [E, ...] leaves fill
    # from E (or 2E split-layout) per-expert writes — a zero-check alone
    # would miss a truncated shard that covered only some experts
    from collections import Counter

    expert_writes: Counter = Counter()

    def norm_name(name: str) -> str:
        return name[6:] if name.startswith("model.") else name

    _DIRECT = {
        "input_layernorm": ("input_norm", "w", False),
        "post_attention_layernorm": ("post_norm", "w", False),
        "self_attn.q_proj": ("q_proj", "w", True),
        "self_attn.k_proj": ("k_proj", "w", True),
        "self_attn.v_proj": ("v_proj", "w", True),
        "self_attn.o_proj": ("o_proj", "w", True),
    }

    def want(nm):
        nm = norm_name(nm)
        return (nm.startswith(("wte.", "ln_f.", "layers."))
                or nm in ("lm_head.weight",))

    for raw, arr in iter_safetensors(model_dir, want):
        name = norm_name(raw)
        if name == "wte.weight":
            tree["embed"]["w"][...] = arr
            n += 1
            continue
        if name == "ln_f.weight":
            tree["final_norm"]["w"][...] = arr
            n += 1
            continue
        if name == "lm_head.weight":
            tree["lm_head"]["w"][...] = arr.T
            n += 1
            continue
        # expert projections ship as bare parameters (no .weight
        # suffix) while Linear/RMSNorm tensors carry one — strip either
        kind, base = "weight", name
        if base.endswith(".bias"):
            kind, base = "bias", base[:-5]
        elif base.endswith(".weight"):
            base = base[:-7]
        m = _LAYER_RE.match(base)
        if not m:
            unmapped.append(raw)
            continue
        li, sub = int(m.group(1)), m.group(2)
        if li >= cfg.num_layers or kind == "bias":
            unmapped.append(raw)
            continue
        layer = tree["layers"][li]
        if sub in _DIRECT:
            key, leaf, transpose = _DIRECT[sub]
            layer[key][leaf][...] = arr.T if transpose else arr
            n += 1
            continue
        if sub in ("mlp.gate.wg", "mlp.gate"):
            layer["gate"][...] = arr.T
            n += 1
            continue
        em = _EXPERT_RE.match(sub)
        if em:
            e, which = int(em.group(1)), em.group(2)
            if which == "gate_and_up_proj":
                # checkpoint order [up; gate] -> ours [gate; up]
                up, gate = np.split(arr, 2, axis=0)
                layer["experts_gate_up"][e, :, :inter] = gate.T
                layer["experts_gate_up"][e, :, inter:] = up.T
                expert_writes[(li, "gate_up")] += 2
            elif which == "gate_proj":
                layer["experts_gate_up"][e, :, :inter] = arr.T
                expert_writes[(li, "gate_up")] += 1
            elif which == "up_proj":
                layer["experts_gate_up"][e, :, inter:] = arr.T
                expert_writes[(li, "gate_up")] += 1
            else:
                layer["experts_down"][e] = arr.T
                expert_writes[(li, "down")] += 1
            n += 1
            continue
        if sub.startswith("mlp.shared_mlp."):
            tail = sub[len("mlp.shared_mlp."):]
            if tail == "gate_and_up_proj":
                up, gate = np.split(arr, 2, axis=0)
                layer["shared_gate_up"]["w"][:, :cfg.intermediate_size] \
                    = gate.T
                layer["shared_gate_up"]["w"][:, cfg.intermediate_size:] \
                    = up.T
            elif tail == "gate_proj":
                layer["shared_gate_up"]["w"][
                    :, :cfg.intermediate_size] = arr.T
            elif tail == "up_proj":
                layer["shared_gate_up"]["w"][
                    :, cfg.intermediate_size:] = arr.T
            elif tail == "down_proj":
                layer["shared_down"]["w"][...] = arr.T
            else:
                unmapped.append(raw)
                continue
            n += 1
            continue
        if sub in ("mlp.gate_up_proj", "mlp.gate_and_up_proj"):
            # dense (non-MoE) layer
            up, gate = np.split(arr, 2, axis=0)
            layer["gate_up"]["w"][:, :cfg.intermediate_size] = gate.T
            layer["gate_up"]["w"][:, cfg.intermediate_size:] = up.T
            n += 1
            continue
        if sub == "mlp.down_proj":
            layer["down"]["w"][...] = arr.T
            n += 1
            continue
        unmapped.append(raw)

    if unmapped:
        logger.warning("hunyuan LM loader: %d unmapped tensors "
                       "(e.g. %s)", len(unmapped), unmapped[:4])
    if cfg.num_experts > 1:
        for li in range(cfg.num_layers):
            if not cfg.is_moe_layer(li):
                continue
            gu = expert_writes[(li, "gate_up")]
            dn_w = expert_writes[(li, "down")]
            # fused layout writes 2 per expert into gate_up, split 2
            if gu < 2 * cfg.num_experts or dn_w < cfg.num_experts:
                raise ValueError(
                    f"{model_dir}: layer {li} expert coverage "
                    f"incomplete (gate_up {gu}/{2 * cfg.num_experts}, "
                    f"down {dn_w}/{cfg.num_experts})")
    n_leaves = len(jax.tree_util.tree_leaves(tree))
    # fused tensors fill one leaf from two writes; count leaves touched
    # via a zero-check instead of write counts
    zero_leaves = [p for p, a in jax.tree_util.tree_leaves_with_path(tree)
                   if not np.any(a)]
    if zero_leaves:
        raise ValueError(
            f"{model_dir}: {len(zero_leaves)}/{n_leaves} LM leaves "
            f"uncovered (e.g. {jax.tree_util.keystr(zero_leaves[0])})")
    return jax.tree_util.tree_map(
        lambda a: jnp.asarray(a, dtype), tree), cfg


def load_hunyuan_heads(model_dir: str, params_shapes, dtype=jnp.bfloat16):
    """Load the UNet projector + timestep-embedder heads into a tree
    shaped like the pipeline's head params (patch_embed / final_layer /
    time_embed / timestep_emb / time_embed_2) — checkpoint names per the
    reference ResBlock/UNetDown/UNetUp/TimestepEmbedder classes
    (hunyuan_image_3_transformer.py:2535-2790, patch_size=1)."""
    from vllm_omni_tpu.models.flux.loader import load_routed

    r: dict[str, tuple] = {}

    def lin(hf, *path):
        r[f"{hf}.weight"] = ("direct", path + ("w",))
        r[f"{hf}.bias"] = ("direct", path + ("b",))

    gn = lin  # groupnorm routes identically (weight/bias -> w/b)

    def conv(hf, *path):
        r[f"{hf}.weight"] = ("conv", path + ("w",))
        r[f"{hf}.bias"] = ("direct", path + ("b",))

    def resblock(hf, *path):
        gn(f"{hf}.in_layers.0", *path, "in_norm")
        conv(f"{hf}.in_layers.2", *path, "in_conv")
        lin(f"{hf}.emb_layers.1", *path, "emb")
        gn(f"{hf}.out_layers.0", *path, "out_norm")
        conv(f"{hf}.out_layers.3", *path, "out_conv")
        conv(f"{hf}.skip_connection", *path, "skip")

    for t in ("time_embed", "timestep_emb", "time_embed_2"):
        lin(f"{t}.mlp.0", t, "fc1")
        lin(f"{t}.mlp.2", t, "fc2")
    conv("patch_embed.model.0", "patch_embed", "conv_in")
    resblock("patch_embed.model.1", "patch_embed", "res")
    resblock("final_layer.model.0", "final_layer", "res")
    gn("final_layer.model.1.0", "final_layer", "out_norm")
    conv("final_layer.model.1.2", "final_layer", "conv_out")

    # conv kernels: torch [out, in, kh, kw] -> NHWC [kh, kw, in, out]
    def load(model_dir, routing, shapes, dtype):
        transforms = {
            name: (lambda a: np.ascontiguousarray(
                a.transpose(2, 3, 1, 0)))
            for name, route in routing.items()
            if route[0] == "conv"
        }
        routing = {k: (("raw",) + v[1:] if v[0] == "conv" else v)
                   for k, v in routing.items()}
        return load_routed(model_dir, routing, shapes, dtype,
                           transforms=transforms)

    return load(model_dir, r, params_shapes, dtype)


def _dcae_conv(arr):
    # torch [out, in, kt, kh, kw] -> NDHWC kernel [kt, kh, kw, in, out]
    return np.ascontiguousarray(arr.transpose(2, 3, 4, 1, 0))


def _dcae_routing(cfg, half: str) -> dict:
    """Routing for one autoencoder half ('encoder' | 'decoder') of the
    AutoencoderKLConv3D checkpoint (reference autoencoder.py)."""
    from vllm_omni_tpu.models.hunyuan_image_3 import autoencoder as ae

    r: dict[str, tuple] = {}

    def conv(hf, *path):
        r[f"{hf}.weight"] = ("conv3d", path + ("w",))
        r[f"{hf}.bias"] = ("direct", path + ("b",))

    def gn(hf, *path):
        r[f"{hf}.weight"] = ("direct", path + ("w",))
        r[f"{hf}.bias"] = ("direct", path + ("b",))

    def resnet(hf, spec, *path):
        cin, cout = spec
        gn(f"{hf}.norm1", *path, "norm1")
        conv(f"{hf}.conv1", *path, "conv1")
        gn(f"{hf}.norm2", *path, "norm2")
        conv(f"{hf}.conv2", *path, "conv2")
        if cin != cout:
            conv(f"{hf}.nin_shortcut", *path, "nin_shortcut")

    def attn(hf, *path):
        gn(f"{hf}.norm", *path, "norm")
        for nm in ("q", "k", "v", "proj_out"):
            conv(f"{hf}.{nm}", *path, nm)

    if half == "encoder":
        levels, block_in = ae._levels_down(cfg)
        lvl_key = "down"
    else:
        levels, block_in = ae._levels_up(cfg)
        lvl_key = "up"
    conv(f"{half}.conv_in", "conv_in")
    for i, (blocks, resample_out, _temporal) in enumerate(levels):
        for j, spec in enumerate(blocks):
            resnet(f"{half}.{lvl_key}.{i}.block.{j}", spec,
                   lvl_key, i, "block", j)
        if resample_out is not None:
            name = ("downsample" if half == "encoder" else "upsample")
            conv(f"{half}.{lvl_key}.{i}.{name}.conv",
                 lvl_key, i, name, "conv")
    mid_ch = (block_in if half == "encoder"
              else cfg.block_out_channels[0])
    for nm in ("block_1", "block_2"):
        resnet(f"{half}.mid.{nm}", (mid_ch, mid_ch), f"mid_{nm}")
    attn(f"{half}.mid.attn_1", "mid_attn_1")
    gn(f"{half}.norm_out", "norm_out")
    conv(f"{half}.conv_out", "conv_out")
    return r


def load_dcae(model_dir: str, cfg=None, dtype=jnp.bfloat16,
              encoder: bool = False, decoder: bool = True,
              prefix: str = ""):
    """Load the AutoencoderKLConv3D halves.  Returns
    ({"encoder"?, "decoder"?}, DCAEConfig)."""
    from vllm_omni_tpu.models.flux.loader import load_routed
    from vllm_omni_tpu.models.hunyuan_image_3 import autoencoder as ae

    if cfg is None:
        with open(os.path.join(model_dir, "config.json")) as f:
            cfg = ae.DCAEConfig.from_hf(json.load(f))
    out = {}
    halves = ([("encoder", ae.init_encoder)] if encoder else []) + \
        ([("decoder", ae.init_decoder)] if decoder else [])
    for half, init in halves:
        routing = _dcae_routing(cfg, half)
        if prefix:
            # the published repo nests the autoencoder under one key
            # namespace of the main shards (e.g. "vae.encoder...")
            routing = {prefix + k: v for k, v in routing.items()}
        transforms = {name: _dcae_conv
                      for name, route in routing.items()
                      if route[0] == "conv3d"}
        routing = {k: (("raw",) + v[1:] if v[0] == "conv3d" else v)
                   for k, v in routing.items()}
        shapes = jax.eval_shape(
            lambda init=init: init(jax.random.PRNGKey(0), cfg,
                                   jnp.float32))
        out[half] = load_routed(model_dir, routing, shapes, dtype,
                                transforms=transforms)
    return out, cfg


def checkpoint_has_prefix(model_dir: str, prefix: str) -> bool:
    """True if any tensor name in the shard set starts with ``prefix``
    (key-level scan only; no tensor data is read)."""
    from safetensors import safe_open

    from vllm_omni_tpu.model_loader.safetensors_loader import (
        _shard_files,
    )

    for path in _shard_files(model_dir):
        with safe_open(path, framework="numpy") as f:
            if any(k.startswith(prefix) for k in f.keys()):
                return True
    return False


def load_hunyuan_vision(model_dir: str, hf: dict, dtype=jnp.bfloat16):
    """Load the understanding tower out of the single-repo checkpoint:
    ``vision_model.*`` is a transformers Siglip2 NaViT encoder (linear
    patch embedding over flattened patches; reference
    pipeline_hunyuan_image_3.py:88) and ``vision_aligner.*`` the
    LightProjector MLP (hunyuan_image_3_transformer.py:723-741,
    nn.Sequential [Linear, GELU, Linear, ...] -> even module indices).

    Returns (vit_params, vit_cfg, aligner_params, aligner_depth)."""
    import dataclasses

    from vllm_omni_tpu.models.common import siglip as sl
    from vllm_omni_tpu.models.flux.loader import load_routed
    from vllm_omni_tpu.models.hunyuan_image_3 import projector

    vit_hf = dict(hf.get("vit") or {})
    vit_cfg = sl.SigLIPConfig.from_hf(vit_hf)
    if "num_patches" in vit_hf:
        # Siglip2 sizes its position table by num_patches, not
        # (image_size // patch)^2
        vit_cfg = dataclasses.replace(
            vit_cfg, num_positions=vit_hf["num_patches"])
    vit_params, _ = sl.load_siglip(model_dir, cfg=vit_cfg, dtype=dtype,
                                   prefix="vision_model.")

    al = dict(hf.get("vit_aligner") or {})
    depth = al.get("depth", 2)
    proj_type = al.get("projector_type", "mlp_gelu")
    if proj_type == "linear":
        depth = 1
    elif proj_type != "mlp_gelu":
        raise ValueError(f"unknown vit_aligner type {proj_type!r}")
    input_dim = al.get("input_dim", vit_cfg.hidden_size)
    n_embed = al.get("n_embed", hf.get("hidden_size"))
    shapes = jax.eval_shape(lambda: projector.light_projector_init(
        jax.random.PRNGKey(0), input_dim, n_embed, depth, jnp.float32))
    routing: dict[str, tuple] = {}
    for i in range(depth):
        hf_name = ("vision_aligner.layers" if proj_type == "linear"
                   else f"vision_aligner.layers.{2 * i}")
        routing[f"{hf_name}.weight"] = ("direct", ("layers", i, "w"))
        routing[f"{hf_name}.bias"] = ("direct", ("layers", i, "b"))
    al_params = load_routed(model_dir, routing, shapes, dtype)
    return vit_params, vit_cfg, al_params, depth
