"""HunyuanImage-3: one causal MoE LLM that runs the image flow.

Reference: vllm_omni/diffusion/models/hunyuan_image_3/
pipeline_hunyuan_image_3.py — HunyuanImage3Pipeline (:65, a
PreTrainedModel + GenerationMixin): the prompt is tokenized with
<boi><img_size><ratio> special tokens, a TIMESTEP TOKEN is instantiated
into the sequence (instantiate_timestep_tokens :289), VAE latents are
projected in through a timestep-conditioned UNetDown patch embed
(instantiate_vae_image_tokens :200), the MoE transformer attends the
cached text context with 2D-rope image positions, and the velocity is
read back out through ragged_final_layer (:338, UNetUp conditioned on a
second timestep embedding).  Requested sizes snap to ResolutionGroup
aspect buckets (hunyuan_image_3_transformer.py:468).

TPU-first: the text prefix prefills ONCE under jit into a
loop-invariant KV pytree; the denoise loop is one jitted fori_loop over
[timestep token ; latent tokens] per step (the reference's
ImageKVCacheManager + per-step Python loop collapse into loop-carried
state).  The CFG branch runs a text-free second prefill so no prompt
information leaks into the unconditional velocity.  Latents stay
spatial [B, H/16, W/16, C] through the loop; the UNetDown/UNetUp convs
run NHWC.  Conditioning images (image edit intake) join the context as
UNetDown-embedded clean latents at t=0.
"""

from __future__ import annotations

import functools
import math
from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.diffusion.request import (
    DiffusionOutput,
    InvalidRequestError,
    OmniDiffusionRequest,
)
from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.models.common import intake, nn
from vllm_omni_tpu.models.common import siglip
from vllm_omni_tpu.models.common.siglip import SigLIPConfig
from vllm_omni_tpu.models.hunyuan_image_3 import projector
from vllm_omni_tpu.models.hunyuan_image_3.resolution import ResolutionGroup
from vllm_omni_tpu.models.hunyuan_image_3.transformer import (
    HunyuanImage3Config,
    diagonal_positions,
    gen_image_step,
    image_grid_positions,
    init_params,
    prefill,
    rope_2d_table,
)
from vllm_omni_tpu.models.qwen_image import vae as vae_mod
from vllm_omni_tpu.models.qwen_image.vae import VAEConfig
from vllm_omni_tpu.utils.tokenizer import ByteTokenizer

logger = init_logger(__name__)


@dataclass(frozen=True)
class HunyuanImage3PipelineConfig:
    llm: HunyuanImage3Config = field(
        default_factory=HunyuanImage3Config.real)
    vae: VAEConfig = field(default_factory=lambda: VAEConfig(
        latent_channels=32, channel_multipliers=(1, 2, 4, 4, 4)))
    max_text_len: int = 64
    steps_bucket: int = 32
    # SigLIP-2 understanding tower for conditioning images (reference:
    # pipeline_hunyuan_image_3.py:86-90 vision_model + vision_aligner;
    # joint image = VAE tokens + ViT tokens, JointImageInfo :650).
    # None disables the tower (VAE-only conditioning).
    vit: Optional[SigLIPConfig] = field(default_factory=SigLIPConfig)
    # LightProjector mlp_gelu depth (hunyuan_image_3_transformer.py:731)
    vit_aligner_depth: int = 2

    def __post_init__(self):
        if self.vae.spatial_ratio != self.llm.vae_ratio:
            raise ValueError(
                f"VAE spatial ratio {self.vae.spatial_ratio} != "
                f"llm.vae_ratio {self.llm.vae_ratio}")
        if self.vae.latent_channels != self.llm.latent_channels:
            raise ValueError("latent channel mismatch between VAE and "
                             "patch embed")

    @staticmethod
    def tiny() -> "HunyuanImage3PipelineConfig":
        return HunyuanImage3PipelineConfig(
            llm=HunyuanImage3Config.tiny(), vae=VAEConfig.tiny(),
            max_text_len=16, steps_bucket=8,
            vit=SigLIPConfig.tiny())


class HunyuanImage3Pipeline:
    """Text -> image through a single causal MoE MM transformer."""

    output_type = "image"
    config_cls = HunyuanImage3PipelineConfig
    param_attrs = ("dit_params", "vae_params", "vae_encoder_params",
                   "dcae_decoder_params", "dcae_encoder_params")

    def __init__(self, config: HunyuanImage3PipelineConfig,
                 dtype=jnp.bfloat16, seed: int = 0, mesh=None,
                 cache_config=None, init_weights: bool = True):
        from vllm_omni_tpu.parallel.pipeline_mesh import MeshWiring

        self.cfg = config
        self.dtype = dtype
        self.mesh = mesh
        self.cache_config = cache_config
        self.wiring = MeshWiring(mesh, type(self).__name__).validate(
            {"dp"})
        if cache_config is not None:
            raise ValueError(
                "HunyuanImage-3's LLM denoise has no step cache yet")
        llm = config.llm
        self.tokenizer = ByteTokenizer(llm.vocab_size)
        self.resolutions = ResolutionGroup(
            llm.image_base_size,
            step=max(llm.image_base_size // 16, llm.vae_ratio),
            align=llm.vae_ratio)
        if llm.ratio_token_base + len(self.resolutions) > llm.vocab_size:
            raise ValueError(
                f"ratio_token_base {llm.ratio_token_base} + "
                f"{len(self.resolutions)} aspect buckets exceeds "
                f"vocab_size {llm.vocab_size}")
        logger.info("Initializing HunyuanImage3Pipeline (dtype=%s, "
                    "%d resolution buckets)", dtype, len(self.resolutions))
        keys = jax.random.split(jax.random.PRNGKey(seed), 9)
        ph = llm.patch_embed_hidden_dim
        towers = {}
        if config.vit is not None and init_weights:
            # SigLIP-2 understanding tower + LightProjector aligner
            # (vision_model / vision_aligner) — conditioning images
            # contribute semantic ViT tokens beside their VAE tokens
            towers["vit"] = siglip.init_params(keys[7], config.vit, dtype)
            towers["vit_aligner"] = projector.light_projector_init(
                keys[8], config.vit.hidden_size, llm.hidden_size,
                config.vit_aligner_depth, dtype)
        self._ckpt_weights = not init_weights
        if not init_weights:
            # from_pretrained overwrites every tree — materializing a
            # checkpoint-sized random MoE first would double peak memory
            self.dit_params = None
            self.vae_params = None
        else:
            self.dit_params = self.wiring.place({
                **towers,
                "llm": init_params(keys[0], llm, dtype),
                # three timestep embedders (reference: time_embed for
                # the patch embed, timestep_emb for the in-sequence
                # token, time_embed_2 for the final layer)
                "time_embed": projector.timestep_embedder_init(
                    keys[1], llm.hidden_size, ph, dtype),
                "timestep_emb": projector.timestep_embedder_init(
                    keys[2], llm.hidden_size, llm.hidden_size, dtype),
                "time_embed_2": projector.timestep_embedder_init(
                    keys[3], llm.hidden_size, ph, dtype),
                "patch_embed": projector.unet_down_init(
                    keys[4], llm.latent_channels, ph, ph,
                    llm.hidden_size, dtype),
                "final_layer": projector.unet_up_init(
                    keys[5], llm.hidden_size, ph, ph,
                    llm.latent_channels, dtype),
            })
            self.vae_params = self.wiring.place(
                vae_mod.init_decoder(keys[6], config.vae, dtype))
        self._seed = seed
        self._denoise_cache: dict = {}
        self._prefill_jit = jax.jit(
            lambda p, ids, mask, cos, sin: prefill(
                p, self.cfg.llm, ids, mask, cos, sin))
        self._prefill_img_jit = jax.jit(
            lambda p, ids, mask, cos, sin, img: prefill(
                p, self.cfg.llm, ids, mask, cos, sin, img_tokens=img))
        self.vae_encoder_params = None  # built on demand (image intake)
        self._vae_decode_jit = jax.jit(
            lambda pp, l: vae_mod.decode(pp, self.cfg.vae, l))
        # real-weight DCAE autoencoder (from_pretrained); None => the
        # random-init stand-in VAE.  A separate attr so engine.sleep()
        # offloads it with the other trees.
        self.dcae_decoder_params = None
        self.dcae_encoder_params = None
        self.dcae_cfg = None
        self.hf_tokenizer = None

    @functools.cached_property
    def _dcae_decode_jit(self):
        from vllm_omni_tpu.models.hunyuan_image_3 import (
            autoencoder as dcae_mod,
        )

        dcfg = self.dcae_cfg
        return jax.jit(lambda pp, z: dcae_mod.decode(pp, dcfg, z))

    @classmethod
    def from_pretrained(cls, model_dir: str, dtype=jnp.bfloat16,
                        seed: int = 0, mesh=None, cache_config=None,
                        max_text_len: int = 512):
        """Build from the published single-repo checkpoint: the causal
        MoE LM + UNet projector heads + DCAE autoencoder all live in one
        shard set (the vae under the ``vae.`` key namespace, its config
        under config.json["vae"]).  The SigLIP-2 understanding tower
        and aligner load when ``vision_model.*`` weights are present
        (image conditioning runs VAE tokens through the DCAE encoder
        and semantic tokens through the tower); otherwise
        text-to-image runs without them."""
        import dataclasses
        import json as _json
        import os

        from vllm_omni_tpu.models.hunyuan_image_3 import (
            autoencoder as dcae_mod,
        )
        from vllm_omni_tpu.models.hunyuan_image_3 import loader as hload

        with open(os.path.join(model_dir, "config.json")) as f:
            hf = _json.load(f)
        llm_cfg = hload.config_from_hf(model_dir)
        dcae_cfg = dcae_mod.DCAEConfig.from_hf(hf.get("vae", {}))
        llm_cfg = dataclasses.replace(
            llm_cfg,
            latent_channels=dcae_cfg.latent_channels,
            vae_ratio=dcae_cfg.ffactor_spatial,
            patch_embed_hidden_dim=hf.get("patch_embed_hidden_dim",
                                          1024),
            image_base_size=hf.get("img_size", 1024),
        )
        gen_cfg_path = os.path.join(model_dir, "generation_config.json")
        shift = 3.0
        if os.path.isfile(gen_cfg_path):
            with open(gen_cfg_path) as f:
                shift = _json.load(f).get("flow_shift", 3.0)
        llm_cfg = dataclasses.replace(llm_cfg, timestep_shift=shift)
        hf_tok = None
        try:
            from transformers import AutoTokenizer

            hf_tok = AutoTokenizer.from_pretrained(model_dir)
        except Exception as e:
            logger.warning("no usable tokenizer under %s (%s); byte "
                           "fallback", model_dir, e)
        if hf_tok is not None:
            if hf_tok.pad_token is None:
                hf_tok.pad_token = hf_tok.eos_token
            # the resolution special tokens (<img_size_1024>,
            # <img_ratio_0>; reference hunyuan_image_3_tokenizer.py:59)
            # are tokenizer-assigned — resolve ids from it rather than
            # trusting config.json to carry them
            size_tok = f"<img_size_{llm_cfg.image_base_size}>"
            sid = hf_tok.convert_tokens_to_ids(size_tok)
            rid = hf_tok.convert_tokens_to_ids("<img_ratio_0>")
            unk = hf_tok.unk_token_id
            overrides = {}
            if sid is not None and sid != unk and sid >= 0:
                overrides["size_token_id"] = sid
            if rid is not None and rid != unk and rid >= 0:
                overrides["ratio_token_base"] = rid
            if overrides:
                llm_cfg = dataclasses.replace(llm_cfg, **overrides)
        # SigLIP-2 understanding tower: load when the checkpoint
        # carries vision_model.* weights; otherwise image-conditioned
        # requests fail loudly (never random-init beside real weights)
        vit_cfg = None
        vit_trees = None
        al_depth = 2
        if hload.checkpoint_has_prefix(model_dir, "vision_model."):
            vit_p, vit_cfg, al_p, al_depth = hload.load_hunyuan_vision(
                model_dir, hf, dtype=dtype)
            vit_trees = {"vit": vit_p, "vit_aligner": al_p}
        import math as _math

        # stand-in VAEConfig consistent with the llm geometry (its
        # random weights are never built on this path — the DCAE is the
        # real decoder); spatial_ratio = 2^(len(multipliers)-1)
        stand_in_vae = VAEConfig(
            latent_channels=llm_cfg.latent_channels,
            channel_multipliers=(1,) * (
                int(_math.log2(llm_cfg.vae_ratio)) + 1),
            base_channels=16, layers_per_block=1,
            scaling_factor=1.0, shift_factor=0.0)
        config = dataclasses.replace(
            cls.config_cls.tiny(), llm=llm_cfg, vit=vit_cfg,
            vit_aligner_depth=al_depth,
            vae=stand_in_vae, max_text_len=max_text_len)
        pipe = cls(config, dtype=dtype, seed=seed, mesh=mesh,
                   cache_config=cache_config, init_weights=False)

        lm_params, _ = hload.load_hunyuan_lm(model_dir, cfg=llm_cfg,
                                             dtype=dtype)
        ph = llm_cfg.patch_embed_hidden_dim
        keys = jax.random.split(jax.random.PRNGKey(seed), 5)
        head_shapes = jax.eval_shape(lambda: {
            "time_embed": projector.timestep_embedder_init(
                keys[0], llm_cfg.hidden_size, ph, jnp.float32),
            "timestep_emb": projector.timestep_embedder_init(
                keys[1], llm_cfg.hidden_size, llm_cfg.hidden_size,
                jnp.float32),
            "time_embed_2": projector.timestep_embedder_init(
                keys[2], llm_cfg.hidden_size, ph, jnp.float32),
            "patch_embed": projector.unet_down_init(
                keys[3], llm_cfg.latent_channels, ph, ph,
                llm_cfg.hidden_size, jnp.float32),
            "final_layer": projector.unet_up_init(
                keys[4], llm_cfg.hidden_size, ph, ph,
                llm_cfg.latent_channels, jnp.float32),
        })
        heads = hload.load_hunyuan_heads(model_dir, head_shapes,
                                         dtype=dtype)
        pipe.dit_params = pipe.wiring.place(
            {"llm": lm_params, **heads, **(vit_trees or {})})
        trees, _ = hload.load_dcae(model_dir, cfg=dcae_cfg, dtype=dtype,
                                   encoder=True, decoder=True,
                                   prefix="vae.")
        pipe.dcae_decoder_params = pipe.wiring.place(trees["decoder"])
        pipe.dcae_encoder_params = pipe.wiring.place(trees["encoder"])
        pipe.dcae_cfg = dcae_cfg
        pipe.hf_tokenizer = hf_tok
        return pipe

    @property
    def geometry_multiple(self) -> int:
        return self.cfg.llm.vae_ratio

    # ----------------------------------------------------------- context

    def _context(self, prompts: list[str], ratio_idx: int):
        """Token ids [B, S_ctx] + mask: [text pad][<boi><size><ratio>].
        The three special tokens carry the target resolution into the
        sequence (prepare_model_inputs builds
        `<boi><img_size_1024><ratio_i>` before the image slots)."""
        cfg = self.cfg
        llm = cfg.llm
        if getattr(self, "hf_tokenizer", None) is not None:
            self.hf_tokenizer.padding_side = "right"
            enc = self.hf_tokenizer(
                list(prompts), padding="max_length", truncation=True,
                max_length=cfg.max_text_len)
            ids = np.asarray(enc["input_ids"], np.int32)
            lens = np.asarray(enc["attention_mask"],
                              np.int32).sum(axis=1)
        else:
            ids, lens = self.tokenizer.batch_encode(prompts,
                                                    cfg.max_text_len)
        b = len(prompts)
        specials = np.array(
            [llm.boi_token_id, llm.size_token_id,
             llm.ratio_token_base + ratio_idx],
            np.int32)
        ids = np.concatenate(
            [ids, np.broadcast_to(specials, (b, 3))], axis=1)
        mask = np.concatenate(
            [(np.arange(cfg.max_text_len)[None, :]
              < lens[:, None]).astype(np.int32),
             np.ones((b, 3), np.int32)], axis=1)
        return jnp.asarray(ids), jnp.asarray(mask)

    # ---------------------------------------------------------- gen_text

    def _bot_prefix_ids(self, bot_task: str) -> list[int]:
        """Token ids of the bot-response prefix for a task (reference
        hunyuan_image_3_tokenizer.py:1036-1043, pretrain template:
        think -> "<think>", recaption -> "<recaption>", img_ratio ->
        "<boi><img_size_N>")."""
        llm = self.cfg.llm
        if bot_task == "img_ratio":
            return [llm.boi_token_id, llm.size_token_id]
        lit = {"think": "<think>", "recaption": "<recaption>"}[bot_task]
        tok = getattr(self, "hf_tokenizer", None)
        if tok is not None:
            tid = tok.convert_tokens_to_ids(lit)
            if tid is not None and tid >= 0 and tid != tok.unk_token_id:
                return [tid]
            return list(tok(lit, add_special_tokens=False)["input_ids"])
        return self.tokenizer.encode(lit, add_bos=False)

    def _gen_text_stop_ids(self, bot_task: str) -> list[int]:
        """Stop set per task (reference pipeline_hunyuan_image_3.py:
        616-622): think/recaption stop at </recaption>, </answer> or
        eos; img_ratio emits exactly one token so needs none."""
        tok = getattr(self, "hf_tokenizer", None)
        if tok is None:
            return [self.tokenizer.eos_token_id]
        stops = []
        for t in ("</recaption>", "</answer>"):
            tid = tok.convert_tokens_to_ids(t)
            if tid is not None and tid >= 0 and tid != tok.unk_token_id:
                stops.append(tid)
        if tok.eos_token_id is not None:
            stops.append(tok.eos_token_id)
        return stops

    def gen_text(self, prompts: list[str], bot_task: str = "think",
                 max_new_tokens: int = 128, temperature: float = 0.0,
                 seed: int = 0):
        """The reference's ``gen_text`` mode over the same MoE trunk
        (pipeline_hunyuan_image_3.py:545 bot_task): AR text rollout
        after [prompt ; task prefix].

        Returns per-prompt strings for think/recaption; for img_ratio a
        dict ``{"ratio_index", "height", "width"}`` resolved through the
        ResolutionGroup aspect buckets (the reference stops on the
        generated ``<img_ratio_i>`` token, :602 max_new_tokens=1)."""
        from vllm_omni_tpu.models.hunyuan_image_3.transformer import (
            make_gen_text,
        )

        if bot_task not in ("think", "recaption", "img_ratio"):
            raise InvalidRequestError(
                f"bot_task must be think|recaption|img_ratio, got "
                f"{bot_task!r}")
        cfg = self.cfg
        llm = cfg.llm
        if bot_task == "img_ratio":
            max_new_tokens = 1  # one <img_ratio_i> token (reference :602)
        prefix = self._bot_prefix_ids(bot_task)
        tok = getattr(self, "hf_tokenizer", None)
        rows, lens = [], []
        for p in prompts:
            if tok is not None:
                ids = tok(p, truncation=True,
                          max_length=cfg.max_text_len)["input_ids"]
            else:
                ids = self.tokenizer.encode(p)[:cfg.max_text_len]
            rows.append(list(ids) + prefix)
            lens.append(len(rows[-1]))
        bucket = cfg.max_text_len + len(prefix)
        b = len(rows)
        ids_np = np.zeros((b, bucket), np.int32)
        for i, r in enumerate(rows):
            ids_np[i, :len(r)] = r

        # bucket the generation length: user-supplied max_new_tokens
        # would otherwise mint one minutes-long MoE-trunk compile per
        # distinct value (the GLM prior buckets for the same reason);
        # extra tokens are generated and sliced off
        n_gen = (1 if max_new_tokens == 1
                 else max(32, -(-max_new_tokens // 32) * 32))
        key = ("gen_text", bucket, n_gen)
        if not hasattr(self, "_gen_text_cache"):
            self._gen_text_cache = {}
        if key not in self._gen_text_cache:
            self._gen_text_cache[key] = make_gen_text(llm, bucket, n_gen)
        cos, sin = rope_2d_table(
            diagonal_positions(0, bucket + n_gen),
            llm.head_dim, llm.rope_theta)
        out = np.asarray(self._gen_text_cache[key](
            self.dit_params["llm"], jnp.asarray(ids_np),
            jnp.asarray(np.asarray(lens, np.int32)),
            jnp.asarray(cos), jnp.asarray(sin),
            jnp.float32(temperature),
            jax.random.PRNGKey(seed)))[:, :max_new_tokens]

        if bot_task == "img_ratio":
            results = []
            for i in range(b):
                idx = int(out[i, 0]) - llm.ratio_token_base
                if not 0 <= idx < len(self.resolutions):
                    # random-init/tiny trunks emit arbitrary ids: snap
                    # into the bucket table rather than crash (disclosed
                    # — a trained checkpoint emits in-range ratio ids)
                    idx = idx % len(self.resolutions)
                h, w = self.resolutions.data[idx]
                results.append(
                    {"ratio_index": idx, "height": h, "width": w})
            return results
        stops = set(self._gen_text_stop_ids(bot_task))
        texts = []
        for i in range(b):
            toks = []
            for t in out[i].tolist():
                if t in stops:
                    break
                toks.append(t)
            texts.append(tok.decode(toks, skip_special_tokens=True)
                         if tok is not None
                         else self.tokenizer.decode(toks))
        return texts

    # ----------------------------------------------------------- denoise

    def _denoise_fn(self, grid_h: int, grid_w: int, s_ctx: int,
                    s_img: int, sched_len: int, use_cfg: bool = True,
                    vit_grid: tuple[int, int] = (0, 0)):
        key = (grid_h, grid_w, s_ctx, s_img, sched_len, use_cfg,
               vit_grid)
        if key in self._denoise_cache:
            return self._denoise_cache[key]
        cfg = self.cfg
        llm = cfg.llm

        # static rope tables: [text/specials diagonal ; cond-image VAE
        # grid ; cond-image ViT grid], then the per-step [timestep ;
        # latent grid] section after it (reference JointImageInfo: the
        # joint image carries one 2D grid per sub-image)
        s_vit = vit_grid[0] * vit_grid[1]
        ctx_pos = diagonal_positions(0, s_ctx)
        if s_img:
            # conditioning image (resized to the same bucket) occupies a
            # centered 2D grid right after the specials
            ctx_pos = np.concatenate(
                [ctx_pos, image_grid_positions(s_ctx, grid_h, grid_w)])
        if s_vit:
            ctx_pos = np.concatenate(
                [ctx_pos, image_grid_positions(s_ctx + s_img,
                                               *vit_grid)])
        off = s_ctx + s_img + s_vit
        step_pos = np.concatenate(
            [diagonal_positions(off, 1),
             image_grid_positions(off + 1, grid_h, grid_w)])
        ctx_cos, ctx_sin = rope_2d_table(ctx_pos, llm.head_dim,
                                         llm.rope_theta)
        step_cos, step_sin = rope_2d_table(step_pos, llm.head_dim,
                                           llm.rope_theta)

        def velocity(params, x, t, ctx_kvs, ctx_mask):
            """x [B, gh, gw, C] spatial latents + flow time t [B] ->
            velocity, same shape."""
            tk = t * 1000.0
            t_patch = projector.timestep_embed(params["time_embed"], tk,
                                               x.dtype)
            lat_tokens, _, _ = projector.unet_down(
                params["patch_embed"], x, t_patch)
            t_tok = projector.timestep_embed(params["timestep_emb"], tk,
                                             x.dtype)
            seq = jnp.concatenate([t_tok[:, None, :], lat_tokens],
                                  axis=1)
            hid = gen_image_step(params["llm"], llm, seq, ctx_kvs,
                                 ctx_mask, jnp.asarray(step_cos),
                                 jnp.asarray(step_sin))
            t_fin = projector.timestep_embed(params["time_embed_2"], tk,
                                             x.dtype)
            # drop the timestep token (ragged_final_layer x[:, 1:, :])
            return projector.unet_up(params["final_layer"], hid[:, 1:],
                                     t_fin, grid_h, grid_w)

        @jax.jit
        def run(params, noise, ctx_kvs, ctx_mask, uncond_kvs, un_mask,
                timesteps, dts, gscale, num_steps):
            def body(i, x):
                t = jnp.broadcast_to(timesteps[i], (x.shape[0],))
                v = velocity(params, x, t, ctx_kvs, ctx_mask)
                if use_cfg:
                    v_u = velocity(params, x, t, uncond_kvs, un_mask)
                    v = v_u + gscale * (v - v_u)
                return x - v * dts[i].astype(x.dtype)

            return jax.lax.fori_loop(0, num_steps, body, noise)

        self._denoise_cache[key] = (run, ctx_cos, ctx_sin)
        return self._denoise_cache[key]

    # ------------------------------------------------------- image intake

    @staticmethod
    def _cond_image(req):
        """The request's conditioning image, from either intake key —
        ONE lookup shared by the VAE and ViT context paths (their
        outputs are concatenated, so they must agree on presence)."""
        sp = req.sampling_params
        return sp.image if sp.image is not None else sp.extra.get(
            "image")

    def _image_context(self, req, batch: int, th: int, tw: int):
        """sampling_params.image -> conditioning tokens [B, S_img,
        hidden] embedded through the UNetDown patch embed at t=0 (the
        clean-image end of the flow; _encode_cond_image), or None."""
        image = self._cond_image(req)
        if image is None:
            return None
        img = intake.prepare_cond_image(image, th, tw)
        if getattr(self, "dcae_encoder_params", None) is not None:
            if not hasattr(self, "_img_ctx_dcae_jit"):
                self._img_ctx_dcae_jit = jax.jit(
                    self._embed_image_context_dcae)
            heads = {k: self.dit_params[k]
                     for k in ("time_embed", "patch_embed")}
            tokens = self._img_ctx_dcae_jit(self.dcae_encoder_params,
                                            heads,
                                            jnp.asarray(img,
                                                        jnp.float32))
            return jnp.repeat(tokens, batch, axis=0)
        if self.vae_encoder_params is None:
            if getattr(self, "_ckpt_weights", False):
                raise RuntimeError(
                    "image conditioning unavailable: the checkpoint "
                    "carries no DCAE encoder weights; a random-init "
                    "encoder would silently corrupt the context")
            self.vae_encoder_params = self.wiring.place(
                vae_mod.init_encoder(
                    jax.random.PRNGKey(self._seed + 1), self.cfg.vae,
                    jnp.float32))
        if not hasattr(self, "_img_ctx_jit"):
            self._img_ctx_jit = jax.jit(self._embed_image_context)
        tokens = self._img_ctx_jit(self.vae_encoder_params,
                                   {k: self.dit_params[k]
                                    for k in ("time_embed",
                                              "patch_embed")},
                                   jnp.asarray(img, jnp.float32))
        return jnp.repeat(tokens, batch, axis=0)

    def _embed_image_context_dcae(self, enc_params, params, img):
        """Real-checkpoint conditioning: DCAE encode -> distribution
        mode -> (x - shift) * scale (reference
        pipeline_hunyuan_image_3.py:377-381) -> UNetDown patch embed at
        t=0."""
        from vllm_omni_tpu.models.hunyuan_image_3 import (
            autoencoder as dcae_mod,
        )

        dcfg = self.dcae_cfg
        moments = dcae_mod.encode(enc_params, dcfg, img[None, None])
        lat = moments[:, 0, :, :, :dcfg.latent_channels]
        if dcfg.shift_factor:
            lat = lat - dcfg.shift_factor
        if dcfg.scaling_factor:
            lat = lat * dcfg.scaling_factor
        lat = lat.astype(self.dtype)
        t0 = projector.timestep_embed(params["time_embed"],
                                      jnp.zeros((1,)), lat.dtype)
        tokens, _, _ = projector.unet_down(params["patch_embed"], lat,
                                           t0)
        return tokens

    def _embed_image_context(self, enc_params, params, img):
        lat = vae_mod.encode(enc_params, self.cfg.vae, img[None])
        lat = lat.astype(self.dtype)
        t0 = projector.timestep_embed(params["time_embed"],
                                      jnp.zeros((1,)), lat.dtype)
        tokens, _, _ = projector.unet_down(params["patch_embed"], lat,
                                           t0)
        return tokens

    def _vit_context(self, req, batch: int):
        """Conditioning image -> semantic ViT tokens [B, gh*gw, hidden]
        through the SigLIP tower + aligner (reference:
        instantiate_vit_image_tokens, pipeline_hunyuan_image_3.py:306),
        plus the token grid for the rope section.  (None, (0, 0)) when
        the request has no image or the tower is disabled."""
        vit_cfg = self.cfg.vit
        image = self._cond_image(req)
        if image is None or vit_cfg is None:
            return None, (0, 0)
        side_p = int(math.isqrt(vit_cfg.num_positions))
        side = side_p * vit_cfg.patch_size
        img = intake.prepare_cond_image(image, side, side)
        patches = siglip.patchify(img.transpose(2, 0, 1),
                                  vit_cfg.patch_size)
        pos = siglip.flattened_position_ids_extrapolate(
            side, side, vit_cfg.patch_size, side_p)
        if not hasattr(self, "_vit_jit"):
            n = side_p * side_p

            def run(p_vit, p_al, toks, pids):
                feats = siglip.forward_packed(p_vit, vit_cfg, toks, pids,
                                              [n])
                return projector.light_projector(p_al, feats)

            self._vit_jit = jax.jit(run)
        tokens = self._vit_jit(self.dit_params["vit"],
                               self.dit_params["vit_aligner"],
                               jnp.asarray(patches, self.dtype),
                               jnp.asarray(pos))
        return (jnp.repeat(tokens[None], batch, axis=0),
                (side_p, side_p))

    # ----------------------------------------------------------- forward

    def forward(self, req: OmniDiffusionRequest) -> list[DiffusionOutput]:
        sp = req.sampling_params
        cfg = self.cfg
        llm = cfg.llm
        extra = sp.extra if getattr(sp, "extra", None) else {}
        bot_task = extra.get("bot_task")
        if bot_task:
            # gen_text mode: think / recaption / img_ratio produce TEXT
            # (or a ratio choice), not an image (reference bot_task,
            # pipeline_hunyuan_image_3.py:545)
            outs = self.gen_text(
                list(req.prompt), bot_task=bot_task,
                max_new_tokens=int(extra.get("max_new_tokens", 128)),
                temperature=float(extra.get("temperature", 0.0)),
                seed=sp.seed if sp.seed is not None else 0)
            return [
                DiffusionOutput(request_id=req.request_ids[i],
                                prompt=req.prompt[i], data=outs[i],
                                output_type="text")
                for i in range(len(req.prompt))
            ]
        base = llm.image_base_size
        height = sp.height or base
        width = sp.width or base
        if height <= 0 or width <= 0:
            raise InvalidRequestError("height/width must be positive")
        # snap to the nearest aspect bucket (get_target_size)
        tw, th = self.resolutions.get_target_size(width, height)
        ratio_idx = self.resolutions.ratio_index(width, height)
        grid_h = th // llm.vae_ratio
        grid_w = tw // llm.vae_ratio
        prompts = req.prompt
        b = len(prompts)

        ids, mask = self._context(prompts, ratio_idx)
        s_ctx = int(ids.shape[1])

        steps = max(1, sp.num_inference_steps)
        sched_len = max(steps, cfg.steps_bucket)
        # intake the conditioning image first: its token count shapes
        # the rope tables (grid positions come from the denoise-cache
        # entry)
        cond_tokens = self._image_context(req, b, th, tw)
        s_img = 0 if cond_tokens is None else int(cond_tokens.shape[1])
        # joint image: the semantic ViT tokens ride beside the VAE
        # tokens in the conditioning section, each on its own rope grid
        vit_tokens, vit_grid = self._vit_context(req, b)
        if vit_tokens is not None:
            # both context methods gate on the same image lookup, so the
            # VAE tokens are always present here
            cond_tokens = jnp.concatenate([cond_tokens, vit_tokens],
                                          axis=1)
        use_cfg = sp.guidance_scale > 1.0
        run, ctx_cos, ctx_sin = self._denoise_fn(grid_h, grid_w, s_ctx,
                                                 s_img, sched_len,
                                                 use_cfg,
                                                 vit_grid=vit_grid)
        blank = jnp.asarray(np.concatenate(
            [np.zeros((b, cfg.max_text_len), np.int32),
             np.ones((b, 3), np.int32)], axis=1))
        if cond_tokens is not None:
            ctx_kvs, mask = self._prefill_img_jit(
                self.dit_params["llm"], ids, mask, jnp.asarray(ctx_cos),
                jnp.asarray(ctx_sin), cond_tokens)
            # text-free second prefill for the CFG branch: the cond
            # image's KVs must not have attended the prompt (cfg_text
            # semantics) or the prompt leaks into the "unconditional"
            # velocity through the image keys
            uncond_kvs, un_mask = (self._prefill_img_jit(
                self.dit_params["llm"], ids, blank, jnp.asarray(ctx_cos),
                jnp.asarray(ctx_sin), cond_tokens)
                if use_cfg else (ctx_kvs, mask))
        else:
            ctx_kvs, mask = self._prefill_jit(
                self.dit_params["llm"], ids, mask, jnp.asarray(ctx_cos),
                jnp.asarray(ctx_sin))
            uncond_kvs, un_mask = (self._prefill_jit(
                self.dit_params["llm"], ids, blank, jnp.asarray(ctx_cos),
                jnp.asarray(ctx_sin)) if use_cfg else (ctx_kvs, mask))

        # shifted flow-match schedule (shared scheduler module — the
        # reference drives a FlowMatch scheduler via retrieve_timesteps)
        from vllm_omni_tpu.diffusion.scheduler import make_schedule

        sched = make_schedule(steps, shift=llm.timestep_shift)
        sig = np.asarray(sched.sigmas, np.float32)
        t_pad = np.zeros(sched_len, np.float32)
        t_pad[:steps] = sig[:steps]
        d_pad = np.zeros(sched_len, np.float32)
        d_pad[:steps] = sig[:steps] - sig[1:steps + 1]

        seed = (sp.seed if sp.seed is not None
                else int(np.random.randint(0, 2 ** 31 - 1)))
        noise = jax.random.normal(
            jax.random.PRNGKey(seed),
            (b, grid_h, grid_w, llm.latent_channels), jnp.float32,
        ).astype(self.dtype)

        latents = run(self.dit_params, noise, ctx_kvs, mask,
                      uncond_kvs, un_mask, jnp.asarray(t_pad),
                      jnp.asarray(d_pad), jnp.float32(sp.guidance_scale),
                      jnp.int32(steps))

        if getattr(self, "dcae_decoder_params", None) is not None:
            # real DCAE decode: invert (x - shift) * scale, run the 3D
            # autoencoder on the single frame
            dcfg = self.dcae_cfg
            z = latents.astype(jnp.float32)
            if dcfg.scaling_factor:
                z = z / dcfg.scaling_factor
            if dcfg.shift_factor:
                z = z + dcfg.shift_factor
            img = self._dcae_decode_jit(self.dcae_decoder_params,
                                        z[:, None])[:, 0]
        else:
            img = self._vae_decode_jit(self.vae_params,
                                       latents.astype(jnp.float32))
        img = np.asarray(jnp.clip(
            (img.astype(jnp.float32) + 1.0) * 127.5, 0, 255)
            .astype(jnp.uint8))
        return [
            DiffusionOutput(request_id=req.request_ids[i],
                            prompt=prompts[i], data=img[i],
                            output_type="image")
            for i in range(b)
        ]
