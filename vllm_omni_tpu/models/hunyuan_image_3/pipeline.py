"""HunyuanImage-3: causal multimodal LLM that runs the image flow.

Reference: vllm_omni/diffusion/models/hunyuan_image_3/ —
``HunyuanImage3Pipeline`` (pipeline_hunyuan_image_3.py:65, a
PreTrainedModel + GenerationMixin): ONE causal (MoE) LLM serves both the
text context and flow-matching image generation, with TIMESTEP TOKENS
instantiated into the sequence (instantiate_timestep_tokens, :289), 2D
rotary embeddings for image positions, and an image KV-cache manager
(hunyuan_image_3_transformer.py:839) giving the denoise loop a static
prefilled context — the same unified-AR-diffusion execution shape as
Bagel, WITHOUT Bagel's dual expert weights.

Composition: reuses the Bagel machinery (prefill + context-attending
flow step) with a SINGLE transformer stack (the per-layer und/gen slots
alias one expert dict — weight sharing, not duplication) and a timestep
token prepended to the latent stream instead of Bagel's per-token
timestep addition.  Reduced scope (documented): the ffn is dense here —
the reference's fused-MoE ffn drops in through ops/moe at real-weight
time; resolution-group bucketing and image editing follow.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp

from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.models.bagel.pipeline import (
    BagelConfig,
    BagelPipeline,
    BagelPipelineConfig,
    _expert_init,
)
from vllm_omni_tpu.models.common import nn
from vllm_omni_tpu.models.qwen_image.vae import VAEConfig

logger = init_logger(__name__)


@dataclass(frozen=True)
class HunyuanImage3PipelineConfig(BagelPipelineConfig):
    @staticmethod
    def tiny() -> "HunyuanImage3PipelineConfig":
        return HunyuanImage3PipelineConfig(
            llm=BagelConfig.tiny(), vae=VAEConfig.tiny(),
            max_text_len=16, steps_bucket=8)


def init_params(key, pcfg: HunyuanImage3PipelineConfig,
                dtype=jnp.float32):
    """Single-stack variant of the Bagel tree: each layer's und/gen
    slots reference ONE expert dict (the reference has one transformer
    serving both roles)."""
    cfg = pcfg.llm
    keys = jax.random.split(key, cfg.num_layers + 8)
    ki = iter(keys)
    shared_layers = [{"shared": _expert_init(next(ki), cfg, dtype)}
                     for _ in range(cfg.num_layers)]
    return {
        "embed": nn.embedding_init(next(ki), cfg.vocab_size,
                                   cfg.hidden_size, dtype),
        "layers": shared_layers,
        "final_norm": nn.rmsnorm_init(cfg.hidden_size, dtype),
        "time_in1": nn.linear_init(next(ki), 256, cfg.hidden_size,
                                   dtype=dtype),
        "time_in2": nn.linear_init(next(ki), cfg.hidden_size,
                                   cfg.hidden_size, dtype=dtype),
        "vae2llm": nn.linear_init(next(ki), cfg.latent_dim,
                                  cfg.hidden_size, dtype=dtype),
        "llm2vae": nn.linear_init(next(ki), cfg.hidden_size,
                                  cfg.latent_dim, dtype=dtype),
        "pos_embed": jax.random.normal(
            next(ki), (cfg.max_latent_size * cfg.max_latent_size,
                       cfg.hidden_size), dtype) * 0.02,
    }


class HunyuanImage3Pipeline(BagelPipeline):
    """Text -> image through one shared-stack causal MM transformer."""

    config_cls = HunyuanImage3PipelineConfig

    # engine.sleep() stashes llm_shared (the alias-free tree); the
    # derived dit_params would otherwise stash every shared dict TWICE
    # and wake() would materialize two device copies, silently doubling
    # weight memory
    param_attrs = ("llm_shared", "vae_params", "vae_encoder_params")

    def _build_llm_params(self, key, config, dtype):
        # shared single stack instead of Bagel's dual experts; aliasing
        # happens AFTER device placement (a pytree containing the same
        # dict twice would be placed as two separate copies)
        self.llm_shared = self.wiring.place(
            init_params(key, config, dtype))
        return self._alias_shared()

    def _alias_shared(self):
        tree = dict(self.llm_shared)
        tree["layers"] = [{"und": l["shared"], "gen": l["shared"]}
                          for l in self.llm_shared["layers"]]
        return tree

    def post_sleep(self):
        self.dit_params = None  # derived aliases must not pin buffers

    def post_wake(self):
        self.dit_params = self._alias_shared()
