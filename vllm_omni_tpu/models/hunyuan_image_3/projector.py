"""Timestep-conditioned UNet latent projectors (img_proj_type="unet").

Reference: hunyuan_image_3_transformer.py — ResBlock (:2571, adaptive
group norm: emb -> scale/shift on the out-norm), UNetDown patch embed
(:2666: conv3x3 -> ResBlock, flatten to tokens), UNetUp final layer
(:2717: ResBlock -> GN+SiLU+conv3x3 back to latent channels),
TimestepEmbedder (:2535, 256-dim sinusoid -> MLP).

Convs run in NHWC (TPU-native layout for lax.conv); patch_size=1 is the
published checkpoint's configuration so no up/down resampling paths are
carried.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from vllm_omni_tpu.models.common import nn


def timestep_embedder_init(key, hidden: int, out: int, dtype):
    k1, k2 = jax.random.split(key)
    return {"fc1": nn.linear_init(k1, 256, hidden, dtype=dtype),
            "fc2": nn.linear_init(k2, hidden, out, dtype=dtype)}


def timestep_embed(p, t, dtype):
    """t [B] (0..1 flow time scaled to 0..1000 by the caller) -> [B, out].
    GELU between the two layers (TimestepEmbedder act_layer=nn.GELU)."""
    h = nn.timestep_embedding(t, 256).astype(dtype)
    return nn.linear(p["fc2"], jax.nn.gelu(nn.linear(p["fc1"], h)))


def resblock_init(key, cin: int, cemb: int, cout: int, dtype):
    k = jax.random.split(key, 4)
    p = {
        "in_norm": nn.groupnorm_init(cin, dtype),
        "in_conv": nn.conv2d_init(k[0], cin, cout, 3, dtype=dtype),
        "emb": nn.linear_init(k[1], cemb, 2 * cout, dtype=dtype),
        "out_norm": nn.groupnorm_init(cout, dtype),
        "out_conv": nn.conv2d_init(k[2], cout, cout, 3, dtype=dtype),
    }
    # zero_module on the out conv: identity residual at init (:2631)
    p["out_conv"]["w"] = jnp.zeros_like(p["out_conv"]["w"])
    p["out_conv"]["b"] = jnp.zeros_like(p["out_conv"]["b"])
    if cin != cout:
        p["skip"] = nn.conv2d_init(k[3], cin, cout, 1, dtype=dtype)
    return p


def resblock(p, x, emb, groups: int = 32):
    """x [B, H, W, C], emb [B, cemb] — adaptive-GN residual block."""
    h = nn.conv2d(p["in_conv"], jax.nn.silu(
        nn.groupnorm(p["in_norm"], x, groups)))
    scale, shift = jnp.split(
        nn.linear(p["emb"], jax.nn.silu(emb)), 2, axis=-1)
    h = nn.groupnorm(p["out_norm"], h, groups) \
        * (1.0 + scale[:, None, None, :]) + shift[:, None, None, :]
    h = nn.conv2d(p["out_conv"], jax.nn.silu(h))
    skip = nn.conv2d(p["skip"], x) if "skip" in p else x
    return skip + h


def unet_down_init(key, cin: int, cemb: int, chidden: int, cout: int,
                   dtype):
    k1, k2 = jax.random.split(key)
    return {"conv_in": nn.conv2d_init(k1, cin, chidden, 3, dtype=dtype),
            "res": resblock_init(k2, chidden, cemb, cout, dtype)}


def unet_down(p, lat, t_emb):
    """VAE latents [B, H, W, C] + t_emb [B, cemb] -> tokens
    [B, H*W, cout] (patch_size=1: no spatial reduction)."""
    h = nn.conv2d(p["conv_in"], lat)
    h = resblock(p["res"], h, t_emb)
    b, gh, gw, c = h.shape
    return h.reshape(b, gh * gw, c), gh, gw


def unet_up_init(key, cin: int, cemb: int, chidden: int, cout: int,
                 dtype):
    k1, k2 = jax.random.split(key)
    return {"res": resblock_init(k1, cin, cemb, chidden, dtype),
            "out_norm": nn.groupnorm_init(chidden, dtype),
            "conv_out": nn.conv2d_init(k2, chidden, cout, 3, dtype=dtype)}


def unet_up(p, tokens, t_emb, grid_h: int, grid_w: int):
    """Hidden tokens [B, S, cin] -> latent prediction [B, H, W, cout]
    (UNetUp with out_norm: ResBlock -> GN+SiLU+conv3x3)."""
    b, s, c = tokens.shape
    x = tokens.reshape(b, grid_h, grid_w, c)
    x = resblock(p["res"], x, t_emb)
    x = jax.nn.silu(nn.groupnorm(p["out_norm"], x))
    return nn.conv2d(p["conv_out"], x)


def light_projector_init(key, input_dim: int, n_embed: int, depth: int,
                         dtype):
    """ViT aligner (reference: LightProjector mlp_gelu,
    hunyuan_image_3_transformer.py:723-741): Linear(input, n_embed) then
    depth-1 x [GELU, Linear(n_embed, n_embed)]."""
    ks = jax.random.split(key, max(depth, 1))
    layers = [nn.linear_init(ks[0], input_dim, n_embed, dtype=dtype)]
    for i in range(1, depth):
        layers.append(nn.linear_init(ks[i], n_embed, n_embed, dtype=dtype))
    return {"layers": layers}


def light_projector(p, x):
    x = nn.linear(p["layers"][0], x)
    for lp in p["layers"][1:]:
        # torch nn.GELU default is the exact erf form
        x = nn.linear(lp, jax.nn.gelu(x, approximate=False))
    return x
