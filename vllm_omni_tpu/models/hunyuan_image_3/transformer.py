"""HunyuanImage-3 causal multimodal transformer — TPU-native.

Reference: vllm_omni/diffusion/models/hunyuan_image_3/
hunyuan_image_3_transformer.py — HunyuanImage3Config (:978, 80B-total /
13B-active MoE: 64 routed experts + 1 shared, top-8), 2D rotary
embeddings with centered image grids (build_2d_rope :239),
HunYuanSparseMoeBlock (:1335, softmax-renormalized top-k + shared
expert), GQA attention (:1435), decoder layers (:1608).

TPU-first redesign: the reference's per-layer nn.Modules with a mutable
KV cache become pure functions over a param pytree; the denoise loop's
context KV is a loop-invariant array computed once by a prefill jit
(the ImageKVCacheManager :839 exists only to re-materialize the prefix
KV each step — a fori_loop carrying x with frozen context needs no
manager).  Routed experts run through ops/moe's ragged_dot grouped
matmul (MXU-shaped) instead of a fused-CUDA MoE; 2D rope tables are
precomputed host-side per (text_len, grid) geometry — static shapes,
one compile per resolution bucket.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.models.common import nn
from vllm_omni_tpu.ops import rms_norm, silu_mul
from vllm_omni_tpu.ops.moe import routed_moe


@dataclass(frozen=True)
class HunyuanImage3Config:
    """Geometry of the causal MM generator.

    ``real()`` is the published HunyuanImage-3 shape (reference config
    defaults :1070-1145 + the 80B/13B-active MoE card): 32 layers,
    hidden 4096, 32 q / 8 kv heads, 64 routed experts top-8 with one
    shared expert, vocab 290943, 16x-downsampling VAE with patch 1 so a
    1024px image is 64x64 = 4096 latent tokens (+1 timestep token =
    the ImageKVCacheManager's 4097, :844)."""

    vocab_size: int = 290943
    hidden_size: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    head_dim: int = 128
    intermediate_size: int = 11008       # shared-expert / dense MLP
    moe_intermediate_size: int = 3072    # per routed expert
    num_experts: int = 64
    moe_topk: int = 8
    moe_layer_num_skipped: int = 0       # leading dense layers
    rope_theta: float = 10000.0
    rms_eps: float = 1e-5
    # latent interface (vae_downsample_factor=(16,16), patch_size=1)
    latent_channels: int = 32
    patch_embed_hidden_dim: int = 1024
    image_base_size: int = 1024
    vae_ratio: int = 16
    timestep_shift: float = 3.0
    # special vocab ids (reference :1085-1092)
    boi_token_id: int = 4
    eoi_token_id: int = 5
    image_token_id: int = 8
    # <img_size_1024> / <ratio_i> live in the vocab tail; resolved from
    # the real tokenizer at load time, stable defaults for random-init
    size_token_id: int = 290800
    ratio_token_base: int = 290816

    @property
    def q_dim(self) -> int:
        return self.num_heads * self.head_dim

    @property
    def kv_dim(self) -> int:
        return self.num_kv_heads * self.head_dim

    def is_moe_layer(self, idx: int) -> bool:
        return self.num_experts > 1 and idx >= self.moe_layer_num_skipped

    @staticmethod
    def real() -> "HunyuanImage3Config":
        return HunyuanImage3Config()

    @staticmethod
    def tiny(moe: bool = True) -> "HunyuanImage3Config":
        return HunyuanImage3Config(
            hidden_size=64, num_layers=2, num_heads=4,
            num_kv_heads=2, head_dim=16, intermediate_size=128,
            moe_intermediate_size=32, num_experts=4 if moe else 1,
            moe_topk=2, latent_channels=4, patch_embed_hidden_dim=32,
            image_base_size=32, vae_ratio=2,
            vocab_size=768, size_token_id=600, ratio_token_base=601,
        )


# ---------------------------------------------------------------------------
# 2D rotary embeddings


def rope_2d_table(pos_yx: np.ndarray, head_dim: int,
                  theta: float) -> tuple[np.ndarray, np.ndarray]:
    """(y, x) positions [S, 2] -> neox-style cos/sin [S, head_dim].

    Frequency pairs alternate between the y and x axes (reference
    build_2d_rope :257: theta reshaped [d//4, 2], multiplied by the
    [S, 1, 2] position stack) — text tokens pass diagonal (p, p)
    positions so their rotation matches plain 1D rope."""
    assert head_dim % 4 == 0, head_dim
    freqs = 1.0 / theta ** (np.arange(0, head_dim, 2,
                                      dtype=np.float64) / head_dim)
    freqs = freqs.reshape(head_dim // 4, 2)           # [d//4, (y,x)]
    ang = (pos_yx[:, None, :] * freqs[None]).reshape(len(pos_yx), -1)
    cos = np.cos(ang)
    sin = np.sin(ang)
    # neox rotate-half convention: duplicate to the full head dim
    return (np.concatenate([cos, cos], axis=-1).astype(np.float32),
            np.concatenate([sin, sin], axis=-1).astype(np.float32))


def image_grid_positions(start: int, grid_h: int,
                         grid_w: int) -> np.ndarray:
    """Centered 2D grid for an image section beginning at sequence
    offset ``start`` (build_2d_rope :270-276: beta offsets center the
    grid on the 1D axis so text before/after stays ordered)."""
    beta_y = start + (grid_w * grid_h - grid_h) / 2.0
    beta_x = start + (grid_w * grid_h - grid_w) / 2.0
    ys = beta_y + np.arange(grid_h, dtype=np.float64)
    xs = beta_x + np.arange(grid_w, dtype=np.float64)
    grid = np.stack(np.meshgrid(ys, xs, indexing="ij"), axis=-1)
    return grid.reshape(-1, 2)


def diagonal_positions(start: int, n: int) -> np.ndarray:
    p = np.arange(start, start + n, dtype=np.float64)
    return np.stack([p, p], axis=-1)


def _rotate_half(x):
    half = x.shape[-1] // 2
    return jnp.concatenate([-x[..., half:], x[..., :half]], axis=-1)


def apply_rope_2d(x: jax.Array, cos: jax.Array, sin: jax.Array):
    """x [B, S, H, D] with tables [S, D]."""
    c = cos[None, :, None, :].astype(x.dtype)
    s = sin[None, :, None, :].astype(x.dtype)
    return x * c + _rotate_half(x) * s


# ---------------------------------------------------------------------------
# parameters


def _layer_init(key, cfg: HunyuanImage3Config, idx: int, dtype):
    k = jax.random.split(key, 9)
    h = cfg.hidden_size
    p = {
        "input_norm": nn.rmsnorm_init(h, dtype),
        "q_proj": nn.linear_init(k[0], h, cfg.q_dim, bias=False,
                                 dtype=dtype),
        "k_proj": nn.linear_init(k[1], h, cfg.kv_dim, bias=False,
                                 dtype=dtype),
        "v_proj": nn.linear_init(k[2], h, cfg.kv_dim, bias=False,
                                 dtype=dtype),
        "o_proj": nn.linear_init(k[3], cfg.q_dim, h, bias=False,
                                 dtype=dtype),
        "post_norm": nn.rmsnorm_init(h, dtype),
    }
    if cfg.is_moe_layer(idx):
        e, mi = cfg.num_experts, cfg.moe_intermediate_size
        scale = 1.0 / math.sqrt(h)
        p["gate"] = jax.random.normal(k[4], (h, e), dtype) * scale
        p["experts_gate_up"] = jax.random.normal(
            k[5], (e, h, 2 * mi), dtype) * scale
        p["experts_down"] = jax.random.normal(
            k[6], (e, mi, h), dtype) * (1.0 / math.sqrt(mi))
        # shared expert: a full dense MLP beside the routed ones
        p["shared_gate_up"] = nn.linear_init(
            k[7], h, 2 * cfg.intermediate_size, bias=False, dtype=dtype)
        p["shared_down"] = nn.linear_init(
            k[8], cfg.intermediate_size, h, bias=False, dtype=dtype)
    else:
        p["gate_up"] = nn.linear_init(k[4], h, 2 * cfg.intermediate_size,
                                      bias=False, dtype=dtype)
        p["down"] = nn.linear_init(k[5], cfg.intermediate_size, h,
                                   bias=False, dtype=dtype)
    return p


def init_params(key, cfg: HunyuanImage3Config, dtype=jnp.float32,
                lm_head: bool = False):
    keys = jax.random.split(key, cfg.num_layers + 3)
    p = {
        "embed": nn.embedding_init(keys[0], cfg.vocab_size,
                                   cfg.hidden_size, dtype),
        "layers": [_layer_init(keys[1 + i], cfg, i, dtype)
                   for i in range(cfg.num_layers)],
        "final_norm": nn.rmsnorm_init(cfg.hidden_size, dtype),
    }
    if lm_head:
        # untied output head (reference tie_word_embeddings=False,
        # pipeline_hunyuan_image_3.py:112) — needed by gen_text mode
        p["lm_head"] = nn.linear_init(
            keys[-1], cfg.hidden_size, cfg.vocab_size, bias=False,
            dtype=dtype)
    return p


def text_logits(params, hidden):
    """LM logits from final-norm hidden (untied lm_head when loaded,
    tied embedding otherwise)."""
    if "lm_head" in params:
        return nn.linear(params["lm_head"], hidden)
    return hidden @ params["embed"]["w"].T


# ---------------------------------------------------------------------------
# forward


def _mlp(layer, cfg: HunyuanImage3Config, x, moe: bool):
    h = rms_norm(x, layer["post_norm"]["w"], cfg.rms_eps)
    if not moe:
        return nn.linear(layer["down"], silu_mul(
            nn.linear(layer["gate_up"], h)))
    b, s, d = h.shape
    flat = h.reshape(b * s, d)
    routed = routed_moe(flat, layer["gate"], layer["experts_gate_up"],
                        layer["experts_down"], cfg.moe_topk)
    shared = nn.linear(layer["shared_down"], silu_mul(
        nn.linear(layer["shared_gate_up"], flat)))
    return (routed + shared).reshape(b, s, d)


def _qkv(layer, cfg: HunyuanImage3Config, x, cos, sin):
    b, s, _ = x.shape
    h = rms_norm(x, layer["input_norm"]["w"], cfg.rms_eps)
    flat = h.reshape(b * s, -1)
    q = nn.linear(layer["q_proj"], flat).reshape(b, s, -1, cfg.head_dim)
    k = nn.linear(layer["k_proj"], flat).reshape(b, s, -1, cfg.head_dim)
    v = nn.linear(layer["v_proj"], flat).reshape(b, s, -1, cfg.head_dim)
    return (apply_rope_2d(q, cos, sin), apply_rope_2d(k, cos, sin), v)


def prefill(params, cfg: HunyuanImage3Config, token_ids: jax.Array,
            ctx_mask: jax.Array, cos: jax.Array, sin: jax.Array,
            img_tokens: jax.Array | None = None):
    """Causal text/special-token prefill -> per-layer (k, v) context.

    The reference fills a HF DynamicCache through gen_text mode; here
    the whole prefix runs once under jit and the KV pytree is returned
    as loop-invariant context for the denoise fori_loop.

    ``img_tokens`` (already embedded through the UNetDown patch embed at
    t=0) extend the sequence after the text/specials as a CONDITIONING
    image section (_encode_cond_image): bidirectional attention among
    themselves, causal over the preceding text.  ``cos``/``sin`` must
    cover the full extended sequence; ``ctx_mask`` only the token ids
    (the image extension is always live)."""
    b, s = token_ids.shape
    x = nn.embedding(params["embed"], token_ids)
    if img_tokens is not None:
        s_img = img_tokens.shape[1]
        x = jnp.concatenate([x, img_tokens.astype(x.dtype)], axis=1)
        ctx_mask = jnp.concatenate(
            [ctx_mask, jnp.ones((b, s_img), ctx_mask.dtype)], axis=1)
    s_all = x.shape[1]
    causal = jnp.arange(s_all)[None, :] <= jnp.arange(s_all)[:, None]
    if img_tokens is not None:
        img_zone = (jnp.arange(s_all) >= s)[None, :] \
            & (jnp.arange(s_all) >= s)[:, None]
        causal = causal | img_zone
    bias = jnp.where(causal[None] & (ctx_mask[:, None, :] > 0),
                     0.0, -1e30)[:, None]
    kvs = []
    for i, layer in enumerate(params["layers"]):
        q, k, v = _qkv(layer, cfg, x, cos, sin)
        kvs.append((k, v))
        o = nn.bias_attention(q, k, v, bias)
        x = x + nn.linear(layer["o_proj"], o.reshape(b, s_all, -1))
        x = x + _mlp(layer, cfg, x, cfg.is_moe_layer(i))
    return kvs, ctx_mask


def make_gen_text(cfg: HunyuanImage3Config, ctx_bucket: int,
                  n_gen: int):
    """Jitted KV-cached AR TEXT rollout — the reference's ``gen_text``
    mode (pipeline_hunyuan_image_3.py:545: bot_task think/recaption/
    img_ratio runs HF ``generate`` over the same trunk).  Prompts
    right-pad to ``ctx_bucket`` (mask-aware prefill, one executable per
    bucket); decode is a fori_loop of dense single-query GQA attention
    over a preallocated cache.  Text tokens ride diagonal 2D-rope
    positions, so each generated token continues the 1D axis from the
    REAL per-prompt context length (pad slots are masked out of every
    attention and claim no positions).

    Returns ``gen(params, ids [B, ctx_bucket], ctx_lens [B], cos, sin,
    temperature, key) -> [B, n_gen] token ids`` (cos/sin must cover
    ctx_bucket + n_gen diagonal positions)."""
    hd, kvh = cfg.head_dim, cfg.num_kv_heads
    total = ctx_bucket + n_gen
    groups = cfg.num_heads // kvh

    def decode_one(params, x_tok, cos_b, sin_b, k_cache, v_cache,
                   valid, write_pos):
        """One single-token forward: per-batch rope rows ``cos_b``/
        ``sin_b`` [B, hd]; K/V written to cache slot ``write_pos``
        (None = replay a token whose K/V is already cached)."""
        b = x_tok.shape[0]
        x = x_tok  # [B, 1, D]
        nk, nv = [], []
        for li, layer in enumerate(params["layers"]):
            h = rms_norm(x, layer["input_norm"]["w"], cfg.rms_eps)
            flat = h.reshape(b, -1)
            q = nn.linear(layer["q_proj"], flat).reshape(b, 1, -1, hd)
            c = cos_b[:, None, None, :].astype(q.dtype)
            s_ = sin_b[:, None, None, :].astype(q.dtype)
            q = q * c + _rotate_half(q) * s_
            if write_pos is None:
                kc, vc = k_cache[li], v_cache[li]
            else:
                kq = nn.linear(layer["k_proj"], flat).reshape(
                    b, 1, -1, hd)
                vq = nn.linear(layer["v_proj"], flat).reshape(
                    b, 1, -1, hd)
                kq = kq * c + _rotate_half(kq) * s_
                kc = jax.lax.dynamic_update_slice_in_dim(
                    k_cache[li], kq, write_pos, axis=1)
                vc = jax.lax.dynamic_update_slice_in_dim(
                    v_cache[li], vq, write_pos, axis=1)
                nk.append(kc)
                nv.append(vc)
            qh = q[:, 0].reshape(b, kvh, groups, hd)
            s = jnp.einsum("bkgh,btkh->bkgt", qh.astype(jnp.float32),
                           kc.astype(jnp.float32)) / math.sqrt(hd)
            s = jnp.where(valid[:, None, None, :], s, -jnp.inf)
            o = jnp.einsum("bkgt,btkh->bkgh",
                           jax.nn.softmax(s, axis=-1),
                           vc.astype(jnp.float32))
            o = o.reshape(b, 1, cfg.q_dim).astype(x.dtype)
            x = x + nn.linear(layer["o_proj"], o)
            x = x + _mlp(layer, cfg, x, cfg.is_moe_layer(li))
        h = rms_norm(x, params["final_norm"]["w"], cfg.rms_eps)
        logits = text_logits(params, h[:, 0])
        if write_pos is None:
            return logits, k_cache, v_cache
        return logits, jnp.stack(nk), jnp.stack(nv)

    @jax.jit
    def gen(params, ids, ctx_lens, cos, sin, temperature, key):
        b = ids.shape[0]
        mask = (jnp.arange(ctx_bucket)[None, :]
                < ctx_lens[:, None]).astype(jnp.int32)
        kvs, _ = prefill(params, cfg, ids, mask,
                         cos[:ctx_bucket], sin[:ctx_bucket])
        k_cache = jnp.stack([
            jnp.zeros((b, total, kvh, hd), kvs[0][0].dtype)
            .at[:, :ctx_bucket].set(k) for k, _ in kvs])
        v_cache = jnp.stack([
            jnp.zeros((b, total, kvh, hd), kvs[0][1].dtype)
            .at[:, :ctx_bucket].set(v) for _, v in kvs])

        def pick(logits, k):
            greedy = jnp.argmax(logits, axis=-1)
            sampled = jax.random.categorical(
                k, logits / jnp.maximum(temperature, 1e-6))
            return jnp.where(temperature > 0, sampled,
                             greedy).astype(jnp.int32)

        ar = jnp.arange(total)

        # seed the rollout by REPLAYING the last real context token
        # through the decode path (its K/V is already cached from the
        # prefill) to read the next-token logits
        last_ids = jnp.take_along_axis(ids, ctx_lens[:, None] - 1,
                                       axis=1)
        x_last = nn.embedding(params["embed"], last_ids)
        valid0 = ar[None, :] < ctx_lens[:, None]
        logits0, _, _ = decode_one(
            params, x_last, cos[ctx_lens - 1], sin[ctx_lens - 1],
            k_cache, v_cache, valid0, None)
        key, sub = jax.random.split(key)
        first = pick(logits0, sub)

        def step(i, carry):
            k_cache, v_cache, tok, out, kk = carry
            x = nn.embedding(params["embed"], tok[:, None])
            # rope row continues from the REAL length; cache slot is
            # bucket-aligned
            valid = valid0 | ((ar[None, :] >= ctx_bucket)
                              & (ar[None, :] <= ctx_bucket + i))
            logits, k_cache, v_cache = decode_one(
                params, x, cos[ctx_lens + i], sin[ctx_lens + i],
                k_cache, v_cache, valid, ctx_bucket + i)
            kk, sub = jax.random.split(kk)
            nxt = pick(logits, sub)
            out = out.at[:, i].set(tok)
            return (k_cache, v_cache, nxt, out, kk)

        out = jnp.zeros((b, n_gen), jnp.int32)
        _, _, _, out, _ = jax.lax.fori_loop(
            0, n_gen, step, (k_cache, v_cache, first, out, key))
        return out

    return gen


def gen_image_step(params, cfg: HunyuanImage3Config, x_tokens: jax.Array,
                   ctx_kvs, ctx_mask: jax.Array, cos: jax.Array,
                   sin: jax.Array):
    """One gen_image forward: embedded [timestep ; latent] tokens attend
    [cached context ; themselves] with full self-attention inside the
    image section (the reference's gen_image attention mode), returning
    final-norm hidden states [B, S_img, hidden]."""
    b, s_img, _ = x_tokens.shape
    s_ctx = ctx_mask.shape[1]
    x = x_tokens
    bias = jnp.concatenate(
        [jnp.where(ctx_mask[:, None, None, :] > 0, 0.0, -1e30),
         jnp.zeros((b, 1, 1, s_img))], axis=-1)
    bias = jnp.broadcast_to(bias, (b, 1, s_img, s_ctx + s_img))
    for i, layer in enumerate(params["layers"]):
        q, k, v = _qkv(layer, cfg, x, cos, sin)
        ck, cv = ctx_kvs[i]
        k = jnp.concatenate([ck, k], axis=1)
        v = jnp.concatenate([cv, v], axis=1)
        o = nn.bias_attention(q, k, v, bias)
        x = x + nn.linear(layer["o_proj"], o.reshape(b, s_img, -1))
        x = x + _mlp(layer, cfg, x, cfg.is_moe_layer(i))
    return rms_norm(x, params["final_norm"]["w"], cfg.rms_eps)
