"""Aspect-ratio resolution buckets for HunyuanImage-3.

Reference: hunyuan_image_3_transformer.py — ResolutionGroup (:468):
starting from (base, base), step height up / width down (and the
mirror) between base/2 and base*2, aligning each side down to ``align``;
requests snap to the bucket with the nearest aspect ratio
(get_target_size :543).  Bucketing keeps the set of compiled
(grid_h, grid_w) executables finite — on TPU each bucket is one XLA
compilation, so this doubles as the jit-cache policy.
"""

from __future__ import annotations

import numpy as np


class ResolutionGroup:
    def __init__(self, base_size: int, step: int | None = None,
                 align: int = 1):
        if base_size % align:
            raise ValueError(f"base_size {base_size} not divisible by "
                             f"align {align}")
        if step is None:
            step = max(base_size // 16, align)
        if step > base_size // 2:
            raise ValueError(f"step {step} > base_size//2")
        self.base_size = base_size
        self.step = step
        self.align = align
        self.data = self._calc_by_step()
        self.ratio = np.array([h / w for h, w in self.data])

    def _calc_by_step(self) -> list[tuple[int, int]]:
        base, step, align = self.base_size, self.step, self.align
        lo, hi = base // 2, base * 2
        out = [(base, base)]
        h, w = base, base
        while not (h >= hi and w <= lo):
            h = min(h + step, hi)
            w = max(w - step, lo)
            out.append((h // align * align, w // align * align))
        h, w = base, base
        while not (h <= lo and w >= hi):
            h = max(h - step, lo)
            w = min(w + step, hi)
            out.append((h // align * align, w // align * align))
        return sorted(set(out), key=lambda s: s[0] / s[1])

    def __len__(self) -> int:
        return len(self.data)

    def get_target_size(self, width: int, height: int) -> tuple[int, int]:
        """(width, height) of the nearest-ratio bucket."""
        idx = self.ratio_index(width, height)
        h, w = self.data[idx]
        return w, h

    def ratio_index(self, width: int, height: int) -> int:
        return int(np.argmin(np.abs(self.ratio - height / width)))
