"""Qwen2.5-Omni family: thinker / talker / token2wav (3-stage pipeline).

Reference: vllm_omni/model_executor/models/qwen2_5_omni/ — composite
Qwen2_5OmniForConditionalGeneration split into an AV-L understanding
thinker, an AR codec talker, and token2wav (a DiT mel generator + BigVGAN
vocoder — an in-repo diffusion model inside an AR stage; SURVEY §2.8).
"""
