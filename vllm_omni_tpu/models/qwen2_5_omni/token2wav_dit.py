"""Qwen2.5-Omni token2wav DiT (codec tokens -> mel, flow matching).

Checkpoint-schema implementation of the transformers
``Qwen2_5OmniToken2WavDiTModel`` (reference:
vllm_omni/model_executor/models/qwen2_5_omni/qwen2_5_omni_token2wav.py —
an in-repo diffusion model running inside an AR stage):

- ECAPA-TDNN speaker encoder over the reference mel (Res2Net + SE
  blocks, attentive-statistics pooling),
- codec embedding repeat-interleaved 2x to the mel frame rate,
- input projection over [noised mel | ECAPA vector | codec embed |
  speaker embedding],
- 22 DiT blocks: AdaLayerNormZero modulation, BLOCK-DIAGONAL attention
  (block_size 24) where per-layer look_ahead/look_backward flags admit
  the neighbouring block, rotary applied to the FIRST head only (a
  reference training quirk, kept for checkpoint compatibility),
- AdaLN-final + projection to mel, integrated with an RK4 flow-matching
  solver over a sway-warped time grid, with classifier-free guidance
  run as a doubled batch.

TPU-first: the velocity evaluation is one jitted function; the RK4
integration is a ``lax.scan`` over the (static-length) time grid; the
block-diagonal mask is a static bias XLA folds into the softmax.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.models.common import nn

logger = init_logger(__name__)


@dataclass(frozen=True)
class T2WDiTConfig:
    """Mirrors transformers ``Qwen2_5OmniDiTConfig``."""
    hidden_size: int = 1024
    num_layers: int = 22
    num_heads: int = 16
    head_dim: int = 64
    ff_mult: int = 2
    emb_dim: int = 512            # codec embedding width
    num_embeds: int = 8193
    mel_dim: int = 80
    repeats: int = 2
    block_size: int = 24
    look_ahead_layers: tuple = (10,)
    look_backward_layers: tuple = (0, 20)
    rope_theta: float = 10000.0
    # ECAPA speaker encoder geometry
    enc_dim: int = 128
    enc_emb_dim: int = 192
    enc_channels: tuple = (256, 256, 256, 256, 768)
    enc_kernel_sizes: tuple = (5, 3, 3, 3, 1)
    enc_dilations: tuple = (1, 2, 3, 4, 1)
    enc_attention_channels: int = 64
    enc_res2net_scale: int = 2
    enc_se_channels: int = 64
    freq_embed_dim: int = 256
    # the 2.5-Omni checkpoint rotates only head 0 (training quirk); the
    # Qwen3-TTS 25 Hz V1 decoder rotates every head
    rope_all_heads: bool = False

    @staticmethod
    def tiny() -> "T2WDiTConfig":
        return T2WDiTConfig(
            hidden_size=32, num_layers=3, num_heads=2, head_dim=8,
            emb_dim=12, num_embeds=40, mel_dim=8, block_size=4,
            look_ahead_layers=(1,), look_backward_layers=(0,),
            enc_dim=10, enc_emb_dim=6, enc_channels=(8, 8, 8, 8, 24),
            enc_kernel_sizes=(5, 3, 3, 3, 1),
            enc_dilations=(1, 2, 3, 4, 1), enc_attention_channels=4,
            enc_res2net_scale=2, enc_se_channels=4,
        )

    @staticmethod
    def from_hf(d: dict, rope_all_heads: bool = False) -> "T2WDiTConfig":
        return T2WDiTConfig(
            rope_all_heads=rope_all_heads,
            hidden_size=d.get("hidden_size", 1024),
            num_layers=d.get("num_hidden_layers", 22),
            num_heads=d.get("num_attention_heads", 16),
            head_dim=d.get("head_dim", 64),
            ff_mult=d.get("ff_mult", 2),
            emb_dim=d.get("emb_dim", 512),
            num_embeds=d.get("num_embeds", 8193),
            mel_dim=d.get("mel_dim", 80),
            repeats=d.get("repeats", 2),
            block_size=d.get("block_size", 24),
            look_ahead_layers=tuple(d.get("look_ahead_layers", (10,))),
            look_backward_layers=tuple(d.get("look_backward_layers",
                                             (0, 20))),
            rope_theta=d.get("rope_theta", 10000.0),
            enc_dim=d.get("enc_dim", 128),
            enc_emb_dim=d.get("enc_emb_dim", 192),
            enc_channels=tuple(d.get("enc_channels",
                                     (256, 256, 256, 256, 768))),
            enc_kernel_sizes=tuple(d.get("enc_kernel_sizes",
                                         (5, 3, 3, 3, 1))),
            enc_dilations=tuple(d.get("enc_dilations", (1, 2, 3, 4, 1))),
            enc_attention_channels=d.get("enc_attention_channels", 64),
            enc_res2net_scale=d.get("enc_res2net_scale", 2),
            enc_se_channels=d.get("enc_se_channels", 64),
        )


_PRECISION = jax.lax.Precision.HIGHEST


# ----------------------------------------------------------- ECAPA-TDNN
def _tdnn(p, x, k: int, dilation: int = 1):
    """TimeDelayNetBlock: reflect-pad SAME conv + ReLU, NWC."""
    pad = (k * dilation - dilation) // 2
    h = jnp.pad(x, ((0, 0), (pad, pad), (0, 0)),
                mode="reflect") if pad else x
    y = jax.lax.conv_general_dilated(
        h, p["w"].astype(x.dtype), window_strides=(1,), padding="VALID",
        rhs_dilation=(dilation,),
        dimension_numbers=("NWC", "WIO", "NWC"), precision=_PRECISION)
    return jax.nn.relu(y + p["b"].astype(x.dtype))


def _res2net(p, x, scale: int, k: int, dilation: int):
    parts = jnp.split(x, scale, axis=-1)
    outs = [parts[0]]
    prev = None
    for i in range(1, scale):
        inp = parts[i] if i == 1 else parts[i] + prev
        prev = _tdnn(p["blocks"][i - 1], inp, k, dilation)
        outs.append(prev)
    return jnp.concatenate(outs, axis=-1)


def _se(p, x):
    m = jnp.mean(x, axis=1, keepdims=True)
    m = jax.nn.relu(nn.linear(p["conv1"], m))
    m = jax.nn.sigmoid(nn.linear(p["conv2"], m))
    return x * m


def _asp(p, x, eps: float = 1e-12):
    """Attentive statistics pooling: [B, T, C] -> [B, 2C]."""
    t = x.shape[1]
    w = jnp.full((x.shape[0], t, 1), 1.0 / t, x.dtype)
    mean = jnp.sum(w * x, axis=1)
    std = jnp.sqrt(jnp.clip(
        jnp.sum(w * jnp.square(x - mean[:, None]), axis=1), eps, None))
    attn_in = jnp.concatenate(
        [x, jnp.broadcast_to(mean[:, None], x.shape),
         jnp.broadcast_to(std[:, None], x.shape)], axis=-1)
    a = _tdnn(p["tdnn"], attn_in, 1)
    a = nn.linear(p["conv"], jnp.tanh(a))
    a = jax.nn.softmax(a, axis=1)
    mean = jnp.sum(a * x, axis=1)
    std = jnp.sqrt(jnp.clip(
        jnp.sum(a * jnp.square(x - mean[:, None]), axis=1), eps, None))
    return jnp.concatenate([mean, std], axis=-1)


def ecapa_forward(p, cfg: T2WDiTConfig, mel):
    """Reference mel [B, T, mel_dim] -> speaker vector [B, enc_dim].

    Runs under full matmul precision: the reference pins token2wav to
    fp32 inference (Qwen2_5OmniToken2WavModel warns and refuses fp16
    attention), and the default TPU/oneDNN bf16 matmul pass visibly
    perturbs the RK4 trajectory."""
    with jax.default_matmul_precision("highest"):
        return _ecapa_forward(p, cfg, mel)


def _ecapa_forward(p, cfg: T2WDiTConfig, mel):
    ch, ks, dil = cfg.enc_channels, cfg.enc_kernel_sizes, cfg.enc_dilations
    feats = []
    x = _tdnn(p["blocks"][0], mel, ks[0], dil[0])
    feats.append(x)
    for i in range(1, len(ch) - 1):
        blk = p["blocks"][i]
        res = x
        h = _tdnn(blk["tdnn1"], x, 1)
        h = _res2net(blk["res2net"], h, cfg.enc_res2net_scale, ks[i],
                     dil[i])
        h = _tdnn(blk["tdnn2"], h, 1)
        h = _se(blk["se"], h)
        x = h + res
        feats.append(x)
    x = jnp.concatenate(feats[1:], axis=-1)
    x = _tdnn(p["mfa"], x, ks[-1], dil[-1])
    x = _asp(p["asp"], x)
    return nn.linear(p["fc"], x)


# ------------------------------------------------------------- DiT core
def _sinus_time_embed(t, dim: int):
    """SinusPositionEmbedding (scale 1000, half sin / half cos)."""
    half = dim // 2
    freqs = jnp.exp(-math.log(10000.0) * jnp.arange(half) / (half - 1))
    ang = 1000.0 * t.astype(jnp.float32)[:, None] * freqs[None, :]
    return jnp.concatenate([jnp.sin(ang), jnp.cos(ang)], axis=-1)


def _rope_first_head(q, k, cfg: T2WDiTConfig):
    """Rotary on head 0 only (reference quirk) — or all heads for the
    V1 decoder — duplicated-pair freq layout, interleaved rotation."""
    t = q.shape[2]
    half = cfg.head_dim // 2
    inv = 1.0 / (cfg.rope_theta
                 ** (jnp.arange(0, cfg.head_dim, 2) / cfg.head_dim))
    freqs = jnp.arange(t)[:, None].astype(jnp.float32) * inv[None, :]
    freqs = jnp.stack([freqs, freqs], axis=-1).reshape(t, cfg.head_dim)
    cos, sin = jnp.cos(freqs), jnp.sin(freqs)

    def rot_pairs(x):
        # interleaved-pair rotation (reference rotate_half_codec):
        # (x0, x1, x2, x3, ...) -> (-x1, x0, -x3, x2, ...)
        xp = x.reshape(*x.shape[:-1], -1, 2)
        return jnp.stack([-xp[..., 1], xp[..., 0]],
                         axis=-1).reshape(x.shape)

    def apply(x):
        n = x.shape[1] if cfg.rope_all_heads else 1
        h0 = x[:, :n].astype(jnp.float32)
        h0 = h0 * cos[None, None] + rot_pairs(h0) * sin[None, None]
        if cfg.rope_all_heads:
            return h0.astype(x.dtype)
        return jnp.concatenate([h0.astype(x.dtype), x[:, 1:]], axis=1)

    return apply(q), apply(k)


def _block_bias(seq_len: int, block_size: int, ahead: int, back: int):
    blocks = jnp.arange(seq_len) // block_size
    diff = blocks[None, :] - blocks[:, None]
    ok = (diff >= -back) & (diff <= ahead)
    return jnp.where(ok, 0.0, -jnp.inf).astype(jnp.float32)


def _ada_ln_zero(p, x, temb):
    e = nn.linear(p["linear"], jax.nn.silu(temb))
    shift_msa, scale_msa, gate_msa, shift_mlp, scale_mlp, gate_mlp = \
        jnp.split(e, 6, axis=-1)
    h = _ln(x) * (1 + scale_msa[:, None]) + shift_msa[:, None]
    return h, gate_msa, shift_mlp, scale_mlp, gate_mlp


def _ln(x, eps: float = 1e-6):
    xf = x.astype(jnp.float32)
    m = jnp.mean(xf, axis=-1, keepdims=True)
    v = jnp.var(xf, axis=-1, keepdims=True)
    return ((xf - m) * jax.lax.rsqrt(v + eps)).astype(x.dtype)


def _dit_layer(p, cfg: T2WDiTConfig, x, temb, bias):
    h, gate_msa, shift_mlp, scale_mlp, gate_mlp = _ada_ln_zero(
        p["attn_norm"], x, temb)
    b, t, _ = h.shape
    flat = h.reshape(b * t, -1)
    q = nn.linear(p["to_q"], flat).reshape(b, t, cfg.num_heads,
                                           cfg.head_dim)
    k = nn.linear(p["to_k"], flat).reshape(b, t, cfg.num_heads,
                                           cfg.head_dim)
    v = nn.linear(p["to_v"], flat).reshape(b, t, cfg.num_heads,
                                           cfg.head_dim)
    q = q.transpose(0, 2, 1, 3)  # [B, H, T, D]
    k = k.transpose(0, 2, 1, 3)
    v = v.transpose(0, 2, 1, 3)
    q, k = _rope_first_head(q, k, cfg)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32),
                   precision=_PRECISION) / math.sqrt(cfg.head_dim)
    a = jax.nn.softmax(s + bias[None, None], axis=-1).astype(v.dtype)
    o = jnp.einsum("bhqk,bhkd->bhqd", a, v, precision=_PRECISION)
    o = o.transpose(0, 2, 1, 3).reshape(b, t, -1)
    o = nn.linear(p["to_out"], o)
    x = x + gate_msa[:, None] * o
    h = _ln(x) * (1 + scale_mlp[:, None]) + shift_mlp[:, None]
    h = nn.linear(p["ff2"], jax.nn.gelu(nn.linear(p["ff1"], h),
                                        approximate=True))
    return x + gate_mlp[:, None] * h


def forward(params, cfg: T2WDiTConfig, noised_mel, spk_vec, code_embed,
            speaker_embedding, t):
    """Velocity prediction for one (possibly CFG-doubled) batch.

    noised_mel [B, T, mel]; spk_vec [B, enc_dim] (ECAPA output, zeroed
    for the uncond half); code_embed [B, T, emb_dim];
    speaker_embedding [B, T, enc_emb_dim]; t [B] flow time.
    """
    with jax.default_matmul_precision("highest"):
        return _forward(params, cfg, noised_mel, spk_vec, code_embed,
                        speaker_embedding, t)


def _forward(params, cfg, noised_mel, spk_vec, code_embed,
             speaker_embedding, t):
    temb = _sinus_time_embed(t, cfg.freq_embed_dim).astype(noised_mel.dtype)
    temb = nn.linear(params["time_mlp2"],
                     jax.nn.silu(nn.linear(params["time_mlp1"], temb)))
    seq = noised_mel.shape[1]
    cond = jnp.broadcast_to(spk_vec[:, None],
                            (spk_vec.shape[0], seq, spk_vec.shape[-1]))
    x = jnp.concatenate([noised_mel, cond, code_embed,
                         speaker_embedding], axis=-1)
    x = nn.linear(params["in_proj"], x)
    for i, layer in enumerate(params["layers"]):
        ahead = 1 if i in cfg.look_ahead_layers else 0
        back = 1 if i in cfg.look_backward_layers else 0
        bias = _block_bias(seq, cfg.block_size, ahead, back)
        x = _dit_layer(layer, cfg, x, temb, bias)
    e = nn.linear(params["norm_out"], jax.nn.silu(temb))
    scale, shift = jnp.split(e, 2, axis=-1)
    x = _ln(x) * (1 + scale)[:, None] + shift[:, None]
    return nn.linear(params["proj_out"], x)


def embed_code(params, cfg: T2WDiTConfig, code, drop: bool = False):
    """Codec ids [B, Tc] -> [B, Tc*repeats, emb_dim]."""
    ids = jnp.zeros_like(code) if drop else code
    e = nn.embedding(params["codec_embed"], ids)
    return jnp.repeat(e, cfg.repeats, axis=1)


def sample(params, cfg: T2WDiTConfig, code, ref_mel, spk_embedding,
           num_steps: int = 10, guidance_scale: float = 0.5,
           sway_coefficient: float = -1.0, initial_noise=None,
           solver: str = "rk4"):
    """Flow-matching integration -> mel [B, T, mel_dim] (RK4 for the
    2.5-Omni token2wav; plain Euler for the 25 Hz V1 decoder, whose
    reference sample loop steps x <- x + v dt).

    code [B, Tc]; ref_mel [B, Tref, mel] (speaker reference audio);
    spk_embedding [B, enc_emb_dim] (per-voice vector).  Deterministic
    when ``initial_noise`` is given (the reference draws torch.randn
    internally).
    """
    b, tc = code.shape
    t_mel = tc * cfg.repeats
    if initial_noise is None:
        initial_noise = jax.random.normal(
            jax.random.PRNGKey(0), (b, t_mel, cfg.mel_dim))
    state = initial_noise.astype(ref_mel.dtype)[:, :t_mel]
    spk_seq = jnp.broadcast_to(spk_embedding[:, None],
                               (b, t_mel, spk_embedding.shape[-1]))

    spk_vec = ecapa_forward(params["spk_encoder"], cfg, ref_mel)
    # the uncond CFG half zeroes the reference MEL before the speaker
    # encoder (reference DiTInputEmbedding.forward), not the encoder's
    # output — ECAPA(0) is a nonzero bias vector
    spk_vec_uncond = ecapa_forward(params["spk_encoder"], cfg,
                                   jnp.zeros_like(ref_mel))
    code_cond = embed_code(params, cfg, code, drop=False)
    code_uncond = embed_code(params, cfg, code, drop=True)

    def velocity(x, t):
        if guidance_scale < 1e-5:
            return forward(params, cfg, x, spk_vec, code_cond, spk_seq,
                           t)
        x2 = jnp.concatenate([x, x], axis=0)
        sv = jnp.concatenate([spk_vec, spk_vec_uncond], 0)
        ce = jnp.concatenate([code_cond, code_uncond], 0)
        se = jnp.concatenate([spk_seq, jnp.zeros_like(spk_seq)], 0)
        t2 = jnp.concatenate([t, t], 0)
        v = forward(params, cfg, x2, sv, ce, se, t2)
        pos, neg = jnp.split(v, 2, axis=0)
        return pos + (pos - neg) * guidance_scale

    ts = jnp.linspace(0.0, 1.0, num_steps)
    if sway_coefficient is not None:
        ts = ts + sway_coefficient * (jnp.cos(jnp.pi / 2 * ts) - 1 + ts)

    def f(t_scalar, yy):
        return velocity(yy, jnp.broadcast_to(t_scalar, (b,)))

    def rk4_step(y, tt):
        t0, t1 = tt
        h = t1 - t0
        k1 = f(t0, y)
        k2 = f(t0 + h / 3, y + h * k1 / 3)
        k3 = f(t0 + h * 2 / 3, y + h * (k2 - k1 / 3))
        k4 = f(t1, y + h * (k1 - k2 + k3))
        return y + (k1 + 3 * (k2 + k3) + k4) * h / 8, None

    def euler_step(y, tt):
        t0, t1 = tt
        return y + f(t0, y) * (t1 - t0), None

    pairs = jnp.stack([ts[:-1], ts[1:]], axis=1)
    step = euler_step if solver == "euler" else rk4_step
    state, _ = jax.lax.scan(step, state, pairs)
    return state


# ------------------------------------------------------- checkpoint load
def init_params(key, cfg: T2WDiTConfig, dtype=jnp.float32):
    ki = iter(jax.random.split(key, 1024))
    h = cfg.hidden_size
    inner = cfg.num_heads * cfg.head_dim
    in_dim = cfg.mel_dim + cfg.enc_dim + cfg.enc_emb_dim + cfg.emb_dim
    p = {
        "time_mlp1": nn.linear_init(next(ki), cfg.freq_embed_dim, h,
                                    dtype=dtype),
        "time_mlp2": nn.linear_init(next(ki), h, h, dtype=dtype),
        "codec_embed": nn.embedding_init(next(ki), cfg.num_embeds + 1,
                                         cfg.emb_dim, dtype),
        "in_proj": nn.linear_init(next(ki), in_dim, h, dtype=dtype),
        "norm_out": nn.linear_init(next(ki), h, 2 * h, dtype=dtype),
        "proj_out": nn.linear_init(next(ki), h, cfg.mel_dim, dtype=dtype),
        "layers": [],
        "spk_encoder": _ecapa_init(ki, cfg, dtype),
    }
    for _ in range(cfg.num_layers):
        p["layers"].append({
            "attn_norm": {"linear": nn.linear_init(next(ki), h, 6 * h,
                                                   dtype=dtype)},
            "to_q": nn.linear_init(next(ki), h, inner, dtype=dtype),
            "to_k": nn.linear_init(next(ki), h, inner, dtype=dtype),
            "to_v": nn.linear_init(next(ki), h, inner, dtype=dtype),
            "to_out": nn.linear_init(next(ki), inner, h, dtype=dtype),
            "ff1": nn.linear_init(next(ki), h, h * cfg.ff_mult,
                                  dtype=dtype),
            "ff2": nn.linear_init(next(ki), h * cfg.ff_mult, h,
                                  dtype=dtype),
        })
    return p


def _conv_init(ki, cin, cout, k, dtype):
    return {"w": nn.conv1d_init(next(ki), cin, cout, k, dtype=dtype)["w"],
            "b": jnp.zeros((cout,), dtype)}


def _ecapa_init(ki, cfg: T2WDiTConfig, dtype):
    ch = cfg.enc_channels
    scale = cfg.enc_res2net_scale
    p = {"blocks": [_conv_init(ki, cfg.mel_dim, ch[0],
                               cfg.enc_kernel_sizes[0], dtype)]}
    for i in range(1, len(ch) - 1):
        p["blocks"].append({
            "tdnn1": _conv_init(ki, ch[i - 1], ch[i], 1, dtype),
            "res2net": {"blocks": [
                _conv_init(ki, ch[i] // scale, ch[i] // scale,
                           cfg.enc_kernel_sizes[i], dtype)
                for _ in range(scale - 1)]},
            "tdnn2": _conv_init(ki, ch[i], ch[i], 1, dtype),
            "se": {"conv1": nn.linear_init(next(ki), ch[i],
                                           cfg.enc_se_channels,
                                           dtype=dtype),
                   "conv2": nn.linear_init(next(ki), cfg.enc_se_channels,
                                           ch[i], dtype=dtype)},
        })
    cat = sum(ch[1:-1])
    p["mfa"] = _conv_init(ki, cat, ch[-1], cfg.enc_kernel_sizes[-1],
                          dtype)
    p["asp"] = {
        "tdnn": _conv_init(ki, ch[-1] * 3, cfg.enc_attention_channels, 1,
                           dtype),
        "conv": nn.linear_init(next(ki), cfg.enc_attention_channels,
                               ch[-1], dtype=dtype),
    }
    p["fc"] = nn.linear_init(next(ki), ch[-1] * 2, cfg.enc_dim,
                             dtype=dtype)
    return p


def hf_flat_map(cfg: T2WDiTConfig,
                prefix: str = "token2wav.code2wav_dit_model.") -> dict:
    m: dict[str, tuple] = {}

    def lin(hf, path):
        m[f"{hf}.weight"] = path + ("w",)
        m[f"{hf}.bias"] = path + ("b",)

    def conv(hf, path):
        m[f"{hf}.weight"] = path + ("w",)
        m[f"{hf}.bias"] = path + ("b",)

    lin(f"{prefix}time_embed.time_mlp.0", ("time_mlp1",))
    lin(f"{prefix}time_embed.time_mlp.2", ("time_mlp2",))
    m[f"{prefix}text_embed.codec_embed.weight"] = ("codec_embed", "w")
    lin(f"{prefix}input_embed.proj", ("in_proj",))
    lin(f"{prefix}norm_out.linear", ("norm_out",))
    lin(f"{prefix}proj_out", ("proj_out",))
    for i in range(cfg.num_layers):
        b = f"{prefix}transformer_blocks.{i}"
        tgt = ("layers", i)
        lin(f"{b}.attn_norm.linear", tgt + ("attn_norm", "linear"))
        for proj in ("to_q", "to_k", "to_v"):
            lin(f"{b}.attn.{proj}", tgt + (proj,))
        lin(f"{b}.attn.to_out.0", tgt + ("to_out",))
        lin(f"{b}.ff.ff.0", tgt + ("ff1",))
        lin(f"{b}.ff.ff.3", tgt + ("ff2",))

    sp = f"{prefix}input_embed.spk_encoder"
    st = ("spk_encoder",)
    conv(f"{sp}.blocks.0.conv", st + ("blocks", 0))
    for i in range(1, len(cfg.enc_channels) - 1):
        bb = f"{sp}.blocks.{i}"
        bt = st + ("blocks", i)
        conv(f"{bb}.tdnn1.conv", bt + ("tdnn1",))
        for j in range(cfg.enc_res2net_scale - 1):
            conv(f"{bb}.res2net_block.blocks.{j}.conv",
                 bt + ("res2net", "blocks", j))
        conv(f"{bb}.tdnn2.conv", bt + ("tdnn2",))
        lin(f"{bb}.se_block.conv1", bt + ("se", "conv1"))
        lin(f"{bb}.se_block.conv2", bt + ("se", "conv2"))
    conv(f"{sp}.mfa.conv", st + ("mfa",))
    conv(f"{sp}.asp.tdnn.conv", st + ("asp", "tdnn"))
    lin(f"{sp}.asp.conv", st + ("asp", "conv"))
    lin(f"{sp}.fc", st + ("fc",))
    return m


def hf_transform(name: str, arr):
    """Conv1d [out, in, k] -> [k, in, out]; 1x1 convs that we apply as
    linears ([out, in, 1]) -> [in, out]; linears [out, in] -> [in,
    out]; embeddings stay."""
    if arr.ndim == 3:
        if arr.shape[-1] == 1 and (".se_block." in name
                                   or ".asp.conv" in name
                                   or name.endswith("fc.weight")):
            return arr[..., 0].transpose(1, 0)
        return arr.transpose(2, 1, 0)
    if arr.ndim == 2 and name.endswith("weight") \
            and "codec_embed" not in name:
        return arr.T
    return arr


def load_dit(model_dir: str, cfg: T2WDiTConfig = None, dtype=jnp.float32,
             prefix: str = "token2wav.code2wav_dit_model."):
    import json
    import os

    from vllm_omni_tpu.model_loader.safetensors_loader import (
        load_checkpoint_tree,
    )

    if cfg is None:
        cfg_path = os.path.join(model_dir, "config.json")
        d = {}
        if os.path.isfile(cfg_path):
            with open(cfg_path) as f:
                d = (json.load(f).get("token2wav_config", {})
                     .get("dit_config", {}))
        cfg = T2WDiTConfig.from_hf(d)
    shapes = jax.eval_shape(
        lambda: init_params(jax.random.PRNGKey(0), cfg, jnp.float32))
    tree = jax.tree.map(lambda t: np.zeros(t.shape, np.float32), shapes)
    flat = hf_flat_map(cfg, prefix)
    n, _ = load_checkpoint_tree(
        model_dir, flat.get, tree, dtype=np.float32,
        transform=hf_transform, name_filter=lambda nm: nm in flat,
    )
    n_leaves = len(jax.tree.leaves(tree))
    if n != n_leaves:
        raise ValueError(
            f"{model_dir} covered {n}/{n_leaves} token2wav-DiT weights")
    tree = jax.tree.map(lambda a: jnp.asarray(a, dtype), tree)
    return tree, cfg


# --------------------------------------------------- stage integration
class Token2WavRealModel:
    """Generation-runner model protocol over the checkpoint-schema
    stack: talker codec ids -> flow-matched mel -> BigVGAN waveform.

    Voice conditioning rides the generation runner's conditioning hook
    (``batch_conditioning``): requests may carry a named ``voice``
    (resolved through the ``voices`` registry — the reference keeps
    speaker embedding + reference mel per speaker) or raw
    ``speaker_embedding`` / ``reference_mel`` arrays in
    additional_information; anything absent falls back to neutral
    zeros."""

    REF_MEL_FRAMES = 8  # bucketed reference-mel length (resized into)

    def __init__(self, dit_cfg: T2WDiTConfig, bv_cfg, num_steps: int = 10,
                 guidance_scale: float = 0.5,
                 sway_coefficient: float = -1.0, solver: str = "rk4",
                 voices: dict = None):
        self.cfg = dit_cfg
        self.bv_cfg = bv_cfg
        self.num_steps = num_steps
        self.guidance_scale = guidance_scale
        self.sway = sway_coefficient
        self.solver = solver
        self.voices = voices or {}

    def batch_conditioning(self, requests, batch: int):
        """[B]-stacked (spk [B, enc_emb], ref_mel [B, F, mel]) from the
        requests' additional_information; None when every row is
        unconditioned (keeps the cond-free jit specialization hot)."""
        cfg = self.cfg
        f = self.REF_MEL_FRAMES
        spk = np.zeros((batch, cfg.enc_emb_dim), np.float32)
        ref = np.zeros((batch, f, cfg.mel_dim), np.float32)
        any_cond = False
        for i, req in enumerate(requests):
            info = getattr(req, "additional_information", None) or {}
            # malformed per-request assets must not take down the whole
            # batch (a poll exception kills every in-flight request) —
            # degrade that row to the neutral voice with a warning
            try:
                v = info.get("voice")
                if isinstance(v, str) and v in self.voices:
                    info = {**info, **self.voices[v]}
                se = info.get("speaker_embedding")
                if se is not None:
                    se = np.asarray(se, np.float32).reshape(-1)
                    n = min(cfg.enc_emb_dim, se.shape[0])
                    spk[i, :n] = se[:n]
                    any_cond = True
                rm = info.get("reference_mel")
                if rm is not None:
                    rm = np.atleast_2d(np.asarray(rm, np.float32))
                    n = min(f, rm.shape[0])
                    m = min(cfg.mel_dim, rm.shape[1])
                    ref[i, :n, :m] = rm[:n, :m]
                    any_cond = True
            except Exception as e:
                logger.warning(
                    "request %s: malformed voice conditioning (%s) — "
                    "using the neutral voice",
                    getattr(req, "request_id", "?"), e)
        if not any_cond:
            return None
        return {"spk": jnp.asarray(spk), "ref_mel": jnp.asarray(ref)}

    def forward(self, params, token_ids, lengths, cond=None):
        from vllm_omni_tpu.models.qwen2_5_omni import bigvgan as bv

        del lengths
        b = token_ids.shape[0]
        cfg = self.cfg
        if cond is not None:
            ref_mel = cond["ref_mel"]
            spk = cond["spk"]
        else:
            ref_mel = jnp.zeros((b, self.REF_MEL_FRAMES, cfg.mel_dim),
                                jnp.float32)
            spk = jnp.zeros((b, cfg.enc_emb_dim), jnp.float32)
        code = jnp.clip(token_ids, 0, cfg.num_embeds - 1)
        mel = sample(params["dit"], cfg, code, ref_mel, spk,
                     num_steps=self.num_steps,
                     guidance_scale=self.guidance_scale,
                     sway_coefficient=self.sway, solver=self.solver,
                     initial_noise=jax.random.normal(
                         jax.random.PRNGKey(0),
                         (b, code.shape[1] * cfg.repeats, cfg.mel_dim)))
        wav = bv.forward(params["bigvgan"], self.bv_cfg, mel)
        return {"audio": wav}

    def slice_output(self, outputs, row: int, in_len: int):
        up = self.cfg.repeats * self.bv_cfg.total_upsample
        return {"audio": np.asarray(outputs["audio"][row, : in_len * up])}


def load_token2wav(model_dir: str, dtype="float32", num_steps: int = 10,
                   guidance_scale: float = 0.5):
    """model_factory for real-weight Qwen2.5-Omni token2wav stages:
    (params, model, eos)."""
    from vllm_omni_tpu.models.qwen2_5_omni import bigvgan as bv

    jdtype = jnp.dtype(dtype) if isinstance(dtype, str) else dtype
    dit_params, dit_cfg = load_dit(model_dir, dtype=jdtype)
    bv_params, bv_cfg = bv.load_bigvgan(model_dir, dtype=jdtype)
    model = Token2WavRealModel(dit_cfg, bv_cfg, num_steps=num_steps,
                               guidance_scale=guidance_scale)
    return {"dit": dit_params, "bigvgan": bv_params}, model, None
