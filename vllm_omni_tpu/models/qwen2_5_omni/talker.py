"""Qwen2.5-Omni talker: dense AR codec-token LM (stage 1).

Reference: vllm_omni/model_executor/models/qwen2_5_omni/
qwen2_5_omni_talker.py — a smaller dense Qwen2 LM consuming the thinker's
hidden states (projected into its own width) and emitting speech-codec
tokens for token2wav.  Same handoff as the Qwen3 talker: thinker states
ride prompt_embeds through the transformer's ``embed_proj``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from vllm_omni_tpu.models.common import nn
from vllm_omni_tpu.models.common.transformer import (
    TransformerConfig,
    init_params,
)

# Real Qwen2.5-Omni talker geometry: hidden 896, 24 layers (HF config).
QWEN2_5_OMNI_TALKER_7B = TransformerConfig(
    vocab_size=8192 + 8,  # codec codes + specials
    hidden_size=896,
    num_layers=24,
    num_heads=12,
    num_kv_heads=2,
    head_dim=128,
    intermediate_size=4864,
    attention_bias=True,
    qk_norm=False,
)


def tiny_config(codec_vocab: int = 64) -> TransformerConfig:
    return TransformerConfig(
        vocab_size=codec_vocab,
        hidden_size=64,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        head_dim=16,
        intermediate_size=128,
        attention_bias=True,
        qk_norm=False,
    )


def init_talker_params(key, cfg: TransformerConfig, thinker_hidden: int,
                       dtype=jnp.float32):
    params = init_params(key, cfg, dtype)
    params["embed_proj"] = nn.linear_init(
        jax.random.fold_in(key, 77), thinker_hidden, cfg.hidden_size,
        bias=False, dtype=dtype,
    )
    return params


def tiny_factory():
    """model_factory: tiny dense talker consuming 64-wide thinker states."""
    cfg = tiny_config()
    params = init_talker_params(jax.random.PRNGKey(11), cfg,
                                thinker_hidden=64)
    return params, cfg, None
