"""Qwen2.5-Omni BigVGAN vocoder (mel spectrogram -> waveform).

Checkpoint-schema implementation of the transformers
``Qwen2_5OmniToken2WavBigVGANModel`` (reference:
vllm_omni/model_executor/models/qwen2_5_omni/qwen2_5_omni_token2wav.py
serves it as the second half of the token2wav stage): log-mel is
re-normalized to dB scale, a conv stem lifts it to
``upsample_initial_channel``, six transposed-conv stages upsample 240x
to 24 kHz, each stage averaging three AMP residual blocks (dilated
convs with ANTI-ALIASED SnakeBeta activations — 2x Kaiser-sinc
upsample, snake, 2x downsample), and a final conv + clamp emits the
waveform.

TPU-first: NWC layout, every conv an explicit-padding ``lax`` conv; the
Kaiser-sinc resampling filters are host-precomputed constants (numpy)
closed over by the jitted forward, and the anti-aliased activation's
up/down pair are depthwise convs the MXU pipeline handles like any
other channel-last conv.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.models.common import vocoder as vk

logger = init_logger(__name__)

_PRECISION = jax.lax.Precision.HIGHEST


@dataclass(frozen=True)
class BigVGANConfig:
    """Mirrors transformers ``Qwen2_5OmniBigVGANConfig``; the
    ``tts_v1`` variant covers the Qwen3-TTS 25 Hz tokenizer's BigVGAN
    (reference modeling_qwen3_tts_tokenizer_v1.py:865-1071): conv stem
    kernel 5, and CHAINED AMP blocks — causal convs1, the first two
    upsample stages add a pre-conv + pre-activation and causal convs2,
    with per-unit outputs accumulating onto the block input."""
    mel_dim: int = 80
    upsample_initial_channel: int = 1536
    resblock_kernel_sizes: tuple = (3, 7, 11)
    resblock_dilation_sizes: tuple = ((1, 3, 5), (1, 3, 5), (1, 3, 5))
    upsample_rates: tuple = (5, 3, 2, 2, 2, 2)
    upsample_kernel_sizes: tuple = (11, 7, 4, 4, 4, 4)
    variant: str = "qwen2_5"      # | "tts_v1"

    @property
    def conv_pre_kernel(self) -> int:
        return 5 if self.variant == "tts_v1" else 7

    def causal_type(self, layer_idx: int) -> str:
        """V1 AMP flavour per upsample stage ("2" adds pre conv/act and
        causal convs2)."""
        return "2" if layer_idx <= 1 else "1"

    @property
    def total_upsample(self) -> int:
        return int(math.prod(self.upsample_rates))

    @staticmethod
    def tiny() -> "BigVGANConfig":
        return BigVGANConfig(
            mel_dim=8, upsample_initial_channel=16,
            resblock_kernel_sizes=(3,), resblock_dilation_sizes=((1, 3),),
            upsample_rates=(2, 2), upsample_kernel_sizes=(4, 4),
        )

    @staticmethod
    def from_hf(d: dict, variant: str = "qwen2_5") -> "BigVGANConfig":
        return BigVGANConfig(
            variant=variant,
            mel_dim=d.get("mel_dim", 80),
            upsample_initial_channel=d.get("upsample_initial_channel",
                                           1536),
            resblock_kernel_sizes=tuple(d.get("resblock_kernel_sizes",
                                              (3, 7, 11))),
            resblock_dilation_sizes=tuple(
                tuple(x) for x in d.get("resblock_dilation_sizes",
                                        ((1, 3, 5),) * 3)),
            upsample_rates=tuple(d.get("upsample_rates",
                                       (5, 3, 2, 2, 2, 2))),
            upsample_kernel_sizes=tuple(d.get("upsample_kernel_sizes",
                                              (11, 7, 4, 4, 4, 4))),
        )


# --------------------------------------------------- kaiser-sinc filters
def kaiser_sinc_filter(cutoff: float, half_width: float,
                       kernel_size: int) -> np.ndarray:
    """Kaiser-windowed sinc low-pass, matching the HF reference
    (kaiser_sinc_filter1d) bit-for-bit in fp32."""
    even = kernel_size % 2 == 0
    half = kernel_size // 2
    delta_f = 4 * half_width
    atten = 2.285 * (half - 1) * math.pi * delta_f + 7.95
    if atten > 50.0:
        beta = 0.1102 * (atten - 8.7)
    elif atten >= 21.0:
        beta = 0.5842 * (atten - 21) ** 0.4 + 0.07886 * (atten - 21.0)
    else:
        beta = 0.0
    window = np.kaiser(kernel_size, beta).astype(np.float32)
    if even:
        t = np.arange(-half, half, dtype=np.float32) + 0.5
    else:
        t = np.arange(kernel_size, dtype=np.float32) - half
    if cutoff == 0:
        return np.zeros(kernel_size, np.float32)
    filt = 2 * cutoff * window * np.sinc(2 * cutoff * t)
    return (filt / filt.sum()).astype(np.float32)


def _aa_filters(ratio: int = 2, kernel_size: int = 12):
    # HOST numpy constants: caching jnp arrays here would capture a
    # tracer when the first call happens inside a jit trace and leak it
    # into later traces (UnexpectedTracerError)
    up = kaiser_sinc_filter(0.5 / ratio, 0.6 / ratio, kernel_size)
    down = kaiser_sinc_filter(0.5 / ratio, 0.6 / ratio, kernel_size)
    return up, down


_UP_FILTER, _DOWN_FILTER = None, None


def _filters():
    global _UP_FILTER, _DOWN_FILTER
    if _UP_FILTER is None:
        _UP_FILTER, _DOWN_FILTER = _aa_filters()
    return _UP_FILTER, _DOWN_FILTER


def _aa_snake(p, x):
    """Anti-aliased SnakeBeta (TorchActivation1d): replicate-pad, 2x
    Kaiser-sinc upsample (depthwise transpose conv), snake, replicate-
    pad, 2x downsample.  x: [B, T, C]."""
    upf, downf = _filters()
    ch = x.shape[-1]
    k, ratio = 12, 2
    pad = k // ratio - 1
    pad_left = pad * ratio + (k - ratio) // 2
    pad_right = pad * ratio + (k - ratio + 1) // 2
    h = jnp.pad(x, ((0, 0), (pad, pad), (0, 0)), mode="edge")
    kern = jnp.broadcast_to(upf[:, None, None], (k, 1, ch))
    # depthwise transposed conv as an lhs-dilated conv (conv_transpose
    # has no feature_group_count); the Kaiser-sinc filter is symmetric
    # so the kernel flip is a no-op
    h = ratio * jax.lax.conv_general_dilated(
        h.astype(jnp.float32), kern, window_strides=(1,),
        padding=((k - 1, k - 1),), lhs_dilation=(ratio,),
        dimension_numbers=("NWC", "WIO", "NWC"),
        feature_group_count=ch, precision=_PRECISION)
    h = h[:, pad_left: h.shape[1] - pad_right]
    h = vk.snake(p, h)
    pad_left_d = k // 2 - 1  # even kernel
    pad_right_d = k // 2
    h = jnp.pad(h, ((0, 0), (pad_left_d, pad_right_d), (0, 0)),
                mode="edge")
    kern = jnp.broadcast_to(downf[:, None, None], (k, 1, ch))
    h = jax.lax.conv_general_dilated(
        h, kern, window_strides=(ratio,), padding="VALID",
        dimension_numbers=("NWC", "WIO", "NWC"),
        feature_group_count=ch, precision=_PRECISION)
    return h.astype(x.dtype)


# -------------------------------------------------------------- layers
def _conv(p, x, k: int, pad: int, dilation: int = 1):
    """Symmetric-zero-pad conv, NWC (torch Conv1d padding=pad)."""
    y = jax.lax.conv_general_dilated(
        jnp.pad(x, ((0, 0), (pad, pad), (0, 0))),
        p["w"].astype(x.dtype), window_strides=(1,), padding="VALID",
        rhs_dilation=(dilation,),
        dimension_numbers=("NWC", "WIO", "NWC"), precision=_PRECISION)
    return y + p["b"].astype(x.dtype) if "b" in p else y


def _amp_block(p, x, k: int, dilations):
    """AMPBlock: per dilation — aa-snake, dilated conv, aa-snake,
    conv(d=1) — with residuals."""
    acts = p["acts"]
    for i, d in enumerate(dilations):
        res = x
        h = _aa_snake(acts[2 * i], x)
        h = _conv(p["convs1"][i], h, k, (k * d - d) // 2, dilation=d)
        h = _aa_snake(acts[2 * i + 1], h)
        h = _conv(p["convs2"][i], h, k, (k - 1) // 2)
        x = res + h
    return x


def _causal_conv(p, x, k: int, dilation: int = 1):
    """Left-pad-only conv (V1 CausalConv1d)."""
    pad = dilation * (k - 1)
    y = jax.lax.conv_general_dilated(
        jnp.pad(x, ((0, 0), (pad, 0), (0, 0))),
        p["w"].astype(x.dtype), window_strides=(1,), padding="VALID",
        rhs_dilation=(dilation,),
        dimension_numbers=("NWC", "WIO", "NWC"), precision=_PRECISION)
    return y + p["b"].astype(x.dtype)


def _amp_block_v1(p, x, k: int, dilations, causal_type: str):
    """V1 chained AMPBlock (modeling_qwen3_tts_tokenizer_v1.py:979-991):
    hidden CHAINS through the units while each unit's output accumulates
    onto the block input; convs1 causal, convs2 causal only for
    causal_type "2", which also runs a pre conv + pre aa-snake."""
    acts = p["acts"]
    if causal_type == "2":
        h = _conv(p["pre_conv"], x, k, (k - 1) // 2)
        h = _aa_snake(p["pre_act"], h)
    else:
        h = x
    for i, d in enumerate(dilations):
        h = _aa_snake(acts[2 * i], h)
        h = _causal_conv(p["convs1"][i], h, k, dilation=d)
        h = _aa_snake(acts[2 * i + 1], h)
        if causal_type == "2":
            h = _causal_conv(p["convs2"][i], h, k)
        else:
            h = _conv(p["convs2"][i], h, k, (k - 1) // 2)
        x = x + h
    return x


def init_params(key, cfg: BigVGANConfig, dtype=jnp.float32):
    from vllm_omni_tpu.models.common import nn

    ki = iter(jax.random.split(key, 256))
    c0 = cfg.upsample_initial_channel
    kp = cfg.conv_pre_kernel
    p = {"conv_pre": {"w": nn.conv1d_init(next(ki), cfg.mel_dim, c0, kp,
                                          dtype=dtype)["w"],
                      "b": jnp.zeros((c0,), dtype)},
         "ups": [], "resblocks": []}
    for i, (r, k) in enumerate(zip(cfg.upsample_rates,
                                   cfg.upsample_kernel_sizes)):
        cin, cout = c0 // (2 ** i), c0 // (2 ** (i + 1))
        p["ups"].append(vk.tconv_init(next(ki), cin, cout, k, dtype))
        for ks, dils in zip(cfg.resblock_kernel_sizes,
                            cfg.resblock_dilation_sizes):
            blk = {"convs1": [], "convs2": [], "acts": []}
            if cfg.variant == "tts_v1" and cfg.causal_type(i) == "2":
                blk["pre_conv"] = {
                    "w": nn.conv1d_init(next(ki), cout, cout, ks,
                                        dtype=dtype)["w"],
                    "b": jnp.zeros((cout,), dtype)}
                blk["pre_act"] = vk.snake_init(cout, dtype)
            for d in dils:
                blk["convs1"].append(
                    {"w": nn.conv1d_init(next(ki), cout, cout, ks,
                                         dtype=dtype)["w"],
                     "b": jnp.zeros((cout,), dtype)})
                blk["convs2"].append(
                    {"w": nn.conv1d_init(next(ki), cout, cout, ks,
                                         dtype=dtype)["w"],
                     "b": jnp.zeros((cout,), dtype)})
                blk["acts"].extend([vk.snake_init(cout, dtype),
                                    vk.snake_init(cout, dtype)])
            p["resblocks"].append(blk)
    out_ch = c0 // (2 ** len(cfg.upsample_rates))
    p["act_post"] = vk.snake_init(out_ch, dtype)
    p["conv_post"] = {"w": nn.conv1d_init(next(ki), out_ch, 1, 7,
                                          dtype=dtype)["w"]}
    return p


def process_mel(mel):
    """log-mel -> clamped dB spectrum (reference
    process_mel_spectrogram: exp, amplitude->dB w/ -115 floor, -20,
    normalize to [-1, 1])."""
    amp = jnp.exp(mel.astype(jnp.float32))
    min_level = math.exp(-115 / 20.0 * math.log(10))
    db = 20.0 * jnp.log10(jnp.clip(amp, min_level, None)) - 20.0
    return jnp.clip(2.0 * ((db + 115.0) / 115.0) - 1.0, -1.0, 1.0)


def forward(params, cfg: BigVGANConfig, mel):
    """mel [B, T, mel_dim] (log scale) -> waveform [B, T*upsample]."""
    x = process_mel(mel).astype(mel.dtype)
    kp = cfg.conv_pre_kernel
    x = _conv(params["conv_pre"], x, kp, (kp - 1) // 2)
    n_res = len(cfg.resblock_kernel_sizes)
    for i, (r, k) in enumerate(zip(cfg.upsample_rates,
                                   cfg.upsample_kernel_sizes)):
        # torch ConvTranspose1d padding=(k-r)//2 trims both sides
        y = jax.lax.conv_transpose(
            x, params["ups"][i]["w"].astype(x.dtype), strides=(r,),
            padding="VALID", dimension_numbers=("NWC", "WIO", "NWC"),
            transpose_kernel=True, precision=_PRECISION)
        trim = (k - r) // 2
        if trim:
            y = y[:, trim: y.shape[1] - trim]
        x = y + params["ups"][i]["b"].astype(x.dtype)
        acc = 0.0
        for j, (ks, dils) in enumerate(zip(cfg.resblock_kernel_sizes,
                                           cfg.resblock_dilation_sizes)):
            blk = params["resblocks"][i * n_res + j]
            if cfg.variant == "tts_v1":
                acc = acc + _amp_block_v1(blk, x, ks, dils,
                                          cfg.causal_type(i))
            else:
                acc = acc + _amp_block(blk, x, ks, dils)
        x = acc / n_res
    x = _aa_snake(params["act_post"], x)
    x = _conv(params["conv_post"], x, 7, 3)
    return jnp.clip(x[..., 0], -1.0, 1.0)


# ------------------------------------------------------- checkpoint load
def hf_flat_map(cfg: BigVGANConfig,
                prefix: str = "token2wav.code2wav_bigvgan_model.") -> dict:
    m: dict[str, tuple] = {}
    m[f"{prefix}conv_pre.weight"] = ("conv_pre", "w")
    m[f"{prefix}conv_pre.bias"] = ("conv_pre", "b")
    n_res = len(cfg.resblock_kernel_sizes)
    for i in range(len(cfg.upsample_rates)):
        m[f"{prefix}ups.{i}.0.weight"] = ("ups", i, "w")
        m[f"{prefix}ups.{i}.0.bias"] = ("ups", i, "b")
        for j, dils in enumerate([cfg.resblock_dilation_sizes[q]
                                  for q in range(n_res)]):
            rb = f"{prefix}resblocks.{i * n_res + j}"
            tgt = ("resblocks", i * n_res + j)
            if cfg.variant == "tts_v1" and cfg.causal_type(i) == "2":
                m[f"{rb}.pre_conv.weight"] = tgt + ("pre_conv", "w")
                m[f"{rb}.pre_conv.bias"] = tgt + ("pre_conv", "b")
                m[f"{rb}.pre_act.act.alpha"] = tgt + ("pre_act", "alpha")
                m[f"{rb}.pre_act.act.beta"] = tgt + ("pre_act", "beta")
            for di in range(len(dils)):
                for cv in ("convs1", "convs2"):
                    m[f"{rb}.{cv}.{di}.weight"] = tgt + (cv, di, "w")
                    m[f"{rb}.{cv}.{di}.bias"] = tgt + (cv, di, "b")
            for a in range(2 * len(dils)):
                m[f"{rb}.activations.{a}.act.alpha"] = \
                    tgt + ("acts", a, "alpha")
                m[f"{rb}.activations.{a}.act.beta"] = \
                    tgt + ("acts", a, "beta")
    m[f"{prefix}activation_post.act.alpha"] = ("act_post", "alpha")
    m[f"{prefix}activation_post.act.beta"] = ("act_post", "beta")
    m[f"{prefix}conv_post.weight"] = ("conv_post", "w")
    return m


def hf_transform(name: str, arr):
    """Conv1d [out, in, k] -> [k, in, out]; ConvTranspose1d (the ups)
    [in, out, k] -> [k, out, in] (transpose_kernel layout) — both
    transpose(2, 1, 0)."""
    if arr.ndim == 3:
        return arr.transpose(2, 1, 0)
    return arr


def load_bigvgan(model_dir: str, cfg: BigVGANConfig = None,
                 dtype=jnp.float32,
                 prefix: str = "token2wav.code2wav_bigvgan_model."):
    import json
    import os

    from vllm_omni_tpu.model_loader.safetensors_loader import (
        load_checkpoint_tree,
    )

    if cfg is None:
        cfg_path = os.path.join(model_dir, "config.json")
        d = {}
        if os.path.isfile(cfg_path):
            with open(cfg_path) as f:
                d = (json.load(f).get("token2wav_config", {})
                     .get("bigvgan_config", {}))
        cfg = BigVGANConfig.from_hf(d)
    shapes = jax.eval_shape(
        lambda: init_params(jax.random.PRNGKey(0), cfg, jnp.float32))
    tree = jax.tree.map(lambda t: np.zeros(t.shape, np.float32), shapes)
    flat = hf_flat_map(cfg, prefix)
    n, _ = load_checkpoint_tree(
        model_dir, flat.get, tree, dtype=np.float32,
        transform=hf_transform, name_filter=lambda nm: nm in flat,
    )
    n_leaves = len(jax.tree.leaves(tree))
    if n != n_leaves:
        raise ValueError(
            f"{model_dir} covered {n}/{n_leaves} BigVGAN weights")
    tree = jax.tree.map(
        lambda a: jnp.asarray(a, dtype), tree)
    return tree, cfg
