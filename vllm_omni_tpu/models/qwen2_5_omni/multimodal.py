"""Qwen2.5-Omni thinker multimodal front end (real-weight towers).

The qwen3_omni ThinkerMMProcessor machinery (placeholder expansion,
embeds scatter, MRoPE) reused over the CHECKPOINT-SCHEMA towers
(audio_tower.py / vision_tower.py): images flatten to the HF
Qwen2VL patch order (CLIP-normalized, temporal-repeated,
merge-interleaved — verified against the transformers image processor)
and run the windowed ViT; waveforms become 128-bin log-mels through the
chunked whisper-style encoder.  Reference: the thinker's multimodal
path in vllm_omni/model_executor/models/qwen2_5_omni/.
"""

from __future__ import annotations

import numpy as np

from vllm_omni_tpu.models.qwen2_5_omni import audio_tower as at
from vllm_omni_tpu.models.qwen2_5_omni import vision_tower as vt
from vllm_omni_tpu.models.qwen3_omni.multimodal import ThinkerMMProcessor

# CLIP normalization the HF Qwen2VL image processor applies
_MEAN = np.array([0.48145466, 0.4578275, 0.40821073], np.float32)
_STD = np.array([0.26862954, 0.26130258, 0.27577711], np.float32)
# HF Qwen2VLImageProcessor pixel budgets scale with the merge factor:
# at the real 28-pixel factor they are 56*56 and 28*28*1280
def _default_budget(factor: int) -> tuple[int, int]:
    return 4 * factor * factor, 1280 * factor * factor


def smart_resize(h: int, w: int, factor: int,
                 min_pixels: int = None,
                 max_pixels: int = None) -> tuple[int, int]:
    """HF Qwen2VL smart_resize: round to the nearest factor multiple,
    then scale into the [min_pixels, max_pixels] budget preserving
    aspect — bounds the image token count the way the checkpoint's
    training-time preprocessing did."""
    import math

    d_min, d_max = _default_budget(factor)
    min_pixels = d_min if min_pixels is None else min_pixels
    max_pixels = d_max if max_pixels is None else max_pixels
    if max(h, w) / min(h, w) > 200:
        raise ValueError("aspect ratio beyond 200 is unsupported")
    hb = max(factor, round(h / factor) * factor)
    wb = max(factor, round(w / factor) * factor)
    if hb * wb > max_pixels:
        beta = math.sqrt((h * w) / max_pixels)
        hb = max(factor, math.floor(h / beta / factor) * factor)
        wb = max(factor, math.floor(w / beta / factor) * factor)
    elif hb * wb < min_pixels:
        beta = math.sqrt(min_pixels / (h * w))
        hb = math.ceil(h * beta / factor) * factor
        wb = math.ceil(w * beta / factor) * factor
    return hb, wb


def flatten_image(img: np.ndarray, cfg: vt.VisionTowerConfig,
                  max_pixels: int = None):
    """[H, W, 3] (uint8 or [0, 1] float) -> (pixels [S, patch_dim],
    (t, h, w) patch grid) in the HF Qwen2VLImageProcessor order:
    smart-resize into the pixel budget (bicubic, like HF), CLIP-
    normalize, repeat the frame to temporal_patch_size, and
    merge-interleave the patch grid."""
    img = np.asarray(img)
    if img.dtype == np.uint8:
        img = img.astype(np.float32) / 255.0
    img = img.astype(np.float32)
    ps, sm, tps = cfg.patch_size, cfg.spatial_merge_size, \
        cfg.temporal_patch_size
    mult = ps * sm
    h, w = smart_resize(img.shape[0], img.shape[1], mult,
                        max_pixels=max_pixels)
    if (h, w) != img.shape[:2]:
        import jax
        import jax.numpy as jnp

        img = np.asarray(jax.image.resize(jnp.asarray(img), (h, w, 3),
                                          "cubic", antialias=True))
    img = (img - _MEAN) / _STD
    chw = img.transpose(2, 0, 1)                    # [C, H, W]
    frames = np.repeat(chw[None], tps, axis=0)      # [tps, C, H, W]
    gh, gw = h // ps, w // ps
    x = frames.reshape(1, tps, 3, gh // sm, sm, ps, gw // sm, sm, ps)
    x = x.transpose(0, 3, 6, 4, 7, 2, 1, 5, 8)
    return x.reshape(gh * gw, 3 * tps * ps * ps), (1, gh, gw)


class Qwen25ThinkerMMProcessor(ThinkerMMProcessor):
    """Placeholder/MRoPE machinery from the shared processor; encoding
    through the checkpoint towers."""

    def __init__(self, embed_table, image_token_id: int,
                 audio_token_id: int, at_params, at_cfg: at.AudioTowerConfig,
                 vt_params, vt_cfg: vt.VisionTowerConfig,
                 sample_rate: int = 16000):
        super().__init__(embed_table, image_token_id, audio_token_id,
                         vision_params=None, vision_cfg=None,
                         audio_params=None, audio_cfg=None,
                         sample_rate=sample_rate)
        self.at_params, self.at_cfg = at_params, at_cfg
        self.vt_params, self.vt_cfg = vt_params, vt_cfg
        import jax

        # shape-keyed jit like the parent's encoders: cfg/grid are
        # static, so each (grid, mel-length) compiles once and caches
        self._vt_jit = jax.jit(vt.forward, static_argnums=(1, 3))
        self._at_jit = jax.jit(at.forward, static_argnums=(1,))

    def _encode_image(self, img: np.ndarray):
        pixels, grid = flatten_image(img, self.vt_cfg)
        import jax.numpy as jnp

        feats = self._vt_jit(self.vt_params, self.vt_cfg,
                             jnp.asarray(pixels), grid)
        t, gh, gw = grid
        sm = self.vt_cfg.spatial_merge_size
        # MRoPE walks the MERGED (llm) grid
        return np.asarray(feats), (t, gh // sm, gw // sm), None

    def _encode_audio(self, aud: np.ndarray):
        from vllm_omni_tpu.utils.audio import bucket_waveform_to_mel

        aud = bucket_waveform_to_mel(
            aud, sr=self.sample_rate, n_mels=self.at_cfg.num_mel_bins,
            max_frames=2 * self.at_cfg.max_source_positions)
        import jax.numpy as jnp

        feats = self._at_jit(self.at_params, self.at_cfg,
                             jnp.asarray(aud))
        return np.asarray(feats), (feats.shape[0],), None


def build_real_processor(params, model_cfg, model_dir: str,
                         image_token_id: int = 151655,
                         audio_token_id: int = 151646,
                         dtype="float32", **_):
    """mm_processor factory for real-weight Qwen2.5-Omni thinker stages:
    loads both towers from the composite checkpoint (default placeholder
    ids are the HF thinker config's image/audio token indexes)."""
    import jax.numpy as jnp

    jdtype = jnp.dtype(dtype) if isinstance(dtype, str) else dtype
    at_params, at_cfg = at.load_audio_tower(model_dir, dtype=jdtype)
    vt_params, vt_cfg = vt.load_vision_tower(model_dir, dtype=jdtype)
    return Qwen25ThinkerMMProcessor(
        embed_table=np.asarray(params["embed"]["w"]),
        image_token_id=image_token_id,
        audio_token_id=audio_token_id,
        at_params=at_params, at_cfg=at_cfg,
        vt_params=vt_params, vt_cfg=vt_cfg,
    )


def build_tiny_processor(params, model_cfg, **_):
    """Random tiny towers at the real schema (placeholder ids at the top
    of the tiny vocab, matching the shared tiny convention)."""
    import jax
    import jax.numpy as jnp

    hidden = model_cfg.hidden_size
    import dataclasses

    at_cfg = dataclasses.replace(at.AudioTowerConfig.tiny(),
                                 output_dim=hidden)
    vt_cfg = dataclasses.replace(vt.VisionTowerConfig.tiny(),
                                 out_hidden_size=hidden)
    vocab = model_cfg.vocab_size
    return Qwen25ThinkerMMProcessor(
        embed_table=np.asarray(params["embed"]["w"]),
        image_token_id=vocab - 3,
        audio_token_id=vocab - 2,
        at_params=at.init_params(jax.random.PRNGKey(31), at_cfg,
                                 jnp.float32),
        at_cfg=at_cfg,
        vt_params=vt.init_params(jax.random.PRNGKey(32), vt_cfg,
                                 jnp.float32),
        vt_cfg=vt_cfg,
    )
