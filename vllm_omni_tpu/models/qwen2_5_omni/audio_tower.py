"""Checkpoint-schema Qwen2.5-Omni audio tower (real-weight path).

Structural match for the HF ``Qwen2_5OmniAudioEncoder`` (transformers
qwen2_5_omni/modeling_qwen2_5_omni.py; the reference thinker consumes
the same tower): mel frames split into chunks of ``2 * n_window``, each
chunk runs gelu(conv1) masked then gelu(conv2, stride 2), whisper-style
sinusoid positions RESTART per chunk, the valid tokens run a pre-LN
transformer with BLOCK-DIAGONAL per-chunk attention, and the head is
avg-pool(2) -> ln_post -> proj to ``output_dim``.  The 2-row
``audio_bos_eos_token`` table the thinker wraps audio segments with is
loaded alongside.

TPU-first (same stance as the Qwen3 AuT tower): the reference splits
into a ragged python list and boolean-indexes — dynamic shapes XLA
cannot tile.  Here the clip zero-pads to whole chunks, ALL chunks
convolve as ONE batched static conv, and validity is a host-computed
static mask: attention runs over the padded token grid with an additive
block-diagonal bias, and the valid-token gather is a static index take.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.models.common import nn

logger = init_logger(__name__)

_PRECISION = jax.lax.Precision.HIGHEST


@dataclass(frozen=True)
class AudioTowerConfig:
    num_mel_bins: int = 128
    d_model: int = 1280
    encoder_layers: int = 32
    num_heads: int = 20
    ffn_dim: int = 5120
    n_window: int = 50
    output_dim: int = 3584
    max_source_positions: int = 1500
    eps: float = 1e-5

    @property
    def chunk_frames(self) -> int:
        return 2 * self.n_window

    @staticmethod
    def tiny() -> "AudioTowerConfig":
        return AudioTowerConfig(num_mel_bins=16, d_model=32,
                                encoder_layers=2, num_heads=4,
                                ffn_dim=64, n_window=4, output_dim=24,
                                max_source_positions=64)

    @staticmethod
    def from_hf(d: dict) -> "AudioTowerConfig":
        return AudioTowerConfig(
            num_mel_bins=d.get("num_mel_bins", 128),
            d_model=d.get("d_model", 1280),
            encoder_layers=d.get("encoder_layers", 32),
            num_heads=d.get("encoder_attention_heads", 20),
            ffn_dim=d.get("encoder_ffn_dim", 5120),
            n_window=d.get("n_window", 50),
            output_dim=d.get("output_dim", 3584),
            max_source_positions=d.get("max_source_positions", 1500),
        )


sinusoid_positions = nn.sinusoid_positions


def init_params(key, cfg: AudioTowerConfig, dtype=jnp.float32):
    ki = iter(jax.random.split(key, 8 + 8 * cfg.encoder_layers))
    d = cfg.d_model
    p = {
        "conv1": {"w": nn.conv1d_init(next(ki), cfg.num_mel_bins, d, 3,
                                      dtype=dtype)["w"],
                  "b": jnp.zeros((d,), dtype)},
        "conv2": {"w": nn.conv1d_init(next(ki), d, d, 3,
                                      dtype=dtype)["w"],
                  "b": jnp.zeros((d,), dtype)},
        "bos_eos": nn.embedding_init(next(ki), 2, cfg.output_dim, dtype),
        "ln_post": nn.layernorm_init(d, dtype=dtype),
        "proj": nn.linear_init(next(ki), d, cfg.output_dim, dtype=dtype),
        "layers": [],
    }
    for _ in range(cfg.encoder_layers):
        p["layers"].append({
            "attn_norm": nn.layernorm_init(d, dtype=dtype),
            "q_proj": nn.linear_init(next(ki), d, d, dtype=dtype),
            # whisper-style: k_proj carries no bias
            "k_proj": nn.linear_init(next(ki), d, d, bias=False,
                                     dtype=dtype),
            "v_proj": nn.linear_init(next(ki), d, d, dtype=dtype),
            "out_proj": nn.linear_init(next(ki), d, d, dtype=dtype),
            "final_norm": nn.layernorm_init(d, dtype=dtype),
            "fc1": nn.linear_init(next(ki), d, cfg.ffn_dim, dtype=dtype),
            "fc2": nn.linear_init(next(ki), cfg.ffn_dim, d, dtype=dtype),
        })
    return p


def _conv(p, x, stride: int = 1):
    y = jax.lax.conv_general_dilated(
        jnp.pad(x, ((0, 0), (1, 1), (0, 0))),
        p["w"].astype(x.dtype), window_strides=(stride,),
        padding="VALID", dimension_numbers=("NWC", "WIO", "NWC"),
        precision=_PRECISION)
    return y + p["b"].astype(x.dtype)


def forward(params, cfg: AudioTowerConfig, mel: jax.Array) -> jax.Array:
    """One clip: mel [T, num_mel_bins] -> audio tokens
    [ceil(ceil(T/2)/2)... , output_dim] (conv stride 2, then avg-pool 2;
    chunked exactly like the reference)."""
    t = int(mel.shape[0])
    if t == 0:
        raise ValueError("empty mel clip: audio towers need >= 1 frame")
    chunk = cfg.chunk_frames
    nc = -(-t // chunk)
    lens = np.full(nc, chunk, np.int64)
    tail = t % chunk
    if tail:
        lens[-1] = tail
    pad = nc * chunk - t
    x = jnp.pad(mel, ((0, pad), (0, 0))).reshape(nc, chunk, -1)

    # gelu(conv1) masked to each chunk's true length, then strided conv2
    mask1 = (np.arange(chunk)[None, :] < lens[:, None])
    h = jax.nn.gelu(_conv(params["conv1"], x),
                    approximate=False) * jnp.asarray(
        mask1[..., None], x.dtype)
    h = jax.nn.gelu(_conv(params["conv2"], h, stride=2),
                    approximate=False)          # [nc, t2, d]
    t2 = h.shape[1]
    pos = sinusoid_positions(cfg.max_source_positions, cfg.d_model)
    h = h + jnp.asarray(pos[None, :t2], h.dtype)

    # valid tokens per chunk after the stride-2 conv
    lens2 = (lens - 1) // 2 + 1
    valid = (np.arange(t2)[None, :] < lens2[:, None])   # [nc, t2]
    n = nc * t2
    flat_valid = valid.reshape(-1)
    chunk_of = np.repeat(np.arange(nc), t2)
    same = (chunk_of[:, None] == chunk_of[None, :]) \
        & flat_valid[None, :] & flat_valid[:, None]
    bias = jnp.asarray(np.where(same, 0.0, -1e30), jnp.float32)

    x = h.reshape(n, -1)
    heads = cfg.num_heads
    hd = cfg.d_model // heads
    scale = 1.0 / math.sqrt(hd)
    for lp in params["layers"]:
        hh = nn.layernorm(lp["attn_norm"], x, eps=cfg.eps)
        q = nn.linear(lp["q_proj"], hh).reshape(n, heads, hd)
        k = nn.linear(lp["k_proj"], hh).reshape(n, heads, hd)
        v = nn.linear(lp["v_proj"], hh).reshape(n, heads, hd)
        s = jnp.einsum("qhd,khd->hqk", q.astype(jnp.float32),
                       k.astype(jnp.float32),
                       precision=_PRECISION) * scale
        a = jax.nn.softmax(s + bias[None], axis=-1).astype(x.dtype)
        o = jnp.einsum("hqk,khd->qhd", a, v, precision=_PRECISION)
        x = x + nn.linear(lp["out_proj"], o.reshape(n, -1))
        hh = nn.layernorm(lp["final_norm"], x, eps=cfg.eps)
        hh = nn.linear(lp["fc2"],
                       jax.nn.gelu(nn.linear(lp["fc1"], hh),
                                   approximate=False))
        x = x + hh

    # gather the valid tokens (static host-side indices), then the head:
    # avg-pool pairs over the WHOLE clip, ln_post, proj
    idx = np.nonzero(flat_valid)[0]
    tokens = jnp.take(x, jnp.asarray(idx), axis=0)    # [T2, d]
    t_valid = idx.shape[0]
    pairs = t_valid // 2
    pooled = tokens[: 2 * pairs].reshape(pairs, 2, -1).mean(axis=1)
    pooled = nn.layernorm(params["ln_post"], pooled, eps=cfg.eps)
    return nn.linear(params["proj"], pooled)


def bos_eos(params):
    """[2, output_dim] — the audio segment delimiter embeddings."""
    return params["bos_eos"]["w"]


# ------------------------------------------------------- checkpoint load
def hf_flat_map(cfg: AudioTowerConfig,
                prefix: str = "thinker.audio_tower.") -> dict:
    m: dict[str, tuple] = {}

    def lin(hf, path, bias=True):
        m[f"{hf}.weight"] = path + ("w",)
        if bias:
            m[f"{hf}.bias"] = path + ("b",)

    lin(f"{prefix}conv1", ("conv1",))
    lin(f"{prefix}conv2", ("conv2",))
    m[f"{prefix}audio_bos_eos_token.weight"] = ("bos_eos", "w")
    lin(f"{prefix}ln_post", ("ln_post",))
    lin(f"{prefix}proj", ("proj",))
    for i in range(cfg.encoder_layers):
        lp = f"{prefix}layers.{i}"
        tgt = ("layers", i)
        lin(f"{lp}.self_attn_layer_norm", tgt + ("attn_norm",))
        lin(f"{lp}.self_attn.q_proj", tgt + ("q_proj",))
        lin(f"{lp}.self_attn.k_proj", tgt + ("k_proj",), bias=False)
        lin(f"{lp}.self_attn.v_proj", tgt + ("v_proj",))
        lin(f"{lp}.self_attn.out_proj", tgt + ("out_proj",))
        lin(f"{lp}.final_layer_norm", tgt + ("final_norm",))
        lin(f"{lp}.fc1", tgt + ("fc1",))
        lin(f"{lp}.fc2", tgt + ("fc2",))
    return m


def hf_transform(name: str, arr):
    if arr.ndim == 3:  # Conv1d [out, in, k] -> [k, in, out]
        return arr.transpose(2, 1, 0)
    if arr.ndim == 2 and name.endswith("weight") \
            and "audio_bos_eos_token" not in name:
        return arr.T
    return arr


def load_audio_tower(model_dir: str, cfg: AudioTowerConfig = None,
                     dtype=jnp.float32,
                     prefix: str = "thinker.audio_tower."):
    import json
    import os

    from vllm_omni_tpu.model_loader.safetensors_loader import (
        load_checkpoint_tree,
    )

    if cfg is None:
        cfg_path = os.path.join(model_dir, "config.json")
        d = {}
        if os.path.isfile(cfg_path):
            with open(cfg_path) as f:
                d = (json.load(f).get("thinker_config", {})
                     .get("audio_config", {}))
        cfg = AudioTowerConfig.from_hf(d)
    shapes = jax.eval_shape(
        lambda: init_params(jax.random.PRNGKey(0), cfg, jnp.float32))
    tree = jax.tree.map(lambda t: np.zeros(t.shape, np.float32), shapes)
    flat = hf_flat_map(cfg, prefix)
    n, _ = load_checkpoint_tree(
        model_dir, flat.get, tree, dtype=np.float32,
        transform=hf_transform, name_filter=lambda nm: nm in flat,
    )
    n_leaves = len(jax.tree.leaves(tree))
    if n != n_leaves:
        raise ValueError(
            f"{model_dir} covered {n}/{n_leaves} audio-tower weights")
    tree = jax.tree.map(lambda a: jnp.asarray(a, dtype), tree)
    return tree, cfg
