"""Qwen2.5-Omni token2wav: flow-matching mel DiT + vocoder (stage 2).

Reference: vllm_omni/model_executor/models/qwen2_5_omni/
qwen2_5_omni_token2wav.py — a diffusion model *inside an AR stage*: codec
tokens condition a DiT that flow-matches mel frames, and a BigVGAN
vocoder renders the waveform.  Runs under the generation scheduler's
one-shot fast path like code2wav (SURVEY §2.8).

TPU-first: the whole flow loop is a jitted fori_loop (fixed step count —
one executable per shape bucket); the mel DiT is a small bidirectional
transformer over frames with the code conditioning concatenated
channel-wise; the vocoder is the NWC transposed-conv stack of
qwen3_omni/code2wav.  Deterministic: noise comes from a config seed, so
identical codec input reproduces identical audio.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.models.common import nn
from vllm_omni_tpu.ops import flash_attention, rms_norm


@dataclass(frozen=True)
class Token2WavConfig:
    codec_vocab: int = 8200
    mel_bins: int = 80
    frames_per_code: int = 2  # mel frames per codec token
    d_model: int = 256
    num_layers: int = 4
    num_heads: int = 4
    flow_steps: int = 10
    vocoder_channels: int = 256
    vocoder_upsample: tuple = (8, 5, 4)  # per mel frame
    kernel: int = 7
    noise_seed: int = 0

    @property
    def total_upsample(self) -> int:
        """Waveform samples per codec token."""
        return self.frames_per_code * math.prod(self.vocoder_upsample)

    @staticmethod
    def tiny() -> "Token2WavConfig":
        return Token2WavConfig(
            codec_vocab=64, mel_bins=8, frames_per_code=2, d_model=32,
            num_layers=2, num_heads=4, flow_steps=4,
            vocoder_channels=16, vocoder_upsample=(2,), kernel=3,
        )


def init_token2wav_params(key, cfg: Token2WavConfig, dtype=jnp.float32):
    keys = jax.random.split(key, cfg.num_layers + 8)
    d = cfg.d_model
    p = {
        "code_embed": nn.embedding_init(keys[0], cfg.codec_vocab, d, dtype),
        # DiT input: [mel ; cond] -> d_model
        "in_proj": nn.linear_init(keys[1], cfg.mel_bins + d, d, dtype=dtype),
        "time1": nn.linear_init(keys[2], 256, d, dtype=dtype),
        "time2": nn.linear_init(keys[3], d, d, dtype=dtype),
        "out_norm": nn.rmsnorm_init(d, dtype),
        "out_proj": nn.linear_init(keys[4], d, cfg.mel_bins, dtype=dtype),
        "blocks": [],
        # vocoder: mel -> channels -> upsample stack -> wave
        "voc_pre": nn.conv1d_init(keys[5], cfg.mel_bins,
                                  cfg.vocoder_channels, cfg.kernel,
                                  dtype=dtype),
        "voc_ups": [],
        "voc_post": None,
    }
    head_dim = d // cfg.num_heads
    del head_dim
    for i in range(cfg.num_layers):
        k = jax.random.split(keys[i + 6], 6)
        p["blocks"].append({
            "norm1": nn.rmsnorm_init(d, dtype),
            "qkv": nn.linear_init(k[0], d, 3 * d, dtype=dtype),
            "out": nn.linear_init(k[1], d, d, dtype=dtype),
            "norm2": nn.rmsnorm_init(d, dtype),
            "up": nn.linear_init(k[2], d, 4 * d, dtype=dtype),
            "down": nn.linear_init(k[3], 4 * d, d, dtype=dtype),
            "mod": nn.linear_init(k[4], d, 3 * d, dtype=dtype),
        })
    ch = cfg.vocoder_channels
    kv = jax.random.split(keys[-1], 2 * len(cfg.vocoder_upsample) + 1)
    for i, f in enumerate(cfg.vocoder_upsample):
        out_ch = max(4, ch // 2)
        p["voc_ups"].append({
            "up": nn.conv1d_init(kv[2 * i], ch, out_ch, 2 * f,
                                 dtype=dtype),
            "res": nn.conv1d_init(kv[2 * i + 1], out_ch, out_ch,
                                  cfg.kernel, dtype=dtype),
        })
        ch = out_ch
    p["voc_post"] = nn.conv1d_init(kv[-1], ch, 1, cfg.kernel, dtype=dtype)
    return p


def _dit_velocity(p, cfg: Token2WavConfig, mel, cond, t):
    """One DiT evaluation: mel [B, F, M], cond [B, F, D], t [B] in [0,1]
    -> velocity [B, F, M]."""
    b, f, _ = mel.shape
    x = nn.linear(p["in_proj"], jnp.concatenate([mel, cond], axis=-1))
    temb = nn.timestep_embedding(t * 1000.0, 256).astype(x.dtype)
    temb = nn.linear(p["time2"], jax.nn.silu(nn.linear(p["time1"], temb)))
    h = cfg.num_heads
    hd = cfg.d_model // h
    for blk in p["blocks"]:
        shift, scale, gate = jnp.split(
            nn.linear(blk["mod"], jax.nn.silu(temb)), 3, axis=-1)
        y = rms_norm(x, blk["norm1"]["w"])
        y = y * (1.0 + scale[:, None]) + shift[:, None]
        q, k, v = jnp.split(nn.linear(blk["qkv"], y), 3, axis=-1)
        o = flash_attention(
            q.reshape(b, f, h, hd), k.reshape(b, f, h, hd),
            v.reshape(b, f, h, hd), causal=False,
        )
        x = x + gate[:, None] * nn.linear(blk["out"], o.reshape(b, f, -1))
        y = rms_norm(x, blk["norm2"]["w"])
        x = x + nn.linear(blk["down"],
                          jax.nn.gelu(nn.linear(blk["up"], y),
                                      approximate=True))
    return nn.linear(p["out_proj"], rms_norm(x, p["out_norm"]["w"]))


class Token2WavModel:
    """Generation-runner model protocol implementation (one-shot)."""

    def __init__(self, cfg: Token2WavConfig):
        self.cfg = cfg

    def forward(self, params, token_ids: jax.Array, lengths: jax.Array):
        """token_ids [B, S] codec ids -> {"audio": [B, S*total_upsample]}.

        Flow-matches mel frames conditioned on upsampled code embeddings,
        then renders the waveform through the vocoder.  Padding rows
        produce garbage past lengths*up; the runner slices per request.
        """
        cfg = self.cfg
        del lengths  # padded rows are sliced by the runner
        b, s = token_ids.shape
        frames = s * cfg.frames_per_code
        cond = nn.embedding(params["code_embed"], token_ids)  # [B, S, D]
        cond = jnp.repeat(cond, cfg.frames_per_code, axis=1)  # [B, F, D]

        noise = jax.random.normal(
            jax.random.PRNGKey(cfg.noise_seed),
            (b, frames, cfg.mel_bins), cond.dtype,
        )
        n = cfg.flow_steps

        def body(i, mel):
            # straight flow sigma: 1 -> 0 in n steps
            sigma = 1.0 - i / n
            t = jnp.full((b,), sigma, jnp.float32)
            v = _dit_velocity(params, cfg, mel, cond, t)
            return mel - (1.0 / n) * v

        mel = jax.lax.fori_loop(0, n, body, noise)

        # vocoder: [B, F, M] -> [B, F*up, 1]
        x = nn.conv1d(params["voc_pre"], mel)
        for blk, f in zip(params["voc_ups"], cfg.vocoder_upsample):
            x = jax.nn.silu(x)
            x = nn.conv1d_transpose(blk["up"], x, stride=f)
            x = x + nn.conv1d(blk["res"], jax.nn.silu(x))
        wav = jnp.tanh(nn.conv1d(params["voc_post"], jax.nn.silu(x)))
        return {"audio": wav[..., 0], "mel": mel}

    def slice_output(self, outputs: dict, row: int, in_len: int):
        up = self.cfg.total_upsample
        return {"audio": np.asarray(outputs["audio"][row, : in_len * up])}


def tiny_factory():
    """model_factory for generation stages: (params, model_obj, eos)."""
    cfg = Token2WavConfig.tiny()
    params = init_token2wav_params(jax.random.PRNGKey(12), cfg)
    return params, Token2WavModel(cfg), None
