"""Qwen2.5-Omni thinker: dense AV-L understanding LM (stage 0).

Reference: vllm_omni/model_executor/models/qwen2_5_omni/
qwen2_5_omni_thinker.py — a *dense* Qwen2.5 backbone (QKV projection
biases, no per-head qk-norm — the two switches distinguishing Qwen2 from
Qwen3 layers) with audio/vision front ends and multimodal 3D-RoPE.  The
shared functional transformer covers both generations through its config
flags; the same encoder modules and mm processor as Qwen3-Omni feed the
prompt_embeds path.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from vllm_omni_tpu.models.common.transformer import (
    TransformerConfig,
    init_params,
)

# Real Qwen2.5-Omni-7B thinker geometry (HF config): hidden 3584,
# 28 layers, 28 heads / 4 kv, dense MLP 18944, mrope_section [16, 24, 24].
QWEN2_5_OMNI_THINKER_7B = TransformerConfig(
    vocab_size=152064,
    hidden_size=3584,
    num_layers=28,
    num_heads=28,
    num_kv_heads=4,
    head_dim=128,
    intermediate_size=18944,
    attention_bias=True,   # Qwen2-style QKV biases
    qk_norm=False,
    mrope_sections=(16, 24, 24),
)


def tiny_config(vocab_size: int = 128) -> TransformerConfig:
    return dataclasses.replace(
        TransformerConfig.tiny(vocab_size),
        attention_bias=True,
        qk_norm=False,
        mrope_sections=(4, 2, 2),  # head_dim 16 -> half 8
    )


def tiny_factory():
    """model_factory: random-weight tiny dense thinker."""
    cfg = tiny_config()
    params = init_params(jax.random.PRNGKey(10), cfg, jnp.float32)
    return params, cfg, None


def real_factory(model_dir: str, dtype="bfloat16", **kw):
    """Arch-registry front door: load the REAL thinker LM from a
    Qwen2.5-Omni checkpoint directory (the loader the family's stage
    YAML names, stage_configs/qwen2_5_omni.yaml:10-15)."""
    from vllm_omni_tpu.model_loader.hf_qwen import load_qwen_lm

    return load_qwen_lm(
        model_dir, dtype=dtype,
        hf_config_name="thinker_config.text_config",
        submodel="thinker", **kw)
