"""Checkpoint-schema Qwen2.5-Omni vision tower (real-weight path).

Structural match for the HF ``Qwen2_5OmniVisionEncoder`` (the Qwen2.5-VL
ViT family; the reference thinker consumes it for image/video input):
Conv3d patch embedding applied as a linear over flattened
[C, t_patch, patch, patch] voxels, 2-D rotary positions (h/w split
halves of head_dim//2, rotate-half application), WINDOWED attention —
tokens permuted into spatial-merge windows, block-diagonal per-window
masks, with designated full-attention blocks — RMSNorm blocks with
biased silu MLPs, and the spatial-merge PatchMerger head (ln_q + MLP
over 2x2-merged tokens) followed by the inverse window permutation.

TPU-first: the window permutation, rope tables and per-block masks are
host-precomputed numpy for a given (t, h, w) grid; the device graph is
one static sequence of dense attentions with additive biases.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.models.common import nn
from vllm_omni_tpu.ops import rms_norm

logger = init_logger(__name__)

_PRECISION = jax.lax.Precision.HIGHEST


@dataclass(frozen=True)
class VisionTowerConfig:
    depth: int = 32
    hidden_size: int = 1280
    intermediate_size: int = 3420
    num_heads: int = 16
    in_channels: int = 3
    patch_size: int = 14
    temporal_patch_size: int = 2
    spatial_merge_size: int = 2
    out_hidden_size: int = 3584
    window_size: int = 112
    fullatt_block_indexes: tuple = (7, 15, 23, 31)
    eps: float = 1e-6

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def patch_dim(self) -> int:
        return (self.in_channels * self.temporal_patch_size
                * self.patch_size * self.patch_size)

    @property
    def merge_unit(self) -> int:
        return self.spatial_merge_size ** 2

    @staticmethod
    def tiny() -> "VisionTowerConfig":
        return VisionTowerConfig(
            depth=2, hidden_size=32, intermediate_size=64, num_heads=4,
            patch_size=4, temporal_patch_size=2, spatial_merge_size=2,
            out_hidden_size=24, window_size=16,
            fullatt_block_indexes=(1,))

    @staticmethod
    def from_hf(d: dict) -> "VisionTowerConfig":
        return VisionTowerConfig(
            depth=d.get("depth", 32),
            hidden_size=d.get("hidden_size", 1280),
            intermediate_size=d.get("intermediate_size", 3420),
            num_heads=d.get("num_heads", 16),
            in_channels=d.get("in_channels", 3),
            patch_size=d.get("patch_size", 14),
            temporal_patch_size=d.get("temporal_patch_size", 2),
            spatial_merge_size=d.get("spatial_merge_size", 2),
            out_hidden_size=d.get("out_hidden_size", 3584),
            window_size=d.get("window_size", 112),
            fullatt_block_indexes=tuple(
                d.get("fullatt_block_indexes", (7, 15, 23, 31))),
        )


def init_params(key, cfg: VisionTowerConfig, dtype=jnp.float32):
    ki = iter(jax.random.split(key, 8 + 8 * cfg.depth))
    h = cfg.hidden_size
    merged = h * cfg.merge_unit
    p = {
        "patch_embed": nn.linear_init(next(ki), cfg.patch_dim, h,
                                      bias=False, dtype=dtype),
        "layers": [],
        "merger": {
            "ln_q": nn.rmsnorm_init(h, dtype),
            "mlp0": nn.linear_init(next(ki), merged, merged, dtype=dtype),
            "mlp2": nn.linear_init(next(ki), merged,
                                   cfg.out_hidden_size, dtype=dtype),
        },
    }
    for _ in range(cfg.depth):
        p["layers"].append({
            "norm1": nn.rmsnorm_init(h, dtype),
            "norm2": nn.rmsnorm_init(h, dtype),
            "q": nn.linear_init(next(ki), h, h, dtype=dtype),
            "k": nn.linear_init(next(ki), h, h, dtype=dtype),
            "v": nn.linear_init(next(ki), h, h, dtype=dtype),
            "proj": nn.linear_init(next(ki), h, h, dtype=dtype),
            "gate": nn.linear_init(next(ki), h, cfg.intermediate_size,
                                   dtype=dtype),
            "up": nn.linear_init(next(ki), h, cfg.intermediate_size,
                                 dtype=dtype),
            "down": nn.linear_init(next(ki), cfg.intermediate_size, h,
                                   dtype=dtype),
        })
    return p


def _grid_geometry(cfg: VisionTowerConfig, t: int, h: int, w: int):
    """Host-side: window permutation + per-flavour group ids + rope
    freqs for one (t, h, w) patch grid (reference rot_pos_emb +
    get_window_index)."""
    sm = cfg.spatial_merge_size
    llm_h, llm_w = h // sm, w // sm
    mw = cfg.window_size // sm // cfg.patch_size  # merger window side

    # merged-token window permutation
    idx = np.arange(t * llm_h * llm_w).reshape(t, llm_h, llm_w)
    # reference pads by (mw - dim % mw) even when that equals mw — the
    # padding rows carry -100 and are dropped either way
    pad_h = mw - llm_h % mw
    pad_w = mw - llm_w % mw
    padded = np.full((t, llm_h + pad_h, llm_w + pad_w), -100, np.int64)
    padded[:, :llm_h, :llm_w] = idx
    nh, nw = (llm_h + pad_h) // mw, (llm_w + pad_w) // mw
    padded = padded.reshape(t, nh, mw, nw, mw).transpose(0, 1, 3, 2, 4)
    padded = padded.reshape(-1)
    seqlens = (padded.reshape(t * nh * nw, -1) != -100).sum(axis=1)
    window_index = padded[padded != -100]          # merged-token order
    win_of_merged = np.repeat(np.arange(seqlens.shape[0]), seqlens)

    # raw-token group ids after the permutation: each merged token is
    # merge_unit consecutive raw tokens
    unit = cfg.merge_unit
    win_of_raw = np.repeat(win_of_merged, unit)

    # 2-D rope position ids in the ORIGINAL raw order (h-major with the
    # spatial-merge interleave), then permuted like the tokens
    hh = np.arange(h)[:, None].repeat(w, 1)
    ww = np.arange(w)[None, :].repeat(h, 0)

    def merge_order(a):
        a = a.reshape(llm_h, sm, llm_w, sm).transpose(0, 2, 1, 3)
        return a.reshape(-1)

    hpos = np.tile(merge_order(hh), t)
    wpos = np.tile(merge_order(ww), t)
    half = cfg.head_dim // 2
    inv = 1.0 / (10000.0 ** (np.arange(0, half, 2, np.float32) / half))
    freqs = np.concatenate(
        [hpos[:, None] * inv[None, :], wpos[:, None] * inv[None, :]],
        axis=1)                                     # [S, head_dim//2]
    # permute raw tokens into window order
    perm = (window_index[:, None] * unit
            + np.arange(unit)[None, :]).reshape(-1)
    return perm, win_of_raw, freqs[perm], window_index


def forward(params, cfg: VisionTowerConfig, pixels: jax.Array,
            grid_thw: tuple) -> jax.Array:
    """One image/video clip.

    pixels [S_raw, patch_dim] — flattened temporal-spatial patches in
    the HF processor's order; grid_thw = (t, h, w) patch grid.  Returns
    merged tokens [S_raw / merge_unit, out_hidden_size] in the original
    (pre-window-permutation) order.
    """
    t, h, w = grid_thw
    perm, win_of, freqs, window_index = _grid_geometry(cfg, t, h, w)
    n = pixels.shape[0]
    assert n == t * h * w, (n, grid_thw)

    x = nn.linear(params["patch_embed"], pixels)
    x = jnp.take(x, jnp.asarray(perm), axis=0)

    # rope tables: freqs repeat 2x along the feature dim, rotate-half
    cos = jnp.asarray(np.cos(np.concatenate([freqs, freqs], axis=1)),
                      jnp.float32)
    sin = jnp.asarray(np.sin(np.concatenate([freqs, freqs], axis=1)),
                      jnp.float32)

    def rope(q):
        qf = q.astype(jnp.float32)
        q1, q2 = jnp.split(qf, 2, axis=-1)
        rot = jnp.concatenate([-q2, q1], axis=-1)
        return (qf * cos[:, None] + rot * sin[:, None]).astype(q.dtype)

    window_bias = jnp.asarray(
        np.where(win_of[:, None] == win_of[None, :], 0.0, -1e30),
        jnp.float32)
    # "full" attention still groups per temporal frame (reference
    # cu_seqlens repeat h*w per t) — in the permuted order
    frame_of = perm // (h * w)
    full_bias = jnp.asarray(
        np.where(frame_of[:, None] == frame_of[None, :], 0.0, -1e30),
        jnp.float32)

    heads, hd = cfg.num_heads, cfg.head_dim
    scale = 1.0 / math.sqrt(hd)
    for li, lp in enumerate(params["layers"]):
        bias = (full_bias if li in cfg.fullatt_block_indexes
                else window_bias)
        hh_ = rms_norm(x, lp["norm1"]["w"], cfg.eps)
        q = rope(nn.linear(lp["q"], hh_).reshape(n, heads, hd))
        k = rope(nn.linear(lp["k"], hh_).reshape(n, heads, hd))
        v = nn.linear(lp["v"], hh_).reshape(n, heads, hd)
        s = jnp.einsum("qhd,khd->hqk", q.astype(jnp.float32),
                       k.astype(jnp.float32),
                       precision=_PRECISION) * scale
        a = jax.nn.softmax(s + bias[None], axis=-1).astype(x.dtype)
        o = jnp.einsum("hqk,khd->qhd", a, v, precision=_PRECISION)
        x = x + nn.linear(lp["proj"], o.reshape(n, -1))
        hh_ = rms_norm(x, lp["norm2"]["w"], cfg.eps)
        x = x + nn.linear(lp["down"],
                          jax.nn.silu(nn.linear(lp["gate"], hh_))
                          * nn.linear(lp["up"], hh_))

    # merger: ln_q then the 2x2-merged MLP, then undo the permutation
    m = params["merger"]
    xq = rms_norm(x, m["ln_q"]["w"], cfg.eps)
    merged = xq.reshape(n // cfg.merge_unit, -1)
    out = nn.linear(m["mlp2"],
                    jax.nn.gelu(nn.linear(m["mlp0"], merged),
                                approximate=False))
    # out rows follow window_index order; invert it
    inverse = np.argsort(window_index)
    return jnp.take(out, jnp.asarray(inverse), axis=0)


# ------------------------------------------------------- checkpoint load
def hf_flat_map(cfg: VisionTowerConfig,
                prefix: str = "thinker.visual.") -> dict:
    m: dict[str, tuple] = {}
    m[f"{prefix}patch_embed.proj.weight"] = ("patch_embed", "w")
    for i in range(cfg.depth):
        b = f"{prefix}blocks.{i}"
        tgt = ("layers", i)
        m[f"{b}.norm1.weight"] = tgt + ("norm1", "w")
        m[f"{b}.norm2.weight"] = tgt + ("norm2", "w")
        for hf, ours in (("attn.q", "q"), ("attn.k", "k"),
                         ("attn.v", "v"), ("attn.proj", "proj"),
                         ("mlp.gate_proj", "gate"),
                         ("mlp.up_proj", "up"),
                         ("mlp.down_proj", "down")):
            m[f"{b}.{hf}.weight"] = tgt + (ours, "w")
            m[f"{b}.{hf}.bias"] = tgt + (ours, "b")
    m[f"{prefix}merger.ln_q.weight"] = ("merger", "ln_q", "w")
    m[f"{prefix}merger.mlp.0.weight"] = ("merger", "mlp0", "w")
    m[f"{prefix}merger.mlp.0.bias"] = ("merger", "mlp0", "b")
    m[f"{prefix}merger.mlp.2.weight"] = ("merger", "mlp2", "w")
    m[f"{prefix}merger.mlp.2.bias"] = ("merger", "mlp2", "b")
    return m


def hf_transform(name: str, arr):
    if arr.ndim == 5:  # Conv3d [out, C, tp, p, p] -> linear [C*tp*p*p, out]
        return arr.reshape(arr.shape[0], -1).T
    if arr.ndim == 2 and name.endswith("weight"):
        return arr.T
    return arr


def load_vision_tower(model_dir: str, cfg: VisionTowerConfig = None,
                      dtype=jnp.float32,
                      prefix: str = "thinker.visual."):
    import json
    import os

    from vllm_omni_tpu.model_loader.safetensors_loader import (
        load_checkpoint_tree,
    )

    if cfg is None:
        cfg_path = os.path.join(model_dir, "config.json")
        d = {}
        if os.path.isfile(cfg_path):
            with open(cfg_path) as f:
                d = (json.load(f).get("thinker_config", {})
                     .get("vision_config", {}))
        cfg = VisionTowerConfig.from_hf(d)
    shapes = jax.eval_shape(
        lambda: init_params(jax.random.PRNGKey(0), cfg, jnp.float32))
    tree = jax.tree.map(lambda t_: np.zeros(t_.shape, np.float32), shapes)
    flat = hf_flat_map(cfg, prefix)
    n, _ = load_checkpoint_tree(
        model_dir, flat.get, tree, dtype=np.float32,
        transform=hf_transform, name_filter=lambda nm: nm in flat,
    )
    n_leaves = len(jax.tree.leaves(tree))
    if n < n_leaves:
        # Qwen2.5-VL image checkpoints fuse attention projections into
        # one ``attn.qkv`` tensor (the Omni thinker ships them split) —
        # split the fused rows into the q/k/v leaves
        from vllm_omni_tpu.model_loader.safetensors_loader import (
            iter_safetensors,
        )

        def want(nm):
            return nm.startswith(prefix) and ".attn.qkv." in nm

        for name, arr in iter_safetensors(model_dir, want):
            i = int(name.split(".blocks.")[1].split(".")[0])
            layer = tree["layers"][i]
            for part, key in zip(np.split(arr, 3, axis=0),
                                 ("q", "k", "v")):
                if name.endswith("weight"):
                    layer[key]["w"][...] = part.T
                else:
                    layer[key]["b"][...] = part
                n += 1
    if n != n_leaves:
        raise ValueError(
            f"{model_dir} covered {n}/{n_leaves} vision-tower weights")
    tree = jax.tree.map(lambda a: jnp.asarray(a, dtype), tree)
    return tree, cfg
