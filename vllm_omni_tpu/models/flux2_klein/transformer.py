"""Flux2-Klein transformer (functional JAX).

Reference: vllm_omni/diffusion/models/flux2_klein/flux2_klein_transformer.py:556
``Flux2Transformer2DModel`` — 8 double + 48 single stream blocks at
48 heads x 128 (inner 6144), joint_attention_dim 15360 (three stacked
Qwen3 hidden layers), patch_size 1 over 128-channel packed latents.
Structural deltas vs Flux-1:

- modulation is MODEL-LEVEL and SHARED by all blocks: one silu+linear
  per stream produces (shift, scale, gate) sets consumed by every
  double block (2 sets img + 2 sets txt) and every single block (1 set)
  (Flux2Modulation, :540-554)
- every linear is bias-free
- FFs are gate-FIRST SwiGLU (silu(x1) * x2, :45-55) with a fused
  [inner; inner] input projection; single blocks fuse qkv + the doubled
  MLP projection into one matmul (Flux2ParallelSelfAttention, :236-334)
- rope is 4-axis (32, 32, 32, 32) at theta 2000: text ids
  (0, 0, 0, n), image ids (0, row, col, 0), interleaved pairing
- no pooled conditioning; timestep (+ optional embedded guidance)
  bias-free MLPs; AdaLayerNormContinuous output head (bias-free)
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from vllm_omni_tpu.models.common import nn
from vllm_omni_tpu.ops import flash_attention, rms_norm


@dataclass(frozen=True)
class Flux2KleinDiTConfig:
    in_channels: int = 128   # 32 VAE latent channels x 2x2 packing
    out_channels: int = 128
    patch_size: int = 1
    num_double_blocks: int = 8
    num_single_blocks: int = 48
    num_heads: int = 48
    head_dim: int = 128
    ctx_dim: int = 15360     # 3 stacked Qwen3 hidden layers
    axes_dims: tuple = (32, 32, 32, 32)
    theta: float = 2000.0
    mlp_ratio: float = 3.0
    guidance_embed: bool = True
    rope_interleaved: bool = False  # from_pretrained sets True
    eps: float = 1e-6

    @property
    def inner_dim(self) -> int:
        return self.num_heads * self.head_dim

    @property
    def mlp_dim(self) -> int:
        return int(self.inner_dim * self.mlp_ratio)

    @staticmethod
    def tiny() -> "Flux2KleinDiTConfig":
        return Flux2KleinDiTConfig(
            in_channels=16, out_channels=16, num_double_blocks=2,
            num_single_blocks=2, num_heads=4, head_dim=32, ctx_dim=128,
            axes_dims=(8, 8, 8, 8),
        )


def init_params(key, cfg: Flux2KleinDiTConfig, dtype=jnp.float32):
    inner = cfg.inner_dim
    mlp = cfg.mlp_dim
    nb = cfg.num_double_blocks + cfg.num_single_blocks
    keys = jax.random.split(key, nb + 12)

    def lin(k, i, o):
        return nn.linear_init(k, i, o, bias=False, dtype=dtype)

    p = {
        "x_in": lin(keys[0], cfg.in_channels, inner),
        "ctx_in": lin(keys[1], cfg.ctx_dim, inner),
        "time_in1": lin(keys[2], 256, inner),
        "time_in2": lin(keys[3], inner, inner),
        "mod_img": lin(keys[4], inner, 6 * inner),
        "mod_txt": lin(keys[5], inner, 6 * inner),
        "mod_single": lin(keys[6], inner, 3 * inner),
        "norm_out_mod": lin(keys[7], inner, 2 * inner),
        "proj_out": lin(keys[8], inner,
                        cfg.patch_size ** 2 * cfg.out_channels),
        "double": [],
        "single": [],
    }
    if cfg.guidance_embed:
        p["guidance_in1"] = lin(keys[9], 256, inner)
        p["guidance_in2"] = lin(keys[10], inner, inner)
    for i in range(cfg.num_double_blocks):
        k = jax.random.split(keys[i + 12], 8)
        p["double"].append({
            "img_qkv": lin(k[0], inner, 3 * inner),
            "txt_qkv": lin(k[1], inner, 3 * inner),
            "img_norm_q": nn.rmsnorm_init(cfg.head_dim, dtype),
            "img_norm_k": nn.rmsnorm_init(cfg.head_dim, dtype),
            "txt_norm_q": nn.rmsnorm_init(cfg.head_dim, dtype),
            "txt_norm_k": nn.rmsnorm_init(cfg.head_dim, dtype),
            "img_out": lin(k[2], inner, inner),
            "txt_out": lin(k[3], inner, inner),
            # fused gate-first SwiGLU input [gate; value]
            "img_ff1": lin(k[4], inner, 2 * mlp),
            "img_ff2": lin(k[5], mlp, inner),
            "txt_ff1": lin(k[6], inner, 2 * mlp),
            "txt_ff2": lin(k[7], mlp, inner),
        })
    for i in range(cfg.num_single_blocks):
        k = jax.random.split(keys[cfg.num_double_blocks + i + 12], 2)
        p["single"].append({
            # qkv + doubled MLP projection in one matmul
            "fused": lin(k[0], inner, 3 * inner + 2 * mlp),
            "norm_q": nn.rmsnorm_init(cfg.head_dim, dtype),
            "norm_k": nn.rmsnorm_init(cfg.head_dim, dtype),
            "out": lin(k[1], inner + mlp, inner),
        })
    return p


def rope_freqs(cfg: Flux2KleinDiTConfig, grid_h: int, grid_w: int,
               txt_len: int, cond_grids: tuple = ()):
    """4-axis rope angles [S, head_dim//2], text first.

    Text ids (0, 0, 0, n); generated image (0, row, col, 0); appended
    condition image j at time coordinate 10*(j+1) with its own grid
    (reference _prepare_latent_ids/_prepare_text_ids/_prepare_image_ids
    with scale=10, pipeline_flux2_klein.py:305-395)."""
    halves = [d // 2 for d in cfg.axes_dims]

    def ax(pos, half):
        inv = 1.0 / (cfg.theta ** (
            jnp.arange(half, dtype=jnp.float32) / half))
        return pos.astype(jnp.float32)[:, None] * inv[None, :]

    def grid(gh, gw, t_coord):
        n = gh * gw
        r = jnp.arange(gh).repeat(gw)
        c = jnp.tile(jnp.arange(gw), gh)
        z = jnp.zeros((n,), jnp.int32)
        t = jnp.full((n,), t_coord, jnp.int32)
        return jnp.concatenate(
            [ax(t, halves[0]), ax(r, halves[1]), ax(c, halves[2]),
             ax(z, halves[3])], axis=-1)

    parts = [grid(grid_h, grid_w, 0)]
    for j, (ch, cw) in enumerate(cond_grids):
        parts.append(grid(ch, cw, 10 * (j + 1)))
    img_angles = jnp.concatenate(parts, axis=0)
    zt = jnp.zeros((txt_len,), jnp.int32)
    tn = jnp.arange(txt_len)
    txt_angles = jnp.concatenate(
        [ax(zt, halves[0]), ax(zt, halves[1]), ax(zt, halves[2]),
         ax(tn, halves[3])], axis=-1)
    angles = jnp.concatenate([txt_angles, img_angles], axis=0)
    return jnp.cos(angles), jnp.sin(angles)


def _rope_apply(x, cos, sin, interleaved):
    c = cos[None, :, None, :]
    s = sin[None, :, None, :]
    if interleaved:
        x1 = x[..., 0::2].astype(jnp.float32)
        x2 = x[..., 1::2].astype(jnp.float32)
        out = jnp.stack([x1 * c - x2 * s, x1 * s + x2 * c], axis=-1)
        return out.reshape(x.shape).astype(x.dtype)
    d = x.shape[-1]
    x1 = x[..., : d // 2].astype(jnp.float32)
    x2 = x[..., d // 2:].astype(jnp.float32)
    return jnp.concatenate(
        [x1 * c - x2 * s, x2 * c + x1 * s], axis=-1).astype(x.dtype)


def _swiglu(x):
    # gate FIRST: silu(x1) * x2 (Flux2SwiGLU)
    g, v = jnp.split(x, 2, axis=-1)
    return jax.nn.silu(g) * v


def _heads(x, h):
    b, s, _ = x.shape
    return x.reshape(b, s, h, -1)


def _mod_ln(x, mod):
    shift, scale, gate = mod
    return (nn.layernorm({}, x, eps=1e-6) * (1.0 + scale)
            + shift), gate


def _double_block(blk, cfg, img, txt, mod_img, mod_txt, freqs, kv_mask):
    h = cfg.num_heads
    s_txt = txt.shape[1]
    (img_msa, img_mlp) = mod_img
    (txt_msa, txt_mlp) = mod_txt
    img_n, img_gate = _mod_ln(img, img_msa)
    txt_n, txt_gate = _mod_ln(txt, txt_msa)
    qi, ki, vi = jnp.split(nn.linear(blk["img_qkv"], img_n), 3, -1)
    qt, kt, vt = jnp.split(nn.linear(blk["txt_qkv"], txt_n), 3, -1)
    qi = rms_norm(_heads(qi, h), blk["img_norm_q"]["w"], cfg.eps)
    ki = rms_norm(_heads(ki, h), blk["img_norm_k"]["w"], cfg.eps)
    qt = rms_norm(_heads(qt, h), blk["txt_norm_q"]["w"], cfg.eps)
    kt = rms_norm(_heads(kt, h), blk["txt_norm_k"]["w"], cfg.eps)
    q = _rope_apply(jnp.concatenate([qt, qi], 1), *freqs,
                    cfg.rope_interleaved)
    k = _rope_apply(jnp.concatenate([kt, ki], 1), *freqs,
                    cfg.rope_interleaved)
    v = jnp.concatenate([_heads(vt, h), _heads(vi, h)], 1)
    o = flash_attention(q, k, v, causal=False, kv_mask=kv_mask)
    txt_o = o[:, :s_txt].reshape(*txt.shape[:2], -1)
    img_o = o[:, s_txt:].reshape(*img.shape[:2], -1)
    img = img + img_gate * nn.linear(blk["img_out"], img_o)
    txt = txt + txt_gate * nn.linear(blk["txt_out"], txt_o)

    img_n2, img_gate2 = _mod_ln(img, img_mlp)
    img = img + img_gate2 * nn.linear(
        blk["img_ff2"], _swiglu(nn.linear(blk["img_ff1"], img_n2)))
    txt_n2, txt_gate2 = _mod_ln(txt, txt_mlp)
    txt = txt + txt_gate2 * nn.linear(
        blk["txt_ff2"], _swiglu(nn.linear(blk["txt_ff1"], txt_n2)))
    return img, txt


def _single_block(blk, cfg, x, mod, freqs, kv_mask):
    h = cfg.num_heads
    inner = cfg.inner_dim
    x_n, gate = _mod_ln(x, mod)
    fused = nn.linear(blk["fused"], x_n)
    qkv, mlp_h = fused[..., :3 * inner], fused[..., 3 * inner:]
    q, k, v = jnp.split(qkv, 3, -1)
    q = rms_norm(_heads(q, h), blk["norm_q"]["w"], cfg.eps)
    k = rms_norm(_heads(k, h), blk["norm_k"]["w"], cfg.eps)
    q = _rope_apply(q, *freqs, cfg.rope_interleaved)
    k = _rope_apply(k, *freqs, cfg.rope_interleaved)
    o = flash_attention(q, k, _heads(v, h), causal=False,
                        kv_mask=kv_mask)
    o = o.reshape(*x.shape[:2], -1)
    out = nn.linear(blk["out"],
                    jnp.concatenate([o, _swiglu(mlp_h)], axis=-1))
    return x + gate * out


def forward(
    params,
    cfg: Flux2KleinDiTConfig,
    img_tokens: jax.Array,   # [B, S_img, in_channels]
    txt_states: jax.Array,   # [B, S_txt, ctx_dim]
    timesteps: jax.Array,    # [B] in [0, 1000)
    grid_hw: tuple,
    guidance: Optional[jax.Array] = None,  # [B] embedded guidance
    txt_mask: Optional[jax.Array] = None,
    cond_grids: tuple = (),
) -> jax.Array:
    """Velocity prediction [B, S_img, out_channels] (caller slices off
    appended condition tokens)."""
    b, s_img = img_tokens.shape[:2]
    img = nn.linear(params["x_in"], img_tokens)
    txt = nn.linear(params["ctx_in"], txt_states)
    s_txt = txt.shape[1]

    temb = nn.timestep_embedding(timesteps, 256).astype(img.dtype)
    temb = nn.linear(params["time_in2"],
                     jax.nn.silu(nn.linear(params["time_in1"], temb)))
    if cfg.guidance_embed and guidance is not None:
        g = nn.timestep_embedding(guidance * 1000.0, 256).astype(
            img.dtype)
        temb = temb + nn.linear(
            params["guidance_in2"],
            jax.nn.silu(nn.linear(params["guidance_in1"], g)))

    def mod_sets(name, n_sets):
        m = nn.linear(params[name], jax.nn.silu(temb))[:, None, :]
        chunks = jnp.split(m, 3 * n_sets, axis=-1)
        return tuple(tuple(chunks[3 * i:3 * (i + 1)])
                     for i in range(n_sets))

    mod_img = mod_sets("mod_img", 2)
    mod_txt = mod_sets("mod_txt", 2)
    (mod_single,) = mod_sets("mod_single", 1)

    freqs = rope_freqs(cfg, grid_hw[0], grid_hw[1], s_txt,
                       cond_grids=cond_grids)
    kv_mask = None
    if txt_mask is not None:
        kv_mask = jnp.concatenate(
            [txt_mask.astype(jnp.int32),
             jnp.ones((b, img.shape[1]), jnp.int32)], axis=1)

    for blk in params["double"]:
        img, txt = _double_block(blk, cfg, img, txt, mod_img, mod_txt,
                                 freqs, kv_mask)
    x = jnp.concatenate([txt, img], axis=1)
    for blk in params["single"]:
        x = _single_block(blk, cfg, x, mod_single, freqs, kv_mask)
    img = x[:, s_txt:]

    # AdaLayerNormContinuous (scale first; silu applied inside)
    mod = nn.linear(params["norm_out_mod"], jax.nn.silu(temb))
    scale, shift = jnp.split(mod, 2, axis=-1)
    img = nn.layernorm({}, img, eps=1e-6) * (1.0 + scale[:, None, :]) \
        + shift[:, None, :]
    return nn.linear(params["proj_out"], img)
