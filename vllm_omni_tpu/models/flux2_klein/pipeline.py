"""Flux2-Klein text->image pipeline.

Reference: vllm_omni/diffusion/models/flux2_klein/ — the Flux-2
architecture (8 double + 48 single stream blocks,
flux2_klein_transformer.py:572-576) with an embedded guidance scale;
the step-distilled "Klein" variant ignores classifier-free guidance at
sampling time (pipeline_flux2_klein.py:621-622).  Reuses the shared
Flux MMDiT implementation at the Flux-2 geometry (the reference's
joint_attention_dim 15360 is the concatenated multi-encoder width; the
text-encoder hidden size stands in for it here — re-map at real-weight
time)."""

from __future__ import annotations

from dataclasses import dataclass, field

from vllm_omni_tpu.models.common.transformer import TransformerConfig
from vllm_omni_tpu.models.flux.pipeline import (
    FluxPipeline,
    FluxPipelineConfig,
)
from vllm_omni_tpu.models.flux.transformer import FluxDiTConfig
from vllm_omni_tpu.models.qwen_image.vae import VAEConfig


def _klein_dit() -> FluxDiTConfig:
    return FluxDiTConfig(
        num_double_blocks=8, num_single_blocks=48, num_heads=24,
        head_dim=128, ctx_dim=4096, guidance_embed=True,
    )


@dataclass(frozen=True)
class Flux2KleinPipelineConfig(FluxPipelineConfig):
    dit: FluxDiTConfig = field(default_factory=_klein_dit)

    @staticmethod
    def tiny() -> "Flux2KleinPipelineConfig":
        return Flux2KleinPipelineConfig(
            text=TransformerConfig.tiny(vocab_size=256),
            dit=FluxDiTConfig.tiny(),
            vae=VAEConfig.tiny(),
        )


class Flux2KleinPipeline(FluxPipeline):
    """Text -> image (distilled: embedded guidance, no CFG batch)."""

    config_cls = Flux2KleinPipelineConfig
