"""Flux2-Klein text->image pipeline.

Reference: vllm_omni/diffusion/models/flux2_klein/ — the TRUE Flux-2
architecture (models/flux2_klein/transformer.py: 8 double + 48 single
blocks, 48 heads x 128, shared model-level modulation, bias-free
linears, 4-axis rope) conditioned on THREE stacked Qwen3 hidden layers
(default (9, 18, 27) -> joint width 3 x hidden = 15360 for the real
Qwen3-8B encoder; pipeline_flux2_klein.py:247-302).  The Klein variant
runs true classifier-free guidance with no embedded guidance at
inference (guidance=None, :927-947); latents live in the VAE's
batch-norm-normalized space and are unnormalized with the bn running
stats before decode (:977-990).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.diffusion import cache as step_cache
from vllm_omni_tpu.diffusion import scheduler as fm
from vllm_omni_tpu.diffusion.request import (
    DiffusionOutput,
    InvalidRequestError,
    OmniDiffusionRequest,
)
from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.models.common.transformer import (
    TransformerConfig,
    forward_hidden,
    init_params as init_text_params,
)
from vllm_omni_tpu.models.flux2_klein import transformer as f2dit
from vllm_omni_tpu.models.flux2_klein.transformer import (
    Flux2KleinDiTConfig,
)
from vllm_omni_tpu.models.qwen_image import vae as vae_mod
from vllm_omni_tpu.models.qwen_image.vae import VAEConfig
from vllm_omni_tpu.utils.tokenizer import ByteTokenizer

logger = init_logger(__name__)


def compute_empirical_mu(image_seq_len: int, num_steps: int) -> float:
    """Flux2's empirically fitted schedule shift (reference
    compute_empirical_mu, pipeline_flux2_klein.py:164-179) — NOT the
    Flux-1 linear calculate_shift."""
    a1, b1 = 8.73809524e-05, 1.89833333
    a2, b2 = 0.00016927, 0.45666666
    if image_seq_len > 4300:
        return float(a2 * image_seq_len + b2)
    m_200 = a2 * image_seq_len + b2
    m_10 = a1 * image_seq_len + b1
    a = (m_200 - m_10) / 190.0
    b = m_200 - 200.0 * a
    return float(a * num_steps + b)


@dataclass(frozen=True)
class Flux2KleinPipelineConfig:
    text: TransformerConfig = field(default_factory=TransformerConfig)
    dit: Flux2KleinDiTConfig = field(
        default_factory=Flux2KleinDiTConfig)
    vae: VAEConfig = field(default_factory=VAEConfig)
    # HF hidden_states indices stacked into the DiT context width
    # (len(text_out_layers) * text hidden == dit.ctx_dim)
    text_out_layers: tuple = (9, 18, 27)
    max_text_len: int = 512
    scheduler: str = "euler"
    pack: int = 2

    @staticmethod
    def tiny() -> "Flux2KleinPipelineConfig":
        # 2 stacked layers x hidden 64 = dit ctx 128
        return Flux2KleinPipelineConfig(
            text=TransformerConfig.tiny(vocab_size=256),
            dit=Flux2KleinDiTConfig.tiny(),
            vae=VAEConfig.tiny(),
            text_out_layers=(1, 2),
            max_text_len=32,
        )


class Flux2KleinPipeline:
    """Text -> image (true CFG; latents in bn-normalized space)."""

    output_type = "image"

    @property
    def geometry_multiple(self) -> int:
        return self.cfg.vae.spatial_ratio * self.cfg.pack

    def __init__(self, config: Flux2KleinPipelineConfig,
                 dtype=jnp.bfloat16, seed: int = 0, mesh=None,
                 cache_config=None, init_weights: bool = True):
        from vllm_omni_tpu.parallel.pipeline_mesh import MeshWiring

        self.cfg = config
        self.dtype = dtype
        self.mesh = mesh
        self.cache_config = cache_config
        self.wiring = MeshWiring(mesh, type(self).__name__).validate(
            {"dp", "cfg"})
        want_ctx = len(config.text_out_layers) * config.text.hidden_size
        if want_ctx != config.dit.ctx_dim:
            raise ValueError(
                f"dit ctx_dim {config.dit.ctx_dim} != "
                f"{len(config.text_out_layers)} stacked text layers x "
                f"hidden {config.text.hidden_size}")
        want_in = config.vae.latent_channels * config.pack ** 2
        if config.dit.in_channels != want_in:
            raise ValueError(
                f"dit.in_channels must be latent*pack^2 = {want_in}")
        self.tokenizer = ByteTokenizer(config.text.vocab_size)
        self.hf_tokenizer = None
        # bn running stats over the PACKED latent channels ((mean, std)
        # in token-feature order); identity when absent
        self.latent_bn = None
        k1, k2, k3 = jax.random.split(jax.random.PRNGKey(seed), 3)
        logger.info("Initializing %s (dtype=%s)", type(self).__name__,
                    dtype)
        if init_weights:
            self.text_params = self.wiring.place(
                init_text_params(k1, config.text, dtype))
            self.dit_params = self.wiring.place(
                f2dit.init_params(k2, config.dit, dtype))
            self.vae_params = self.wiring.place(
                vae_mod.init_decoder(k3, config.vae, dtype))
        else:
            self.text_params = self.dit_params = self.vae_params = None
        self._denoise_cache: dict = {}
        self._text_encode_jit = jax.jit(
            lambda p, i, m: forward_hidden(
                p, self.cfg.text, i, attn_mask=m,
                collect_hidden_layers=self.cfg.text_out_layers))
        self._vae_decode_jit = jax.jit(
            lambda pp, l: vae_mod.decode(pp, self.cfg.vae, l))

    # ------------------------------------------------------------- encode
    def encode_prompt(self, prompts: list[str]):
        if self.hf_tokenizer is not None:
            texts = []
            for p in prompts:
                msg = [{"role": "user", "content": p}]
                try:
                    texts.append(self.hf_tokenizer.apply_chat_template(
                        msg, tokenize=False, add_generation_prompt=True,
                        enable_thinking=False))
                except Exception:
                    texts.append(
                        f"<|im_start|>user\n{p}<|im_end|>\n"
                        "<|im_start|>assistant\n<think>\n\n</think>\n\n")
            self.hf_tokenizer.padding_side = "right"
            enc = self.hf_tokenizer(
                texts, padding="max_length", truncation=True,
                max_length=self.cfg.max_text_len)
            ids = np.asarray(enc["input_ids"], np.int32)
            # the LM runs with the pad attention mask (reference
            # :287-292); its output keeps EVERY position and the DiT
            # attends them all — pad rows differ without the mask
            mask = jnp.asarray(
                np.asarray(enc["attention_mask"], np.int32))
        else:
            ids, lens = self.tokenizer.batch_encode(
                prompts, self.cfg.max_text_len)
            mask = jnp.asarray(
                (np.arange(self.cfg.max_text_len)[None, :]
                 < lens[:, None]).astype(np.int32))
        hidden = self._text_encode_jit(self.text_params,
                                       jnp.asarray(ids), mask)
        return hidden.astype(self.dtype)

    @classmethod
    def from_pretrained(cls, model_dir: str, dtype=jnp.bfloat16,
                        seed: int = 0, mesh=None, cache_config=None,
                        max_text_len: int = 512):
        """Build from a diffusers-format Flux2-Klein checkpoint
        (transformer/ + Qwen3 text_encoder/ + tokenizer/ + vae/ with
        optional bn latent stats + scheduler/)."""
        import json
        import os

        from transformers import AutoTokenizer

        from vllm_omni_tpu.model_loader import diffusers_loader as dl
        from vllm_omni_tpu.models.flux2_klein import loader as f2loader

        dl.load_model_index(model_dir)
        tdir = os.path.join(model_dir, "transformer")
        dit_params, dit_cfg = f2loader.load_flux2_dit(tdir, dtype=dtype)
        text_params, text_cfg = dl.load_text_encoder(
            os.path.join(model_dir, "text_encoder"), dtype=dtype)
        n_stack = dit_cfg.ctx_dim // text_cfg.hidden_size
        if n_stack * text_cfg.hidden_size != dit_cfg.ctx_dim:
            raise ValueError(
                f"text hidden {text_cfg.hidden_size} does not divide "
                f"dit ctx_dim {dit_cfg.ctx_dim}")
        # evenly spaced interior layers, matching the reference's
        # (9, 18, 27) for 36-layer Qwen3-8B
        step = text_cfg.num_layers // (n_stack + 1)
        out_layers = tuple(
            step * (i + 1) for i in range(n_stack)) if step else tuple(
            range(1, n_stack + 1))
        vae_dir = os.path.join(model_dir, "vae")
        vae_tree, vae_cfg = dl.load_image_vae(vae_dir, dtype=dtype,
                                              decoder=True)
        config = Flux2KleinPipelineConfig(
            text=text_cfg, dit=dit_cfg, vae=vae_cfg,
            text_out_layers=out_layers, max_text_len=max_text_len)
        pipe = cls(config, dtype=dtype, seed=seed, mesh=mesh,
                   cache_config=cache_config, init_weights=False)
        pipe.dit_params = pipe.wiring.place(dit_params)
        pipe.text_params = pipe.wiring.place(text_params)
        pipe.vae_params = pipe.wiring.place(vae_tree["decoder"])
        pipe.latent_bn = f2loader.load_latent_bn(vae_dir)
        pipe.hf_tokenizer = AutoTokenizer.from_pretrained(
            os.path.join(model_dir, "tokenizer"))
        return pipe

    # ------------------------------------------------------------ denoise
    def _denoise_fn(self, grid_h, grid_w, sched_len):
        key = (grid_h, grid_w, sched_len)
        if key in self._denoise_cache:
            return self._denoise_cache[key]
        cfg = self.cfg
        wiring = self.wiring
        cache_cfg = self.cache_config

        @jax.jit
        def run(dit_params, latents, ctx, neg_ctx, sigmas, timesteps,
                gscale, num_steps):
            schedule = fm.FlowMatchSchedule(sigmas=sigmas,
                                            timesteps=timesteps)
            do_cfg = neg_ctx is not None
            ctx_all = (jnp.concatenate([ctx, neg_ctx], 0)
                       if do_cfg else ctx)

            def eval_velocity(lat, i):
                t = jnp.broadcast_to(timesteps[i], (lat.shape[0],))
                lat_in = (jnp.concatenate([lat, lat], 0)
                          if do_cfg else lat)
                lat_in = wiring.constrain(lat_in)
                t_in = jnp.concatenate([t, t], 0) if do_cfg else t
                v = f2dit.forward(
                    dit_params, cfg.dit, lat_in, ctx_all, t_in,
                    (grid_h, grid_w),
                )
                if do_cfg:
                    v_pos, v_neg = jnp.split(v, 2, axis=0)
                    v = v_neg + gscale * (v_pos - v_neg)
                return v

            return step_cache.run_denoise_loop(
                cache_cfg, schedule, eval_velocity, latents, num_steps,
                solver=cfg.scheduler)

        self._denoise_cache[key] = run
        return run

    # ------------------------------------------------------------ forward
    def forward(self, req: OmniDiffusionRequest) -> list[DiffusionOutput]:
        sp = req.sampling_params
        cfg = self.cfg
        mult = self.geometry_multiple
        if sp.height % mult or sp.width % mult:
            raise InvalidRequestError(
                f"height/width must be multiples of {mult}")
        if sp.num_inference_steps < 1:
            raise InvalidRequestError("num_inference_steps must be >= 1")
        lat_h = sp.height // cfg.vae.spatial_ratio
        lat_w = sp.width // cfg.vae.spatial_ratio
        gh, gw = lat_h // cfg.pack, lat_w // cfg.pack
        prompts = req.prompt
        b = len(prompts)

        ctx = self.encode_prompt(prompts)
        do_cfg = sp.guidance_scale > 1.0
        neg_ctx = (self.encode_prompt([sp.negative_prompt] * b)
                   if do_cfg else None)
        seed = (sp.seed if sp.seed is not None
                else int(np.random.randint(0, 2 ** 31 - 1)))
        noise = jax.random.normal(
            jax.random.PRNGKey(seed),
            (b, gh * gw, cfg.dit.in_channels), jnp.float32,
        ).astype(self.dtype)
        num_steps = sp.num_inference_steps
        mu = compute_empirical_mu(gh * gw, num_steps)
        schedule = fm.make_schedule(num_steps, use_dynamic_shifting=True,
                                    mu=mu)
        sched_len = max(8, 1 << (num_steps - 1).bit_length())
        sigmas = jnp.zeros((sched_len + 1,)).at[: num_steps + 1].set(
            schedule.sigmas)
        timesteps = jnp.zeros((sched_len,)).at[:num_steps].set(
            schedule.timesteps)
        run = self._denoise_fn(gh, gw, sched_len)
        latents, skipped = run(
            self.dit_params, noise, ctx, neg_ctx, sigmas, timesteps,
            jnp.float32(sp.guidance_scale), jnp.int32(num_steps))
        self.last_skipped_steps = int(skipped)

        if self.latent_bn is not None:
            # latents live in bn-normalized space; unnormalize over the
            # packed channels before decode (pipeline_flux2_klein.py:977)
            mean, std = self.latent_bn
            latents = latents * std + mean
        c = cfg.vae.latent_channels
        p = cfg.pack
        lat = latents.reshape(b, gh, gw, p, p, c).transpose(
            0, 1, 3, 2, 4, 5)
        lat = lat.reshape(b, lat_h, lat_w, c)
        imgs = np.asarray(self._vae_decode_jit(
            self.vae_params, lat.astype(jnp.float32)))
        imgs = ((np.clip(imgs, -1, 1) + 1) * 127.5).astype(np.uint8)
        return [
            DiffusionOutput(request_id=req.request_ids[i],
                            prompt=prompts[i], data=imgs[i],
                            output_type="image")
            for i in range(b)
        ]
