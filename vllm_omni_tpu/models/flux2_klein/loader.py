"""Diffusers-format Flux2-Klein transformer loader.

Checkpoint names per the reference module tree
(flux2_klein_transformer.py:556-650): model-level modulation linears,
``time_guidance_embed.{timestep,guidance}_embedder.linear_{1,2}``
(bias-free), double blocks with separate to_q/to_k/to_v (+add_*) fused
here into qkv matmuls, fused ``ff.linear_in`` ([gate; value] SwiGLU),
single blocks with the pre-fused ``attn.to_qkv_mlp_proj`` (some
checkpoints name it ``to_qkvkv_mlp_proj``) and a bare ``attn.to_out``.

Channel-order shim: the reference packs latents (c, dy, dx) while this
repo's pipelines pack (dy, dx, c) — x_in input rows, proj_out output
columns, and the VAE bn latent stats permute accordingly at load time
(zero runtime cost).
"""

from __future__ import annotations

import json
import os

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.models.flux.loader import load_routed
from vllm_omni_tpu.models.flux2_klein.transformer import (
    Flux2KleinDiTConfig,
    init_params,
)


def dit_config_from_diffusers(d: dict) -> Flux2KleinDiTConfig:
    in_ch = d.get("in_channels", 128)
    return Flux2KleinDiTConfig(
        in_channels=in_ch,
        out_channels=d.get("out_channels") or in_ch,
        patch_size=d.get("patch_size", 1),
        num_double_blocks=d.get("num_layers", 8),
        num_single_blocks=d.get("num_single_layers", 48),
        num_heads=d.get("num_attention_heads", 48),
        head_dim=d.get("attention_head_dim", 128),
        ctx_dim=d.get("joint_attention_dim", 15360),
        axes_dims=tuple(d.get("axes_dims_rope", (32, 32, 32, 32))),
        theta=d.get("rope_theta", 2000),
        mlp_ratio=d.get("mlp_ratio", 3.0),
        guidance_embed=d.get("guidance_embeds", True),
        rope_interleaved=True,
    )


def _routing(cfg: Flux2KleinDiTConfig) -> dict:
    r: dict[str, tuple] = {}

    def lin(hf, *path):
        # every Flux2 linear is bias-free
        r[f"{hf}.weight"] = ("direct", path + ("w",))

    def fuse(names, *path):
        for s, n in enumerate(names):
            r[f"{n}.weight"] = ("fuse", path + ("w",), s, len(names))

    lin("x_embedder", "x_in")
    lin("context_embedder", "ctx_in")
    lin("time_guidance_embed.timestep_embedder.linear_1", "time_in1")
    lin("time_guidance_embed.timestep_embedder.linear_2", "time_in2")
    if cfg.guidance_embed:
        lin("time_guidance_embed.guidance_embedder.linear_1",
            "guidance_in1")
        lin("time_guidance_embed.guidance_embedder.linear_2",
            "guidance_in2")
    lin("double_stream_modulation_img.linear", "mod_img")
    lin("double_stream_modulation_txt.linear", "mod_txt")
    lin("single_stream_modulation.linear", "mod_single")
    lin("norm_out.linear", "norm_out_mod")
    lin("proj_out", "proj_out")
    for i in range(cfg.num_double_blocks):
        b = f"transformer_blocks.{i}"
        t = ("double", i)
        fuse([f"{b}.attn.to_q", f"{b}.attn.to_k", f"{b}.attn.to_v"],
             *t, "img_qkv")
        fuse([f"{b}.attn.add_q_proj", f"{b}.attn.add_k_proj",
              f"{b}.attn.add_v_proj"], *t, "txt_qkv")
        for hf, ours in (("norm_q", "img_norm_q"),
                         ("norm_k", "img_norm_k"),
                         ("norm_added_q", "txt_norm_q"),
                         ("norm_added_k", "txt_norm_k")):
            r[f"{b}.attn.{hf}.weight"] = ("direct", t + (ours, "w"))
        lin(f"{b}.attn.to_out.0", *t, "img_out")
        lin(f"{b}.attn.to_add_out", *t, "txt_out")
        lin(f"{b}.ff.linear_in", *t, "img_ff1")
        lin(f"{b}.ff.linear_out", *t, "img_ff2")
        lin(f"{b}.ff_context.linear_in", *t, "txt_ff1")
        lin(f"{b}.ff_context.linear_out", *t, "txt_ff2")
    for i in range(cfg.num_single_blocks):
        b = f"single_transformer_blocks.{i}"
        t = ("single", i)
        # both published spellings route to the same fused leaf
        r[f"{b}.attn.to_qkv_mlp_proj.weight"] = (
            "direct", t + ("fused", "w"))
        r[f"{b}.attn.to_qkvkv_mlp_proj.weight"] = (
            "direct", t + ("fused", "w"))
        r[f"{b}.attn.norm_q.weight"] = ("direct", t + ("norm_q", "w"))
        r[f"{b}.attn.norm_k.weight"] = ("direct", t + ("norm_k", "w"))
        lin(f"{b}.attn.to_out", *t, "out")
    return r


def _chan_perm(in_channels: int, pack: int = 2) -> np.ndarray:
    """Index permutation from the reference's (c, dy, dx) packed order
    to this repo's (dy, dx, c)."""
    c = in_channels // (pack * pack)
    idx = np.arange(in_channels).reshape(c, pack, pack)
    return idx.transpose(1, 2, 0).reshape(-1)


def load_flux2_dit(model_dir: str, cfg: Flux2KleinDiTConfig = None,
                   dtype=jnp.bfloat16):
    if cfg is None:
        with open(os.path.join(model_dir, "config.json")) as f:
            cfg = dit_config_from_diffusers(json.load(f))
    shapes = jax.eval_shape(
        lambda: init_params(jax.random.PRNGKey(0), cfg, jnp.float32))
    perm_in = _chan_perm(cfg.in_channels)
    perm_out = _chan_perm(cfg.out_channels)

    def x_in_t(arr):
        # HF [inner, in] -> [in, inner] with rows permuted to (dy,dx,c)
        return np.ascontiguousarray(arr.T[perm_in])

    def proj_out_t(arr):
        # HF [out, inner] -> [inner, out] with cols permuted
        return np.ascontiguousarray(arr.T[:, perm_out])

    tree = load_routed(
        model_dir, _routing(cfg), shapes, dtype,
        transforms={"x_embedder.weight": x_in_t,
                    "proj_out.weight": proj_out_t})
    return tree, cfg


def load_latent_bn(vae_dir: str, pack: int = 2):
    """(mean, std) over packed latent channels in this repo's
    (dy, dx, c) token order, or None when the VAE ships no bn stats
    (reference: AutoencoderKLFlux2 bn running stats,
    pipeline_flux2_klein.py:977-984)."""
    from safetensors import safe_open

    mean = var = eps = None
    for fn in sorted(os.listdir(vae_dir)):
        if not fn.endswith(".safetensors"):
            continue
        with safe_open(os.path.join(vae_dir, fn), "np") as f:
            keys = set(f.keys())
            if "bn.running_mean" in keys:
                mean = f.get_tensor("bn.running_mean")
                var = f.get_tensor("bn.running_var")
    if mean is None:
        return None
    cfg_path = os.path.join(vae_dir, "config.json")
    eps = 1e-4
    if os.path.isfile(cfg_path):
        with open(cfg_path) as f:
            eps = json.load(f).get("batch_norm_eps", 1e-4)
    perm = _chan_perm(mean.shape[0], pack)
    std = np.sqrt(var + eps)
    return (jnp.asarray(mean[perm], jnp.float32),
            jnp.asarray(std[perm], jnp.float32))
