"""Bagel: unified AR + diffusion hybrid (text/image understanding LLM
that *is* the image generator).

Reference: vllm_omni/diffusion/models/bagel/ — ``BagelPipeline``
(pipeline_bagel.py:153) around a Qwen2-MoT LLM
(bagel_transformer.py:532): one transformer with TWO expert weight sets
per layer ("Mixture-of-Transformers": an understanding expert serving
text/ViT tokens and a generation expert serving VAE-latent tokens,
Qwen2MoTConfig :167), shared attention.  Generation is flow matching
run BY the LLM: the prompt (and optional conditioning image) prefill a
KV cache once; each denoise step embeds the noisy packed VAE latents
(vae2llm + timestep + 2D position embedding, :1019-1044), runs them
through the generation expert attending the cached context, and reads
velocity off ``llm2vae``; x advances x <- x - v*dt on a shifted 1->0
schedule (generate_image, :1286-1371) with dual text/image CFG +
global renorm.

TPU-first: the reference's per-step Python loop over a mutable
NaiveCache becomes ONE jitted fori_loop whose context KV is a
loop-invariant array pytree (computed once by the prefill jit) — no
cache mutation inside the loop, latent tokens attend [ctx ; latents]
with full self-attention among themselves.  CFG branches batch as rows
of a 3-deep context stack instead of three sequential forwards.
Conditioning images join the context as VAE-latent tokens projected
through ``vae2llm`` (forward_cache_update_vae, :1019) — packed image
tokens attend each other bidirectionally while text stays causal.
Understanding input: the SigLIP NaViT tower (models/common/siglip.py)
feeds the und expert through the MLP connector + frozen 2D sincos
position table when ``BagelPipelineConfig.vit`` is set; text +
VAE-image conditioning and the dual-branch CFG flow ride alongside.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.diffusion.request import (
    DiffusionOutput,
    InvalidRequestError,
    OmniDiffusionRequest,
)
from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.models.common import intake, nn
from vllm_omni_tpu.models.qwen_image import vae as vae_mod
from vllm_omni_tpu.models.qwen_image.vae import VAEConfig
from vllm_omni_tpu.ops import apply_rope, compute_rope_freqs, rms_norm, silu_mul
from vllm_omni_tpu.utils.tokenizer import ByteTokenizer

logger = init_logger(__name__)


@dataclass(frozen=True)
class BagelConfig:
    vocab_size: int = 152064
    hidden_size: int = 3584
    num_layers: int = 28
    num_heads: int = 28
    num_kv_heads: int = 4
    head_dim: int = 128
    intermediate_size: int = 18944
    rope_theta: float = 1e6
    rms_eps: float = 1e-6
    latent_channels: int = 16
    patch: int = 2              # latent 2x2 packing (latent_downsample)
    max_latent_size: int = 64
    timestep_shift: float = 3.0
    # per-head RMS QK-norm (the published MoT checkpoint has it;
    # reference forces qk_norm=True, pipeline_bagel.py:185)
    qk_norm: bool = False

    @property
    def latent_dim(self) -> int:
        return self.latent_channels * self.patch ** 2

    @staticmethod
    def tiny() -> "BagelConfig":
        return BagelConfig(
            vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
            num_kv_heads=2, head_dim=16, intermediate_size=128,
            latent_channels=4, max_latent_size=16,
        )


@dataclass(frozen=True)
class BagelPipelineConfig:
    llm: BagelConfig = field(default_factory=BagelConfig)
    vae: VAEConfig = field(default_factory=VAEConfig)
    max_text_len: int = 64
    steps_bucket: int = 32
    # SigLIP understanding tower (reference: SiglipNaViTWrapper +
    # MLPconnector + frozen 2D sincos vit_pos_embed,
    # pipeline_bagel.py:121-149, bagel_transformer.py:855-860); None =>
    # conditioning images ride the VAE/gen-expert path only
    vit: "object" = None          # SigLIPConfig when enabled
    vit_max_patch_per_side: int = 70

    @staticmethod
    def tiny() -> "BagelPipelineConfig":
        return BagelPipelineConfig(
            llm=BagelConfig.tiny(), vae=VAEConfig.tiny(),
            max_text_len=16, steps_bucket=8)

    @staticmethod
    def tiny_vit() -> "BagelPipelineConfig":
        from vllm_omni_tpu.models.common.siglip import SigLIPConfig

        return BagelPipelineConfig(
            llm=BagelConfig.tiny(), vae=VAEConfig.tiny(),
            max_text_len=16, steps_bucket=8,
            vit=SigLIPConfig(hidden_size=16, num_layers=1, num_heads=2,
                             intermediate_size=32, patch_size=8,
                             num_positions=16),
            vit_max_patch_per_side=4)


def _expert_init(key, cfg: BagelConfig, dtype):
    """One expert's per-layer weights (und or gen — MoT)."""
    k = jax.random.split(key, 7)
    h, q = cfg.hidden_size, cfg.num_heads * cfg.head_dim
    kv = cfg.num_kv_heads * cfg.head_dim
    extra = ({"q_norm": nn.rmsnorm_init(cfg.head_dim, dtype),
              "k_norm": nn.rmsnorm_init(cfg.head_dim, dtype)}
             if cfg.qk_norm else {})
    return {
        **extra,
        "input_norm": nn.rmsnorm_init(h, dtype),
        "q_proj": nn.linear_init(k[0], h, q, dtype=dtype),
        "k_proj": nn.linear_init(k[1], h, kv, dtype=dtype),
        "v_proj": nn.linear_init(k[2], h, kv, dtype=dtype),
        "o_proj": nn.linear_init(k[3], q, h, bias=False, dtype=dtype),
        "post_norm": nn.rmsnorm_init(h, dtype),
        "gate_up": nn.linear_init(k[4], h, 2 * cfg.intermediate_size,
                                  bias=False, dtype=dtype),
        "down": nn.linear_init(k[5], cfg.intermediate_size, h,
                               bias=False, dtype=dtype),
    }


def init_params(key, pcfg: BagelPipelineConfig, dtype=jnp.float32):
    cfg = pcfg.llm
    keys = jax.random.split(key, 2 * cfg.num_layers + 8)
    ki = iter(keys)
    p = {
        "embed": nn.embedding_init(next(ki), cfg.vocab_size,
                                   cfg.hidden_size, dtype),
        "layers": [
            {"und": _expert_init(next(ki), cfg, dtype),
             "gen": _expert_init(next(ki), cfg, dtype)}
            for _ in range(cfg.num_layers)
        ],
        "final_norm": nn.rmsnorm_init(cfg.hidden_size, dtype),
        "time_in1": nn.linear_init(next(ki), 256, cfg.hidden_size,
                                   dtype=dtype),
        "time_in2": nn.linear_init(next(ki), cfg.hidden_size,
                                   cfg.hidden_size, dtype=dtype),
        "vae2llm": nn.linear_init(next(ki), cfg.latent_dim,
                                  cfg.hidden_size, dtype=dtype),
        "llm2vae": nn.linear_init(next(ki), cfg.hidden_size,
                                  cfg.latent_dim, dtype=dtype),
        # learned 2D position embedding over the latent grid
        "pos_embed": jax.random.normal(
            next(ki), (cfg.max_latent_size * cfg.max_latent_size,
                       cfg.hidden_size), dtype) * 0.02,
    }
    return p


def _qkv(exp, cfg: BagelConfig, x, cos, sin):
    b, s, _ = x.shape
    h = rms_norm(x, exp["input_norm"]["w"], cfg.rms_eps)
    flat = h.reshape(b * s, -1)
    q = nn.linear(exp["q_proj"], flat).reshape(b * s, -1, cfg.head_dim)
    k = nn.linear(exp["k_proj"], flat).reshape(b * s, -1, cfg.head_dim)
    v = nn.linear(exp["v_proj"], flat).reshape(b * s, -1, cfg.head_dim)
    if "q_norm" in exp:
        q = rms_norm(q, exp["q_norm"]["w"], cfg.rms_eps)
        k = rms_norm(k, exp["k_norm"]["w"], cfg.rms_eps)
    q = apply_rope(q, cos, sin).reshape(b, s, -1, cfg.head_dim)
    k = apply_rope(k, cos, sin).reshape(b, s, -1, cfg.head_dim)
    return q, k, v.reshape(b, s, -1, cfg.head_dim)


def _mlp(exp, cfg: BagelConfig, x):
    h = rms_norm(x, exp["post_norm"]["w"], cfg.rms_eps)
    return nn.linear(exp["down"], silu_mul(nn.linear(exp["gate_up"], h)))


def _rope(cfg: BagelConfig, positions):
    return compute_rope_freqs(positions.reshape(-1), cfg.head_dim,
                              cfg.rope_theta)


def prefill_context(params, cfg: BagelConfig, token_ids: jax.Array,
                    ctx_mask: jax.Array, img_tokens=None,
                    vit_tokens=None):
    """Context prefill (the NaiveCache fill): text rides the
    UNDERSTANDING expert (forward_cache_update_text); conditioning-image
    VAE-latent tokens ride the GENERATION expert
    (forward_cache_update_vae — MoT routes VAE tokens to the gen branch)
    with shared attention over the packed [text ; vit ; image] sequence.
    Returns per-layer (k, v) [B, S_ctx(+S_vit)(+S_img), Hkv, D] plus the
    extended context mask.  ``img_tokens`` are already embedded
    (vae2llm + t=0 timestep + 2D pos, see ``_image_context``); image
    tokens attend each other bidirectionally while text stays causal.
    ``vit_tokens`` are SigLIP understanding features projected to LLM
    width (connector + frozen 2D sincos pos embed) — they ride the UND
    expert like text, all at one rope position (the reference packs the
    whole vit segment at curr_position_id,
    bagel_transformer.py:1116-1117), attending bidirectionally."""
    b, s = token_ids.shape
    xt = nn.embedding(params["embed"], token_ids)
    tok_mask = ctx_mask
    cos_t, sin_t = _rope(cfg, jnp.broadcast_to(
        jnp.arange(s)[None], (b, s)))
    s_vit = 0 if vit_tokens is None else vit_tokens.shape[1]
    xv = None
    if vit_tokens is not None:
        xv = vit_tokens.astype(xt.dtype)
        tok_mask = jnp.concatenate(
            [tok_mask, jnp.ones((b, s_vit), ctx_mask.dtype)], axis=1)
        # one shared rope position for the whole vit segment
        cos_v, sin_v = _rope(cfg, jnp.full((b, s_vit), s, jnp.int32))
    if img_tokens is None:
        s_all, xi = s + s_vit, None
    else:
        s_img = img_tokens.shape[1]
        s_all = s + s_vit + s_img
        xi = img_tokens.astype(xt.dtype)
        tok_mask = jnp.concatenate(
            [tok_mask, jnp.ones((b, s_img), ctx_mask.dtype)], axis=1)
        # the vit segment consumes ONE rope position (reference packs
        # it at curr_position_id and advances by one) — image tokens
        # continue right after, not s_vit later
        rope_start = s + (1 if s_vit else 0)
        cos_i, sin_i = _rope(cfg, jnp.broadcast_to(
            (rope_start + jnp.arange(s_img))[None], (b, s_img)))
    causal = jnp.arange(s_all)[None, :] <= jnp.arange(s_all)[:, None]
    if vit_tokens is not None:
        vit_zone = ((jnp.arange(s_all) >= s)
                    & (jnp.arange(s_all) < s + s_vit))
        causal = causal | (vit_zone[None, :] & vit_zone[:, None])
    if img_tokens is not None:
        # packed image attention: image tokens see each other
        # bidirectionally; text stays causal and precedes the image
        img_zone = (jnp.arange(s_all) >= s + s_vit)[None, :] \
            & (jnp.arange(s_all) >= s + s_vit)[:, None]
        causal = causal | img_zone
    bias = jnp.where(causal[None] & (tok_mask[:, None, :] > 0),
                     0.0, -1e30)[:, None]  # [B,1,S,S]
    kvs = []
    for layer in params["layers"]:
        und = layer["und"]
        qs, ks, vs = [], [], []
        qt, kt, vt = _qkv(und, cfg, xt, cos_t, sin_t)
        qs.append(qt); ks.append(kt); vs.append(vt)
        if xv is not None:
            qv, kv, vv = _qkv(und, cfg, xv, cos_v, sin_v)
            qs.append(qv); ks.append(kv); vs.append(vv)
        if xi is not None:
            gen = layer["gen"]
            qi, ki, vi = _qkv(gen, cfg, xi, cos_i, sin_i)
            qs.append(qi); ks.append(ki); vs.append(vi)
        q = jnp.concatenate(qs, axis=1) if len(qs) > 1 else qs[0]
        k = jnp.concatenate(ks, axis=1) if len(ks) > 1 else ks[0]
        v = jnp.concatenate(vs, axis=1) if len(vs) > 1 else vs[0]
        kvs.append((k, v))
        o = _attend(q, k, v, bias)
        xt = xt + nn.linear(und["o_proj"], o[:, :s].reshape(b, s, -1))
        xt = xt + _mlp(und, cfg, xt)
        if xv is not None:
            xv = xv + nn.linear(und["o_proj"],
                                o[:, s:s + s_vit].reshape(b, s_vit, -1))
            xv = xv + _mlp(und, cfg, xv)
        if xi is not None:
            xi = xi + nn.linear(gen["o_proj"],
                                o[:, s + s_vit:].reshape(
                                    b, s_all - s - s_vit, -1))
            xi = xi + _mlp(gen, cfg, xi)
    return kvs, tok_mask



_attend = nn.bias_attention


def flow_velocity(params, cfg: BagelConfig, x_t: jax.Array,
                  t: jax.Array, ctx_kvs, ctx_mask, grid_h: int,
                  grid_w: int):
    """One flow step through the GENERATION expert: packed latents
    [B, S_lat, latent_dim] + timestep -> velocity (reference
    _forward_flow: vae2llm + time + pos embed, gen-expert layers
    attending [cached context ; latents], llm2vae head)."""
    b, s_lat, _ = x_t.shape
    temb = nn.timestep_embedding(t, 256)
    temb = nn.linear(params["time_in2"], jax.nn.silu(
        nn.linear(params["time_in1"], temb.astype(x_t.dtype))))
    pos2d = params["pos_embed"][
        (jnp.arange(grid_h).repeat(grid_w) * cfg.max_latent_size
         + jnp.tile(jnp.arange(grid_w), grid_h))]
    x = nn.linear(params["vae2llm"], x_t) + temb[:, None, :] \
        + pos2d[None].astype(x_t.dtype)

    s_ctx = ctx_mask.shape[1]
    # latent tokens sit after the context on the rope axis
    positions = jnp.broadcast_to(
        (s_ctx + jnp.arange(s_lat))[None], (b, s_lat))
    cos, sin = _rope(cfg, positions)
    # attend: masked context keys + FULL attention among latent tokens
    bias = jnp.concatenate(
        [jnp.where(ctx_mask[:, None, None, :] > 0, 0.0, -1e30),
         jnp.zeros((b, 1, 1, s_lat))], axis=-1)
    bias = jnp.broadcast_to(bias, (b, 1, s_lat, s_ctx + s_lat))

    for layer, (ck, cv) in zip(params["layers"], ctx_kvs):
        exp = layer["gen"]
        q, k, v = _qkv(exp, cfg, x, cos, sin)
        k = jnp.concatenate([ck, k], axis=1)
        v = jnp.concatenate([cv, v], axis=1)
        o = _attend(q, k, v, bias)
        x = x + nn.linear(exp["o_proj"], o.reshape(b, s_lat, -1))
        x = x + _mlp(exp, cfg, x)
    x = rms_norm(x, params["final_norm"]["w"], cfg.rms_eps)
    return nn.linear(params["llm2vae"], x)


class BagelPipeline:
    """Text (+ optional conditioning image) -> image."""

    output_type = "image"
    needs_image_cond = False  # image conditioning is optional
    # vit trees live outside the default engine list
    param_attrs = ("dit_params", "vae_params", "vae_encoder_params",
                   "vit_params", "vit_connector")

    def __init__(self, config: BagelPipelineConfig, dtype=jnp.bfloat16,
                 seed: int = 0, mesh=None, cache_config=None,
                 init_weights: bool = True):
        from vllm_omni_tpu.parallel.pipeline_mesh import MeshWiring

        self.cfg = config
        self.dtype = dtype
        self.mesh = mesh
        self.cache_config = cache_config
        self.wiring = MeshWiring(mesh, type(self).__name__).validate(
            {"dp"})
        if cache_config is not None:
            raise ValueError("Bagel's LLM denoise has no step cache yet")
        self.tokenizer = ByteTokenizer(config.llm.vocab_size)
        k1, k2, k3 = jax.random.split(jax.random.PRNGKey(seed), 3)
        logger.info("Initializing BagelPipeline (dtype=%s)", dtype)
        # the MoT LLM *is* this pipeline's generator; stored as
        # dit_params so engine-level weight bookkeeping (LoRA/quant/
        # sleep) addresses the same tree the forward reads.  Subclasses
        # with a different stack override _build_llm_params (a second
        # full init after super().__init__ would transiently double the
        # weight memory).
        if init_weights:
            self.dit_params = self._build_llm_params(k1, config, dtype)
            self.vae_params = self.wiring.place(
                vae_mod.init_decoder(k2, config.vae, dtype))
        else:
            # from_pretrained fills every tree — a random 7B MoT first
            # would double peak host memory
            self.dit_params = None
            self.vae_params = None
        self.hf_tokenizer = None
        self._seed = seed
        self._denoise_cache: dict = {}
        self.vae_encoder_params = None  # built on demand (image intake)
        self._prefill_jit = jax.jit(
            lambda p, ids, mask: prefill_context(p, self.cfg.llm, ids,
                                                 mask))
        self._prefill_img_jit = jax.jit(
            lambda p, ids, mask, img: prefill_context(
                p, self.cfg.llm, ids, mask, img_tokens=img))
        self._prefill_vit_jit = jax.jit(
            lambda p, ids, mask, vit: prefill_context(
                p, self.cfg.llm, ids, mask, vit_tokens=vit))
        self._prefill_img_vit_jit = jax.jit(
            lambda p, ids, mask, img, vit: prefill_context(
                p, self.cfg.llm, ids, mask, img_tokens=img,
                vit_tokens=vit))
        # SigLIP understanding tower (optional)
        self.vit_params = None
        self.vit_connector = None
        if config.vit is not None:
            from vllm_omni_tpu.models.common import siglip

            kv1, kv2, kv3 = jax.random.split(
                jax.random.fold_in(k3, 7), 3)
            h = config.llm.hidden_size
            if init_weights:
                self.vit_params = self.wiring.place(
                    siglip.init_params(kv1, config.vit, dtype))
                self.vit_connector = self.wiring.place({
                    "fc1": nn.linear_init(kv2, config.vit.hidden_size,
                                          h, dtype=dtype),
                    "fc2": nn.linear_init(kv3, h, h, dtype=dtype),
                })
            # frozen 2D sincos table at LLM width (PositionEmbedding)
            self.vit_pos_embed = jnp.asarray(siglip.sincos_2d_pos_embed(
                h, config.vit_max_patch_per_side))
            # the flattened ids index row*max_side+col into the SigLIP
            # learned table — a too-small table would silently clamp
            # (real Bagel checkpoints interpolate the table to the
            # max_side grid at load time)
            need = config.vit_max_patch_per_side ** 2
            if config.vit.num_positions < need:
                raise ValueError(
                    f"SigLIP pos table ({config.vit.num_positions} rows)"
                    f" smaller than vit_max_patch_per_side^2 ({need}) — "
                    "interpolate the table or lower the grid")

            def _vit_fwd(vp, cp, toks, pos):
                feats = siglip.forward_packed(
                    vp, config.vit, toks, pos, [toks.shape[0]])
                x = nn.linear(cp["fc2"], jax.nn.gelu(
                    nn.linear(cp["fc1"], feats), approximate=True))
                return x + self.vit_pos_embed[pos].astype(x.dtype)

            self._vit_fwd_jit = jax.jit(_vit_fwd)
        self._img_ctx_jit = jax.jit(self._embed_image_context)
        self._vae_decode_jit = jax.jit(
            lambda pp, l: vae_mod.decode(pp, self.cfg.vae, l))

    def _build_llm_params(self, key, config, dtype):
        return self.wiring.place(init_params(key, config, dtype))

    @property
    def geometry_multiple(self) -> int:
        return self.cfg.vae.spatial_ratio * self.cfg.llm.patch

    def _denoise_fn(self, grid_h, grid_w, sched_len, use_cfg=True):
        key = (grid_h, grid_w, sched_len, use_cfg)
        if key in self._denoise_cache:
            return self._denoise_cache[key]
        cfg = self.cfg

        @jax.jit
        def run(params, noise, ctx_kvs, ctx_mask, uncond_kvs,
                uncond_mask, timesteps, dts, gscale, num_steps):
            def body(i, x):
                t = jnp.broadcast_to(timesteps[i], (x.shape[0],))
                v_cond = flow_velocity(params, cfg.llm, x, t, ctx_kvs,
                                       ctx_mask, grid_h, grid_w)
                if not use_cfg:
                    return x - v_cond * dts[i].astype(x.dtype)
                v_un = flow_velocity(params, cfg.llm, x, t, uncond_kvs,
                                     uncond_mask, grid_h, grid_w)
                v = v_un + gscale * (v_cond - v_un)
                # CFG renorm to the conditional norm, PER SAMPLE —
                # batched requests must not couple (generate_image
                # cfg_renorm_type="global" is global over one image)
                cn = jnp.linalg.norm(
                    v_cond.astype(jnp.float32).reshape(v.shape[0], -1),
                    axis=-1)
                vn = jnp.linalg.norm(
                    v.astype(jnp.float32).reshape(v.shape[0], -1),
                    axis=-1)
                scale = jnp.clip(cn / jnp.maximum(vn, 1e-8), 0.0, 1.0)
                v = (v.astype(jnp.float32)
                     * scale[:, None, None]).astype(v.dtype)
                # velocity points data -> noise: x <- x - v dt (:1369)
                return x - v * dts[i].astype(x.dtype)

            return jax.lax.fori_loop(0, num_steps, body, noise)

        self._denoise_cache[key] = run
        return run

    @staticmethod
    def _cond_image(req):
        """The request's conditioning image (sampling_params.image with
        the extra["image"] fallback) — ONE retrieval convention shared
        by the VAE and ViT intake paths."""
        sp = req.sampling_params
        return sp.image if sp.image is not None else sp.extra.get(
            "image")

    def _image_context(self, req, batch: int):
        """sampling_params.image -> vae2llm-projected context tokens
        [B, S_img, hidden] (prepare_vae_images, pipeline_bagel.py:393)
        or None."""
        image = self._cond_image(req)
        if image is None:
            return None
        sp = req.sampling_params
        cfg = self.cfg
        mult = self.geometry_multiple
        max_hw = cfg.llm.max_latent_size * cfg.vae.spatial_ratio
        h, w = np.asarray(image).shape[:2]
        th = max(mult, h // mult * mult)
        tw = max(mult, w // mult * mult)
        if th > max_hw or tw > max_hw:
            # an image beyond the pos_embed grid would index past the
            # 2D position table and silently corrupt the conditioning
            raise InvalidRequestError(
                f"conditioning image {h}x{w} exceeds the checkpoint "
                f"limit {max_hw}x{max_hw} (max_latent_size)")
        img = intake.prepare_cond_image(image, th, tw)
        if self.vae_encoder_params is None:
            self.vae_encoder_params = self.wiring.place(
                vae_mod.init_encoder(
                    jax.random.PRNGKey(self._seed + 1), cfg.vae,
                    jnp.float32))
        tokens = self._img_ctx_jit(self.vae_encoder_params,
                                   self.dit_params,
                                   jnp.asarray(img, jnp.float32))
        return jnp.repeat(tokens, batch, axis=0)

    def _embed_image_context(self, enc_params, params, img):
        """jit body: [H, W, 3] -> embedded context tokens [1, S, hidden]
        — VAE encode, 2x2 latent pack, vae2llm + t=0 timestep + 2D pos
        (the same embedding flow_velocity gives generated latents; the
        conditioning image is CLEAN, so t=0 on the 1->0 schedule)."""
        cfg = self.cfg
        lat = vae_mod.encode(enc_params, cfg.vae, img[None])
        p = cfg.llm.patch
        c = cfg.vae.latent_channels
        lh, lw = lat.shape[1:3]
        gh, gw = lh // p, lw // p
        x = lat.reshape(1, gh, p, gw, p, c)
        x = x.transpose(0, 1, 3, 2, 4, 5).reshape(1, gh * gw, p * p * c)
        x = x.astype(self.dtype)
        temb = nn.timestep_embedding(jnp.zeros((1,)), 256)
        temb = nn.linear(params["time_in2"], jax.nn.silu(
            nn.linear(params["time_in1"], temb.astype(x.dtype))))
        pos2d = params["pos_embed"][
            (jnp.arange(gh).repeat(gw) * cfg.llm.max_latent_size
             + jnp.tile(jnp.arange(gw), gh))]
        return (nn.linear(params["vae2llm"], x) + temb[:, None, :]
                + pos2d[None].astype(x.dtype))

    def _vit_context(self, req, batch: int):
        """sampling_params.image -> SigLIP understanding tokens
        [B, S_vit, hidden] (prepare_vit_images semantics: patchify,
        packed SigLIP, MLPconnector, frozen 2D sincos pos embed) or
        None when no tower / no image."""
        if self.vit_params is None:
            return None
        image = self._cond_image(req)
        if image is None:
            return None
        from vllm_omni_tpu.models.common import siglip

        patch = self.cfg.vit.patch_size
        max_side = self.cfg.vit_max_patch_per_side
        h, w = np.asarray(image).shape[:2]
        th = min(max_side * patch, max(patch, h // patch * patch))
        tw = min(max_side * patch, max(patch, w // patch * patch))
        img = intake.prepare_cond_image(image, th, tw)
        toks = siglip.patchify(img.transpose(2, 0, 1), patch)
        pos = siglip.flattened_position_ids_extrapolate(
            th, tw, patch, max_side)
        x = self._vit_fwd_jit(self.vit_params, self.vit_connector,
                              jnp.asarray(toks, self.dtype),
                              jnp.asarray(pos))
        return jnp.repeat(x[None], batch, axis=0)

    def _context_ids(self, prompts: list[str]):
        if self.hf_tokenizer is not None:
            # reference prepare_prompts wraps every prompt as
            # [<|im_start|>] + text + [<|im_end|>] (add_special_tokens
            # registers them, bagel_transformer.py:886)
            tok = self.hf_tokenizer
            bos = tok.convert_tokens_to_ids("<|im_start|>")
            eos = tok.convert_tokens_to_ids("<|im_end|>")
            unk = tok.unk_token_id
            wrap = (bos is not None and bos != unk and bos >= 0
                    and eos is not None and eos != unk and eos >= 0)
            s_max = self.cfg.max_text_len
            body = s_max - 2 if wrap else s_max
            ids = np.zeros((len(prompts), s_max), np.int64)
            mask = np.zeros((len(prompts), s_max), np.int32)
            pad = tok.pad_token_id or 0
            ids[:] = pad
            for i, ptxt in enumerate(prompts):
                t = tok(ptxt, add_special_tokens=False,
                        truncation=True,
                        max_length=body)["input_ids"]
                if wrap:
                    t = [bos] + list(t) + [eos]
                ids[i, :len(t)] = t
                mask[i, :len(t)] = 1
            return jnp.asarray(ids), jnp.asarray(mask)
        ids, lens = self.tokenizer.batch_encode(prompts,
                                                self.cfg.max_text_len)
        mask = (np.arange(self.cfg.max_text_len)[None, :]
                < lens[:, None]).astype(np.int32)
        return jnp.asarray(ids), jnp.asarray(mask)

    @classmethod
    def from_pretrained(cls, model_dir: str, dtype=jnp.bfloat16,
                        seed: int = 0, mesh=None, cache_config=None,
                        max_text_len: int = 128) -> "BagelPipeline":
        """Build from the published single-repo BAGEL checkpoint:
        config.json + llm_config.json + vit_config.json describe the
        stacks, ema.safetensors carries the MoT LLM + bagel heads +
        SigLIP tower, ae.safetensors the FLUX autoencoder at the BFL
        names (reference pipeline_bagel.py:159-258)."""
        import os

        from vllm_omni_tpu.models.bagel import loader as bloader

        llm_cfg, vit_cfg, vae_cfg, bagel_hf = \
            bloader.config_from_bagel(model_dir)
        config = BagelPipelineConfig(
            llm=llm_cfg, vae=vae_cfg, max_text_len=max_text_len,
            vit=vit_cfg,
            vit_max_patch_per_side=int(
                bagel_hf.get("vit_max_num_patch_per_side", 70)))
        pipe = cls(config, dtype=dtype, seed=seed, mesh=mesh,
                   cache_config=cache_config, init_weights=False)
        pipe.dit_params = pipe.wiring.place(
            bloader.load_bagel_lm(model_dir, config, dtype=dtype))
        if vit_cfg is not None:
            vit_params, extra = bloader.load_bagel_vit(
                model_dir, config, dtype=dtype)
            pipe.vit_params = pipe.wiring.place(vit_params)
            pipe.vit_connector = pipe.wiring.place(
                {"fc1": extra["fc1"], "fc2": extra["fc2"]})
            # the checkpoint's frozen sincos table replaces the
            # locally built one (identical content, checkpoint wins)
            pipe.vit_pos_embed = extra["pos"]
        ae_path = os.path.join(model_dir, "ae.safetensors")
        if not os.path.isfile(ae_path):
            raise ValueError(f"{model_dir} has no ae.safetensors")
        trees, _ = bloader.load_bagel_vae(
            ae_path, cfg=vae_cfg, dtype=jnp.float32, encoder=True,
            decoder=True)
        pipe.vae_params = pipe.wiring.place(trees["decoder"])
        pipe.vae_encoder_params = pipe.wiring.place(trees["encoder"])
        from transformers import AutoTokenizer

        # a byte-tokenizer fallback beside real weights would feed
        # garbage conditioning — fail loudly instead
        pipe.hf_tokenizer = AutoTokenizer.from_pretrained(model_dir)
        return pipe

    def forward(self, req: OmniDiffusionRequest) -> list[DiffusionOutput]:
        sp = req.sampling_params
        cfg = self.cfg
        mult = self.geometry_multiple
        max_hw = cfg.llm.max_latent_size * cfg.vae.spatial_ratio
        height = sp.height or max_hw
        width = sp.width or max_hw
        if height % mult or width % mult:
            raise InvalidRequestError(
                f"height/width must be multiples of {mult}")
        if height > max_hw or width > max_hw:
            raise InvalidRequestError(
                f"{height}x{width} exceeds the checkpoint limit "
                f"{max_hw}x{max_hw} (max_latent_size)")
        grid_h = height // mult
        grid_w = width // mult
        prompts = req.prompt
        b = len(prompts)

        ids, mask = self._context_ids(prompts)
        img_tokens = self._image_context(req, b)
        vit_tokens = self._vit_context(req, b)

        def prefill(text_mask):
            # conditioning image(s): VAE latents join the context
            # through vae2llm (forward_cache_update_vae); SigLIP
            # understanding tokens ride the und expert
            if img_tokens is None and vit_tokens is None:
                return self._prefill_jit(self.dit_params, ids, text_mask)
            if img_tokens is None:
                return self._prefill_vit_jit(self.dit_params, ids,
                                             text_mask, vit_tokens)
            if vit_tokens is None:
                return self._prefill_img_jit(self.dit_params, ids,
                                             text_mask, img_tokens)
            return self._prefill_img_vit_jit(
                self.dit_params, ids, text_mask, img_tokens, vit_tokens)

        ctx_kvs, mask = prefill(mask)
        # text-CFG branch: drop the TEXT, keep the conditioning image
        # (cfg_text semantics — the reference cfg_text branch holds the
        # image context constant and only blanks the prompt).  Without a
        # conditioning image the all-masked context makes latents attend
        # only themselves, so the conditional KVs can be reused; WITH an
        # image the image KVs were computed attending the text, so a
        # text-free second prefill is required or the prompt leaks into
        # the "unconditional" branch through the image keys
        use_cfg = sp.guidance_scale > 1.0
        un_mask = jnp.zeros_like(mask)
        if (img_tokens is not None or vit_tokens is not None) and use_cfg:
            un_mask = un_mask.at[:, ids.shape[1]:].set(1)
            uncond_kvs, _ = prefill(
                jnp.zeros_like(mask[:, :ids.shape[1]]))
        else:
            uncond_kvs = ctx_kvs

        steps = max(1, sp.num_inference_steps)
        sched_len = max(steps, cfg.steps_bucket)
        shift = cfg.llm.timestep_shift
        ts = np.linspace(1.0, 0.0, steps + 1)
        ts = shift * ts / (1 + (shift - 1) * ts)
        dts = ts[:-1] - ts[1:]
        t_pad = np.zeros(sched_len, np.float32)
        t_pad[:steps] = ts[:-1]
        d_pad = np.zeros(sched_len, np.float32)
        d_pad[:steps] = dts

        seed = (sp.seed if sp.seed is not None
                else int(np.random.randint(0, 2 ** 31 - 1)))
        noise = jax.random.normal(
            jax.random.PRNGKey(seed),
            (b, grid_h * grid_w, cfg.llm.latent_dim), jnp.float32,
        ).astype(self.dtype)

        run = self._denoise_fn(grid_h, grid_w, sched_len, use_cfg)
        latents = run(self.dit_params, noise, ctx_kvs, mask, uncond_kvs,
                      un_mask, jnp.asarray(t_pad), jnp.asarray(d_pad),
                      jnp.float32(sp.guidance_scale),
                      jnp.int32(steps))

        p = cfg.llm.patch
        c = cfg.vae.latent_channels
        x = latents.reshape(b, grid_h, grid_w, p, p, c)
        x = x.transpose(0, 1, 3, 2, 4, 5).reshape(
            b, grid_h * p, grid_w * p, c)
        img = self._vae_decode_jit(self.vae_params, x.astype(jnp.float32))
        img = np.asarray(jnp.clip(
            (img.astype(jnp.float32) + 1.0) * 127.5, 0, 255)
            .astype(jnp.uint8))
        return [
            DiffusionOutput(request_id=req.request_ids[i],
                            prompt=prompts[i], data=img[i],
                            output_type="image")
            for i in range(b)
        ]
