"""BAGEL single-repo checkpoint loaders.

The published repo is non-diffusers: ``config.json`` (bagel core knobs +
vae/vit sub-dicts), ``llm_config.json`` (Qwen2 MoT fields, qk_norm
forced on — reference pipeline_bagel.py:183-190), ``vit_config.json``
(SigLIP), ``ema.safetensors`` (LLM + bagel heads + vit tower) and
``ae.safetensors`` (FLUX AutoencoderKL at the original BFL module names
— reference autoencoder.py Decoder/Encoder, NOT the diffusers
up_blocks naming).
"""

from __future__ import annotations

import json
import os

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.logger import init_logger

logger = init_logger(__name__)

_LM_PREFIX = "language_model.model."


def config_from_bagel(model_dir: str):
    """(BagelConfig, SigLIPConfig | None, VAEConfig, max_text_len hint)
    from config.json + llm_config.json + vit_config.json."""
    from vllm_omni_tpu.models.bagel.pipeline import BagelConfig
    from vllm_omni_tpu.models.common.siglip import SigLIPConfig
    from vllm_omni_tpu.models.qwen_image.vae import VAEConfig

    with open(os.path.join(model_dir, "config.json")) as f:
        bagel = json.load(f)
    with open(os.path.join(model_dir, "llm_config.json")) as f:
        llm = json.load(f)
    heads = llm["num_attention_heads"]
    llm_cfg = BagelConfig(
        vocab_size=llm["vocab_size"],
        hidden_size=llm["hidden_size"],
        num_layers=llm["num_hidden_layers"],
        num_heads=heads,
        num_kv_heads=llm.get("num_key_value_heads", heads),
        head_dim=llm["hidden_size"] // heads,
        intermediate_size=llm.get("intermediate_size", 18944),
        rope_theta=llm.get("rope_theta", 1e6),
        rms_eps=llm.get("rms_norm_eps", 1e-6),
        # the reference forces QK-norm on for MoT (pipeline_bagel:185)
        qk_norm=True,
        latent_channels=int(
            (bagel.get("vae_config") or {}).get("z_channels", 16)),
        patch=int(bagel.get("latent_patch_size", 2)),
        max_latent_size=int(bagel.get("max_latent_size", 32)),
        timestep_shift=float(bagel.get("timestep_shift", 1.0)),
    )
    vit_cfg = None
    vit_path = os.path.join(model_dir, "vit_config.json")
    if os.path.isfile(vit_path):
        with open(vit_path) as f:
            vit_hf = json.load(f)
        vit_cfg = SigLIPConfig.from_hf(vit_hf)
    vd = bagel.get("vae_config") or {}
    # flux AE defaults (default_ae_params, :107-120); the extra keys
    # exist so tiny test checkpoints can shrink the autoencoder
    vae_cfg = VAEConfig(
        latent_channels=int(vd.get("z_channels", 16)),
        base_channels=int(vd.get("base_channels", 128)),
        channel_multipliers=tuple(vd.get("channel_multipliers",
                                         (1, 2, 4, 4))),
        layers_per_block=int(vd.get("layers_per_block", 2)),
        scaling_factor=float(vd.get("scale_factor", 0.3611)),
        shift_factor=float(vd.get("shift_factor", 0.1159)),
    )
    return llm_cfg, vit_cfg, vae_cfg, bagel


def load_bagel_lm(model_dir: str, pcfg, dtype=jnp.bfloat16):
    """The MoT LLM + bagel heads out of ema.safetensors: per-layer und
    (plain names) and gen (``_moe_gen``) experts, QK norms, the
    time/vae2llm/llm2vae heads and the frozen latent pos table.  The
    gen head norm (``norm_moe_gen``) lands in ``final_norm`` — the
    velocity head normalizes only VAE tokens (Qwen2MoTModel.forward
    gen branch)."""
    from vllm_omni_tpu.models.bagel.pipeline import init_params
    from vllm_omni_tpu.models.flux.loader import load_routed

    cfg = pcfg.llm
    shapes = jax.eval_shape(
        lambda: init_params(jax.random.PRNGKey(0), pcfg, jnp.float32))
    # the pipeline tree also carries vit trees when pcfg.vit is set;
    # init_params only builds the LLM side, which is what we cover here
    r: dict[str, tuple] = {
        f"{_LM_PREFIX}embed_tokens.weight": ("raw", ("embed", "w")),
        f"{_LM_PREFIX}norm_moe_gen.weight":
            ("direct", ("final_norm", "w")),
        "time_embedder.mlp.0.weight": ("direct", ("time_in1", "w")),
        "time_embedder.mlp.0.bias": ("direct", ("time_in1", "b")),
        "time_embedder.mlp.2.weight": ("direct", ("time_in2", "w")),
        "time_embedder.mlp.2.bias": ("direct", ("time_in2", "b")),
        "vae2llm.weight": ("direct", ("vae2llm", "w")),
        "vae2llm.bias": ("direct", ("vae2llm", "b")),
        "llm2vae.weight": ("direct", ("llm2vae", "w")),
        "llm2vae.bias": ("direct", ("llm2vae", "b")),
        "latent_pos_embed.pos_embed": ("raw", ("pos_embed",)),
    }
    for i in range(cfg.num_layers):
        lp = f"{_LM_PREFIX}layers.{i}"
        for ours, sfx in (("und", ""), ("gen", "_moe_gen")):
            t = ("layers", i, ours)
            for nm in ("q_proj", "k_proj", "v_proj"):
                r[f"{lp}.self_attn.{nm}{sfx}.weight"] = \
                    ("direct", t + (nm, "w"))
                r[f"{lp}.self_attn.{nm}{sfx}.bias"] = \
                    ("direct", t + (nm, "b"))
            r[f"{lp}.self_attn.o_proj{sfx}.weight"] = \
                ("direct", t + ("o_proj", "w"))
            if cfg.qk_norm:
                r[f"{lp}.self_attn.q_norm{sfx}.weight"] = \
                    ("direct", t + ("q_norm", "w"))
                r[f"{lp}.self_attn.k_norm{sfx}.weight"] = \
                    ("direct", t + ("k_norm", "w"))
            mlp = f"{lp}.mlp{sfx}" if sfx else f"{lp}.mlp"
            r[f"{mlp}.gate_proj.weight"] = \
                ("fuse", t + ("gate_up", "w"), 0, 2)
            r[f"{mlp}.up_proj.weight"] = \
                ("fuse", t + ("gate_up", "w"), 1, 2)
            r[f"{mlp}.down_proj.weight"] = ("direct", t + ("down", "w"))
            r[f"{lp}.input_layernorm{sfx}.weight"] = \
                ("direct", t + ("input_norm", "w"))
            r[f"{lp}.post_attention_layernorm{sfx}.weight"] = \
                ("direct", t + ("post_norm", "w"))
    return load_routed(model_dir, r, shapes, dtype)


def load_bagel_vit(model_dir: str, pcfg, dtype=jnp.bfloat16):
    """SigLIP tower (``vit_model.vision_model.*``) + MLPconnector +
    learned vit position table out of ema.safetensors."""
    from vllm_omni_tpu.models.common import siglip
    from vllm_omni_tpu.models.flux.loader import load_routed
    from vllm_omni_tpu.models.common import nn

    vit_params, _ = siglip.load_siglip(model_dir, cfg=pcfg.vit,
                                       dtype=dtype)
    h = pcfg.llm.hidden_size
    shapes = jax.eval_shape(lambda: {
        "fc1": nn.linear_init(jax.random.PRNGKey(0),
                              pcfg.vit.hidden_size, h,
                              dtype=jnp.float32),
        "fc2": nn.linear_init(jax.random.PRNGKey(0), h, h,
                              dtype=jnp.float32),
        "pos": jnp.zeros((pcfg.vit_max_patch_per_side ** 2, h),
                         jnp.float32),
    })
    r = {
        "connector.fc1.weight": ("direct", ("fc1", "w")),
        "connector.fc1.bias": ("direct", ("fc1", "b")),
        "connector.fc2.weight": ("direct", ("fc2", "w")),
        "connector.fc2.bias": ("direct", ("fc2", "b")),
        "vit_pos_embed.pos_embed": ("raw", ("pos",)),
    }
    extra = load_routed(model_dir, r, shapes, dtype)
    return vit_params, extra


def _bfl_vae_routing(cfg, half: str):
    """BFL AutoEncoder names (reference bagel/autoencoder.py) -> the
    qwen_image.vae tree paths, with the decoder's ``up`` ModuleList in
    BFL's REVERSED index order (Decoder builds via ``up.insert(0, ...)``
    so up.{n-1} runs first)."""
    flat: dict[str, tuple] = {}
    attn_names: set = set()

    def wb(hf, *path):
        flat[f"{hf}.weight"] = path + ("w",)
        flat[f"{hf}.bias"] = path + ("b",)

    def resnet(hf, tgt, cin, cout):
        wb(f"{hf}.norm1", *tgt, "norm1")
        wb(f"{hf}.conv1", *tgt, "conv1")
        wb(f"{hf}.norm2", *tgt, "norm2")
        wb(f"{hf}.conv2", *tgt, "conv2")
        if cin != cout:
            wb(f"{hf}.nin_shortcut", *tgt, "skip")

    def attn(hf, tgt):
        wb(f"{hf}.norm", *tgt, "norm")
        for bfl, ours in (("q", "q"), ("k", "k"), ("v", "v"),
                          ("proj_out", "o")):
            wb(f"{hf}.{bfl}", *tgt, ours)
            attn_names.add(f"{hf}.{bfl}.weight")

    chans = [cfg.base_channels * x for x in cfg.channel_multipliers]
    n = len(chans)
    if half == "decoder":
        top = chans[-1]
        wb("decoder.conv_in", "conv_in")
        resnet("decoder.mid.block_1", ("mid_res1",), top, top)
        attn("decoder.mid.attn_1", ("mid_attn",))
        resnet("decoder.mid.block_2", ("mid_res2",), top, top)
        cur = top
        for i, ch in enumerate(reversed(chans)):
            bfl = f"decoder.up.{n - 1 - i}"
            for j in range(cfg.layers_per_block + 1):
                resnet(f"{bfl}.block.{j}", ("ups", i, "res", j), cur, ch)
                cur = ch
            if i < n - 1:
                wb(f"{bfl}.upsample.conv", "ups", i, "up_conv")
        wb("decoder.norm_out", "norm_out")
        wb("decoder.conv_out", "conv_out")
    else:
        wb("encoder.conv_in", "conv_in")
        cur = chans[0]
        for i, ch in enumerate(chans):
            bfl = f"encoder.down.{i}"
            for j in range(cfg.layers_per_block):
                resnet(f"{bfl}.block.{j}", ("downs", i, "res", j),
                       cur, ch)
                cur = ch
            if i < n - 1:
                wb(f"{bfl}.downsample.conv", "downs", i, "down_conv")
        resnet("encoder.mid.block_1", ("mid_res1",), cur, cur)
        attn("encoder.mid.attn_1", ("mid_attn",))
        resnet("encoder.mid.block_2", ("mid_res2",), cur, cur)
        wb("encoder.norm_out", "norm_out")
        wb("encoder.conv_out", "conv_out")
    return flat, attn_names


def load_bagel_vae(ae_path: str, cfg=None, dtype=jnp.float32,
                   encoder: bool = False, decoder: bool = True):
    """ae.safetensors (BFL FLUX AutoencoderKL, bare encoder./decoder.
    names) -> {"decoder"?, "encoder"?} qwen_image.vae trees."""
    from vllm_omni_tpu.model_loader.safetensors_loader import (
        load_checkpoint_tree,
    )
    from vllm_omni_tpu.models.qwen_image import vae as iv
    from vllm_omni_tpu.models.qwen_image.vae import VAEConfig

    if cfg is None:
        cfg = VAEConfig()
    out = {}
    halves = ([("decoder", iv.init_decoder)] if decoder else []) + \
        ([("encoder", iv.init_encoder)] if encoder else [])
    for half, init in halves:
        flat, attn_names = _bfl_vae_routing(cfg, half)
        shapes = jax.eval_shape(
            lambda init=init: init(jax.random.PRNGKey(0), cfg,
                                   jnp.float32))
        tree = jax.tree.map(lambda t: np.zeros(t.shape, np.float32),
                            shapes)

        def transform(name, arr, attn_names=attn_names):
            if name in attn_names:
                # BFL attention q/k/v/proj_out are 1x1 Conv2d
                # [O, I, 1, 1] -> linear [I, O]
                return np.ascontiguousarray(
                    arr.reshape(arr.shape[0], arr.shape[1]).T)
            if arr.ndim == 4:
                return arr.transpose(2, 3, 1, 0)   # NHWC
            if arr.ndim == 2:
                return arr.T
            return arr

        nloaded, _ = load_checkpoint_tree(
            ae_path, flat.get, tree, dtype=np.float32,
            transform=transform,
            name_filter=lambda nm, flat=flat: nm in flat,
        )
        n_leaves = len(jax.tree.leaves(tree))
        if nloaded < n_leaves:
            raise ValueError(
                f"{ae_path} covered {nloaded}/{n_leaves} {half} VAE "
                "weights")
        out[half] = jax.tree.map(lambda a: jnp.asarray(a, dtype), tree)
    return out, cfg
