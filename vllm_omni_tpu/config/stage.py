"""Declarative multi-stage pipeline configuration.

Reimplements the reference's stage-config YAML system
(vllm_omni/model_executor/stage_configs/*.yaml, e.g. qwen3_omni_moe.yaml:8-101,
loaded by entrypoints/utils.py ``load_stage_configs_from_model`` /
``load_stage_configs_from_yaml`` / ``resolve_model_config_path``).

Schema (YAML):

.. code-block:: yaml

    stage_args:
      - stage_id: 0
        stage_type: llm            # llm | diffusion
        runtime:
          devices: "0"             # device ids for this stage
          max_batch_size: 8
          batch_timeout: 0.05
        engine_args: { ... }       # OmniModelConfig / OmniDiffusionConfig kwargs
        engine_input_source: [-1]  # stage ids feeding this stage (-1 = user)
        custom_process_input_func: "pkg.mod:fn"   # optional
        final_output: true
        final_output_type: text
        default_sampling_params: { ... }
        output_connectors: { "1": {connector: shm} }
"""

from __future__ import annotations

import importlib
import os
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import yaml

from vllm_omni_tpu.logger import init_logger

logger = init_logger(__name__)

# In-tree stage configs directory (analogue of
# vllm_omni/model_executor/stage_configs/).
_STAGE_CONFIG_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "models",
    "stage_configs",
)


@dataclass
class StageRuntime:
    devices: str = "all"  # "all" | comma-separated local device ids
    max_batch_size: int = 1
    batch_timeout: float = 0.0
    # run this stage in its own spawned process (cross-process stage
    # disaggregation; reference: omni_stage.py:394-504 worker spawn) with
    # env applied before jax import (device scoping — a TPU chip admits
    # one process, so sibling stages pin JAX_PLATFORMS/TPU_VISIBLE_CHIPS)
    process: bool = False
    device_env: dict = field(default_factory=dict)
    # orchestrator<->worker message transport for process stages:
    # "tcp" (default; also cross-host) | "shm" (native C++ shared-memory
    # rings, same-host — vllm_omni_tpu/native/shm_ring.cpp)
    transport: str = "tcp"
    # Cross-HOST stage placement (reference: Ray per-node worker
    # scheduling, distributed/ray_utils/utils.py): remote=True makes the
    # orchestrator LISTEN on (bind_host, bind_port) instead of spawning a
    # local child; the worker is started on its host with the serve-stage
    # CLI and connects (directly, or via KV-store discovery when
    # ``discovery`` is a store address the orchestrator publishes to).
    remote: bool = False
    bind_host: str = "127.0.0.1"
    bind_port: int = 0
    discovery: str = ""
    # address REMOTE workers should dial (published to discovery): the
    # bind address is often undialable (0.0.0.0, or 127.0.0.1 from
    # another host); defaults to this host's primary IP when binding all
    # interfaces, else bind_host
    advertise_host: str = ""
    # Stage supervision (resilience/supervisor.py): heartbeat the worker
    # over ping/pong frames, restart it on crash/hang with exponential
    # backoff (locally-spawned workers only), redeliver queued-but-
    # unstarted requests once, and fail mid-execution requests fast with
    # a retryable error.  supervise=False keeps the bare ProcStage
    # behavior (a dead worker permanently fails its in-flight set).
    supervise: bool = True
    max_restarts: int = 3
    # heartbeat budget before a silent worker is declared HUNG; generous
    # by default — an XLA compile mid-traffic stalls pongs for tens of
    # seconds and must not read as a hang (set interval 0 to disable)
    heartbeat_interval_s: float = 5.0
    heartbeat_misses: int = 12


@dataclass
class StageConfig:
    stage_id: int
    stage_type: str  # "llm" | "diffusion"
    runtime: StageRuntime = field(default_factory=StageRuntime)
    engine_args: dict[str, Any] = field(default_factory=dict)
    # stage ids whose outputs feed this stage; -1 means the user prompt
    engine_input_source: list[int] = field(default_factory=lambda: [-1])
    custom_process_input_func: str = ""
    final_output: bool = False
    final_output_type: str = "text"
    default_sampling_params: dict[str, Any] = field(default_factory=dict)
    # next_stage_id(str) -> connector spec dict
    output_connectors: dict[str, dict[str, Any]] = field(default_factory=dict)

    def resolve_input_processor(self) -> Optional[Callable]:
        """Import the ``pkg.mod:fn`` hook deriving this stage's inputs from
        upstream outputs (reference: custom_process_input_func in stage YAML,
        e.g. stage_input_processors/qwen3_omni.py)."""
        if not self.custom_process_input_func:
            return None
        mod_name, _, fn_name = self.custom_process_input_func.partition(":")
        mod = importlib.import_module(mod_name)
        return getattr(mod, fn_name)


def _parse_stage(d: dict[str, Any]) -> StageConfig:
    d = dict(d)
    runtime = d.pop("runtime", {}) or {}
    known = StageConfig.__dataclass_fields__
    unknown = [k for k in d if k not in known]
    if unknown:
        raise KeyError(f"unknown stage config keys: {unknown}")
    eis = d.pop("engine_input_source", [-1])
    if isinstance(eis, int):
        eis = [eis]
    oc = d.pop("output_connectors", {}) or {}
    oc = {str(k): dict(v) for k, v in oc.items()}
    return StageConfig(
        runtime=StageRuntime(**runtime),
        engine_input_source=[int(x) for x in eis],
        output_connectors=oc,
        **d,
    )


def load_stage_configs_from_yaml(path: str) -> list[StageConfig]:
    with open(path) as f:
        doc = yaml.safe_load(f)
    if not isinstance(doc, dict) or not doc.get("stage_args"):
        raise ValueError(f"{path}: expected non-empty top-level 'stage_args' list")
    stages = [_parse_stage(s) for s in doc["stage_args"]]
    ids = [s.stage_id for s in stages]
    if sorted(ids) != list(range(len(stages))):
        raise ValueError(f"{path}: stage_ids must be 0..N-1, got {ids}")
    stages.sort(key=lambda s: s.stage_id)
    if not any(s.final_output for s in stages):
        stages[-1].final_output = True
    return stages


# real HF checkpoint names carry size/variant suffixes
# (Qwen3-Omni-30B-A3B-Instruct); the FAMILY prefix picks the pipeline
_FAMILY_YAMLS = (
    ("qwen3_omni", "qwen3_omni_moe"),
    ("qwen2_5_omni", "qwen2_5_omni"),
    ("qwen3_tts", "qwen3_tts"),
    ("qwen_image", "qwen_image"),
)

# checkpoint config.json `architectures` -> family YAML: the front door
# for local directories whose basename says nothing (reference: the
# registry resolves models by architecture,
# model_executor/models/registry.py:65)
_ARCH_YAMLS = {
    "Qwen3OmniMoeForConditionalGeneration": "qwen3_omni_moe",
    "Qwen2_5OmniForConditionalGeneration": "qwen2_5_omni",
    "Qwen2_5OmniModel": "qwen2_5_omni",
    "Qwen3TTSForConditionalGeneration": "qwen3_tts",
}


def _arch_of(model: str) -> Optional[str]:
    """architectures[0] from a local checkpoint's config.json, if any."""
    p = os.path.join(model, "config.json")
    if not os.path.isfile(p):
        return None
    try:
        import json

        with open(p) as f:
            archs = json.load(f).get("architectures") or []
        return archs[0] if archs else None
    except Exception:
        return None


def resolve_model_config_path(model: str) -> Optional[str]:
    """Map a model name/path to an in-tree stage YAML (reference:
    entrypoints/utils.py resolve_model_config_path): exact normalized
    basename first, then the model-family prefix, then — for local
    checkpoint directories — the config.json architecture name."""
    base = os.path.basename(os.path.normpath(model)).lower().replace("-", "_")
    candidates = [base, base.replace(".", "_")]
    for cand in candidates:
        p = os.path.join(_STAGE_CONFIG_DIR, cand + ".yaml")
        if os.path.exists(p):
            return p
    for prefix, yaml_name in _FAMILY_YAMLS:
        if any(c.startswith(prefix) for c in candidates):
            p = os.path.join(_STAGE_CONFIG_DIR, yaml_name + ".yaml")
            if os.path.exists(p):
                return p
    arch = _arch_of(model)
    if arch and arch in _ARCH_YAMLS:
        p = os.path.join(_STAGE_CONFIG_DIR, _ARCH_YAMLS[arch] + ".yaml")
        if os.path.exists(p):
            logger.info("resolved %s via architecture %s", model, arch)
            return p
    return None


def load_stage_configs_from_model(
    model: str, stage_configs_path: Optional[str] = None
) -> list[StageConfig]:
    """Load stage configs for a model: explicit path wins, then the in-tree
    YAML for the model name, else a single-stage default (llm)."""
    if stage_configs_path:
        return load_stage_configs_from_yaml(stage_configs_path)
    p = resolve_model_config_path(model)
    if p is not None:
        logger.info("Using stage config %s for model %s", p, model)
        stages = load_stage_configs_from_yaml(p)
        for s in stages:
            # Single-model stages inherit the user's checkpoint path
            # (reference: the serve CLI's model arg overrides the stage
            # YAML's model field); factory-built stages keep theirs —
            # EXCEPT factory args that declare ``model_dir: null``,
            # which real-model YAMLs use to receive the user's path.
            if ("model" not in s.engine_args
                    and "model_factory" not in s.engine_args):
                s.engine_args["model"] = model
            for key in ("model_factory_args", "mm_processor_args"):
                fa = s.engine_args.get(key)
                if isinstance(fa, dict) and fa.get("model_dir", "") is None:
                    fa["model_dir"] = model
        return stages
    # Single-stage default, like the reference's diffusion autodetect
    # (cli/serve.py:55-63): model_index.json => diffusion.
    stage_type = "llm"
    if os.path.isdir(model) and os.path.exists(
        os.path.join(model, "model_index.json")
    ):
        stage_type = "diffusion"
    return [
        StageConfig(
            stage_id=0,
            stage_type=stage_type,
            engine_args={"model": model},
            final_output=True,
        )
    ]
