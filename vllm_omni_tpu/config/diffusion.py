"""Diffusion engine configuration (reference: ``OmniDiffusionConfig``,
vllm_omni/diffusion/data.py:245-385, and ``DiffusionParallelConfig``
data.py:28-52)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from vllm_omni_tpu.config.model import split_known_kwargs
from vllm_omni_tpu.parallel.mesh import MeshConfig


@dataclass
class OmniDiffusionConfig:
    model: str = ""
    model_arch: str = ""  # pipeline class key in DiffusionModelRegistry
    dtype: str = "auto"
    seed: int = 0

    # attention backend override ("auto" => platform pick)
    attention_backend: str = "auto"

    # step-cache acceleration (reference: cache/base.py:31 + selector):
    # "" => off; "teacache" (lax.cond-gated rel-L1 step skip)
    cache_backend: str = ""
    cache_config: dict[str, Any] = field(default_factory=dict)

    # parallel degrees (dp/cfg/sp=ulysses*ring/pp/tp)
    parallel: MeshConfig = field(default_factory=MeshConfig)
    # VAE spatial patch parallel degree (reference: data.py:52)
    vae_patch_parallel_size: int = 1

    # host offload of weights between stage invocations (reference sleep
    # mode via CuMemAllocator, diffusion_worker.py:204-271 -> TPU host
    # offload via device_put)
    enable_sleep_mode: bool = False

    # "" | "layerwise": stream block weights host->HBM per use so models
    # larger than HBM run on one chip (reference:
    # diffusion/offloader/layerwise_backend.py)
    offload: str = ""

    # quantization: "" | "int8" | "fp8"
    quantization: str = ""

    # default generation geometry
    default_height: int = 1024
    default_width: int = 1024
    default_num_inference_steps: int = 50
    # spatial cap (per side) for the video-pipeline warmup generation —
    # video token counts scale with frames * H * W and must not inherit
    # the image default geometry (ADVICE r1 high: 1024² video warmup
    # attempted a ~1.1 TiB allocation)
    warmup_video_size: int = 256

    extra: dict[str, Any] = field(default_factory=dict)

    @classmethod
    def from_kwargs(cls, **kwargs: Any) -> "OmniDiffusionConfig":
        if "parallel" in kwargs and isinstance(kwargs["parallel"], dict):
            kwargs["parallel"] = MeshConfig.from_dict(kwargs["parallel"])
        known, extra = split_known_kwargs(cls, kwargs)
        return cls(**known, extra=extra)
