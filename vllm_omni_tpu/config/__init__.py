from vllm_omni_tpu.config.model import OmniModelConfig
from vllm_omni_tpu.config.diffusion import OmniDiffusionConfig
from vllm_omni_tpu.config.stage import (
    StageConfig,
    load_stage_configs_from_model,
    load_stage_configs_from_yaml,
)

__all__ = [
    "OmniModelConfig",
    "OmniDiffusionConfig",
    "StageConfig",
    "load_stage_configs_from_model",
    "load_stage_configs_from_yaml",
]
