"""Model / engine configuration.

``OmniModelConfig`` covers the reference's ``OmniModelConfig``
(vllm_omni/config/model.py:18,46-60): per-stage identity (stage_id,
model_stage), worker type (ar vs one-shot generation vs diffusion), the
engine output type flowing to the next stage, sub-config selection for
multi-part HF checkpoints, and cross-stage connector/KV config.  It also
absorbs the slice of vLLM's ``ModelConfig``/``EngineArgs`` the reference
leans on (max_model_len, dtype, kv-cache geometry) since there is no
upstream vllm dependency here.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

import jax.numpy as jnp

_DTYPES = {
    "bfloat16": jnp.bfloat16,
    "bf16": jnp.bfloat16,
    "float32": jnp.float32,
    "fp32": jnp.float32,
    "float16": jnp.float16,
    "fp16": jnp.float16,
    "auto": None,
}


def split_known_kwargs(cls, kwargs: dict) -> tuple[dict, dict]:
    """Split kwargs into dataclass fields vs the ``extra`` escape hatch
    (shared by the config ``from_kwargs`` constructors)."""
    fields = cls.__dataclass_fields__
    known = {k: v for k, v in kwargs.items() if k in fields and k != "extra"}
    extra = {k: v for k, v in kwargs.items() if k not in fields}
    extra.update(kwargs.get("extra") or {})
    return known, extra


def resolve_dtype(name: Optional[str]):
    if name is None or name == "auto":
        from vllm_omni_tpu.platforms import current_platform

        return current_platform().preferred_dtype()
    if isinstance(name, str):
        return _DTYPES[name]
    return name


@dataclass
class OmniModelConfig:
    # --- identity -----------------------------------------------------
    model: str = ""  # model name or local path
    stage_id: int = 0
    # thinker / talker / code2wav / dit / text_encoder / vae ...
    model_stage: str = ""
    model_arch: str = ""  # architecture key into the model registry
    # "ar" (continuous batching) | "generation" (one-shot) | "diffusion"
    worker_type: str = "ar"
    # what the engine emits for the next stage / user:
    # "text" | "latent" | "audio" | "image" | "embedding" | "token_ids"
    engine_output_type: str = "text"
    # sub-config name inside a multi-part HF checkpoint
    # (reference: hf_config_name, config/model.py:52)
    hf_config_name: str = ""

    # --- engine geometry ---------------------------------------------
    dtype: str = "auto"
    seed: int = 0
    max_model_len: int = 4096
    max_num_seqs: int = 64
    max_num_batched_tokens: int = 2048
    block_size: int = 16  # paged-KV block (tokens per page)
    num_kv_cache_blocks: Optional[int] = None  # None => auto from memory
    gpu_memory_utilization: float = 0.9  # kept for CLI parity; HBM fraction
    enforce_eager: bool = False

    # --- parallel -----------------------------------------------------
    tensor_parallel_size: int = 1
    data_parallel_size: int = 1
    pipeline_parallel_size: int = 1
    prefill_context_parallel_size: int = 1
    expert_parallel_size: int = 1

    # --- cross-stage --------------------------------------------------
    stage_connector_config: dict[str, Any] = field(default_factory=dict)
    omni_kv_config: dict[str, Any] = field(default_factory=dict)
    async_chunk: bool = False

    # --- escape hatch for per-arch extras ----------------------------
    extra: dict[str, Any] = field(default_factory=dict)

    def jax_dtype(self):
        return resolve_dtype(self.dtype)

    @classmethod
    def from_kwargs(cls, **kwargs: Any) -> "OmniModelConfig":
        """Filtering constructor in the style of the reference's
        ``OmniDiffusionConfig.from_kwargs`` (diffusion/data.py:~500):
        known keys become fields, the rest land in ``extra``."""
        known, extra = split_known_kwargs(cls, kwargs)
        return cls(**known, extra=extra)
