"""Arrival processes + scenario catalog for the open-loop harness.

Everything here is DETERMINISTIC given a seed: the same (seed, rate,
catalog) produces bit-identical arrival schedules, scenario picks,
prompt tokens, and output lengths — a serving-curve regression between
two builds can only come from the system under test, never from the
workload.  Nothing in this module touches jax or the network.

Arrival processes (the open-loop stance: offered load is a property of
the CLIENT population, so inter-arrival gaps are drawn up front and
never stretched by slow completions — the closed-loop alternative
flatters an overloaded server by self-throttling):

- ``poisson_arrivals``: exponential inter-arrival gaps at a target
  rate, the standard model for a large independent user population.
- ``trace_replay_arrivals``: replay explicit offsets (production logs,
  adversarial bursts), optionally time-scaled to sweep rates.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Optional, Sequence


@dataclass(frozen=True)
class Scenario:
    """One traffic class in the mix.

    ``shared_prefix_len`` > 0 models multi-turn / system-prompt reuse:
    every request of the scenario starts with the SAME token run (drawn
    once per scenario from the workload seed), so prefix caching and
    the radix index see realistic overlap.  Length bounds are inclusive
    uniform draws per request.
    """

    name: str
    weight: float
    prompt_len: tuple[int, int]
    output_len: tuple[int, int]
    shared_prefix_len: int = 0
    stream: bool = False
    tenant: Optional[str] = None  # None -> the workload-level default


def default_catalog() -> list[Scenario]:
    """The mixed serving catalog the ROADMAP asks the curve to cover:
    chat, long-context, multi-turn shared-prefix, and streaming."""
    return [
        Scenario("chat", weight=0.5,
                 prompt_len=(32, 128), output_len=(16, 64)),
        Scenario("long_context", weight=0.2,
                 prompt_len=(512, 1024), output_len=(16, 32)),
        Scenario("multi_turn", weight=0.2,
                 prompt_len=(16, 64), output_len=(16, 32),
                 shared_prefix_len=256),
        Scenario("streaming", weight=0.1,
                 prompt_len=(32, 64), output_len=(32, 64), stream=True),
    ]


@dataclass
class LoadRequest:
    """One generated arrival: fire at ``at_s`` (offset from the run's
    t0), submit ``prompt_token_ids`` (or ``prompt`` text for HTTP
    drivers), collect up to ``max_tokens``."""

    at_s: float
    request_id: str
    scenario: str
    tenant: str
    prompt_token_ids: list[int] = field(default_factory=list)
    max_tokens: int = 16
    stream: bool = False

    @property
    def prompt(self) -> str:
        """Text form for HTTP drivers (the byte-tokenizer server path
        re-encodes it; exact token identity doesn't matter over HTTP,
        deterministic length does)."""
        return " ".join(f"tok{t}" for t in self.prompt_token_ids[:64])


def poisson_arrivals(rate_rps: float, num_requests: int,
                     seed: int = 0) -> list[float]:
    """``num_requests`` arrival offsets with exponential inter-arrival
    gaps at ``rate_rps`` (a Poisson process).  Seeded: same inputs,
    same schedule."""
    if rate_rps <= 0:
        raise ValueError(f"rate_rps must be > 0, got {rate_rps}")
    rng = random.Random(seed)
    t = 0.0
    out = []
    for _ in range(max(int(num_requests), 0)):
        t += rng.expovariate(rate_rps)
        out.append(t)
    return out


def trace_replay_arrivals(offsets: Sequence[float],
                          time_scale: float = 1.0) -> list[float]:
    """Replay explicit arrival offsets (seconds from t0), optionally
    compressed/stretched by ``time_scale`` (< 1 replays faster,
    sweeping offered load without editing the trace).  Offsets must be
    non-negative and sorted — a shuffled trace is almost always a
    units bug in the caller, not a workload."""
    if time_scale <= 0:
        raise ValueError(f"time_scale must be > 0, got {time_scale}")
    out = []
    prev = 0.0
    for i, off in enumerate(offsets):
        off = float(off)
        if off < 0 or off < prev:
            raise ValueError(
                f"trace offsets must be sorted and non-negative "
                f"(offset {i} = {off}, previous {prev})")
        prev = off
        out.append(off * time_scale)
    return out


def build_workload(
    arrivals: Sequence[float],
    catalog: Optional[Sequence[Scenario]] = None,
    seed: int = 0,
    vocab_size: int = 32000,
    tenants: Sequence[str] = ("default",),
    id_prefix: str = "load",
) -> list[LoadRequest]:
    """Bind one scenario + concrete prompt/output draws to every
    arrival offset.  ``tenants`` round-robins across requests unless a
    scenario pins its own tenant.  Deterministic per (arrivals order,
    catalog, seed, vocab_size, tenants)."""
    catalog = list(catalog if catalog is not None else default_catalog())
    if not catalog:
        raise ValueError("catalog must not be empty")
    rng = random.Random(seed)
    weights = [max(s.weight, 0.0) for s in catalog]
    if sum(weights) <= 0:
        raise ValueError("catalog weights must sum > 0")
    # shared prefixes drawn ONCE per scenario, before the per-request
    # stream, so adding requests never reshuffles them
    prefixes = {
        s.name: [rng.randrange(1, vocab_size)
                 for _ in range(s.shared_prefix_len)]
        for s in catalog if s.shared_prefix_len > 0
    }
    out: list[LoadRequest] = []
    for i, at_s in enumerate(arrivals):
        sc = rng.choices(catalog, weights=weights, k=1)[0]
        n_prompt = rng.randint(*sc.prompt_len)
        n_out = rng.randint(*sc.output_len)
        toks = list(prefixes.get(sc.name, ()))
        toks += [rng.randrange(1, vocab_size) for _ in range(n_prompt)]
        tenant = sc.tenant or tenants[i % len(tenants)]
        out.append(LoadRequest(
            at_s=float(at_s),
            request_id=f"{id_prefix}-{i}",
            scenario=sc.name,
            tenant=tenant,
            prompt_token_ids=toks,
            max_tokens=n_out,
            stream=sc.stream,
        ))
    return out
