"""Arrival processes + scenario catalog for the open-loop harness.

Everything here is DETERMINISTIC given a seed: the same (seed, rate,
catalog) produces bit-identical arrival schedules, scenario picks,
prompt tokens, and output lengths — a serving-curve regression between
two builds can only come from the system under test, never from the
workload.  Nothing in this module touches jax or the network.

Arrival processes (the open-loop stance: offered load is a property of
the CLIENT population, so inter-arrival gaps are drawn up front and
never stretched by slow completions — the closed-loop alternative
flatters an overloaded server by self-throttling):

- ``poisson_arrivals``: exponential inter-arrival gaps at a target
  rate, the standard model for a large independent user population.
- ``trace_replay_arrivals``: replay explicit offsets (production logs,
  adversarial bursts), optionally time-scaled to sweep rates.
- ``diurnal_arrivals``: sinusoid-modulated Poisson — the compressed
  day/night cycle the control plane (docs/control_plane.md) must track:
  offered load swings around the mean, so any STATIC topology is wrong
  for part of the period.
- ``burst_arrivals``: on/off MMPP-style bursts — alternating
  exponentially-distributed ON (high-rate) and OFF (low-rate) phases,
  the adversarial shape for admission control and autoscaling
  (cold-start cost means a controller that chases every burst flaps).
"""

from __future__ import annotations

import math
import random
from dataclasses import dataclass, field
from typing import Optional, Sequence


@dataclass(frozen=True)
class Scenario:
    """One traffic class in the mix.

    ``shared_prefix_len`` > 0 models multi-turn / system-prompt reuse:
    every request of the scenario starts with the SAME token run (drawn
    once per scenario from the workload seed), so prefix caching and
    the radix index see realistic overlap.  Length bounds are inclusive
    uniform draws per request.

    ``prefix_group`` names the prefix draw to share: scenarios with
    the same group (and equal ``shared_prefix_len``) emit the SAME
    shared prefix — N tenant-pinned scenarios over one common system
    prompt, the cross-replica redundancy workload
    (``shared_prefix_catalog``).  None keeps the per-scenario-name
    draw, so existing catalogs generate exactly the traffic they
    always did.
    """

    name: str
    weight: float
    prompt_len: tuple[int, int]
    output_len: tuple[int, int]
    shared_prefix_len: int = 0
    stream: bool = False
    tenant: Optional[str] = None  # None -> the workload-level default
    # client priority/weight (x-omni-priority): None -> the neutral
    # weight (metrics/stats.py DEFAULT_PRIORITY), so catalogs that
    # never set it generate exactly the traffic they always did
    priority: Optional[int] = None
    prefix_group: Optional[str] = None


def default_catalog() -> list[Scenario]:
    """The mixed serving catalog the ROADMAP asks the curve to cover:
    chat, long-context, multi-turn shared-prefix, and streaming."""
    return [
        Scenario("chat", weight=0.5,
                 prompt_len=(32, 128), output_len=(16, 64)),
        Scenario("long_context", weight=0.2,
                 prompt_len=(512, 1024), output_len=(16, 32)),
        Scenario("multi_turn", weight=0.2,
                 prompt_len=(16, 64), output_len=(16, 32),
                 shared_prefix_len=256),
        Scenario("streaming", weight=0.1,
                 prompt_len=(32, 64), output_len=(32, 64), stream=True),
    ]


def shared_prefix_catalog(n_tenants: int = 4,
                          prefix_len: int = 64,
                          prompt_len: tuple[int, int] = (8, 24),
                          output_len: tuple[int, int] = (8, 16),
                          group: str = "system_prompt"
                          ) -> list[Scenario]:
    """The cache-economics workload (docs/load_testing.md): N equal-
    weight tenant-pinned scenarios all opening with ONE common system
    prompt (``prefix_group`` shares the draw).  Under a cache-blind
    router the common prefix lands on every replica — the redundancy
    `scripts/cache_bench.py` scores and prefix-affinity routing
    (ROADMAP item 3) must reclaim.  Seed-deterministic like every
    catalog: the prefix is drawn once from the workload seed."""
    if n_tenants < 1:
        raise ValueError("n_tenants must be positive")
    if prefix_len < 1:
        raise ValueError("prefix_len must be positive")
    return [
        Scenario(f"shared_prefix_t{i}", weight=1.0,
                 prompt_len=prompt_len, output_len=output_len,
                 shared_prefix_len=prefix_len,
                 tenant=f"tenant{i}", prefix_group=group)
        for i in range(n_tenants)
    ]


@dataclass
class LoadRequest:
    """One generated arrival: fire at ``at_s`` (offset from the run's
    t0), submit ``prompt_token_ids`` (or ``prompt`` text for HTTP
    drivers), collect up to ``max_tokens``."""

    at_s: float
    request_id: str
    scenario: str
    tenant: str
    prompt_token_ids: list[int] = field(default_factory=list)
    max_tokens: int = 16
    stream: bool = False
    # weighted-fair-queueing priority (None = neutral): run_inproc
    # stamps it into request metadata, run_http into x-omni-priority
    priority: Optional[int] = None

    @property
    def prompt(self) -> str:
        """Text form for HTTP drivers (the byte-tokenizer server path
        re-encodes it; exact token identity doesn't matter over HTTP,
        deterministic length does)."""
        return " ".join(f"tok{t}" for t in self.prompt_token_ids[:64])


def poisson_arrivals(rate_rps: float, num_requests: int,
                     seed: int = 0) -> list[float]:
    """``num_requests`` arrival offsets with exponential inter-arrival
    gaps at ``rate_rps`` (a Poisson process).  Seeded: same inputs,
    same schedule."""
    if rate_rps <= 0:
        raise ValueError(f"rate_rps must be > 0, got {rate_rps}")
    rng = random.Random(seed)
    t = 0.0
    out = []
    for _ in range(max(int(num_requests), 0)):
        t += rng.expovariate(rate_rps)
        out.append(t)
    return out


def trace_replay_arrivals(offsets: Sequence[float],
                          time_scale: float = 1.0) -> list[float]:
    """Replay explicit arrival offsets (seconds from t0), optionally
    compressed/stretched by ``time_scale`` (< 1 replays faster,
    sweeping offered load without editing the trace).  Offsets must be
    non-negative and sorted — a shuffled trace is almost always a
    units bug in the caller, not a workload."""
    if time_scale <= 0:
        raise ValueError(f"time_scale must be > 0, got {time_scale}")
    out = []
    prev = 0.0
    for i, off in enumerate(offsets):
        off = float(off)
        if off < 0 or off < prev:
            raise ValueError(
                f"trace offsets must be sorted and non-negative "
                f"(offset {i} = {off}, previous {prev})")
        prev = off
        out.append(off * time_scale)
    return out


def diurnal_arrivals(rate_rps: float, num_requests: int,
                     period_s: float = 60.0, amplitude: float = 0.8,
                     seed: int = 0, phase: float = 0.0) -> list[float]:
    """``num_requests`` offsets from a sinusoid-modulated Poisson
    process: instantaneous rate ``rate_rps * (1 + amplitude *
    sin(2*pi*t/period_s + phase))`` — a compressed diurnal cycle whose
    prefill:decode pressure mix shifts over the period.  Generated by
    Lewis-Shedler thinning against the peak rate, so the draws stay
    bit-deterministic per seed regardless of the modulation shape.
    ``amplitude`` in [0, 1): 0 degenerates to plain Poisson."""
    if rate_rps <= 0:
        raise ValueError(f"rate_rps must be > 0, got {rate_rps}")
    if not 0.0 <= amplitude < 1.0:
        raise ValueError(
            f"amplitude must be in [0, 1), got {amplitude}")
    if period_s <= 0:
        raise ValueError(f"period_s must be > 0, got {period_s}")
    rng = random.Random(seed)
    lam_max = rate_rps * (1.0 + amplitude)
    t = 0.0
    out: list[float] = []
    while len(out) < max(int(num_requests), 0):
        t += rng.expovariate(lam_max)
        lam_t = rate_rps * (1.0 + amplitude
                            * math.sin(2.0 * math.pi * t / period_s
                                       + phase))
        # thinning: accept with prob lambda(t)/lambda_max.  The draw
        # happens on EVERY candidate so the accept stream stays aligned
        # with the seed regardless of where the sinusoid sits
        if rng.random() * lam_max <= lam_t:
            out.append(t)
    return out


def burst_arrivals(base_rps: float, burst_rps: float,
                   num_requests: int, mean_on_s: float = 5.0,
                   mean_off_s: float = 15.0, seed: int = 0
                   ) -> list[float]:
    """``num_requests`` offsets from an on/off MMPP-style process:
    exponentially-distributed ON phases (mean ``mean_on_s``) arriving
    at ``burst_rps`` alternate with OFF phases (mean ``mean_off_s``)
    at ``base_rps`` — quiet background traffic punctured by bursts the
    controller must absorb without flapping.  ``base_rps`` may be 0
    (silent troughs).  Seeded and bit-deterministic."""
    if burst_rps <= 0:
        raise ValueError(f"burst_rps must be > 0, got {burst_rps}")
    if base_rps < 0:
        raise ValueError(f"base_rps must be >= 0, got {base_rps}")
    if mean_on_s <= 0 or mean_off_s <= 0:
        raise ValueError("mean_on_s and mean_off_s must be > 0")
    rng = random.Random(seed)
    out: list[float] = []
    t = 0.0          # current time
    on = False       # start in the OFF (background) phase
    phase_end = rng.expovariate(1.0 / mean_off_s)
    while len(out) < max(int(num_requests), 0):
        rate = burst_rps if on else base_rps
        if rate <= 0:
            # silent phase: jump to its end
            t = phase_end
            on = not on
            phase_end = t + rng.expovariate(
                1.0 / (mean_on_s if on else mean_off_s))
            continue
        gap = rng.expovariate(rate)
        if t + gap >= phase_end:
            # the next arrival would land past the phase boundary:
            # advance to the boundary and flip phase (memorylessness
            # makes discarding the partial gap distribution-correct)
            t = phase_end
            on = not on
            phase_end = t + rng.expovariate(
                1.0 / (mean_on_s if on else mean_off_s))
            continue
        t += gap
        out.append(t)
    return out


def build_workload(
    arrivals: Sequence[float],
    catalog: Optional[Sequence[Scenario]] = None,
    seed: int = 0,
    vocab_size: int = 32000,
    tenants: Sequence[str] = ("default",),
    id_prefix: str = "load",
    tenant_priorities: Optional[dict] = None,
) -> list[LoadRequest]:
    """Bind one scenario + concrete prompt/output draws to every
    arrival offset.  ``tenants`` round-robins across requests unless a
    scenario pins its own tenant.  ``tenant_priorities`` maps tenant ->
    WFQ priority (a scenario's own ``priority`` wins; unmapped tenants
    stay at the neutral weight).  Deterministic per (arrivals order,
    catalog, seed, vocab_size, tenants)."""
    catalog = list(catalog if catalog is not None else default_catalog())
    if not catalog:
        raise ValueError("catalog must not be empty")
    rng = random.Random(seed)
    weights = [max(s.weight, 0.0) for s in catalog]
    if sum(weights) <= 0:
        raise ValueError("catalog weights must sum > 0")
    # shared prefixes drawn ONCE per prefix key (the scenario's
    # prefix_group, or its name when ungrouped), before the
    # per-request stream, so adding requests never reshuffles them and
    # grouped scenarios share one draw in catalog order
    prefixes: dict[str, list[int]] = {}
    for s in catalog:
        if s.shared_prefix_len <= 0:
            continue
        k = s.prefix_group or s.name
        if k not in prefixes:
            prefixes[k] = [rng.randrange(1, vocab_size)
                           for _ in range(s.shared_prefix_len)]
    out: list[LoadRequest] = []
    for i, at_s in enumerate(arrivals):
        sc = rng.choices(catalog, weights=weights, k=1)[0]
        n_prompt = rng.randint(*sc.prompt_len)
        n_out = rng.randint(*sc.output_len)
        toks = list(prefixes.get(sc.prefix_group or sc.name, ())
                    if sc.shared_prefix_len > 0 else ())
        toks += [rng.randrange(1, vocab_size) for _ in range(n_prompt)]
        tenant = sc.tenant or tenants[i % len(tenants)]
        priority = sc.priority
        if priority is None and tenant_priorities:
            priority = tenant_priorities.get(tenant)
        out.append(LoadRequest(
            at_s=float(at_s),
            request_id=f"{id_prefix}-{i}",
            scenario=sc.name,
            tenant=tenant,
            prompt_token_ids=toks,
            max_tokens=n_out,
            stream=sc.stream,
            priority=priority,
        ))
    return out
