"""Open-loop runner: drive a workload, fold records into curve points.

Three drivers share one record shape and one summarizer:

- ``run_http``    — the OpenAI HTTP server (one thread per arrival,
  fired at its scheduled offset regardless of completions).
- ``run_inproc``  — an ``AsyncOmni`` in this process (asyncio tasks;
  arrivals are ``sleep``-scheduled, never awaited-on-completion).
- ``simulate``    — a virtual-time FCFS queue: no clock, no server,
  bit-deterministic records.  The goodput math's oracle (tests) and
  the CI smoke curve's backend (scripts/loadgen.sh) — a real engine's
  scheduling noise must not gate a merge.

The OPEN-LOOP invariant everywhere: offered load is fixed by the
arrival schedule.  A saturated server sees requests keep arriving and
must shed (429) or queue — which is exactly what the serving curve is
supposed to show; a closed-loop client would self-throttle and hide it.

Timing note (omnilint OL4): every duration here is wall-clock around a
NETWORK or queue round trip on purpose — client-observed latency is
the product being measured, and no jax dispatch happens in this
module.  Durations come from ``time.monotonic``.
"""

from __future__ import annotations

import heapq
import threading
import time
from dataclasses import dataclass
from typing import Optional, Sequence

from vllm_omni_tpu.loadgen.workload import LoadRequest
from vllm_omni_tpu.metrics.stats import nearest_rank_pct


@dataclass
class SLOTargets:
    """Per-request SLO upper bounds (ms).  ``None`` legs always pass;
    a leg the driver could not MEASURE (e.g. TTFT on a non-streaming
    HTTP request) also passes — absence of evidence must not zero the
    goodput of an otherwise healthy run."""

    ttft_ms: Optional[float] = None
    tpot_ms: Optional[float] = None
    e2e_ms: Optional[float] = None

    def as_dict(self) -> dict:
        return {"ttft_ms": self.ttft_ms, "tpot_ms": self.tpot_ms,
                "e2e_ms": self.e2e_ms}


@dataclass
class RequestRecord:
    """One request's observed lifecycle.  All times are SECONDS offset
    from the run's t0 (monotonic deltas — never wall-clock pairs)."""

    request_id: str
    tenant: str = "default"
    scenario: str = "chat"
    arrival_s: float = 0.0           # scheduled offset
    fired_s: float = 0.0             # when the driver actually submitted
    first_s: Optional[float] = None  # first output observed
    end_s: Optional[float] = None
    tokens_out: int = 0
    # "ok" | "shed" (429 / error_kind shed) | "expired" (504 /
    # deadline_exceeded) | "error" (everything else)
    status: str = "error"

    @property
    def ttft_ms(self) -> Optional[float]:
        if self.first_s is None:
            return None
        return max(self.first_s - self.fired_s, 0.0) * 1e3

    @property
    def e2e_ms(self) -> Optional[float]:
        if self.end_s is None:
            return None
        return max(self.end_s - self.fired_s, 0.0) * 1e3

    @property
    def tpot_ms(self) -> Optional[float]:
        """Per-output-token time excluding the first token; None when
        fewer than 2 tokens exist (no per-token time to report)."""
        if (self.first_s is None or self.end_s is None
                or self.tokens_out <= 1):
            return None
        return (max(self.end_s - self.first_s, 0.0) * 1e3
                / (self.tokens_out - 1))


def slo_met(rec: RequestRecord, slo: SLOTargets) -> bool:
    """True when the request completed AND every configured+measured
    SLO leg held (<= — exactly at the target counts as met)."""
    if rec.status != "ok":
        return False
    for target, value in ((slo.ttft_ms, rec.ttft_ms),
                         (slo.tpot_ms, rec.tpot_ms),
                         (slo.e2e_ms, rec.e2e_ms)):
        if target is not None and value is not None and value > target:
            return False
    return True


def _pcts(xs: list) -> dict:
    return {"p50": round(nearest_rank_pct(xs, 0.50), 3),
            "p90": round(nearest_rank_pct(xs, 0.90), 3),
            "p99": round(nearest_rank_pct(xs, 0.99), 3)}


def summarize(records: Sequence[RequestRecord], offered_rps: float,
              slo: Optional[SLOTargets] = None,
              duration_s: Optional[float] = None) -> dict:
    """Fold one rate point's records into a ``serving_curve`` entry.

    Throughput counts every completed request; GOODPUT counts only the
    SLO-met ones (sheds/expiries/errors are attainment misses by
    definition — refusing a request is not serving it).  ``duration_s``
    defaults to the observed makespan (first fire to last event)."""
    slo = slo or SLOTargets()
    if duration_s is None:
        lo = min((r.fired_s for r in records), default=0.0)
        hi = max((r.end_s if r.end_s is not None else r.fired_s
                  for r in records), default=0.0)
        duration_s = max(hi - lo, 1e-9)
    ok = [r for r in records if r.status == "ok"]
    met = [r for r in ok if slo_met(r, slo)]
    tokens_ok = sum(r.tokens_out for r in ok)
    tokens_good = sum(r.tokens_out for r in met)
    n = len(records)
    point = {
        "offered_rps": round(float(offered_rps), 4),
        "duration_s": round(duration_s, 3),
        "num_requests": n,
        "completed": len(ok),
        "shed": sum(1 for r in records if r.status == "shed"),
        "expired": sum(1 for r in records if r.status == "expired"),
        "errors": sum(1 for r in records if r.status == "error"),
        "attained_req_per_s": round(len(ok) / duration_s, 4),
        "attained_tok_per_s": round(tokens_ok / duration_s, 4),
        "goodput_req_per_s": round(len(met) / duration_s, 4),
        "goodput_tok_per_s": round(tokens_good / duration_s, 4),
        # SLO-met over OFFERED (not over completed): the non-increasing
        # quantity the curve's knee is read from
        "slo_attainment": round(len(met) / n, 4) if n else 0.0,
        "slo": slo.as_dict(),
        "ttft_ms": _pcts([r.ttft_ms for r in ok
                          if r.ttft_ms is not None]),
        "tpot_ms": _pcts([r.tpot_ms for r in ok
                          if r.tpot_ms is not None]),
        "e2e_ms": _pcts([r.e2e_ms for r in ok
                         if r.e2e_ms is not None]),
    }
    return point


#: required keys of a serving_curve point (the BENCH_*.json contract —
#: tests and the loadgen.sh gate validate artifacts against this)
CURVE_POINT_KEYS = (
    "offered_rps", "duration_s", "num_requests", "completed", "shed",
    "expired", "errors", "attained_req_per_s", "attained_tok_per_s",
    "goodput_req_per_s", "goodput_tok_per_s", "slo_attainment", "slo",
    "ttft_ms", "tpot_ms", "e2e_ms",
)


def validate_curve_point(point: dict) -> list[str]:
    """Schema check for one serving_curve entry; returns violations
    (empty = valid)."""
    errors = [f"missing key {k!r}" for k in CURVE_POINT_KEYS
              if k not in point]
    for k in ("ttft_ms", "tpot_ms", "e2e_ms"):
        sub = point.get(k)
        if isinstance(sub, dict):
            errors += [f"{k} missing {p!r}" for p in ("p50", "p90", "p99")
                       if p not in sub]
    counted = sum(point.get(k, 0) or 0 for k in
                  ("completed", "shed", "expired", "errors"))
    if point.get("num_requests") is not None \
            and counted != point["num_requests"]:
        errors.append(
            f"counts don't partition num_requests: {counted} != "
            f"{point['num_requests']}")
    return errors


# ------------------------------------------------------------ simulator
def simulate(workload: Sequence[LoadRequest], prefill_s: float,
             per_token_s: float, servers: int = 1,
             queue_limit: Optional[int] = None) -> list[RequestRecord]:
    """Virtual-time FCFS queue: ``servers`` identical seats, service
    time = prefill_s + max_tokens * per_token_s, first token after the
    prefill + one token time.  An arrival finding ``queue_limit``
    requests already waiting is SHED (mirroring the scheduler's
    queue-depth admission control).  Pure math — deterministic records
    with zero wall-clock, which is what makes it a CI gate."""
    free = [0.0] * max(int(servers), 1)
    heapq.heapify(free)
    starts: list[float] = []  # admitted requests' start times, in order
    records = []
    for lr in sorted(workload, key=lambda r: r.at_s):
        rec = RequestRecord(
            request_id=lr.request_id, tenant=lr.tenant,
            scenario=lr.scenario, arrival_s=lr.at_s, fired_s=lr.at_s)
        waiting = sum(1 for s in starts if s > lr.at_s)
        if queue_limit is not None and waiting >= queue_limit:
            rec.status = "shed"
            rec.end_s = lr.at_s
            records.append(rec)
            continue
        start = max(lr.at_s, heapq.heappop(free))
        service = prefill_s + lr.max_tokens * per_token_s
        end = start + service
        heapq.heappush(free, end)
        starts.append(start)
        rec.first_s = start + prefill_s + per_token_s
        rec.end_s = end
        rec.tokens_out = lr.max_tokens
        rec.status = "ok"
        records.append(rec)
    return records


# ---------------------------------------------------------- in-process
_ERROR_STATUS = {"shed": "shed", "deadline_exceeded": "expired"}


def run_inproc(omni, workload: Sequence[LoadRequest],
               deadline_s: Optional[float] = None,
               temperature: float = 0.0,
               timeout_s: float = 600.0) -> list[RequestRecord]:
    """Drive an ``AsyncOmni`` open-loop: one asyncio task per arrival,
    created at its scheduled offset — task creation never waits on any
    completion.  Runs a private event loop to completion and returns
    the records."""
    import asyncio

    records: list[RequestRecord] = []

    async def one(lr: LoadRequest, t0: float) -> None:
        rec = RequestRecord(
            request_id=lr.request_id, tenant=lr.tenant,
            scenario=lr.scenario, arrival_s=lr.at_s,
            fired_s=time.monotonic() - t0)
        info = {"tenant": lr.tenant}
        if lr.priority is not None:
            info["priority"] = lr.priority
        prompt = {"prompt_token_ids": list(lr.prompt_token_ids),
                  "additional_information": info}
        sp = {"max_tokens": lr.max_tokens, "temperature": temperature,
              "ignore_eos": True}
        failed = None
        try:
            async for o in omni.generate(prompt, sp, lr.request_id,
                                         deadline_s=deadline_s):
                now = time.monotonic() - t0
                if o.is_error:
                    failed = _ERROR_STATUS.get(o.error_kind, "error")
                    rec.end_s = now
                    break
                if rec.first_s is None:
                    rec.first_s = now
                rec.end_s = now
                rec.tokens_out += sum(len(c.token_ids)
                                      for c in o.outputs)
        except Exception:
            failed = "error"
            rec.end_s = time.monotonic() - t0
        rec.status = failed if failed else (
            "ok" if rec.end_s is not None else "error")
        records.append(rec)

    async def drive() -> None:
        t0 = time.monotonic()
        tasks: list = []
        for lr in workload:
            delay = lr.at_s - (time.monotonic() - t0)
            if delay > 0:
                await asyncio.sleep(delay)
            tasks.append((asyncio.ensure_future(one(lr, t0)), lr))
        if not tasks:
            return
        _, pending = await asyncio.wait([t for t, _ in tasks],
                                        timeout=timeout_s)
        if pending:
            # requests still in flight at the timeout are RECORDED as
            # errors, never silently dropped — dropping them would
            # shrink the offered population and flatter the knee of
            # the curve in exactly the overload regime the harness
            # exists to measure
            for t in pending:
                t.cancel()
            await asyncio.gather(*pending, return_exceptions=True)
            now = time.monotonic() - t0
            seen = {r.request_id for r in records}
            for t, lr in tasks:
                if t in pending and lr.request_id not in seen:
                    records.append(RequestRecord(
                        request_id=lr.request_id, tenant=lr.tenant,
                        scenario=lr.scenario, arrival_s=lr.at_s,
                        fired_s=lr.at_s, end_s=now, status="error"))

    loop = asyncio.new_event_loop()
    try:
        loop.run_until_complete(drive())
    finally:
        loop.close()
    return records


# ---------------------------------------------------------------- HTTP
def _classify_http(code: int) -> str:
    if code == 429:
        return "shed"
    if code == 504:
        return "expired"
    return "error"


def _http_one(base_url: str, lr: LoadRequest, t0: float,
              records: list, lock: threading.Lock,
              timeout_s: float) -> None:
    """Fire one chat completion immediately (the dispatcher already
    slept to its offset) and record the client-observed lifecycle
    (TTFT from the first SSE data event when streaming).  The wire
    work lives in the shared ``chat_http_request`` driver."""
    from vllm_omni_tpu.benchmarks.serving import chat_http_request

    rec = RequestRecord(
        request_id=lr.request_id, tenant=lr.tenant, scenario=lr.scenario,
        arrival_s=lr.at_s, fired_s=time.monotonic() - t0)
    headers = {"x-omni-tenant": lr.tenant}
    if lr.priority is not None:
        headers["x-omni-priority"] = str(lr.priority)
    res = chat_http_request(base_url, {
        "model": "loadgen",
        "messages": [{"role": "user", "content": lr.prompt}],
        "max_tokens": lr.max_tokens,
        "temperature": 0,
        # pin the output length (server extension): SSE carries no
        # usage block, so exact goodput/TPOT accounting needs the
        # token count to BE max_tokens
        "ignore_eos": True,
        "stream": bool(lr.stream),
    }, headers=headers, timeout_s=timeout_s)
    rec.end_s = res["end_mono"] - t0
    if res["first_event_mono"] is not None:
        rec.first_s = res["first_event_mono"] - t0
    if res["ok"]:
        rec.tokens_out = (res["usage_completion_tokens"]
                          if res["usage_completion_tokens"] is not None
                          else lr.max_tokens)
        rec.status = "ok"
    elif res["http_status"] is not None:
        rec.status = _classify_http(res["http_status"])
    elif res["error"] is not None:
        # mid-stream SSE error event: the taxonomy rides its would-be
        # HTTP code (429 shed / 504 expired / ...)
        code = res["error"].get("code") \
            if isinstance(res["error"], dict) else None
        rec.status = (_classify_http(code) if isinstance(code, int)
                      else "error")
    else:
        rec.status = "error"
    with lock:
        records.append(rec)


def run_http(base_url: str, workload: Sequence[LoadRequest],
             timeout_s: float = 600.0) -> list[RequestRecord]:
    """Drive the OpenAI server open-loop: the dispatcher (this thread)
    sleeps to each arrival's offset and spawns that request's thread
    AT FIRE TIME — live threads scale with the in-flight count, not
    the workload size (pre-spawning a 10-minute trace would hold
    thousands of sleeping stacks on the measurement host).  A thread
    per in-flight request is deliberate: a bounded pool would gate
    arrivals on completions and close the loop."""
    records: list[RequestRecord] = []
    lock = threading.Lock()
    t0 = time.monotonic()
    threads = []
    for lr in sorted(workload, key=lambda r: r.at_s):
        delay = lr.at_s - (time.monotonic() - t0)
        if delay > 0:
            time.sleep(delay)
        t = threading.Thread(target=_http_one,
                             args=(base_url, lr, t0, records, lock,
                                   timeout_s))
        t.start()
        threads.append(t)
    for t in threads:
        t.join()
    return records
