"""Open-loop traffic harness (docs/load_testing.md).

``workload.py`` builds deterministic arrival schedules (seeded Poisson
or trace replay) over a mixed scenario catalog; ``runner.py`` drives
them open-loop — arrivals NEVER gate on completions — against the
OpenAI HTTP server, an in-process ``AsyncOmni``, or a virtual-time
queue simulator, and folds the per-request records into ``serving_curve``
points (attained throughput, goodput, SLO attainment, latency
percentiles, shed/expired counts) per offered-load rate.
"""

from vllm_omni_tpu.loadgen.workload import (  # noqa: F401
    LoadRequest,
    Scenario,
    build_workload,
    burst_arrivals,
    default_catalog,
    diurnal_arrivals,
    poisson_arrivals,
    shared_prefix_catalog,
    trace_replay_arrivals,
)
from vllm_omni_tpu.loadgen.runner import (  # noqa: F401
    RequestRecord,
    SLOTargets,
    run_http,
    run_inproc,
    simulate,
    summarize,
    validate_curve_point,
)
