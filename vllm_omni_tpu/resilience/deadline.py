"""End-to-end request deadlines.

A request enters the pipeline with a time budget (``deadline_s`` at
``Omni``/``AsyncOmni`` arrival — per call, per request dict, or the
``OMNI_TPU_DEFAULT_DEADLINE_S`` env default).  The orchestrator keeps
the authoritative expiry on its monotonic clock and re-stamps the
REMAINING budget onto every ``StageRequest`` it forwards
(``StageRequest.deadline_s``, riding OmniSerializer next to the trace
context), so the budget survives cross-process and cross-host handoffs
without assuming synchronized wall clocks.  Each receiving engine
converts the remaining budget back to its own monotonic expiry
(``Request.deadline_ts``) and enforces it at admission and on every
scheduler step; connector waits clamp their timeouts to it.

Expiry surfaces as a distinct output status: an error output with
``error_kind == "deadline_exceeded"`` (HTTP 504 at the serving layer),
never a hang and never a generic internal error.
"""

from __future__ import annotations

import time
from typing import Optional

from vllm_omni_tpu.outputs import OmniRequestOutput
from vllm_omni_tpu.resilience.metrics import resilience_metrics

#: error_kind of a deadline kill (outputs.OmniRequestOutput)
DEADLINE_EXCEEDED = "deadline_exceeded"
#: error_kind of a retry-safe failure (e.g. the stage worker died while
#: the request was mid-execution): the request produced no partial
#: output and an idempotent client may safely resubmit
RETRYABLE = "retryable"


def expiry_ts(deadline_s: Optional[float]) -> Optional[float]:
    """Remaining budget -> monotonic expiry on THIS process's clock."""
    if deadline_s is None:
        return None
    return time.monotonic() + max(float(deadline_s), 0.0)


def remaining_s(deadline_ts: Optional[float]) -> Optional[float]:
    """Monotonic expiry -> remaining budget (negative once expired)."""
    if deadline_ts is None:
        return None
    return deadline_ts - time.monotonic()


def expired(deadline_ts: Optional[float]) -> bool:
    return deadline_ts is not None and time.monotonic() >= deadline_ts


def clamp_timeout(timeout: Optional[float],
                  deadline_ts: Optional[float]) -> Optional[float]:
    """Bound a blocking wait by the request's remaining budget: a lost
    payload must never be waited for past the deadline."""
    rem = remaining_s(deadline_ts)
    if rem is None:
        return timeout
    rem = max(rem, 0.0)
    return rem if timeout is None else min(timeout, rem)


def deadline_output(request_id: str, stage_id: int,
                    detail: str = "") -> OmniRequestOutput:
    """The DeadlineExceeded terminal output (counted per stage)."""
    resilience_metrics.inc("deadline_exceeded_total", stage=stage_id)
    msg = f"deadline exceeded{': ' + detail if detail else ''}"
    return OmniRequestOutput.from_error(
        request_id, msg, stage_id=stage_id, kind=DEADLINE_EXCEEDED)
