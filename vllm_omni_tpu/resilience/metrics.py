"""Process-global resilience event counters.

Restarts, retries, breaker trips, deadline kills, heartbeat misses, and
injected faults all count here; ``metrics/prometheus.py`` renders the
snapshot into the ``/metrics`` exposition (every name below is declared
in ``METRIC_SPECS`` so the drift guard covers the resilience surface
too).  Orchestrator-side events only: a stage WORKER process keeps its
own instance, and worker-side injected faults surface indirectly (as
the orchestrator-side retry/restart they provoke).

Deliberately tiny — labeled monotonic counters and gauges, no
histograms: resilience events are rare and discrete, and the latency
story already lives in the engine step metrics.
"""

from __future__ import annotations

import threading
from typing import Iterable

from vllm_omni_tpu.analysis.runtime import traced


def _label_key(labels: dict) -> tuple:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


class ResilienceMetrics:
    """Thread-safe labeled counters/gauges with a render-ready snapshot."""

    def __init__(self):
        self._lock = traced(threading.Lock(), "ResilienceMetrics._lock")
        # name -> {label_key -> value}
        self._counters: dict[str, dict[tuple, float]] = {}
        self._gauges: dict[str, dict[tuple, float]] = {}

    def inc(self, name: str, n: float = 1, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            series = self._counters.setdefault(name, {})
            series[key] = series.get(key, 0) + n

    def set_gauge(self, name: str, value: float, **labels) -> None:
        with self._lock:
            self._gauges.setdefault(name, {})[_label_key(labels)] = value

    def get(self, name: str, **labels) -> float:
        key = _label_key(labels)
        with self._lock:
            return (self._counters.get(name, {}).get(key)
                    or self._gauges.get(name, {}).get(key, 0))

    def snapshot(self) -> dict[str, list[tuple[dict, float]]]:
        """name -> [(labels, value)] for the exposition renderer."""
        out: dict[str, list[tuple[dict, float]]] = {}
        with self._lock:
            for store in (self._counters, self._gauges):
                for name, series in store.items():
                    out.setdefault(name, []).extend(
                        (dict(key), value)
                        for key, value in sorted(series.items()))
        return out

    def reset(self) -> None:
        """Test isolation only — production counters are monotonic."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()


def merge_snapshots(*snaps: dict) -> dict:
    """Sum snapshot dicts from several processes into one exposition
    payload (identical (name, labels) series add — each resilience
    event originates in exactly one process, so summing never double
    counts a single event).  Worker restarts reset that worker's
    contribution; Prometheus counter semantics tolerate the reset."""
    out: dict[str, dict[tuple, float]] = {}
    for snap in snaps:
        for name, samples in (snap or {}).items():
            series = out.setdefault(name, {})
            for labels, value in samples:
                key = _label_key(labels)
                series[key] = series.get(key, 0) + value
    return {name: [(dict(k), v) for k, v in sorted(series.items())]
            for name, series in out.items()}


resilience_metrics = ResilienceMetrics()

#: metric names this module emits (mirrored in
#: metrics/prometheus.py METRIC_SPECS; the selflint round-trip keeps
#: the two in sync)
RESILIENCE_METRIC_NAMES: Iterable[str] = (
    "stage_restarts_total",
    "stage_heartbeat_misses_total",
    "requests_redelivered_total",
    "requests_failed_retryable_total",
    "connector_retries_total",
    "circuit_breaker_trips_total",
    "circuit_breaker_open",
    "deadline_exceeded_total",
    "faults_injected_total",
)
