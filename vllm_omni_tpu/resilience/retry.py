"""Retry policy + circuit breakers for connector edges.

Every cross-process edge in the disaggregated pipeline (stage command
channels, the TCP KV store, per-layer KV transfers, address discovery)
can fail transiently; bare timeouts turn those blips into dead requests.
``RetryPolicy`` centralizes the retry stance (bounded attempts,
exponential backoff with deterministic jitter, deadline awareness) and
``CircuitBreaker`` keeps one flapping edge from stalling the pipeline:
after ``failure_threshold`` consecutive failures the edge fails fast
(OPEN) until ``reset_timeout_s`` passes, then a single probe is let
through (HALF-OPEN) — success closes the breaker, failure re-opens it.

Both take injectable ``clock``/``sleep`` so the unit tests replay exact
schedules on a fake clock (tests/resilience/test_retry.py), and both
emit counters through the resilience metrics registry so ``/metrics``
shows retries and breaker trips per edge.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.resilience.metrics import resilience_metrics

logger = init_logger(__name__)

#: exception classes a retry policy treats as transient by default —
#: connection-level failures, NOT protocol errors (a malformed frame
#: repeats identically on retry)
TRANSIENT_ERRORS: tuple[type[BaseException], ...] = (
    ConnectionError,
    TimeoutError,
    OSError,
)


class RetriesExhausted(ConnectionError):
    """All attempts failed; ``last`` is the final underlying error."""

    def __init__(self, site: str, attempts: int, last: BaseException):
        super().__init__(
            f"{site}: {attempts} attempt(s) failed; last error: "
            f"{type(last).__name__}: {last}")
        self.site = site
        self.attempts = attempts
        self.last = last


class CircuitOpenError(ConnectionError):
    """The edge's breaker is OPEN — failing fast instead of waiting on a
    known-bad peer."""

    def __init__(self, site: str, retry_after_s: float):
        super().__init__(
            f"{site}: circuit open (retry after {retry_after_s:.1f}s)")
        self.site = site
        self.retry_after_s = retry_after_s


@dataclass(frozen=True)
class RetryPolicy:
    """Bounded exponential backoff.  ``jitter`` is the +/- fraction
    applied to each delay from a seeded RNG (deterministic given the
    same seed), so synchronized retry storms decorrelate without making
    tests flaky."""

    max_attempts: int = 3
    base_delay_s: float = 0.05
    multiplier: float = 2.0
    max_delay_s: float = 2.0
    jitter: float = 0.1
    retry_on: tuple[type[BaseException], ...] = TRANSIENT_ERRORS

    def delay_s(self, attempt: int, rng: Optional[random.Random] = None
                ) -> float:
        """Backoff before retry number ``attempt`` (1-based: the delay
        after the first failure is ``delay_s(1)``)."""
        d = min(self.base_delay_s * (self.multiplier ** (attempt - 1)),
                self.max_delay_s)
        if self.jitter and rng is not None:
            d *= 1.0 + self.jitter * (2.0 * rng.random() - 1.0)
        return max(d, 0.0)


class CircuitBreaker:
    """Per-edge failure latch: CLOSED -> (N consecutive failures) ->
    OPEN -> (reset timeout) -> HALF_OPEN -> one probe decides.

    Thread-safe by construction for the pipeline's use: state
    transitions are simple attribute writes guarded by the GIL, and a
    duplicate probe in a race degrades to one extra request — never a
    wrong fail-fast."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, site: str = "edge", failure_threshold: int = 5,
                 reset_timeout_s: float = 10.0,
                 clock: Callable[[], float] = time.monotonic):
        self.site = site
        self.failure_threshold = failure_threshold
        self.reset_timeout_s = reset_timeout_s
        self._clock = clock
        self._state = self.CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0

    @property
    def state(self) -> str:
        # OPEN decays to HALF_OPEN lazily when the reset timeout passed
        if (self._state == self.OPEN
                and self._clock() - self._opened_at >= self.reset_timeout_s):
            self._state = self.HALF_OPEN
        return self._state

    def check(self) -> None:
        """Raise ``CircuitOpenError`` when the edge must fail fast.
        HALF_OPEN lets the call through as the probe."""
        if self.state == self.OPEN:
            remaining = (self._opened_at + self.reset_timeout_s
                         - self._clock())
            raise CircuitOpenError(self.site, max(remaining, 0.0))

    def record_success(self) -> None:
        if self._state != self.CLOSED:
            logger.info("breaker %s: probe succeeded; closing", self.site)
        self._state = self.CLOSED
        self._consecutive_failures = 0
        resilience_metrics.set_gauge("circuit_breaker_open", 0,
                                     site=self.site)

    def record_failure(self) -> None:
        self._consecutive_failures += 1
        tripped = (self._state == self.HALF_OPEN
                   or (self._state == self.CLOSED
                       and self._consecutive_failures
                       >= self.failure_threshold))
        if tripped:
            self._state = self.OPEN
            self._opened_at = self._clock()
            resilience_metrics.inc("circuit_breaker_trips_total",
                                   site=self.site)
            resilience_metrics.set_gauge("circuit_breaker_open", 1,
                                         site=self.site)
            logger.warning(
                "breaker %s: OPEN after %d consecutive failures "
                "(reset in %.1fs)", self.site,
                self._consecutive_failures, self.reset_timeout_s)


def call_with_retry(
    fn: Callable,
    *,
    site: str,
    policy: Optional[RetryPolicy] = None,
    breaker: Optional[CircuitBreaker] = None,
    deadline_ts: Optional[float] = None,
    clock: Callable[[], float] = time.monotonic,
    sleep: Callable[[float], None] = time.sleep,
    rng: Optional[random.Random] = None,
):
    """Run ``fn()`` under ``policy`` + ``breaker``.

    ``deadline_ts`` (on ``clock``'s timeline) bounds the WHOLE retry
    sequence: no retry starts past it, and the backoff sleep is clamped
    to the remaining budget — a deadline-carrying request never waits
    out a full backoff schedule it can't use.  The breaker is consulted
    before every attempt and fed the outcome after, so a tripped edge
    fails fast inside the retry loop too."""
    policy = policy or RetryPolicy()
    last: Optional[BaseException] = None
    for attempt in range(1, max(policy.max_attempts, 1) + 1):
        if breaker is not None:
            breaker.check()
        try:
            result = fn()
        except policy.retry_on as e:
            last = e
            if breaker is not None:
                breaker.record_failure()
            resilience_metrics.inc("connector_retries_total", site=site)
            if attempt >= policy.max_attempts:
                break
            delay = policy.delay_s(attempt, rng)
            if deadline_ts is not None:
                remaining = deadline_ts - clock()
                if remaining <= 0:
                    break
                delay = min(delay, remaining)
            logger.warning(
                "%s: attempt %d/%d failed (%s: %s); retrying in %.3fs",
                site, attempt, policy.max_attempts, type(e).__name__, e,
                delay)
            if delay > 0:
                sleep(delay)
        else:
            if breaker is not None:
                breaker.record_success()
            return result
    assert last is not None
    raise RetriesExhausted(site, attempt, last) from last
