"""Stage supervision: keep a crashed/hung stage worker from being fatal.

``StageSupervisor`` wraps a ``ProcStage`` (entrypoints/stage_proc.py)
behind the same stage surface the orchestrators poll (submit / poll /
has_unfinished / process_engine_inputs / profiling / shutdown), adding:

- **liveness heartbeats** — a background thread sends ``ping`` frames
  on the existing command channel; the worker's ``pong`` reports which
  requests are mid-execution.  Missed pongs beyond the budget declare
  the worker hung (catching wedges ``is_alive`` can't see, e.g. a
  deadlocked remote worker whose process the orchestrator can't
  observe at all).
- **crash detection on both transports** — the wrapped stage's fatal
  latch covers proc death (``is_alive``), channel EOF (the only signal
  a remote worker gives), and failed sends.
- **bounded automatic restart** — exponential backoff + deterministic
  jitter, at most ``max_restarts`` respawns per stage; remote workers
  are never respawned (their lifecycle belongs to their host).
- **redelivery, exactly once** — queued-but-unstarted requests are
  resubmitted to the fresh worker (the worker-side request-id dedup
  makes duplicate delivery harmless); requests the dead worker had
  STARTED fail fast with a structured *retryable* error instead of the
  old permanent ``_fatal`` mass-failure, and a request that outlives a
  second crash fails rather than looping forever.  Started-ness is as
  fresh as the last pong, so a request that entered the running batch
  just before the crash may be redelivered instead of failed — that
  re-executes it, but never duplicates client-visible output: the
  stage channel carries outputs at finished-request granularity only,
  and the dead worker's outputs died with it.

All events count through the resilience metrics registry
(``stage_restarts_total``, ``stage_heartbeat_misses_total``,
``requests_redelivered_total``, ``requests_failed_retryable_total``)
and the heartbeat defaults are deliberately generous — a mid-traffic
XLA compile stalls pongs for tens of seconds and must not read as a
hang.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Optional

from vllm_omni_tpu.analysis.runtime import traced
from vllm_omni_tpu.config.stage import StageConfig
from vllm_omni_tpu.entrypoints.omni_stage import StageRequest
from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.outputs import OmniRequestOutput
from vllm_omni_tpu.resilience.deadline import RETRYABLE
from vllm_omni_tpu.resilience.metrics import resilience_metrics
from vllm_omni_tpu.resilience.retry import RetryPolicy

logger = init_logger(__name__)


class StageSupervisor:
    """Supervised face of a process-disaggregated stage.

    ``stage_factory`` is injectable so the unit tests drive the whole
    failure state machine against a fake stage with a fake clock —
    no spawned processes, no sleeps."""

    def __init__(
        self,
        config: StageConfig,
        device_env: Optional[dict] = None,
        *,
        ready_timeout: float = 300.0,
        restart_policy: Optional[RetryPolicy] = None,
        heartbeat_interval_s: Optional[float] = None,
        heartbeat_misses: Optional[int] = None,
        stage_factory: Optional[Callable] = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ):
        rt = config.runtime
        if stage_factory is None:
            from vllm_omni_tpu.entrypoints.stage_proc import ProcStage

            stage_factory = ProcStage
        self._stage = stage_factory(config, device_env=device_env,
                                    ready_timeout=ready_timeout,
                                    supervised=True)
        self.config = config
        self.stage_id = config.stage_id
        self.engine = None  # orchestrator-side: never a local engine
        self._restart_policy = restart_policy or RetryPolicy(
            max_attempts=getattr(rt, "max_restarts", 3),
            base_delay_s=0.5, multiplier=2.0, max_delay_s=15.0,
            jitter=0.2,
        )
        self._hb_interval = (heartbeat_interval_s
                             if heartbeat_interval_s is not None
                             else getattr(rt, "heartbeat_interval_s", 5.0))
        self._hb_misses = (heartbeat_misses
                           if heartbeat_misses is not None
                           else getattr(rt, "heartbeat_misses", 12))
        self._clock = clock
        self._sleep = sleep
        self._rng = random.Random(f"supervisor/{config.stage_id}")
        self._lock = traced(threading.RLock(),
                            "StageSupervisor._lock")
        # request_id -> original StageRequest (the redelivery payload)
        self._tracked: dict[str, StageRequest] = {}
        self._redelivered: set[str] = set()
        self._failed_outs: list[OmniRequestOutput] = []
        self._restarts = 0
        self._restarting = False
        self._dead = False  # restart budget exhausted / not restartable
        self._closed = False
        if self._hb_interval > 0:
            self._hb_thread = threading.Thread(
                target=self._heartbeat_loop, daemon=True,
                name=f"supervise-stage{config.stage_id}")
            self._hb_thread.start()

    # ------------------------------------------------------ stage surface
    @property
    def request_stats(self):
        return self._stage.request_stats

    @property
    def has_unfinished(self) -> bool:
        with self._lock:
            return bool(self._stage.has_unfinished or self._tracked
                        or self._failed_outs)

    def process_engine_inputs(self, upstream_outputs):
        return self._stage.process_engine_inputs(upstream_outputs)

    def engine_metrics_snapshot(self) -> dict:
        return self._stage.engine_metrics_snapshot()

    def resilience_snapshot(self) -> dict:
        fn = getattr(self._stage, "resilience_snapshot", None)
        return fn() if fn is not None else {}

    def start_profile(self, trace_dir: str) -> None:
        self._stage.start_profile(trace_dir)

    def stop_profile(self, timeout: float = 60.0, wait: bool = True) -> None:
        self._stage.stop_profile(timeout=timeout, wait=wait)

    def wait_profile_ack(self, timeout: float = 60.0) -> None:
        self._stage.wait_profile_ack(timeout)

    def submit(self, reqs: list[StageRequest]) -> None:
        with self._lock:
            for r in reqs:
                self._tracked[r.request_id] = r
            if self._dead:
                # no worker will ever serve these — fail now, same
                # shape as any other stage error output
                for r in reqs:
                    self._fail_locked(
                        r.request_id,
                        "stage worker unavailable (restart budget "
                        "exhausted)")
                return
            self._stage.submit(reqs)

    def poll(self) -> list[OmniRequestOutput]:
        outs = self._stage.poll()
        with self._lock:
            for o in outs:
                if o.finished:
                    self._tracked.pop(o.request_id, None)
                    self._redelivered.discard(o.request_id)
            # failure handling BEFORE the drain so fail-fast outputs
            # surface in this very poll, not the next one
            if (self._stage._fatal is not None and not self._restarting
                    and not self._dead and not self._closed):
                self._on_failure(self._stage._fatal)
            if self._failed_outs:
                outs = outs + self._failed_outs
                self._failed_outs = []
        return outs

    def shutdown(self, timeout: float = 10.0) -> None:
        with self._lock:
            self._closed = True
        self._stage.shutdown(timeout)

    # --------------------------------------------------------- heartbeats
    def _heartbeat_loop(self) -> None:
        while True:
            self._sleep(self._hb_interval)
            with self._lock:
                if self._closed:
                    return
                if self._restarting or self._dead:
                    continue
                if self._stage._fatal is not None:
                    self._on_failure(self._stage._fatal)
                    continue
                self._stage.ping()
                age = self._clock() - self._stage.last_pong
                if age > self._hb_interval * 2:
                    # a miss needs a full unanswered ping cycle: one
                    # interval of age is NORMAL (sleep overshoot plus
                    # pong round trip), and counting it would make the
                    # miss series climb on perfectly healthy stages
                    resilience_metrics.inc(
                        "stage_heartbeat_misses_total",
                        stage=self.stage_id)
                if age > self._hb_interval * self._hb_misses:
                    logger.error(
                        "stage %d: no heartbeat for %.1fs (budget "
                        "%.1fs) — declaring the worker hung",
                        self.stage_id, age,
                        self._hb_interval * self._hb_misses)
                    self._stage.mark_hung(
                        f"worker hung: no heartbeat for {age:.1f}s")
                    self._on_failure(self._stage._fatal)

    # ----------------------------------------------------- failure policy
    def _fail_locked(self, request_id: str, detail: str,
                     kind: str = RETRYABLE) -> None:
        self._tracked.pop(request_id, None)
        self._stage._inflight.discard(request_id)
        o = OmniRequestOutput.from_error(
            request_id, detail, stage_id=self.stage_id, kind=kind)
        self._stage._record(o)
        self._failed_outs.append(o)
        resilience_metrics.inc("requests_failed_retryable_total",
                               stage=self.stage_id)

    def _on_failure(self, reason: str) -> None:
        """Split the in-flight set (lock held): mid-execution requests
        fail fast as retryable; queued-but-unstarted ones await
        redelivery to the restarted worker — unless they already got
        their one redelivery, or restarting is off the table."""
        reason = reason or "worker lost"
        started = self._stage.started_request_ids & set(self._tracked)
        for rid in sorted(started):
            self._fail_locked(
                rid, f"stage worker died mid-execution: {reason}")
        for rid in sorted(set(self._tracked)):
            if rid in self._redelivered:
                self._fail_locked(
                    rid,
                    f"stage worker died again after redelivery: "
                    f"{reason}")
        can_restart = (self._stage.restartable
                       and self._restarts
                       < self._restart_policy.max_attempts)
        if not can_restart:
            logger.error(
                "stage %d: worker lost (%s) and %s — failing %d "
                "in-flight request(s)", self.stage_id, reason,
                ("not restartable" if not self._stage.restartable
                 else "restart budget exhausted"), len(self._tracked))
            for rid in sorted(set(self._tracked)):
                self._fail_locked(
                    rid, f"stage worker died: {reason}")
            self._dead = True
            return
        self._restarting = True
        threading.Thread(target=self._do_restart, args=(reason,),
                         daemon=True,
                         name=f"restart-stage{self.stage_id}").start()

    def _do_restart(self, reason: str) -> None:
        while True:
            with self._lock:
                if self._closed:
                    return
                self._restarts += 1
                attempt = self._restarts
            delay = self._restart_policy.delay_s(attempt, self._rng)
            logger.warning(
                "stage %d: worker lost (%s); restart %d/%d in %.2fs",
                self.stage_id, reason, attempt,
                self._restart_policy.max_attempts, delay)
            self._sleep(delay)
            with self._lock:
                if self._closed:
                    return
            try:
                self._stage.restart()
                break
            except Exception as e:
                logger.error("stage %d: restart attempt %d failed: %s",
                             self.stage_id, attempt, e)
                with self._lock:
                    if self._restarts >= self._restart_policy.max_attempts:
                        for rid in sorted(set(self._tracked)):
                            self._fail_locked(
                                rid,
                                f"stage worker unrecoverable after "
                                f"{attempt} restart attempt(s): {e}")
                        self._dead = True
                        self._restarting = False
                        return
        with self._lock:
            resilience_metrics.inc("stage_restarts_total",
                                   stage=self.stage_id)
            redeliver = [self._tracked[rid]
                         for rid in sorted(self._tracked)]
            self._redelivered.update(r.request_id for r in redeliver)
            self._restarting = False
        if redeliver:
            logger.warning(
                "stage %d: restarted; redelivering %d unstarted "
                "request(s)", self.stage_id, len(redeliver))
            resilience_metrics.inc("requests_redelivered_total",
                                   n=len(redeliver), stage=self.stage_id)
            self._stage.submit(redeliver)
