"""Deterministic fault injection for resilience testing.

``OMNI_TPU_FAULTS`` describes a *fault plan* — which injection sites
fail, how, and when — with a seed so two runs of the same plan replay
the exact failure schedule (the replay-determinism test keys on this).
Spawned stage workers inherit the orchestrator's environ, so one env
var drives faults on both sides of every channel.

Grammar (sites separated by ``;``, actions by ``,``)::

    OMNI_TPU_FAULTS="seed=42;stage1:kill_after=2;conn:drop_pct=0.25"
    OMNI_TPU_FAULTS="chan:delay_ms=50,drop_after=10"

Sites (each ``fault_point(site)`` call is one step at that site):

- ``stage{N}``  — stage N's worker main loop (one step per submit frame)
- ``chan``      — stage command-channel send/recv
- ``conn``      — connector ``put``/``get``
- ``kv``        — per-layer KV transfer gets
- ``handoff``   — the disagg prefill→decode KV handoff edge
  (disagg/roles.py ship/recv; ``drop_pct``/``drop_after`` fail the
  whole handoff — the router degrades to decode-side recompute —
  and ``delay_ms`` models a slow tier link)
- ``replica{N}``— disagg replica N's step loop (disagg/router.py
  ``EngineReplica.step``; prefill replicas are numbered first).
  ``fail_step``/``drop_after`` crash the replica IN-PROC (the router
  marks it dead and fails its requests over); ``kill_after`` remains
  the process-exit fault, meaningful only for process-backed replicas
- ``step``      — ``LLMEngine.step`` entry (``delay_ms`` stalls every
  engine step — the stall-watchdog tests' deterministic hang;
  ``fail_step`` raises into the stepping loop)

Actions:

- ``kill_after=N``  — hard-exit the process (``os._exit``) on step N —
  the worker-crash fault; only meaningful inside a stage worker
- ``drop_after=N``  — every step > N raises ``InjectedFault`` (a
  ``ConnectionError``, so it flows through the same except/retry paths
  a real connection failure would)
- ``drop_pct=P``    — seeded Bernoulli drop with probability P; the
  k-th step at a site always gets the k-th draw of that site's RNG
  stream, so a given (seed, site, step) decision never changes
- ``delay_ms=D``    — sleep D ms before proceeding (latency fault)
- ``fail_step=N``   — raise on exactly step N (single-shot fault)

Injection is a no-op (one dict lookup) when no plan is installed.
"""

from __future__ import annotations

import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from vllm_omni_tpu.analysis.runtime import traced
from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.resilience.metrics import resilience_metrics

logger = init_logger(__name__)

_KILL_EXIT_CODE = 86  # distinctive, so tests can assert the fault fired


class InjectedFault(ConnectionError):
    """A fault-plan-injected failure (subclasses ConnectionError so the
    production except/retry paths treat it as a transport failure)."""

    def __init__(self, site: str, step: int, action: str):
        super().__init__(f"injected fault at {site} step {step} ({action})")
        self.site = site
        self.step = step
        self.action = action


@dataclass
class SiteFaults:
    kill_after: Optional[int] = None
    drop_after: Optional[int] = None
    drop_pct: float = 0.0
    delay_ms: float = 0.0
    fail_step: Optional[int] = None


@dataclass
class FaultPlan:
    seed: int = 0
    sites: dict[str, SiteFaults] = field(default_factory=dict)

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        plan = cls()
        for entry in filter(None, (e.strip() for e in spec.split(";"))):
            if entry.startswith("seed="):
                plan.seed = int(entry[5:])
                continue
            site, sep, actions = entry.partition(":")
            if not sep:
                raise ValueError(
                    f"bad fault entry {entry!r}: want 'site:action=value'")
            sf = plan.sites.setdefault(site.strip(), SiteFaults())
            for action in filter(None,
                                 (a.strip() for a in actions.split(","))):
                name, sep, value = action.partition("=")
                if not sep:
                    raise ValueError(
                        f"bad fault action {action!r}: want 'name=value'")
                name = name.strip()
                if name in ("kill_after", "drop_after", "fail_step"):
                    setattr(sf, name, int(value))
                elif name == "drop_pct":
                    sf.drop_pct = float(value)
                elif name == "delay_ms":
                    sf.delay_ms = float(value)
                else:
                    raise ValueError(f"unknown fault action {name!r}")
        return plan


class FaultInjector:
    """Executes a plan: per-site step counters + a per-site seeded RNG
    stream (step-indexed — decision k at a site depends only on
    (seed, site, k), never on interleaving with other sites)."""

    def __init__(self, plan: FaultPlan):
        self.plan = plan
        self._lock = traced(threading.Lock(), "FaultInjector._lock")
        self._steps: dict[str, int] = {}
        self._rngs: dict[str, random.Random] = {}

    def _advance(self, site: str) -> tuple[int, float]:
        """(step number, this step's uniform draw) — the draw is taken
        every step so probabilistic decisions stay aligned to steps."""
        with self._lock:
            step = self._steps.get(site, 0) + 1
            self._steps[site] = step
            rng = self._rngs.get(site)
            if rng is None:
                rng = self._rngs[site] = random.Random(
                    f"{self.plan.seed}/{site}")
            return step, rng.random()

    def point(self, site: str) -> None:
        sf = self.plan.sites.get(site)
        if sf is None:
            return
        step, draw = self._advance(site)
        if sf.delay_ms > 0:
            time.sleep(sf.delay_ms / 1e3)
        action = None
        if sf.kill_after is not None and step >= sf.kill_after:
            logger.warning("fault plan: killing process at %s step %d",
                           site, step)
            os._exit(_KILL_EXIT_CODE)
        if sf.fail_step is not None and step == sf.fail_step:
            action = f"fail_step={sf.fail_step}"
        elif sf.drop_after is not None and step > sf.drop_after:
            action = f"drop_after={sf.drop_after}"
        elif sf.drop_pct > 0 and draw < sf.drop_pct:
            action = f"drop_pct={sf.drop_pct}"
        if action is not None:
            resilience_metrics.inc("faults_injected_total", site=site)
            raise InjectedFault(site, step, action)

    def schedule(self, site: str, steps: int) -> list[bool]:
        """Pure preview of the drop decisions the next ``steps`` calls at
        ``site`` would make (ignores kill/delay) — the determinism test's
        oracle.  Does not advance the live counters."""
        sf = self.plan.sites.get(site, SiteFaults())
        rng = random.Random(f"{self.plan.seed}/{site}")
        out = []
        for step in range(1, steps + 1):
            draw = rng.random()
            out.append(
                (sf.fail_step is not None and step == sf.fail_step)
                or (sf.drop_after is not None and step > sf.drop_after)
                or (sf.drop_pct > 0 and draw < sf.drop_pct))
        return out


_injector: Optional[FaultInjector] = None
_env_loaded = False
_install_lock = threading.Lock()


def set_fault_plan(plan: Optional[FaultPlan]) -> None:
    """Install (or clear, with None) the process fault plan
    programmatically — tests use this instead of the env var."""
    global _injector, _env_loaded
    with _install_lock:
        _injector = FaultInjector(plan) if plan is not None else None
        _env_loaded = True  # explicit install wins over the env


def get_injector() -> Optional[FaultInjector]:
    global _injector, _env_loaded
    if not _env_loaded:
        with _install_lock:
            if not _env_loaded:
                spec = os.environ.get("OMNI_TPU_FAULTS", "")
                if spec:
                    _injector = FaultInjector(FaultPlan.parse(spec))
                    logger.warning("fault plan active: %s", spec)
                _env_loaded = True
    return _injector


def fault_point(site: str) -> None:
    """Production injection hook: no-op unless a plan names ``site``."""
    inj = get_injector()
    if inj is not None:
        inj.point(site)
