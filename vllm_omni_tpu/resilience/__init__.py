"""Resilience subsystem: supervised stages, retrying connector edges,
request deadlines, deterministic fault injection.

The failure surface of a disaggregated multi-stage pipeline (stage
worker processes, shm rings, TCP channels, KV-transfer edges) recovers
here instead of killing requests: see docs/resilience.md for the
failure model and knobs.
"""

from vllm_omni_tpu.resilience.deadline import (
    DEADLINE_EXCEEDED,
    RETRYABLE,
    clamp_timeout,
    deadline_output,
    expired,
    expiry_ts,
    remaining_s,
)
from vllm_omni_tpu.resilience.faults import (
    FaultInjector,
    FaultPlan,
    InjectedFault,
    fault_point,
    get_injector,
    set_fault_plan,
)
from vllm_omni_tpu.resilience.metrics import (
    RESILIENCE_METRIC_NAMES,
    resilience_metrics,
)
from vllm_omni_tpu.resilience.retry import (
    TRANSIENT_ERRORS,
    CircuitBreaker,
    CircuitOpenError,
    RetriesExhausted,
    RetryPolicy,
    call_with_retry,
)
from vllm_omni_tpu.resilience.supervisor import StageSupervisor

__all__ = [
    "DEADLINE_EXCEEDED",
    "RETRYABLE",
    "clamp_timeout",
    "deadline_output",
    "expired",
    "expiry_ts",
    "remaining_s",
    "FaultInjector",
    "FaultPlan",
    "InjectedFault",
    "fault_point",
    "get_injector",
    "set_fault_plan",
    "RESILIENCE_METRIC_NAMES",
    "resilience_metrics",
    "TRANSIENT_ERRORS",
    "CircuitBreaker",
    "CircuitOpenError",
    "RetriesExhausted",
    "RetryPolicy",
    "call_with_retry",
    "StageSupervisor",
]
